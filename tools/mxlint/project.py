"""mxlint project model: whole-program facts for the dataflow rules.

PR 3's rules are per-line AST passes; the MX014-MX017 bug classes
(traced ambient state, env-contract drift, use-after-donation,
lock-order cycles) are *dataflow* properties that no single line
reveals. This module is the shared analysis substrate: each Python file
is parsed ONCE (the same parse the lexical rules consume), one AST walk
extracts a compact, picklable :class:`ModuleFacts` record — imports,
function symbol tables, an approximate call graph, env-var reads,
ambient-state reads (clocks / host RNG), named-lock bindings and their
lexical ``with`` nesting — and :class:`ProjectModel` aggregates the
records into the cross-file indexes the rules query:

* ``resolve(mf, dotted)`` — best-effort callee resolution through the
  import graph (module-level functions, ``mod.fn`` attribute calls,
  ``from x import fn`` aliases, same-class ``self.fn`` methods),
* ``reachable(entries)`` — BFS over calls *and* bare function
  references (a traced closure usually receives its callees as
  values, not calls),
* ``callers_of(key)`` — the reverse graph, used by MX015 to resolve
  env-var names one level through helper functions like
  ``watchdog._env_float(name, ...)``,
* ``lock_graph()`` — the global lexical lock-nesting digraph MX017
  checks for cycles and ``--lock-graph`` diffs against a locktrace
  runtime dump.

Facts are plain tuples/dicts so ``--jobs N`` can extract them in
worker processes and merge in the parent; the ASTs never cross the
process boundary.
"""
from __future__ import annotations

import ast

# env-read kinds recorded by the extractor
READ_DIRECT = "environ"      # os.environ / os.getenv, any spelling
READ_GETENV = "getenv"       # base.getenv(...)
READ_DYNAMIC = "dynamic"     # base.getenv_dynamic(..., family=...)

CLOCK_FNS = frozenset(("time", "monotonic", "perf_counter", "now",
                       "time_ns", "monotonic_ns", "perf_counter_ns"))
RNG_MODULES = ("random", "numpy.random")


class FunctionFacts:
    __slots__ = ("qualname", "lineno", "params", "param_defaults",
                 "calls", "refs", "env_reads", "ambient", "decorators")

    def __init__(self, qualname, lineno, params, param_defaults):
        self.qualname = qualname
        self.lineno = lineno
        self.params = tuple(params)          # positional params, in order
        self.param_defaults = param_defaults  # {param: literal str | None}
        # (dotted callee, lineno, positional literal-str args (None for
        #  non-literals), {kw: literal str}) — the approximate call graph
        self.calls = []
        self.refs = []          # (dotted name referenced, lineno)
        # (kind, name-or-(param,..)-or-None, lineno, family-or-None)
        self.env_reads = []
        self.ambient = []       # ("clock"|"rng", dotted, lineno)
        self.decorators = []    # (dotted, lineno)


class ModuleFacts:
    __slots__ = ("path", "module", "package", "imports", "functions",
                 "consts", "env_globals", "lock_names", "lock_edges",
                 "sig_tokens", "classes")

    def __init__(self, path, module, package):
        self.path = path            # repo-relative, forward slashes
        self.module = module        # dotted module name
        self.package = package      # dotted package (for relative imports)
        self.imports = {}           # alias -> absolute dotted target
        self.functions = {}         # qualname -> FunctionFacts
        self.consts = {}            # module-level NAME -> str literal
        self.env_globals = {}       # module global -> env var it derives from
        self.lock_names = {}        # "VAR" or ".attr" -> lock name literal
        self.lock_edges = []        # (outer name, inner name, lineno)
        self.sig_tokens = []        # (env name, lineno) registered as tokens
        self.classes = {}           # class name -> [method qualnames]


def module_name_of(path):
    """Repo-relative path -> dotted module name."""
    mod = path[:-3] if path.endswith(".py") else path
    parts = mod.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _dotted(node):
    """Best-effort dotted name of an expression: Name/Attribute chains
    ('a.b.c'), with 'self.x' kept literally. None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _lit_str(node):
    return node.value if isinstance(node, ast.Constant) \
        and isinstance(node.value, str) else None


class _Extractor(ast.NodeVisitor):
    """One walk per file; fills a ModuleFacts."""

    def __init__(self, mf):
        self.mf = mf
        self._stack = []        # enclosing FunctionFacts qualname parts
        self._class = []        # enclosing class names
        self._fn = None         # innermost FunctionFacts (or None)
        self._fn_stack = []
        self._with_locks = []   # lexical stack of held lock names
        self._os_aliases = {"os"}

    # -- plumbing ------------------------------------------------------

    def _module_fn(self):
        """Facts bucket for module-level statements."""
        mf = self.mf
        top = mf.functions.get("<module>")
        if top is None:
            top = mf.functions["<module>"] = FunctionFacts(
                "<module>", 0, (), {})
        return top

    def _cur(self):
        return self._fn if self._fn is not None else self._module_fn()

    # -- imports -------------------------------------------------------

    def visit_Import(self, node):
        for a in node.names:
            self.mf.imports[a.asname or a.name.split(".")[0]] = a.name
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        base = node.module or ""
        if node.level:
            pkg = self.mf.package.split(".") if self.mf.package else []
            up = node.level - 1
            pkg = pkg[:len(pkg) - up] if up else pkg
            base = ".".join(pkg + ([node.module] if node.module else []))
        for a in node.names:
            if a.name == "*":
                continue
            self.mf.imports[a.asname or a.name] = \
                ("%s.%s" % (base, a.name)) if base else a.name
        self.generic_visit(node)

    # -- defs ----------------------------------------------------------

    def _qual(self, name):
        parts = []
        for kind, n in self._stack:
            parts.append(n)
            if kind == "fn":
                parts.append("<locals>")
        parts.append(name)
        return ".".join(parts)

    def visit_ClassDef(self, node):
        self._stack.append(("class", node.name))
        self._class.append(node.name)
        self.mf.classes.setdefault(node.name, [])
        self.generic_visit(node)
        self._class.pop()
        self._stack.pop()

    def _visit_fn(self, node):
        qual = self._qual(node.name)
        a = node.args
        params = [p.arg for p in a.posonlyargs + a.args]
        defaults = {}
        pos_defaults = a.defaults
        if pos_defaults:
            for p, d in zip(params[-len(pos_defaults):], pos_defaults):
                defaults[p] = _lit_str(d)
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            defaults[p.arg] = _lit_str(d) if d is not None else None
        fn = FunctionFacts(qual, node.lineno, params, defaults)
        for dec in node.decorator_list:
            d = dec.func if isinstance(dec, ast.Call) else dec
            dn = _dotted(d)
            if dn:
                fn.decorators.append((dn, dec.lineno))
        self.mf.functions[qual] = fn
        if self._class:
            self.mf.classes.setdefault(self._class[-1], []).append(qual)
        # decorators execute at DEF time in the enclosing scope — visit
        # them there, not as part of the function body (a kernel's
        # @attributed(...) must not become a call edge from the kernel)
        decs = node.decorator_list
        for dec in decs:
            self.visit(dec)
        node.decorator_list = []
        self._stack.append(("fn", node.name))
        self._fn_stack.append(self._fn)
        self._fn = fn
        outer_locks = self._with_locks
        self._with_locks = []  # lexical nesting does not cross a def
        self.generic_visit(node)
        self._with_locks = outer_locks
        self._fn = self._fn_stack.pop()
        self._stack.pop()
        node.decorator_list = decs

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- assignments (consts, env globals, lock bindings) --------------

    def _lock_name_of_call(self, call):
        """'x' for named_lock('x')/named_condition('x') calls, else
        None. Resolution of the callee is lexical: any callee whose
        final attribute is named_lock/named_condition counts."""
        if not isinstance(call, ast.Call):
            return None
        dn = _dotted(call.func)
        if dn and dn.split(".")[-1] in ("named_lock", "named_condition"):
            return _lit_str(call.args[0]) if call.args else None
        return None

    def visit_Assign(self, node):
        if len(node.targets) == 1:
            t = node.targets[0]
            v = node.value
            lock = self._lock_name_of_call(v)
            if isinstance(t, ast.Name):
                if self._fn is None and not self._class:
                    s = _lit_str(v)
                    if s is not None:
                        self.mf.consts[t.id] = s
                    if self._reads_env(v):
                        name = self._env_name_in(v)
                        if name:
                            self.mf.env_globals[t.id] = name
                if lock:
                    self.mf.lock_names[t.id] = lock
            elif isinstance(t, ast.Attribute) and lock and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                # class-qualified first (two classes in one module may
                # both use `self._lock`); bare-attr entry is the
                # first-wins fallback for cross-class helper methods
                if self._class:
                    self.mf.lock_names[
                        "%s.%s" % (self._class[-1], t.attr)] = lock
                self.mf.lock_names.setdefault("." + t.attr, lock)
        self.generic_visit(node)

    def _reads_env(self, node):
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                dn = _dotted(n.func)
                if dn and (dn.endswith(".environ.get")
                           or dn.endswith("os.getenv")
                           or dn.split(".")[-1] in ("_getenv", "getenv")):
                    return True
        return False

    def _env_name_in(self, node):
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and n.args:
                s = _lit_str(n.args[0])
                if s is not None and self._reads_env(n):
                    return s
        return None

    # -- with (lock nesting) -------------------------------------------

    def _lock_of_expr(self, e):
        """Lock NAME for a with-item context expression, or None."""
        if isinstance(e, ast.Call):
            # with named_lock("x"): — anonymous, still carries the name
            return self._lock_name_of_call(e)
        if isinstance(e, ast.Name):
            return self.mf.lock_names.get(e.id)
        if isinstance(e, ast.Attribute) and \
                isinstance(e.value, ast.Name) and e.value.id == "self":
            if self._class:
                got = self.mf.lock_names.get(
                    "%s.%s" % (self._class[-1], e.attr))
                if got is not None:
                    return got
            return self.mf.lock_names.get("." + e.attr)
        return None

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            name = self._lock_of_expr(item.context_expr)
            if name is None:
                continue
            # mirror the runtime detector: one edge from EVERY held
            # lock, not just the innermost
            for holder in self._with_locks:
                if holder != name:
                    self.mf.lock_edges.append(
                        (holder, name, node.lineno))
            self._with_locks.append(name)
            acquired.append(name)
        self.generic_visit(node)
        for _ in acquired:
            self._with_locks.pop()

    visit_AsyncWith = visit_With

    # -- calls / reads -------------------------------------------------

    def visit_Call(self, node):
        fn = self._cur()
        dn = _dotted(node.func)
        if dn:
            args_lits = tuple(_lit_str(a) for a in node.args)
            kw_lits = {k.arg: _lit_str(k.value)
                       for k in node.keywords if k.arg}
            fn.calls.append((dn, node.lineno, args_lits, kw_lits))
            leaf = dn.split(".")[-1]
            root = self.mf.imports.get(dn.split(".")[0],
                                       dn.split(".")[0])
            if leaf in ("register_signature_token",) and node.args:
                s = _lit_str(node.args[0])
                if s:
                    self.mf.sig_tokens.append((s, node.lineno))
            if dn.endswith("environ.get") or \
                    (root == "os" and leaf == "getenv"):
                # os.environ.get / os.getenv (any os alias): a direct
                # read bypassing the base.getenv choke point
                fn.env_reads.append((READ_DIRECT,
                                     _lit_str(node.args[0])
                                     if node.args else None,
                                     node.lineno, None))
            elif leaf in ("getenv", "_getenv", "getenv_dynamic",
                          "_getenv_dynamic"):
                self._record_env_call(fn, node, dn, leaf)
            self._record_ambient(fn, node, dn)
        self.generic_visit(node)

    def _record_env_call(self, fn, node, dn, leaf):
        dynamic = "dynamic" in leaf
        name = None
        if node.args:
            a = node.args[0]
            name = _lit_str(a)
            if name is None and isinstance(a, ast.Name):
                if a.id in self.mf.consts:
                    name = self.mf.consts[a.id]
                elif a.id in fn.params:
                    name = ("param", a.id)
        family = None
        for k in node.keywords:
            if k.arg == "family":
                family = _lit_str(k.value)
        fn.env_reads.append((READ_DYNAMIC if dynamic else READ_GETENV,
                             name, node.lineno, family))

    def _record_ambient(self, fn, node, dn):
        parts = dn.split(".")
        if len(parts) < 2:
            return
        leaf = parts[-1]
        root = self.mf.imports.get(parts[0], parts[0])
        full = ".".join([root] + parts[1:])
        if leaf in CLOCK_FNS and (root == "time"
                                  or full.startswith("datetime.")):
            fn.ambient.append(("clock", dn, node.lineno))
        elif full.startswith("random.") \
                or full.startswith("numpy.random."):
            fn.ambient.append(("rng", dn, node.lineno))

    def visit_Attribute(self, node):
        # direct os.environ access (subscripts, membership tests,
        # aliases) — everything except the sanctioned write form
        # os.environ[k] = v / del os.environ[k]. The ENCLOSING function
        # is captured here so the read lands in its facts (MX014
        # reachability needs the real owner, not <module>).
        if node.attr == "environ" and isinstance(node.value, ast.Name) \
                and self.mf.imports.get(node.value.id,
                                        node.value.id) == "os":
            self._env_attr_sites.append((node, self._cur()))
        elif isinstance(node.value, ast.Name) and \
                isinstance(node.ctx, ast.Load):
            # two-part `alias.NAME` loads become dotted refs — the
            # cross-module env-derived-global clause (MX014) and
            # function-reference edges resolve them; unresolvable ones
            # are pruned in extract()
            self._cur().refs.append(
                ("%s.%s" % (node.value.id, node.attr), node.lineno))
        self.generic_visit(node)

    _env_attr_sites = None  # set per-run in extract()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            fn = self._cur()
            # bare references to known/imported callables feed the
            # reference edges (callbacks handed to jit/closures)
            fn.refs.append((node.id, node.lineno))
        self.generic_visit(node)


def extract(path, tree, parents=None):
    """Extract ModuleFacts for one parsed file. ``parents`` (a child ->
    parent node map) is reused from the caller's per-file phase when
    available so the tree is walked for it only once."""
    module = module_name_of(path)
    package = module if path.endswith("__init__.py") \
        else module.rpartition(".")[0]
    mf = ModuleFacts(path, module, package)
    ex = _Extractor(mf)
    ex._env_attr_sites = []
    ex.visit(tree)

    # classify raw os.environ attribute sites: a Subscript STORE/DEL
    # through os.environ is the sanctioned publish form; anything else
    # (get/subscript-load/membership/aliasing) is a direct read.
    if parents is None:
        parents = {}
        for n in ast.walk(tree):
            for c in ast.iter_child_nodes(n):
                parents[c] = n
    seen_direct = set()
    for site, owner in ex._env_attr_sites:
        p = parents.get(site)
        if isinstance(p, ast.Attribute) and p.attr == "get":
            continue  # already recorded as a call read
        name = None
        if isinstance(p, ast.Subscript) and p.value is site:
            if isinstance(p.ctx, (ast.Store, ast.Del)):
                continue  # the sanctioned publish form
            name = _lit_str(p.slice)  # os.environ["X"] subscript READ
        key = (owner.qualname, site.lineno)
        if key in seen_direct:
            continue
        seen_direct.add(key)
        owner.env_reads.append((READ_DIRECT, name, site.lineno, None))
    # prune the (noisy) reference lists down to names that can resolve:
    # module-level functions, imported symbols, and env-derived globals
    resolvable = set(mf.imports)
    resolvable.update(q for q in mf.functions if "." not in q)
    resolvable.update(mf.env_globals)
    for fn in mf.functions.values():
        fn.refs = [(n, ln) for n, ln in fn.refs
                   if (n.split(".")[0] if "." in n else n)
                   in resolvable and n not in fn.params]
    return mf


class ProjectModel:
    """Cross-file index over ModuleFacts."""

    def __init__(self, facts):
        self.modules = {mf.path: mf for mf in facts}
        self.by_name = {mf.module: mf for mf in facts}
        self.functions = {}
        for mf in facts:
            for q, fn in mf.functions.items():
                self.functions[(mf.path, q)] = fn
        self._callers = None

    # -- resolution ----------------------------------------------------

    def _fn_in_module(self, mf, name):
        if name in mf.functions:
            return (mf.path, name)
        return None

    def resolve(self, mf, dotted, from_qual=None):
        """Resolve a dotted callee to [(path, qualname)] candidates."""
        parts = dotted.split(".")
        head, rest = parts[0], parts[1:]
        out = []
        if head == "self" and rest and from_qual:
            cls = from_qual.split(".")[0]
            cand = "%s.%s" % (cls, rest[0])
            got = self._fn_in_module(mf, cand)
            if got:
                out.append(got)
            return out
        if not rest:
            got = self._fn_in_module(mf, head)
            if got:
                return [got]
        target = mf.imports.get(head)
        if target is None:
            return out
        if not rest:
            # from x import fn as head
            tmod, _, tfn = target.rpartition(".")
            tm = self.by_name.get(tmod)
            if tm:
                got = self._fn_in_module(tm, tfn)
                if got:
                    out.append(got)
            return out
        # mod.fn / mod.sub.fn
        tm = self.by_name.get(target)
        if tm is None:
            tm = self.by_name.get("%s.%s" % (target,
                                             ".".join(rest[:-1])))
            if tm:
                got = self._fn_in_module(tm, rest[-1])
                if got:
                    out.append(got)
                return out
        if tm:
            got = self._fn_in_module(tm, ".".join(rest)) or \
                self._fn_in_module(tm, rest[0])
            if got:
                out.append(got)
        return out

    # -- call/reference graph ------------------------------------------

    def edges_from(self, key):
        path, qual = key
        mf = self.modules[path]
        fn = self.functions[key]
        seen = set()
        for dn, _ln, _a, _k in fn.calls:
            for tgt in self.resolve(mf, dn, from_qual=qual):
                seen.add(tgt)
        for name, _ln in fn.refs:
            for tgt in self.resolve(mf, name, from_qual=qual):
                seen.add(tgt)
        # a function lexically encloses its nested defs: anything a
        # nested (traced) closure does, the closure's creator wired up
        prefix = qual + ".<locals>."
        for (p, q) in self.functions:
            if p == path and q.startswith(prefix) \
                    and "." not in q[len(prefix):]:
                seen.add((p, q))
        return seen

    def reachable(self, entries):
        seen = set()
        work = [k for k in entries if k in self.functions]
        while work:
            k = work.pop()
            if k in seen:
                continue
            seen.add(k)
            for nxt in self.edges_from(k):
                if nxt not in seen:
                    work.append(nxt)
        return seen

    def callers_of(self, key):
        """[(caller key, call record), ...] for calls resolving to key."""
        if self._callers is None:
            idx = {}
            for ck, fn in self.functions.items():
                mf = self.modules[ck[0]]
                for rec in fn.calls:
                    for tgt in self.resolve(mf, rec[0],
                                            from_qual=ck[1]):
                        idx.setdefault(tgt, []).append((ck, rec))
            self._callers = idx
        return self._callers.get(key, [])

    # -- locks ---------------------------------------------------------

    def lock_graph(self, path_filter=None):
        """{(outer, inner): [(path, lineno), ...]} over matching files."""
        edges = {}
        for mf in self.modules.values():
            if path_filter and not path_filter(mf.path):
                continue
            for a, b, ln in mf.lock_edges:
                edges.setdefault((a, b), []).append((mf.path, ln))
        return edges

    def lock_nodes(self, path_filter=None):
        """Every named-lock NAME allocated in matching files."""
        out = set()
        for mf in self.modules.values():
            if path_filter and not path_filter(mf.path):
                continue
            out.update(mf.lock_names.values())
        return out

    # -- env tokens ----------------------------------------------------

    def signature_tokens(self):
        """{env name: (path, lineno)} for every registered token."""
        out = {}
        for mf in self.modules.values():
            for name, ln in mf.sig_tokens:
                out.setdefault(name, (mf.path, ln))
        return out


def find_cycles(edges):
    """Cycles in a digraph given as {(a, b): ...} or iterable of (a, b).
    Returns a list of cycles, each a list of nodes [n0, n1, ..., n0]."""
    adj = {}
    for e in (edges.keys() if isinstance(edges, dict) else edges):
        a, b = e
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    stack = []
    cycles = []

    def dfs(n):
        color[n] = GRAY
        stack.append(n)
        for m in sorted(adj[n]):
            if color[m] == GRAY:
                i = stack.index(m)
                cyc = stack[i:] + [m]
                if sorted(cyc[:-1]) not in [sorted(c[:-1])
                                            for c in cycles]:
                    cycles.append(cyc)
            elif color[m] == WHITE:
                dfs(m)
        stack.pop()
        color[n] = BLACK

    for n in sorted(adj):
        if color[n] == WHITE:
            dfs(n)
    return cycles
