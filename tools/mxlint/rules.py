"""mxlint rule set: framework-specific invariants as checked analyses.

Each rule is a class with a ``code``, a one-line ``summary``, a path
``scope`` (repo-relative, forward slashes), and a ``check`` returning
findings. Python rules get the parsed AST plus a parent map; the C++
rule (MX006) is a text pass. The invariants come from PRs 1-2 (the
imperative fast path and the telemetry layer) — see docs/LINTING.md
for the catalog with rationale and example waivers.
"""
from __future__ import annotations

import ast
import os
import re

from .core import Finding


# -- shared AST helpers ------------------------------------------------------

def _parents(tree):
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _ancestors(node, parents):
    n = parents.get(node)
    while n is not None:
        yield n
        n = parents.get(n)


def _import_aliases(tree, module):
    """Local names bound to ``module`` (e.g. 'jnp' for jax.numpy)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == module:
                    names.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            parent, _, leaf = module.rpartition(".")
            if node.module == parent or (
                    node.module or "").endswith(parent.lstrip(".")):
                for a in node.names:
                    if a.name == leaf:
                        names.add(a.asname or a.name)
    return names


def _profiler_aliases(tree):
    """Names the file binds to the profiler module (``from .. import
    profiler as _profiler`` and friends)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "profiler":
                    names.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("profiler"):
                    names.add(a.asname or a.name.split(".")[0])
    return names


def _in_function(node, parents):
    return any(isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
               for a in _ancestors(node, parents))


_HOT_MODULES = (
    "mxnet_tpu/ndarray/",
    "mxnet_tpu/engine.py",
    "mxnet_tpu/kvstore.py",
    "mxnet_tpu/kvstore_async.py",
    "mxnet_tpu/kvstore_server.py",
    "mxnet_tpu/io/",
)


def _is_hot(path):
    return any(path.startswith(p) for p in _HOT_MODULES)


# -- MX001 -------------------------------------------------------------------

class MX001JnpBypassesInvoke:
    """Direct jnp compute in ndarray/ op paths bypasses the
    ``register.invoke`` choke point — such ops are invisible to the jit
    dispatch cache, bulk segments, and the per-op profiler lane.
    Host<->device conversion (``asarray``/``array``) is exempt: it
    moves bytes, it doesn't dispatch an op."""

    code = "MX001"
    summary = "direct jnp call in ndarray/ bypasses register.invoke"
    kind = "python"
    _CONVERSIONS = frozenset(("asarray", "array"))

    def scope(self, path):
        return (path.startswith("mxnet_tpu/ndarray/")
                and not path.endswith("/register.py"))

    def check(self, path, src, tree, parents):
        aliases = _import_aliases(tree, "jax.numpy")
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # jnp.take(...) / alias.X(...); also jnp.x.y(...) chains
            base = func
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name) and base.id in aliases \
                    and isinstance(func, ast.Attribute) \
                    and func.attr not in self._CONVERSIONS:
                out.append(Finding(
                    self.code, path, node.lineno,
                    "jnp.%s() dispatches outside register.invoke — "
                    "route through the op registry or waive with the "
                    "reason it cannot be an op" % func.attr))
        return out


# -- MX002 -------------------------------------------------------------------

_GUARD_TOKENS = ("_ACTIVE", "_HOOKS", "_LIVE", "is_running")
# `account` is deliberately NOT here: since ISSUE 6 it accumulates its
# cumulative counter unconditionally (only the trace-event emission
# gates on _ACTIVE internally), so production counters stay trustworthy
# with profiling off — call sites must NOT wrap it in the guard.
_HOOK_FNS = ("record_op", "record_counter", "sample_memory")


def _test_is_guard(test):
    """Does a conditional's test gate on the profiler being active?
    Accepts the inlined guard (``_HOOKS and _profiler._ACTIVE``), the
    derived form (``t0 is not None`` where t0 was set under the
    guard), and ``is_running()``."""
    for n in ast.walk(test):
        if isinstance(n, ast.Attribute) and n.attr in _GUARD_TOKENS:
            return True
        if isinstance(n, ast.Name) and (
                n.id in _GUARD_TOKENS or n.id.endswith("t0")):
            return True
        if isinstance(n, ast.Call):
            f = n.func
            if (isinstance(f, ast.Attribute) and f.attr == "is_running") \
                    or (isinstance(f, ast.Name)
                        and f.id == "is_running"):
                return True
    return False


class MX002UnguardedProfilerHook:
    """Profiler hook calls in hot modules must sit behind the inlined
    active-guard — otherwise the '<2% overhead when profiling is off'
    gate (BENCH_MODEL=profiler_overhead) is a lie."""

    code = "MX002"
    summary = "profiler hook in hot module not behind the active guard"
    kind = "python"

    def scope(self, path):
        return _is_hot(path)

    def check(self, path, src, tree, parents):
        aliases = _profiler_aliases(tree)
        if not aliases:
            return []
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _HOOK_FNS
                    and isinstance(f.value, ast.Name)
                    and f.value.id in aliases):
                continue
            guarded = False
            for anc in _ancestors(node, parents):
                if isinstance(anc, (ast.If, ast.IfExp)) \
                        and _test_is_guard(anc.test):
                    guarded = True
                    break
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    break
            if not guarded:
                out.append(Finding(
                    self.code, path, node.lineno,
                    "%s.%s() in a hot module must be inside an "
                    "`if _HOOKS and _profiler._ACTIVE` (or derived "
                    "`t0 is not None`) guard" % (f.value.id, f.attr)))
        return out


# -- MX003 -------------------------------------------------------------------

_MUTATORS = frozenset((
    "append", "add", "update", "pop", "clear", "extend", "insert",
    "remove", "setdefault", "popitem", "discard",
))
_LOCK_FACTORIES = ("Lock", "RLock", "Condition", "named_lock",
                   "named_condition")


class MX003UnlockedModuleState:
    """Module-level mutable containers mutated from function bodies
    need a named lock (``with <lock>:`` around the mutation), a
    ``threading.local`` home, or a waiver on the container's
    definition line stating why unlocked access is sound (e.g.
    GIL-atomic counter bumps on the dispatch hot path)."""

    code = "MX003"
    summary = "module-level mutable state mutated without a lock"
    kind = "python"

    def scope(self, path):
        return path.startswith("mxnet_tpu/") and path.endswith(".py")

    def _module_containers(self, tree):
        """name -> def lineno for module-level dict/list/set bindings."""
        out = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name, v = node.targets[0].id, node.value
                if isinstance(v, (ast.Dict, ast.List, ast.Set)):
                    out[name] = node.lineno
                elif isinstance(v, ast.Call):
                    f = v.func
                    callee = f.id if isinstance(f, ast.Name) else (
                        f.attr if isinstance(f, ast.Attribute) else "")
                    if callee in ("dict", "list", "set", "defaultdict",
                                  "OrderedDict", "deque"):
                        out[name] = node.lineno
        return out

    def _module_locks(self, tree):
        locks = set()
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                f = node.value.func
                callee = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else "")
                if callee in _LOCK_FACTORIES:
                    locks.add(node.targets[0].id)
        return locks

    def _locals_names(self, tree):
        """Module-level names bound to threading.local()."""
        out = set()
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                f = node.value.func
                if isinstance(f, ast.Attribute) and f.attr == "local":
                    out.add(node.targets[0].id)
        return out

    def _under_lock(self, node, parents, locks):
        for anc in _ancestors(node, parents):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    e = item.context_expr
                    if isinstance(e, ast.Name) and (
                            e.id in locks
                            or e.id.lower().endswith("lock")):
                        return True
                    if isinstance(e, ast.Attribute) and \
                            e.attr.lower().endswith(("lock", "cv")):
                        return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # keep climbing: a nested helper may still be inside
                # an outer function's with-lock block
                continue
        return False

    def check(self, path, src, tree, parents):
        containers = self._module_containers(tree)
        if not containers:
            return []
        locks = self._module_locks(tree)
        local_names = self._locals_names(tree)
        out = []

        def flag(node, name, how):
            out.append(Finding(
                self.code, path, node.lineno,
                "module-level %r mutated (%s) outside any lock — hold "
                "a named lock, make it threading.local, or waive at "
                "the definition (line %d) with why unlocked access is "
                "sound" % (name, how, containers[name]),
                extra_waiver_lines=(containers[name],)))

        for node in ast.walk(tree):
            if not _in_function(node, parents):
                continue
            name = None
            how = ""
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id in containers:
                        name, how = t.value.id, "item assignment"
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id in containers:
                        name, how = t.value.id, "del"
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        f.attr in _MUTATORS and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id in containers:
                    name, how = f.value.id, ".%s()" % f.attr
            if name is None or name in local_names or name == "__all__":
                # __all__ population (populate()-style op injection) is
                # import-time namespace bookkeeping, not shared state
                continue
            if not self._under_lock(node, parents, locks):
                flag(node, name, how)
        return out


# -- MX004 -------------------------------------------------------------------

class MX004RawBufOutsideNdarray:
    """``._buf`` may hold a _PendingSlot (a queued-but-unflushed bulk
    op). Only ndarray/ internals may touch it; everything else must
    read ``._data``, which drains the owning segment first."""

    code = "MX004"
    summary = "._buf read outside ndarray/ internals (use ._data)"
    kind = "python"

    def scope(self, path):
        return (path.startswith("mxnet_tpu/")
                and not path.startswith("mxnet_tpu/ndarray/")
                and path.endswith(".py"))

    def check(self, path, src, tree, parents):
        out = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and node.attr == "_buf":
                out.append(Finding(
                    self.code, path, node.lineno,
                    "._buf access outside ndarray/ — a pending bulk "
                    "segment would resolve stale data; use ._data"))
        return out


# -- MX005 -------------------------------------------------------------------

_SANCTIONED_JIT = (
    "mxnet_tpu/ndarray/register.py",   # imperative dispatch + bulk caches
    "mxnet_tpu/jit.py",                # the explicit user-facing jit cache
    "mxnet_tpu/gluon/block.py",        # HybridBlock compile cache
    "mxnet_tpu/gluon/fused_step.py",   # fused train-step program cache
)


class MX005UnsanctionedJaxJit:
    """Every ``jax.jit`` call site is a retrace-storm risk unless its
    key management lives in a sanctioned cache module. New sites must
    either move behind those caches or waive with the reason the local
    cache is bounded."""

    code = "MX005"
    summary = "bare jax.jit outside the sanctioned cache modules"
    kind = "python"

    def scope(self, path):
        return (path.startswith("mxnet_tpu/") and path.endswith(".py")
                and path not in _SANCTIONED_JIT)

    def check(self, path, src, tree, parents):
        jax_names = _import_aliases(tree, "jax")
        jit_names = _import_aliases(tree, "jax.jit")
        out = []
        # call-form decorators (@jax.jit(static_argnums=...)) are Call
        # nodes too — record them so the Call branch below doesn't
        # report the same site twice
        dec_calls = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    if self._is_jit(d, jax_names, jit_names):
                        out.append(self._finding(path, dec.lineno))
                        if isinstance(dec, ast.Call):
                            dec_calls.add(id(dec))
            elif isinstance(node, ast.Call) and id(node) not in dec_calls \
                    and self._is_jit(node.func, jax_names, jit_names):
                out.append(self._finding(path, node.lineno))
        return out

    @staticmethod
    def _is_jit(f, jax_names, jit_names):
        if isinstance(f, ast.Attribute) and f.attr == "jit" and \
                isinstance(f.value, ast.Name) and f.value.id in jax_names:
            return True
        return isinstance(f, ast.Name) and f.id in jit_names

    def _finding(self, path, lineno):
        return Finding(
            self.code, path, lineno,
            "bare jax.jit outside the sanctioned cache modules "
            "(%s) — retrace-storm risk; cache through them or waive "
            "with how this site bounds its keys"
            % ", ".join(_SANCTIONED_JIT))


# -- MX006 (C++ text pass) ---------------------------------------------------

_CC_FN_RE = re.compile(r"^int (MXT\w+)\s*\(")


class MX006CApiErrorMacros:
    """Every int-returning MXT* entry point must wrap its body in
    API_BEGIN/API_END (or the MXT_ spellings): a C++ exception crossing
    the C ABI is undefined behavior, and the macros are what turn it
    into the -1/MXTGetLastError() contract."""

    code = "MX006"
    summary = "MXT* entry point without API_BEGIN/API_END"
    kind = "cc"

    def scope(self, path):
        return path.startswith("src/c_") and path.endswith(".cc")

    def check(self, path, src, tree=None, parents=None):
        lines = src.splitlines()
        out = []
        i = 0
        while i < len(lines):
            m = _CC_FN_RE.match(lines[i])
            if not m:
                i += 1
                continue
            fn_name, fn_line = m.group(1), i + 1
            # swallow the (possibly multi-line) signature up to '{'
            j = i
            while j < len(lines) and "{" not in lines[j]:
                j += 1
            # body runs to the first line that CLOSES the depth
            depth = 0
            body = []
            k = j
            while k < len(lines):
                depth += lines[k].count("{") - lines[k].count("}")
                body.append(lines[k])
                if depth <= 0 and k > j or (depth == 0 and "{" in
                                            lines[k] and "}" in lines[k]):
                    break
                k += 1
            text = "\n".join(body)
            if not ("API_BEGIN" in text and "API_END" in text):
                out.append(Finding(
                    self.code, path, fn_line,
                    "%s() is not wrapped in API_BEGIN()/API_END() — a "
                    "C++ exception here crosses the C ABI" % fn_name))
            i = k + 1
        return out


# -- MX007 -------------------------------------------------------------------

class MX007WallClockInTrace:
    """Trace-event timestamps must be monotonic: ``time.time()`` goes
    backwards under NTP steps and breaks span math. Use
    ``time.perf_counter()`` / ``time.monotonic()``."""

    code = "MX007"
    summary = "time.time() in trace-emission / hot modules"
    kind = "python"

    def scope(self, path):
        return (path == "mxnet_tpu/profiler.py"
                or path.startswith("mxnet_tpu/_debug/")
                or _is_hot(path))

    def check(self, path, src, tree, parents):
        time_names = _import_aliases(tree, "time")
        out = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "time" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in time_names:
                out.append(Finding(
                    self.code, path, node.lineno,
                    "time.time() in a trace-emitting module — wall "
                    "clock steps under NTP; use perf_counter()/"
                    "monotonic()"))
        return out


# -- MX008 -------------------------------------------------------------------

class MX008BareExcept:
    """A bare ``except:`` in engine/dispatch paths swallows
    KeyboardInterrupt and SystemExit mid-dispatch, wedging sync points.
    Catch ``Exception`` (or narrower) instead."""

    code = "MX008"
    summary = "bare except: in engine/dispatch paths"
    kind = "python"

    def scope(self, path):
        return path in ("mxnet_tpu/engine.py", "mxnet_tpu/autograd.py",
                        "mxnet_tpu/executor.py") \
            or path.startswith("mxnet_tpu/ndarray/")

    def check(self, path, src, tree, parents):
        out = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                out.append(Finding(
                    self.code, path, node.lineno,
                    "bare `except:` catches KeyboardInterrupt/"
                    "SystemExit mid-dispatch — catch Exception or "
                    "narrower"))
        return out


# -- MX009 -------------------------------------------------------------------

_BROAD_EXC_NAMES = frozenset(("Exception", "BaseException"))


class MX009SwallowedBroadExcept:
    """Retry/except sites in the transport and data-pipeline layers
    (``kvstore_async.py``, ``io/``, ``_retry.py``) must not swallow
    ``Exception``/``BaseException`` silently: a failure a retry loop
    quietly eats is exactly the unaccounted degradation the faultpoint
    chaos suite exists to expose. Every broad handler must re-raise,
    count the event via ``profiler.account``, or carry an inline waiver
    stating why swallowing is sound."""

    code = "MX009"
    summary = "broad except swallowed without re-raise or accounting"
    kind = "python"

    def scope(self, path):
        return path in ("mxnet_tpu/kvstore_async.py",
                        "mxnet_tpu/_retry.py") \
            or path.startswith("mxnet_tpu/io/")

    @staticmethod
    def _is_broad(handler):
        t = handler.type
        if t is None:
            return True  # bare except
        names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
        for n in names:
            if isinstance(n, ast.Name) and n.id in _BROAD_EXC_NAMES:
                return True
            if isinstance(n, ast.Attribute) and \
                    n.attr in _BROAD_EXC_NAMES:
                return True
        return False

    @staticmethod
    def _handled(handler):
        """True if the handler body re-raises or accounts the event."""
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "account":
                return True
        return False

    def check(self, path, src, tree, parents):
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node) or self._handled(node):
                continue
            out.append(Finding(
                self.code, path, node.lineno,
                "broad except handler neither re-raises nor counts via "
                "profiler.account — a swallowed transport/pipeline "
                "failure is an unaccounted degradation; handle, count, "
                "or waive with why silence is sound"))
        return out


# -- MX010 -------------------------------------------------------------------

_LATENCY_HOOK_FNS = ("record_latency", "record_flow")


class MX010UnguardedLatencyTelemetry:
    """The ISSUE-6 telemetry primitives — ``record_latency`` histograms
    and ``record_flow`` wire-causality events — sit on the hottest
    paths of all (the kvstore request loop, the fused train step). Call
    sites there must stay behind the inlined ``_HOOKS and _ACTIVE``
    guard (or the derived ``t0 is not None`` form), exactly like MX002
    for spans: the <0.5% wire-RTT and <2% dispatch overhead budgets of
    ``BENCH_MODEL=profiler_overhead`` are only true because the off
    path never builds an event or touches the histogram lock."""

    code = "MX010"
    summary = "record_latency/record_flow not behind the active guard"
    kind = "python"

    def scope(self, path):
        return _is_hot(path) \
            or path == "mxnet_tpu/gluon/fused_step.py"

    def check(self, path, src, tree, parents):
        aliases = _profiler_aliases(tree)
        if not aliases:
            return []
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _LATENCY_HOOK_FNS
                    and isinstance(f.value, ast.Name)
                    and f.value.id in aliases):
                continue
            guarded = False
            for anc in _ancestors(node, parents):
                if isinstance(anc, (ast.If, ast.IfExp)) \
                        and _test_is_guard(anc.test):
                    guarded = True
                    break
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    break
            if not guarded:
                out.append(Finding(
                    self.code, path, node.lineno,
                    "%s.%s() on a hot path must be inside an "
                    "`if _HOOKS and _profiler._ACTIVE` (or derived "
                    "`t0 is not None`) guard — the profiler-overhead "
                    "bench budget assumes the off path is one bool "
                    "test" % (f.value.id, f.attr)))
        return out


# -- MX011 -------------------------------------------------------------------

_FLIGHTREC_FNS = ("record_span", "record_counter", "record_marker")


def _flightrec_aliases(tree):
    """Names the file binds to the flight-recorder module (``from
    .._debug import flightrec as _flightrec`` and friends)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "flightrec":
                    names.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("flightrec"):
                    names.add(a.asname or a.name.split(".")[0])
    return names


class MX011FlightrecSecondBranch:
    """Flight-recorder record calls in hot modules must sit under the
    SAME inlined guard as the profiler hooks (``_HOOKS and
    _profiler._LIVE``, or the derived ``t0 is not None`` form) — never
    under their own ``if _flightrec.ENABLED:`` as a separate hot-path
    branch. The always-on budget (<0.5% of eager dispatch,
    BENCH_MODEL=flightrec_overhead) is only true because the off path
    is ONE shared truth test; a second guard per call site doubles the
    branch cost and silently drifts as sites are added. This covers
    both the helper recorders (``record_span``/``record_counter``/
    ``record_marker``) and the raw inlined ``RING.append`` form the
    dispatch choke point uses."""

    code = "MX011"
    summary = "flight-recorder record not under the shared guard"
    kind = "python"

    def scope(self, path):
        return _is_hot(path) \
            or path == "mxnet_tpu/gluon/fused_step.py"

    def check(self, path, src, tree, parents):
        aliases = _flightrec_aliases(tree)
        if not aliases:
            return []
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_rec = (isinstance(f, ast.Attribute)
                      and f.attr in _FLIGHTREC_FNS
                      and isinstance(f.value, ast.Name)
                      and f.value.id in aliases)
            # raw form: <alias>.RING.append(...)
            is_raw = (isinstance(f, ast.Attribute)
                      and f.attr == "append"
                      and isinstance(f.value, ast.Attribute)
                      and f.value.attr == "RING"
                      and isinstance(f.value.value, ast.Name)
                      and f.value.value.id in aliases)
            if not (is_rec or is_raw):
                continue
            guarded = False
            for anc in _ancestors(node, parents):
                if isinstance(anc, (ast.If, ast.IfExp)) \
                        and _test_is_guard(anc.test):
                    guarded = True
                    break
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    break
            if not guarded:
                out.append(Finding(
                    self.code, path, node.lineno,
                    "flight-recorder record on a hot path must share "
                    "the inlined `_HOOKS and _profiler._LIVE` (or "
                    "derived `t0 is not None`) guard — a standalone "
                    "`if ENABLED:` branch is a second hot-path guard "
                    "the flightrec_overhead budget does not price"))
        return out


# -- MX012 -------------------------------------------------------------------

class MX012PallasKernelContract:
    """Every kernel module in ``pallas_kernels/`` carries the
    conv_fused contract: a pure-jnp reference implementation with
    identical semantics (``*_reference`` / ``*_jnp`` naming), an
    ``interpret=`` path so the CPU tier-1 suite executes the real
    kernel code in interpreter mode, and registration in the package's
    ``KERNEL_BENCH`` map so a bench gate prices it (the
    ``fused_kernels`` gate for the PR 9 campaign kernels). A kernel
    without a reference can't be parity-gated, one without interpret
    is dead code on the CPU suite, and one outside KERNEL_BENCH ships
    unpriced."""

    code = "MX012"
    summary = "pallas kernel module missing reference/interpret/bench"
    kind = "python"

    def scope(self, path):
        if not path.startswith("mxnet_tpu/pallas_kernels/"):
            return False
        name = path.rsplit("/", 1)[-1]
        return (name.endswith(".py") and name != "__init__.py"
                and not name.startswith("_"))

    def _bench_registry(self):
        from . import core
        init = os.path.join(core.REPO_ROOT, "mxnet_tpu",
                            "pallas_kernels", "__init__.py")
        try:
            with open(init, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "KERNEL_BENCH"
                    for t in node.targets):
                if isinstance(node.value, ast.Dict):
                    return {k.value for k in node.value.keys
                            if isinstance(k, ast.Constant)}
        return set()

    def check(self, path, src, tree, parents):
        out = []
        defs = [n for n in tree.body
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))]
        has_ref = any("reference" in n.name or n.name.endswith("_jnp")
                      for n in defs)
        has_interp = any(
            any(a.arg == "interpret" for a in
                list(n.args.args) + list(n.args.kwonlyargs))
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
        if not has_ref:
            out.append(Finding(
                self.code, path, 1,
                "pallas kernel module exports no reference "
                "implementation (*_reference / *_jnp) — parity gates "
                "need the identical-semantics jnp form"))
        if not has_interp:
            out.append(Finding(
                self.code, path, 1,
                "pallas kernel module has no interpret= path — the "
                "CPU tier-1 suite must run the real kernel code in "
                "interpreter mode"))
        mod = path.rsplit("/", 1)[-1][:-3]
        if mod not in self._bench_registry():
            out.append(Finding(
                self.code, path, 1,
                "kernel module %r is not registered in "
                "pallas_kernels/__init__.py KERNEL_BENCH — every "
                "kernel must be priced by a bench gate "
                "(BENCH_MODEL=fused_kernels for campaign kernels)"
                % mod))
        return out


# -- MX013 -------------------------------------------------------------------

def _faultpoint_aliases(tree):
    """Names the faultpoint module is bound to in this file
    (``from .._debug import faultpoint as _faultpoint``,
    ``import mxnet_tpu._debug.faultpoint as fp``, ...)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "faultpoint":
                    out.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith(".faultpoint") \
                        or a.name == "faultpoint":
                    out.add(a.asname or a.name.split(".")[0])
    return out


class MX013FaultpointInCatalog:
    """Every ``faultpoint.check("<literal>")`` in the tree must name a
    point in the ``POINTS`` catalog (``mxnet_tpu/_debug/faultpoint.py``).
    ``configure()`` validates spec names at runtime, but an instrumented
    *seam* with a typo'd or never-cataloged name fails silently the
    other way: the check is a permanent no-op, the chaos suite can
    never arm it, and the docs/RESILIENCE.md catalog (whose sync the
    faultpoint catalog test enforces) never hears about it. Variable
    arguments are exempt (the kvstore per-op dispatch passes a
    computed name)."""

    code = "MX013"
    summary = "faultpoint.check() literal not in the POINTS catalog"
    kind = "python"

    def scope(self, path):
        # instrumented seams live in the framework tree (tests arm
        # points through configure(), which validates at runtime)
        return path.endswith(".py") and (
            path.startswith("mxnet_tpu/") or path.startswith("tools/")
            or path == "bench.py")

    _catalog_cache = None  # (repo_root, frozenset) — one parse per run

    def _catalog(self):
        from . import core
        cached = self._catalog_cache
        if cached is not None and cached[0] == core.REPO_ROOT:
            return cached[1]
        points = self._parse_catalog()
        self._catalog_cache = (core.REPO_ROOT, points)
        return points

    def _parse_catalog(self):
        from . import core
        src_path = os.path.join(core.REPO_ROOT, "mxnet_tpu", "_debug",
                                "faultpoint.py")
        try:
            with open(src_path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return None  # no catalog to check against (synthetic tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "POINTS"
                    for t in node.targets):
                call = node.value
                if isinstance(call, ast.Call) and call.args and \
                        isinstance(call.args[0], (ast.Tuple, ast.List,
                                                  ast.Set)):
                    return {e.value for e in call.args[0].elts
                            if isinstance(e, ast.Constant)}
        return None

    def check(self, path, src, tree, parents):
        aliases = _faultpoint_aliases(tree)
        if not aliases:
            return []
        catalog = self._catalog()
        if catalog is None:
            return []
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and f.attr == "check"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in aliases):
                continue
            if not node.args or not isinstance(node.args[0],
                                               ast.Constant):
                continue  # computed names validate at configure() time
            name = node.args[0].value
            if isinstance(name, str) and name not in catalog:
                out.append(Finding(
                    self.code, path, node.lineno,
                    "faultpoint.check(%r) names a point missing from "
                    "the POINTS catalog — the seam is a permanent "
                    "no-op chaos can never arm; add it to "
                    "mxnet_tpu/_debug/faultpoint.py POINTS (and its "
                    "docstring/RESILIENCE.md rows)" % (name,)))
        return out


# -- MX020 -------------------------------------------------------------------

class MX020ShardingImportOutsideCompat:
    """``shard_map`` has relocated twice across jax releases (and its
    check kwarg renamed); the ``jax.sharding`` type names ride the same
    churn risk. ``mxnet_tpu/parallel/compat.py`` is the ONE import
    seam that absorbs those moves — the 3D GSPMD fused step and the
    whole parallel stack import ``Mesh``/``NamedSharding``/
    ``PartitionSpec``/``shard_map`` from there. A module importing
    them from jax directly re-opens a version seam the shim already
    closed: it works today and breaks on the next relocation, in
    exactly the code (hot parallel paths) where the breakage is a
    cluster-wide outage rather than a test failure."""

    code = "MX020"
    summary = "jax sharding/shard_map import bypasses parallel/compat"
    kind = "python"
    _MODULES = frozenset(("jax.sharding", "jax.experimental.shard_map"))

    def scope(self, path):
        return (path.startswith("mxnet_tpu/")
                and path.endswith(".py")
                and path != "mxnet_tpu/parallel/compat.py")

    def check(self, path, src, tree, parents):
        out = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                names = [a.name for a in node.names]
                bad = (mod in self._MODULES
                       or (mod == "jax.experimental"
                           and "shard_map" in names)
                       or (mod == "jax" and ("sharding" in names
                                             or "shard_map" in names)))
            elif isinstance(node, ast.Import):
                bad = any(a.name in self._MODULES
                          or a.name.startswith("jax.sharding.")
                          for a in node.names)
            else:
                continue
            if bad:
                out.append(Finding(
                    self.code, path, node.lineno,
                    "sharding/shard_map imported from jax directly — "
                    "import it from mxnet_tpu/parallel/compat.py, the "
                    "one seam that tracks jax's relocations of these "
                    "names (shard_map has moved twice already)"))
        return out


class MX021HardwareConstantDrift:
    """``benchmark/comm_model.py`` ``ASSUMPTIONS`` is the ONE home for
    the chip's modeled rates (peak TFLOPs by dtype, HBM/ICI/DCN
    bandwidth). A modeled-math surface (bench.py, the report tools,
    the _debug attribution plane, the fused step) that spells one of
    those rates as a numeric literal forks the hardware model: a chip
    retarget then changes the roofline in one place and not the other,
    and the MFU ledger silently disagrees with the comm model it is
    supposed to share assumptions with (ISSUE 17). Only literals used
    as *math* (inside an arithmetic expression or as a lookup-table
    value) fire — argparse defaults and thresholds that merely collide
    with a rate value stay clean."""

    code = "MX021"
    summary = "hardware rate literal duplicates comm_model.ASSUMPTIONS"
    kind = "python"

    # the modeled-math surfaces: files whose arithmetic prices steps
    # against the hardware model
    _SCOPE = (
        "bench.py",
        "benchmark/",
        "tools/",
        "mxnet_tpu/_debug/",
        "mxnet_tpu/profiler.py",
        "mxnet_tpu/gluon/fused_step.py",
    )
    _EXEMPT = (
        "benchmark/comm_model.py",  # the one home itself
        "tools/mxlint/",
    )

    def scope(self, path):
        return (path.endswith(".py")
                and any(path == p or path.startswith(p)
                        for p in self._SCOPE)
                and not any(path == p or path.startswith(p)
                            for p in self._EXEMPT))

    # -- the rate table (one comm_model.py parse per run) --------------

    _rates_cache = None  # (repo_root, frozenset[float])

    def _rates(self):
        from . import core
        cached = self._rates_cache
        if cached is not None and cached[0] == core.REPO_ROOT:
            return cached[1]
        rates = set()
        path = os.path.join(core.REPO_ROOT, "benchmark",
                            "comm_model.py")
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            tree = None
        if tree is not None:
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Assign)
                        and any(isinstance(t, ast.Name)
                                and t.id == "ASSUMPTIONS"
                                for t in node.targets)
                        and isinstance(node.value, ast.Dict)):
                    continue
                for k, v in zip(node.value.keys, node.value.values):
                    key = k.value if isinstance(k, ast.Constant) else ""
                    if not ("tflops" in str(key) or "GBps" in str(key)):
                        continue
                    vals = v.values if isinstance(v, ast.Dict) else (v,)
                    for vv in vals:
                        if isinstance(vv, ast.Constant) \
                                and isinstance(vv.value, float):
                            rates.add(vv.value)
        out = frozenset(rates)
        self._rates_cache = (core.REPO_ROOT, out)
        return out

    def check(self, path, src, tree, parents):
        rates = self._rates()
        if not rates:
            return []
        out = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, float)
                    and node.value in rates):
                continue
            p = parents.get(node)
            # math context: an arithmetic operand, or a value in a
            # lookup-table dict (the per-chip peaks idiom). Call
            # kwargs, argparse defaults, and comparisons stay clean.
            in_math = isinstance(p, (ast.BinOp, ast.AugAssign))
            in_table = (isinstance(p, ast.Dict)
                        and any(v is node for v in p.values))
            if in_math or in_table:
                out.append(Finding(
                    self.code, path, node.lineno,
                    "hardware rate %g duplicates comm_model."
                    "ASSUMPTIONS — resolve it from the table "
                    "(peak_tflops(dtype) / ASSUMPTIONS[...]) so a "
                    "chip retarget changes one file, not a fork of "
                    "the roofline" % node.value))
        return out


from .dataflow import DATAFLOW_RULES  # noqa: E402 (needs Finding above)

ALL_RULES = (
    MX001JnpBypassesInvoke(),
    MX002UnguardedProfilerHook(),
    MX003UnlockedModuleState(),
    MX004RawBufOutsideNdarray(),
    MX005UnsanctionedJaxJit(),
    MX006CApiErrorMacros(),
    MX007WallClockInTrace(),
    MX008BareExcept(),
    MX009SwallowedBroadExcept(),
    MX010UnguardedLatencyTelemetry(),
    MX011FlightrecSecondBranch(),
    MX012PallasKernelContract(),
    MX013FaultpointInCatalog(),
    MX020ShardingImportOutsideCompat(),
    MX021HardwareConstantDrift(),
) + DATAFLOW_RULES
