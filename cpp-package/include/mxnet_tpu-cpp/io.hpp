// MXDataIter: data iterators by registry name over the C ABI
// (ref: cpp-package/include/mxnet-cpp/io.h MXDataIter with
// SetParam/CreateDataIter over MXDataIter*).
#ifndef MXNET_TPU_CPP_IO_HPP_
#define MXNET_TPU_CPP_IO_HPP_

#include <memory>
#include <string>
#include <vector>

#include "base.h"
#include "ndarray.hpp"

namespace mxnet_tpu {
namespace cpp {

class MXDataIter {
 public:
  explicit MXDataIter(const std::string& name) : name_(name) {}

  MXDataIter& SetParam(const std::string& k, const std::string& v) {
    keys_.push_back(k);
    vals_.push_back(v);
    return *this;
  }

  // instantiate on first use (reference's CreateDataIter_ lazy flow)
  void CreateDataIter() {
    if (handle_) return;
    std::vector<const char*> k, v;
    for (const auto& s : keys_) k.push_back(s.c_str());
    for (const auto& s : vals_) v.push_back(s.c_str());
    void* h = nullptr;
    Check(MXTDataIterCreate(name_.c_str(),
                            static_cast<uint32_t>(k.size()),
                            k.empty() ? nullptr : k.data(),
                            v.empty() ? nullptr : v.data(), &h));
    handle_.reset(h, [](void* p) { MXTDataIterFree(p); });
  }

  bool Next() {
    CreateDataIter();
    int more = 0;
    Check(MXTDataIterNext(handle_.get(), &more));
    return more != 0;
  }

  NDArray GetData() {
    void* h = nullptr;
    Check(MXTDataIterGetData(handle_.get(), &h));
    return NDArray(h);
  }

  NDArray GetLabel() {
    void* h = nullptr;
    Check(MXTDataIterGetLabel(handle_.get(), &h));
    return NDArray(h);
  }

  void Reset() {
    CreateDataIter();
    Check(MXTDataIterBeforeFirst(handle_.get()));
  }

  static std::vector<std::string> ListIters() {
    uint32_t n = 0;
    const char** names = nullptr;
    Check(MXTListDataIters(&n, &names));
    return std::vector<std::string>(names, names + n);
  }

 private:
  std::string name_;
  std::vector<std::string> keys_, vals_;
  std::shared_ptr<void> handle_;
};

}  // namespace cpp
}  // namespace mxnet_tpu

#endif  // MXNET_TPU_CPP_IO_HPP_
