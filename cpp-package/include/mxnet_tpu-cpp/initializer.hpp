// Weight initializers (ref: cpp-package/include/mxnet-cpp/initializer.h
// — Initializer base dispatching on argument-name suffix, Xavier /
// Uniform / Normal / Zero / One).
#ifndef MXNET_TPU_CPP_INITIALIZER_HPP_
#define MXNET_TPU_CPP_INITIALIZER_HPP_

#include <cmath>
#include <cstdint>
#include <random>
#include <string>
#include <vector>

#include "ndarray.hpp"

namespace mxnet_tpu {
namespace cpp {

class Initializer {
 public:
  virtual ~Initializer() = default;

  // dispatch on name suffix like the reference (initializer.h
  // operator()): *_bias/_gamma/_beta/_moving_* get fixed values
  void operator()(const std::string& name, NDArray* arr) {
    if (EndsWith(name, "_bias") || EndsWith(name, "_beta") ||
        EndsWith(name, "_moving_mean") || EndsWith(name, "_moving_var")) {
      Fill(arr, 0.0f);
    } else if (EndsWith(name, "_gamma")) {
      Fill(arr, 1.0f);
    } else {
      InitWeight(arr);
    }
  }

 protected:
  virtual void InitWeight(NDArray* arr) = 0;

  static void Fill(NDArray* arr, float v) {
    std::vector<float> buf(arr->Size(), v);
    arr->SyncCopyFromCPU(buf.data(), buf.size());
  }

  static bool EndsWith(const std::string& s, const std::string& suf) {
    return s.size() >= suf.size() &&
           s.compare(s.size() - suf.size(), suf.size(), suf) == 0;
  }

  std::mt19937 rng_{5489u};
};

class Zero : public Initializer {
 protected:
  void InitWeight(NDArray* arr) override { Fill(arr, 0.0f); }
};

class One : public Initializer {
 protected:
  void InitWeight(NDArray* arr) override { Fill(arr, 1.0f); }
};

class Uniform : public Initializer {
 public:
  explicit Uniform(float scale = 0.07f) : scale_(scale) {}

 protected:
  void InitWeight(NDArray* arr) override {
    std::uniform_real_distribution<float> d(-scale_, scale_);
    std::vector<float> buf(arr->Size());
    for (auto& x : buf) x = d(rng_);
    arr->SyncCopyFromCPU(buf.data(), buf.size());
  }

 private:
  float scale_;
};

class Normal : public Initializer {
 public:
  explicit Normal(float mu = 0.0f, float sigma = 0.01f)
      : mu_(mu), sigma_(sigma) {}

 protected:
  void InitWeight(NDArray* arr) override {
    std::normal_distribution<float> d(mu_, sigma_);
    std::vector<float> buf(arr->Size());
    for (auto& x : buf) x = d(rng_);
    arr->SyncCopyFromCPU(buf.data(), buf.size());
  }

 private:
  float mu_, sigma_;
};

// Xavier/Glorot (ref: initializer.h Xavier — gaussian|uniform,
// avg|in|out fan, magnitude 3 default).
class Xavier : public Initializer {
 public:
  enum RandType { gaussian, uniform };
  enum FactorType { avg, in, out };

  explicit Xavier(RandType rand_type = gaussian,
                  FactorType factor_type = avg, float magnitude = 3.0f)
      : rand_type_(rand_type), factor_type_(factor_type),
        magnitude_(magnitude) {}

 protected:
  void InitWeight(NDArray* arr) override {
    std::vector<int64_t> shape = arr->Shape();
    float hw = 1.0f;
    for (size_t i = 2; i < shape.size(); ++i)
      hw *= static_cast<float>(shape[i]);
    float fan_out = shape.empty() ? 1.0f
                                  : static_cast<float>(shape[0]) * hw;
    float fan_in = shape.size() < 2 ? 1.0f
                                    : static_cast<float>(shape[1]) * hw;
    float factor = fan_in;
    if (factor_type_ == avg) factor = (fan_in + fan_out) / 2.0f;
    if (factor_type_ == out) factor = fan_out;
    float scale = std::sqrt(magnitude_ / factor);
    std::vector<float> buf(arr->Size());
    if (rand_type_ == uniform) {
      std::uniform_real_distribution<float> d(-scale, scale);
      for (auto& x : buf) x = d(rng_);
    } else {
      std::normal_distribution<float> d(0.0f, scale);
      for (auto& x : buf) x = d(rng_);
    }
    arr->SyncCopyFromCPU(buf.data(), buf.size());
  }

 private:
  RandType rand_type_;
  FactorType factor_type_;
  float magnitude_;
};

}  // namespace cpp
}  // namespace mxnet_tpu

#endif  // MXNET_TPU_CPP_INITIALIZER_HPP_
