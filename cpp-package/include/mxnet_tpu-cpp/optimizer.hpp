// Optimizer: client-side updates via the fused update ops
// (ref: cpp-package/include/mxnet-cpp/optimizer.hpp — SGDOptimizer /
// AdamOptimizer call sgd_update / adam_update through the imperative
// invoke path, mirroring src/operator/optimizer_op.cc).
#ifndef MXNET_TPU_CPP_OPTIMIZER_HPP_
#define MXNET_TPU_CPP_OPTIMIZER_HPP_

#include <map>
#include <memory>
#include <string>
#include <vector>

#include "base.h"
#include "ndarray.hpp"

namespace mxnet_tpu {
namespace cpp {

class Optimizer {
 public:
  virtual ~Optimizer() = default;

  Optimizer& SetParam(const std::string& k, const std::string& v) {
    params_[k] = v;
    return *this;
  }

  // In-place update of weight from grad, with per-index state
  // (ref: mxnet-cpp optimizer.h Update(index, weight, grad)).
  virtual void Update(int index, NDArray* weight,
                      const NDArray& grad) = 0;

  static std::unique_ptr<Optimizer> Create(const std::string& name);

 protected:
  // invoke `op` on inputs + params_; copy result into weight in place
  void ApplyUpdate(const std::string& op, NDArray* weight,
                   const std::vector<void*>& input_handles) {
    std::vector<const char*> k, v;
    for (const auto& kv : params_) {
      k.push_back(kv.first.c_str());
      v.push_back(kv.second.c_str());
    }
    std::vector<void*> ins = input_handles;
    void* out = nullptr;
    uint32_t nout = 0;
    Check(MXTImperativeInvoke(op.c_str(),
                              static_cast<uint32_t>(ins.size()),
                              ins.data(),
                              static_cast<uint32_t>(k.size()),
                              k.empty() ? nullptr : k.data(),
                              v.empty() ? nullptr : v.data(), &nout,
                              &out, 1));
    Check(MXTNDArrayCopyFrom(weight->handle(), out));
    MXTNDArrayFree(out);
  }

  // lazily created zero state shaped like `like`
  NDArray& State(std::map<int, NDArray>* store, int index,
                 const NDArray& like) {
    auto it = store->find(index);
    if (it == store->end()) {
      it = store->emplace(index, NDArray(like.Shape())).first;
    }
    return it->second;
  }

  std::map<std::string, std::string> params_;
};

class SGDOptimizer : public Optimizer {
 public:
  void Update(int index, NDArray* weight, const NDArray& grad) override {
    if (params_.count("momentum") != 0u) {
      NDArray& mom = State(&mom_, index, *weight);
      ApplyUpdate("sgd_mom_update", weight,
                  {weight->handle(), grad.handle(), mom.handle()});
    } else {
      ApplyUpdate("sgd_update", weight,
                  {weight->handle(), grad.handle()});
    }
  }

 private:
  std::map<int, NDArray> mom_;
};

class AdamOptimizer : public Optimizer {
 public:
  void Update(int index, NDArray* weight, const NDArray& grad) override {
    NDArray& mean = State(&mean_, index, *weight);
    NDArray& var = State(&var_, index, *weight);
    ApplyUpdate("adam_update", weight,
                {weight->handle(), grad.handle(), mean.handle(),
                 var.handle()});
  }

 private:
  std::map<int, NDArray> mean_, var_;
};

inline std::unique_ptr<Optimizer> Optimizer::Create(
    const std::string& name) {
  if (name == "sgd") return std::unique_ptr<Optimizer>(new SGDOptimizer());
  if (name == "adam")
    return std::unique_ptr<Optimizer>(new AdamOptimizer());
  throw std::runtime_error("unknown optimizer: " + name);
}

}  // namespace cpp
}  // namespace mxnet_tpu

#endif  // MXNET_TPU_CPP_OPTIMIZER_HPP_
