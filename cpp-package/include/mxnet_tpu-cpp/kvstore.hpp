// KVStore: parameter synchronization over the C ABI
// (ref: cpp-package/include/mxnet-cpp/kvstore.h over MXKVStore*).
#ifndef MXNET_TPU_CPP_KVSTORE_HPP_
#define MXNET_TPU_CPP_KVSTORE_HPP_

#include <map>
#include <memory>
#include <string>
#include <vector>

#include "base.h"
#include "ndarray.hpp"

namespace mxnet_tpu {
namespace cpp {

class KVStore {
 public:
  explicit KVStore(const std::string& type = "local") {
    void* h = nullptr;
    Check(MXTKVStoreCreate(type.c_str(), &h));
    handle_.reset(h, [](void* p) { MXTKVStoreFree(p); });
  }

  void Init(int key, const NDArray& value) {
    Check(MXTKVStoreInit(handle(), key, value.handle()));
  }

  void Init(const std::string& key, const NDArray& value) {
    Check(MXTKVStoreInitEx(handle(), key.c_str(), value.handle()));
  }

  void Push(int key, const NDArray& value, int priority = 0) {
    Check(MXTKVStorePush(handle(), key, value.handle(), priority));
  }

  void Push(const std::string& key, const NDArray& value,
            int priority = 0) {
    Check(MXTKVStorePushEx(handle(), key.c_str(), value.handle(),
                           priority));
  }

  void Pull(int key, NDArray* out, int priority = 0) {
    Check(MXTKVStorePull(handle(), key, out->handle(), priority));
  }

  void Pull(const std::string& key, NDArray* out, int priority = 0) {
    Check(MXTKVStorePullEx(handle(), key.c_str(), out->handle(),
                           priority));
  }

  void PushPull(int key, const NDArray& in, NDArray* out,
                int priority = 0) {
    Check(MXTKVStorePushPull(handle(), key, in.handle(), out->handle(),
                             priority));
  }

  // Server-side optimizer from name+params (ref: MXKVStoreSetOptimizer
  // / the pickled-optimizer UX of kvstore_server.py).
  void SetOptimizer(const std::string& name,
                    const std::map<std::string, std::string>& params) {
    std::vector<const char*> k, v;
    for (const auto& kv : params) {
      k.push_back(kv.first.c_str());
      v.push_back(kv.second.c_str());
    }
    Check(MXTKVStoreSetOptimizer(handle(), name.c_str(),
                                 static_cast<uint32_t>(k.size()),
                                 k.empty() ? nullptr : k.data(),
                                 v.empty() ? nullptr : v.data()));
  }

  int GetRank() const {
    int r = 0;
    Check(MXTKVStoreGetRank(handle(), &r));
    return r;
  }

  int GetNumWorkers() const {
    int n = 0;
    Check(MXTKVStoreGetGroupSize(handle(), &n));
    return n;
  }

  std::string GetType() const {
    const char* t = nullptr;
    Check(MXTKVStoreGetType(handle(), &t));
    return t;
  }

  void* handle() const { return handle_.get(); }

 private:
  std::shared_ptr<void> handle_;
};

}  // namespace cpp
}  // namespace mxnet_tpu

#endif  // MXNET_TPU_CPP_KVSTORE_HPP_
