// Symbol: declarative graph construction over the C ABI
// (ref: cpp-package/include/mxnet-cpp/symbol.h Symbol + op_suppl.h
// conveniences; the atomic+compose flow mirrors MXSymbolCreateAtomicSymbol
// -> MXSymbolCompose in c_api_symbolic.cc).
#ifndef MXNET_TPU_CPP_SYMBOL_HPP_
#define MXNET_TPU_CPP_SYMBOL_HPP_

#include <map>
#include <memory>
#include <string>
#include <utility>
#include <vector>

#include "base.h"

namespace mxnet_tpu {
namespace cpp {

class Executor;  // fwd (executor.hpp)

// Shared-handle Symbol (reference Symbols are also cheaply copyable).
class Symbol {
 public:
  Symbol() = default;

  explicit Symbol(void* handle)
      : handle_(handle, [](void* h) { MXTSymbolFree(h); }) {}

  static Symbol Variable(const std::string& name) {
    void* h = nullptr;
    Check(MXTSymbolCreateVariable(name.c_str(), &h));
    return Symbol(h);
  }

  static Symbol FromJSON(const std::string& json) {
    void* h = nullptr;
    Check(MXTSymbolCreateFromJSON(json.c_str(), &h));
    return Symbol(h);
  }

  static Symbol FromFile(const std::string& path) {
    void* h = nullptr;
    Check(MXTSymbolCreateFromFile(path.c_str(), &h));
    return Symbol(h);
  }

  // Create an op node: atomic symbol + compose, positional or named
  // inputs (ref: mxnet-cpp Operator::CreateSymbol).
  static Symbol CreateOp(
      const std::string& op_name, const std::string& node_name,
      const std::vector<Symbol>& inputs,
      const std::map<std::string, std::string>& params = {},
      const std::vector<std::string>& input_keys = {}) {
    std::vector<const char*> pk, pv;
    for (const auto& kv : params) {
      pk.push_back(kv.first.c_str());
      pv.push_back(kv.second.c_str());
    }
    void* atomic = nullptr;
    Check(MXTSymbolCreateAtomicSymbol(
        op_name.c_str(), static_cast<uint32_t>(pk.size()),
        pk.empty() ? nullptr : pk.data(),
        pv.empty() ? nullptr : pv.data(), &atomic));
    std::vector<void*> args;
    for (const auto& s : inputs) args.push_back(s.handle());
    std::vector<const char*> ik;
    for (const auto& k : input_keys) ik.push_back(k.c_str());
    void* out = nullptr;
    int rc = MXTSymbolCompose(
        atomic, node_name.c_str(), static_cast<uint32_t>(args.size()),
        ik.empty() ? nullptr : ik.data(), args.data(), &out);
    MXTSymbolFree(atomic);
    Check(rc);
    return Symbol(out);
  }

  std::string ToJSON() const {
    const char* json = nullptr;
    Check(MXTSymbolSaveToJSON(handle(), &json));
    return json;
  }

  void Save(const std::string& path) const {
    Check(MXTSymbolSaveToFile(handle(), path.c_str()));
  }

  std::vector<std::string> ListArguments() const {
    return StrListOf(MXTSymbolListArguments);
  }

  std::vector<std::string> ListOutputs() const {
    return StrListOf(MXTSymbolListOutputs);
  }

  std::vector<std::string> ListAuxiliaryStates() const {
    return StrListOf(MXTSymbolListAuxiliaryStates);
  }

  std::string GetName() const {
    const char* n = nullptr;
    Check(MXTSymbolGetName(handle(), &n));
    return n;
  }

  // Infer shapes given named input shapes; fills arg/out/aux shape
  // lists (ref: mxnet-cpp symbol.h InferShape).
  void InferShape(
      const std::map<std::string, std::vector<int64_t>>& provided,
      std::vector<std::vector<int64_t>>* arg_shapes,
      std::vector<std::vector<int64_t>>* out_shapes,
      std::vector<std::vector<int64_t>>* aux_shapes) const {
    std::vector<const char*> names;
    std::vector<uint32_t> ndims;
    std::vector<int64_t> flat;
    for (const auto& kv : provided) {
      names.push_back(kv.first.c_str());
      ndims.push_back(static_cast<uint32_t>(kv.second.size()));
      for (int64_t d : kv.second) flat.push_back(d);
    }
    uint32_t argc = 0, outc = 0, auxc = 0;
    const uint32_t* all_nd = nullptr;
    const int64_t* all_d = nullptr;
    Check(MXTSymbolInferShape(handle(),
                              static_cast<uint32_t>(names.size()),
                              names.data(), ndims.data(), flat.data(),
                              &argc, &outc, &auxc, &all_nd, &all_d));
    size_t entry = 0, off = 0;
    auto take = [&](uint32_t count,
                    std::vector<std::vector<int64_t>>* dst) {
      if (dst != nullptr) dst->clear();
      for (uint32_t i = 0; i < count; ++i, ++entry) {
        std::vector<int64_t> s(all_d + off, all_d + off + all_nd[entry]);
        off += all_nd[entry];
        if (dst != nullptr) dst->push_back(std::move(s));
      }
    };
    take(argc, arg_shapes);
    take(outc, out_shapes);
    take(auxc, aux_shapes);
  }

  // Bind with data shapes; allocates everything else (executor.hpp
  // defines the Executor; declared here, implemented below the class).
  Executor SimpleBind(
      const std::map<std::string, std::vector<int64_t>>& provided,
      const std::string& grad_req = "write") const;

  void* handle() const { return handle_.get(); }

 private:
  using ListFn = int (*)(void*, uint32_t*, const char***);
  std::vector<std::string> StrListOf(ListFn fn) const {
    uint32_t n = 0;
    const char** names = nullptr;
    Check(fn(handle(), &n, &names));
    return std::vector<std::string>(names, names + n);
  }

  std::shared_ptr<void> handle_;
};

}  // namespace cpp
}  // namespace mxnet_tpu

#endif  // MXNET_TPU_CPP_SYMBOL_HPP_
