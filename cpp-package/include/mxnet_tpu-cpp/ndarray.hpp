// mxnet_tpu-cpp: header-only C++ frontend over the training C ABI.
//
// Analog of the reference's cpp-package
// (ref: cpp-package/include/mxnet-cpp/ndarray.h NDArray value class,
// op.h generated operator wrappers, autograd scope) — proof that
// language frontends attach at the C ABI seam
// (src/c_api_runtime.cc): RAII NDArray handles, operator invocation by
// registry name, autograd record/backward. Compute stays jax/XLA under
// the ABI; this header is pure marshalling.
//
// Link against libmxnet_tpu.so; run with PYTHONPATH pointing at the
// repo (the ABI embeds CPython on first use).
#ifndef MXNET_TPU_CPP_NDARRAY_HPP_
#define MXNET_TPU_CPP_NDARRAY_HPP_

#include <cstdint>
#include <initializer_list>
#include <map>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "base.h"

namespace mxnet_tpu {
namespace cpp {

// Value-semantics NDArray over an opaque ABI handle
// (ref: mxnet-cpp/ndarray.h NDArray — same shared-handle idiom).
class NDArray {
 public:
  NDArray() : handle_(nullptr) {}

  explicit NDArray(void* handle) : handle_(handle) {}

  NDArray(const std::vector<int64_t>& shape, int dtype = 0) {
    Check(MXTNDArrayCreate(shape.data(),
                           static_cast<uint32_t>(shape.size()), dtype,
                           &handle_));
  }

  NDArray(const std::vector<float>& data,
          const std::vector<int64_t>& shape) {
    Check(MXTNDArrayFromData(shape.data(),
                             static_cast<uint32_t>(shape.size()), 0,
                             data.data(), data.size() * sizeof(float),
                             &handle_));
  }

  NDArray(const NDArray&) = delete;             // handles are unique
  NDArray& operator=(const NDArray&) = delete;  // (move-only, like
                                                // unique_ptr)
  NDArray(NDArray&& o) noexcept : handle_(o.handle_) {
    o.handle_ = nullptr;
  }
  NDArray& operator=(NDArray&& o) noexcept {
    if (this != &o) {
      Reset();
      handle_ = o.handle_;
      o.handle_ = nullptr;
    }
    return *this;
  }

  ~NDArray() { Reset(); }

  void Reset() {
    if (handle_ != nullptr) {
      MXTNDArrayFree(handle_);
      handle_ = nullptr;
    }
  }

  void* handle() const { return handle_; }

  std::vector<int64_t> Shape() const {
    uint32_t ndim = 0;
    int64_t dims[8];
    Check(MXTNDArrayGetShape(handle_, &ndim, dims));
    return std::vector<int64_t>(dims, dims + ndim);
  }

  size_t Size() const {
    size_t n = 1;
    for (int64_t d : Shape()) n *= static_cast<size_t>(d);
    return n;
  }

  std::vector<float> ToVector() const {
    std::vector<float> out(Size());
    Check(MXTNDArraySyncCopyToCPU(handle_, out.data(),
                                  out.size() * sizeof(float)));
    return out;
  }

  void SyncCopyFromCPU(const float* data, size_t count) {
    Check(MXTNDArraySyncCopyFromCPU(handle_, data,
                                    count * sizeof(float)));
  }

  // device-side value copy, this <- other (no host round trip)
  void CopyFrom(const NDArray& other) {
    Check(MXTNDArrayCopyFrom(handle_, other.handle()));
  }

  // Save/Load in the reference .params byte format
  // (ref: mxnet-cpp/ndarray.h Save/LoadToMap over MXNDArraySave/Load).
  static void Save(const std::string& fname,
                   const std::vector<std::pair<std::string,
                                               const NDArray*>>& arrays) {
    std::vector<void*> handles;
    std::vector<const char*> names;
    for (const auto& kv : arrays) {
      names.push_back(kv.first.c_str());
      handles.push_back(kv.second->handle());
    }
    Check(MXTNDArraySave(fname.c_str(),
                         static_cast<uint32_t>(handles.size()),
                         handles.data(), names.data()));
  }

  static std::map<std::string, NDArray> LoadToMap(
      const std::string& fname) {
    uint32_t n = 0;
    void** handles = nullptr;
    uint32_t nn = 0;
    const char** names = nullptr;
    Check(MXTNDArrayLoad(fname.c_str(), &n, &handles, &nn, &names));
    std::map<std::string, NDArray> out;
    for (uint32_t i = 0; i < n; ++i)
      out.emplace(i < nn ? names[i] : std::to_string(i),
                  NDArray(handles[i]));
    return out;
  }

  void AttachGrad() {
    void* h = handle_;
    Check(MXTAutogradMarkVariables(1, &h));
  }

  NDArray Grad() const {
    void* g = nullptr;
    Check(MXTNDArrayGetGrad(handle_, &g));
    return NDArray(g);
  }

 private:
  void* handle_;
};

// Operator invocation by registry name with string params
// (ref: mxnet-cpp/op.h generated wrappers over MXImperativeInvoke).
class Operator {
 public:
  explicit Operator(const std::string& name) : name_(name) {}

  Operator& SetParam(const std::string& k, const std::string& v) {
    keys_.push_back(k);
    vals_.push_back(v);
    return *this;
  }

  Operator& SetInput(const NDArray& arr) {
    inputs_.push_back(arr.handle());
    return *this;
  }

  std::vector<NDArray> InvokeMulti(uint32_t max_out = 4) {
    std::vector<const char*> k;
    std::vector<const char*> v;
    for (const auto& s : keys_) k.push_back(s.c_str());
    for (const auto& s : vals_) v.push_back(s.c_str());
    std::vector<void*> outs(max_out, nullptr);
    uint32_t nout = 0;
    Check(MXTImperativeInvoke(
        name_.c_str(), static_cast<uint32_t>(inputs_.size()),
        inputs_.data(), static_cast<uint32_t>(k.size()),
        k.empty() ? nullptr : k.data(), v.empty() ? nullptr : v.data(),
        &nout, outs.data(), max_out));
    std::vector<NDArray> result;
    result.reserve(nout);
    for (uint32_t i = 0; i < nout; ++i) result.emplace_back(outs[i]);
    return result;
  }

  NDArray Invoke(uint32_t max_out = 4) {
    auto outs = InvokeMulti(max_out);
    if (outs.empty()) throw std::runtime_error(name_ + ": no outputs");
    return std::move(outs[0]);
  }

 private:
  std::string name_;
  std::vector<std::string> keys_, vals_;
  std::vector<void*> inputs_;
};

// RAII autograd recording scope (ref: mxnet-cpp has no scope class;
// python's autograd.record() is the model).
class AutogradRecord {
 public:
  AutogradRecord() { Check(MXTAutogradSetIsRecording(1)); }
  ~AutogradRecord() { MXTAutogradSetIsRecording(0); }
  AutogradRecord(const AutogradRecord&) = delete;
  AutogradRecord& operator=(const AutogradRecord&) = delete;
};

inline void Backward(const NDArray& loss) {
  void* h = loss.handle();
  Check(MXTAutogradBackward(1, &h));
}

inline void WaitAll() { Check(MXTNDArrayWaitAll()); }

}  // namespace cpp
}  // namespace mxnet_tpu

#endif  // MXNET_TPU_CPP_NDARRAY_HPP_
