// Umbrella header (ref: cpp-package/include/mxnet-cpp/MxNetCpp.h).
#ifndef MXNET_TPU_CPP_MXNETCPP_H_
#define MXNET_TPU_CPP_MXNETCPP_H_

#include "base.h"
#include "ndarray.hpp"
#include "symbol.hpp"
#include "executor.hpp"
#include "optimizer.hpp"
#include "kvstore.hpp"
#include "io.hpp"
#include "op.h"
#include "metric.hpp"
#include "initializer.hpp"
#include "lr_scheduler.hpp"

#endif  // MXNET_TPU_CPP_MXNETCPP_H_
