// Executor: bound computation over the C ABI
// (ref: cpp-package/include/mxnet-cpp/executor.h — Forward/Backward/
// outputs/arg_dict over MXExecutor*).
#ifndef MXNET_TPU_CPP_EXECUTOR_HPP_
#define MXNET_TPU_CPP_EXECUTOR_HPP_

#include <map>
#include <memory>
#include <string>
#include <utility>
#include <vector>

#include "base.h"
#include "ndarray.hpp"
#include "symbol.hpp"

namespace mxnet_tpu {
namespace cpp {

class Executor {
 public:
  Executor() = default;

  explicit Executor(void* handle)
      : handle_(handle, [](void* h) { MXTExecutorFree(h); }) {}

  void Forward(bool is_train) {
    Check(MXTExecutorForward(handle(), is_train ? 1 : 0));
  }

  // empty heads => implicit ones (reference backward() semantics)
  void Backward(const std::vector<NDArray>& head_grads = {}) {
    std::vector<void*> h;
    for (const auto& g : head_grads) h.push_back(g.handle());
    Check(MXTExecutorBackward(handle(),
                              static_cast<uint32_t>(h.size()),
                              h.empty() ? nullptr : h.data()));
  }

  std::vector<NDArray> Outputs(uint32_t max_out = 8) const {
    std::vector<void*> outs(max_out, nullptr);
    uint32_t n = 0;
    Check(MXTExecutorOutputs(handle(), &n, outs.data(), max_out));
    std::vector<NDArray> result;
    result.reserve(n);
    for (uint32_t i = 0; i < n; ++i) result.emplace_back(outs[i]);
    return result;
  }

  NDArray ArgArray(const std::string& name) const {
    void* h = nullptr;
    Check(MXTExecutorArgArray(handle(), name.c_str(), &h));
    return NDArray(h);
  }

  NDArray GradArray(const std::string& name) const {
    void* h = nullptr;
    Check(MXTExecutorGradArray(handle(), name.c_str(), &h));
    return NDArray(h);
  }

  NDArray AuxArray(const std::string& name) const {
    void* h = nullptr;
    Check(MXTExecutorAuxArray(handle(), name.c_str(), &h));
    return NDArray(h);
  }

  void* handle() const { return handle_.get(); }

 private:
  std::shared_ptr<void> handle_;
};

inline Executor Symbol::SimpleBind(
    const std::map<std::string, std::vector<int64_t>>& provided,
    const std::string& grad_req) const {
  std::vector<const char*> names;
  std::vector<uint32_t> ndims;
  std::vector<int64_t> flat;
  for (const auto& kv : provided) {
    names.push_back(kv.first.c_str());
    ndims.push_back(static_cast<uint32_t>(kv.second.size()));
    for (int64_t d : kv.second) flat.push_back(d);
  }
  void* ex = nullptr;
  Check(MXTExecutorSimpleBind(handle(),
                              static_cast<uint32_t>(names.size()),
                              names.data(), ndims.data(), flat.data(),
                              grad_req.c_str(), &ex));
  return Executor(ex);
}

}  // namespace cpp
}  // namespace mxnet_tpu

#endif  // MXNET_TPU_CPP_EXECUTOR_HPP_
