// Evaluation metrics (ref: cpp-package/include/mxnet-cpp/metric.h —
// EvalMetric base with Accuracy / MSE, host-side accumulation).
#ifndef MXNET_TPU_CPP_METRIC_HPP_
#define MXNET_TPU_CPP_METRIC_HPP_

#include <cmath>
#include <cstddef>
#include <string>
#include <vector>

#include "ndarray.hpp"

namespace mxnet_tpu {
namespace cpp {

class EvalMetric {
 public:
  explicit EvalMetric(const std::string& name) : name_(name) {}
  virtual ~EvalMetric() = default;

  virtual void Update(const NDArray& labels, const NDArray& preds) = 0;

  float Get() const {
    return num_inst_ == 0 ? 0.0f
                          : static_cast<float>(sum_metric_ / num_inst_);
  }

  void Reset() {
    sum_metric_ = 0.0;
    num_inst_ = 0;
  }

  const std::string& GetName() const { return name_; }

 protected:
  std::string name_;
  double sum_metric_ = 0.0;
  size_t num_inst_ = 0;
};

// argmax-vs-label accuracy (ref: metric.h Accuracy)
class Accuracy : public EvalMetric {
 public:
  Accuracy() : EvalMetric("accuracy") {}

  void Update(const NDArray& labels, const NDArray& preds) override {
    std::vector<float> l = labels.ToVector();
    std::vector<float> p = preds.ToVector();
    size_t batch = l.size();
    size_t nclass = p.size() / batch;
    for (size_t i = 0; i < batch; ++i) {
      size_t best = 0;
      for (size_t c = 1; c < nclass; ++c)
        if (p[i * nclass + c] > p[i * nclass + best]) best = c;
      sum_metric_ += (static_cast<float>(best) == l[i]) ? 1.0 : 0.0;
      ++num_inst_;
    }
  }
};

// mean squared error (ref: metric.h MSE)
class MSE : public EvalMetric {
 public:
  MSE() : EvalMetric("mse") {}

  void Update(const NDArray& labels, const NDArray& preds) override {
    std::vector<float> l = labels.ToVector();
    std::vector<float> p = preds.ToVector();
    for (size_t i = 0; i < l.size() && i < p.size(); ++i) {
      double d = p[i] - l[i];
      sum_metric_ += d * d;
      ++num_inst_;
    }
  }
};

// mean absolute error (ref: metric.h MAE)
class MAE : public EvalMetric {
 public:
  MAE() : EvalMetric("mae") {}

  void Update(const NDArray& labels, const NDArray& preds) override {
    std::vector<float> l = labels.ToVector();
    std::vector<float> p = preds.ToVector();
    for (size_t i = 0; i < l.size() && i < p.size(); ++i) {
      sum_metric_ += std::fabs(p[i] - l[i]);
      ++num_inst_;
    }
  }
};

}  // namespace cpp
}  // namespace mxnet_tpu

#endif  // MXNET_TPU_CPP_METRIC_HPP_
