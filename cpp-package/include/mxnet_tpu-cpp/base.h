// mxnet_tpu-cpp base: ABI declarations + error handling shared by all
// frontend headers (ref: cpp-package/include/mxnet-cpp/base.h).
//
// The frontend is header-only marshalling over the C ABI
// (src/c_api_runtime.cc + src/c_api_symbol.cc) — exactly the
// reference's architecture, where mxnet-cpp wraps include/mxnet/c_api.h.
#ifndef MXNET_TPU_CPP_BASE_H_
#define MXNET_TPU_CPP_BASE_H_

#include <cstdint>
#include <stdexcept>
#include <string>

extern "C" {
const char* MXTGetLastError();
int MXTGetVersion(int* out);
int MXTRandomSeed(int seed);
int MXTListAllOpNames(uint32_t* n, const char*** names);
int MXTLoadLib(const char* path);

int MXTNDArrayCreate(const int64_t* shape, uint32_t ndim, int dtype,
                     void** out);
int MXTNDArrayFromData(const int64_t* shape, uint32_t ndim, int dtype,
                       const void* data, size_t nbytes, void** out);
int MXTNDArrayFree(void* h);
int MXTNDArrayGetShape(void* h, uint32_t* ndim, int64_t* shape);
int MXTNDArrayGetDType(void* h, int* dtype);
int MXTNDArraySyncCopyToCPU(void* h, void* data, size_t nbytes);
int MXTNDArraySyncCopyFromCPU(void* h, const void* data, size_t nbytes);
int MXTNDArrayCopyFrom(void* dst, void* src);
int MXTNDArrayReshape(void* h, uint32_t ndim, const int64_t* dims,
                      void** out);
int MXTNDArraySlice(void* h, int64_t begin, int64_t end, void** out);
int MXTNDArrayAt(void* h, int64_t idx, void** out);
int MXTNDArrayWaitAll();
int MXTNDArraySave(const char* fname, uint32_t n, void** handles,
                   const char** names);
int MXTNDArrayLoad(const char* fname, uint32_t* n, void*** handles,
                   uint32_t* nn, const char*** names);

int MXTImperativeInvoke(const char* op, uint32_t nin, void** in,
                        uint32_t nparam, const char** keys,
                        const char** vals, uint32_t* nout, void** out,
                        uint32_t max_out);
int MXTAutogradMarkVariables(uint32_t n, void** h);
int MXTAutogradSetIsRecording(int rec);
int MXTAutogradBackward(uint32_t n, void** out);
int MXTNDArrayGetGrad(void* h, void** grad);
int MXTAutogradIsRecording(int* out);
int MXTAutogradIsTraining(int* out);
int MXTAutogradSetIsTraining(int train_mode);
int MXTProfileSetConfig(uint32_t n, const char** keys, const char** vals);
int MXTProfileSetState(int state);
int MXTProfileDump();

int MXTSymbolCreateFromJSON(const char* json, void** out);
int MXTSymbolCreateFromFile(const char* path, void** out);
int MXTSymbolSaveToJSON(void* sym, const char** out_json);
int MXTSymbolSaveToFile(void* sym, const char* path);
int MXTSymbolCreateVariable(const char* name, void** out);
int MXTSymbolCreateAtomicSymbol(const char* op, uint32_t nparam,
                                const char** keys, const char** vals,
                                void** out);
int MXTSymbolCompose(void* atomic, const char* name, uint32_t nargs,
                     const char** keys, void** args, void** out);
int MXTSymbolListArguments(void* sym, uint32_t* n, const char*** names);
int MXTSymbolListOutputs(void* sym, uint32_t* n, const char*** names);
int MXTSymbolListAuxiliaryStates(void* sym, uint32_t* n,
                                 const char*** names);
int MXTSymbolGetName(void* sym, const char** name);
int MXTSymbolInferShape(void* sym, uint32_t nprov, const char** names,
                        const uint32_t* ndims, const int64_t* flat,
                        uint32_t* argc, uint32_t* outc, uint32_t* auxc,
                        const uint32_t** all_ndims,
                        const int64_t** all_dims);
int MXTSymbolGetAttr(void* sym, const char* key, const char** out,
                     int* success);
int MXTSymbolSetAttr(void* sym, const char* key, const char* value);
int MXTSymbolListAttr(void* sym, const char** out_json);
int MXTSymbolGetInternals(void* sym, void** out);
int MXTSymbolGetOutput(void* sym, uint32_t index, void** out);
int MXTSymbolCopy(void* sym, void** out);
int MXTSymbolFree(void* sym);

int MXTExecutorSimpleBind(void* sym, uint32_t nprov, const char** names,
                          const uint32_t* ndims, const int64_t* flat,
                          const char* grad_req, void** out);
int MXTExecutorForward(void* ex, int is_train);
int MXTExecutorBackward(void* ex, uint32_t nhead, void** heads);
int MXTExecutorOutputs(void* ex, uint32_t* nout, void** outs,
                       uint32_t max_out);
int MXTExecutorArgArray(void* ex, const char* name, void** out);
int MXTExecutorGradArray(void* ex, const char* name, void** out);
int MXTExecutorAuxArray(void* ex, const char* name, void** out);
int MXTExecutorFree(void* ex);

int MXTKVStoreCreate(const char* type, void** out);
int MXTKVStoreInit(void* kv, int key, void* nd);
int MXTKVStoreInitEx(void* kv, const char* key, void* nd);
int MXTKVStorePush(void* kv, int key, void* nd, int priority);
int MXTKVStorePushEx(void* kv, const char* key, void* nd, int priority);
int MXTKVStorePull(void* kv, int key, void* out, int priority);
int MXTKVStorePullEx(void* kv, const char* key, void* out, int priority);
int MXTKVStorePushPull(void* kv, int key, void* in, void* out,
                       int priority);
int MXTKVStoreGetRank(void* kv, int* out);
int MXTKVStoreGetGroupSize(void* kv, int* out);
int MXTKVStoreGetType(void* kv, const char** out);
int MXTKVStoreSetOptimizer(void* kv, const char* name, uint32_t nparam,
                           const char** keys, const char** vals);
int MXTKVStoreBarrier(void* kv);
int MXTKVStoreFree(void* kv);

int MXTListDataIters(uint32_t* n, const char*** names);
int MXTDataIterCreate(const char* name, uint32_t nparam,
                      const char** keys, const char** vals, void** out);
int MXTDataIterNext(void* it, int* more);
int MXTDataIterGetData(void* it, void** out);
int MXTDataIterGetLabel(void* it, void** out);
int MXTDataIterBeforeFirst(void* it);
int MXTDataIterFree(void* it);
}

namespace mxnet_tpu {
namespace cpp {

inline void Check(int rc) {
  if (rc != 0) throw std::runtime_error(MXTGetLastError());
}

inline int GetVersion() {
  int v = 0;
  Check(MXTGetVersion(&v));
  return v;
}

inline void RandomSeed(int seed) { Check(MXTRandomSeed(seed)); }

// dtype ids shared with the Python frontend (c_runtime._DTYPES)
enum DType {
  kFloat32 = 0,
  kFloat64 = 1,
  kFloat16 = 2,
  kUint8 = 3,
  kInt32 = 4,
  kInt8 = 5,
  kInt64 = 6,
  kBfloat16 = 12,
};

}  // namespace cpp
}  // namespace mxnet_tpu

#endif  // MXNET_TPU_CPP_BASE_H_
