#include <cstdio>
#include <vector>
#include "mxnet_tpu-cpp/MxNetCpp.h"
using namespace mxnet_tpu::cpp;
int main() {
  Symbol data = Symbol::Variable("data");
  Symbol label = Symbol::Variable("label");
  Symbol fc1 = op::FullyConnected("fc1", {data}, {{"num_hidden", "16"}});
  Symbol a1 = op::Activation("a1", {fc1}, {{"act_type", "relu"}});
  Symbol fc2 = op::FullyConnected("fc2", {a1}, {{"num_hidden", "4"}});
  Symbol net = op::SoftmaxOutput("sm", {fc2, label});
  Executor ex = net.SimpleBind({{"data", {2, 8}}, {"label", {2}}});
  ex.Forward(false);
  auto out = ex.Outputs()[0].ToVector();
  double s = 0; for (float v : out) s += v;
  printf("op.h wrappers OK, prob sum %.3f\n", s);
  return (s > 1.9 && s < 2.1) ? 0 : 1;
}
