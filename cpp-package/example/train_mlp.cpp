// C++ frontend demo: train an MLP through mxnet_tpu-cpp
// (ref: cpp-package/example/mlp.cpp — the reference's C++ training
// example over mxnet-cpp). Same task as example/capi/train_mnist.c but
// written against the header-only C++ API: RAII arrays, fluent
// Operator calls, scope-based autograd.
//
// Build (tests/test_capi_train.py compiles+runs this in CI):
//   g++ -O2 -std=c++17 -I cpp-package/include train_mlp.cpp \
//       -L mxnet_tpu -lmxnet_tpu -Wl,-rpath,mxnet_tpu -o train_mlp
//   PYTHONPATH=$REPO JAX_PLATFORMS=cpu ./train_mlp
#include <cstdio>
#include <cstdlib>
#include <random>
#include <vector>

#include "mxnet_tpu-cpp/ndarray.hpp"

namespace mc = mxnet_tpu::cpp;

int main() {
  const int N = 128, D = 64, H = 32, C = 4, EPOCHS = 40;
  const float LR = 0.5f;
  std::mt19937 rng(13);
  std::normal_distribution<float> gauss(0.0f, 1.0f);
  std::uniform_real_distribution<float> unif(-0.05f, 0.05f);

  // separable blobs
  std::vector<float> x(N * D);
  std::vector<float> y(N);
  for (int i = 0; i < N; ++i) {
    int c = i % C;
    y[i] = static_cast<float>(c);
    for (int j = 0; j < D; ++j)
      x[i * D + j] = 0.3f * gauss(rng) + ((j % C) == c ? 1.0f : 0.0f);
  }
  std::vector<float> w1(H * D), b1(H, 0.0f), w2(C * H), b2(C, 0.0f);
  for (auto& v : w1) v = unif(rng);
  for (auto& v : w2) v = unif(rng);

  mc::NDArray xa(x, {N, D});
  mc::NDArray ya(y, {N});

  float first = -1.0f, last = -1.0f;
  for (int ep = 0; ep < EPOCHS; ++ep) {
    mc::NDArray W1(w1, {H, D}), B1(b1, {H}), W2(w2, {C, H}), B2(b2, {C});
    W1.AttachGrad();
    B1.AttachGrad();
    W2.AttachGrad();
    B2.AttachGrad();

    mc::NDArray loss;
    {
      mc::AutogradRecord rec;
      auto h1 = mc::Operator("FullyConnected")
                    .SetInput(xa).SetInput(W1).SetInput(B1)
                    .SetParam("num_hidden", "32").Invoke();
      auto a1 = mc::Operator("Activation")
                    .SetInput(h1).SetParam("act_type", "relu").Invoke();
      auto logits = mc::Operator("FullyConnected")
                        .SetInput(a1).SetInput(W2).SetInput(B2)
                        .SetParam("num_hidden", "4").Invoke();
      loss = mc::Operator("softmax_cross_entropy")
                 .SetInput(logits).SetInput(ya).Invoke();
    }
    mc::Backward(loss);

    float lval = loss.ToVector()[0] / N;
    if (ep == 0) first = lval;
    last = lval;

    auto step = [&](mc::NDArray& p, std::vector<float>& buf) {
      auto g = p.Grad().ToVector();
      for (size_t i = 0; i < buf.size(); ++i)
        buf[i] -= LR / N * g[i];
    };
    step(W1, w1);
    step(B1, b1);
    step(W2, w2);
    step(B2, b2);
    if (ep % 10 == 0) std::printf("epoch %d loss %.4f\n", ep, lval);
  }
  std::printf("first %.4f last %.4f\n", first, last);
  if (!(last < first / 5.0f)) {
    std::fprintf(stderr, "FAIL: loss did not drop 5x\n");
    return 1;
  }
  std::printf("cpp-package MLP training OK\n");
  return 0;
}
