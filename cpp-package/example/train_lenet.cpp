// LeNet training through the full mxnet_tpu-cpp class set:
// Symbol::CreateOp graph building, Xavier initializer, SGDOptimizer
// with FactorScheduler, Accuracy metric, checkpoint Save/LoadToMap.
//
// ref slot: cpp-package/example/lenet.cpp — the reference's canonical
// C++ training example (conv -> pool -> conv -> pool -> fc -> fc ->
// SoftmaxOutput with client-side optimizer updates).
//
// Build (see tests/test_capi_symbol.py::test_cpp_lenet_trains):
//   g++ -O2 -std=c++17 -I cpp-package/include train_lenet.cpp \
//       -L mxnet_tpu -lmxnet_tpu -Wl,-rpath,mxnet_tpu
#include <cstdio>
#include <random>
#include <string>
#include <vector>

#include "mxnet_tpu-cpp/MxNetCpp.h"

using mxnet_tpu::cpp::Accuracy;
using mxnet_tpu::cpp::Executor;
using mxnet_tpu::cpp::FactorScheduler;
using mxnet_tpu::cpp::NDArray;
using mxnet_tpu::cpp::Optimizer;
using mxnet_tpu::cpp::Symbol;
using mxnet_tpu::cpp::Xavier;

namespace {

constexpr int kBatch = 16;
constexpr int kSide = 16;
constexpr int kClasses = 10;
constexpr int kTrain = 32;  // memorize a small set

Symbol LeNet() {
  Symbol data = Symbol::Variable("data");
  Symbol label = Symbol::Variable("label");
  Symbol c1 = Symbol::CreateOp("Convolution", "conv1", {data},
                               {{"kernel", "(3, 3)"},
                                {"num_filter", "8"}});
  Symbol a1 = Symbol::CreateOp("Activation", "relu1", {c1},
                               {{"act_type", "relu"}});
  Symbol p1 = Symbol::CreateOp("Pooling", "pool1", {a1},
                               {{"kernel", "(2, 2)"},
                                {"pool_type", "max"},
                                {"stride", "(2, 2)"}});
  Symbol c2 = Symbol::CreateOp("Convolution", "conv2", {p1},
                               {{"kernel", "(3, 3)"},
                                {"num_filter", "16"}});
  Symbol a2 = Symbol::CreateOp("Activation", "relu2", {c2},
                               {{"act_type", "relu"}});
  Symbol p2 = Symbol::CreateOp("Pooling", "pool2", {a2},
                               {{"kernel", "(2, 2)"},
                                {"pool_type", "max"},
                                {"stride", "(2, 2)"}});
  Symbol fl = Symbol::CreateOp("Flatten", "flatten", {p2});
  Symbol f1 = Symbol::CreateOp("FullyConnected", "fc1", {fl},
                               {{"num_hidden", "64"}});
  Symbol a3 = Symbol::CreateOp("Activation", "relu3", {f1},
                               {{"act_type", "relu"}});
  Symbol f2 = Symbol::CreateOp("FullyConnected", "fc2", {a3},
                               {{"num_hidden", "10"}});
  return Symbol::CreateOp("SoftmaxOutput", "softmax", {f2, label});
}

}  // namespace

int main() {
  // deterministic synthetic dataset: class k = base pattern k + noise
  std::mt19937 rng(7);
  std::normal_distribution<float> noise(0.0f, 0.3f);
  std::uniform_real_distribution<float> unif(-1.0f, 1.0f);
  std::vector<std::vector<float>> base(kClasses,
                                       std::vector<float>(kSide * kSide));
  for (auto& b : base)
    for (auto& x : b) x = unif(rng);
  std::vector<float> images(kTrain * kSide * kSide);
  std::vector<float> labels(kTrain);
  for (int i = 0; i < kTrain; ++i) {
    int cls = i % kClasses;
    labels[i] = static_cast<float>(cls);
    for (int p = 0; p < kSide * kSide; ++p)
      images[i * kSide * kSide + p] = base[cls][p] + noise(rng);
  }

  Symbol net = LeNet();
  Executor exec = net.SimpleBind(
      {{"data", {kBatch, 1, kSide, kSide}}, {"label", {kBatch}}});

  // initialize weights (dispatches on name suffix like the reference)
  Xavier init;
  std::vector<std::string> args = net.ListArguments();
  for (const auto& name : args) {
    if (name == "data" || name == "label") continue;
    NDArray w = exec.ArgArray(name);
    init(name, &w);
  }

  auto opt = Optimizer::Create("sgd");
  opt->SetParam("momentum", "0.9");
  // SoftmaxOutput grads are per-batch sums; the reference normalizes in
  // the optimizer (Module sets rescale_grad = 1/batch_size)
  char rescale[32];
  snprintf(rescale, sizeof(rescale), "%f", 1.0 / kBatch);
  opt->SetParam("rescale_grad", rescale);
  FactorScheduler sched(20, 0.9f);
  sched.SetLR(0.02f);
  Accuracy acc;

  NDArray data_arr = exec.ArgArray("data");
  NDArray label_arr = exec.ArgArray("label");

  const int nbatches = kTrain / kBatch;
  unsigned update = 0;
  for (int epoch = 0; epoch < 40; ++epoch) {
    acc.Reset();
    for (int b = 0; b < nbatches; ++b) {
      data_arr.SyncCopyFromCPU(images.data() + b * kBatch * kSide * kSide,
                               kBatch * kSide * kSide);
      label_arr.SyncCopyFromCPU(labels.data() + b * kBatch, kBatch);
      exec.Forward(true);
      exec.Backward();
      char lr[32];
      snprintf(lr, sizeof(lr), "%f", sched.GetLR(++update));
      opt->SetParam("lr", lr);
      int idx = 0;
      for (const auto& name : args) {
        if (name == "data" || name == "label") continue;
        NDArray w = exec.ArgArray(name);
        NDArray g = exec.GradArray(name);
        opt->Update(idx++, &w, g);
      }
      acc.Update(label_arr, exec.Outputs()[0]);
    }
    if (epoch % 10 == 0 || epoch == 39)
      printf("epoch %d train-accuracy %.3f\n", epoch, acc.Get());
  }

  if (acc.Get() < 0.9f) {
    printf("FAILED: final accuracy %.3f < 0.9\n", acc.Get());
    return 1;
  }

  // checkpoint through the ABI and read it back
  std::vector<std::pair<std::string, const NDArray*>> to_save;
  std::vector<NDArray> owned;
  owned.reserve(args.size());
  for (const auto& name : args) {
    if (name == "data" || name == "label") continue;
    owned.push_back(exec.ArgArray(name));
    to_save.emplace_back(name, &owned.back());
  }
  NDArray::Save("lenet.params", to_save);
  auto loaded = NDArray::LoadToMap("lenet.params");
  if (loaded.size() != to_save.size()) {
    printf("FAILED: checkpoint round trip %zu != %zu\n", loaded.size(),
           to_save.size());
    return 1;
  }

  printf("cpp-package LeNet training OK (accuracy %.3f, %zu params "
         "saved)\n", acc.Get(), loaded.size());
  return 0;
}
