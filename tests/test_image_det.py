"""Detection data pipeline tests (ref: python/mxnet/image/detection.py;
tests/python/unittest/test_image.py TestImageDetIter is the model)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.image import (CreateDetAugmenter, DetBorrowAug,
                             DetHorizontalFlipAug, DetRandomCropAug,
                             DetRandomPadAug, DetRandomSelectAug,
                             ImageDetIter)


def _det_label(boxes, header_width=2, obj_width=5):
    """Reference raw label layout: [hdr_w, obj_w, (cls,x1,y1,x2,y2)*N]."""
    flat = [float(header_width), float(obj_width)]
    for b in boxes:
        flat.extend(float(v) for v in b)
    return flat


def _write_det_rec(tmp_path, n=6, size=64):
    import cv2
    path = str(tmp_path / "det.rec")
    idx = str(tmp_path / "det.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        nobj = 1 + i % 3
        boxes = []
        for j in range(nobj):
            x1, y1 = rng.uniform(0, 0.5, 2)
            boxes.append([j % 4, x1, y1, x1 + 0.3, y1 + 0.3])
        header = recordio.IRHeader(0, _det_label(boxes), i, 0)
        w.write_idx(i, recordio.pack_img(header, img, quality=90))
    w.close()
    return path, idx


class TestDetAugmenters:
    def _img_label(self):
        rng = np.random.RandomState(1)
        img = mx.nd.array((rng.rand(60, 80, 3) * 255).astype(np.float32))
        label = np.array([[0, 0.1, 0.2, 0.5, 0.6],
                          [2, 0.4, 0.1, 0.9, 0.8]], np.float32)
        return img, label

    def test_flip_label_math(self):
        img, label = self._img_label()
        aug = DetHorizontalFlipAug(p=1.0)
        out, lab = aug(img, label)
        # x coords mirror: new_x1 = 1-x2, new_x2 = 1-x1; y unchanged
        np.testing.assert_allclose(lab[:, 1], 1.0 - label[:, 3])
        np.testing.assert_allclose(lab[:, 3], 1.0 - label[:, 1])
        np.testing.assert_allclose(lab[:, (2, 4)], label[:, (2, 4)])
        np.testing.assert_allclose(out.asnumpy(),
                                   img.asnumpy()[:, ::-1])

    def test_random_crop_boxes_stay_normalized(self):
        img, label = self._img_label()
        aug = DetRandomCropAug(min_object_covered=0.1, max_attempts=30)
        for _ in range(10):
            out, lab = aug(img, label)
            assert lab.shape[1] == 5
            assert lab.shape[0] >= 1           # never ejects everything
            assert (lab[:, 1:5] >= 0).all() and (lab[:, 1:5] <= 1).all()
            assert (lab[:, 3] > lab[:, 1]).all()
            assert (lab[:, 4] > lab[:, 2]).all()

    def test_random_pad_shrinks_boxes(self):
        img, label = self._img_label()
        aug = DetRandomPadAug(area_range=(1.5, 2.5))
        out, lab = aug(img, label)
        oh, ow = out.shape[0], out.shape[1]
        assert oh * ow > 60 * 80              # canvas grew
        # padded boxes cover a smaller normalized area
        area = lambda b: ((b[:, 3] - b[:, 1]) * (b[:, 4] - b[:, 2])).sum()
        assert area(lab) < area(label)

    def test_select_and_borrow(self):
        from mxnet_tpu.image import CastAug
        img, label = self._img_label()
        aug = DetRandomSelectAug([DetBorrowAug(CastAug())], skip_prob=0)
        out, lab = aug(img, label)
        np.testing.assert_allclose(lab, label)

    def test_create_det_augmenter_list(self):
        augs = CreateDetAugmenter((3, 32, 32), rand_crop=0.5, rand_pad=0.5,
                                  rand_mirror=True, mean=True, std=True,
                                  brightness=0.1)
        img, label = self._img_label()
        for aug in augs:
            img, label = aug(img, label)
        assert img.shape[:2] == (32, 32)
        assert (label[:, 1:5] >= -0.01).all()


class TestImageDetIter:
    def test_batches_and_label_padding(self, tmp_path):
        path, idx = _write_det_rec(tmp_path)
        it = ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                          path_imgrec=path, path_imgidx=idx, shuffle=True,
                          rand_crop=0.5, rand_pad=0.5, rand_mirror=True)
        assert it.label_shape == (3, 5)       # max 3 objects per image
        assert it.provide_label[0].shape == (4, 3, 5)
        b = next(iter(it))
        assert b.data[0].shape == (4, 3, 32, 32)
        lab = b.label[0].asnumpy()
        assert lab.shape == (4, 3, 5)
        # padding rows are -1; real rows have valid classes
        for row in lab.reshape(-1, 5):
            assert row[0] >= 0 or (row == -1).all()

    def test_full_epoch_and_reset(self, tmp_path):
        path, idx = _write_det_rec(tmp_path, n=8)
        it = ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                          path_imgrec=path)
        n = sum(b.data[0].shape[0] for b in it)
        assert n == 8
        it.reset()
        assert next(iter(it)).data[0].shape[0] == 4

    def test_parse_label_rejects_bad(self):
        with pytest.raises(RuntimeError):
            ImageDetIter._parse_label(np.zeros(3))
        with pytest.raises(RuntimeError):  # inconsistent widths
            ImageDetIter._parse_label(
                np.array([2.0, 5.0, 0, 0.1, 0.1, 0.5]))

    def test_sync_label_shape(self, tmp_path):
        p1, i1 = _write_det_rec(tmp_path, n=4)
        train = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                             path_imgrec=p1)
        val = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                           path_imgrec=p1)
        val.reshape(label_shape=(7, 5))
        val = train.sync_label_shape(val)
        assert train.label_shape == val.label_shape == (7, 5)
