"""Bucketed, backward-overlapped gradient reduction
(parallel/overlap.py + the mesh-mode fused step + the chunked-CE
local-accumulation fix, ISSUE 7 tentpole b).

SCALING_r05: 256-chip efficiency is 84.5% with zero comm/compute
overlap and ~100% once the grad reduction hides under backward. These
tests pin the machinery that makes the overlap real: bucket planning,
the custom-vjp markers that place one collective per bucket
mid-backward, numerical parity with the unbucketed reduction, the
fused/parallel train steps that wire it in, and the chunked-CE
wire-bytes fix (unembedding grad accumulated locally, reduced once).
"""
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import jax.random as jr
from jax import lax
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.parallel import (ShardedTrainStep, bucket_plan,
                                bucketed_reduce, create_mesh,
                                data_parallel, default_bucket_bytes, fsdp,
                                shard_map, tag_gradient_buckets)
from mxnet_tpu.parallel import transformer as T

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmark"))

from comm_model import hlo_collective_bytes  # noqa: E402


def _leaves(*shapes, dtype=jnp.float32):
    return [jnp.zeros(s, dtype) for s in shapes]


class TestBucketPlan:
    def test_size_cap_splits(self):
        # 3 x 1KiB leaves under a 2KiB cap -> [0,1] then [2]
        leaves = _leaves((256,), (256,), (256,))
        plan = bucket_plan(leaves, bucket_bytes=2048)
        assert plan == [[0, 1], [2]]

    def test_dtype_homogeneous(self):
        leaves = [jnp.zeros(4, jnp.float32), jnp.zeros(4, jnp.bfloat16),
                  jnp.zeros(4, jnp.float32)]
        plan = bucket_plan(leaves, bucket_bytes=1 << 20)
        # one flat wire message per bucket => no dtype mixing
        for bucket in plan:
            dts = {leaves[i].dtype for i in bucket}
            assert len(dts) == 1
        assert [i for b in plan for i in b] == [0, 1, 2]  # order kept

    def test_oversize_leaf_gets_own_bucket(self):
        leaves = _leaves((16,), (4096,), (16,))
        plan = bucket_plan(leaves, bucket_bytes=256)
        assert [len(b) for b in plan] == [1, 1, 1]

    def test_env_default_cap(self, monkeypatch):
        monkeypatch.setenv("MXTPU_ELASTIC_BUCKET_MB", "2")
        assert default_bucket_bytes() == 2 << 20


@pytest.fixture()
def dp_mesh():
    return create_mesh(devices=jax.devices()[:4])  # dp=4


def _rand_leaves(key, shapes):
    ks = jr.split(key, len(shapes))
    return [jr.normal(k, s, jnp.float32) for k, s in zip(ks, shapes)]


class TestBucketedParity:
    SHAPES = [(8, 4), (32,), (4, 4, 2), (128,), (3,)]

    def test_bucketed_reduce_bitwise_equals_per_leaf_psum(self, dp_mesh):
        """Concatenation batches wire messages but never mixes leaves:
        each leaf's reduced value is bitwise what lax.psum gives."""
        leaves = _rand_leaves(jr.PRNGKey(0), self.SHAPES)

        def plain(*ls):
            return tuple(lax.psum(l, "dp") for l in ls)

        def bucketed(*ls):
            return tuple(bucketed_reduce(list(ls), "dp",
                                         bucket_bytes=256))

        specs = tuple(P() for _ in leaves)
        want = shard_map(plain, dp_mesh, in_specs=specs,
                         out_specs=specs, check_vma=False)(*leaves)
        got = shard_map(bucketed, dp_mesh, in_specs=specs,
                        out_specs=specs, check_vma=False)(*leaves)
        for w, g in zip(want, got):
            np.testing.assert_array_equal(np.asarray(w), np.asarray(g))

    def test_tagged_backward_grads_bitwise_equal_unbucketed(self, dp_mesh):
        """Gradients through the bucket markers == psum of the plain
        gradients, bitwise — the markers change WHERE the collective
        sits in the backward, never what it computes."""
        ws = _rand_leaves(jr.PRNGKey(1), self.SHAPES)
        xs = _rand_leaves(jr.PRNGKey(2), self.SHAPES)

        def loss(ws_, xs_):
            return sum(jnp.sum(w * x) ** 2 for w, x in zip(ws_, xs_))

        def ref(ws_, xs_):
            g = jax.grad(loss)(list(ws_), list(xs_))
            return tuple(lax.psum(gi, "dp") for gi in g)

        def tagged(ws_, xs_):
            def loss_tagged(raw):
                return loss(tag_gradient_buckets(raw, "dp",
                                                 bucket_bytes=256), xs_)
            return tuple(jax.grad(loss_tagged)(list(ws_)))

        specs = tuple(P() for _ in ws)
        want = shard_map(ref, dp_mesh, in_specs=(specs, specs),
                         out_specs=specs, check_vma=False)(
            tuple(ws), tuple(xs))
        got = shard_map(tagged, dp_mesh, in_specs=(specs, specs),
                        out_specs=specs, check_vma=False)(
            tuple(ws), tuple(xs))
        for w, g in zip(want, got):
            np.testing.assert_array_equal(np.asarray(w), np.asarray(g))

    def test_bucketing_collapses_collective_count(self, dp_mesh):
        """The compiled HLO carries ONE all-reduce per bucket, not one
        per leaf — the wire-batching half of the overlap story — and
        the payload bytes match the unbucketed lowering exactly."""
        shapes = [(64,)] * 6
        ws = _rand_leaves(jr.PRNGKey(3), shapes)
        xs = _rand_leaves(jr.PRNGKey(4), shapes)
        specs = tuple(P() for _ in ws)

        def loss(ws_, xs_):
            return sum(jnp.sum(w * x) ** 2 for w, x in zip(ws_, xs_))

        def grads_of(fn):
            body = shard_map(fn, dp_mesh, in_specs=(specs, specs),
                             out_specs=specs, check_vma=False)
            return jax.jit(body).lower(tuple(ws),
                                       tuple(xs)).compile().as_text()

        def ref(ws_, xs_):
            g = jax.grad(loss)(list(ws_), list(xs_))
            return tuple(lax.psum(gi, "dp") for gi in g)

        def tagged(ws_, xs_):
            def loss_tagged(raw):
                # 3 leaves x 256B per 768B bucket -> 2 buckets of 3
                return loss(tag_gradient_buckets(raw, "dp",
                                                 bucket_bytes=768), xs_)
            return tuple(jax.grad(loss_tagged)(list(ws_)))

        b_ref, c_ref, _ = hlo_collective_bytes(grads_of(ref))
        b_tag, c_tag, _ = hlo_collective_bytes(grads_of(tagged))
        assert c_ref.get("all-reduce", 0) >= 6
        assert c_tag.get("all-reduce", 0) == 2
        assert b_tag["all-reduce"] == b_ref["all-reduce"]


def _dense_pair(seed=0):
    """Two structurally identical nets with identical init."""
    from mxnet_tpu.gluon import nn
    rs = np.random.RandomState(seed)
    w1 = rs.randn(16, 12).astype(np.float32) * 0.1
    b1 = np.zeros(16, np.float32)
    w2 = rs.randn(4, 16).astype(np.float32) * 0.1
    b2 = np.zeros(4, np.float32)

    def build():
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=12))
        net.add(nn.Dense(4, in_units=16))
        net.initialize()
        net.hybridize()
        params = [p for _, p in sorted(net.collect_params().items())]
        for p, v in zip(params, [b1, w1, b2, w2]
                        if params[0].shape == (16,) else [w1, b1, w2, b2]):
            if p.shape != v.shape:
                raise AssertionError("param order drifted")
            p.set_data(mx.nd.array(v))
        return net
    return build(), build()


class TestFusedStepMesh:
    def _train(self, net, mesh, steps=6):
        from mxnet_tpu import gluon
        loss_fn = gluon.loss.L2Loss()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9})
        step = tr.fuse_step(lambda xx, yy: loss_fn(net(xx), yy),
                            mesh=mesh, bucket_bytes=512)
        rs = np.random.RandomState(7)
        losses = []
        for i in range(steps):
            x = mx.nd.array(rs.rand(8, 12).astype(np.float32))
            y = mx.nd.array(rs.rand(8, 4).astype(np.float32))
            losses.append(float(step(x, y, batch_size=8)
                                .asnumpy().mean()))
        params = [p.data().asnumpy()
                  for _, p in sorted(net.collect_params().items())]
        return losses, params

    def test_mesh_step_matches_single_device(self):
        """The mesh-sharded fused step (bucketed psum over 'dp') trains
        to the same trajectory as the plain single-device fused step —
        the overlap machinery must not change the math."""
        from mxnet_tpu.gluon import fused_step as fs
        net_a, net_b = _dense_pair()
        mesh = create_mesh(devices=jax.devices()[:4])
        losses_m, params_m = self._train(net_a, mesh)
        losses_p, params_p = self._train(net_b, None)
        np.testing.assert_allclose(losses_m, losses_p,
                                   rtol=1e-5, atol=1e-6)
        for a, b in zip(params_m, params_p):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
        st = fs.stats()
        assert st["hits"] >= 1                   # mesh path compiled+hit

    def test_mesh_step_indivisible_batch_falls_back(self):
        """A batch 'dp' cannot split runs the eager path (counted),
        never a crash — and training continues."""
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon import fused_step as fs
        net, _ = _dense_pair(seed=1)
        mesh = create_mesh(devices=jax.devices()[:4])
        loss_fn = gluon.loss.L2Loss()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05})
        step = tr.fuse_step(lambda xx, yy: loss_fn(net(xx), yy),
                            mesh=mesh)
        rs = np.random.RandomState(3)
        before = fs.stats()["fallbacks"]
        x = mx.nd.array(rs.rand(7, 12).astype(np.float32))   # 7 % 4 != 0
        y = mx.nd.array(rs.rand(7, 4).astype(np.float32))
        out = step(x, y, batch_size=7)
        assert np.isfinite(out.asnumpy()).all()
        assert fs.stats()["fallbacks"] == before + 1
        # divisible batches still take the fused mesh path afterwards
        x8 = mx.nd.array(rs.rand(8, 12).astype(np.float32))
        y8 = mx.nd.array(rs.rand(8, 4).astype(np.float32))
        for _ in range(3):
            out = step(x8, y8, batch_size=8)
        assert np.isfinite(out.asnumpy()).all()


class TestShardedTrainStepOverlap:
    def _step(self, overlap):
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss
        import mxnet_tpu.optimizer as opt
        rs = np.random.RandomState(11)
        net = nn.Dense(6, in_units=10)
        net.initialize()
        for _, p in sorted(net.collect_params().items()):
            p.set_data(mx.nd.array(
                rs.randn(*p.shape).astype(np.float32) * 0.1))
        mesh = create_mesh(devices=jax.devices()[:8])
        return ShardedTrainStep(net, L2Loss(),
                                opt.create("sgd", learning_rate=0.05,
                                           momentum=0.9),
                                strategy=data_parallel(mesh),
                                overlap_grads=overlap, bucket_bytes=128)

    def test_overlap_matches_gspmd_path(self):
        rs = np.random.RandomState(5)
        x = rs.rand(16, 10).astype(np.float32)
        y = rs.rand(16, 6).astype(np.float32)
        s_ref, s_ovl = self._step(False), self._step(True)
        for i in range(5):
            l_ref = s_ref(x, y)
            l_ovl = s_ovl(x, y)
            np.testing.assert_allclose(float(l_ref), float(l_ovl),
                                       rtol=1e-5, atol=1e-6)
        for k in s_ref.params:
            np.testing.assert_allclose(
                np.asarray(s_ref.params[k]), np.asarray(s_ovl.params[k]),
                rtol=1e-5, atol=1e-6, err_msg=k)

    def test_overlap_requires_pure_dp(self):
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.loss import L2Loss
        import mxnet_tpu.optimizer as opt
        net = nn.Dense(8, in_units=64)
        net.initialize()
        mesh = create_mesh(dp=2, fsdp=4)
        with pytest.raises(ValueError, match="pure data-parallel"):
            ShardedTrainStep(net, L2Loss(),
                             opt.create("sgd", learning_rate=0.01),
                             strategy=fsdp(mesh, min_size=64),
                             overlap_grads=True)


class TestChunkedCELocalAccum:
    def _cfg(self, **kw):
        base = dict(vocab_size=64, dim=16, n_layers=2, n_heads=4,
                    ffn_hidden=32, loss_chunks=4)
        base.update(kw)
        return T.TransformerConfig(**base)

    @pytest.mark.parametrize("axes", [{}, dict(tp=2)])
    def test_local_accum_matches_plain_chunked(self, axes):
        """ce_local_accum moves WHERE the unembedding-grad reduction
        happens (once, at the shard_map boundary) — loss and every
        gradient stay numerically identical; the tp variant also pins
        the distributed logsumexp + target gather."""
        cfg_a = self._cfg()
        cfg_b = self._cfg(ce_local_accum=True)
        # 4 devices: dp=4, or dp=2 x tp=2
        mesh = create_mesh(devices=jax.devices()[:4], **axes)
        params = T.init_params(jr.PRNGKey(0), cfg_a)
        toks = jr.randint(jr.PRNGKey(1), (4, 16), 0, 64)
        tgts = jr.randint(jr.PRNGKey(2), (4, 16), 0, 64)
        with mesh.mesh:
            la, ga = jax.value_and_grad(
                lambda p: T.loss_fn(p, toks, tgts, cfg_a, mesh))(params)
            lb, gb = jax.value_and_grad(
                lambda p: T.loss_fn(p, toks, tgts, cfg_b, mesh))(params)
        assert abs(float(la) - float(lb)) < 1e-5
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
            ga, gb)

    def test_local_accum_cuts_wire_bytes(self):
        """The SCALING_r05 finding, fixed and measured: with the chunk
        scan inside shard_map the unembedding grad is reduced ONCE, so
        the pure-dp train step's all-reduce payload drops by
        ~(loss_chunks-1) * vocab * dim * 4 bytes."""
        V, D, chunks = 64, 16, 4
        bytes_by_cfg = {}
        for local in (False, True):
            cfg = self._cfg(ce_local_accum=local)
            mesh = create_mesh(dp=8)
            init_fn, step_fn = T.make_train_step(cfg, mesh)
            with mesh.mesh:
                state = init_fn(jr.PRNGKey(0))
                toks = jnp.zeros((8, 16), jnp.int32)
                txt = step_fn.lower(state, toks,
                                    toks).compile().as_text()
            by_kind, _, _ = hlo_collective_bytes(txt)
            bytes_by_cfg[local] = by_kind.get("all-reduce", 0)
        saved = bytes_by_cfg[False] - bytes_by_cfg[True]
        expect = (chunks - 1) * V * D * 4
        assert saved > 0, bytes_by_cfg
        # the win is the per-chunk re-reduction, within 25% (other
        # partitioner noise moves a few small ops between kinds)
        assert abs(saved - expect) <= 0.25 * expect, \
            (saved, expect, bytes_by_cfg)

    def test_bad_chunk_split_raises(self):
        cfg = self._cfg(ce_local_accum=True, loss_chunks=3)
        mesh = create_mesh(devices=jax.devices()[:4], sp=2)
        params = T.init_params(jr.PRNGKey(0), cfg)
        toks = jr.randint(jr.PRNGKey(1), (4, 16), 0, 64)
        with mesh.mesh, pytest.raises(ValueError,
                                      match="does not divide"):
            T.loss_fn(params, toks, toks, cfg, mesh)
