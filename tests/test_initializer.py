"""Initializers (ref strategy: tests/python/unittest/test_init.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.gluon import nn


def _filled(init, shape=(50, 40), name="w_weight"):
    p = gluon.Parameter(name, shape=shape)
    p.initialize(init=init)
    return p.data().asnumpy()


def test_zero_one_constant():
    np.testing.assert_allclose(_filled(mx.initializer.Zero()), 0.0)
    np.testing.assert_allclose(_filled(mx.initializer.One()), 1.0)
    np.testing.assert_allclose(
        _filled(mx.initializer.Constant(2.5)), 2.5)


def test_uniform_normal_ranges():
    u = _filled(mx.initializer.Uniform(0.3))
    assert u.min() >= -0.3 and u.max() <= 0.3 and u.std() > 0.05
    n = _filled(mx.initializer.Normal(0.1))
    assert abs(n.mean()) < 0.02 and 0.05 < n.std() < 0.2


def test_xavier_magnitude():
    x = _filled(mx.initializer.Xavier(factor_type="avg", magnitude=3.0))
    bound = np.sqrt(3.0 * 2.0 / (50 + 40))
    assert x.min() >= -bound - 1e-6 and x.max() <= bound + 1e-6
    assert x.std() > bound / 4


def test_orthogonal_is_orthogonal():
    w = _filled(mx.initializer.Orthogonal(), shape=(20, 20))
    wtw = w @ w.T
    scale = wtw[0, 0]
    np.testing.assert_allclose(wtw, np.eye(20) * scale, atol=1e-3)


def test_msra_prelu():
    w = _filled(mx.initializer.MSRAPrelu(), shape=(30, 20))
    assert np.isfinite(w).all() and w.std() > 0


def test_bilinear_upsampling_kernel():
    w = _filled(mx.initializer.Bilinear(), shape=(1, 1, 4, 4))
    # symmetric interpolation kernel
    np.testing.assert_allclose(w[0, 0], w[0, 0][::-1, ::-1], rtol=1e-5)


def test_lstmbias_forget_gate():
    b = _filled(mx.initializer.LSTMBias(forget_bias=1.0),
                shape=(20,), name="lstm_bias")
    # second quarter (forget gate) set to 1, rest 0
    np.testing.assert_allclose(b[5:10], 1.0)
    np.testing.assert_allclose(b[:5], 0.0)


def test_name_pattern_dispatch():
    """Initializer dispatches on parameter name suffix: biases zero,
    gamma one (ref: initializer.py Initializer.__call__ patterns)."""
    net = nn.Sequential()
    net.add(nn.Dense(4, in_units=3), nn.BatchNorm())
    net.initialize(mx.initializer.Xavier())
    net(nd.ones((1, 3)))
    np.testing.assert_allclose(net[0].bias.data().asnumpy(), 0.0)
    np.testing.assert_allclose(net[1].gamma.data().asnumpy(), 1.0)
    np.testing.assert_allclose(net[1].beta.data().asnumpy(), 0.0)


def test_mixed_initializer():
    init = mx.initializer.Mixed(
        [".*special.*", ".*"],
        [mx.initializer.Constant(9.0), mx.initializer.Zero()])
    p1 = gluon.Parameter("special_weight", shape=(3,))
    p1.initialize(init=init)
    np.testing.assert_allclose(p1.data().asnumpy(), 9.0)
    p2 = gluon.Parameter("fc_weight", shape=(3, 3))
    p2.initialize(init=init)
    np.testing.assert_allclose(p2.data().asnumpy(), 0.0)


def test_registry_get_by_string():
    init = mx.initializer.get("xavier")
    assert isinstance(init, mx.initializer.Xavier)
    # gluon accepts string initializers too
    net = nn.Dense(2, in_units=2, weight_initializer="zeros")
    net.initialize()
    np.testing.assert_allclose(net.weight.data().asnumpy(), 0.0)


def test_init_reproducible_with_seed():
    mx.random.seed(42)
    a = _filled(mx.initializer.Uniform(1.0))
    mx.random.seed(42)
    b = _filled(mx.initializer.Uniform(1.0))
    np.testing.assert_allclose(a, b)
