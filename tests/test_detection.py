"""Detection op family tests — numpy loop oracles ported from the
reference kernels' specs (ref slots: tests/python/unittest/test_operator.py
test_psroipooling / test_deformable_* and tests/python/gpu counterparts).
"""
import math
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

# the numpy-oracle op tests are seconds-scale and stay in the quick
# lane; only the SSD end-to-end training class below is marked slow


def _nd(a):
    return mx.nd.array(np.asarray(a, dtype="float32"))


# ---------------------------------------------------------------------------
# DeformableConvolution
# ---------------------------------------------------------------------------

class TestDeformableConvolution:
    def test_zero_offset_matches_dense_conv(self):
        rs = np.random.RandomState(0)
        x = rs.randn(2, 4, 9, 9).astype("float32")
        w = rs.randn(6, 4, 3, 3).astype("float32")
        b = rs.randn(6).astype("float32")
        off = np.zeros((2, 2 * 9, 7, 7), "float32")
        out = nd.contrib.DeformableConvolution(
            _nd(x), _nd(off), _nd(w), _nd(b), kernel=(3, 3),
            num_filter=6).asnumpy()
        ref = nd.Convolution(_nd(x), _nd(w), _nd(b), kernel=(3, 3),
                             num_filter=6).asnumpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_integer_offset_shifts_sampling(self):
        rs = np.random.RandomState(1)
        x = rs.randn(1, 1, 8, 8).astype("float32")
        w = np.ones((1, 1, 1, 1), "float32")
        # constant offset (+1, +2): out[y,x] = x[y+1, x+2]
        off = np.zeros((1, 2, 8, 8), "float32")
        off[:, 0] = 1.0
        off[:, 1] = 2.0
        out = nd.contrib.DeformableConvolution(
            _nd(x), _nd(off), _nd(w), kernel=(1, 1), num_filter=1,
            no_bias=True).asnumpy()
        ref = np.zeros_like(x)
        ref[0, 0, :7, :6] = x[0, 0, 1:, 2:]
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_fractional_offset_bilinear(self):
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        w = np.ones((1, 1, 1, 1), "float32")
        off = np.zeros((1, 2, 4, 4), "float32")
        off[:, 0] = 0.5  # halfway between rows -> average
        out = nd.contrib.DeformableConvolution(
            _nd(x), _nd(off), _nd(w), kernel=(1, 1), num_filter=1,
            no_bias=True).asnumpy()
        ref = np.zeros((4, 4), "float32")
        for i in range(3):
            ref[i] = (x[0, 0, i] + x[0, 0, i + 1]) / 2
        ref[3] = 0.0  # y=3.5 is outside (>H-1 edge but valid<H) -> clamp
        # row 3 samples y=3.5: valid (<4) and clamps to row 3
        ref[3] = x[0, 0, 3]
        np.testing.assert_allclose(out[0, 0], ref, rtol=1e-5, atol=1e-5)

    def test_gradients_flow(self):
        rs = np.random.RandomState(2)
        x = _nd(rs.randn(1, 2, 5, 5))
        off = _nd(0.1 * rs.randn(1, 2 * 4, 4, 4))
        w = _nd(rs.randn(3, 2, 2, 2))
        for a in (x, off, w):
            a.attach_grad()
        with mx.autograd.record():
            y = nd.contrib.DeformableConvolution(
                x, off, w, kernel=(2, 2), num_filter=3, no_bias=True)
        y.backward()
        assert float(np.abs(x.grad.asnumpy()).sum()) > 0
        assert float(np.abs(off.grad.asnumpy()).sum()) > 0
        assert float(np.abs(w.grad.asnumpy()).sum()) > 0


# ---------------------------------------------------------------------------
# PSROIPooling
# ---------------------------------------------------------------------------

def psroi_oracle(data, rois, spatial_scale, output_dim, pooled, group):
    """Direct port of psroi_pooling.cc:56-110."""
    N, C, H, W = data.shape
    R = rois.shape[0]
    out = np.zeros((R, output_dim, pooled, pooled), "float32")
    for n in range(R):
        bi = int(rois[n, 0])
        rsw = round(rois[n, 1]) * spatial_scale
        rsh = round(rois[n, 2]) * spatial_scale
        rew = (round(rois[n, 3]) + 1.0) * spatial_scale
        reh = (round(rois[n, 4]) + 1.0) * spatial_scale
        rw = max(rew - rsw, 0.1)
        rh = max(reh - rsh, 0.1)
        bh, bw = rh / pooled, rw / pooled
        for ctop in range(output_dim):
            for ph in range(pooled):
                for pw in range(pooled):
                    hstart = int(np.floor(ph * bh + rsh))
                    wstart = int(np.floor(pw * bw + rsw))
                    hend = int(np.ceil((ph + 1) * bh + rsh))
                    wend = int(np.ceil((pw + 1) * bw + rsw))
                    hstart, hend = min(max(hstart, 0), H), min(max(hend, 0), H)
                    wstart, wend = min(max(wstart, 0), W), min(max(wend, 0), W)
                    gw = min(max(int(np.floor(pw * group / pooled)), 0),
                             group - 1)
                    gh = min(max(int(np.floor(ph * group / pooled)), 0),
                             group - 1)
                    c = (ctop * group + gh) * group + gw
                    if hend <= hstart or wend <= wstart:
                        continue
                    patch = data[bi, c, hstart:hend, wstart:wend]
                    out[n, ctop, ph, pw] = patch.mean()
    return out


class TestPSROIPooling:
    def test_matches_oracle(self):
        rs = np.random.RandomState(3)
        G, P, OD = 3, 3, 4
        data = rs.randn(2, OD * G * G, 12, 12).astype("float32")
        rois = np.array([[0, 1, 2, 8, 9],
                         [1, 0, 0, 11, 11],
                         [0, 4, 4, 6, 6]], "float32")
        out = nd.contrib.PSROIPooling(_nd(data), _nd(rois),
                                      spatial_scale=1.0, output_dim=OD,
                                      pooled_size=P, group_size=G).asnumpy()
        ref = psroi_oracle(data, rois, 1.0, OD, P, G)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_spatial_scale(self):
        rs = np.random.RandomState(4)
        data = rs.randn(1, 4, 8, 8).astype("float32")
        rois = np.array([[0, 2, 2, 13, 13]], "float32")
        out = nd.contrib.PSROIPooling(_nd(data), _nd(rois),
                                      spatial_scale=0.5, output_dim=1,
                                      pooled_size=2, group_size=2).asnumpy()
        ref = psroi_oracle(data, rois, 0.5, 1, 2, 2)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# DeformablePSROIPooling
# ---------------------------------------------------------------------------

def _bilin(img, h, w):
    H, W = img.shape
    h = min(max(h, 0.0), H - 1.0)
    w = min(max(w, 0.0), W - 1.0)
    h0, w0 = int(np.floor(h)), int(np.floor(w))
    h1, w1 = min(h0 + 1, H - 1), min(w0 + 1, W - 1)
    lh, lw = h - h0, w - w0
    return (img[h0, w0] * (1 - lh) * (1 - lw) + img[h0, w1] * (1 - lh) * lw
            + img[h1, w0] * lh * (1 - lw) + img[h1, w1] * lh * lw)


def def_psroi_oracle(data, rois, trans, scale, od, group, pooled,
                     part, spp, tstd, no_trans):
    """Direct port of deformable_psroi_pooling.cc:60-146."""
    N, C, H, W = data.shape
    R = rois.shape[0]
    ncls = 1 if no_trans else trans.shape[1] // 2
    cec = od // ncls
    out = np.zeros((R, od, pooled, pooled), "float32")
    for n in range(R):
        bi = int(rois[n, 0])
        rsw = round(rois[n, 1]) * scale - 0.5
        rsh = round(rois[n, 2]) * scale - 0.5
        rew = (round(rois[n, 3]) + 1.0) * scale - 0.5
        reh = (round(rois[n, 4]) + 1.0) * scale - 0.5
        rw = max(rew - rsw, 0.1)
        rh = max(reh - rsh, 0.1)
        bh, bw = rh / pooled, rw / pooled
        sbh, sbw = bh / spp, bw / spp
        for ctop in range(od):
            for ph in range(pooled):
                for pw in range(pooled):
                    ph_p = int(np.floor(ph / pooled * part))
                    pw_p = int(np.floor(pw / pooled * part))
                    cid = ctop // cec
                    tx = 0.0 if no_trans else \
                        trans[n, cid * 2, ph_p, pw_p] * tstd
                    ty = 0.0 if no_trans else \
                        trans[n, cid * 2 + 1, ph_p, pw_p] * tstd
                    wst = pw * bw + rsw + tx * rw
                    hst = ph * bh + rsh + ty * rh
                    gw = min(max(int(np.floor(pw * group / pooled)), 0),
                             group - 1)
                    gh = min(max(int(np.floor(ph * group / pooled)), 0),
                             group - 1)
                    c = (ctop * group + gh) * group + gw
                    s = cnt = 0
                    for ih in range(spp):
                        for iw in range(spp):
                            w_ = wst + iw * sbw
                            h_ = hst + ih * sbh
                            if w_ < -0.5 or w_ > W - 0.5 or h_ < -0.5 \
                                    or h_ > H - 0.5:
                                continue
                            s += _bilin(data[bi, c], h_, w_)
                            cnt += 1
                    out[n, ctop, ph, pw] = 0.0 if cnt == 0 else s / cnt
    return out


class TestDeformablePSROIPooling:
    def test_no_trans_matches_oracle(self):
        rs = np.random.RandomState(5)
        G = P = 3
        OD = 2
        data = rs.randn(1, OD * G * G, 10, 10).astype("float32")
        rois = np.array([[0, 1, 1, 8, 8]], "float32")
        out = nd.contrib.DeformablePSROIPooling(
            _nd(data), _nd(rois), spatial_scale=1.0, output_dim=OD,
            group_size=G, pooled_size=P, sample_per_part=2,
            no_trans=True).asnumpy()
        ref = def_psroi_oracle(data, rois, None, 1.0, OD, G, P, P, 2,
                               0.0, True)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_with_trans_matches_oracle(self):
        rs = np.random.RandomState(6)
        G = P = 2
        OD = 4  # 2 classes x 2 channels
        data = rs.randn(2, OD * G * G, 9, 9).astype("float32")
        rois = np.array([[0, 0, 0, 7, 7], [1, 2, 1, 8, 6]], "float32")
        trans = 0.3 * rs.randn(2, 4, P, P).astype("float32")
        out = nd.contrib.DeformablePSROIPooling(
            _nd(data), _nd(rois), _nd(trans), spatial_scale=1.0,
            output_dim=OD, group_size=G, pooled_size=P, part_size=P,
            sample_per_part=2, trans_std=0.1).asnumpy()
        ref = def_psroi_oracle(data, rois, trans, 1.0, OD, G, P, P, 2,
                               0.1, False)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# Proposal / MultiProposal
# ---------------------------------------------------------------------------

def _anchors_oracle(stride, scales, ratios):
    base = np.array([0, 0, stride - 1, stride - 1], "float32")
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx, cy = base[0] + 0.5 * (w - 1), base[1] + 0.5 * (h - 1)
    size = w * h
    out = []
    for r in ratios:
        sr = math.floor(size / r)
        nw = math.floor(math.sqrt(sr) + 0.5)
        nh = math.floor(nw * r + 0.5)
        for s in scales:
            sw, sh = nw * s, nh * s
            out.append([cx - 0.5 * (sw - 1), cy - 0.5 * (sh - 1),
                        cx + 0.5 * (sw - 1), cy + 0.5 * (sh - 1)])
    return np.array(out, "float32")


class TestProposal:
    def _mk(self, rs, H=6, W=8, A=3):
        cls_prob = rs.rand(1, 2 * A, H, W).astype("float32")
        bbox_pred = 0.1 * rs.randn(1, 4 * A, H, W).astype("float32")
        im_info = np.array([[H * 16.0, W * 16.0, 1.0]], "float32")
        return cls_prob, bbox_pred, im_info

    def test_shapes_and_validity(self):
        rs = np.random.RandomState(7)
        cls_prob, bbox_pred, im_info = self._mk(rs)
        rois = nd.contrib.Proposal(
            _nd(cls_prob), _nd(bbox_pred), _nd(im_info),
            rpn_pre_nms_top_n=50, rpn_post_nms_top_n=16,
            scales=(8,), ratios=(0.5, 1, 2), threshold=0.7,
            rpn_min_size=4).asnumpy()
        assert rois.shape == (16, 5)
        assert (rois[:, 0] == 0).all()
        # boxes clipped to image
        assert (rois[:, 1] >= 0).all() and (rois[:, 2] >= 0).all()
        assert (rois[:, 3] <= im_info[0, 1] - 1).all()
        assert (rois[:, 4] <= im_info[0, 0] - 1).all()

    def test_top_proposal_is_highest_scoring_box(self):
        """With deltas=0 and no NMS interference, the first output is the
        anchor with the highest fg score (after clipping)."""
        rs = np.random.RandomState(8)
        H, W, A = 4, 4, 1
        cls_prob = np.zeros((1, 2, H, W), "float32")
        cls_prob[0, 1] = rs.rand(H, W)
        best = np.unravel_index(cls_prob[0, 1].argmax(), (H, W))
        bbox_pred = np.zeros((1, 4, H, W), "float32")
        im_info = np.array([[64.0, 64.0, 1.0]], "float32")
        rois, scores = nd.contrib.Proposal(
            _nd(cls_prob), _nd(bbox_pred), _nd(im_info),
            rpn_pre_nms_top_n=16, rpn_post_nms_top_n=4,
            scales=(4,), ratios=(1,), feature_stride=16,
            rpn_min_size=4, output_score=True)
        rois = rois.asnumpy()
        scores = scores.asnumpy()
        anc = _anchors_oracle(16, [4], [1])[0]
        want = anc + np.array([best[1] * 16, best[0] * 16,
                               best[1] * 16, best[0] * 16], "float32")
        want = np.clip(want, 0, 63)
        np.testing.assert_allclose(rois[0, 1:], want, atol=1e-3)
        assert abs(scores[0, 0] - cls_prob[0, 1][best]) < 1e-5

    def test_nms_suppresses_overlaps(self):
        """Two anchors at the same location: only one survives NMS."""
        H, W, A = 2, 2, 2
        cls_prob = np.zeros((1, 2 * A, H, W), "float32")
        cls_prob[0, A:] = 0.9
        cls_prob[0, A, 0, 0] = 0.95
        bbox_pred = np.zeros((1, 4 * A, H, W), "float32")
        im_info = np.array([[32.0, 32.0, 1.0]], "float32")
        rois, sc = nd.contrib.Proposal(
            _nd(cls_prob), _nd(bbox_pred), _nd(im_info),
            rpn_pre_nms_top_n=8, rpn_post_nms_top_n=8,
            scales=(4, 4.01), ratios=(1,), feature_stride=16,
            rpn_min_size=4, threshold=0.5, output_score=True)
        sc = sc.asnumpy().ravel()
        # duplicates cycle — count distinct surviving scores
        assert len(np.unique(np.round(sc, 5))) <= 4

    def test_multi_proposal_batches(self):
        rs = np.random.RandomState(9)
        H, W, A = 4, 4, 2
        cls_prob = rs.rand(3, 2 * A, H, W).astype("float32")
        bbox_pred = 0.05 * rs.randn(3, 4 * A, H, W).astype("float32")
        im_info = np.tile(np.array([[64.0, 64.0, 1.0]], "float32"),
                          (3, 1))
        rois = nd.contrib.MultiProposal(
            _nd(cls_prob), _nd(bbox_pred), _nd(im_info),
            rpn_pre_nms_top_n=20, rpn_post_nms_top_n=8,
            scales=(4,), ratios=(0.5, 1), rpn_min_size=2).asnumpy()
        assert rois.shape == (24, 5)
        np.testing.assert_array_equal(rois[:, 0],
                                      np.repeat([0.0, 1.0, 2.0], 8))
        # per-image result equals single-image Proposal
        rois0 = nd.contrib.Proposal(
            _nd(cls_prob[:1]), _nd(bbox_pred[:1]), _nd(im_info[:1]),
            rpn_pre_nms_top_n=20, rpn_post_nms_top_n=8,
            scales=(4,), ratios=(0.5, 1), rpn_min_size=2).asnumpy()
        np.testing.assert_allclose(rois[:8], rois0, atol=1e-4)


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------

class TestMultiBoxTarget:
    def test_simple_assignment(self):
        # one gt box exactly equal to anchor 1 -> anchor 1 positive
        anchors = np.array([[[0.0, 0.0, 0.2, 0.2],
                             [0.4, 0.4, 0.8, 0.8],
                             [0.1, 0.6, 0.3, 0.9]]], "float32")
        label = np.array([[[2, 0.4, 0.4, 0.8, 0.8],
                           [-1, -1, -1, -1, -1]]], "float32")
        cls_pred = np.zeros((1, 4, 3), "float32")
        lt, lm, ct = nd.contrib.MultiBoxTarget(
            _nd(anchors), _nd(label), _nd(cls_pred))
        ct = ct.asnumpy()[0]
        lm = lm.asnumpy()[0].reshape(3, 4)
        lt = lt.asnumpy()[0].reshape(3, 4)
        assert ct[1] == 3.0           # class 2 + 1
        assert ct[0] == 0.0 and ct[2] == 0.0  # negatives (no mining)
        assert (lm[1] == 1).all() and (lm[0] == 0).all()
        # perfect match -> zero offsets
        np.testing.assert_allclose(lt[1], 0.0, atol=1e-5)

    def test_loc_target_encoding(self):
        anchors = np.array([[[0.0, 0.0, 0.5, 0.5]]], "float32")
        label = np.array([[[0, 0.1, 0.1, 0.6, 0.6]]], "float32")
        lt, lm, ct = nd.contrib.MultiBoxTarget(
            _nd(anchors), _nd(label), _nd(np.zeros((1, 2, 1), "float32")),
            variances=(0.1, 0.1, 0.2, 0.2))
        lt = lt.asnumpy()[0]
        # same size, center shifted +0.1 => dx = 0.1/0.5/0.1 = 2.0
        np.testing.assert_allclose(lt, [2.0, 2.0, 0.0, 0.0], atol=1e-4)

    def test_no_gt_all_ignore(self):
        anchors = np.array([[[0.0, 0.0, 0.2, 0.2],
                             [0.4, 0.4, 0.8, 0.8]]], "float32")
        label = -np.ones((1, 2, 5), "float32")
        lt, lm, ct = nd.contrib.MultiBoxTarget(
            _nd(anchors), _nd(label), _nd(np.zeros((1, 2, 2), "float32")))
        assert (ct.asnumpy() == -1.0).all()
        assert (lm.asnumpy() == 0).all()

    def test_negative_mining(self):
        rs = np.random.RandomState(10)
        A = 8
        anchors = np.zeros((1, A, 4), "float32")
        anchors[0, :, 0] = np.linspace(0, 0.7, A)
        anchors[0, :, 1] = 0.0
        anchors[0, :, 2] = anchors[0, :, 0] + 0.25
        anchors[0, :, 3] = 0.3
        label = np.array([[[1, 0.0, 0.0, 0.25, 0.3]]], "float32")
        cls_pred = rs.randn(1, 3, A).astype("float32")
        lt, lm, ct = nd.contrib.MultiBoxTarget(
            _nd(anchors), _nd(label), _nd(cls_pred),
            negative_mining_ratio=2.0, negative_mining_thresh=0.3)
        ct = ct.asnumpy()[0]
        assert ct[0] == 2.0  # the matching anchor, class 1 + 1
        n_pos = (ct > 0).sum()
        n_neg = (ct == 0).sum()
        n_ign = (ct == -1).sum()
        assert n_pos == 1 and n_neg == 2  # ratio 2 x 1 positive
        assert n_ign == A - 3


# ---------------------------------------------------------------------------
# RROIAlign
# ---------------------------------------------------------------------------

class TestRROIAlign:
    def test_axis_aligned_equals_average(self):
        """theta=0 over a constant region -> plain average."""
        data = np.zeros((1, 1, 8, 8), "float32")
        data[0, 0, 1:7, 1:7] = 5.0
        rois = np.array([[0, 4.0, 4.0, 4.0, 4.0, 0.0]], "float32")
        out = nd.contrib.RROIAlign(_nd(data), _nd(rois),
                                   pooled_size=(2, 2), spatial_scale=1.0,
                                   sampling_ratio=2).asnumpy()
        # all bilinear samples (y,x in [2.5, 5.5]) sit strictly inside the
        # constant 5.0 region [1, 7) so every bin averages to exactly 5
        np.testing.assert_allclose(out, 5.0, atol=1e-4)

    def test_rotation_90_degrees(self):
        rs = np.random.RandomState(11)
        data = rs.rand(1, 1, 12, 12).astype("float32")
        roi0 = np.array([[0, 6.0, 6.0, 6.0, 2.0, 0.0]], "float32")
        roi90 = np.array([[0, 6.0, 6.0, 6.0, 2.0, 90.0]], "float32")
        out0 = nd.contrib.RROIAlign(_nd(data), _nd(roi0),
                                    pooled_size=(1, 3),
                                    sampling_ratio=2).asnumpy()
        out90 = nd.contrib.RROIAlign(_nd(data), _nd(roi90),
                                     pooled_size=(1, 3),
                                     sampling_ratio=2).asnumpy()
        # 90-degree rotation about the center swaps the sampled axis;
        # outputs must differ for generic data but share the center value
        assert out0.shape == out90.shape == (1, 1, 1, 3)
        assert abs(out0[0, 0, 0, 1] - out90[0, 0, 0, 1]) < 0.2


# ---------------------------------------------------------------------------
# Crop
# ---------------------------------------------------------------------------

class TestCrop:
    def test_offset_crop(self):
        x = np.arange(2 * 3 * 6 * 6, dtype="float32").reshape(2, 3, 6, 6)
        out = nd.Crop(_nd(x), num_args=1, offset=(1, 2),
                      h_w=(3, 3)).asnumpy()
        np.testing.assert_array_equal(out, x[:, :, 1:4, 2:5])

    def test_center_crop(self):
        x = np.arange(1 * 1 * 6 * 6, dtype="float32").reshape(1, 1, 6, 6)
        out = nd.Crop(_nd(x), num_args=1, h_w=(2, 2),
                      center_crop=True).asnumpy()
        np.testing.assert_array_equal(out, x[:, :, 2:4, 2:4])

    def test_crop_like(self):
        x = _nd(np.arange(64, dtype="float32").reshape(1, 1, 8, 8))
        like = _nd(np.zeros((1, 1, 3, 5), "float32"))
        out = nd.Crop(x, like, num_args=2).asnumpy()
        np.testing.assert_array_equal(out, x.asnumpy()[:, :, :3, :5])


@pytest.mark.slow  # minutes-scale: full training loops + JPEG .rec
class TestSSDExample:
    def test_ssd_pipeline_trains(self):
        """End-to-end SSD example (example/ssd/train_ssd.py): prior ->
        target assignment -> masked joint loss -> SGD must reduce the
        loss, and MultiBoxDetection must decode."""
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "example", "ssd", "train_ssd.py")
        spec = importlib.util.spec_from_file_location("train_ssd", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        net, losses = mod.train(epochs=80, log=lambda *a: None)
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        rng = np.random.RandomState(1)
        x, _ = mod.make_batch(rng, batch=2)
        dets = mod.detect(net, x)
        assert dets.shape[0] == 2 and dets.shape[2] == 6

    def test_ssd_trains_from_rec_via_image_det_iter(self, tmp_path):
        """VERDICT r2 item 7 criterion: the SSD example trains from a
        .rec through ImageDetIter with label-aware crop/pad/flip."""
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "example", "ssd", "train_ssd.py")
        spec = importlib.util.spec_from_file_location("train_ssd2", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        # determinism comes from conftest's _seed_rngs (incl. python
        # `random`, which the Det augmenters draw from — an unseeded
        # augmenter stream made the 0.7 threshold ~1/50 flaky)
        net, losses = mod.train_from_rec(str(tmp_path), epochs=8,
                                         log=lambda *a: None)
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
