"""Flight recorder + watchdog + compile attribution tests (ISSUE 8):
the always-on black box under the opt-in telemetry plane. Ring
semantics, dump triggers (exception hooks, SIGUSR2, watchdog), the
shared hot-path guard, straggler gauges on the heartbeat wire,
compile/device-time attribution, and the subprocess post-mortems the
acceptance criteria name (crash mid-epoch, SIGKILLed stall — each
leaving a shard ``tools/trace_merge.py`` merges with a live profiler
shard)."""
import glob
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, gluon, profiler
from mxnet_tpu import kvstore_async as KA
from mxnet_tpu._debug import faultpoint, flightrec, watchdog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHTREC_DIR", str(tmp_path))
    profiler._reset()
    profiler.set_config(filename=str(tmp_path / "live.json"),
                        xprof=False)
    faultpoint.reset()
    watchdog.reset()
    flightrec.reset_ring()
    with flightrec._context_lock:
        flightrec._context.clear()
    if not flightrec.ENABLED:
        flightrec.enable()
    yield
    faultpoint.reset()
    watchdog.reset()
    flightrec.reset_ring()
    flightrec.configure(capacity=4096, enabled=True)
    profiler._reset()
    profiler.set_config(filename="profile.json", xprof=True)


def _frec_dumps(tmp_path, trigger="*"):
    return sorted(glob.glob(
        str(tmp_path / ("flightrec_r*_%s_*.json" % trigger))))


# -- ring semantics ----------------------------------------------------------

def test_ring_capacity_and_overwrite():
    """deque(maxlen) semantics: the ring keeps exactly the newest
    ``capacity`` entries, oldest fall off, accounting stays truthful."""
    flightrec.configure(capacity=16)
    try:
        flightrec.reset_ring()
        for i in range(40):
            flightrec.record_marker("m%d" % i)
        st = flightrec.stats()
        assert st["capacity"] == 16
        assert st["buffered"] == 16
        assert st["recorded"] == 40  # all appends counted, 24 overwritten
        names = [e[1] for e in flightrec.snapshot()]
        assert names == ["m%d" % i for i in range(24, 40)]
    finally:
        flightrec.configure(capacity=4096)


def test_ring_shrink_keeps_newest():
    flightrec.configure(capacity=64)
    try:
        flightrec.reset_ring()
        for i in range(32):
            flightrec.record_marker("m%d" % i)
        flightrec.configure(capacity=16)
        names = [e[1] for e in flightrec.snapshot()]
        assert names == ["m%d" % i for i in range(16, 32)]
        assert flightrec.stats()["capacity"] == 16
    finally:
        flightrec.configure(capacity=4096)


def test_reset_ring_clears_entries_and_counters(tmp_path):
    flightrec.record_marker("x")
    flightrec.dump("manual")
    assert flightrec.stats()["dumps"] == 1
    flightrec.reset_ring()
    st = flightrec.stats()
    assert st["buffered"] == 0 and st["recorded"] == 0 \
        and st["dumps"] == 0
    assert flightrec.last_dumps() == []


def test_enable_disable_syncs_shared_guard():
    """flightrec.ENABLED and profiler._ACTIVE are the two inputs of the
    ONE shared hot-path guard (profiler._LIVE)."""
    assert profiler._LIVE  # recorder on by default
    prev = flightrec.disable()
    assert prev is True
    assert not profiler._LIVE
    flightrec.enable()
    assert profiler._LIVE
    # a profile run keeps the guard live even with the recorder off
    flightrec.disable()
    profiler.set_state("run")
    try:
        assert profiler._LIVE
    finally:
        profiler.set_state("stop")
    assert not profiler._LIVE
    flightrec.enable()


# -- hot-path feeds ----------------------------------------------------------

def test_eager_ops_leave_bare_name_breadcrumbs():
    """With profiling OFF, the per-op dispatch path appends bare op
    names (no clock read) — order exact, anchored at dump time."""
    flightrec.reset_ring()
    a = mx.nd.array(np.ones((8, 8), np.float32))
    b = mx.nd.softmax(a * 2 + 1)
    b.wait_to_read()
    engine.wait_for_all()
    entries = flightrec.snapshot()
    bare = [e for e in entries if isinstance(e, str)]
    assert "softmax" in bare
    assert "multiply" in bare and "add" in bare
    # dispatch order is preserved verbatim
    assert bare.index("multiply") < bare.index("add") \
        < bare.index("softmax")


def test_profiling_on_records_full_spans_into_ring():
    """While a profile run is active the ring gets the full timestamped
    span tuples (record_op fans out before gating on _ACTIVE)."""
    flightrec.reset_ring()
    profiler.set_state("run")
    try:
        a = mx.nd.array(np.ones((4, 4), np.float32))
        (a + 1).wait_to_read()
        engine.wait_for_all()
    finally:
        profiler.set_state("stop")
    spans = [e for e in flightrec.snapshot()
             if not isinstance(e, str) and e[0] == "X"]
    assert spans, "no timestamped spans reached the ring"
    ph, name, cat, tid, ts_s, dur_us, args = spans[0]
    assert isinstance(ts_s, float) and dur_us >= 0


def test_counters_and_markers_feed_ring_with_profiling_off():
    flightrec.reset_ring()
    profiler.account("unit.bytes", 64, emit=True)
    profiler.marker("unit.marker", args={"k": 1})
    kinds = {e[0] for e in flightrec.snapshot() if not isinstance(e, str)}
    assert "C" in kinds and "i" in kinds
    # the trace itself stayed empty: profiling is off
    assert profiler.metrics()["num_events"] == 0


# -- dump contents and rendering ---------------------------------------------

def test_dump_bundles_stacks_metrics_faults_context(tmp_path):
    flightrec.record_marker("breadcrumb")
    flightrec.set_context("unit_ctx", {"hello": 1})
    path = flightrec.dump("manual", extra={"why": "test"})
    d = json.load(open(path))
    meta = d["metadata"]
    assert meta["flightrec"] is True
    assert meta["trigger"] == "manual"
    assert meta["trigger_info"] == {"why": "test"}
    assert meta["context"]["unit_ctx"] == {"hello": 1}
    assert meta["ring"]["buffered"] >= 1
    # all-thread python stacks: at least this (the main) thread
    assert any("MainThread" in k for k in meta["python_stacks"])
    assert any("test_dump_bundles" in ln
               for lines in meta["python_stacks"].values()
               for ln in lines)
    # metrics snapshot carries the provider sections
    for section in ("watchdog", "faults", "flightrec"):
        assert section in meta["metrics"], sorted(meta["metrics"])
    assert "faults" in meta
    names = {e.get("name") for e in d["traceEvents"]}
    assert "breadcrumb" in names
    assert "flightrec:manual" in names  # the dump's own marker


def test_bare_names_render_anchored_to_neighbors(tmp_path):
    """A bare-name breadcrumb renders as an instant event at the
    nearest timestamped neighbor, flagged ts_approx; leading ones
    backfill from the first anchor."""
    flightrec.reset_ring()
    flightrec.RING.append("lead_op")       # before any anchor
    flightrec.record_marker("anchor1")
    flightrec.RING.append("mid_op")
    flightrec.record_marker("anchor2")
    path = flightrec.dump("manual")
    evs = json.load(open(path))["traceEvents"]
    by_name = {e["name"]: e for e in evs if e.get("name", "").endswith(
        ("_op", "anchor1", "anchor2"))}
    a1, a2 = by_name["anchor1"], by_name["anchor2"]
    lead, mid = by_name["lead_op"], by_name["mid_op"]
    assert lead["args"]["ts_approx"] and mid["args"]["ts_approx"]
    assert lead["ts"] == a1["ts"]  # backfilled from the first anchor
    assert mid["ts"] == a1["ts"]   # carried forward from anchor1
    assert a1["ts"] <= a2["ts"]


def test_bare_names_render_without_any_anchor(tmp_path):
    flightrec.reset_ring()
    flightrec.RING.append("only_op")
    path = flightrec.dump("manual")
    evs = json.load(open(path))["traceEvents"]
    ev = next(e for e in evs if e["name"] == "only_op")
    assert ev["args"]["ts_approx"] and ev["ts"] >= 0


def test_dump_storm_cap(tmp_path, monkeypatch):
    monkeypatch.setattr(flightrec, "_MAX_DUMPS", 2)
    flightrec.record_marker("x")
    assert flightrec.dump("manual") is not None
    assert flightrec.dump("manual") is not None
    assert flightrec.dump("manual") is None  # capped
    assert flightrec.stats()["dumps"] == 2
    # an explicit path (operator asked for it) bypasses the storm cap
    p = flightrec.dump("manual", path=str(tmp_path / "explicit.json"))
    assert p is not None and os.path.exists(p)


def test_dump_failure_swallowed_and_counted(tmp_path, monkeypatch):
    # a FILE where the dump dir should be: lazy creation (makedirs)
    # cannot help, the shard write fails — swallowed and counted
    blocker = tmp_path / "blocked"
    blocker.write_text("not a directory")
    monkeypatch.setenv("MXTPU_FLIGHTREC_DIR", str(blocker))
    assert flightrec.dump("manual", swallow=True) is None
    assert flightrec.stats()["dump_failures"] == 1
    with pytest.raises(Exception):
        flightrec.dump("manual", swallow=False)


def test_dump_dir_created_lazily(tmp_path, monkeypatch):
    # ISSUE 13 satellite: a missing dump dir is created at the first
    # write (default ./flightrec), never at import
    target = tmp_path / "fresh" / "flightrec"
    monkeypatch.setenv("MXTPU_FLIGHTREC_DIR", str(target))
    assert not target.exists()
    p = flightrec.dump("manual")
    assert p is not None and os.path.exists(p)
    assert str(target) == os.path.dirname(p)


def test_default_dump_dir_is_flightrec_subdir(tmp_path, monkeypatch):
    monkeypatch.delenv("MXTPU_FLIGHTREC_DIR", raising=False)
    monkeypatch.chdir(tmp_path)
    assert flightrec.dump_dir() == str(tmp_path / "flightrec")
    assert not (tmp_path / "flightrec").exists()  # lazy until a write


# -- crash hooks -------------------------------------------------------------

def test_excepthook_dumps_and_chains(tmp_path, monkeypatch):
    called = []
    monkeypatch.setattr(flightrec, "_prev_sys_hook",
                        lambda *a: called.append(a))
    try:
        raise ValueError("unit boom")
    except ValueError:
        ei = sys.exc_info()
    flightrec._sys_excepthook(*ei)
    assert len(called) == 1, "previous excepthook must still run"
    dumps = _frec_dumps(tmp_path, "exception")
    assert len(dumps) == 1
    meta = json.load(open(dumps[0]))["metadata"]
    assert "unit boom" in meta["trigger_info"]["exception"]


def test_threading_excepthook_dumps_and_skips_systemexit(tmp_path,
                                                         monkeypatch):
    chained = []
    monkeypatch.setattr(flightrec, "_prev_threading_hook",
                        lambda a: chained.append(a))

    class Args:
        def __init__(self, exc_type, exc_value):
            self.exc_type = exc_type
            self.exc_value = exc_value
            self.exc_traceback = None
            self.thread = None

    flightrec._threading_excepthook(Args(SystemExit, SystemExit(0)))
    assert _frec_dumps(tmp_path, "thread-exception") == []
    flightrec._threading_excepthook(Args(RuntimeError,
                                         RuntimeError("worker died")))
    dumps = _frec_dumps(tmp_path, "thread-exception")
    assert len(dumps) == 1
    meta = json.load(open(dumps[0]))["metadata"]
    assert "worker died" in meta["trigger_info"]["exception"]
    assert len(chained) == 2  # chained for BOTH (SystemExit included)


def test_install_uninstall_roundtrip():
    assert flightrec._installed  # installed at import (hooks default on)
    assert sys.excepthook is flightrec._sys_excepthook
    assert signal.getsignal(signal.SIGUSR2) is flightrec._sigusr2_handler
    try:
        flightrec.uninstall()
        assert sys.excepthook is not flightrec._sys_excepthook
        assert signal.getsignal(signal.SIGUSR2) \
            is not flightrec._sigusr2_handler
    finally:
        flightrec.install()
    assert sys.excepthook is flightrec._sys_excepthook
    flightrec.install()  # idempotent: no double-chain
    assert flightrec._prev_sys_hook is not flightrec._sys_excepthook


def test_faulthandler_file_appends_across_incarnations(tmp_path, monkeypatch):
    """Regression: an elastic restart in the same dump dir (same
    MXTPU_PROC_ID) must not truncate the previous incarnation's native
    stacks — install() opens the fatal file in append mode, and the
    clean-exit cleanup removes it only when empty."""
    import faulthandler
    fatal = tmp_path / "flightrec_r0_fatal.txt"
    fatal.write_text("previous incarnation's SIGSEGV stacks\n")
    # simulate the fresh process: hooks not yet installed, faulthandler
    # not yet owned (pytest enables it globally — restore after)
    had_fh = faulthandler.is_enabled()
    flightrec.uninstall()
    if had_fh:
        faulthandler.disable()
    try:
        flightrec.install()
        assert flightrec._fatal_file is not None
        assert "previous incarnation" in fatal.read_text()
        flightrec._cleanup_fatal_file(str(fatal))
        # non-empty: the preserved post-mortem is NOT litter
        assert fatal.exists()
        assert "previous incarnation" in fatal.read_text()
    finally:
        flightrec.uninstall()
        if had_fh:
            faulthandler.enable()
        flightrec.install()


def test_sigusr2_while_holding_profiler_lock_does_not_deadlock(tmp_path):
    """Regression: the handler preempts the main thread between
    bytecodes, and dump() takes profiler._lock — non-reentrant. With the
    signal landing while THIS thread holds that lock (any account() on a
    kvstore byte ledger is such a window), an inline dump would deadlock
    the process; the handler must hand off to a helper thread instead."""
    with profiler._lock:
        os.kill(os.getpid(), signal.SIGUSR2)
        time.sleep(0.05)  # handler ran inline here; dump thread blocks
    deadline = time.monotonic() + 10.0
    while flightrec._sigusr2_inflight.locked() \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert _frec_dumps(tmp_path, "sigusr2")


def _deterministic_run(kick_at=None):
    """6 deterministic fused steps; optionally SIGUSR2 ourselves
    mid-run. Returns (per-step losses, final param bytes)."""
    mx.random.seed(7)
    np.random.seed(7)
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize(mx.init.Uniform(0.1), force_reinit=True)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), trainer)
    x = mx.nd.array(np.random.rand(4, 8).astype(np.float32))
    y = mx.nd.array(np.random.rand(4, 4).astype(np.float32))
    losses = []
    for i in range(6):
        if i == kick_at:
            os.kill(os.getpid(), signal.SIGUSR2)
        loss = step(x, y, batch_size=4)
        losses.append(loss.asnumpy().copy())
    # ordered values, not a dict: a fresh net gets fresh auto-generated
    # param name prefixes, but the (weight, bias) order is stable
    params = [p.data().asnumpy().tobytes()
              for p in net.collect_params().values()]
    return losses, params


def test_sigusr2_dump_is_loss_and_bitwise_neutral(tmp_path):
    """An on-demand SIGUSR2 dump mid-training changes nothing: same
    per-step losses, bitwise-identical final params — and one shard
    with trigger 'sigusr2' lands on disk."""
    base_losses, base_params = _deterministic_run(kick_at=None)
    watchdog.reset()
    kicked_losses, kicked_params = _deterministic_run(kick_at=3)
    # the handler hands the dump to a helper thread (dumping inline from
    # a signal handler could deadlock on the profiler lock): wait for it
    deadline = time.monotonic() + 10.0
    while flightrec._sigusr2_inflight.locked() \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    dumps = _frec_dumps(tmp_path, "sigusr2")
    assert len(dumps) == 1
    meta = json.load(open(dumps[0]))["metadata"]
    assert meta["trigger"] == "sigusr2"
    for a, b in zip(base_losses, kicked_losses):
        assert a.tobytes() == b.tobytes()
    assert base_params == kicked_params


# -- watchdog ----------------------------------------------------------------

def test_watchdog_arms_after_min_samples_and_thresholds():
    watchdog.configure(factor=4.0, min_s=0.05, min_samples=3)
    assert watchdog.threshold_s() is None
    for dur in (0.01, 0.02, 0.01):
        watchdog.step_begin()
        time.sleep(dur)
        watchdog.step_end()
    thr = watchdog.threshold_s()
    assert thr is not None
    # max(factor * median, min_s); median ~= 0.01-0.03
    assert thr >= 0.05
    st = watchdog.stats()
    assert st["armed"] == 1 and st["steps"] == 3
    assert watchdog.last_step()[0] == 3


def test_watchdog_warmup_steps_excluded_from_median():
    watchdog.configure(factor=2.0, min_s=0.01, min_samples=2)
    watchdog.step_begin()
    time.sleep(0.3)
    watchdog.step_end(warmup=True)  # the compile step
    st = watchdog.stats()
    assert st["warmup_steps"] == 1 and st["steps"] == 0
    assert watchdog.threshold_s() is None  # warmup never arms
    for _ in range(2):
        watchdog.step_begin()
        time.sleep(0.01)
        watchdog.step_end()
    assert watchdog.stats()["median_s"] < 0.1  # 0.3s warmup not in it


def test_watchdog_reentrant_outer_step_owns_beacon():
    watchdog.configure(min_samples=1)
    watchdog.step_begin()          # outer (elastic_train_loop)
    watchdog.step_begin()          # nested (fused step)
    time.sleep(0.02)
    watchdog.step_end()            # nested end: beacon still in flight
    assert watchdog.stats()["steps"] == 0
    watchdog.step_end()
    assert watchdog.stats()["steps"] == 1
    assert watchdog.last_step()[1] >= 0.02


def test_watchdog_check_now_idle_is_false():
    watchdog.configure(min_samples=1)
    assert watchdog.check_now() is False  # nothing in flight
    watchdog.step_begin()
    watchdog.step_end()
    assert watchdog.check_now() is False  # in-flight step completed


def test_watchdog_trips_on_kvstore_stall_one_dump_per_stall(tmp_path):
    """E2E: a faultpoint delay in kvstore.pull wedges a beaconed step;
    the watchdog daemon trips within the bound, dumps the flight record
    exactly once for that stall, and a second stall dumps again."""
    watchdog.configure(factor=3.0, min_s=0.3, poll_s=0.02,
                       min_samples=3)
    srv = KA.AsyncPSServer()
    cli = KA.AsyncPSClient("127.0.0.1", srv.port)
    try:
        cli.init("w", np.zeros(8, np.float32))

        def beat_step():
            watchdog.step_begin()
            cli.pull("w")
            watchdog.step_end()

        for _ in range(4):
            beat_step()
        assert watchdog.threshold_s() is not None
        assert watchdog.stats()["stalls"] == 0

        faultpoint.configure({"kvstore.pull": "delay:1200ms@n=1"})
        t0 = time.monotonic()
        beat_step()
        wall = time.monotonic() - t0
        assert wall >= 1.0  # the injected stall really happened
        st = watchdog.stats()
        assert st["stalls"] == 1 and st["dumps"] == 1
        # tripped while the step was still wedged, not at step_end
        assert st["last_stall_elapsed_s"] < wall
        assert st["last_stall_elapsed_s"] >= 0.3
        dumps = _frec_dumps(tmp_path, "watchdog")
        assert len(dumps) == 1
        meta = json.load(open(dumps[0]))["metadata"]
        assert meta["trigger"] == "watchdog"
        assert meta["trigger_info"]["threshold_s"] >= 0.3
        assert meta["trigger_info"]["step"] == st["last_stall_step"]

        # healthy steps after the stall: no further dumps
        for _ in range(3):
            beat_step()
        assert watchdog.stats()["stalls"] == 1
        assert len(_frec_dumps(tmp_path, "watchdog")) == 1

        # a SECOND stall is a new incident: one more dump
        faultpoint.configure({"kvstore.pull": "delay:1200ms@n=1"})
        beat_step()
        assert watchdog.stats()["stalls"] == 2
        assert len(_frec_dumps(tmp_path, "watchdog")) == 2
    finally:
        cli.stop_server()
        srv.stop()


def test_watchdog_never_false_positives_on_compile_step(tmp_path):
    """A faultpoint delay in fused_step.trace makes the compile step
    ~40x the steady-state step time — but warm-up steps never feed the
    median and the watchdog is unarmed until enough representative
    steps completed, so it must NOT trip."""
    watchdog.configure(factor=2.0, min_s=0.05, poll_s=0.01,
                       min_samples=2)
    faultpoint.configure({"fused_step.trace": "delay:800ms@n=1"})
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize(mx.init.Uniform(0.1), force_reinit=True)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), trainer)
    x = mx.nd.array(np.ones((4, 8), np.float32))
    y = mx.nd.array(np.zeros((4, 4), np.float32))
    for _ in range(5):
        step(x, y, batch_size=4)
    assert step.last_mode == "fused", step.last_mode
    st = watchdog.stats()
    assert faultpoint.triggers("fused_step.trace") == 1  # delay fired
    assert st["stalls"] == 0 and st["dumps"] == 0
    assert st["warmup_steps"] >= 2  # eager-warming + delayed compile
    assert st["steps"] >= 2         # the fused steady-state steps
    assert st["median_s"] < 0.4     # 0.8s compile not in the median
    assert _frec_dumps(tmp_path, "watchdog") == []


# -- straggler gauges on the heartbeat wire ----------------------------------

@pytest.fixture
def _only_my_servers(monkeypatch):
    """_server_stats aggregates over every live AsyncPSServer; a
    stopped-but-uncollected server from an earlier test (a handler
    thread sleeping out an injected delay keeps it referenced) would
    leak phantom ranks into these exact-gauge assertions. Give each
    unit test a private registry."""
    import weakref
    monkeypatch.setattr(KA, "_SERVERS", weakref.WeakSet())


def test_server_stats_names_straggler_leave_one_out(_only_my_servers):
    """Unit: skew = own step duration over the median of the OTHERS'
    (leave-one-out), straggler when above MXTPU_STRAGGLER_FACTOR."""
    srv = KA.AsyncPSServer()
    try:
        now = time.monotonic()
        with srv._lock:
            srv._step_stats = {0: (0.05, 9, now), 1: (0.06, 9, now),
                               2: (0.5, 8, now)}
        ks = profiler.metrics()["kvstore_server"]
        assert ks["stragglers"] == [2]
        assert ks["straggler_count"] == 1
        assert ks["straggler.2"] == 1
        assert "straggler.0" not in ks and "straggler.1" not in ks
        assert ks["step_skew.2"] > 2.0
        assert ks["step_skew.0"] < 1.5 and ks["step_skew.1"] < 1.5
        assert ks["rank_step_s.2"] == 0.5
        assert ks["rank_step_seq.2"] == 8
    finally:
        srv.stop()


def test_server_stats_ages_out_dead_rank_step_entries(monkeypatch, _only_my_servers):
    """A rank that stopped beating (SIGKILL, no _OP_DONE) must fall out
    of the straggler gauges after MXTPU_PS_DEAD_TIMEOUT — its last
    duration must not distort the leave-one-out baseline, or keep it on
    the straggler list, forever."""
    monkeypatch.setenv("MXTPU_PS_DEAD_TIMEOUT", "3.0")
    srv = KA.AsyncPSServer()
    try:
        now = time.monotonic()
        with srv._lock:
            # rank 2 died mid-slow-step 10s ago; 0 and 1 are current
            srv._step_stats = {0: (0.05, 9, now), 1: (0.06, 9, now),
                               2: (0.5, 8, now - 10.0)}
        ks = profiler.metrics()["kvstore_server"]
        assert "rank_step_s.2" not in ks
        assert "step_skew.2" not in ks
        assert ks["stragglers"] == []
        assert ks["rank_step_s.0"] == 0.05 and ks["rank_step_s.1"] == 0.06
    finally:
        srv.stop()


def test_heartbeat_carries_step_duration_to_server(_only_my_servers):
    """The v1 timestamped beat rides the watchdog beacon's newest
    completed step (duration, seq) — no extra wire round trip."""
    watchdog.configure(min_samples=1)
    srv = KA.AsyncPSServer()
    cli = KA.AsyncPSClient("127.0.0.1", srv.port)
    try:
        cli.init("w", np.zeros(4, np.float32))  # negotiates v1
        assert cli._peer_version >= 1
        watchdog.step_begin()
        time.sleep(0.02)
        watchdog.step_end()
        seq, dur = watchdog.last_step()
        cli.heartbeat(0, sync_clock=True)
        ks = profiler.metrics()["kvstore_server"]
        assert ks["rank_step_s.0"] == pytest.approx(dur, abs=1e-6)
        assert ks["rank_step_seq.0"] == seq
        # a single reporting rank: no skew/straggler keys
        assert not any(k.startswith("step_skew.") for k in ks)
    finally:
        cli.stop_server()
        srv.stop()


def test_plain_v0_heartbeat_still_works_without_step_stats(_only_my_servers):
    srv = KA.AsyncPSServer()
    cli = KA.AsyncPSClient("127.0.0.1", srv.port)
    try:
        cli.heartbeat(3)  # un-timestamped beat, no step payload
        ks = profiler.metrics()["kvstore_server"]
        assert "rank_heartbeat_age.3" in ks
        assert "rank_step_s.3" not in ks
    finally:
        cli.stop_server()
        srv.stop()


# -- compile/device-time attribution -----------------------------------------

def test_fused_step_compile_attribution():
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize(mx.init.Uniform(0.1), force_reinit=True)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), trainer)
    x = mx.nd.array(np.ones((4, 8), np.float32))
    y = mx.nd.array(np.zeros((4, 4), np.float32))
    for _ in range(4):
        step(x, y, batch_size=4)
    assert step.last_mode == "fused"
    cs = profiler.compile_stats()
    assert "fused_step" in cs, sorted(cs)
    st = cs["fused_step"]
    assert st["count"] == 1          # one signature, one compile
    assert st["last_us"] > 0 and st["key"]
    # AOT cost analysis fed flops/bytes on the CPU backend
    assert st.get("flops", 0) > 0
    assert st.get("bytes_accessed", 0) > 0
    assert st.get("modeled_compute_us", 0) > 0
    # replays never re-enter the registry
    for _ in range(3):
        step(x, y, batch_size=4)
    assert profiler.compile_stats()["fused_step"]["count"] == 1


def test_fused_step_attribution_failure_never_reruns_the_step(monkeypatch):
    """Regression: _record_compile runs AFTER the compile step committed
    (outside the trace-failure try). If it raises — cost-model or JAX
    API drift — the already-applied update must stand (no eager re-run =
    double update), the signature must stay cached, and the error is
    counted, not raised."""
    from mxnet_tpu.gluon import fused_step as FS
    monkeypatch.setattr(
        FS.FusedTrainStep, "_record_compile",
        lambda self, *a, **k: (_ for _ in ()).throw(RuntimeError("drift")))
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize(mx.init.Uniform(0.1), force_reinit=True)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), trainer)
    x = mx.nd.array(np.ones((4, 8), np.float32))
    y = mx.nd.array(np.zeros((4, 4), np.float32))
    FS.reset_stats()
    w0 = net.weight.data().asnumpy().copy()
    modes = []
    for _ in range(4):
        step(x, y, batch_size=4)
        modes.append(step.last_mode)
    assert "compile" in modes           # the compile step itself succeeded
    assert step.last_mode == "fused"    # ...and stayed cached (no blacklist)
    st = FS.stats()
    assert st["attr_errors"] >= 1
    assert st["fallbacks"] == 0
    assert not np.allclose(w0, net.weight.data().asnumpy())


def test_fused_step_attribution_model_is_per_signature():
    """Regression: the modeled compute/comm split is keyed by signature.
    A run alternating two compiled batch shapes must subtract each
    step's OWN program's modeled device time — not whichever program
    compiled last."""
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize(mx.init.Uniform(0.1), force_reinit=True)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), trainer)
    big = (mx.nd.array(np.ones((64, 8), np.float32)),
           mx.nd.array(np.zeros((64, 4), np.float32)))
    small = (mx.nd.array(np.ones((4, 8), np.float32)),
             mx.nd.array(np.zeros((4, 4), np.float32)))
    for _ in range(4):  # compile both signatures, then alternate hits
        step(*big, batch_size=64)
        step(*small, batch_size=4)
    assert step.last_mode == "fused"
    models = {k: v for k, v in step._attr_models.items()}
    assert len(models) == 2
    # the executing step's model is the one looked up by ITS key
    step(*big, batch_size=64)
    big_key = next(k for k in models
                   if step._step_attr is models[k])
    step(*small, batch_size=4)
    small_key = next(k for k in models
                     if step._step_attr is models[k])
    assert big_key != small_key
    # the bigger batch models strictly more compute
    assert models[big_key]["compute_us"] > models[small_key]["compute_us"]


def test_imperative_compile_attribution_records_signature():
    a = mx.nd.array(np.ones((8, 8), np.float32))
    for _ in range(8):
        b = mx.nd.softmax(a)
        b.wait_to_read()
    cs = profiler.compile_stats()
    key = "imperative:softmax"
    assert key in cs, sorted(cs)
    assert cs[key]["count"] >= 1
    assert cs[key]["last_us"] > 0
    assert "float32[8, 8]" in cs[key]["key"]
    count = cs[key]["count"]
    for _ in range(4):  # cache hits do not re-record
        mx.nd.softmax(a).wait_to_read()
    assert profiler.compile_stats()[key]["count"] == count


def test_dumps_renders_compile_and_attribution_tables():
    profiler.record_compile("unit:prog", key="sig0", dur_us=1500.0,
                            flops=2.0e9, bytes_accessed=1.0e6,
                            modeled_compute_us=10.0,
                            modeled_comm_us=2.0)
    out = profiler.dumps()
    assert "Compile" in out and "unit:prog" in out
    assert "Attribution (modeled)" in out


# -- elastic world context ---------------------------------------------------

def test_elastic_controller_publishes_world_to_dump_context(tmp_path):
    from mxnet_tpu.parallel.elastic import ElasticController
    ElasticController(kvstore=None, world=[0, 1, 2], rank=1)
    path = flightrec.dump("manual")
    ctx = json.load(open(path))["metadata"]["context"]
    assert ctx["elastic_world"]["world"] == [0, 1, 2]
    assert ctx["elastic_world"]["rank"] == 1
    assert ctx["elastic_world"]["dead"] == []


# -- trace_merge integration -------------------------------------------------

def _make_live_shard(tmp_path):
    shard = str(tmp_path / "live.json")
    profiler.set_config(filename=shard, xprof=False)
    profiler.set_state("run")
    a = mx.nd.array(np.ones((4, 4), np.float32))
    (a + 1).wait_to_read()
    engine.wait_for_all()
    profiler.set_state("stop")
    profiler.dump()
    return shard


def test_merge_tags_flightrec_events(tmp_path):
    live = _make_live_shard(tmp_path)
    flightrec.record_marker("black_box_marker")
    frec = flightrec.dump("manual")
    out = str(tmp_path / "merged.json")
    merged, summary = profiler.merge_traces([live, frec], output=out)
    assert summary["flightrec_shards"] == 1
    evs = merged["traceEvents"]
    tagged = [e for e in evs
              if e.get("args", {}).get("source") == "flightrec"]
    untagged = [e for e in evs if e.get("ph") != "M"
                and e.get("args", {}).get("source") != "flightrec"]
    assert tagged and untagged, "both sources must be distinguishable"
    assert any(e["name"] == "black_box_marker" for e in tagged)
    assert json.load(open(out))["traceEvents"]


def test_trace_merge_cli_zero_shards_exits_nonzero(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    cli = os.path.join(REPO, "tools", "trace_merge.py")
    r = subprocess.run([sys.executable, cli], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 2
    assert "no input shards" in r.stderr

    empty = tmp_path / "empty_shard.json"
    empty.write_text(json.dumps({"traceEvents": [],
                                 "metadata": {"rank": 0}}))
    out = tmp_path / "should_not_exist.json"
    r2 = subprocess.run([sys.executable, cli, str(empty),
                         "-o", str(out)], env=env,
                        capture_output=True, text=True, timeout=120)
    assert r2.returncode == 1
    assert "zero events" in r2.stderr
    assert not out.exists(), "an empty trace must not be written"


# -- subprocess post-mortems (acceptance) ------------------------------------

def _worker_env(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["MXTPU_FLIGHTREC_DIR"] = str(tmp_path)
    return env


def _merge_with_cli(tmp_path, shards):
    out = str(tmp_path / "merged.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_merge.py")]
        + shards + ["-o", out],
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO),
        capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    merged = json.load(open(out))
    evs = merged["traceEvents"]
    assert any(e.get("args", {}).get("source") == "flightrec"
               for e in evs)
    assert any(e.get("ph") != "M"
               and e.get("args", {}).get("source") != "flightrec"
               for e in evs)
    return merged


def test_crash_subprocess_leaves_postmortem_that_merges(tmp_path):
    """Acceptance: an uncaught exception mid-epoch leaves a valid
    chrome-trace shard (last spans + all-thread stacks) that the CLI
    merges with the run's live profiler shard."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests",
                                      "flightrec_worker.py"), "crash"],
        env=_worker_env(tmp_path), capture_output=True, text=True,
        timeout=300)
    assert r.returncode != 0
    assert "boom mid-epoch" in r.stderr
    dumps = _frec_dumps(tmp_path, "exception")
    assert len(dumps) == 1
    d = json.load(open(dumps[0]))  # valid JSON or this raises
    meta = d["metadata"]
    assert "boom mid-epoch" in meta["trigger_info"]["exception"]
    assert meta["python_stacks"], "no thread stacks in the post-mortem"
    names = {e.get("name") for e in d["traceEvents"]}
    # the last spans of the dying run: the fused step anchor + eager ops
    assert "gluon.train_step" in names, sorted(names)[:40]
    assert "softmax" in names
    live = str(tmp_path / "live_trace.json")
    assert os.path.exists(live)
    _merge_with_cli(tmp_path, [live, dumps[0]])


def test_sigkill_stalled_subprocess_watchdog_postmortem(tmp_path):
    """Acceptance: a run wedged by a faultpoint delay gets a watchdog
    flight-record dump while still stalled; the process is then
    SIGKILLed (a real hang autopsy: nothing after the wedge ever ran)
    and the shard still merges with the live profiler shard."""
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests",
                                      "flightrec_worker.py"), "stall"],
        env=_worker_env(tmp_path), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.time() + 240
        dumps = []
        while time.time() < deadline and not dumps:
            if proc.poll() is not None:
                out, err = proc.communicate()
                raise AssertionError(
                    "worker exited before stalling: %s%s" % (out, err))
            dumps = _frec_dumps(tmp_path, "watchdog")
            time.sleep(0.1)
        assert dumps, "watchdog never dumped within the deadline"
    finally:
        if proc.poll() is None:
            proc.kill()  # SIGKILL mid-stall
        proc.wait(timeout=30)
    assert proc.returncode != 0  # killed, not a clean exit
    d = json.load(open(dumps[0]))
    meta = d["metadata"]
    assert meta["trigger"] == "watchdog"
    assert meta["trigger_info"]["elapsed_s"] >= 0.3
    # the wedged pull is visible in the stacks the dump captured
    assert any("pull" in ln for lines in meta["python_stacks"].values()
               for ln in lines)
    live = str(tmp_path / "live_trace.json")
    assert os.path.exists(live)
    _merge_with_cli(tmp_path, [live, dumps[0]])


@pytest.mark.slow
def test_two_process_straggler_gauge_names_slow_rank(tmp_path):
    """Acceptance: in a 2-process run with an injected per-rank delay
    the PS server's metrics name the slow rank — verified in-worker by
    both ranks via kv.server_metrics()."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["MXTPU_PS_HEARTBEAT_INTERVAL"] = "0.1"
    env["MXTPU_FLIGHTREC_DIR"] = str(tmp_path)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable,
         os.path.join(REPO, "tests", "flightrec_straggler_worker.py")],
        env=env, capture_output=True, text=True, timeout=480)
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout + r.stderr
    for rank in range(2):
        assert "rank %d: STRAGGLER_OK" % rank in out, out
