"""Run-level goodput ledger (ISSUE 14; mxnet_tpu/_debug/goodput.py).

Four halves:

* classification units — every category fed through its weld shape,
  the drain-time partition summing exactly to wall-clock;
* manifest contract — schema, atomic publication, failure surfacing;
* surfaces — metrics()['goodput'], Prometheus families, the dumps()
  table, the flight-record block;
* the chaos-attribution acceptance pair + the compare CLI — a
  rank-death run's manifest must price recovery+rewind within 20% of
  the independently measured restore-to-caught-up interval, while the
  fault-free twin attributes ~0 to recovery and ≥95% of non-warmup
  wall-clock to compute+input_wait; `goodput_report --compare` flags
  an injected 2x step-time slowdown and passes an identical pair.

Plus the satellite watchdog bugfix: the rolling step-time median
window resets on elastic reshard/restore, so old-world durations never
skew stall detection after a resize.
"""
import json
import os
import subprocess
import sys
import time

import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu import profiler
from mxnet_tpu._debug import flightrec, goodput, watchdog
from mxnet_tpu.parallel.elastic import (CheckpointManager,
                                        ElasticController,
                                        elastic_train_loop)
from tools import goodput_report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_RUNS_DIR", str(tmp_path / "runs"))
    goodput.reset()
    watchdog.reset()
    yield
    goodput.reset()
    watchdog.reset()


def _step(begin, dur, warmup=False, mode=None):
    goodput.note_step(begin, dur, warmup=warmup, mode=mode)


# -- classification units ----------------------------------------------------

class TestClassification:
    def test_mode_mapping(self):
        goodput.open_run(run_id="cls")
        t = time.monotonic()
        _step(t, 0.10, warmup=False, mode="fused")          # compute
        _step(t + 0.1, 0.20, warmup=True, mode="compile")   # compile
        _step(t + 0.3, 0.05, warmup=True,
              mode="eager-warming")                          # compile
        _step(t + 0.35, 0.04, warmup=True,
              mode="fallback:kvstore")                       # host
        _step(t + 0.39, 0.08, warmup=False, mode=None)       # compute
        m = goodput.close_run()
        c = m["categories_s"]
        assert c["compute"] == pytest.approx(0.18)
        assert c["compile"] == pytest.approx(0.25)
        assert c["host_overhead"] >= 0.04  # fallback + gap residual
        assert m["steps"]["count"] == 2    # representative steps only
        assert m["steps"]["warmup"] == 3
        assert m["steps"]["fallback"] == 1

    def test_replay_marks_exactly_next_step(self):
        goodput.open_run(run_id="rp")
        t = time.monotonic()
        _step(t, 0.1)
        goodput.mark_replay()
        _step(t + 0.1, 0.2)          # replay
        _step(t + 0.3, 0.1)          # back to compute
        m = goodput.close_run()
        assert m["categories_s"]["rewind_replay"] == pytest.approx(0.2)
        assert m["categories_s"]["compute"] == pytest.approx(0.2)
        assert m["steps"]["replayed"] == 1
        # replays ARE representative (same program): all 3 in the stats
        assert m["steps"]["count"] == 3

    def test_replayed_compile_step_not_representative(self):
        """A post-reshard rewind forces a recompile under the new mesh:
        its seconds are rewind_replay badput, but a seconds-long
        compile must NOT feed the representative step-time stats the
        compare CLI judges regressions by (review finding)."""
        goodput.open_run(run_id="rpw")
        t = time.monotonic()
        _step(t, 0.001)
        _step(t + 0.1, 0.001)
        goodput.mark_replay()
        _step(t + 0.2, 5.0, warmup=True, mode="compile")
        m = goodput.close_run()
        assert m["categories_s"]["rewind_replay"] == pytest.approx(5.0)
        assert m["steps"]["replayed"] == 1
        assert m["steps"]["count"] == 2
        assert m["steps"]["time_s"]["max"] == pytest.approx(0.001)

    def test_input_wait_and_checkpoint(self):
        goodput.open_run(run_id="iw")
        time.sleep(0.05)  # real elapsed wall must cover the feeds
        goodput.note_input_wait(15000.0)   # 0.015 s
        goodput.note_input_wait(5000.0)
        goodput.note_checkpoint(0.012, "save")
        m = goodput.close_run()
        assert m["categories_s"]["input_wait"] == pytest.approx(0.02)
        assert m["categories_s"]["checkpoint"] == pytest.approx(0.012)
        assert m["counters"]["checkpoint_saves"] == 1
        assert m["counters"]["input_wait_overbooked_s"] == 0.0

    def test_recovery_interval_subsumes_restore(self):
        """A restore inside a recovery interval must not double-count:
        the interval's clock owns the seconds, the counter still
        ticks."""
        goodput.open_run(run_id="rec")
        goodput.recovery_begin()
        time.sleep(0.05)
        goodput.note_checkpoint(0.04, "restore")  # inside: no category
        goodput.recovery_end(kind="reshard", resharded=True,
                             restored_step=7, replay_span=3)
        m = goodput.close_run()
        assert m["categories_s"]["checkpoint"] == 0.0
        assert m["categories_s"]["recovery"] >= 0.05
        assert m["counters"]["checkpoint_restores"] == 1
        assert m["counters"]["recoveries"] == 1
        assert m["counters"]["reshards"] == 1
        ev = [e for e in m["events"] if e["kind"] == "recovery"]
        assert ev and ev[0]["restored_step"] == 7 \
            and ev[0]["replay_span"] == 3

    def test_discarded_recovery_counts_nothing(self):
        goodput.open_run(run_id="rec0")
        goodput.recovery_begin()
        goodput.recovery_end(count=False)
        m = goodput.close_run()
        assert m["categories_s"]["recovery"] == 0.0
        assert m["counters"]["recoveries"] == 0

    def test_partition_sums_to_wall(self):
        """The eight categories always partition wall-clock exactly —
        including idle edges and the between-step host residual."""
        goodput.open_run(run_id="sum")
        time.sleep(0.03)                       # leading idle
        t = time.monotonic()
        _step(t, 0.02)
        _step(t + 0.05, 0.02)                  # 0.03 un-attributed gap
        goodput.note_input_wait(10000.0)       # 0.01 of that gap
        time.sleep(0.09)
        m = goodput.close_run()
        total = sum(m["categories_s"].values())
        assert total == pytest.approx(m["wall_s"], rel=1e-6)
        assert m["categories_s"]["idle"] > 0.0
        assert m["categories_s"]["host_overhead"] > 0.0
        assert 0.0 <= m["goodput_ratio"] <= 1.0

    def test_default_run_ids_unique_within_one_second(self):
        """Review finding: two sub-second back-to-back runs in one
        process must not collide on the default id and silently
        overwrite each other's manifest."""
        a = goodput.open_run()
        goodput.close_run()
        b = goodput.open_run()
        goodput.close_run()
        assert a != b

    def test_overbooked_input_wait_trimmed_not_summed_past_wall(self):
        """Review finding: input_wait fed from threads concurrent with
        steps (a stacked consumer measuring the same stall twice) must
        not break the categories-partition-wall contract — the excess
        is trimmed and surfaced, never silently summed past wall."""
        goodput.open_run(run_id="over")
        t = time.monotonic()
        _step(t, 0.01)
        time.sleep(0.012)  # wall covers the step window
        goodput.note_input_wait(3e6)  # 3s of "wait" in a ~12ms run
        m = goodput.close_run()
        total = sum(m["categories_s"].values())
        assert total == pytest.approx(m["wall_s"], rel=1e-6)
        assert m["counters"]["input_wait_overbooked_s"] > 2.0

    def test_input_wait_attributed_with_flightrec_off(self):
        """Review finding: with the flight recorder AND profiler both
        off (profiler._LIVE false), an open goodput run must still see
        consumer stalls — they book under input_wait, not silently
        under host_overhead."""
        from mxnet_tpu.io.worker_pool import DecodePool
        prev = flightrec.disable()
        try:
            assert not profiler._LIVE
            goodput.open_run(run_id="frecoff")
            pool = DecodePool(iter(range(5)),
                              lambda x: (time.sleep(0.002), x)[1],
                              workers=1)
            assert list(pool) == list(range(5))
            m = goodput.close_run()
            assert m["categories_s"]["input_wait"] > 0.0
        finally:
            if prev:
                flightrec.enable()

    def test_events_bounded(self):
        goodput.open_run(run_id="ev")
        for i in range(200):
            goodput.note_event("step_failure", step=i)
        m = goodput.close_run()
        assert len(m["events"]) <= 64
        assert m["counters"]["events_dropped"] == 200 - len(m["events"])

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setattr(goodput, "ENABLED", False)
        assert goodput.open_run() is None
        assert not goodput.OPEN
        goodput.note_step(0.0, 1.0)  # no-op, no crash
        assert goodput.close_run() is None

    def test_open_is_exclusive(self):
        assert goodput.open_run(run_id="a") == "a"
        assert goodput.open_run(run_id="b") is None  # nested: no reopen
        assert goodput.current_run_id() == "a"
        goodput.close_run()

    def test_step_time_summary_percentiles(self):
        goodput.open_run(run_id="pct")
        t = time.monotonic()
        for i in range(100):
            _step(t + i, 0.001 if i < 99 else 0.1)  # one straggler
        m = goodput.close_run()
        ts = m["steps"]["time_s"]
        assert ts["p50"] == pytest.approx(0.001, rel=0.15)
        assert ts["max"] == pytest.approx(0.1)
        assert ts["p50"] <= ts["p95"] <= ts["p99"] <= ts["max"]


# -- the watchdog beacon weld ------------------------------------------------

class TestBeaconWeld:
    def test_beacon_feeds_ledger_with_mode(self):
        goodput.open_run(run_id="wd")
        watchdog.step_begin()
        time.sleep(0.02)
        watchdog.step_end(mode="fused")
        watchdog.step_begin()
        time.sleep(0.01)
        watchdog.step_end(warmup=True, mode="compile")
        s = goodput.snapshot()
        assert s["steps"] == 1 and s["warmup_steps"] == 1
        assert s["compute_s"] >= 0.02
        assert s["compile_s"] >= 0.01
        goodput.close_run()

    def test_nested_beacon_outer_owns_with_mode_taint(self):
        """elastic_train_loop's outer beacon wraps the fused step's:
        ONE ledger entry, carrying the inner mode."""
        goodput.open_run(run_id="nest")
        watchdog.step_begin()                 # outer (elastic loop)
        watchdog.step_begin()                 # inner (fused step)
        time.sleep(0.01)
        watchdog.step_end(warmup=True, mode="compile")
        watchdog.step_end()                   # outer completion
        m = goodput.close_run()
        assert m["steps"]["warmup"] == 1
        assert m["steps"]["count"] == 0
        assert m["categories_s"]["compile"] >= 0.01
        assert m["categories_s"]["compute"] == 0.0

    def test_fold_backstop_bounds_pending(self, monkeypatch):
        monkeypatch.setattr(goodput, "_FOLD_AT", 64)
        goodput.open_run(run_id="fold")
        t = time.monotonic()
        for i in range(1000):
            _step(t, 0.001)
        assert len(goodput._PENDING) < 64
        m = goodput.close_run()
        assert m["steps"]["count"] == 1000


# -- manifest contract -------------------------------------------------------

class TestManifest:
    def test_schema_and_atomic_publication(self, tmp_path):
        goodput.open_run(run_id="man", meta={"world": [0, 1]})
        t = time.monotonic()
        _step(t, 0.01)
        m = goodput.close_run(outcome="completed")
        path = m["manifest_path"]
        assert os.path.exists(path)
        run_dir = os.path.dirname(path)
        assert os.listdir(run_dir) == ["manifest.json"]  # no .tmp
        loaded = goodput.load_manifest(run_dir)
        assert loaded["schema"] == goodput.SCHEMA
        assert loaded["outcome"] == "completed"
        assert loaded["meta"]["world"] == [0, 1]
        assert set(loaded["categories_s"]) == set(goodput.CATEGORIES)
        assert "signature_tokens" in loaded["env"]
        assert loaded["closed_unix"] >= loaded["opened_unix"]
        assert goodput.last_manifest()["run_id"] == "man"

    def test_write_failure_surfaces_not_raises(self, tmp_path,
                                               monkeypatch):
        blocker = tmp_path / "blocker"
        blocker.write_text("a file where the runs dir should be")
        monkeypatch.setenv("MXTPU_RUNS_DIR", str(blocker))
        goodput.open_run(run_id="wf")
        m = goodput.close_run()
        assert "write_error" in m
        assert not goodput.is_open()  # run is closed regardless

    def test_load_rejects_wrong_schema(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"schema": "nope/9"}))
        with pytest.raises(ValueError):
            goodput.load_manifest(str(p))


# -- surfaces ----------------------------------------------------------------

class TestSurfaces:
    def test_metrics_provider_and_prometheus(self):
        goodput.open_run(run_id="surf")
        t = time.monotonic()
        _step(t, 0.02)
        m = profiler.metrics()["goodput"]
        assert m["open"] == 1 and m["run_id"] == "surf"
        assert m["compute_s"] >= 0.02
        for c in goodput.CATEGORIES:
            assert "%s_s" % c in m
        prom = profiler.prometheus_text()
        assert 'mxtpu_goodput_seconds' in prom
        assert 'category="compute"' in prom
        assert "mxtpu_goodput_ratio" in prom
        assert 'mxtpu_goodput_steps_total' in prom
        goodput.close_run()
        # after close: the last run's totals keep serving
        assert profiler.metrics()["goodput"]["open"] == 0

    def test_dumps_table(self):
        goodput.open_run(run_id="table")
        t = time.monotonic()
        _step(t, 0.01)
        txt = profiler.dumps()
        assert "Goodput run=table" in txt
        assert "rewind_replay" in txt
        goodput.close_run()

    def test_flightrec_dump_carries_goodput_block(self, tmp_path):
        goodput.open_run(run_id="frec")
        t = time.monotonic()
        _step(t, 0.01)
        shard = str(tmp_path / "shard.json")
        flightrec.dump("manual", path=shard)
        data = json.load(open(shard))
        g = data["metadata"]["goodput"]
        assert g["run_id"] == "frec" and g["open"] == 1
        goodput.close_run()


# -- the chaos-attribution acceptance pair (ISSUE 14) ------------------------

class _FakeKV:
    def __init__(self, nworkers=2):
        self.dead = []
        self.num_workers = nworkers
        self.resized = []

    def dead_nodes(self, timeout=3.0):
        return list(self.dead)

    def resize(self, n):
        self.resized.append(int(n))
        self.num_workers = int(n)


_SLEEP = 0.05


def _sleep_step(state, idx):
    time.sleep(_SLEEP)
    return {"acc": state["acc"] + idx}, None


class TestChaosAttribution:
    def test_fault_free_twin_attributes_nothing_to_recovery(
            self, tmp_path):
        """The control half of the acceptance pair: no faults -> zero
        recovery/rewind, and >=95% of non-warmup wall-clock is
        compute+input_wait."""
        ckpt = CheckpointManager(str(tmp_path / "ck"), use_orbax=False)
        state, last, done = elastic_train_loop(
            _sleep_step, {"acc": jnp.asarray(0.0)},
            [jnp.asarray(float(i)) for i in range(8)], ckpt,
            save_every=3)
        assert done
        m = goodput.last_manifest()
        assert m is not None and m["outcome"] == "completed"
        c = m["categories_s"]
        assert c["recovery"] == 0.0
        assert c["rewind_replay"] == 0.0
        assert m["steps"]["count"] == 8
        non_warmup_wall = m["wall_s"] - c["compile"]
        assert (c["compute"] + c["input_wait"]) >= 0.95 * \
            non_warmup_wall, m

    def test_rank_death_recovery_and_rewind_match_measured(
            self, tmp_path):
        """The acceptance run: a rank dies mid-epoch; the survivor
        reshards, rewinds to the newest checkpoint and replays. The
        manifest's recovery+rewind seconds must match the
        independently measured restore-to-caught-up interval within
        20%."""
        kv = _FakeKV(2)
        ctl = ElasticController(kvstore=kv, world=range(2), rank=0,
                                poll_interval=0.0)
        ckpt = CheckpointManager(str(tmp_path / "ck"), use_orbax=False)
        marks = {}

        def step(state, b):
            i = int(b)
            if i == 6 and len(ctl.survivors) == 2:
                kv.dead = [1]  # rank 1 vanishes mid-epoch
                marks["fail_t"] = time.monotonic()
                raise ConnectionError("collective failed: peer gone")
            if i == 6 and "caught_t" not in marks:
                # first NEW work after the rewind: caught up
                marks["caught_t"] = time.monotonic()
            return _sleep_step(state, b)

        state, last, done = elastic_train_loop(
            step, {"acc": jnp.asarray(0.0)},
            [jnp.asarray(float(i)) for i in range(8)], ckpt,
            save_every=3, max_failures=0, controller=ctl)
        assert done and kv.resized == [1]
        m = goodput.last_manifest()
        assert m["outcome"] == "completed"
        c = m["categories_s"]
        # checkpoints landed at 3, 6 is never reached pre-death ->
        # restore step 3, replay 4 and 5
        assert m["steps"]["replayed"] == 2
        assert c["recovery"] > 0.0
        assert c["rewind_replay"] >= 2 * _SLEEP * 0.9
        measured = marks["caught_t"] - marks["fail_t"]
        booked = c["recovery"] + c["rewind_replay"]
        assert booked == pytest.approx(measured, rel=0.20), \
            (booked, measured, m)
        kinds = {e["kind"] for e in m["events"]}
        assert "step_failure" in kinds and "recovery" in kinds
        rec = [e for e in m["events"] if e["kind"] == "recovery"][0]
        assert rec["resharded"] is True and rec["restored_step"] == 3

    def test_resume_counts_as_recovery(self, tmp_path):
        """A second incarnation resuming from a checkpoint books the
        restore under 'recovery' (the badput of the death it follows)."""
        ckpt = CheckpointManager(str(tmp_path / "ck"), use_orbax=False)
        elastic_train_loop(
            _sleep_step, {"acc": jnp.asarray(0.0)},
            [jnp.asarray(float(i)) for i in range(4)], ckpt,
            save_every=2)
        m1 = goodput.last_manifest()
        assert m1["counters"]["recoveries"] == 0
        elastic_train_loop(
            _sleep_step, {"acc": jnp.asarray(0.0)},
            [jnp.asarray(float(i)) for i in range(6)], ckpt,
            save_every=2)
        m2 = goodput.last_manifest()
        assert m2["counters"]["recoveries"] == 1
        assert m2["categories_s"]["recovery"] > 0.0

    def test_failing_resume_restore_still_closes_run(self, tmp_path):
        """Review finding: a restore that raises at loop start (the
        elastic.restore faultpoint; a lost filesystem) must not leak
        the run open — a leaked run would suppress every later loop's
        manifest in this process."""
        from mxnet_tpu._debug import faultpoint
        ckpt = CheckpointManager(str(tmp_path / "ck"), use_orbax=False)
        elastic_train_loop(_sleep_step, {"acc": jnp.asarray(0.0)},
                           [jnp.asarray(0.0)], ckpt, save_every=1)
        faultpoint.configure("elastic.restore=raise:RuntimeError@n=1")
        try:
            with pytest.raises(RuntimeError):
                elastic_train_loop(
                    _sleep_step, {"acc": jnp.asarray(0.0)},
                    [jnp.asarray(0.0)], ckpt, save_every=1)
        finally:
            faultpoint.reset()
        assert not goodput.is_open()
        assert goodput.last_manifest()["outcome"] == "failed"
        # the NEXT loop still opens, records, and publishes
        elastic_train_loop(_sleep_step, {"acc": jnp.asarray(0.0)},
                           [jnp.asarray(float(i)) for i in range(2)],
                           ckpt, save_every=1)
        m = goodput.last_manifest()
        assert m["outcome"] == "completed"
        # resumed past the first loop's step-0 checkpoint: 1 new step
        assert m["steps"]["count"] == 1
        # checkpoint accounting is live again (in_recovery not stuck)
        assert m["categories_s"]["checkpoint"] > 0.0

    def test_failed_run_closes_with_failed_outcome(self, tmp_path):
        ckpt = CheckpointManager(str(tmp_path / "ck"), use_orbax=False)

        def bad_step(state, b):
            raise RuntimeError("unrecoverable")

        with pytest.raises(RuntimeError):
            elastic_train_loop(bad_step, {"acc": jnp.asarray(0.0)},
                               [jnp.asarray(0.0)], ckpt,
                               save_every=1, max_failures=0)
        m = goodput.last_manifest()
        assert m["outcome"] == "failed"
        assert not goodput.is_open()


# -- compare CLI -------------------------------------------------------------

def _run_manifest(run_id, step_s, n=50, extra_cats=None):
    """A deterministic synthetic manifest (published through the same
    atomic writer): CLI verdicts must not depend on this process's
    scheduling noise."""
    cats = {c: 0.0 for c in goodput.CATEGORIES}
    cats["compute"] = n * step_s
    cats.update(extra_cats or {})
    wall = sum(cats.values())
    t = {"mean": step_s, "min": step_s, "max": step_s,
         "p50": step_s, "p95": step_s, "p99": step_s}
    m = {"schema": goodput.SCHEMA, "run_id": run_id, "rank": 0,
         "opened_unix": 1.0, "closed_unix": 1.0 + wall,
         "wall_s": wall, "open": False, "outcome": "completed",
         "categories_s": cats,
         "goodput_ratio": cats["compute"] / wall if wall else 0.0,
         "steps": {"count": n, "warmup": 0, "replayed": 0,
                   "fallback": 0, "time_s": t},
         "counters": {"recoveries": 0, "reshards": 0,
                      "checkpoint_saves": 0, "checkpoint_restores": 0,
                      "events_dropped": 0},
         "env": {"rank": 0, "world": None, "mesh": None,
                 "signature_tokens": {}},
         "events": [], "meta": {}}
    goodput._write_manifest(m)
    return os.path.dirname(goodput.manifest_path(run_id))


class TestCompareCLI:
    def test_identical_pair_passes(self):
        a = _run_manifest("cmp_a", 0.001)
        b = _run_manifest("cmp_b", 0.001)
        assert goodput_report.main(["--compare", a, b]) == 0

    def test_2x_slowdown_flagged(self, capsys):
        a = _run_manifest("slow_a", 0.001)
        b = _run_manifest("slow_b", 0.002)
        assert goodput_report.main(["--compare", a, b]) == 1
        out = capsys.readouterr().out
        assert "REGRESSION" in out and "median step time" in out

    def test_small_relative_noise_passes(self):
        """Noise robustness: +30% on a 3us step is under the absolute
        floor — never a page."""
        a = _run_manifest("tiny_a", 3e-6)
        b = _run_manifest("tiny_b", 4e-6)
        assert goodput_report.main(["--compare", a, b]) == 0

    def test_goodput_ratio_drop_and_category_drift_flagged(
            self, capsys):
        a = _run_manifest("drift_a", 0.001)
        b = _run_manifest("drift_b", 0.001,
                          extra_cats={"recovery": 5.0})
        assert goodput_report.main(["--compare", a, b]) == 1
        out = capsys.readouterr().out
        assert "recovery" in out

    def test_render_single_run(self, capsys):
        a = _run_manifest("render", 0.001)
        assert goodput_report.main([a]) == 0
        out = capsys.readouterr().out
        assert "goodput run render" in out and "compute" in out

    def test_bad_manifest_exits_2(self, tmp_path):
        p = tmp_path / "nope.json"
        assert goodput_report.main([str(p)]) == 2
        p.write_text("{}")
        assert goodput_report.main([str(p)]) == 2
        a = _run_manifest("one", 0.001)
        assert goodput_report.main(["--compare", a]) == 2

    def test_cli_subprocess_entry(self):
        a = _run_manifest("sub_a", 0.001)
        b = _run_manifest("sub_b", 0.0021)
        script = os.path.join(REPO, "tools", "goodput_report.py")
        r = subprocess.run([sys.executable, script, "--compare", a, b],
                           capture_output=True, text=True)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "verdict: REGRESSION" in r.stdout


# -- bench manifests (the trajectory satellite) ------------------------------

class TestBenchManifests:
    def test_gate_result_roundtrips_through_schema(self):
        result = {"metric": "train_step_steps_per_sec", "value": 1234.5,
                  "speedup": 6.1, "gate": {"ok": True}}
        path = goodput.write_bench_manifest("train_step", result)
        m = goodput.load_manifest(path)
        assert m["schema"] == goodput.SCHEMA
        assert m["bench"]["model"] == "train_step"
        assert m["outcome"] == "completed"
        assert m["steps"]["time_s"]["p50"] == pytest.approx(1 / 1234.5)
        # identical rounds compare clean through the standing tool
        assert goodput_report.main(
            ["--compare", os.path.dirname(path),
             os.path.dirname(path)]) == 0

    def test_breached_gate_recorded(self):
        result = {"metric": "goodput_overhead_pct", "value": 0.5,
                  "fused_step_us": 800.0, "gate": {"ok": False}}
        m = goodput.load_manifest(goodput.write_bench_manifest(
            "goodput_overhead", result))
        assert m["outcome"] == "gate_breached"
        assert m["steps"]["time_s"]["p50"] == pytest.approx(8e-4)


# -- satellite: watchdog median-window reset on reshard/restore --------------

class TestWatchdogWindowReset:
    def test_reshard_then_slower_cadence_does_not_false_trip(self):
        """The bugfix regression: after a reshard the shrunk world's
        slower cadence must NOT trip against the old world's fast
        median. First demonstrate the false positive the fix targets,
        then pin the fix."""
        watchdog.configure(min_s=0.01, factor=2.0, min_samples=3,
                           poll_s=100.0)  # poller effectively manual
        for _ in range(3):  # old-world cadence: fast
            watchdog.step_begin()
            time.sleep(0.002)
            watchdog.step_end()
        assert watchdog.threshold_s() == pytest.approx(0.01, abs=0.005)
        # WITHOUT the reset, a slower-world step trips falsely:
        watchdog.step_begin()
        time.sleep(0.03)
        assert watchdog.check_now() is True  # the bug being fixed
        watchdog.step_end()
        # the fix: reshard/restore clears the window -> disarmed until
        # min_samples at the NEW cadence, so no false trip
        watchdog.reset_window()
        assert watchdog.threshold_s() is None
        watchdog.step_begin()
        time.sleep(0.03)
        assert watchdog.check_now() is False
        watchdog.step_end()
        for _ in range(2):
            watchdog.step_begin()
            time.sleep(0.02)
            watchdog.step_end()
        # re-armed on the new cadence: threshold reflects the NEW median
        thr = watchdog.threshold_s()
        assert thr is not None and thr >= 0.04
        watchdog.step_begin()
        time.sleep(0.025)  # slower-world step, inside the new envelope
        assert watchdog.check_now() is False
        watchdog.step_end()
        assert watchdog.stats()["window_resets"] == 1

    def test_elastic_recovery_resets_window(self, tmp_path):
        """elastic_train_loop wires the reset on every restore path."""
        ckpt = CheckpointManager(str(tmp_path / "ck"), use_orbax=False)
        calls = {"n": 0}

        def step(state, b):
            calls["n"] += 1
            if calls["n"] == 3:
                raise ConnectionError("transient")
            return {"acc": state["acc"] + b}, None

        elastic_train_loop(step, {"acc": jnp.asarray(0.0)},
                           [jnp.asarray(float(i)) for i in range(4)],
                           ckpt, save_every=1, max_failures=2)
        assert watchdog.stats()["window_resets"] >= 1
