"""Gluon core Block/HybridBlock/Parameter behaviors.

Ports the strategy of tests/python/unittest/test_gluon.py (parameter
sharing, deferred init, hybridize-vs-eager numerics, save/load round
trips, hooks, naming) against our TPU-native gluon."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon, autograd
from mxnet_tpu.gluon import nn


def test_parameter_basic():
    p = gluon.Parameter("w", shape=(3, 2))
    p.initialize(init=mx.initializer.One())
    np.testing.assert_allclose(p.data().asnumpy(), 1.0)
    assert p.shape == (3, 2)
    p.set_data(nd.zeros((3, 2)))
    np.testing.assert_allclose(p.data().asnumpy(), 0.0)
    assert p.grad() is not None


def test_parameter_deferred_init():
    net = nn.Dense(4)
    net.initialize()
    with pytest.raises(Exception):
        net.weight.data()           # shape unknown until first forward
    net(nd.zeros((2, 5)))
    assert net.weight.shape == (4, 5)


def test_parameter_sharing():
    # sharing matches by full name, so the sharer uses the same prefix
    # (ref: test_gluon.py test_parameter_sharing pattern)
    d1 = nn.Dense(4, in_units=3, prefix="shared_")
    d2 = nn.Dense(4, in_units=3, prefix="shared_",
                  params=d1.collect_params())
    d1.initialize()
    x = nd.array(np.random.RandomState(0).rand(2, 3).astype("float32"))
    np.testing.assert_allclose(d1(x).asnumpy(), d2(x).asnumpy())
    # mutating through one alias is visible through the other
    d1.weight.set_data(nd.zeros((4, 3)))
    np.testing.assert_allclose(d2(x).asnumpy(), d1.bias.data().asnumpy()
                               [None].repeat(2, 0))


def test_block_naming_and_collect():
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    net(nd.zeros((1, 3)))
    names = sorted(net.collect_params().keys())
    assert all(n.startswith("model_") for n in names), names
    sub = net.collect_params(".*weight")
    assert all(n.endswith("weight") for n in sub.keys())


def test_hybridize_matches_eager():
    rs = np.random.RandomState(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="tanh"), nn.BatchNorm(),
            nn.Dense(3))
    net.initialize()
    x = nd.array(rs.rand(4, 6).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(hybrid, eager, rtol=2e-5, atol=2e-6)
    # gradients agree too
    for mode in (True,):
        xg = nd.array(rs.rand(4, 6).astype("float32"))
        xg.attach_grad()
        with autograd.record():
            y = net(xg).sum()
        y.backward()
        g1 = xg.grad.asnumpy()
        assert np.isfinite(g1).all()


def test_save_load_parameters_roundtrip(tmp_path):
    rs = np.random.RandomState(0)
    net = nn.Sequential()
    net.add(nn.Dense(5, activation="relu"), nn.Dense(2))
    net.initialize()
    x = nd.array(rs.rand(3, 4).astype("float32"))
    ref = net(x).asnumpy()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    net2 = nn.Sequential()
    net2.add(nn.Dense(5, activation="relu"), nn.Dense(2))
    net2.load_parameters(f)
    np.testing.assert_allclose(net2(x).asnumpy(), ref, rtol=1e-6)


def test_load_parameters_strictness(tmp_path):
    net = nn.Dense(3, in_units=2)
    net.initialize()
    f = str(tmp_path / "d.params")
    net.save_parameters(f)
    other = nn.Dense(4, in_units=2)
    with pytest.raises(Exception):
        other.load_parameters(f)    # shape mismatch must not pass silently


def test_forward_hooks():
    calls = []
    net = nn.Dense(2, in_units=2)
    net.initialize()
    net.register_forward_pre_hook(lambda blk, ins: calls.append("pre"))
    net.register_forward_hook(lambda blk, ins, out: calls.append("post"))
    net(nd.zeros((1, 2)))
    assert calls == ["pre", "post"]


def test_apply_and_cast():
    net = nn.Sequential()
    net.add(nn.Dense(2, in_units=2))
    net.initialize()
    seen = []
    net.apply(lambda b: seen.append(type(b).__name__))
    assert "Dense" in seen and "Sequential" in seen
    net.cast("float16")
    assert net[0].weight.dtype == np.float16


def test_zero_grad():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    x = nd.ones((1, 3))
    with autograd.record():
        y = net(x).sum()
    y.backward()
    assert np.abs(net.weight.grad().asnumpy()).sum() > 0
    net.zero_grad()
    np.testing.assert_allclose(net.weight.grad().asnumpy(), 0.0)


def test_constant_parameter():
    c = gluon.Constant("c", np.array([1.0, 2.0], "float32"))
    c.initialize()
    np.testing.assert_allclose(c.data().asnumpy(), [1, 2])
    # constants do not receive gradients through Trainer updates
    assert c.grad_req == "null"


def test_sequential_indexing_and_len():
    net = nn.Sequential()
    net.add(nn.Dense(2), nn.Dense(3), nn.Dense(4))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)


def test_summary_runs():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    net.summary(nd.zeros((1, 3)))


def test_symbolblock_from_symbol():
    """SymbolBlock wraps a symbolic graph as a gluon layer
    (ref: test_gluon.py test_symbol_block)."""
    data = mx.sym.var("data")
    out = mx.sym.FullyConnected(data, num_hidden=3, name="fc1")
    out = mx.sym.Activation(out, act_type="relu")
    blk = gluon.SymbolBlock(out, data)
    blk.initialize()
    y = blk(nd.ones((2, 4)))
    assert y.shape == (2, 3)
    assert (y.asnumpy() >= 0).all()


def test_block_repr():
    net = nn.Sequential()
    net.add(nn.Dense(2))
    assert "Dense" in repr(net)


def test_symbolblock_trains():
    """SymbolBlock joins the autograd tape: gradients flow to its params
    through a single-output wrapped graph (regression: single-output
    cotangent structure)."""
    rs = np.random.RandomState(0)
    data = mx.sym.var("data")
    out = mx.sym.FullyConnected(data, num_hidden=1, name="sbt_fc")
    blk = gluon.SymbolBlock(out, data)
    blk.initialize()
    X = rs.rand(16, 3).astype("float32")
    Y = X.sum(1, keepdims=True)
    blk(nd.array(X[:2]))
    tr = gluon.Trainer(blk.collect_params(), "adam",
                       {"learning_rate": 0.1})
    fn = gluon.loss.L2Loss()
    first = last = None
    for _ in range(60):
        with autograd.record():
            L = fn(blk(nd.array(X)), nd.array(Y))
        L.backward()
        tr.step(16)
        v = float(L.mean().asscalar())
        first = v if first is None else first
        last = v
    assert last < first * 0.1, (first, last)


def test_symbolblock_batchnorm_aux_updates():
    """BatchNorm moving stats inside a SymbolBlock update during training
    forwards and feed inference."""
    data = mx.sym.var("data")
    out = mx.sym.BatchNorm(data, name="sbbn", momentum=0.5)
    blk = gluon.SymbolBlock(out, data)
    blk.initialize()
    rs = np.random.RandomState(0)
    x = nd.array((rs.rand(8, 4) * 10 + 5).astype("float32"))
    with autograd.record():
        y = blk(x)
    mm = blk.collect_params()["sbbn_moving_mean"].data().asnumpy()
    assert np.abs(mm).max() > 0.1, mm
    y2 = blk(x)  # inference path with updated stats
    assert np.isfinite(y2.asnumpy()).all()
