"""Async snapshot-then-persist checkpoints + delta dedup (ISSUE 19a;
mxnet_tpu/parallel/elastic.py CheckpointManager).

Four halves:

* async persist semantics — save() blocks only for the device→host
  snapshot; the durable temp-write + atomic rename + commit runs on a
  background thread with at-most-one in flight, backpressure counted
  when the writer falls behind, and a persist failure surfacing on the
  NEXT save()/flush(), never silently;
* crash consistency — the ``checkpoint.persist`` faultpoint (the
  snapshot→persist gap) proves a death there loses exactly the one
  unpublished step: every previously PUBLISHED step stays restorable;
* delta checkpoints — unchanged-leaf dedup vs the last published full
  snapshot, one-hop restore, ``.base`` sidecar pinning the base past
  the keep policy, full fallback on structure change or >50% churn;
* the two satellite bugfixes — restore(step=N) probes completeness
  before loading (clear FileNotFoundError, not a raw pickle EOF), and
  _prune is in-flight-aware (never deletes the step a concurrent async
  persist is about to publish; the persist re-prunes on completion);

plus the chaos acceptance pair: a run killed between snapshot and
persist resumes from the newest published step with the lost work
booked under recovery, bitwise-identical to an unfaulted twin, while a
fault-free async twin's blocking ``checkpoint`` seconds drop vs the
sync baseline at equal cadence.
"""
import os
import pickle
import time

import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu import profiler
from mxnet_tpu._debug import faultpoint, goodput, watchdog
from mxnet_tpu.parallel.elastic import CheckpointManager, \
    elastic_train_loop


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_RUNS_DIR", str(tmp_path / "runs"))
    monkeypatch.delenv("MXTPU_CKPT_ASYNC", raising=False)
    monkeypatch.delenv("MXTPU_CKPT_DELTA", raising=False)
    goodput.reset()
    watchdog.reset()
    faultpoint.reset()
    yield
    faultpoint.reset()
    goodput.reset()
    watchdog.reset()


def _state(a=1.0, b=2.0):
    return {"w": jnp.asarray([a, a]), "m": jnp.asarray([b])}


def _mgr(tmp_path, **kw):
    kw.setdefault("use_orbax", False)
    return CheckpointManager(str(tmp_path / "ck"), **kw)


def _leaves_equal(x, y):
    import jax
    xs = jax.tree_util.tree_leaves(x)
    ys = jax.tree_util.tree_leaves(y)
    assert len(xs) == len(ys)
    return all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(xs, ys))


class TestAsyncPersist:
    def test_save_blocks_only_for_snapshot(self, tmp_path):
        """With a 200ms stall injected into the durable write, the
        async save() returns long before the persist finishes; flush()
        is the durability point where the step becomes restorable."""
        m = _mgr(tmp_path, async_persist=True)
        faultpoint.configure("checkpoint.save=delay:200ms")
        t0 = time.monotonic()
        m.save(0, _state())
        blocked = time.monotonic() - t0
        assert blocked < 0.15, blocked
        m.flush()
        assert m.latest_step() == 0
        got, s = m.restore()
        assert s == 0 and _leaves_equal(got, _state())

    def test_at_most_one_inflight_with_backpressure(self, tmp_path):
        """A second save while the previous persist is still writing
        joins it first — visible badput on THIS save, counted, never an
        unbounded queue of persist threads."""
        m = _mgr(tmp_path, async_persist=True)
        before = profiler.metrics().get("elastic", {}).get(
            "checkpoint_backpressure", 0)
        faultpoint.configure("checkpoint.save=delay:150ms")
        m.save(0, _state())
        t0 = time.monotonic()
        m.save(1, _state(3.0))
        waited = time.monotonic() - t0
        assert m.backpressure_waits == 1
        assert waited > 0.05, waited  # joined the in-flight persist
        m.flush()
        assert m.all_steps() == [0, 1]
        after = profiler.metrics().get("elastic", {}).get(
            "checkpoint_backpressure", 0)
        assert after == before + 1

    def test_snapshot_copies_host_leaves(self, tmp_path):
        """The persist thread must never race the trainer mutating a
        host-resident numpy leaf: async snapshots deep-copy them."""
        m = _mgr(tmp_path, async_persist=True)
        arr = np.ones(4, np.float32)
        faultpoint.configure("checkpoint.save=delay:100ms")
        m.save(0, {"w": arr})
        arr[:] = 7.0  # trainer moves on while the persist writes
        m.flush()
        got, _ = m.restore()
        assert np.array_equal(np.asarray(got["w"]),
                              np.ones(4, np.float32))

    def test_persist_failure_surfaces_on_next_save(self, tmp_path):
        m = _mgr(tmp_path, async_persist=True)
        m.save(0, _state())
        m.flush()
        before = profiler.metrics().get("elastic", {}).get(
            "persist_failures", 0)
        faultpoint.configure("checkpoint.persist=raise:OSError@n=1")
        m.save(1, _state(3.0))  # returns fine; the thread dies
        with pytest.raises(RuntimeError,
                           match="async checkpoint persist failed"):
            m.save(2, _state(4.0))
        assert profiler.metrics().get("elastic", {}).get(
            "persist_failures", 0) == before + 1
        # the error is one-shot: the manager keeps working after
        m.save(3, _state(5.0))
        m.flush()
        assert m.latest_step() == 3

    def test_flush_reraises_persist_failure(self, tmp_path):
        m = _mgr(tmp_path, async_persist=True)
        faultpoint.configure("checkpoint.persist=raise:OSError@n=1")
        m.save(0, _state())
        with pytest.raises(RuntimeError,
                           match="async checkpoint persist failed"):
            m.flush()

    def test_env_switch_arms_async(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXTPU_CKPT_ASYNC", "1")
        assert _mgr(tmp_path).async_persist
        monkeypatch.setenv("MXTPU_CKPT_ASYNC", "0")
        assert not _mgr(tmp_path).async_persist


class TestCrashConsistency:
    def test_crash_in_snapshot_persist_gap_keeps_published(
            self, tmp_path):
        """The tentpole faultpoint: a death BETWEEN snapshot and
        persist (``checkpoint.persist``) loses exactly the one
        unpublished step — every step that published before it stays
        restorable, and nothing torn is left behind."""
        m = _mgr(tmp_path, async_persist=True)
        m.save(0, _state())
        m.save(1, _state(3.0))
        m.flush()
        faultpoint.configure(
            "checkpoint.persist=raise:RuntimeError@n=1")
        m.save(2, _state(9.0))
        m.flush(raise_error=False)
        assert m.all_steps() == [0, 1]
        got, s = m.restore()
        assert s == 1 and _leaves_equal(got, _state(3.0))
        # no torn artifact for step 2: the faultpoint fired before the
        # temp write began, and a mid-write crash leaves only a .tmp
        # that all_steps()/restore() never consider
        assert not m._is_complete(m._step_path(2))

    def test_crash_mid_durable_write_keeps_published(self, tmp_path):
        """Same contract one layer deeper: a crash between temp-write
        and rename (``checkpoint.save`` inside the persist thread)
        leaves a .tmp leftover, never a half-published step."""
        m = _mgr(tmp_path, async_persist=True)
        m.save(0, _state())
        m.flush()
        faultpoint.configure("checkpoint.save=raise:OSError@n=1")
        m.save(1, _state(3.0))
        m.flush(raise_error=False)
        assert m.all_steps() == [0]
        got, s = m.restore()
        assert s == 0 and _leaves_equal(got, _state())


class TestDelta:
    def test_delta_roundtrip_and_sidecar(self, tmp_path):
        m = _mgr(tmp_path, async_persist=False, delta=True)
        s0 = _state(1.0, 2.0)
        m.save(0, s0)
        s1 = {"w": s0["w"], "m": jnp.asarray([7.0])}  # one leaf changed
        m.save(1, s1)
        with open(m._step_path(1), "rb") as f:
            raw = pickle.load(f)
        assert raw.get("__mxtpu_delta__") == 1 and raw["base"] == 0
        assert len(raw["leaves"]) == 1  # only the changed leaf shipped
        assert m._delta_base_of(1) == 0  # .base sidecar pins the base
        got, s = m.restore()
        assert s == 1 and _leaves_equal(got, s1)

    def test_big_churn_falls_back_to_full(self, tmp_path):
        m = _mgr(tmp_path, async_persist=False, delta=True)
        m.save(0, _state(1.0, 2.0))
        s1 = _state(5.0, 6.0)  # 2/2 leaves changed > the 50% cap
        m.save(1, s1)
        with open(m._step_path(1), "rb") as f:
            raw = pickle.load(f)
        assert not (isinstance(raw, dict)
                    and raw.get("__mxtpu_delta__"))
        # the new full snapshot becomes the base for later deltas
        s2 = {"w": s1["w"], "m": jnp.asarray([9.0])}
        m.save(2, s2)
        assert m._delta_base_of(2) == 1

    def test_structure_change_falls_back_to_full(self, tmp_path):
        m = _mgr(tmp_path, async_persist=False, delta=True)
        m.save(0, _state())
        s1 = {"w": jnp.asarray([1.0, 1.0]), "m": jnp.asarray([2.0]),
              "extra": jnp.asarray([0.0])}
        m.save(1, s1)
        with open(m._step_path(1), "rb") as f:
            raw = pickle.load(f)
        assert not (isinstance(raw, dict)
                    and raw.get("__mxtpu_delta__"))
        got, s = m.restore()
        assert s == 1 and _leaves_equal(got, s1)

    def test_keep_policy_pins_delta_base(self, tmp_path):
        """keep=2 would normally drop step 0, but steps 1 and 2 are
        deltas over it — the .base sidecar protects the full base, so
        every kept delta stays restorable."""
        m = _mgr(tmp_path, async_persist=False, delta=True, keep=2)
        s0 = _state(1.0, 2.0)
        m.save(0, s0)
        for i, v in ((1, 7.0), (2, 8.0)):
            m.save(i, {"w": s0["w"], "m": jnp.asarray([v])})
        assert m.all_steps() == [0, 1, 2]  # 0 pinned by the deltas
        got, s = m.restore(step=1)
        assert s == 1 and np.asarray(got["m"])[0] == 7.0

    def test_failed_publish_never_becomes_base(self, tmp_path):
        """A full snapshot whose persist DIED must not be the base a
        later delta references — the delta would be unrestorable."""
        m = _mgr(tmp_path, async_persist=True, delta=True)
        m.save(0, _state(1.0, 2.0))
        m.flush()
        faultpoint.configure(
            "checkpoint.persist=raise:RuntimeError@n=1")
        m.save(1, _state(5.0, 6.0))  # full (all leaves changed), dies
        m.flush(raise_error=False)
        faultpoint.reset()
        # the recorded failure surfaces once on the next save, then
        # the manager keeps working
        with pytest.raises(RuntimeError,
                           match="async checkpoint persist failed"):
            m.save(2, _state())
        m.save(2, {"w": jnp.asarray([1.0, 1.0]),
                   "m": jnp.asarray([9.0])})
        m.flush()
        assert m._delta_base_of(2) in (None, 0)  # never the dead 1
        got, s = m.restore()
        assert s == 2 and np.asarray(got["m"])[0] == 9.0


class TestSatelliteBugfixes:
    def test_restore_explicit_step_missing_is_clear(self, tmp_path):
        """Satellite 1: restore(step=N) for a step that never published
        gives the same clear verdict the step=None walk gets, not a raw
        deserialize error."""
        m = _mgr(tmp_path)
        m.save(0, _state())
        with pytest.raises(FileNotFoundError,
                           match="incomplete or missing"):
            m.restore(step=5)

    def test_restore_explicit_step_truncated_is_clear(self, tmp_path):
        m = _mgr(tmp_path)
        m.save(3, _state())
        with open(m._step_path(3), "rb") as f:
            whole = f.read()
        with open(m._step_path(3), "wb") as f:
            f.write(whole[:-1])  # crash mid-write: no STOP opcode
        with pytest.raises(FileNotFoundError,
                           match="incomplete or missing"):
            m.restore(step=3)

    def test_prune_skips_inflight_persist_step(self, tmp_path):
        """Satellite 2, unit half: a prune running while step 9's
        persist is in flight must not delete its artifacts (the .tmp
        being written right now); once nothing is in flight the same
        leftovers are swept."""
        m = _mgr(tmp_path, keep=1)
        m.save(0, _state())
        tmp9 = m._step_path(9) + ".tmp"
        with open(tmp9, "wb") as f:
            f.write(b"partial")
        m._persist_step = 9
        m._prune()
        assert os.path.exists(tmp9)  # in flight: untouched
        m._persist_step = None
        m._prune()
        assert not os.path.exists(tmp9)  # stale leftover: swept

    def test_concurrent_prune_during_persist_end_to_end(self, tmp_path):
        """Satellite 2, interleaved half: prune fired from the main
        thread while the persist thread is mid-write; the in-flight
        step still publishes, and the persist's own re-prune then
        applies the keep policy."""
        m = _mgr(tmp_path, async_persist=True, keep=1)
        m.save(0, _state())
        m.flush()
        faultpoint.configure("checkpoint.save=delay:200ms")
        m.save(1, _state(3.0))
        m._prune()  # concurrent with the in-flight persist of step 1
        faultpoint.reset()
        m.flush()
        assert m.all_steps() == [1]  # published, then re-pruned 0
        got, s = m.restore()
        assert s == 1 and _leaves_equal(got, _state(3.0))


def _sleep_step(state, b):
    time.sleep(0.02)
    return {"acc": state["acc"] + b}, None


class TestChaosAcceptancePair:
    def test_kill_between_snapshot_and_persist_books_lost_work(
            self, tmp_path):
        """Satellite 3: incarnation 1 dies between snapshot and persist
        (the persist failure surfaces on the next save, felling the
        loop exactly like a process death would). Incarnation 2 resumes
        from the newest PUBLISHED step, books the resume under
        ``recovery``, and finishes bitwise-identical to an unfaulted
        twin."""
        batches = [jnp.asarray(float(i)) for i in range(8)]
        twin, _, done = elastic_train_loop(
            _sleep_step, {"acc": jnp.asarray(0.0)}, batches,
            CheckpointManager(str(tmp_path / "twin"), use_orbax=False),
            save_every=2)
        assert done

        ck = CheckpointManager(str(tmp_path / "ck"), use_orbax=False,
                               async_persist=True)
        # save@0 publishes; save@2's persist dies in the gap; save@4
        # surfaces the failure and fells incarnation 1
        faultpoint.configure(
            "checkpoint.persist=raise:RuntimeError@skip=1@n=1")
        with pytest.raises(RuntimeError,
                           match="async checkpoint persist failed"):
            elastic_train_loop(
                _sleep_step, {"acc": jnp.asarray(0.0)}, batches, ck,
                save_every=2)
        faultpoint.reset()
        assert goodput.last_manifest()["outcome"] == "failed"
        assert ck.all_steps() == [0]  # newest PUBLISHED step

        ck2 = CheckpointManager(str(tmp_path / "ck"), use_orbax=False,
                                async_persist=True)
        state, last, done = elastic_train_loop(
            _sleep_step, {"acc": jnp.asarray(0.0)}, batches, ck2,
            save_every=2)
        assert done and last == len(batches) - 1
        m = goodput.last_manifest()
        assert m["outcome"] == "completed"
        assert m["counters"]["recoveries"] == 1
        assert m["categories_s"]["recovery"] > 0.0
        # resumed training is bitwise-identical to the unfaulted twin
        assert float(state["acc"]) == float(twin["acc"])

    def test_fault_free_async_twin_checkpoint_drops_vs_sync(
            self, tmp_path):
        """The control half: at EQUAL cadence with the same injected
        30ms durable-write stall, the async twin's blocking
        ``checkpoint`` seconds collapse (the stall moved off-thread
        into ``checkpoint_persist_s``) while the sync baseline pays it
        inline."""
        batches = [jnp.asarray(float(i)) for i in range(6)]
        faultpoint.configure("checkpoint.save=delay:30ms")
        try:
            elastic_train_loop(
                _sleep_step, {"acc": jnp.asarray(0.0)}, batches,
                CheckpointManager(str(tmp_path / "sync"),
                                  use_orbax=False, async_persist=False),
                save_every=2)
            m_sync = goodput.last_manifest()
            elastic_train_loop(
                _sleep_step, {"acc": jnp.asarray(0.0)}, batches,
                CheckpointManager(str(tmp_path / "async"),
                                  use_orbax=False, async_persist=True),
                save_every=2)
            m_async = goodput.last_manifest()
        finally:
            faultpoint.reset()
        sync_s = m_sync["categories_s"]["checkpoint"]
        async_s = m_async["categories_s"]["checkpoint"]
        assert sync_s >= 0.09  # 3 saves x 30ms paid inline
        assert async_s < 0.5 * sync_s, (async_s, sync_s)
        # the hidden work is accounted, not vanished
        assert m_async["counters"]["checkpoint_persist_s"] >= 0.09
        assert m_async["counters"]["checkpoint_saves"] == \
            m_sync["counters"]["checkpoint_saves"]
