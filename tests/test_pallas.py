"""Pallas kernel tests — run under interpret mode on the CPU test platform
(ref slot: src/common/rtc.cc custom-kernel tests, tests/python/gpu/test_rtc.py;
gradient compression: tests/nightly/test_kvstore.py compression cases)."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.pallas_kernels import (flash_attention, quantize_2bit,
                                      dequantize_2bit, quantize_2bit_jnp,
                                      dequantize_2bit_jnp)
from mxnet_tpu.pallas_kernels.flash_attention import attention_reference


def _qkv(b=2, h=4, s=256, d=64, seed=0):
    rng = onp.random.RandomState(seed)
    return (jnp.array(rng.randn(b, h, s, d).astype("float32")),
            jnp.array(rng.randn(b, h, s, d).astype("float32")),
            jnp.array(rng.randn(b, h, s, d).astype("float32")))


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=causal, block_q=64,
                              block_k=64, interpret=True)
        ref = attention_reference(q, k, v, causal=causal)
        assert float(jnp.abs(out - ref).max()) < 1e-5

    def test_block_sizes_equivalent(self):
        q, k, v = _qkv(s=128)
        ref = attention_reference(q, k, v)
        for bq, bk in [(128, 128), (64, 128), (128, 64), (32, 32)]:
            out = flash_attention(q, k, v, block_q=bq, block_k=bk,
                                  interpret=True)
            assert float(jnp.abs(out - ref).max()) < 1e-5, (bq, bk)

    def test_gradients(self):
        q, k, v = _qkv(s=128)
        g = jax.grad(lambda a, b, c: flash_attention(
            a, b, c, causal=True, interpret=True).sum(), (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda a, b, c: attention_reference(
            a, b, c, causal=True).sum(), (0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            assert float(jnp.abs(got - want).max()) < 1e-4

    def test_cross_attention_lengths(self):
        q, _, _ = _qkv(s=128)
        _, k, v = _qkv(s=256, seed=1)
        out = flash_attention(q, k, v, interpret=True)
        ref = attention_reference(q, k, v)
        assert out.shape == (2, 4, 128, 64)
        assert float(jnp.abs(out - ref).max()) < 1e-5

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward_matches_reference(self, causal):
        """The Pallas dq/dk/dv kernels (flash-2 recompute) vs the XLA vjp
        of the dense reference."""
        q, k, v = _qkv(s=128)
        g = jax.grad(lambda a, b, c: (flash_attention(
            a, b, c, causal=causal, block_q=64, block_k=32,
            interpret=True) ** 2).sum(), (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda a, b, c: (attention_reference(
            a, b, c, causal=causal) ** 2).sum(), (0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            assert float(jnp.abs(got - want).max()) < 1e-3

    def test_backward_cross_attention(self):
        q, _, _ = _qkv(s=64)
        _, k, v = _qkv(s=128, seed=1)
        g = jax.grad(lambda a, b, c: flash_attention(
            a, b, c, block_q=32, block_k=64, interpret=True).sum(),
            (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda a, b, c: attention_reference(
            a, b, c).sum(), (0, 1, 2))(q, k, v)
        assert g[0].shape == q.shape and g[1].shape == k.shape
        for got, want in zip(g, gr):
            assert float(jnp.abs(got - want).max()) < 1e-3

    def test_backward_bf16(self):
        q, k, v = _qkv(s=128)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        g = jax.grad(lambda a, b, c: flash_attention(
            a, b, c, causal=True, interpret=True).astype(
                jnp.float32).sum(), (0, 1, 2))(qb, kb, vb)
        gr = jax.grad(lambda a, b, c: attention_reference(
            a, b, c, causal=True).sum(), (0, 1, 2))(q, k, v)
        for got, want in zip(g, gr):
            assert got.dtype == jnp.bfloat16
            err = jnp.abs(got.astype(jnp.float32) - want).max()
            assert float(err) < 0.2  # bf16 has ~3 decimal digits

    def test_jittable(self):
        q, k, v = _qkv(s=128)
        f = jax.jit(lambda a, b, c: flash_attention(a, b, c, causal=True,
                                                    interpret=True))
        ref = attention_reference(q, k, v, causal=True)
        assert float(jnp.abs(f(q, k, v) - ref).max()) < 1e-5


class TestCompression:
    def test_semantics_match_reference_struct(self):
        """ref: gradient_compression-inl.h quantize_2bit — +thr / -thr / 0
        with error feedback."""
        grad = jnp.array([0.6, -0.7, 0.1, 0.0, 0.49, -0.5])
        res = jnp.zeros(6)
        words, new_res = quantize_2bit_jnp(grad, res, 0.5)
        deq = dequantize_2bit_jnp(words, 6, 0.5)
        onp.testing.assert_allclose(
            onp.asarray(deq), [0.5, -0.5, 0.0, 0.0, 0.0, -0.5], atol=1e-6)
        # residual keeps what quantization dropped
        onp.testing.assert_allclose(
            onp.asarray(new_res),
            [0.1, -0.2, 0.1, 0.0, 0.49, 0.0], atol=1e-6)

    def test_error_feedback_identity(self):
        rng = onp.random.RandomState(0)
        grad = jnp.array(rng.randn(1000).astype("float32"))
        words, new_res = quantize_2bit_jnp(grad, jnp.zeros(1000), 0.5)
        deq = dequantize_2bit_jnp(words, 1000, 0.5)
        # deq + residual == grad exactly (nothing lost, only deferred)
        assert float(jnp.abs((deq + new_res) - grad).max()) < 1e-6

    def test_pallas_matches_jnp(self):
        rng = onp.random.RandomState(1)
        grad = jnp.array(rng.randn(4096).astype("float32"))
        res = jnp.array(rng.randn(4096).astype("float32")) * 0.1
        w_j, r_j = quantize_2bit_jnp(grad, res, 0.5)
        w_p, r_p = quantize_2bit(grad, res, 0.5, interpret=True)
        assert bool((w_j == w_p).all())
        assert float(jnp.abs(r_j - r_p).max()) == 0.0
        d_j = dequantize_2bit_jnp(w_j, 4096, 0.5)
        d_p = dequantize_2bit(w_p, 4096, 0.5, interpret=True)
        assert bool((d_j == d_p).all())

    def test_ragged_length(self):
        grad = jnp.ones((37,)) * 0.6
        words, res = quantize_2bit_jnp(grad, jnp.zeros(37), 0.5)
        assert words.shape == (3,)  # ceil(37/16)
        deq = dequantize_2bit_jnp(words, 37, 0.5)
        assert deq.shape == (37,)
        assert bool((deq == 0.5).all())

    def test_compression_ratio(self):
        grad = jnp.zeros((1600,), jnp.float32)
        words, _ = quantize_2bit_jnp(grad, jnp.zeros(1600), 0.5)
        assert grad.nbytes / words.nbytes == 16.0


class TestKVStoreCompression:
    def test_kvstore_roundtrip_with_residual(self):
        import mxnet_tpu as mx
        kv = mx.kv.create("local")
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5,
                                     "size_lower_bound": 0})
        kv.init(3, mx.nd.zeros((8, 8)))
        g = mx.nd.ones((8, 8)) * 0.3  # below threshold -> all zeros, kept
        kv.push(3, g)
        out = mx.nd.zeros((8, 8))
        kv.pull(3, out=out)
        assert onp.abs(out.asnumpy()).max() == 0.0  # quantized to zero
        kv.push(3, g)  # residual 0.3 + 0.3 = 0.6 >= thr -> fires now
        kv.pull(3, out=out)
        assert onp.allclose(out.asnumpy(), 0.5)

    def test_small_tensors_not_compressed(self):
        import mxnet_tpu as mx
        kv = mx.kv.create("local")
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.init(4, mx.nd.zeros((10,)))
        g = mx.nd.ones((10,)) * 0.01  # small bias-like gradient
        kv.push(4, g)
        out = mx.nd.zeros((10,))
        kv.pull(4, out=out)
        # below size_lower_bound: passes through uncompressed
        assert onp.allclose(out.asnumpy(), 0.01)


def test_flash_causal_rejects_unequal_lengths():
    """The fully-masked-row invariant is enforced at the public boundary
    (ADVICE r4): causal with kv shorter than q would leave leading rows
    with no visible keys and NaN silently in the kernel."""
    import jax.numpy as jnp
    from mxnet_tpu.pallas_kernels.flash_attention import flash_attention
    q = jnp.zeros((1, 2, 256, 64), jnp.float32)
    kv = jnp.zeros((1, 2, 128, 64), jnp.float32)
    with pytest.raises(ValueError, match="equal q/kv lengths"):
        flash_attention(q, kv, kv, causal=True)
    # non-causal cross-attention with unequal lengths stays legal
    out = flash_attention(q, kv, kv, causal=False, interpret=True)
    assert out.shape == q.shape
