"""The fault-tolerant sharded data plane (ISSUE 11; docs/DATA.md).

Four layers, matching the module split:

* pure assignment math — partition/coverage/purity of
  ``epoch_order``/``assign_shards``/``reassign_shards``/``batch_slices``
  at world sizes 1/2/4, plus the mid-epoch reassignment of a dead
  rank's unconsumed shards;
* the committed sample cursor — commit/seek round-trips through the
  PR 7 crash-consistency contract (temp+rename, injected mid-save
  crash leaves the previous cursor restorable);
* the hardened io plane — ``RecordIORangeReader`` (retry, crc,
  corrupt-record budget) and ``DecodePool`` (order preservation at any
  worker count, bounded per-worker restarts, graceful degradation,
  poison items, the raise-once surface);
* chaos acceptance — a training run with a decode worker killed
  abruptly, 15% injected read faults, and a rank death mid-epoch
  produces final params BITWISE-equal to the fault-free run resumed
  from the same checkpoint, with full ``metrics()['io']``/``['faults']``
  accounting.
"""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401 — package init wires the io provider
import mxnet_tpu._debug.faultpoint as fp
from mxnet_tpu import profiler
from mxnet_tpu._retry import RetryPolicy
from mxnet_tpu.io import (ShardService, DecodePool, RecordIORangeReader,
                          CorruptRecordError, build_crc_sidecar,
                          epoch_order, assign_shards, reassign_shards,
                          unconsumed_shards, batch_slices)
from mxnet_tpu.io import _stats as io_stats
from mxnet_tpu.io.shard_service import num_shards, shard_positions
from mxnet_tpu.parallel.elastic import CheckpointManager, \
    elastic_train_loop
from mxnet_tpu.recordio import MXIndexedRecordIO


@pytest.fixture(autouse=True)
def _clean():
    fp.reset()
    io_stats.reset()
    yield
    fp.reset()
    io_stats.reset()


# -- pure assignment math -----------------------------------------------------

class TestAssignmentMath:
    def test_epoch_order_is_pure_permutation(self):
        a = epoch_order(257, 4, seed=9)
        b = epoch_order(257, 4, seed=9)
        np.testing.assert_array_equal(a, b)
        assert sorted(a) == list(range(257))
        # epoch and seed both move the sequence
        assert not np.array_equal(a, epoch_order(257, 5, seed=9))
        assert not np.array_equal(a, epoch_order(257, 4, seed=10))

    @pytest.mark.parametrize("world", [(0,), (0, 1), (0, 1, 2, 3),
                                       (3, 7, 11)])
    def test_assign_shards_partitions_exactly(self, world):
        ns = 13
        owned = [assign_shards(2, world, r, ns) for r in world]
        flat = sorted(s for o in owned for s in o)
        assert flat == list(range(ns))  # disjoint AND complete
        # pure: identical on recomputation (the survivors-agree
        # property is exactly this)
        assert owned == [assign_shards(2, world, r, ns) for r in world]

    def test_assign_shards_rotates_by_epoch(self):
        w = (0, 1, 2)
        e0 = assign_shards(0, w, 0, 9)
        e1 = assign_shards(1, w, 0, 9)
        assert e0 != e1  # pairing rebalances across epochs
        for e in (0, 1, 2):
            flat = sorted(s for r in w
                          for s in assign_shards(e, w, r, 9))
            assert flat == list(range(9))

    def test_assign_shards_rejects_foreign_rank(self):
        with pytest.raises(ValueError, match="not in world"):
            assign_shards(0, (0, 1), 2, 4)

    def test_reassign_covers_exactly_the_given_set(self):
        un = unconsumed_shards(130, 1000, 64)  # shards 2..15
        assert un == tuple(range(2, 16))
        survivors = (0, 2)
        re = [reassign_shards(3, survivors, r, un) for r in survivors]
        assert sorted(s for o in re for s in o) == sorted(un)
        assert re == [reassign_shards(3, survivors, r, un)
                      for r in survivors]

    def test_unconsumed_boundaries(self):
        assert unconsumed_shards(0, 100, 10) == tuple(range(10))
        assert unconsumed_shards(100, 100, 10) == ()
        # offset mid-shard: that shard is still (partially) unconsumed
        assert unconsumed_shards(15, 100, 10)[0] == 1

    def test_batch_slices_contiguous_sorted_ragged(self):
        sl = batch_slices(40, 10, (2, 0, 1))
        assert [list(sl[r]) for r in (0, 1, 2)] == \
            [[40, 41, 42, 43], [44, 45, 46], [47, 48, 49]]
        # total coverage, no overlap, in sorted-rank order
        flat = [p for r in sorted(sl) for p in sl[r]]
        assert flat == list(range(40, 50))

    @pytest.mark.parametrize("world", [(0,), (0, 1), (0, 1, 2, 3)])
    def test_global_sequence_identical_across_world_sizes(self, world):
        """THE determinism contract: the union of all ranks' streams,
        ordered by global position, is the same sample sequence at
        every world size."""
        n, seed = 50, 1
        out = {}
        for r in world:
            svc = ShardService(n, shard_size=8, seed=seed, world=world,
                               rank=r)
            for pos, sid in svc.iter_samples(0):
                assert pos not in out, "duplicate position"
                out[pos] = sid
        seq = [out[p] for p in sorted(out)]
        assert sorted(out) == list(range(n))
        assert seq == list(epoch_order(n, 0, seed))

    def test_mid_epoch_resize_covers_unconsumed_exactly(self):
        """After a rank death the survivors' reassigned streams cover
        exactly the positions at or past the committed cursor — no
        loss, no duplication — computed from committed state alone."""
        n, sz, seed = 96, 8, 2
        world, survivors, offset = (0, 1, 2), (0, 2), 40
        cover = {}
        for r in survivors:
            svc = ShardService(n, shard_size=sz, seed=seed,
                               world=world, rank=r)
            svc.offset = offset       # the committed cursor
            svc.resize(survivors)
            for pos, sid in svc.iter_samples():
                assert pos not in cover
                cover[pos] = sid
        assert sorted(cover) == list(range(offset, n))
        order = epoch_order(n, 0, seed)
        assert [cover[p] for p in sorted(cover)] == \
            [int(order[p]) for p in range(offset, n)]

    def test_shard_positions_ragged_tail(self):
        assert list(shard_positions(2, 20, 8)) == [16, 17, 18, 19]
        assert num_shards(20, 8) == 3


# -- the committed sample cursor ---------------------------------------------

class TestSampleCursor:
    def test_commit_seek_roundtrip(self, tmp_path):
        svc = ShardService(100, shard_size=10, seed=3,
                           cursor_dir=str(tmp_path / "cur"))
        svc.begin_epoch(2)
        svc.advance(37)
        svc.commit(step=5)
        svc.advance(20)
        svc.commit(step=6)
        # a fresh incarnation (the restarted process) seeks back
        svc2 = ShardService(100, shard_size=10, seed=3,
                            cursor_dir=str(tmp_path / "cur"))
        cur = svc2.seek(5)
        assert (cur["epoch"], cur["offset"]) == (2, 37)
        assert (svc2.epoch, svc2.offset) == (2, 37)
        # seek(None) -> newest; seek past the last commit -> newest <=
        assert svc2.seek(None)["offset"] == 57
        assert svc2.seek(99)["offset"] == 57
        m = profiler.metrics()["io"]
        assert m.get("cursor_commits", 0) >= 2
        assert m.get("cursor_restores", 0) >= 3

    def test_seek_without_commits_is_fresh_epoch0(self, tmp_path):
        svc = ShardService(10, shard_size=5,
                           cursor_dir=str(tmp_path / "cur"))
        cur = svc.seek(7)
        assert (cur["epoch"], cur["offset"]) == (0, 0)

    def test_cursor_commit_is_crash_consistent(self, tmp_path):
        """An injected crash between the cursor's temp write and its
        rename (the PR 5 `checkpoint.save` seam — the cursor rides the
        SAME contract) leaves the previous committed cursor
        restorable."""
        svc = ShardService(100, shard_size=10,
                           cursor_dir=str(tmp_path / "cur"))
        svc.advance(30)
        svc.commit(step=3)
        svc.advance(10)
        fp.configure({"checkpoint.save": "raise:OSError@n=1"})
        with pytest.raises(OSError):
            svc.commit(step=4)
        fp.reset()
        svc2 = ShardService(100, shard_size=10,
                            cursor_dir=str(tmp_path / "cur"))
        assert svc2.seek(None)["offset"] == 30  # step-3 cursor intact

    def test_advance_rolls_epochs(self):
        svc = ShardService(20, shard_size=5)
        svc.advance(20 + 7)
        assert (svc.epoch, svc.offset) == (1, 7)
        # the new epoch re-derives the full-epoch pure assignment
        assert svc.my_shards == assign_shards(1, svc.world, 0,
                                              svc.n_shards, svc.seed)


# -- the decode pool ----------------------------------------------------------

class TestDecodePool:
    @pytest.mark.parametrize("workers", [1, 2, 4])
    def test_order_preserved_at_any_worker_count(self, workers):
        pool = DecodePool(list(range(40)), lambda x: x * 3,
                          workers=workers)
        assert list(pool) == [x * 3 for x in range(40)]

    def test_transient_chaos_recovers_with_accounting(self):
        fp.configure({"io.worker.decode": "raise:ValueError@p=0.3"},
                     seed=5)
        pool = DecodePool(list(range(40)), lambda x: x * 2, workers=2)
        got = list(pool)
        deaths = fp.triggers("io.worker.decode")
        fp.reset()
        assert got == [x * 2 for x in range(40)]  # nothing lost/reordered
        assert deaths > 0
        m = profiler.metrics()["io"]
        assert sum(v for k, v in m.items()
                   if k.startswith("worker_deaths.")) == deaths
        assert sum(v for k, v in m.items()
                   if k.startswith("worker_restarts.")) == deaths

    def test_abrupt_systemexit_death_is_recovered(self):
        """The thread-world SIGKILL: a worker dying via SystemExit
        (BaseException, no cleanup by the decode_fn) still requeues the
        claimed item and restarts — no sample lost, order intact."""
        killed = []

        def decode(x):
            if x == 5 and not killed:
                killed.append(x)
                raise SystemExit("worker killed")
            return x + 100

        pool = DecodePool(list(range(12)), decode, workers=2)
        assert list(pool) == [x + 100 for x in range(12)]
        m = profiler.metrics()["io"]
        assert sum(v for k, v in m.items()
                   if k.startswith("worker_deaths.")) == 1
        assert sum(v for k, v in m.items()
                   if k.startswith("worker_restarts.")) == 1

    def test_budget_exhaustion_degrades_then_serves(self):
        """One injected death with a zero-restart budget retires that
        worker; the pool degrades to fewer workers and still delivers
        everything in order."""
        fp.configure({"io.worker.decode": "raise:ValueError@n=1"})
        pool = DecodePool(list(range(20)), lambda x: x, workers=2,
                          restarts_per_worker=0)
        got = list(pool)
        fp.reset()
        assert got == list(range(20))
        assert len(pool.live_workers) == 1
        m = profiler.metrics()["io"]
        assert m.get("workers_retired") == 1
        assert m.get("pool_workers") == 1  # the degraded gauge

    def test_all_workers_dead_raises_once_then_exhausts_then_resets(self):
        calls = {"broken": True}

        def decode(x):
            if calls["broken"]:
                raise IOError("decoder broken")
            return x * 7

        src = list(range(6))
        pool = DecodePool(src, decode, workers=2,
                          restarts_per_worker=1, item_retries=1000)
        with pytest.raises(RuntimeError, match="all 2 workers retired"):
            list(pool)
        # raise-once surface: afterwards it reads exhausted
        with pytest.raises(StopIteration):
            next(pool)
        assert list(pool) == []
        # reset() rebuilds with fresh budgets; a healed decoder serves
        calls["broken"] = False
        pool.reset()
        assert list(pool) == [x * 7 for x in src]

    def test_poison_item_surfaces_once_at_its_ordered_position(self):
        def decode(x):
            if x == 7:
                raise ValueError("poison payload 7")
            return x

        pool = DecodePool(list(range(12)), decode, workers=2,
                          item_retries=2)
        got = []
        with pytest.raises(ValueError, match="poison payload 7"):
            for v in pool:
                got.append(v)
        assert got == list(range(7))  # everything before, in order
        with pytest.raises(StopIteration):
            next(pool)
        # the workers survived — the item was poison, not the pool
        assert pool.live_workers == [0, 1]

    def test_source_error_surfaces_once_in_order(self):
        def src():
            yield from range(5)
            raise OSError("source broke")

        pool = DecodePool(src(), lambda x: x, workers=2)
        got = []
        with pytest.raises(OSError, match="source broke"):
            for v in pool:
                got.append(v)
        assert got == list(range(5))

    def test_per_worker_lanes_and_flightrec_context(self):
        from mxnet_tpu._debug import flightrec
        pool = DecodePool(list(range(4)), lambda x: x, workers=2,
                          name="lanes-test")
        list(pool)
        assert profiler.LANES["io.w0"] >= 16
        assert profiler.LANES["io.w1"] >= 16
        assert profiler.LANES["io.w0"] != profiler.LANES["io.w1"]
        with flightrec._context_lock:
            ctx = flightrec._context.get("io_workers:lanes-test")
        assert ctx is not None and set(ctx) == {"0", "1"}
        assert ctx["0"]["state"] in ("idle", "decoding", "retired")


# -- the range reader ---------------------------------------------------------

def _write_rec(tmp_path, payloads, name="a"):
    rec = str(tmp_path / ("%s.rec" % name))
    idx = str(tmp_path / ("%s.idx" % name))
    w = MXIndexedRecordIO(idx, rec, "w")
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()
    return rec, idx


class TestRangeReader:
    def test_parity_with_indexed_reader_and_scan(self, tmp_path):
        payloads = [bytes([i]) * (5 + 3 * i) for i in range(15)]
        rec, idx = _write_rec(tmp_path, payloads)
        by_idx = RecordIORangeReader(rec, index=idx)
        by_scan = RecordIORangeReader(rec)  # header-hop scan
        assert len(by_idx) == len(by_scan) == 15
        for i in range(15):
            assert by_idx.read_record(i) == payloads[i]
            assert by_scan.read_record(i) == payloads[i]
        assert by_idx._offsets == by_scan._offsets

    def test_transient_read_fault_is_retried_and_counted(self, tmp_path):
        rec, idx = _write_rec(tmp_path, [b"hello world"])
        fp.configure({"io.shard.read": "raise:ConnectionError@n=3"})
        r = RecordIORangeReader(rec, index=idx,
                                retry_policy=RetryPolicy(base=0.001))
        assert r.read_record(0) == b"hello world"
        fp.reset()
        assert profiler.metrics()["io"]["read_retries"] == 3

    def test_crc_catches_payload_bitflip(self, tmp_path):
        payloads = [b"A" * 16, b"B" * 16, b"C" * 16]
        rec, idx = _write_rec(tmp_path, payloads)
        build_crc_sidecar(rec)
        data = bytearray(open(rec, "rb").read())
        # flip one payload byte of record 1 (header 8B + 16B + pad...):
        # structure (magic/length) stays valid — only the crc can tell
        off1 = RecordIORangeReader(rec, index=idx)._offsets[1]
        data[off1 + 8 + 3] ^= 0x01
        with open(rec, "wb") as f:
            f.write(bytes(data))
        r = RecordIORangeReader(rec, index=idx)  # .crc auto-loaded
        assert r.read_record(0) == payloads[0]
        with pytest.raises(CorruptRecordError, match="crc mismatch"):
            r.read_record(1)
        # skip-and-count form drops the sample and keeps serving
        assert r.read(1) is None
        assert r.read(2) == payloads[2]
        assert profiler.metrics()["io"]["corrupt_records"] == 1

    def test_bad_magic_is_corrupt_not_retried(self, tmp_path):
        payloads = [b"x" * 8, b"y" * 8]
        rec, idx = _write_rec(tmp_path, payloads)
        data = bytearray(open(rec, "rb").read())
        data[0] ^= 0xFF  # clobber record 0's magic
        with open(rec, "wb") as f:
            f.write(bytes(data))
        r = RecordIORangeReader(rec, index=idx)
        t0 = __import__("time").perf_counter()
        with pytest.raises(CorruptRecordError, match="bad magic"):
            r.read_record(0)
        # CorruptRecordError must NOT enter the transient-retry set:
        # no backoff sleeps happened
        assert __import__("time").perf_counter() - t0 < 1.0
        assert io_stats.get("read_retries") == 0

    def test_corrupt_budget_trips_to_hard_error(self, tmp_path):
        rec, idx = _write_rec(tmp_path, [b"ok%d" % i for i in range(6)])
        fp.configure({"io.record.corrupt": "raise:ValueError"})
        r = RecordIORangeReader(rec, index=idx, corrupt_budget=2)
        assert r.read(0) is None and r.read(1) is None
        with pytest.raises(CorruptRecordError,
                           match="budget exhausted"):
            r.read(2)
        assert fp.metrics().get("io.record.corrupt") == 3
        fp.reset()
        assert r.corrupt_skipped == 3


# -- service faultpoints ------------------------------------------------------

class TestServiceFaultpoints:
    def test_service_fetch_seam_counts_and_propagates(self):
        svc = ShardService(10, shard_size=5)
        fp.configure({"io.service.fetch": "raise:ConnectionError@n=1"})
        with pytest.raises(ConnectionError):
            svc.fetch_batch([1, 2, 3])
        # the schedule is exhausted: the retried RPC succeeds
        assert svc.fetch_batch([1, 2, 3]) == [1, 2, 3]
        assert fp.metrics().get("io.service.fetch") == 1
        fp.reset()


# -- end-to-end determinism + chaos ------------------------------------------

def _order_sensitive_step(w, batch_vals):
    """An UPDATE whose result depends on the order of the batch — so
    bitwise equality below really pins the global sample order, not
    just the sample multiset."""
    acc = np.float32(0.0)
    for v in batch_vals:
        acc = np.float32(acc * np.float32(1.0009765625)
                         + np.float32(v))
    return np.float32(w * np.float32(0.75) + np.float32(0.01) * acc)


def _assemble_global_batch(svcs, live_world, offset, B):
    """Trainer-side batch assembly: each live rank contributes ITS
    slice of the global batch (batch_slices), concatenated by global
    position — reproducing the world-independent global order."""
    sl = batch_slices(offset, B, live_world)
    parts = []
    for r in live_world:
        order = svcs[r].global_sequence()
        parts.extend((p, int(order[p])) for p in sl[r])
    parts.sort()
    return [sid for _, sid in parts]


class TestEpochDeterminismTraining:
    N, B, SEED = 64, 8, 5  # 8 steps per epoch

    def _run(self, tmp_path, tag, world, kill_rank_at=None,
             resume_ckpt_from=None):
        """One training run over a single epoch. ``kill_rank_at=k``
        declares the highest rank dead after step k completed:
        survivors reshard, rewind params AND cursor to the newest
        checkpoint, and finish the epoch alone. Returns (final w,
        [global batches consumed], ckpt dir)."""
        steps = self.N // self.B
        ckdir = str(tmp_path / ("ck_%s" % tag)) \
            if resume_ckpt_from is None else resume_ckpt_from
        ck = CheckpointManager(ckdir, keep=10, use_orbax=False)
        live = sorted(world)
        svcs = {r: ShardService(
            self.N, shard_size=self.B, seed=self.SEED, world=world,
            rank=r, cursor_dir=str(tmp_path / ("cur_%s_%d" % (tag, r))))
            for r in world}
        w = np.float32(1.0)
        restored, s0 = ck.restore()
        k = 0
        if restored is not None:
            w = np.float32(restored["w"])
            for r in live:
                svcs[r].seek(s0)
            k = s0 + 1
        batches = []
        while k < steps:
            offset = k * self.B
            ids = _assemble_global_batch(svcs, live, offset, self.B)
            batches.append(ids)
            w = _order_sensitive_step(w, ids)
            for r in live:
                svcs[r].advance(self.B)
            if k % 2 == 1:  # checkpoint cadence
                ck.save(k, {"w": w})
                for r in live:
                    svcs[r].commit(k)
            if kill_rank_at is not None and k == kill_rank_at:
                dead = live[-1]
                live = [r for r in live if r != dead]
                # survivors: pure reshard + rewind to the committed pair
                restored, s0 = ck.restore()
                w = np.float32(restored["w"])
                for r in live:
                    svcs[r].resize(live)
                    svcs[r].seek(s0)
                k = s0 + 1
                kill_rank_at = None
                # drop the rolled-back batches from the consumed log
                batches = batches[:k]
                continue
            k += 1
        return w, batches, ckdir

    def test_global_batches_identical_across_world_sizes(self, tmp_path):
        runs = [self._run(tmp_path, "w%d" % len(ws), ws)
                for ws in [(0,), (0, 1), (0, 1, 2, 3)]]
        (w1, b1, _), (w2, b2, _), (w4, b4, _) = runs
        assert b1 == b2 == b4  # the same (seed, epoch) sample sequence
        # and therefore bitwise-identical training
        assert w1.tobytes() == w2.tobytes() == w4.tobytes()

    def test_mid_epoch_rank_death_is_bitwise_equal_to_clean_run(
            self, tmp_path):
        """THE chaos determinism contract: rank 1 dies after step 4;
        rank 0 reshards, rewinds to the step-3 checkpoint+cursor, and
        finishes the epoch alone — final params bitwise-equal to the
        uninterrupted world-(0,1) run AND to a clean run resumed from
        the same checkpoint."""
        w_clean, b_clean, _ = self._run(tmp_path, "clean", (0, 1))
        w_chaos, b_chaos, _ = self._run(tmp_path, "chaos", (0, 1),
                                        kill_rank_at=4)
        assert b_chaos == b_clean
        assert w_chaos.tobytes() == w_clean.tobytes()
        assert profiler.metrics()["io"]["service_resizes"] >= 1

    def test_chaos_resume_equals_clean_resume_from_same_ckpt(
            self, tmp_path):
        """Kill the whole job at step 5 (both variants share the same
        checkpoint dir), then resume once cleanly and once with a rank
        death mid-resume: bitwise-equal finals."""
        steps = self.N // self.B

        def partial(tag):
            ckdir = str(tmp_path / ("ck_%s" % tag))
            ck = CheckpointManager(ckdir, keep=10, use_orbax=False)
            svcs = {r: ShardService(
                self.N, shard_size=self.B, seed=self.SEED,
                world=(0, 1), rank=r,
                cursor_dir=str(tmp_path / ("cur_%s_%d" % (tag, r))))
                for r in (0, 1)}
            w = np.float32(1.0)
            for k in range(6):  # die after step 5 (ckpt at 5)
                ids = _assemble_global_batch(svcs, [0, 1], k * self.B,
                                             self.B)
                w = _order_sensitive_step(w, ids)
                for r in (0, 1):
                    svcs[r].advance(self.B)
                if k % 2 == 1:
                    ck.save(k, {"w": w})
                    for r in (0, 1):
                        svcs[r].commit(k)
            return ckdir

        ck_a, ck_b = partial("ra"), partial("rb")
        w_clean, _, _ = self._run(tmp_path, "ra", (0, 1),
                                  resume_ckpt_from=ck_a)
        w_chaos, _, _ = self._run(tmp_path, "rb", (0, 1),
                                  kill_rank_at=6,
                                  resume_ckpt_from=ck_b)
        assert w_chaos.tobytes() == w_clean.tobytes()


class TestFullPlaneChaos:
    """The acceptance scenario: records on disk, range reads with 15%
    injected faults, a decode worker killed abruptly, AND a rank death
    mid-epoch — the survivors' resumed run is bitwise-equal to the
    fault-free run, with full accounting."""

    N, B, SEED = 48, 8, 7

    def _make_rec(self, tmp_path):
        payloads = [struct.pack("<I", i * 11 + 3)
                    for i in range(self.N)]
        rec, idx = _write_rec(tmp_path, payloads, name="plane")
        build_crc_sidecar(rec)
        return rec, idx

    def _run(self, tmp_path, rec, idx, tag, chaos):
        steps = self.N // self.B

        # the decode-worker SIGKILL leg of the chaos runs through the
        # DecodePool in the companion stream check (below); this
        # trainer-side run injects the READ faults + the rank death
        live = [0, 1]

        def decode(payload):
            return struct.unpack("<I", payload)[0]

        readers = {r: RecordIORangeReader(
            rec, index=idx, retry_policy=RetryPolicy(base=0.001))
            for r in live}
        svcs = {r: ShardService(
            self.N, shard_size=self.B, seed=self.SEED, world=(0, 1),
            rank=r, reader=readers[r], decode_fn=decode,
            cursor_dir=str(tmp_path / ("cur_%s_%d" % (tag, r))))
            for r in live}
        ck = CheckpointManager(str(tmp_path / ("ck_%s" % tag)),
                               keep=10, use_orbax=False)
        if chaos:
            fp.configure({"io.shard.read": "raise:OSError@p=0.15"},
                         seed=13)
        try:
            w = np.float32(2.0)
            k = 0
            kill_at = 3 if chaos else None
            while k < steps:
                offset = k * self.B
                sl = batch_slices(offset, self.B, live)
                # each live rank FETCHES its slice through the full
                # hardened plane (range reader + decode pool),
                # concatenated by global position
                parts = []
                for r in live:
                    order = svcs[r].global_sequence()
                    ids = [int(order[p]) for p in sl[r]]
                    vals = svcs[r].fetch_batch(ids)
                    parts.extend(zip(sl[r], vals))
                parts.sort()
                w = _order_sensitive_step(w, [v for _, v in parts])
                for r in live:
                    svcs[r].advance(self.B)
                if k % 2 == 1:
                    ck.save(k, {"w": w})
                    for r in live:
                        svcs[r].commit(k)
                if kill_at is not None and k == kill_at:
                    live = [0]
                    restored, s0 = ck.restore()
                    w = np.float32(restored["w"])
                    svcs[0].resize(live)
                    svcs[0].seek(s0)
                    k = s0 + 1
                    kill_at = None
                    continue
                k += 1
            return w
        finally:
            fp.reset()

    def test_chaos_run_bitwise_equals_fault_free(self, tmp_path):
        rec, idx = self._make_rec(tmp_path)
        # decode-pool leg of the chaos: stream one rank's epoch through
        # DecodePool under the fault schedule PLUS one abrupt
        # SystemExit (the thread-world decode-worker SIGKILL)
        killed = []

        def decode(payload):
            val = struct.unpack("<I", payload)[0]
            if not killed and val == 5 * 11 + 3:
                killed.append(val)
                raise SystemExit("decode worker SIGKILLed")
            return val

        svc = ShardService(self.N, shard_size=self.B, seed=self.SEED,
                           reader=RecordIORangeReader(
                               rec, index=idx,
                               retry_policy=RetryPolicy(base=0.001)),
                           decode_fn=decode)
        fp.configure({"io.worker.decode": "raise:ValueError@p=0.15",
                      "io.shard.read": "raise:OSError@p=0.15"},
                     seed=13)
        pooled = [v for _, vals in svc.iter_batches(self.B, workers=2)
                  for v in vals]
        fp.reset()
        order = epoch_order(self.N, 0, self.SEED)
        assert pooled == [int(order[p]) * 11 + 3
                          for p in range(self.N)]

        w_clean = self._run(tmp_path, rec, idx, "clean", chaos=False)
        w_chaos = self._run(tmp_path, rec, idx, "chaos", chaos=True)
        assert w_chaos.tobytes() == w_clean.tobytes()
        m = profiler.metrics()
        # full accounting: faults were really injected and the io
        # section carries the whole story
        assert m["io"].get("read_retries", 0) > 0
        assert m["io"].get("service_resizes", 0) >= 1
        assert m["io"].get("cursor_restores", 0) >= 1
        assert sum(v for k_, v in m["io"].items()
                   if k_.startswith("worker_deaths.")) >= 1


# -- elastic_train_loop composition ------------------------------------------

class TestElasticLoopComposition:
    def test_data_service_commits_and_seeks_with_the_loop(
            self, tmp_path):
        """The weld: the loop commits the cursor beside every
        checkpoint, and an injected step failure restores BOTH params
        and cursor to the same step — the resumed run is bitwise-equal
        to a fault-free one."""
        n, B = 48, 8

        def build(tag):
            svc = ShardService(n, shard_size=B, seed=3,
                               cursor_dir=str(tmp_path / ("c" + tag)))
            ck = CheckpointManager(str(tmp_path / ("k" + tag)),
                                   use_orbax=False)
            return svc, ck

        def make_step(svc, fail_at=None):
            state = {"calls": 0}

            def step(s, k):
                if fail_at is not None and k == fail_at \
                        and state["calls"] == 0:
                    state["calls"] = 1
                    raise ConnectionError("transient collective")
                order = svc.global_sequence()
                ids = [int(order[p])
                       for p in range(svc.offset, svc.offset + B)]
                w = _order_sensitive_step(np.float32(s["w"]), ids)
                svc.advance(B)
                return {"w": w}, None

            return step

        svc_a, ck_a = build("a")
        state_a, _, done_a = elastic_train_loop(
            make_step(svc_a), {"w": np.float32(1.0)}, list(range(6)),
            ck_a, save_every=2, data_service=svc_a)
        svc_b, ck_b = build("b")
        state_b, _, done_b = elastic_train_loop(
            make_step(svc_b, fail_at=5), {"w": np.float32(1.0)},
            list(range(6)), ck_b, save_every=2, data_service=svc_b)
        assert done_a and done_b
        assert np.float32(state_b["w"]).tobytes() == \
            np.float32(state_a["w"]).tobytes()
        # the cursor really committed through the loop's saves
        assert profiler.metrics()["io"]["cursor_commits"] >= 2
        assert profiler.metrics()["io"]["cursor_restores"] >= 1
        # ATOMIC pairing (review fix): params and cursor ride ONE
        # checkpoint payload — no crash instant can tear the pair the
        # way two separate stores' back-to-back renames could
        newest = ck_a.latest_step()
        payload, _ = ck_a.restore(newest)
        assert set(payload) == {"__elastic_state__", "__data_cursor__"}
        assert int(payload["__data_cursor__"]["offset"]) == \
            (newest + 1) * B  # the cursor AT that step, not an older one


# -- provider wiring ----------------------------------------------------------

class TestIoProvider:
    def test_metrics_io_section_exists_and_resets(self):
        io_stats.bump("probe_counter", 3)
        io_stats.set_gauge("probe_gauge", 9)
        m = profiler.metrics()
        assert m["io"]["probe_counter"] == 3
        assert m["io"]["probe_gauge"] == 9
        m = profiler.metrics(reset=True)
        assert profiler.metrics()["io"].get("probe_counter", 0) == 0

    def test_counters_mirror_into_account_ledger(self):
        io_stats.bump("probe_counter", 2)
        assert profiler.metrics()["counters"]["io.probe_counter"] >= 2
