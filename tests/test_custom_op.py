"""Custom Python operators (mx.operator.CustomOp / CustomOpProp).

Modeled on the reference's canonical custom softmax example
(ref: python/mxnet/operator.py docs + tests/python/unittest/
test_operator.py test_custom_op, src/operator/custom/custom-inl.h)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


@mx.operator.register("scale2x")
class Scale2xProp(mx.operator.CustomOpProp):
    def __init__(self, factor=2.0):
        super().__init__(need_top_grad=True)
        self.factor = float(factor)

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        factor = self.factor

        class Scale(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0] * factor)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                self.assign(in_grad[0], req[0], out_grad[0] * factor)

        return Scale()


@mx.operator.register("mysoftmax")
class MySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return ([in_shape[0], [in_shape[0][0]]], [in_shape[0]], [])

    def create_operator(self, ctx, in_shapes, in_dtypes):
        class MySoftmax(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = in_data[0].asnumpy()
                y = np.exp(x - x.max(axis=1, keepdims=True))
                y /= y.sum(axis=1, keepdims=True)
                self.assign(out_data[0], req[0], nd.array(y))

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                lbl = in_data[1].asnumpy().astype(int)
                y = out_data[0].asnumpy().copy()
                y[np.arange(lbl.shape[0]), lbl] -= 1.0
                self.assign(in_grad[0], req[0], nd.array(y))
                self.assign(in_grad[1], req[1], nd.zeros(lbl.shape))

        return MySoftmax()


def test_custom_eager_forward():
    x = nd.array(np.array([[1.0, 2.0]], "float32"))
    y = nd.Custom(x, op_type="scale2x")
    np.testing.assert_allclose(y.asnumpy(), [[2.0, 4.0]])
    z = nd.Custom(x, op_type="scale2x", factor=3.0)
    np.testing.assert_allclose(z.asnumpy(), [[3.0, 6.0]])


def test_custom_eager_backward():
    x = nd.array(np.array([[1.0, 2.0]], "float32"))
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="scale2x") * 4.0
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [[8.0, 8.0]])


def test_custom_softmax_trains():
    rs = np.random.RandomState(0)
    x = nd.array(rs.rand(4, 3).astype("float32"))
    lbl = nd.array(np.array([0, 1, 2, 1], "float32"))
    x.attach_grad()
    with autograd.record():
        p = nd.Custom(x, lbl, op_type="mysoftmax")
    p.backward()
    pn = p.asnumpy()
    exp = pn.copy()
    exp[np.arange(4), [0, 1, 2, 1]] -= 1
    np.testing.assert_allclose(x.grad.asnumpy(), exp, rtol=1e-5)
    np.testing.assert_allclose(pn.sum(axis=1), 1.0, rtol=1e-5)


def test_custom_in_compiled_symbol_graph():
    """A Custom node inside a bound (jitted) graph runs as a
    jax.pure_callback island with working gradients."""
    data = mx.sym.var("data")
    h = mx.sym.Custom(data, op_type="scale2x", name="c1")
    out = h * h
    exe = out.bind(args={"data": nd.array(np.array([1.0, 3.0], "float32"))},
                   args_grad={"data": nd.zeros((2,))})
    r = exe.forward(is_train=True)
    np.testing.assert_allclose(r[0].asnumpy(), [4.0, 36.0])
    exe.backward()
    # d/dx (2x)^2 = 8x
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               [8.0, 24.0])
