"""TensorBoard bridge + async parameter server tests (VERDICT r1 #10).

Ref slots: python/mxnet/contrib/tensorboard.py LogMetricsCallback;
tests/nightly/dist_async_kvstore.py (async semantics — immediate apply,
no aggregation barrier)."""
import collections
import os
import struct
import threading

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib.tensorboard import (SummaryWriter,
                                           LogMetricsCallback, _masked_crc)


def _read_events(path):
    """Independent TFRecord+Event reader used to verify what the writer
    produced (length/crc framing, then a minimal proto scan)."""
    from mxnet_tpu.contrib.onnx.proto import _scan, _one, _many
    events = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        (ln,) = struct.unpack("<Q", data[pos:pos + 8])
        (lcrc,) = struct.unpack("<I", data[pos + 8:pos + 12])
        assert lcrc == _masked_crc(data[pos:pos + 8])
        payload = data[pos + 12:pos + 12 + ln]
        (pcrc,) = struct.unpack("<I",
                                data[pos + 12 + ln:pos + 16 + ln])
        assert pcrc == _masked_crc(payload)
        pos += 16 + ln
        f_ev = _scan(payload)
        ev = {"step": _one(f_ev, 2, 0)}
        summ = _one(f_ev, 5)
        if summ is not None:
            vals = {}
            for vb in _many(_scan(summ), 1):
                fv = _scan(vb)
                tag = _one(fv, 1, b"").decode()
                raw = fv.get(2)
                vals[tag] = raw[-1][1] if raw else None
            ev["values"] = vals
        events.append(ev)
    return events


class TestTensorBoard:
    def test_scalar_events_round_trip(self, tmp_path):
        w = SummaryWriter(str(tmp_path))
        w.add_scalar("loss", 1.5, global_step=1)
        w.add_scalar("loss", 0.75, global_step=2)
        w.add_scalar("acc", 0.9, global_step=2)
        w.close()
        files = os.listdir(str(tmp_path))
        assert len(files) == 1 and files[0].startswith("events.out.tfevents")
        evs = _read_events(os.path.join(str(tmp_path), files[0]))
        # first record is the brain.Event:2 version header
        scalars = [e for e in evs if "values" in e]
        assert abs(scalars[0]["values"]["loss"] - 1.5) < 1e-6
        assert scalars[1]["step"] == 2
        assert abs(scalars[2]["values"]["acc"] - 0.9) < 1e-6

    def test_speedometer_style_callback(self, tmp_path):
        """The reference wires LogMetricsCallback as a batch_end_callback
        next to Speedometer; same BatchEndParam protocol here."""
        metric = mx.metric.Accuracy()
        metric.update(mx.nd.array([0.0, 1.0]),
                      mx.nd.array(onp.array([[0.9, 0.1], [0.2, 0.8]],
                                            "float32")))
        cb = LogMetricsCallback(str(tmp_path), prefix="train")
        Param = collections.namedtuple(
            "BatchEndParam", ["epoch", "nbatch", "eval_metric", "locals"])
        cb(Param(epoch=0, nbatch=1, eval_metric=metric, locals=None))
        files = [f for f in os.listdir(str(tmp_path))]
        evs = _read_events(os.path.join(str(tmp_path), files[0]))
        scalars = [e for e in evs if "values" in e]
        assert "train-accuracy" in scalars[0]["values"]
        assert abs(scalars[0]["values"]["train-accuracy"] - 1.0) < 1e-6


class TestAsyncPS:
    def test_immediate_apply_no_barrier(self):
        """Async semantics: each push is applied at once — visible before
        any other worker contributes (sync would wait for NumWorkers
        pushes; ref kvstore_dist_server.h:349 vs :358)."""
        import mxnet_tpu.optimizer as opt
        from mxnet_tpu.kvstore_async import AsyncPSServer, AsyncPSClient
        srv = AsyncPSServer()
        c = AsyncPSClient("127.0.0.1", srv.port)
        try:
            c.set_optimizer(opt.create("sgd", learning_rate=1.0, wd=0.0))
            c.init("w", onp.zeros((2,), "float32"))
            c.push("w", -onp.ones((2,), "float32"))  # w += 1
            # visible immediately, no second worker needed
            onp.testing.assert_allclose(c.pull("w"), [1.0, 1.0])
            assert c.updates_applied() == 1
            c.push("w", -onp.ones((2,), "float32"))
            onp.testing.assert_allclose(c.pull("w"), [2.0, 2.0])
        finally:
            c.stop_server()
            srv.stop()

    def test_uninitialized_pull_is_clean_error(self):
        """Server errors come back as exceptions, not dead sockets."""
        from mxnet_tpu.kvstore_async import AsyncPSServer, AsyncPSClient
        srv = AsyncPSServer()
        c = AsyncPSClient("127.0.0.1", srv.port)
        try:
            with pytest.raises(RuntimeError, match="KeyError"):
                c.pull("never_initialized")
            # connection still alive for further use
            c.init("x", onp.ones((1,), "float32"))
            onp.testing.assert_allclose(c.pull("x"), [1.0])
        finally:
            c.stop_server()
            srv.stop()

    def test_async_differs_from_sync_with_optimizer(self):
        """With a server-side momentum optimizer, applying two grads
        one-at-a-time (async) != applying their sum once (sync) — the
        staleness convergence difference the reference documents."""
        import mxnet_tpu.optimizer as opt
        from mxnet_tpu.kvstore_async import AsyncPSServer, AsyncPSClient
        g1 = onp.full((4,), 1.0, "float32")
        g2 = onp.full((4,), 3.0, "float32")

        srv = AsyncPSServer()
        c = AsyncPSClient("127.0.0.1", srv.port)
        try:
            c.set_optimizer(opt.create("sgd", learning_rate=0.1,
                                       momentum=0.9))
            c.init(0, onp.zeros((4,), "float32"))
            c.push(0, g1)
            c.push(0, g2)
            w_async = c.pull(0)
        finally:
            c.stop_server()
            srv.stop()

        # sync: one aggregated application
        kv = mx.kv.create("local")
        kv.set_optimizer(opt.create("sgd", learning_rate=0.1,
                                    momentum=0.9))
        kv.init(0, mx.nd.zeros((4,)))
        kv.push(0, [mx.nd.array(g1), mx.nd.array(g2)])
        out = mx.nd.zeros((4,))
        kv.pull(0, out=out)
        w_sync = out.asnumpy()

        assert not onp.allclose(w_async, w_sync), (w_async, w_sync)

    def test_async_training_converges(self):
        """Hogwild-style: two threads pushing grads with no coordination
        still converge on a quadratic (the reason async PS exists)."""
        import mxnet_tpu.optimizer as opt
        from mxnet_tpu.kvstore_async import AsyncPSServer, AsyncPSClient
        target = onp.array([1.0, -2.0, 0.5, 3.0], "float32")
        srv = AsyncPSServer()
        try:
            main = AsyncPSClient("127.0.0.1", srv.port)
            main.set_optimizer(opt.create("sgd", learning_rate=0.2))
            main.init("w", onp.zeros((4,), "float32"))

            def worker():
                cli = AsyncPSClient("127.0.0.1", srv.port)
                for _ in range(40):
                    w = cli.pull("w")
                    cli.push("w", w - target)  # d/dw 0.5||w - t||^2
            ts = [threading.Thread(target=worker) for _ in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            w = main.pull("w")
            assert float(onp.abs(w - target).max()) < 1e-2, w
            assert main.updates_applied() == 80
        finally:
            srv.stop()

    def test_dist_async_multiprocess(self):
        """3 processes under the launcher; rank 0 hosts the server
        thread (ref: tests/nightly/dist_async_kvstore.py)."""
        import subprocess
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo
        res = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "launch.py"),
             "-n", "3", sys.executable,
             os.path.join(repo, "tests", "dist_async_kvstore_worker.py")],
            env=env, capture_output=True, text=True, timeout=240)
        assert res.returncode == 0, res.stdout + res.stderr
        for rank in range(3):
            assert "rank %d/3: dist_async checks passed" % rank \
                in res.stdout + res.stderr

    def test_kv_create_dist_async_single_process(self):
        kv = mx.kv.create("dist_async")
        try:
            assert kv.type == "dist_async"
            kv.init("a", mx.nd.zeros((3,)))
            kv.push("a", mx.nd.ones((3,)))
            out = mx.nd.zeros((3,))
            kv.pull("a", out=out)
            onp.testing.assert_allclose(out.asnumpy(), 1.0)
        finally:
            kv.close()
