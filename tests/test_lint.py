"""mxlint self-enforcement (tools/mxlint; docs/LINTING.md).

Two halves:

* the tier-1 gate: mxlint over the whole tree must report ZERO
  unwaived findings — the PR 1-2 invariants (single dispatch choke
  point, guarded telemetry, locked shared state, API_BEGIN/API_END on
  the C ABI, monotonic trace clocks) stay true by construction, and
* unit coverage of each rule and of the waiver/baseline machinery on
  synthetic inputs, so a rule regression can't silently turn the gate
  into a no-op.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools import mxlint
from tools.mxlint import core, rules

REPO = core.REPO_ROOT


# -- the gate ----------------------------------------------------------------

def test_tree_is_lint_clean():
    """`python -m tools.mxlint mxnet_tpu src tests` — zero unwaived
    violations. If this fails: fix the finding, or waive it with an
    inline justification (docs/LINTING.md)."""
    findings, n_waived, n_baselined, bad = mxlint.run(
        ["mxnet_tpu", "src", "tests"])
    assert bad == [], "waivers without justification:\n%s" % "\n".join(
        map(repr, bad))
    assert findings == [], "unwaived mxlint findings:\n%s" % "\n".join(
        map(repr, findings))
    # the gate must actually be exercising the rules, not skipping files
    assert n_waived > 0


def test_cli_exits_zero_on_tree():
    r = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "mxnet_tpu", "src",
         "tests"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr


def test_baseline_is_empty():
    """The checked-in baseline must stay empty: new findings are fixed
    or waived with a reason, never silently baselined."""
    assert core.load_baseline() == []


# -- rule units on synthetic files -------------------------------------------

def _lint_snippet(tmp_path, relpath, src, rule_codes=None):
    """Run mxlint on one synthetic file planted at a scoped repo-relative
    path under tmp_path."""
    target = tmp_path / relpath
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent(src))
    prev = core.REPO_ROOT
    core.REPO_ROOT = str(tmp_path)
    try:
        sel = None
        if rule_codes:
            sel = [r for r in rules.ALL_RULES if r.code in rule_codes]
        return mxlint.run([str(target)], rules=sel, baseline=[])
    finally:
        core.REPO_ROOT = prev


def test_mx001_flags_jnp_and_exempts_asarray(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/ndarray/contrib.py", """\
        import jax.numpy as jnp

        def f(x):
            y = jnp.asarray(x)      # conversion: exempt
            return jnp.tanh(y)      # compute: flagged
        """, {"MX001"})
    assert [f.code for f in findings] == ["MX001"]
    assert "tanh" in findings[0].message


def test_mx002_unguarded_vs_guarded(tmp_path):
    findings, n_waived, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/io/thing.py", """\
        from .. import profiler as _profiler

        def bad():
            _profiler.record_op("x", 1.0)

        def good_inline():
            if _profiler._ACTIVE:
                _profiler.record_op("x", 1.0)

        def good_derived(t0):
            if t0 is not None:
                _profiler.account("bytes", 4)
        """, {"MX002"})
    assert len(findings) == 1
    assert findings[0].line == 4


def test_mx003_mutation_lock_and_definition_waiver(tmp_path):
    findings, n_waived, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/sub/mod.py", """\
        import threading

        _LOCK = threading.Lock()
        _GUARDED = {}
        _NAKED = {}
        _DECLARED = {}  # mxlint: disable=MX003 (import-time only)
        _TLS = threading.local()

        def f(k, v):
            with _LOCK:
                _GUARDED[k] = v
            _NAKED[k] = v
            _DECLARED[k] = v
        """, {"MX003"})
    assert len(findings) == 1
    assert "_NAKED" in findings[0].message
    assert n_waived == 1  # _DECLARED via its definition-line waiver


def test_mx004_buf_outside_ndarray(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/helper.py", """\
        def peek(arr):
            return arr._buf
        """, {"MX004"})
    assert [f.code for f in findings] == ["MX004"]


def test_mx005_jit_call_and_decorator(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/newmod.py", """\
        import jax

        fast = jax.jit(lambda x: x)

        @jax.jit
        def g(x):
            return x
        """, {"MX005"})
    assert [f.code for f in findings] == ["MX005", "MX005"]


def test_mx005_call_form_decorator_reported_once(tmp_path):
    """@jax.jit(...) is both a decorator and a Call node — one site,
    one finding."""
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/decmod.py", """\
        import jax

        @jax.jit(static_argnums=(0,))
        def g(n, x):
            return x
        """, {"MX005"})
    assert len(findings) == 1


def test_mx005_sanctioned_module_is_exempt(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/jit.py", """\
        import jax
        fast = jax.jit(lambda x: x)
        """, {"MX005"})
    assert findings == []


def test_mx005_fused_step_module_is_sanctioned(tmp_path):
    """The fused-train-step program cache (ISSUE 4) is a sanctioned jit
    site: its keys are the signature-keyed compile-on-repeat cache on
    each FusedTrainStep, bounded like the dispatch cache."""
    assert "mxnet_tpu/gluon/fused_step.py" in rules._SANCTIONED_JIT
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/gluon/fused_step.py", """\
        import jax
        prog = jax.jit(lambda x: x)
        """, {"MX005"})
    assert findings == []


def test_mx006_missing_and_present_macros(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "src/c_api_extra.cc", """\
        int MXTGood(void** out) {
          API_BEGIN()
          *out = nullptr;
          API_END()
        }

        int MXTBad(void** out) {
          *out = nullptr;
          return 0;
        }
        """, {"MX006"})
    assert len(findings) == 1
    assert "MXTBad" in findings[0].message


def test_mx007_wall_clock(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/io/meter.py", """\
        import time

        def stamp():
            return time.time()
        """, {"MX007"})
    assert [f.code for f in findings] == ["MX007"]


def test_mx008_bare_except(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/engine.py", """\
        def f():
            try:
                return 1
            except:
                return 2
        """, {"MX008"})
    assert [f.code for f in findings] == ["MX008"]


def test_mx009_flags_swallowed_broad_except(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/io/pipe.py", """\
        def f():
            try:
                return 1
            except Exception:
                return 2
        """, {"MX009"})
    assert [f.code for f in findings] == ["MX009"]


def test_mx009_accepts_reraise_and_accounting(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/kvstore_async.py", """\
        from . import profiler as _profiler

        def f():
            try:
                return 1
            except Exception:
                raise
        def g():
            try:
                return 1
            except BaseException:
                if _profiler._ACTIVE:
                    _profiler.account("kvstore.server_errors", 1)
                return 2
        def narrow():
            try:
                return 1
            except (ConnectionError, OSError):
                return 2  # narrow catches are out of scope
        """, {"MX009"})
    assert findings == []


def test_mx010_flags_unguarded_latency_telemetry(tmp_path):
    """record_latency/record_flow in kvstore_async and the fused step
    must sit behind the inlined active guard (ISSUE 6 satellite)."""
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/gluon/fused_step.py", """\
        from .. import profiler as _profiler

        def bad(dur):
            _profiler.record_latency("fused_step.step", dur)

        def bad_flow(fid):
            _profiler.record_flow("ps.push", fid, "s")
        """, {"MX010"})
    assert [f.code for f in findings] == ["MX010", "MX010"]
    assert "record_latency" in findings[0].message


def test_mx010_accepts_inlined_and_derived_guards(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/kvstore_async.py", """\
        from . import profiler as _profiler

        def good_inline(dur):
            if _profiler._ACTIVE:
                _profiler.record_latency("kvstore.pull_rtt", dur)

        def good_derived(t0):
            if t0 is not None:
                _profiler.record_flow("ps.pull", 7, "f")
        """, {"MX010"})
    assert findings == []


def test_mx010_out_of_scope_module_is_exempt(tmp_path):
    """The rule targets the hot request/step paths; cold modules (e.g.
    a tool) may call the primitives unguarded."""
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/callback.py", """\
        from . import profiler as _profiler

        def f(dur):
            _profiler.record_latency("cb", dur)
        """, {"MX010"})
    assert findings == []


def test_mx011_flags_second_hot_path_branch(tmp_path):
    """Flight-recorder records in hot modules must sit under the ONE
    shared guard — a standalone `if _flightrec.ENABLED:` branch (or no
    guard at all) is a second hot-path cost the flightrec_overhead
    budget does not price. Covers both the helper recorders and the
    raw inlined RING.append form."""
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/ndarray/thing.py", """\
        from .._debug import flightrec as _flightrec

        def bad_own_branch(name):
            if _flightrec.ENABLED:
                _flightrec.RING.append(name)

        def bad_unguarded(name, dur):
            _flightrec.record_span(name, dur)

        def bad_marker(name):
            _flightrec.record_marker(name)
        """, {"MX011"})
    assert [f.code for f in findings] == ["MX011"] * 3
    assert sorted(f.line for f in findings) == [5, 8, 11]


def test_mx011_accepts_shared_and_derived_guards(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/ndarray/thing.py", """\
        from .. import profiler as _profiler
        from .._debug import flightrec as _flightrec

        def good_shared(name, t0):
            if _profiler._HOOKS and _profiler._LIVE:
                _flightrec.RING.append(name)

        def good_derived(name, _prof_t0):
            if _prof_t0 is not None:
                _flightrec.RING.append(name)

        def good_helper(name, dur, t0):
            if t0 is not None:
                _flightrec.record_span(name, dur)
        """, {"MX011"})
    assert findings == []


def test_mx011_out_of_scope_module_is_exempt(tmp_path):
    """Cold modules (the dump path itself, tools) may record freely —
    only the hot dispatch/step modules carry the one-guard contract."""
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/callback.py", """\
        from .._debug import flightrec as _flightrec

        def f(name):
            _flightrec.record_marker(name)
        """, {"MX011"})
    assert findings == []


def test_mx012_flags_contractless_kernel_module(tmp_path):
    """A pallas_kernels module without a reference implementation, an
    interpret= path, or a KERNEL_BENCH registration breaks the kernel
    contract threefold."""
    (tmp_path / "mxnet_tpu" / "pallas_kernels").mkdir(parents=True)
    (tmp_path / "mxnet_tpu" / "pallas_kernels" / "__init__.py") \
        .write_text("KERNEL_BENCH = {'other': 'resnet50'}\n")
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/pallas_kernels/shiny.py", """\
        import jax.numpy as jnp

        def shiny_kernel(x):
            return x * 2
        """, {"MX012"})
    assert [f.code for f in findings] == ["MX012"] * 3
    msgs = " ".join(f.message for f in findings)
    assert "reference" in msgs and "interpret" in msgs \
        and "KERNEL_BENCH" in msgs


def test_mx012_accepts_contract_compliant_module(tmp_path):
    (tmp_path / "mxnet_tpu" / "pallas_kernels").mkdir(parents=True)
    (tmp_path / "mxnet_tpu" / "pallas_kernels" / "__init__.py") \
        .write_text("KERNEL_BENCH = {'shiny': 'fused_kernels'}\n")
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/pallas_kernels/shiny.py", """\
        import jax.numpy as jnp

        def shiny_reference(x):
            return x * 2

        def shiny(x, interpret=False):
            return shiny_reference(x)
        """, {"MX012"})
    assert findings == []


def test_mx012_private_helpers_and_init_are_exempt(tmp_path):
    """_compile_attr.py-style private helpers and the package __init__
    are not kernel modules."""
    for rel in ("mxnet_tpu/pallas_kernels/_helper.py",
                "mxnet_tpu/pallas_kernels/__init__.py"):
        findings, _, _, _ = _lint_snippet(
            tmp_path, rel, "X = 1\n", {"MX012"})
        assert findings == [], rel


def test_mx012_real_tree_kernels_registered():
    """Every shipped kernel module appears in KERNEL_BENCH, and the
    campaign kernels map to the fused_kernels gate."""
    from mxnet_tpu import pallas_kernels as pk
    for mod in ("batchnorm_fused", "optimizer_apply",
                "quantized_matmul"):
        assert pk.KERNEL_BENCH[mod] == "fused_kernels"
    for mod in ("flash_attention", "compression", "conv_fused"):
        assert mod in pk.KERNEL_BENCH


def _plant_catalog(tmp_path, points):
    d = tmp_path / "mxnet_tpu" / "_debug"
    d.mkdir(parents=True, exist_ok=True)
    (d / "faultpoint.py").write_text(
        "POINTS = frozenset((%s,))\n"
        % ", ".join("%r" % p for p in points))


def test_mx013_flags_uncataloged_literal(tmp_path):
    _plant_catalog(tmp_path, ["io.known.point"])
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/io/newthing.py", """\
        from .._debug import faultpoint as _faultpoint

        def f(point):
            _faultpoint.check("io.known.point")    # cataloged: ok
            _faultpoint.check("io.typo.point")     # flagged
            _faultpoint.check(point)               # computed: exempt
        """, {"MX013"})
    assert [f.code for f in findings] == ["MX013"]
    assert "io.typo.point" in findings[0].message
    assert findings[0].line == 5


def test_mx013_import_alias_forms(tmp_path):
    """Both import spellings bind the alias the rule tracks."""
    _plant_catalog(tmp_path, ["a.b"])
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/x.py", """\
        import mxnet_tpu._debug.faultpoint as fp

        def f():
            fp.check("a.b")
            fp.check("a.nope")
        """, {"MX013"})
    assert [f.code for f in findings] == ["MX013"]


def test_mx013_scope_excludes_tests():
    rule = next(r for r in rules.ALL_RULES if r.code == "MX013")
    assert rule.scope("mxnet_tpu/io/shard_service.py")
    assert rule.scope("bench.py")
    assert not rule.scope("tests/test_faultpoints.py")
    assert not rule.scope("docs/DATA.md")


def test_mx013_real_catalog_includes_io_points():
    """The rule reads the REAL catalog: the ISSUE 11 io seams are in
    it, so the clean-tree gate genuinely checks the new check() sites."""
    rule = next(r for r in rules.ALL_RULES if r.code == "MX013")
    catalog = rule._catalog()
    for p in ("io.shard.read", "io.record.corrupt",
              "io.worker.decode", "io.service.fetch",
              "kvstore.send", "checkpoint.save"):
        assert p in catalog, p


def test_mx013_covers_health_points(tmp_path):
    """ISSUE 15: the health chaos seam is cataloged (the real
    healthmon.corruption_operand site lints clean) and a typo'd
    `health.*` literal in an instrumented module is flagged."""
    rule = next(r for r in rules.ALL_RULES if r.code == "MX013")
    assert "health.grad.corrupt" in rule._catalog()
    _plant_catalog(tmp_path, ["health.grad.corrupt"])
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/_debug/newhealth.py", """\
        from . import faultpoint as _faultpoint

        def probe():
            _faultpoint.check("health.grad.corrupt")   # cataloged: ok
            _faultpoint.check("health.grad.corrupted")  # flagged
        """, {"MX013"})
    assert [f.code for f in findings] == ["MX013"]
    assert "health.grad.corrupted" in findings[0].message


def test_mx020_flags_direct_sharding_imports(tmp_path):
    """Every import form that bypasses the compat seam is caught: the
    from-import of the module path, the member pull off ``jax``/
    ``jax.experimental``, and the plain ``import jax.sharding``."""
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/parallel/newplan.py", """\
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from jax.experimental import shard_map as smap
        from jax import sharding
        import jax.sharding

        def f():
            return P, shard_map, smap, sharding
        """, {"MX020"})
    assert [f.code for f in findings] == ["MX020"] * 5
    assert "compat" in findings[0].message


def test_mx020_compat_itself_and_routed_imports_pass(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/parallel/compat.py", """\
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        from jax.experimental.shard_map import shard_map
        """, {"MX020"})
    assert findings == []
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/parallel/user.py", """\
        import jax
        from .compat import PartitionSpec as P
        from ..parallel.compat import shard_map

        def f(x):
            return jax.jit(lambda y: y)(x)  # mxlint: disable=MX005 (t)
        """, {"MX020"})
    assert findings == []


def test_mx020_scope_is_the_package_not_tests():
    rule = next(r for r in rules.ALL_RULES if r.code == "MX020")
    assert rule.scope("mxnet_tpu/parallel/mesh.py")
    assert rule.scope("mxnet_tpu/gluon/fused_step.py")
    assert not rule.scope("mxnet_tpu/parallel/compat.py")
    assert not rule.scope("tests/test_gspmd_step.py")
    assert not rule.scope("bench.py")


# -- waiver machinery --------------------------------------------------------

def test_waiver_without_reason_is_flagged(tmp_path):
    findings, _, _, bad = _lint_snippet(
        tmp_path, "mxnet_tpu/w.py", """\
        import jax
        fast = jax.jit(lambda x: x)  # mxlint: disable=MX005
        """, {"MX005"})
    assert findings == []  # the waiver still suppresses
    assert len(bad) == 1
    assert bad[0].code == "MX000"


def test_waiver_on_line_above(tmp_path):
    findings, n_waived, _, bad = _lint_snippet(
        tmp_path, "mxnet_tpu/w2.py", """\
        import jax
        # mxlint: disable=MX005 (bounded: single key)
        fast = jax.jit(lambda x: x)
        """, {"MX005"})
    assert findings == [] and bad == [] and n_waived == 1


def test_file_level_waiver(tmp_path):
    findings, n_waived, _, bad = _lint_snippet(
        tmp_path, "mxnet_tpu/ndarray/extra.py", """\
        # mxlint: disable-file=MX001 (whole-file design exemption for test)
        import jax.numpy as jnp

        def a(x):
            return jnp.tanh(x)

        def b(x):
            return jnp.exp(x)
        """, {"MX001"})
    assert findings == [] and bad == [] and n_waived == 2


# -- MX014: traced-ambient-state capture -------------------------------------

_MINI_REGISTRY = """\
def register(name, **kw):
    def _reg(fn):
        return fn
    return _reg
"""


def _plant(tmp_path, rel, src):
    target = tmp_path / rel
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent(src))
    return target


def _lint_tree(tmp_path, rule_codes, roots=("mxnet_tpu",)):
    """Run mxlint over a planted synthetic tree (multi-file: the
    dataflow rules need the whole project model)."""
    prev = core.REPO_ROOT
    core.REPO_ROOT = str(tmp_path)
    try:
        sel = [r for r in rules.ALL_RULES if r.code in rule_codes]
        return mxlint.run([str(tmp_path / r) for r in roots],
                          rules=sel, baseline=[])
    finally:
        core.REPO_ROOT = prev


def test_mx014_flags_unregistered_env_read_in_op_body(tmp_path):
    """The PR 9 `_kernel_env_token` bug class as a fixture: an op body
    (trace entry) reads an env var that is NOT in the signature-token
    registry — the compiled path would silently replay the stale value.
    The registered var and the read in plain host code stay clean."""
    _plant(tmp_path, "mxnet_tpu/ops/registry.py", _MINI_REGISTRY)
    _plant(tmp_path, "mxnet_tpu/ndarray/register.py", """\
        def register_signature_token(name, default=""):
            return name

        register_signature_token("MXTPU_GOOD_TOKEN", "1")
        """)
    _plant(tmp_path, "mxnet_tpu/ops/myops.py", """\
        import os

        from ..ops.registry import register

        @register("shiny_op")
        def shiny_op(x):
            if os.environ.get("MXTPU_SHINY_MODE") == "1":   # flagged
                return x * 2
            if os.environ.get("MXTPU_GOOD_TOKEN") == "1":   # registered
                return x * 3
            return x

        def host_only():
            return os.environ.get("MXTPU_SHINY_MODE")       # not traced
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX014"})
    assert [f.code for f in findings] == ["MX014"]
    assert "MXTPU_SHINY_MODE" in findings[0].message
    assert findings[0].path == "mxnet_tpu/ops/myops.py"
    assert findings[0].line == 7


def test_mx014_follows_the_call_graph(tmp_path):
    """The read sits two calls deep behind the entry — per-line rules
    cannot see it; the project-model reachability does."""
    _plant(tmp_path, "mxnet_tpu/ops/registry.py", _MINI_REGISTRY)
    _plant(tmp_path, "mxnet_tpu/ops/helpers.py", """\
        import os

        def leaf_config():
            return os.environ.get("MXTPU_DEEP_KNOB", "0")

        def middle(x):
            return leaf_config()
        """)
    _plant(tmp_path, "mxnet_tpu/ops/myops.py", """\
        from ..ops.registry import register
        from .helpers import middle

        @register("deep_op")
        def deep_op(x):
            return middle(x)
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX014"})
    assert [f.code for f in findings] == ["MX014"]
    assert findings[0].path == "mxnet_tpu/ops/helpers.py"
    assert "MXTPU_DEEP_KNOB" in findings[0].message


def test_mx014_flags_clock_rng_and_env_globals(tmp_path):
    _plant(tmp_path, "mxnet_tpu/ops/registry.py", _MINI_REGISTRY)
    _plant(tmp_path, "mxnet_tpu/ops/myops.py", """\
        import os
        import random
        import time

        from ..ops.registry import register

        _MODE = os.environ.get("MXTPU_AMBIENT_MODE", "fast")

        @register("leaky_op")
        def leaky_op(x):
            t = time.perf_counter()         # clock: flagged
            r = random.random()             # host RNG: flagged
            if _MODE == "fast":             # env-derived global: flagged
                return x + t + r
            return x
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX014"})
    assert len(findings) == 3
    msgs = " ".join(f.message for f in findings)
    assert "clock" in msgs and "RNG" in msgs \
        and "MXTPU_AMBIENT_MODE" in msgs


def test_mx014_cross_module_env_global(tmp_path):
    """A traced op body reading ANOTHER module's env-derived global
    (`cfg.FLAG`) is the same stale-replay hazard as a same-module read
    (review regression: dotted attribute refs must resolve)."""
    _plant(tmp_path, "mxnet_tpu/ops/registry.py", _MINI_REGISTRY)
    _plant(tmp_path, "mxnet_tpu/cfg.py", """\
        import os

        FLAG = os.environ.get("MXTPU_CROSS_FLAG", "0")
        """)
    _plant(tmp_path, "mxnet_tpu/ops/myops.py", """\
        from ..ops.registry import register
        from .. import cfg

        @register("crossy_op")
        def crossy_op(x):
            if cfg.FLAG == "1":
                return x * 2
            return x
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX014"})
    assert [f.code for f in findings] == ["MX014"]
    assert "MXTPU_CROSS_FLAG" in findings[0].message
    assert findings[0].path == "mxnet_tpu/ops/myops.py"


def test_mx014_step_fn_and_waiver(tmp_path):
    """Optimizer step_fns are entries; the waiver idiom applies."""
    findings, n_waived, _, _ = _lint_tree(tmp_path, {"MX014"})
    assert findings == []  # empty tree
    _plant(tmp_path, "mxnet_tpu/optimizer/opt.py", """\
        import os

        class Shiny:
            def step_fn(self, w, g, state, lr, wd, rescale):
                # mxlint: disable=MX014 (test waiver: pretend operand)
                knob = os.environ.get("MXTPU_STEP_KNOB", "0")
                return w - lr * g * float(knob)
        """)
    findings, n_waived, _, _ = _lint_tree(tmp_path, {"MX014"})
    assert findings == [] and n_waived == 1


def test_mx014_real_tree_tokens_registered():
    """The real registry carries the kernel-routing tokens AND the
    bucket-plan cap MX014 found on its first whole-tree run; both
    cache-key builders consume the same tuple."""
    from mxnet_tpu.ndarray import register as r
    names = r.signature_token_names()
    for tok in ("MXTPU_NO_PALLAS", "MXTPU_FUSED_BN",
                "MXTPU_QUANT_MATMUL", "MXTPU_FUSED_APPLY",
                "MXTPU_ELASTIC_BUCKET_MB"):
        assert tok in names, tok
    assert len(r.signature_tokens()) == len(names)


def test_signature_tokens_change_dispatch_key(monkeypatch):
    """Flipping a registered token must change the dispatch partial key
    (the runtime contract MX014 enforces statically)."""
    from mxnet_tpu.ndarray import register as r
    before = r.signature_tokens()
    monkeypatch.setenv("MXTPU_ELASTIC_BUCKET_MB", "17")
    after = r.signature_tokens()
    assert before != after


# -- MX015: env contract sync ------------------------------------------------

_DOCS = """\
# Environment variables

| Variable | Default | Meaning |
|---|---|---|
| `MXTPU_DOCUMENTED` | `1` | a documented knob |
| `MXTPU_PORT_FAMILY` | derived | a documented computed-name family |
"""


def test_mx015_direct_environ_and_undocumented(tmp_path):
    _plant(tmp_path, "docs/ENV_VARS.md", _DOCS)
    _plant(tmp_path, "mxnet_tpu/thing.py", """\
        import os

        from .base import getenv as _getenv

        def bad_direct():
            return os.environ.get("MXTPU_DOCUMENTED")    # choke point

        def bad_direct_getenv():
            return os.getenv("MXTPU_DOCUMENTED")         # choke point

        def bad_undocumented():
            return _getenv("MXTPU_MYSTERY_KNOB", "0")    # not in docs

        def good():
            return _getenv("MXTPU_DOCUMENTED", "1")

        def writes_are_fine(v):
            os.environ["MXTPU_DOCUMENTED"] = v
        """)
    _plant(tmp_path, "mxnet_tpu/base.py",
           "def getenv(name, default=None):\n    return None\n")
    findings, _, _, _ = _lint_tree(tmp_path, {"MX015"})
    assert [f.code for f in findings] == ["MX015"] * 3
    msgs = " ".join(f.message for f in findings)
    assert "choke point" in msgs and "MXTPU_MYSTERY_KNOB" in msgs


def test_mx015_dynamic_family_forms(tmp_path):
    _plant(tmp_path, "docs/ENV_VARS.md", _DOCS)
    _plant(tmp_path, "mxnet_tpu/ports.py", """\
        from .base import getenv_dynamic as _getenv_dynamic

        def good(s):
            name = "MXTPU_PORT_FAMILY_%d" % s
            return _getenv_dynamic(name, 0, family="MXTPU_PORT_FAMILY")

        def bad_no_family(s):
            return _getenv_dynamic("MXTPU_PORT_FAMILY_%d" % s, 0)

        def bad_undoc_family(s):
            return _getenv_dynamic("X_%d" % s, 0, family="MXTPU_NOPE")
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX015"})
    assert [f.code for f in findings] == ["MX015", "MX015"]
    msgs = " ".join(f.message for f in findings)
    assert "family" in msgs and "MXTPU_NOPE" in msgs


def test_mx015_resolves_helper_params_through_callers(tmp_path):
    """The watchdog/flightrec idiom: a helper takes the env NAME as a
    parameter. The rule follows the dataflow one level: literals at
    call sites are doc-checked, computed names are flagged AT THE
    CALLER."""
    _plant(tmp_path, "docs/ENV_VARS.md", _DOCS)
    _plant(tmp_path, "mxnet_tpu/helper.py", """\
        from .base import getenv as _getenv

        def _env_float(name, default):
            return float(_getenv(name, "") or default)

        def good():
            return _env_float("MXTPU_DOCUMENTED", 1.0)

        def bad_literal():
            return _env_float("MXTPU_UNDOC_VIA_HELPER", 0.0)

        def bad_computed(suffix):
            return _env_float("MXTPU_" + suffix, 0.0)
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX015"})
    assert len(findings) == 2
    by_line = {f.line: f.message for f in findings}
    assert any("MXTPU_UNDOC_VIA_HELPER" in m for m in by_line.values())
    assert any("cannot resolve" in m or "computed env name" in m
               for m in by_line.values())


def test_mx015_real_tree_docs_cover_the_satellite_vars():
    """The env-doc drift the ISSUE names is fixed: the seven vars MX015
    found undocumented on its first run now have ENV_VARS.md rows."""
    with open(os.path.join(REPO, "docs", "ENV_VARS.md"),
              encoding="utf-8") as f:
        doc = f.read()
    for var in ("MXTPU_PS_SECRET", "MXTPU_PS_BARRIER_TIMEOUT",
                "MXTPU_PS_DONE_TIMEOUT", "MXTPU_ASYNC_PS_PORT",
                "MXTPU_NUM_SERVERS", "MXTPU_FLASH_AUTOTUNE",
                "MXNET_OPTIMIZER_AGGREGATION_SIZE"):
        assert "`%s`" % var in doc, var


def test_mx015_waiver_form(tmp_path):
    _plant(tmp_path, "docs/ENV_VARS.md", _DOCS)
    _plant(tmp_path, "mxnet_tpu/thing.py", """\
        import os

        def sanctioned():
            # mxlint: disable=MX015 (test: exempted direct read)
            return os.environ.get("MXTPU_DOCUMENTED")
        """)
    findings, n_waived, _, bad = _lint_tree(tmp_path, {"MX015"})
    assert findings == [] and bad == [] and n_waived == 1


# -- MX016: use-after-donation -----------------------------------------------

_MINI_OPS = """\
from .registry import register

@register("sgd_mom_update", num_inputs=3, inplace=(2,))
def sgd_mom_update(weight, grad, mom, lr=None):
    return weight, mom
"""


def test_mx016_jit_donate_use_after_donation(tmp_path):
    """The synthetic use-after-donate repro: a local jitted program
    donates its args; reading one afterwards is the TPU crash the CPU
    tier-1 suite cannot see."""
    _plant(tmp_path, "mxnet_tpu/repro.py", """\
        import jax

        def train_step(w, s, step):
            jfn = jax.jit(step, donate_argnums=(0, 1))
            new_w, new_s = jfn(w, s)
            stale = w + 1          # flagged: w was donated
            return new_w, new_s, stale

        def clean_step(w, s, step):
            jfn = jax.jit(step, donate_argnums=(0, 1))
            new_w, new_s = jfn(w, s)
            w = new_w              # rebind clears the binding
            return w + 1
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX016"})
    assert [f.code for f in findings] == ["MX016"]
    assert findings[0].line == 6
    assert "'w'" in findings[0].message


def test_mx016_registry_op_alias_donation(tmp_path):
    """Registry `*_update` ops donate their inplace positions. The
    wrapper re-adopts the state arg itself, so reading `mom` after is
    fine — but a PRE-call alias (`.copy()` shares the buffer, O(1))
    goes stale. `.asnumpy()` BEFORE the call is the sanctioned
    snapshot."""
    _plant(tmp_path, "mxnet_tpu/ops/registry.py", _MINI_REGISTRY)
    _plant(tmp_path, "mxnet_tpu/ops/optimizer_ops.py", _MINI_OPS)
    _plant(tmp_path, "mxnet_tpu/user.py", """\
        from . import nd

        def bad(weight, grad, mom):
            snap = mom.copy()                    # buffer share
            nd.sgd_mom_update(weight, grad, mom, lr=0.1)
            return snap                          # flagged: stale

        def good(weight, grad, mom):
            snap = mom.asnumpy()                 # real host snapshot
            nd.sgd_mom_update(weight, grad, mom, lr=0.1)
            return snap, mom                     # mom was re-adopted
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX016"})
    assert [f.code for f in findings] == ["MX016"]
    assert findings[0].line == 6
    assert "'snap'" in findings[0].message


def test_mx016_adopt_fused_clears(tmp_path):
    _plant(tmp_path, "mxnet_tpu/repro2.py", """\
        import jax

        def step(w, s, f, p):
            jfn = jax.jit(f, donate_argnums=(0,))
            new_w = jfn(w, s)
            p._adopt_fused(w)
            return w        # re-adopted: clean
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX016"})
    assert findings == []


def test_mx016_real_tree_is_clean_and_table_parsed():
    """On the real tree the rule runs against the real inplace table
    (sanity: the fused optimizer state ops are in it)."""
    rule = next(r for r in rules.ALL_RULES if r.code == "MX016")
    table = rule._table()
    assert table.get("sgd_mom_update") == (2,)
    assert table.get("adam_update") == (2, 3)


def test_mx016_tuple_unpack_rebind_and_augassign(tmp_path):
    """`w, s = jfn(w, s)` is the documented-clean rebind idiom (no
    finding); `w += 1` after a donation READS the stale buffer even
    though the AST target is Store ctx (review regressions)."""
    _plant(tmp_path, "mxnet_tpu/repro5.py", """\
        import jax

        def clean_tuple_rebind(w, s, f):
            jfn = jax.jit(f, donate_argnums=(0, 1))
            w, s = jfn(w, s)
            return w + s

        def bad_augassign(w, f):
            jfn = jax.jit(f, donate_argnums=(0,))
            out = jfn(w)
            w += 1
            return out
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX016"})
    assert [f.code for f in findings] == ["MX016"]
    assert findings[0].line == 11 and "'w'" in findings[0].message


def test_mx014_subscript_env_read_and_telemetry_globals(tmp_path):
    """os.environ["X"] subscript reads inside a traced function carry
    the name to MX014. The telemetry-module exemption (ISSUE 13: the
    ledger/detector hooks make the whole dump/metrics subsystem LOOK
    trace-reachable) covers all clauses for telemetry modules — their
    ambient state gates what gets recorded, never a traced value —
    while env-derived globals in COMPUTE modules stay checked (the PR 9
    bug class the rule exists for)."""
    _plant(tmp_path, "mxnet_tpu/ops/registry.py", _MINI_REGISTRY)
    _plant(tmp_path, "mxnet_tpu/_debug/telem.py", """\
        import os
        import time

        _MODE = os.environ.get("MXTPU_TELEM_MODE", "0")

        def helper():
            t = time.perf_counter()   # telemetry clock: exempt
            if _MODE == "1":          # telemetry-owned global: exempt
                return t
            return 0.0
        """)
    _plant(tmp_path, "mxnet_tpu/ops/myops.py", """\
        import os

        from ..ops.registry import register
        from .._debug.telem import helper

        _ROUTE = os.environ.get("MXTPU_COMPUTE_ROUTE", "0")

        @register("sub_op")
        def sub_op(x):
            helper()
            if _ROUTE == "1":         # compute-module global: flagged
                x = x + 1
            return x * int(os.environ["MXTPU_SUBSCRIPT_KNOB"])
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX014"})
    msgs = sorted(f.message for f in findings)
    assert len(findings) == 2, findings
    assert any("MXTPU_SUBSCRIPT_KNOB" in m for m in msgs)
    assert any("MXTPU_COMPUTE_ROUTE" in m for m in msgs)
    assert not any("MXTPU_TELEM_MODE" in m for m in msgs)
    assert not any("clock" in m for m in msgs)


def test_mx016_rhs_read_of_own_reassignment(tmp_path):
    """`w = w.copy()` after a donation READS the donated buffer on its
    own RHS — the rebind must not clear the poison before the read is
    seen (review regression)."""
    _plant(tmp_path, "mxnet_tpu/repro4.py", """\
        import jax

        def step(w, f):
            jfn = jax.jit(f, donate_argnums=(0,))
            out = jfn(w)
            w = w.copy()
            return out, w

        def rebind_to_result_is_clean(w, f):
            jfn = jax.jit(f, donate_argnums=(0,))
            w = jfn(w)
            return w + 1
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX016"})
    assert [f.code for f in findings] == ["MX016"]
    assert findings[0].line == 6 and "'w'" in findings[0].message


def test_mx016_waiver_form(tmp_path):
    _plant(tmp_path, "mxnet_tpu/repro3.py", """\
        import jax

        def step(w, s, f):
            jfn = jax.jit(f, donate_argnums=(0,))
            new_w = jfn(w, s)
            # mxlint: disable=MX016 (test: deliberate stale read)
            return w
        """)
    findings, n_waived, _, bad = _lint_tree(tmp_path, {"MX016"})
    assert findings == [] and bad == [] and n_waived == 1


# -- MX017: static lock-order graph ------------------------------------------

_CYCLIC_LOCKS = """\
from .._debug.locktrace import named_lock

_A = named_lock("fix.a")
_B = named_lock("fix.b")

def path_one():
    with _A:
        with _B:
            pass

def path_two():
    with _B:
        with _A:
            pass
"""


def test_mx017_flags_cyclic_two_lock_fixture(tmp_path):
    _plant(tmp_path, "mxnet_tpu/sub/locky.py", _CYCLIC_LOCKS)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX017"})
    assert [f.code for f in findings] == ["MX017"]
    assert "fix.a" in findings[0].message \
        and "fix.b" in findings[0].message


def test_mx017_consistent_order_and_self_attr_locks(tmp_path):
    _plant(tmp_path, "mxnet_tpu/sub/locky.py", """\
        from .._debug.locktrace import named_lock

        _A = named_lock("ok.outer")

        class Thing:
            def __init__(self):
                self._lock = named_lock("ok.inner")

            def work(self):
                with _A:
                    with self._lock:
                        pass

            def also(self):
                with _A:
                    with self._lock:
                        pass
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX017"})
    assert findings == []


def test_mx017_cycle_through_three_modules(tmp_path):
    """The graph is global: each module's nesting is locally consistent
    but the union cycles — only a whole-program pass can see it."""
    _plant(tmp_path, "mxnet_tpu/m1.py",
           "from ._debug.locktrace import named_lock\n"
           "A = named_lock('g.a')\nB = named_lock('g.b')\n"
           "def f():\n    with A:\n        with B:\n            pass\n")
    _plant(tmp_path, "mxnet_tpu/m2.py",
           "from ._debug.locktrace import named_lock\n"
           "B = named_lock('g.b')\nC = named_lock('g.c')\n"
           "def f():\n    with B:\n        with C:\n            pass\n")
    _plant(tmp_path, "mxnet_tpu/m3.py",
           "from ._debug.locktrace import named_lock\n"
           "C = named_lock('g.c')\nA = named_lock('g.a')\n"
           "def f():\n    with C:\n        with A:\n            pass\n")
    findings, _, _, _ = _lint_tree(tmp_path, {"MX017"})
    assert len(findings) == 1
    assert "g.a" in findings[0].message


def test_mx017_real_tree_has_no_lexical_nesting():
    """The framework tree deliberately holds at most one named lock per
    lexical scope (matching the runtime detector's zero inversions) —
    the static graph over the real tree has nodes but no edges."""
    model = core.build_model(["mxnet_tpu"])
    assert model.lock_nodes(lambda p: True)
    assert model.lock_graph(lambda p: True) == {}


def test_mx017_waiver_form(tmp_path):
    """A lock-cycle waiver sits on the finding's anchor site (the
    first edge of the cycle in path/line order)."""
    _plant(tmp_path, "mxnet_tpu/sub/locky.py", """\
        from .._debug.locktrace import named_lock

        _A = named_lock("wf.a")
        _B = named_lock("wf.b")

        def path_one():
            with _A:
                # mxlint: disable=MX017 (test: cycle acknowledged)
                with _B:
                    pass

        def path_two():
            with _B:
                with _A:
                    pass
        """)
    findings, n_waived, _, bad = _lint_tree(tmp_path, {"MX017"})
    assert findings == [] and bad == [] and n_waived == 1


# -- --lock-graph CLI + runtime diff -----------------------------------------

def _run_cli(args, cwd=REPO, repo_root=None):
    env = dict(os.environ)
    if repo_root is not None:
        env["MXLINT_REPO_ROOT"] = str(repo_root)
    else:
        env.pop("MXLINT_REPO_ROOT", None)
    return subprocess.run([sys.executable, "-m", "tools.mxlint"] + args,
                          cwd=cwd, capture_output=True, text=True,
                          env=env, timeout=300)


def test_lock_graph_cli_clean_tree():
    r = _run_cli(["--lock-graph"])
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(r.stdout)
    assert "profiler.events" in rep["locks"]
    assert rep["static_cycles"] == []


def test_lock_graph_diff_against_runtime_dump(tmp_path):
    """The PR 3 enforcement pair verifies itself: drive the REAL
    framework locks under the runtime detector (the test_locktrace
    suites' setup), dump locktrace.report(), and diff the static graph
    against it — zero cycles, zero ordering contradictions."""
    from mxnet_tpu import profiler
    from mxnet_tpu._debug import locktrace
    import mxnet_tpu as mx

    prev = locktrace.enable()
    locktrace.reset()
    try:
        profiler.set_config(filename=str(tmp_path / "t.json"))
        profiler.set_state("run")
        (mx.nd.array([1.0, 2.0]) * 2).asnumpy()
        profiler.set_state("stop")
        dump = locktrace.report()
        assert dump["acquisitions"] > 0
    finally:
        locktrace.reset()
        if not prev:
            locktrace.disable()
    dump_path = tmp_path / "locktrace.json"
    dump_path.write_text(json.dumps(dump))
    r = _run_cli(["--lock-graph", "--runtime-dump", str(dump_path)])
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(r.stdout)
    assert rep["static_cycles"] == [] and rep["runtime_cycles"] == []
    assert rep["contradictions"] == []


def test_lock_graph_diff_detects_contradiction(tmp_path):
    """A runtime dump ordering two locks OPPOSITE to the static graph
    is a contradiction and a non-zero exit."""
    _plant(tmp_path, "mxnet_tpu/locky.py",
           "from ._debug.locktrace import named_lock\n"
           "A = named_lock('d.a')\nB = named_lock('d.b')\n"
           "def f():\n    with A:\n        with B:\n            pass\n")
    dump_path = tmp_path / "rt.json"
    dump_path.write_text(json.dumps({"order_edges": ["d.b->d.a"]}))
    r = _run_cli(["--lock-graph", "--runtime-dump", str(dump_path),
                  str(tmp_path / "mxnet_tpu")], repo_root=tmp_path)
    assert r.returncode == 1, r.stdout + r.stderr
    rep = json.loads(r.stdout)
    assert rep["contradictions"]


def test_lock_graph_diff_static_cycle_is_not_a_contradiction(tmp_path):
    """A cycle that exists entirely WITHIN the static graph is a
    static cycle, never a cross-graph contradiction — even when the
    runtime dump adds unrelated edges that change the union-cycle DFS
    entry point (review regression: cycle identity must be by edge
    membership, not node-list spelling)."""
    _plant(tmp_path, "mxnet_tpu/locky.py",
           "from ._debug.locktrace import named_lock\n"
           "A = named_lock('s.a')\nB = named_lock('s.b')\n"
           "def f():\n    with A:\n        with B:\n            pass\n"
           "def g():\n    with B:\n        with A:\n            pass\n")
    dump_path = tmp_path / "rt.json"
    dump_path.write_text(json.dumps({"order_edges": ["s.0->s.b"]}))
    r = _run_cli(["--lock-graph", "--runtime-dump", str(dump_path),
                  str(tmp_path / "mxnet_tpu")], repo_root=tmp_path)
    assert r.returncode == 1  # the static cycle still fails the run
    rep = json.loads(r.stdout)
    assert rep["static_cycles"] and rep["contradictions"] == []


# -- CLI: --format=github, --jobs --------------------------------------------

def test_github_format_annotations(tmp_path):
    _plant(tmp_path, "mxnet_tpu/w.py",
           "import jax\nfast = jax.jit(lambda x: x)\n")
    r = _run_cli(["--format=github", "--rule", "MX005",
                  str(tmp_path / "mxnet_tpu" / "w.py")],
                 repo_root=tmp_path)
    assert r.returncode == 1
    assert "::error file=" in r.stdout and "MX005" in r.stdout


def test_jobs_parallel_matches_serial():
    """--jobs must not change results — identical findings and waiver
    counts on a real subtree (via the CLI: forking inside the test
    process would drag the loaded jax runtime across fork)."""
    serial = _run_cli(["mxnet_tpu/io"])
    par = _run_cli(["--jobs", "2", "mxnet_tpu/io"])
    assert serial.returncode == par.returncode == 0, \
        serial.stdout + par.stdout + serial.stderr + par.stderr
    assert serial.stdout == par.stdout
    assert serial.stderr == par.stderr  # same waived/baselined summary


def test_baseline_suppresses_and_reports(tmp_path):
    target = tmp_path / "mxnet_tpu" / "b.py"
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text("import jax\nfast = jax.jit(lambda x: x)\n")
    prev = core.REPO_ROOT
    core.REPO_ROOT = str(tmp_path)
    try:
        sel = [r for r in rules.ALL_RULES if r.code == "MX005"]
        baseline = [{"code": "MX005", "path": "mxnet_tpu/b.py",
                     "line": 2}]
        findings, _, n_baselined, _ = mxlint.run(
            [str(target)], rules=sel, baseline=baseline)
        assert findings == [] and n_baselined == 1
    finally:
        core.REPO_ROOT = prev


# -- MX018: unledgered device-buffer creation (ISSUE 13) ---------------------

def test_mx018_flags_unledgered_device_put(tmp_path):
    """A device_put in a hot module whose function never reaches a
    storage.ledger_* choke point is anonymous HBM — flagged."""
    _plant(tmp_path, "mxnet_tpu/io/myfeed.py", """\
        import jax

        def place(batch):
            return jax.device_put(batch)
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX018"})
    assert [f.code for f in findings] == ["MX018"]
    assert "device_put" in findings[0].message
    assert findings[0].path.endswith("myfeed.py")


def test_mx018_choke_point_in_function_is_clean(tmp_path):
    _plant(tmp_path, "mxnet_tpu/storage.py", """\
        def ledger_register(buf, tag, site=None):
            pass
        """)
    _plant(tmp_path, "mxnet_tpu/io/myfeed.py", """\
        import jax

        from .. import storage as _storage

        def place(batch):
            placed = jax.device_put(batch)
            _storage.ledger_register(placed, "io")
            return placed
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX018"})
    assert findings == []


def test_mx018_registration_one_call_away_is_clean(tmp_path):
    """The choke point may live in a helper one resolvable call away
    (the _ctx_place idiom)."""
    _plant(tmp_path, "mxnet_tpu/storage.py", """\
        def ledger_register(buf, tag, site=None):
            pass
        """)
    _plant(tmp_path, "mxnet_tpu/ndarray/myfactory.py", """\
        import jax

        from .. import storage as _storage

        def _register_io(buf):
            _storage.ledger_register(buf, "io")

        def place(batch):
            placed = jax.device_put(batch)
            _register_io(placed)
            return placed
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX018"})
    assert findings == []


def test_mx018_jnp_asarray_scoped_to_transport_modules(tmp_path):
    """jnp.asarray is a creator only in the transport/input modules —
    and np.asarray (a HOST array) is never one."""
    _plant(tmp_path, "mxnet_tpu/kvstore_async.py", """\
        import jax.numpy as jnp
        import numpy as np

        def pull_decode(host):
            return jnp.asarray(host)

        def host_only(x):
            return np.asarray(x)
        """)
    _plant(tmp_path, "mxnet_tpu/gluon/parameter.py", """\
        import jax.numpy as jnp

        def outside_asarray_scope(x):
            return jnp.asarray(x)
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX018"})
    assert len(findings) == 1, findings
    assert findings[0].path.endswith("kvstore_async.py")
    assert "jnp.asarray" in findings[0].message


def test_mx018_waiver_form(tmp_path):
    _plant(tmp_path, "mxnet_tpu/io/myfeed.py", """\
        import jax

        def place(batch):
            # mxlint: disable=MX018 (transient staging buffer: consumed and dropped before the call returns)
            return jax.device_put(batch)
        """)
    findings, _, waived, _ = _lint_tree(tmp_path, {"MX018"})
    assert findings == []


# -- MX019: metrics() provider doc contract ----------------------------------

def test_mx019_flags_undocumented_provider(tmp_path):
    """A registered metrics() section OBSERVABILITY.md never mentions
    is an API nobody can find — flagged at the registration site."""
    _plant(tmp_path, "docs/OBSERVABILITY.md", """\
        # Observability

        The snapshot carries `metrics()['documented']` (counts stuff).
        """)
    _plant(tmp_path, "mxnet_tpu/mymod.py", """\
        from . import profiler as _profiler

        def stats():
            return {}

        _profiler.register_stats_provider("documented", stats)
        _profiler.register_stats_provider("shiny", stats)
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX019"})
    assert [f.code for f in findings] == ["MX019"]
    assert "'shiny'" in findings[0].message
    assert findings[0].path == "mxnet_tpu/mymod.py"


def test_mx019_both_quote_styles_and_registration_in_function(tmp_path):
    """The doc may use either quote style, and registrations inside
    functions (the lazy-init idiom) are checked too."""
    _plant(tmp_path, "docs/OBSERVABILITY.md", """\
        `metrics()["lazy"]` — provider registered at first use.
        """)
    _plant(tmp_path, "mxnet_tpu/mymod.py", """\
        from . import profiler as _profiler

        def _install():
            _profiler.register_stats_provider("lazy", dict)
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX019"})
    assert findings == []


def test_mx019_computed_name_flagged(tmp_path):
    """A computed section name defeats the doc contract — the checker
    cannot resolve it, so the call site must pass a literal."""
    _plant(tmp_path, "docs/OBSERVABILITY.md", "everything documented\n")
    _plant(tmp_path, "mxnet_tpu/mymod.py", """\
        from . import profiler as _profiler

        def install(name):
            _profiler.register_stats_provider(name, dict)
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX019"})
    assert [f.code for f in findings] == ["MX019"]
    assert "computed" in findings[0].message


def test_mx019_no_doc_file_skips_doc_clause(tmp_path):
    """A tree without docs/OBSERVABILITY.md (a planted fixture, a
    vendored subtree) only enforces the literal-name clause."""
    _plant(tmp_path, "mxnet_tpu/mymod.py", """\
        from . import profiler as _profiler

        _profiler.register_stats_provider("anything", dict)
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX019"})
    assert findings == []


def test_mx019_tree_providers_all_documented():
    """The live contract: every provider registered in the real tree
    has its metrics() section documented (the rule found the `io`
    section undocumented on its first run — this pins the fix)."""
    rule = next(r for r in rules.ALL_RULES if r.code == "MX019")
    docs = rule._documented()
    assert docs is not None
    for name in ("elastic", "faults", "flightrec", "fused_step",
                 "goodput", "io", "kvstore_server", "watchdog"):
        assert name in docs, "metrics()[%r] undocumented" % name


# -- MX021: hardware-constant drift ------------------------------------------

_ASSUMPTIONS_FIXTURE = """\
ASSUMPTIONS = {
    "chip": "tpu_v5e",
    "bf16_peak_tflops": 197.0,
    "peak_tflops": {"bf16": 197.0, "f32": 98.5, "int8": 394.0},
    "hbm_bw_GBps": 819.0,
    "dcn_bw_per_host_GBps": 25.0,
    "chips_per_host": 4,
}
"""


def test_mx021_flags_math_and_table_literals(tmp_path):
    """A rate spelled as a literal in modeled math (a BinOp operand)
    or as a lookup-table dict value forks the hardware model."""
    _plant(tmp_path, "benchmark/comm_model.py", _ASSUMPTIONS_FIXTURE)
    _plant(tmp_path, "mxnet_tpu/_debug/roof.py", """\
        def mfu(flops, dur):
            return flops / (dur * 197.0 * 1e12)

        PEAKS = {"v5e": 98.5}
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX021"})
    assert sorted(f.line for f in findings) == [2, 4]
    assert all(f.code == "MX021" for f in findings)
    assert "ASSUMPTIONS" in findings[0].message


def test_mx021_defaults_thresholds_and_other_floats_clean(tmp_path):
    """Only math-context literals fire: argparse-style defaults,
    comparisons, and non-rate floats in arithmetic all stay clean —
    the 25.0 DCN rate colliding with a --median-pct default must
    never page."""
    _plant(tmp_path, "benchmark/comm_model.py", _ASSUMPTIONS_FIXTURE)
    _plant(tmp_path, "mxnet_tpu/_debug/clean.py", """\
        def f(pct=25.0, bw=819.0):
            if pct == 98.5:
                return None
            g(threshold=197.0)
            return pct * 3.0

        def g(threshold=0.0):
            return threshold
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX021"})
    assert findings == []


def test_mx021_comm_model_itself_and_int_keys_exempt(tmp_path):
    """The one home is exempt, and non-rate keys (chips_per_host) do
    not poison the rate set."""
    _plant(tmp_path, "benchmark/comm_model.py", _ASSUMPTIONS_FIXTURE
           + "\nWIRE = 2 * (4 - 1) / 4 * 819.0\n")
    _plant(tmp_path, "mxnet_tpu/_debug/ok.py", "N = 4 * 2\n")
    findings, _, _, _ = _lint_tree(tmp_path, {"MX021"})
    assert findings == []


def test_mx021_no_comm_model_skips(tmp_path):
    """A tree without benchmark/comm_model.py (installed wheel,
    planted fixture) has no rate table — the rule stays silent."""
    _plant(tmp_path, "mxnet_tpu/_debug/roof.py", "X = 2.0 * 197.0\n")
    findings, _, _, _ = _lint_tree(tmp_path, {"MX021"})
    assert findings == []


def test_mx021_real_tree_rates_parsed_and_clean():
    """The live contract: the real ASSUMPTIONS table parses into the
    expected rate set, and the rule's full real scope (which includes
    bench.py and tools/ — wider than the default lint paths) is clean.
    First run caught bench.py's hardcoded v5e 197.0 — this pins the
    fix."""
    rule = next(r for r in rules.ALL_RULES if r.code == "MX021")
    rates = rule._rates()
    for v in (197.0, 98.5, 394.0, 819.0, 180.0, 25.0):
        assert v in rates, "rate %r missing from parsed table" % v
    findings, _, _, _ = mxlint.run(
        ["bench.py", "benchmark", "tools", "mxnet_tpu"],
        rules=[rule], baseline=[])
    assert findings == [], "\n".join(map(repr, findings))


# -- MX022: jit sites invisible to the compile registry ----------------------

def test_mx022_flags_unregistered_jit(tmp_path):
    """A jax.jit in a hot module that never reaches record_compile is
    an unattributable compile — flagged at the jit site."""
    findings, _, _, _ = _lint_tree(tmp_path, {"MX022"}, roots=(
        _plant(tmp_path, "mxnet_tpu/gluon/block.py", """\
            import jax

            def build(fn):
                return jax.jit(fn)
            """),))
    assert [f.code for f in findings] == ["MX022"]
    assert "record_compile" in findings[0].message
    assert findings[0].path == "mxnet_tpu/gluon/block.py"


def test_mx022_probe_and_caller_registration_clean(tmp_path):
    """Both sanctioned shapes pass: the one-shot _compile_probe nested
    closure, and a direct caller recording on the builder's behalf
    (the fused_step._dispatch -> _build shape)."""
    _plant(tmp_path, "mxnet_tpu/optimizer/optimizer.py", """\
        import jax
        from .. import profiler as _profiler

        def _jitted(fn):
            jf = jax.jit(fn)
            def probe(*a):
                out = jf(*a)
                _profiler.record_compile("optimizer", dur_us=1.0)
                return out
            return probe
        """)
    _plant(tmp_path, "mxnet_tpu/parallel/train.py", """\
        import functools
        import jax
        from .. import profiler as _profiler

        def _build():
            return functools.partial(jax.jit)(lambda x: x)

        def _dispatch():
            f = _build()
            _profiler.record_compile("step", dur_us=1.0)
            return f
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX022"})
    assert findings == [], "\n".join(map(repr, findings))


def test_mx022_scoped_to_hot_modules_and_waivable(tmp_path):
    """Out-of-scope modules never fire; in-scope bench jits carry an
    inline waiver naming who accounts the compile."""
    _plant(tmp_path, "mxnet_tpu/metric.py", """\
        import jax

        def m(fn):
            return jax.jit(fn)
        """)
    _plant(tmp_path, "mxnet_tpu/pallas_kernels/tune.py", """\
        import jax

        def bench(fn):
            @jax.jit  # mxlint: disable=MX022 (micro-bench: the autotuner times this compile itself)
            def many(x):
                return fn(x)
            return many
        """)
    findings, n_waived, _, _ = _lint_tree(tmp_path, {"MX022"})
    assert findings == []
    assert n_waived == 1


def test_mx022_from_jax_import_jit_detected(tmp_path):
    """The `from jax import jit` spelling resolves through imports —
    the rule keys on the resolved target, not the literal text."""
    findings, _, _, _ = _lint_tree(tmp_path, {"MX022"}, roots=(
        _plant(tmp_path, "mxnet_tpu/ndarray/register.py", """\
            from jax import jit as _jit

            def dispatch(fn):
                return _jit(fn)
            """),))
    assert [f.code for f in findings] == ["MX022"]


# -- MX023: zero-badput knob contract (ISSUE 19) -----------------------------

_ZB_DOCS = """\
# Environment variables

| Variable | Default | Meaning |
|---|---|---|
| `MXTPU_CKPT_ASYNC` | `0` | async snapshot-then-persist checkpoints |
| `MXTPU_COMPILE_CACHE_DIR` | unset | persistent AOT compile cache dir |
| `MXTPU_PEER_SNAPSHOT_EVERY` | `1` | peer-snapshot publish cadence |
"""

_ZB_REGISTER = """\
def register_signature_token(name, default=""):
    return name

register_signature_token("MXTPU_CKPT_ASYNC", "0")
"""


def _plant_zb_tree(tmp_path, module_rel, body):
    _plant(tmp_path, "docs/ENV_VARS.md", _ZB_DOCS)
    _plant(tmp_path, "mxnet_tpu/ndarray/register.py", _ZB_REGISTER)
    _plant(tmp_path, "mxnet_tpu/base.py",
           "def getenv(name, default=None):\n    return None\n")
    _plant(tmp_path, module_rel, body)


def test_mx023_doc_and_token_clauses(tmp_path):
    """One read per contract shape in a zero-badput module: documented
    + registered is clean, documented-but-unregistered trips the token
    clause, an unknown knob trips both, a _CADENCE_ONLY knob needs no
    token, and a knob outside the owned prefixes is not this rule's
    business (MX015 already covers its doc half)."""
    _plant_zb_tree(tmp_path, "mxnet_tpu/gluon/compile_cache.py", """\
        from ..base import getenv as _getenv

        def doc_and_registered():
            return _getenv("MXTPU_CKPT_ASYNC", "0")        # clean

        def documented_not_registered():
            return _getenv("MXTPU_COMPILE_CACHE_DIR", "")  # token clause

        def neither():
            return _getenv("MXTPU_PEER_MAGIC", "0")        # both clauses

        def cadence_only():
            return _getenv("MXTPU_PEER_SNAPSHOT_EVERY", "1")  # clean

        def not_owned():
            return _getenv("MXTPU_UNRELATED_KNOB", "0")    # not ours
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX023"})
    assert [f.code for f in findings] == ["MX023"] * 3
    msgs = " ".join(f.message for f in findings)
    assert "MXTPU_COMPILE_CACHE_DIR" in msgs
    assert "MXTPU_PEER_MAGIC" in msgs
    assert "MXTPU_UNRELATED_KNOB" not in msgs
    assert "MXTPU_PEER_SNAPSHOT_EVERY" not in msgs
    # the unknown knob owes both halves: docs row AND token
    magic = [f for f in findings if "MXTPU_PEER_MAGIC" in f.message]
    assert len(magic) == 2


def test_mx023_scoped_to_zero_badput_modules(tmp_path):
    """The same undocumented/unregistered read OUTSIDE the
    checkpoint/cache/peer plane is not flagged by MX023."""
    _plant_zb_tree(tmp_path, "mxnet_tpu/thing.py", """\
        from .base import getenv as _getenv

        def elsewhere():
            return _getenv("MXTPU_PEER_MAGIC", "0")
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX023"})
    assert findings == []


def test_mx023_real_tree_knobs_hold_the_contract():
    """The shipped knobs honor what the rule enforces: ENV_VARS.md rows
    and signature-token registrations for the graph-shaping three, with
    the cadence knob documented but deliberately token-free."""
    from mxnet_tpu.ndarray import register as r
    with open(os.path.join(REPO, "docs", "ENV_VARS.md"),
              encoding="utf-8") as f:
        doc = f.read()
    tokens = r.signature_token_names()
    for var in ("MXTPU_CKPT_ASYNC", "MXTPU_CKPT_DELTA",
                "MXTPU_COMPILE_CACHE_DIR", "MXTPU_PEER_RESTORE"):
        assert "`%s`" % var in doc, var
        assert var in tokens, var
    assert "`MXTPU_PEER_SNAPSHOT_EVERY`" in doc
    assert "MXTPU_PEER_SNAPSHOT_EVERY" not in tokens


# -- MX024: wire-opcode contract (ISSUE 20) ----------------------------------

_OPCODE_DOCS = """\
# Resilience

| Opcode | # | Resend-safe | Fields / notes |
|---|---|---|---|
| `_OP_GOOD` | 1 | yes | documented |
| `_OP_UNDISPATCHED` | 3 | no | documented but no handler arm |
| `_OP_COMPUTED` | 4 | no | documented but value is computed |
"""


def _plant_wire_tree(tmp_path, body, docs=_OPCODE_DOCS):
    _plant(tmp_path, "docs/RESILIENCE.md", docs)
    return _plant(tmp_path, "mxnet_tpu/kvstore_async.py", body)


def test_mx024_literal_dispatch_and_doc_clauses(tmp_path):
    """One opcode per contract shape: literal+dispatched+documented is
    clean; undocumented trips the doc clause; undispatched trips the
    dispatch clause; a computed value trips the literal clause. The
    _OP_NAMES display map is never an opcode."""
    _plant_wire_tree(tmp_path, """\
        _OP_GOOD = 1
        _OP_UNDOC = 2
        _OP_UNDISPATCHED = 3
        _OP_COMPUTED = _OP_GOOD + 100
        _OP_NAMES = {_OP_GOOD: "good"}

        class AsyncPSServer:
            def _handle(self, conn, buf):
                op = buf[0]
                if op == _OP_GOOD:
                    return 1
                elif op == _OP_UNDOC:
                    return 2
                elif op == _OP_COMPUTED:
                    return 4
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX024"})
    assert all(f.code == "MX024" for f in findings)
    msgs = {f.message.split()[2]: [] for f in findings}
    for f in findings:
        msgs[f.message.split()[2]].append(f.message)
    assert "_OP_GOOD" not in msgs
    assert "_OP_NAMES" not in msgs
    assert len(msgs["_OP_UNDOC"]) == 1
    assert "RESILIENCE.md" in msgs["_OP_UNDOC"][0]
    assert len(msgs["_OP_UNDISPATCHED"]) == 1
    assert "_handle" in msgs["_OP_UNDISPATCHED"][0]
    assert len(msgs["_OP_COMPUTED"]) == 1
    assert "literal" in msgs["_OP_COMPUTED"][0]


def test_mx024_dispatch_must_be_in_handle(tmp_path):
    """A comparison in some *other* method does not satisfy the
    dispatch clause — the contract is the server's _handle arm."""
    _plant_wire_tree(tmp_path, """\
        _OP_GOOD = 1

        class AsyncPSServer:
            def _handle(self, conn, buf):
                return None

            def _replay_record(self, buf):
                if buf[0] == _OP_GOOD:
                    return 1
        """)
    findings, _, _, _ = _lint_tree(tmp_path, {"MX024"})
    assert [f.code for f in findings] == ["MX024"]
    assert "_handle" in findings[0].message


def test_mx024_scoped_to_wire_module(tmp_path):
    """_OP_* constants in any other module are not this rule's
    business — the wire protocol lives in kvstore_async.py alone."""
    _plant(tmp_path, "docs/RESILIENCE.md", _OPCODE_DOCS)
    _plant(tmp_path, "mxnet_tpu/other.py", "_OP_ROGUE = object()\n")
    findings, _, _, _ = _lint_tree(tmp_path, {"MX024"})
    assert findings == []


def test_mx024_real_tree_opcode_table_is_complete():
    """The shipped protocol honors the contract: every _OP_* constant
    in kvstore_async.py is an int literal, dispatched in _handle, and
    documented in the RESILIENCE.md opcode table — including the
    ISSUE 20 fence_epoch/preempt_notice pair."""
    import re as _re
    import mxnet_tpu.kvstore_async as kva
    with open(os.path.join(REPO, "docs", "RESILIENCE.md"),
              encoding="utf-8") as f:
        doc_ops = set(_re.findall(r"`(_OP_[A-Z0-9_]+)`", f.read()))
    declared = [n for n in dir(kva)
                if n.startswith("_OP_") and n != "_OP_NAMES"]
    assert "_OP_EPOCH" in declared and "_OP_PREEMPT" in declared
    for name in declared:
        assert isinstance(getattr(kva, name), int), name
        assert name in doc_ops, "%s missing from RESILIENCE.md" % name
