"""mxlint self-enforcement (tools/mxlint; docs/LINTING.md).

Two halves:

* the tier-1 gate: mxlint over the whole tree must report ZERO
  unwaived findings — the PR 1-2 invariants (single dispatch choke
  point, guarded telemetry, locked shared state, API_BEGIN/API_END on
  the C ABI, monotonic trace clocks) stay true by construction, and
* unit coverage of each rule and of the waiver/baseline machinery on
  synthetic inputs, so a rule regression can't silently turn the gate
  into a no-op.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools import mxlint
from tools.mxlint import core, rules

REPO = core.REPO_ROOT


# -- the gate ----------------------------------------------------------------

def test_tree_is_lint_clean():
    """`python -m tools.mxlint mxnet_tpu src tests` — zero unwaived
    violations. If this fails: fix the finding, or waive it with an
    inline justification (docs/LINTING.md)."""
    findings, n_waived, n_baselined, bad = mxlint.run(
        ["mxnet_tpu", "src", "tests"])
    assert bad == [], "waivers without justification:\n%s" % "\n".join(
        map(repr, bad))
    assert findings == [], "unwaived mxlint findings:\n%s" % "\n".join(
        map(repr, findings))
    # the gate must actually be exercising the rules, not skipping files
    assert n_waived > 0


def test_cli_exits_zero_on_tree():
    r = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "mxnet_tpu", "src",
         "tests"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr


def test_baseline_is_empty():
    """The checked-in baseline must stay empty: new findings are fixed
    or waived with a reason, never silently baselined."""
    assert core.load_baseline() == []


# -- rule units on synthetic files -------------------------------------------

def _lint_snippet(tmp_path, relpath, src, rule_codes=None):
    """Run mxlint on one synthetic file planted at a scoped repo-relative
    path under tmp_path."""
    target = tmp_path / relpath
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent(src))
    prev = core.REPO_ROOT
    core.REPO_ROOT = str(tmp_path)
    try:
        sel = None
        if rule_codes:
            sel = [r for r in rules.ALL_RULES if r.code in rule_codes]
        return mxlint.run([str(target)], rules=sel, baseline=[])
    finally:
        core.REPO_ROOT = prev


def test_mx001_flags_jnp_and_exempts_asarray(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/ndarray/contrib.py", """\
        import jax.numpy as jnp

        def f(x):
            y = jnp.asarray(x)      # conversion: exempt
            return jnp.tanh(y)      # compute: flagged
        """, {"MX001"})
    assert [f.code for f in findings] == ["MX001"]
    assert "tanh" in findings[0].message


def test_mx002_unguarded_vs_guarded(tmp_path):
    findings, n_waived, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/io/thing.py", """\
        from .. import profiler as _profiler

        def bad():
            _profiler.record_op("x", 1.0)

        def good_inline():
            if _profiler._ACTIVE:
                _profiler.record_op("x", 1.0)

        def good_derived(t0):
            if t0 is not None:
                _profiler.account("bytes", 4)
        """, {"MX002"})
    assert len(findings) == 1
    assert findings[0].line == 4


def test_mx003_mutation_lock_and_definition_waiver(tmp_path):
    findings, n_waived, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/sub/mod.py", """\
        import threading

        _LOCK = threading.Lock()
        _GUARDED = {}
        _NAKED = {}
        _DECLARED = {}  # mxlint: disable=MX003 (import-time only)
        _TLS = threading.local()

        def f(k, v):
            with _LOCK:
                _GUARDED[k] = v
            _NAKED[k] = v
            _DECLARED[k] = v
        """, {"MX003"})
    assert len(findings) == 1
    assert "_NAKED" in findings[0].message
    assert n_waived == 1  # _DECLARED via its definition-line waiver


def test_mx004_buf_outside_ndarray(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/helper.py", """\
        def peek(arr):
            return arr._buf
        """, {"MX004"})
    assert [f.code for f in findings] == ["MX004"]


def test_mx005_jit_call_and_decorator(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/newmod.py", """\
        import jax

        fast = jax.jit(lambda x: x)

        @jax.jit
        def g(x):
            return x
        """, {"MX005"})
    assert [f.code for f in findings] == ["MX005", "MX005"]


def test_mx005_call_form_decorator_reported_once(tmp_path):
    """@jax.jit(...) is both a decorator and a Call node — one site,
    one finding."""
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/decmod.py", """\
        import jax

        @jax.jit(static_argnums=(0,))
        def g(n, x):
            return x
        """, {"MX005"})
    assert len(findings) == 1


def test_mx005_sanctioned_module_is_exempt(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/jit.py", """\
        import jax
        fast = jax.jit(lambda x: x)
        """, {"MX005"})
    assert findings == []


def test_mx005_fused_step_module_is_sanctioned(tmp_path):
    """The fused-train-step program cache (ISSUE 4) is a sanctioned jit
    site: its keys are the signature-keyed compile-on-repeat cache on
    each FusedTrainStep, bounded like the dispatch cache."""
    assert "mxnet_tpu/gluon/fused_step.py" in rules._SANCTIONED_JIT
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/gluon/fused_step.py", """\
        import jax
        prog = jax.jit(lambda x: x)
        """, {"MX005"})
    assert findings == []


def test_mx006_missing_and_present_macros(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "src/c_api_extra.cc", """\
        int MXTGood(void** out) {
          API_BEGIN()
          *out = nullptr;
          API_END()
        }

        int MXTBad(void** out) {
          *out = nullptr;
          return 0;
        }
        """, {"MX006"})
    assert len(findings) == 1
    assert "MXTBad" in findings[0].message


def test_mx007_wall_clock(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/io/meter.py", """\
        import time

        def stamp():
            return time.time()
        """, {"MX007"})
    assert [f.code for f in findings] == ["MX007"]


def test_mx008_bare_except(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/engine.py", """\
        def f():
            try:
                return 1
            except:
                return 2
        """, {"MX008"})
    assert [f.code for f in findings] == ["MX008"]


def test_mx009_flags_swallowed_broad_except(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/io/pipe.py", """\
        def f():
            try:
                return 1
            except Exception:
                return 2
        """, {"MX009"})
    assert [f.code for f in findings] == ["MX009"]


def test_mx009_accepts_reraise_and_accounting(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/kvstore_async.py", """\
        from . import profiler as _profiler

        def f():
            try:
                return 1
            except Exception:
                raise
        def g():
            try:
                return 1
            except BaseException:
                if _profiler._ACTIVE:
                    _profiler.account("kvstore.server_errors", 1)
                return 2
        def narrow():
            try:
                return 1
            except (ConnectionError, OSError):
                return 2  # narrow catches are out of scope
        """, {"MX009"})
    assert findings == []


def test_mx010_flags_unguarded_latency_telemetry(tmp_path):
    """record_latency/record_flow in kvstore_async and the fused step
    must sit behind the inlined active guard (ISSUE 6 satellite)."""
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/gluon/fused_step.py", """\
        from .. import profiler as _profiler

        def bad(dur):
            _profiler.record_latency("fused_step.step", dur)

        def bad_flow(fid):
            _profiler.record_flow("ps.push", fid, "s")
        """, {"MX010"})
    assert [f.code for f in findings] == ["MX010", "MX010"]
    assert "record_latency" in findings[0].message


def test_mx010_accepts_inlined_and_derived_guards(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/kvstore_async.py", """\
        from . import profiler as _profiler

        def good_inline(dur):
            if _profiler._ACTIVE:
                _profiler.record_latency("kvstore.pull_rtt", dur)

        def good_derived(t0):
            if t0 is not None:
                _profiler.record_flow("ps.pull", 7, "f")
        """, {"MX010"})
    assert findings == []


def test_mx010_out_of_scope_module_is_exempt(tmp_path):
    """The rule targets the hot request/step paths; cold modules (e.g.
    a tool) may call the primitives unguarded."""
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/callback.py", """\
        from . import profiler as _profiler

        def f(dur):
            _profiler.record_latency("cb", dur)
        """, {"MX010"})
    assert findings == []


def test_mx011_flags_second_hot_path_branch(tmp_path):
    """Flight-recorder records in hot modules must sit under the ONE
    shared guard — a standalone `if _flightrec.ENABLED:` branch (or no
    guard at all) is a second hot-path cost the flightrec_overhead
    budget does not price. Covers both the helper recorders and the
    raw inlined RING.append form."""
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/ndarray/thing.py", """\
        from .._debug import flightrec as _flightrec

        def bad_own_branch(name):
            if _flightrec.ENABLED:
                _flightrec.RING.append(name)

        def bad_unguarded(name, dur):
            _flightrec.record_span(name, dur)

        def bad_marker(name):
            _flightrec.record_marker(name)
        """, {"MX011"})
    assert [f.code for f in findings] == ["MX011"] * 3
    assert sorted(f.line for f in findings) == [5, 8, 11]


def test_mx011_accepts_shared_and_derived_guards(tmp_path):
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/ndarray/thing.py", """\
        from .. import profiler as _profiler
        from .._debug import flightrec as _flightrec

        def good_shared(name, t0):
            if _profiler._HOOKS and _profiler._LIVE:
                _flightrec.RING.append(name)

        def good_derived(name, _prof_t0):
            if _prof_t0 is not None:
                _flightrec.RING.append(name)

        def good_helper(name, dur, t0):
            if t0 is not None:
                _flightrec.record_span(name, dur)
        """, {"MX011"})
    assert findings == []


def test_mx011_out_of_scope_module_is_exempt(tmp_path):
    """Cold modules (the dump path itself, tools) may record freely —
    only the hot dispatch/step modules carry the one-guard contract."""
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/callback.py", """\
        from .._debug import flightrec as _flightrec

        def f(name):
            _flightrec.record_marker(name)
        """, {"MX011"})
    assert findings == []


def test_mx012_flags_contractless_kernel_module(tmp_path):
    """A pallas_kernels module without a reference implementation, an
    interpret= path, or a KERNEL_BENCH registration breaks the kernel
    contract threefold."""
    (tmp_path / "mxnet_tpu" / "pallas_kernels").mkdir(parents=True)
    (tmp_path / "mxnet_tpu" / "pallas_kernels" / "__init__.py") \
        .write_text("KERNEL_BENCH = {'other': 'resnet50'}\n")
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/pallas_kernels/shiny.py", """\
        import jax.numpy as jnp

        def shiny_kernel(x):
            return x * 2
        """, {"MX012"})
    assert [f.code for f in findings] == ["MX012"] * 3
    msgs = " ".join(f.message for f in findings)
    assert "reference" in msgs and "interpret" in msgs \
        and "KERNEL_BENCH" in msgs


def test_mx012_accepts_contract_compliant_module(tmp_path):
    (tmp_path / "mxnet_tpu" / "pallas_kernels").mkdir(parents=True)
    (tmp_path / "mxnet_tpu" / "pallas_kernels" / "__init__.py") \
        .write_text("KERNEL_BENCH = {'shiny': 'fused_kernels'}\n")
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/pallas_kernels/shiny.py", """\
        import jax.numpy as jnp

        def shiny_reference(x):
            return x * 2

        def shiny(x, interpret=False):
            return shiny_reference(x)
        """, {"MX012"})
    assert findings == []


def test_mx012_private_helpers_and_init_are_exempt(tmp_path):
    """_compile_attr.py-style private helpers and the package __init__
    are not kernel modules."""
    for rel in ("mxnet_tpu/pallas_kernels/_helper.py",
                "mxnet_tpu/pallas_kernels/__init__.py"):
        findings, _, _, _ = _lint_snippet(
            tmp_path, rel, "X = 1\n", {"MX012"})
        assert findings == [], rel


def test_mx012_real_tree_kernels_registered():
    """Every shipped kernel module appears in KERNEL_BENCH, and the
    campaign kernels map to the fused_kernels gate."""
    from mxnet_tpu import pallas_kernels as pk
    for mod in ("batchnorm_fused", "optimizer_apply",
                "quantized_matmul"):
        assert pk.KERNEL_BENCH[mod] == "fused_kernels"
    for mod in ("flash_attention", "compression", "conv_fused"):
        assert mod in pk.KERNEL_BENCH


def _plant_catalog(tmp_path, points):
    d = tmp_path / "mxnet_tpu" / "_debug"
    d.mkdir(parents=True, exist_ok=True)
    (d / "faultpoint.py").write_text(
        "POINTS = frozenset((%s,))\n"
        % ", ".join("%r" % p for p in points))


def test_mx013_flags_uncataloged_literal(tmp_path):
    _plant_catalog(tmp_path, ["io.known.point"])
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/io/newthing.py", """\
        from .._debug import faultpoint as _faultpoint

        def f(point):
            _faultpoint.check("io.known.point")    # cataloged: ok
            _faultpoint.check("io.typo.point")     # flagged
            _faultpoint.check(point)               # computed: exempt
        """, {"MX013"})
    assert [f.code for f in findings] == ["MX013"]
    assert "io.typo.point" in findings[0].message
    assert findings[0].line == 5


def test_mx013_import_alias_forms(tmp_path):
    """Both import spellings bind the alias the rule tracks."""
    _plant_catalog(tmp_path, ["a.b"])
    findings, _, _, _ = _lint_snippet(
        tmp_path, "mxnet_tpu/x.py", """\
        import mxnet_tpu._debug.faultpoint as fp

        def f():
            fp.check("a.b")
            fp.check("a.nope")
        """, {"MX013"})
    assert [f.code for f in findings] == ["MX013"]


def test_mx013_scope_excludes_tests():
    rule = next(r for r in rules.ALL_RULES if r.code == "MX013")
    assert rule.scope("mxnet_tpu/io/shard_service.py")
    assert rule.scope("bench.py")
    assert not rule.scope("tests/test_faultpoints.py")
    assert not rule.scope("docs/DATA.md")


def test_mx013_real_catalog_includes_io_points():
    """The rule reads the REAL catalog: the ISSUE 11 io seams are in
    it, so the clean-tree gate genuinely checks the new check() sites."""
    rule = next(r for r in rules.ALL_RULES if r.code == "MX013")
    catalog = rule._catalog()
    for p in ("io.shard.read", "io.record.corrupt",
              "io.worker.decode", "io.service.fetch",
              "kvstore.send", "checkpoint.save"):
        assert p in catalog, p


# -- waiver machinery --------------------------------------------------------

def test_waiver_without_reason_is_flagged(tmp_path):
    findings, _, _, bad = _lint_snippet(
        tmp_path, "mxnet_tpu/w.py", """\
        import jax
        fast = jax.jit(lambda x: x)  # mxlint: disable=MX005
        """, {"MX005"})
    assert findings == []  # the waiver still suppresses
    assert len(bad) == 1
    assert bad[0].code == "MX000"


def test_waiver_on_line_above(tmp_path):
    findings, n_waived, _, bad = _lint_snippet(
        tmp_path, "mxnet_tpu/w2.py", """\
        import jax
        # mxlint: disable=MX005 (bounded: single key)
        fast = jax.jit(lambda x: x)
        """, {"MX005"})
    assert findings == [] and bad == [] and n_waived == 1


def test_file_level_waiver(tmp_path):
    findings, n_waived, _, bad = _lint_snippet(
        tmp_path, "mxnet_tpu/ndarray/extra.py", """\
        # mxlint: disable-file=MX001 (whole-file design exemption for test)
        import jax.numpy as jnp

        def a(x):
            return jnp.tanh(x)

        def b(x):
            return jnp.exp(x)
        """, {"MX001"})
    assert findings == [] and bad == [] and n_waived == 2


def test_baseline_suppresses_and_reports(tmp_path):
    target = tmp_path / "mxnet_tpu" / "b.py"
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text("import jax\nfast = jax.jit(lambda x: x)\n")
    prev = core.REPO_ROOT
    core.REPO_ROOT = str(tmp_path)
    try:
        sel = [r for r in rules.ALL_RULES if r.code == "MX005"]
        baseline = [{"code": "MX005", "path": "mxnet_tpu/b.py",
                     "line": 2}]
        findings, _, n_baselined, _ = mxlint.run(
            [str(target)], rules=sel, baseline=baseline)
        assert findings == [] and n_baselined == 1
    finally:
        core.REPO_ROOT = prev
