"""hlolint — static contract verification of compiled programs
(ISSUE 18 tentpole; tools/hlolint, docs/LINTING.md "HLO contracts").

Two halves, the test_lint.py shape applied to the HLO plane:

* unit coverage: every rule H001-H005 must flag its seeded violation
  on a synthetic artifact AND stay silent on the matching clean
  fixture, so a rule regression can't silently turn the gate into a
  no-op, and
* the tier-1 gate: real fused-step programs captured from the standing
  three-mesh dryrun (dp8, dp4xtp2, dp2xtp2xsp2) analyze CLEAN — zero
  findings, zero baseline entries — with the first signature lowered
  twice so H005 checks a genuine re-lowering group.
"""
import pytest

import jax

from tools import hlolint
from tools.hlolint import capture, core, rules

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")


def _art(hlo, sig="fused_step:deadbeef", name="fused_step", **meta):
    return capture.make_artifact(name, sig, hlo, meta)


def _run(arts, codes=None):
    sel = None
    if codes:
        sel = [r for r in rules.ALL_RULES if r.code in codes]
    findings, _, _ = hlolint.run(arts, rules=sel, baseline=[])
    return findings


# -- H001 donation-took ------------------------------------------------------

_H001_HLO = """\
HloModule m, input_output_alias={ {0}: (0, {}, may-alias) }, \
entry_computation_layout={(f32[8]{0}, f32[8]{0})->(f32[8]{0})}

ENTRY %main (a: f32[8], b: f32[8]) -> (f32[8]) {
  %a = f32[8]{0} parameter(0)
  %b = f32[8]{0} parameter(1)
  ROOT %t = (f32[8]{0}) tuple(%a)
}
"""


def test_h001_flags_dropped_donation():
    """Param 1 was donated but XLA kept only param 0 in the alias map —
    the silently-copied buffer must be reported."""
    fs = _run([_art(_H001_HLO, donated=(0, 1))], {"H001"})
    assert [f.code for f in fs] == ["H001"]
    assert "argument 1" in fs[0].message


def test_h001_clean_when_all_donations_took():
    assert _run([_art(_H001_HLO, donated=(0,))], {"H001"}) == []


def test_h001_vacuous_without_donations():
    # empty donation (the CPU-backend fused step) never fires
    assert _run([_art("HloModule m", donated=())], {"H001"}) == []


# -- H002 collective inventory -----------------------------------------------

def _h002_hlo(extra=""):
    return ("""\
HloModule m, is_scheduled=true

ENTRY %main (g: f32[250000]) -> f32[250000] {
  %g = f32[250000]{0} parameter(0)
  %ar = f32[250000]{0} all-reduce(%g), channel_id=1, to_apply=%add
""" + extra + """\
  ROOT %r = f32[250000]{0} copy(%ar)
}
""")


def test_h002_clean_when_wire_matches_plan():
    fs = _run([_art(_h002_hlo(), plan={"all-reduce": 1000000})], {"H002"})
    assert fs == []


def test_h002_flags_missing_reduction():
    """Plan promises a 2 MB gradient all-reduce, the wire carries half —
    a planned reduction missing from the program."""
    fs = _run([_art(_h002_hlo(), plan={"all-reduce": 2000000})], {"H002"})
    assert [f.code for f in fs] == ["H002"]
    assert "missing from the wire" in fs[0].message


def test_h002_flags_phantom_reshard():
    """An all-gather the analytic plan never asked for (above the
    bookkeeping floor) is phantom resharding traffic."""
    extra = ("  %ag = f32[4096]{0} all-gather(%g), channel_id=2, "
             "dimensions={0}\n")
    fs = _run([_art(_h002_hlo(extra), plan={"all-reduce": 1000000})],
              {"H002"})
    assert [f.code for f in fs] == ["H002"]
    assert "all-gather" in fs[0].message
    assert "phantom" in fs[0].message


def test_h002_tolerates_bookkeeping_floor():
    # a sub-floor unplanned collective (loss gather, health sentinel)
    # stays beneath the 4096 B absolute floor
    extra = ("  %ag = f32[16]{0} all-gather(%g), channel_id=2, "
             "dimensions={0}\n")
    fs = _run([_art(_h002_hlo(extra), plan={"all-reduce": 1000000})],
              {"H002"})
    assert fs == []


def test_h002_vacuous_without_plan():
    assert _run([_art(_h002_hlo())], {"H002"}) == []


# -- H003 replicated outputs -------------------------------------------------

def test_h003_flags_sharded_loss():
    fs = _run([_art("HloModule m", replicated_slots=(0,),
                    out_specs=[[("dp", None)]])], {"H003"})
    assert [f.code for f in fs] == ["H003"]
    assert "slot 0" in fs[0].message and "gather" in fs[0].message


def test_h003_clean_on_replicated_and_ignores_other_slots():
    # slot 0 replicated (empty/None specs); slot 1 sharded but NOT in
    # the contract — only declared slots are checked
    fs = _run([_art("HloModule m", replicated_slots=(0,),
                    out_specs=[[(), (None, None)], [("dp",)]])],
              {"H003"})
    assert fs == []


def test_h003_flags_unverifiable_and_missing_slot():
    no_specs = _run([_art("HloModule m", replicated_slots=(0,))],
                    {"H003"})
    assert [f.code for f in no_specs] == ["H003"]
    assert "not verifiable" in no_specs[0].message
    short = _run([_art("HloModule m", replicated_slots=(0, 4),
                       out_specs=[[()]])], {"H003"})
    assert [f.code for f in short] == ["H003"]
    assert "slot 4" in short[0].message


# -- H004 dtype discipline ---------------------------------------------------

_H004_UPCAST = """\
HloModule m

ENTRY %main (p: bf16[8,16], w: f32[16,4]) -> f32[8,4] {
  %p = bf16[8,16]{1,0} parameter(0)
  %w = f32[16,4]{1,0} parameter(1)
  %cvt = f32[8,16]{1,0} convert(%p)
  ROOT %d = f32[8,4]{1,0} dot(%cvt, %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""

_H004_CLEAN = """\
HloModule m

ENTRY %main (p: bf16[8,16], w: bf16[16,4]) -> bf16[8,4] {
  %p = bf16[8,16]{1,0} parameter(0)
  %w = bf16[16,4]{1,0} parameter(1)
  ROOT %d = bf16[8,4]{1,0} dot(%p, %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""


def test_h004_flags_f32_upcast_feeding_dot():
    fs = _run([_art(_H004_UPCAST, dtype="bf16")], {"H004"})
    assert [f.code for f in fs] == ["H004"]
    assert "convert" in fs[0].message and "bf16" in fs[0].message


def test_h004_clean_on_native_bf16_dot():
    assert _run([_art(_H004_CLEAN, dtype="bf16")], {"H004"}) == []


def test_h004_vacuous_on_f32_program():
    # the same upcast pattern on a declared-f32 path is just mixed
    # precision working as configured
    assert _run([_art(_H004_UPCAST, dtype="f32")], {"H004"}) == []


# -- H005 collective-order determinism ---------------------------------------

def _h005_hlo(order):
    body = {"ar": "  %ar = f32[64]{0} all-reduce(%g), channel_id=1, "
                  "to_apply=%add\n",
            "ag": "  %ag = f32[128]{0} all-gather(%g), channel_id=2, "
                  "dimensions={0}\n"}
    return ("HloModule m\n\nENTRY %main (g: f32[64]) -> f32[64] {\n"
            "  %g = f32[64]{0} parameter(0)\n"
            + "".join(body[k] for k in order)
            + "  ROOT %r = f32[64]{0} copy(%g)\n}\n")


def test_h005_flags_permuted_collective_order():
    a = _art(_h005_hlo(("ar", "ag")), sig="fused_step:cafe0001")
    b = _art(_h005_hlo(("ag", "ar")), sig="fused_step:cafe0001")
    fs = _run([a, b], {"H005"})
    assert [f.code for f in fs] == ["H005"]
    assert "cluster hang" in fs[0].message


def test_h005_clean_on_identical_relowering():
    a = _art(_h005_hlo(("ar", "ag")), sig="fused_step:cafe0002")
    b = _art(_h005_hlo(("ar", "ag")), sig="fused_step:cafe0002")
    assert _run([a, b], {"H005"}) == []


def test_h005_needs_a_group():
    # different sigs are different programs — no cross-sig comparison
    a = _art(_h005_hlo(("ar", "ag")), sig="fused_step:cafe0003")
    b = _art(_h005_hlo(("ag", "ar")), sig="fused_step:cafe0004")
    assert _run([a, b], {"H005"}) == []


# -- driver machinery --------------------------------------------------------

def test_baseline_suppresses_known_finding():
    art = _art(_H001_HLO, donated=(0, 1), sig="fused_step:feed0001")
    kept, n_base, _ = core.run(
        [art], baseline=[{"code": "H001", "path": "fused_step:feed0001",
                          "line": 1}])
    assert kept == [] and n_base == 1


def test_checked_in_baseline_is_empty():
    """The committed baseline must stay empty: a new HLO-contract
    violation is fixed, never silently baselined."""
    assert core.load_baseline() == []


def test_report_shape():
    art = _art(_H004_UPCAST, dtype="bf16")
    findings, n_base, per_sig = core.run([art], baseline=[])
    rep = core.report([art], findings, n_base, per_sig)
    assert rep["programs"][0]["lowerings"] == 1
    assert rep["findings"] and rep["findings"][0]["code"] == "H004"
    assert rep["max_sig_seconds"] >= 0.0


# -- the tier-1 gate: real three-mesh programs analyze clean -----------------

_DRYRUN = None


def _dryrun_artifacts():
    """One shared three-mesh capture for the e2e tests (the compile
    work dominates; do it once per process)."""
    global _DRYRUN
    if _DRYRUN is None:
        _DRYRUN = capture.dryrun_programs(repeat_first=True)
    return _DRYRUN


class TestRealProgramsClean:
    def test_capture_meta_contract(self):
        """Every captured fused-step artifact carries the meta keys the
        rules read (capture.py's producer contract)."""
        arts = _dryrun_artifacts()
        assert len(arts) >= 4
        for a in arts:
            assert a["name"] == "fused_step"
            assert a["sig"].startswith("fused_step:")
            assert "HloModule" in a["hlo"]
            for key in ("donated", "plan", "replicated_slots", "dtype",
                        "mesh", "gspmd"):
                assert key in a["meta"], (a["sig"], key)
        # the analytic plan is live on every multi-device mesh
        assert all(a["meta"]["plan"]["all-reduce"] > 0 for a in arts)
        # GSPMD configs pin replicated output slots; manual-dp pins none
        by_mode = {a["meta"]["gspmd"] for a in arts}
        assert by_mode == {True, False}

    def test_three_meshes_analyze_clean(self):
        """The standing dp8 / dp4xtp2 / dp2xtp2xsp2 programs carry zero
        contract findings with zero waivers or baseline entries — the
        acceptance bar for the whole plane."""
        arts = _dryrun_artifacts()
        findings, n_base, per_sig = core.run(arts, baseline=[])
        assert findings == [], "\n".join(map(repr, findings))
        assert n_base == 0
        assert len(per_sig) == 3
        # the repeat_first group gives H005 a real re-lowering pair
        sigs = [a["sig"] for a in arts]
        assert any(sigs.count(s) >= 2 for s in set(sigs))
        # the bench-gate latency bar, with margin: static analysis only
        assert max(per_sig.values()) < 5.0

    def test_from_profiler_sees_the_same_programs(self):
        arts = _dryrun_artifacts()
        drained = capture.from_profiler()
        assert {a["sig"] for a in arts} <= {a["sig"] for a in drained}
        assert all(a["name"] == "fused_step" for a in drained)
