"""Exception propagation semantics (ref: tests/python/unittest/
test_exc_handling.py — the reference captures async-op exceptions per
engine var and rethrows at WaitToRead/WaitForAll; here XLA dispatch is
the engine, so invalid programs raise at call or at sync points)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError

nd = mx.nd


class TestEagerErrors:
    def test_shape_mismatch_raises(self):
        a = nd.ones((2, 3))
        b = nd.ones((4, 5))
        with pytest.raises(Exception):
            nd.dot(a, b).wait_to_read()

    def test_invalid_op_param(self):
        with pytest.raises(Exception):
            nd.Convolution(nd.ones((1, 1, 4, 4)), nd.ones((1, 1, 3, 3)),
                           None, kernel=(9, 9), num_filter=1,
                           no_bias=True).wait_to_read()

    def test_unknown_kvstore_raises(self):
        with pytest.raises(ValueError):
            mx.kv.create("definitely_not_a_kvstore")

    def test_uninitialized_key_raises(self):
        kv = mx.kv.create("local")
        with pytest.raises(ValueError):
            kv.push(99, nd.ones((2,)))


class TestTrainingErrors:
    def test_backward_without_record_raises(self):
        x = nd.ones((2,))
        x.attach_grad()
        y = x * 2  # not recorded
        with pytest.raises(Exception):
            y.backward()

    def test_stale_grad_warning(self):
        net = gluon.nn.Dense(2, in_units=3)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        x = nd.ones((2, 3))
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        tr.step(2)
        # second step without a fresh backward: stale grads must be
        # detected (ref: trainer.py stale-grad UserWarning)
        with pytest.raises(UserWarning):
            tr.step(2)

    def test_deferred_init_error_message(self):
        net = gluon.nn.Dense(2)  # in_units unknown
        net.initialize()
        with pytest.raises(Exception):
            # accessing data before any forward must raise the deferred
            # init error, not crash obscurely
            net.weight.data()


class TestHybridizedErrors:
    def test_error_in_traced_graph_raises_at_call(self):
        class Bad(gluon.HybridBlock):
            def hybrid_forward(self, F, x):
                return F.reshape(x, shape=(7, 13))  # incompatible

        net = Bad()
        net.hybridize()
        with pytest.raises(Exception):
            out = net(nd.ones((2, 3)))
            out.wait_to_read()

    def test_engine_naive_mode_still_works(self, monkeypatch):
        """MXNET_ENGINE_TYPE=NaiveEngine: the serial debug mode
        (ref: src/engine/engine.cc:32) must still compute correctly.
        engine_type() reads the env per call, so no module reload."""
        monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
        a = nd.ones((4,)) * 3
        assert float(a.sum().asnumpy()) == 12.0


class TestControlFlowErrors:
    def test_foreach_empty_sequences(self):
        with pytest.raises(ValueError, match="at least one"):
            nd.contrib.foreach(lambda x, s: (x, s), [], [])

    def test_deconv_kernel_mismatch(self):
        with pytest.raises(ValueError, match="Deconvolution kernel"):
            nd.Deconvolution(nd.ones((1, 2, 4, 4)), nd.ones((2, 1, 2, 2)),
                             None, kernel=(3, 3), num_filter=1,
                             no_bias=True)

    def test_foreach_mismatched_lengths(self):
        with pytest.raises(ValueError, match="axis-0 length"):
            nd.contrib.foreach(
                lambda xs, s: (xs[0], s),
                [nd.ones((3, 2)), nd.ones((2, 2))], [])
