"""Subprocess worker for the flight-recorder post-mortem tests
(ISSUE 8 acceptance): a training run that dies badly and must leave a
readable black box behind.

Modes (``sys.argv[1]``):

- ``crash``: run a few fused steps + eager ops with profiling on, dump
  the live profiler shard, then raise an uncaught exception mid-epoch —
  the chained ``sys.excepthook`` must write a flight-recorder shard.
- ``stall``: same warm-up, then wedge a watchdog-beaconed kvstore pull
  under a long faultpoint delay. The watchdog daemon must trip, dump
  exactly one shard, and the parent SIGKILLs this process mid-stall
  (nothing after the wedged pull ever runs — like a real hang).

Run via: python tests/flightrec_worker.py {crash|stall}
with MXTPU_FLIGHTREC_DIR pointing at the parent's scratch dir.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as onp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, profiler  # noqa: E402
from mxnet_tpu._debug import faultpoint, watchdog  # noqa: E402


def _train_a_bit():
    """A few fused steps + eager ops: fills the ring with bare-name
    dispatch breadcrumbs AND timestamped anchors (step spans, bulk
    flushes)."""
    net = gluon.nn.Dense(4, in_units=8)
    net.initialize(mx.init.Uniform(0.1))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), trainer)
    x = mx.nd.array(onp.ones((4, 8), onp.float32))
    y = mx.nd.array(onp.zeros((4, 4), onp.float32))
    for _ in range(4):
        step(x, y, batch_size=4)
    a = mx.nd.array(onp.ones((8, 8), onp.float32))
    b = mx.nd.softmax(a * 2 + 1)
    b.wait_to_read()


def main():
    mode = sys.argv[1]
    outdir = os.environ["MXTPU_FLIGHTREC_DIR"]
    live = os.path.join(outdir, "live_trace.json")
    profiler.set_config(filename=live, xprof=False)
    profiler.set_state("run")
    _train_a_bit()
    profiler.dump()  # the live shard a surviving profiler leaves behind

    if mode == "crash":
        raise RuntimeError("boom mid-epoch")

    assert mode == "stall", mode
    from mxnet_tpu import kvstore_async as KA
    watchdog.configure(factor=3.0, min_s=0.4, poll_s=0.05,
                       min_samples=3)
    srv = KA.AsyncPSServer()
    cli = KA.AsyncPSClient("127.0.0.1", srv.port)
    cli.init("w", onp.zeros(8, onp.float32))
    for _ in range(4):  # arm the watchdog with representative steps
        watchdog.step_begin()
        cli.pull("w")
        watchdog.step_end()
    assert watchdog.threshold_s() is not None
    faultpoint.configure({"kvstore.pull": "delay:120s@n=1"})
    print("STALLING", flush=True)
    watchdog.step_begin()
    cli.pull("w")  # wedges 120 s: the watchdog dumps, the parent kills
    watchdog.step_end()


if __name__ == "__main__":
    main()
