"""Gluon losses vs hand-computed formulas + convergence smoke.

Ports the strategy of the reference's tests/python/unittest/test_loss.py
(value checks against numpy formulas, then tiny trainings asserting the
loss head can drive convergence)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon, autograd

L = gluon.loss


def _np(x):
    return x.asnumpy()


def test_l2_l1_values():
    pred = nd.array(np.array([[1.0, 2.0]], "float32"))
    label = nd.array(np.array([[2.0, 0.0]], "float32"))
    np.testing.assert_allclose(
        _np(L.L2Loss()(pred, label)), [(1 + 4) / 2 / 2], rtol=1e-5)
    np.testing.assert_allclose(
        _np(L.L1Loss()(pred, label)), [(1 + 2) / 2], rtol=1e-5)


def test_sigmoid_bce_matches_formula():
    x = np.array([[-1.0, 0.5]], "float32")
    y = np.array([[0.0, 1.0]], "float32")
    out = _np(L.SigmoidBinaryCrossEntropyLoss()(nd.array(x), nd.array(y)))
    p = 1 / (1 + np.exp(-x))
    exp = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean(axis=1)
    np.testing.assert_allclose(out, exp, rtol=1e-5)
    # from_sigmoid variant takes probabilities directly
    out2 = _np(L.SigmoidBinaryCrossEntropyLoss(from_sigmoid=True)(
        nd.array(p.astype("float32")), nd.array(y)))
    np.testing.assert_allclose(out2, exp, rtol=1e-4)


def test_softmax_ce_matches_formula():
    x = np.array([[1.0, 2.0, 3.0], [0.0, 0.0, 0.0]], "float32")
    y = np.array([2, 1], "float32")
    out = _np(L.SoftmaxCrossEntropyLoss()(nd.array(x), nd.array(y)))
    p = np.exp(x - x.max(1, keepdims=True))
    p /= p.sum(1, keepdims=True)
    exp = -np.log(p[np.arange(2), y.astype(int)])
    np.testing.assert_allclose(out, exp, rtol=1e-5)
    # sparse_label=False with one-hot gives the same numbers
    onehot = np.eye(3, dtype="float32")[y.astype(int)]
    out2 = _np(L.SoftmaxCrossEntropyLoss(sparse_label=False)(
        nd.array(x), nd.array(onehot)))
    np.testing.assert_allclose(out2, exp, rtol=1e-5)


def test_kl_div():
    p = np.array([[0.2, 0.3, 0.5]], "float32")
    q = np.array([[0.3, 0.3, 0.4]], "float32")
    out = _np(L.KLDivLoss(from_logits=False)(
        nd.array(np.log(q)), nd.array(p)))  # pred=log-space input
    assert out.shape == (1,) and np.isfinite(out).all()


def test_huber():
    pred = nd.array(np.array([[0.0, 3.0]], "float32"))
    label = nd.array(np.array([[0.5, 0.0]], "float32"))
    out = _np(L.HuberLoss(rho=1.0)(pred, label))
    exp = (0.5 * 0.5 ** 2 + (3.0 - 0.5)) / 2
    np.testing.assert_allclose(out, [exp], rtol=1e-5)


def test_hinge_losses():
    pred = nd.array(np.array([[0.3]], "float32"))
    label = nd.array(np.array([[1.0]], "float32"))
    np.testing.assert_allclose(_np(L.HingeLoss()(pred, label)), [0.7],
                               rtol=1e-5)
    np.testing.assert_allclose(_np(L.SquaredHingeLoss()(pred, label)),
                               [0.49], rtol=1e-4)
    np.testing.assert_allclose(
        _np(L.LogisticLoss()(pred, label)),
        [np.log(1 + np.exp(-0.3))], rtol=1e-4)


def test_triplet_and_cosine():
    a = nd.array(np.array([[1.0, 0.0]], "float32"))
    p = nd.array(np.array([[1.0, 0.1]], "float32"))
    n = nd.array(np.array([[-1.0, 0.0]], "float32"))
    t = _np(L.TripletLoss(margin=1.0)(a, p, n))
    assert t.shape == (1,) and t[0] >= 0
    y = nd.array(np.array([1.0], "float32"))
    c = _np(L.CosineEmbeddingLoss()(a, p, y))
    # 1 - cos(a, p), cos close to 1 -> small loss
    assert c[0] < 0.1


def test_poisson_nll():
    pred = nd.array(np.array([[1.0, 2.0]], "float32"))
    target = nd.array(np.array([[1.0, 1.0]], "float32"))
    out = _np(L.PoissonNLLLoss(from_logits=True)(pred, target))
    exp = (np.exp([1.0, 2.0]) - np.array([1.0, 1.0]) * np.array(
        [1.0, 2.0])).mean()
    np.testing.assert_allclose(out, [exp], rtol=1e-5)


def test_ctc_loss_shape():
    # [B, T, C] activations, labels [B, L]
    pred = nd.array(np.random.RandomState(0).rand(2, 8, 5)
                    .astype("float32"))
    label = nd.array(np.array([[1, 2, 3, -1], [2, 2, -1, -1]], "float32"))
    out = _np(L.CTCLoss(layout="NTC")(pred, label))
    assert out.shape == (2,) and (out > 0).all()


def test_weight_and_sample_weight():
    pred = nd.array(np.ones((2, 2), "float32"))
    label = nd.array(np.zeros((2, 2), "float32"))
    base = _np(L.L2Loss()(pred, label))
    np.testing.assert_allclose(_np(L.L2Loss(weight=2.0)(pred, label)),
                               base * 2, rtol=1e-6)
    sw = nd.array(np.array([[1.0], [0.0]], "float32"))
    out = _np(L.L2Loss()(pred, label, sw))
    np.testing.assert_allclose(out[1], 0.0)
    np.testing.assert_allclose(out[0], base[0], rtol=1e-6)


@pytest.mark.parametrize("loss_cls,out_dim", [
    (L.L2Loss, 1), (L.L1Loss, 1), (L.HuberLoss, 1),
])
def test_regression_losses_converge(loss_cls, out_dim):
    rs = np.random.RandomState(0)
    X = rs.rand(64, 4).astype("float32")
    Y = X.sum(1, keepdims=True)
    net = gluon.nn.Dense(out_dim)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.05})
    fn = loss_cls()
    first = last = None
    for _ in range(60):
        with autograd.record():
            l = fn(net(nd.array(X)), nd.array(Y))
        l.backward()
        tr.step(64)
        v = float(l.mean().asscalar())
        first = v if first is None else first
        last = v
    assert last < first * 0.5, (loss_cls.__name__, first, last)
