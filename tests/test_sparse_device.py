"""Device-native row-sparse path tests (VERDICT r1 missing #5/weak #7):
on-device index/value extraction and kvstore wire bytes that scale with
touched rows, not vocab. Ref: src/kvstore/kvstore_dist.h:522
EncodeRowSparseKey; src/operator/tensor/sparse_retain.cc."""
import numpy as onp
import jax

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.ndarray.sparse import RowSparseNDArray, row_sparse_array


class TestDeviceNativeSparse:
    def test_indices_are_device_arrays(self):
        dense = onp.zeros((10, 4), "float32")
        dense[[2, 7]] = 1.0
        rs = mx.nd.sparse.cast_storage(mx.nd.array(dense), "row_sparse")
        idx = rs.indices
        # the index array lives on device (jax array), not host numpy
        assert isinstance(idx._data, jax.Array)
        assert idx.asnumpy().tolist() == [2, 7]
        vals = rs.data
        assert isinstance(vals._data, jax.Array)
        assert vals.shape == (2, 4)

    def test_wire_nbytes(self):
        dense = onp.zeros((1000, 16), "float32")
        dense[[5, 17, 500]] = 1.0
        rs = mx.nd.sparse.cast_storage(mx.nd.array(dense), "row_sparse")
        # 3 rows x 16 f32 + 3 int32 ids << 1000 x 16 f32
        assert rs.wire_nbytes == 3 * 16 * 4 + 3 * 4
        assert rs.wire_nbytes < rs.nbytes / 100

    def test_retain_on_device(self):
        rs = row_sparse_array(
            (onp.ones((3, 2), "float32"), onp.array([1, 4, 6])),
            shape=(8, 2))
        kept = rs.retain(mx.nd.array(onp.array([4, 6])))
        got = kept.asnumpy()
        assert got[4].tolist() == [1, 1] and got[6].tolist() == [1, 1]
        assert got[1].tolist() == [0, 0]

    def test_row_sparse_array_device_scatter(self):
        vals = mx.nd.array(onp.arange(6, dtype="float32").reshape(2, 3))
        idx = mx.nd.array(onp.array([1, 3], "int64"))
        rs = row_sparse_array((vals, idx), shape=(5, 3))
        dense = rs.asnumpy()
        onp.testing.assert_array_equal(dense[1], [0, 1, 2])
        onp.testing.assert_array_equal(dense[3], [3, 4, 5])
        assert dense[0].sum() == 0


class TestKVStoreSparseWire:
    def test_push_accounts_sparse_bytes(self):
        kv = mx.kv.create("local")
        V, D = 5000, 32
        kv.init(0, mx.nd.zeros((V, D)))
        dense = onp.zeros((V, D), "float32")
        dense[[3, 99, 1234]] = 0.5
        rs = mx.nd.sparse.cast_storage(mx.nd.array(dense), "row_sparse")
        kv.bytes_pushed = 0
        kv.push(0, rs)
        assert kv.bytes_pushed == 3 * D * 4 + 3 * 4
        # a dense push of the same grad would cost the vocab
        kv.bytes_pushed = 0
        kv.push(0, mx.nd.array(dense))
        assert kv.bytes_pushed == V * D * 4

    def test_row_sparse_pull_accounts_rows(self):
        kv = mx.kv.create("local")
        V, D = 1000, 8
        kv.init(1, mx.nd.array(
            onp.random.RandomState(0).rand(V, D).astype("float32")))
        out = mx.nd.sparse.zeros("row_sparse", (V, D))
        rids = mx.nd.array(onp.array([7, 42], "int64"))
        kv.bytes_pulled = 0
        kv.row_sparse_pull(1, out=out, row_ids=rids)
        assert kv.bytes_pulled == 2 * D * 4 + int(rids.nbytes)
        # the pulled rows match the store
        store = kv._store[1].asnumpy()
        got = out.asnumpy()
        onp.testing.assert_allclose(got[7], store[7])
        onp.testing.assert_allclose(got[42], store[42])
        assert got[0].sum() == 0


class TestEmbeddingSparseGrad:
    def test_pushed_bytes_scale_with_touched_rows(self):
        """Embedding-heavy train step: wire bytes ~ touched rows, not
        vocab (the VERDICT 'done' criterion)."""
        V, D, B = 10000, 16, 8
        emb = gluon.nn.Embedding(V, D, sparse_grad=True)
        emb.initialize()
        kv = mx.kv.create("local")
        trainer = gluon.Trainer(emb.collect_params(), "sgd",
                                {"learning_rate": 0.1}, kvstore=kv,
                                update_on_kvstore=False)
        tokens = mx.nd.array(onp.array([1, 5, 9, 1, 5, 2, 7, 3],
                                       "float32"))
        with autograd.record():
            out = emb(tokens)
            loss = (out * out).sum()
        loss.backward()
        kv.bytes_pushed = 0
        trainer.step(B)
        touched = 6  # unique tokens {1,2,3,5,7,9}
        dense_cost = V * D * 4
        assert kv.bytes_pushed <= touched * (D * 4 + 8) * 2
        assert kv.bytes_pushed < dense_cost / 50, \
            (kv.bytes_pushed, dense_cost)

    def test_sparse_grad_training_converges(self):
        V, D = 50, 4
        emb = gluon.nn.Embedding(V, D, sparse_grad=True)
        emb.initialize()
        dense_ref = gluon.nn.Embedding(V, D, sparse_grad=False)
        dense_ref.initialize()
        # same init
        dense_ref.weight.set_data(emb.weight.data())
        tokens = mx.nd.array(onp.array([0, 1, 2, 3], "float32"))
        target = mx.nd.array(
            onp.random.RandomState(0).rand(4, D).astype("float32"))

        def train(net):
            kv = mx.kv.create("local")
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.5}, kvstore=kv,
                               update_on_kvstore=False)
            losses = []
            for _ in range(20):
                with autograd.record():
                    l = ((net(tokens) - target) ** 2).sum()
                l.backward()
                tr.step(4)
                losses.append(float(l.asnumpy()))
            return losses

        ls = train(emb)
        ld = train(dense_ref)
        assert ls[-1] < ls[0] * 0.05
        # sparse and dense paths produce identical numerics
        onp.testing.assert_allclose(ls, ld, rtol=1e-4)
