"""Roofline/MFU attribution plane (ISSUE 17;
mxnet_tpu/_debug/perfmodel.py).

Five halves:

* the drain-time join — modeled compile costs vs measured step
  durations per signature: exact MFU math, one roofline verdict per
  bound, membw utilization and arithmetic intensity;
* the efficiency-collapse detector — latch semantics (ONE dump per
  episode, re-arm on the first clean step) and the window-exclusion
  invariant (a sustained collapse cannot drag its own baseline);
* the feeds — watchdog sig passthrough into perfmodel AND the goodput
  per-signature step summary, the AOT retrace re-record (satellite 3:
  a signature flip re-records, cache-hit replay does NOT double-count
  the compile registry), the dtype-aware peak (satellite 1: f32 pins
  to the ASSUMPTIONS table, not the old bf16 hardcode), and the
  MXTPU_PERF=0/1 bitwise-identity guarantee;
* the surfaces — metrics()['perf'], Prometheus families, the dumps()
  Roofline table, metadata.perf in flight-record dumps, the perf
  block in run manifests;
* the compare CLI — exit 0 on an identical pair, 1 on a 2x slowdown,
  2 on unreadable input, and the noise floor (a relative MFU wobble
  under the absolute floor never pages).

Plus the satellite watchdog bugfix: per-signature rolling windows, so
two interleaved cadences (train + eval) never false-trip the
straggler counter against a mixed median.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, profiler
from mxnet_tpu._debug import flightrec, goodput, perfmodel, watchdog
from tools import perf_report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_RUNS_DIR", str(tmp_path / "runs"))
    goodput.reset()
    watchdog.reset()
    perfmodel.reset()
    yield
    goodput.reset()
    watchdog.reset()
    perfmodel.reset()


def _model(sig="prog:cafe0001", flops=None, bytes_accessed=None,
           comm_us=None, peak=None, dtype=None):
    name, key = sig.split(":")
    perfmodel.note_compile(
        name, key, flops=flops, bytes_accessed=bytes_accessed,
        modeled_comm_us=comm_us,
        args={"peak_tflops": peak, "dtype": dtype})


def _steps(sig, durs):
    for d in durs:
        perfmodel.note_step(sig, d)
    perfmodel.fold_pending()


def _row(sig):
    rows = {r["sig"]: r for r in perfmodel.table()}
    return rows[sig]


# -- the drain-time join -----------------------------------------------------

class TestJoin:
    def test_mfu_exact(self):
        _model("prog:a", flops=2e9, peak=98.5, dtype="f32")
        _steps("prog:a", [1e-3] * 6)
        r = _row("prog:a")
        assert r["steps"] == 6
        assert r["median_s"] == pytest.approx(1e-3)
        assert r["mfu"] == pytest.approx(2e9 / (1e-3 * 98.5e12),
                                         rel=1e-9)
        assert r["dtype"] == "f32" and r["peak_tflops"] == 98.5

    def test_intensity_and_membw(self):
        _model("prog:b", flops=4e9, bytes_accessed=2e8, peak=197.0)
        _steps("prog:b", [1e-3] * 4)
        r = _row("prog:b")
        assert r["intensity"] == pytest.approx(4e9 / 2e8)
        # memory term = bytes / (819 GB/s); utilization = term / median
        assert r["membw_util"] == pytest.approx(
            (2e8 / 819e9) / 1e-3, rel=1e-6)

    def test_bound_compute(self):
        _model("prog:c", flops=1e12, peak=100.0)  # t_compute = 10ms
        _steps("prog:c", [0.011] * 4)
        assert _row("prog:c")["bound"] == "compute"

    def test_bound_memory(self):
        _model("prog:m", flops=1e6, bytes_accessed=8.19e9,
               peak=100.0)  # t_mem = 10ms at the 819 GB/s assumption
        _steps("prog:m", [0.011] * 4)
        assert _row("prog:m")["bound"] == "memory"

    def test_bound_comm(self):
        _model("prog:n", flops=1e6, peak=100.0, comm_us=10000.0)
        _steps("prog:n", [0.012] * 4)
        assert _row("prog:n")["bound"] == "comm"

    def test_bound_overhead(self):
        _model("prog:o", flops=1e6, peak=100.0)  # floor ~ 10ns
        _steps("prog:o", [0.01] * 4)
        r = _row("prog:o")
        assert r["bound"] == "overhead"
        assert r["terms_s"]["overhead"] == pytest.approx(0.01,
                                                         rel=1e-3)

    def test_terms_decompose_to_measured(self):
        _model("prog:d", flops=5e11, bytes_accessed=1e9, peak=100.0,
               comm_us=2000.0)
        _steps("prog:d", [0.01] * 4)
        t = _row("prog:d")["terms_s"]
        floor = max(t["compute"], t["memory"]) + t["comm"]
        assert floor + t["overhead"] == pytest.approx(0.01, rel=1e-6)

    def test_unjoined_measured_sig_has_no_verdict(self):
        _steps("prog:ghost", [1e-3] * 4)
        r = _row("prog:ghost")
        assert r["mfu"] is None and r["bound"] is None
        assert r["steps"] == 4

    def test_disabled_drops_append(self):
        perfmodel.configure(enabled=False)
        perfmodel.note_step("prog:x", 1e-3)
        perfmodel.configure(enabled=True)
        perfmodel.fold_pending()
        assert perfmodel.snapshot()["steps"] == 0


# -- the efficiency-collapse detector ----------------------------------------

class TestCollapse:
    def _arm(self, sig="prog:cl"):
        _model(sig, flops=2e9, peak=98.5)
        _steps(sig, [1e-3] * 6)  # min_samples=5 default: armed
        return sig

    def test_trip_counts_and_latches_one_dump(self):
        sig = self._arm()
        base = perfmodel.snapshot()["collapse_dumps"]
        _steps(sig, [0.01, 0.01, 0.01])  # sustained 10x slowdown
        s = perfmodel.snapshot()
        assert s["collapses"] == 3
        # latched: ONE dump for the whole episode
        assert s["collapse_dumps"] == base + 1

    def test_collapsed_steps_stay_out_of_windows(self):
        sig = self._arm()
        _steps(sig, [0.01] * 10)
        r = _row(sig)
        # the baseline median never absorbed the collapsed durations —
        # a sustained collapse cannot self-heal the alarm
        assert r["median_s"] == pytest.approx(1e-3)
        assert r["collapses"] == 10

    def test_clean_step_rearms_for_next_episode(self):
        sig = self._arm()
        _steps(sig, [0.01])          # episode 1: dump
        _steps(sig, [1e-3] * 2)      # clean: re-arm
        _steps(sig, [0.01])          # episode 2: new dump
        s = perfmodel.snapshot()
        assert s["collapses"] == 2
        assert s["collapse_dumps"] == 2

    def test_dump_names_signature_and_grown_term(self, tmp_path,
                                                 monkeypatch):
        monkeypatch.setenv("MXTPU_FLIGHTREC_DIR", str(tmp_path))
        sig = self._arm()
        _steps(sig, [0.01])
        dumps = [p for p in os.listdir(tmp_path) if "perf" in p]
        assert len(dumps) == 1
        data = json.load(open(os.path.join(tmp_path, dumps[0])))
        info = data["metadata"]["trigger_info"]
        assert info["signature"] == sig
        assert info["grew"] == "overhead"  # modeled terms are fixed
        assert info["measured_s"] == pytest.approx(0.01)
        assert info["baseline_median_s"] == pytest.approx(1e-3)

    def test_no_trip_while_warming(self):
        _model("prog:w", flops=2e9, peak=98.5)
        _steps("prog:w", [1e-3, 0.01, 1e-3])  # under min_samples
        assert perfmodel.snapshot()["collapses"] == 0


# -- the feeds ---------------------------------------------------------------

def _make_net():
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, in_units=8, activation="relu"))
        net.add(gluon.nn.Dense(1, in_units=16))
    net.initialize(mx.init.Uniform(0.1))
    net.hybridize()
    return net


def _fused(net, n=3, batch=4, seed=0):
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.01})
    l2 = gluon.loss.L2Loss()
    step = gluon.train_step(net, lambda o, t: l2(o, t), tr)
    rs = np.random.RandomState(seed)
    x = mx.nd.array(rs.rand(batch, 8).astype("float32"))
    y = mx.nd.array(rs.rand(batch, 1).astype("float32"))
    for _ in range(n):
        step(x, y, batch_size=batch)
    return step, x, y


class TestFeeds:
    def test_watchdog_sig_passthrough(self):
        _model("fs:1234", flops=1e9, peak=98.5)
        goodput.open_run(run_id="feed")
        for _ in range(4):
            watchdog.step_begin()
            watchdog.step_end(mode="fused", sig="fs:1234")
        perfmodel.fold_pending()
        r = _row("fs:1234")
        assert r["steps"] == 4 and r["mfu"] is not None
        m = goodput.close_run()
        sigs = m["steps"]["signatures"]
        assert sigs["fs:1234"]["count"] == 4
        assert m["perf"]["signatures"]["fs:1234"]["steps"] == 4

    def test_warmup_steps_do_not_feed(self):
        _model("fs:warm", flops=1e9, peak=98.5)
        watchdog.step_begin()
        watchdog.step_end(warmup=True, mode="compile", sig="fs:warm")
        perfmodel.fold_pending()
        assert perfmodel.snapshot()["steps"] == 0

    def test_fused_step_tags_and_joins(self):
        step, x, y = _fused(_make_net(), n=5)
        assert step.last_mode == "fused"
        perfmodel.fold_pending()
        rows = [r for r in perfmodel.table()
                if r["sig"].startswith("fused_step:")]
        assert len(rows) == 1
        r = rows[0]
        # the tag is the crc-stable form, joined against the compile
        # registry's XLA cost analysis: a real MFU comes out
        import re
        assert re.fullmatch(r"fused_step:[0-9a-f]{8}", r["sig"])
        assert r["mfu"] is not None and r["mfu"] > 0
        assert r["dtype"] == "f32"

    def test_f32_peak_from_assumptions_table(self):
        """Satellite 1: an all-f32 net prices modeled compute against
        the 98.5 TFLOPs f32 peak, not the old bf16 197.0 hardcode."""
        _fused(_make_net(), n=3)
        st = profiler.compile_stats()["fused_step"]
        assert st["flops"] > 0
        assert st["modeled_compute_us"] == pytest.approx(
            st["flops"] / (98.5 * 1e12) * 1e6, rel=1e-6)
        perfmodel.fold_pending()
        r = [r for r in perfmodel.table()
             if r["sig"].startswith("fused_step:")][0]
        assert r["peak_tflops"] == 98.5

    def test_retrace_rerecords_and_cache_hits_do_not(self):
        """Satellite 3: a signature flip (new batch shape) re-records
        the compile registry; cache-hit replay never double-counts."""
        net = _make_net()
        step, x, y = _fused(net, n=4)
        before = profiler.compile_stats()["fused_step"]["count"]
        for _ in range(5):  # pure cache hits
            step(x, y, batch_size=4)
        assert profiler.compile_stats()["fused_step"]["count"] == before
        rs = np.random.RandomState(1)
        x2 = mx.nd.array(rs.rand(6, 8).astype("float32"))
        y2 = mx.nd.array(rs.rand(6, 1).astype("float32"))
        for _ in range(3):  # new avals: one retrace, then hits
            step(x2, y2, batch_size=6)
        after = profiler.compile_stats()["fused_step"]
        assert after["count"] == before + 1
        perfmodel.fold_pending()
        sigs = [r["sig"] for r in perfmodel.table()
                if r["sig"].startswith("fused_step:")]
        assert len(sigs) == 2  # each shape joined under its own tag

    def test_perf_toggle_is_bitwise_invisible(self):
        """MXTPU_PERF=1 training must be bitwise-identical to =0 —
        the plane observes the beacon, it never touches the graph."""
        net_on = _make_net()
        net_off = _make_net()
        for (_, pa), (_, pb) in zip(
                sorted(net_on.collect_params().items()),
                sorted(net_off.collect_params().items())):
            pb.set_data(pa.data())
        perfmodel.configure(enabled=True)
        _fused(net_on, n=4, seed=7)
        perfmodel.configure(enabled=False)
        _fused(net_off, n=4, seed=7)
        perfmodel.configure(enabled=True)
        for (_, pa), (_, pb) in zip(
                sorted(net_on.collect_params().items()),
                sorted(net_off.collect_params().items())):
            assert np.array_equal(pa.data().asnumpy(),
                                  pb.data().asnumpy())


# -- the watchdog per-signature windows (satellite bugfix) -------------------

class TestWatchdogWindows:
    # watchdog's clock is swapped for a fake that advances only by the
    # injected duration: under full-suite load a real 1ms sleep can
    # overshoot 3x its own median and false-trip the very check this
    # class pins, so wall-clock never enters these tests

    class _Clock:
        def __init__(self):
            self.now = 1000.0

        def monotonic(self):
            return self.now

    @pytest.fixture(autouse=True)
    def _fake_clock(self, monkeypatch):
        self.clock = self._Clock()
        monkeypatch.setattr(watchdog, "time", self.clock)

    def _beat(self, dur, sig):
        watchdog.step_begin()
        self.clock.now += dur
        watchdog.step_end(mode="fused", sig=sig)

    def test_two_cadences_never_false_trip(self):
        """Interleaved train (slow) + eval (fast) steps: the old mixed
        window let the eval majority drag the median down until every
        train step read as a straggler. Per-signature windows keep
        each cadence honest: zero slow_steps."""
        watchdog.configure(factor=3.0, min_s=0.0, min_samples=3)
        for _ in range(4):
            self._beat(0.02, "fs:train")
            for _ in range(3):
                self._beat(0.001, "fs:eval")
        s = watchdog.stats()
        assert s["steps"] == 16
        assert s["slow_steps"] == 0
        assert s["sig_windows"] == 2

    def test_stall_envelope_is_slowest_armed_cadence(self):
        watchdog.configure(factor=3.0, min_s=0.0, min_samples=3)
        for _ in range(4):
            self._beat(0.02, "fs:train")
            self._beat(0.001, "fs:eval")
        thr = watchdog.threshold_s()
        # the in-flight step's signature is unknown, so the envelope
        # must cover the SLOWEST armed cadence, not the mixed median
        assert thr == pytest.approx(3.0 * 0.02, rel=0.5)
        assert thr > 3.0 * 0.005  # far above the old mixed median

    def test_own_window_still_catches_a_real_straggler(self):
        # poll_s high: the completed-step verdict, not the in-flight
        # poller (which would claim the trip first), owns this count
        watchdog.configure(factor=3.0, min_s=0.0, min_samples=3,
                           poll_s=60.0)
        for _ in range(4):
            self._beat(0.002, "fs:train")
        self._beat(0.03, "fs:train")  # 15x its OWN median
        assert watchdog.stats()["slow_steps"] == 1

    def test_reset_window_clears_all_signatures(self):
        watchdog.configure(factor=3.0, min_s=0.0, min_samples=3)
        for _ in range(4):
            self._beat(0.002, "fs:a")
        assert watchdog.stats()["sig_windows"] == 1
        watchdog.reset_window()
        assert watchdog.stats()["sig_windows"] == 0
        assert watchdog.threshold_s() is None


# -- surfaces ----------------------------------------------------------------

class TestSurfaces:
    def test_metrics_provider_keys(self):
        _model("prog:s", flops=2e9, peak=98.5)
        _steps("prog:s", [1e-3] * 4)
        m = profiler.metrics()["perf"]
        for k in ("enabled", "signatures", "steps", "collapses",
                  "collapse_dumps", "dropped_sigs", "per_signature"):
            assert k in m
        assert m["hot_signature"] == "prog:s"
        assert m["hot_bound"] == "overhead"
        assert m["per_signature"]["prog:s"]["mfu"] == pytest.approx(
            m["mfu"], abs=1e-6)  # headline rounds at 6 places
        json.dumps(m)  # JSON-safe contract

    def test_prometheus_families(self):
        _model("prog:p", flops=1e12, bytes_accessed=1e9, peak=100.0)
        _steps("prog:p", [0.011] * 4)
        prom = profiler.prometheus_text()
        assert 'mxtpu_mfu{' in prom
        assert 'signature="prog:p"' in prom
        assert 'mxtpu_membw_util' in prom
        assert 'mxtpu_roofline_bound' in prom
        assert 'bound="compute"' in prom

    def test_dumps_roofline_table(self):
        _model("prog:t", flops=2e9, peak=98.5)
        _steps("prog:t", [1e-3] * 4)
        txt = profiler.dumps()
        assert "Roofline" in txt and "prog:t" in txt

    def test_flightrec_dump_carries_perf_metadata(self, tmp_path):
        _model("prog:f", flops=2e9, peak=98.5)
        _steps("prog:f", [1e-3] * 4)
        shard = str(tmp_path / "shard.json")
        flightrec.dump("manual", path=shard)
        data = json.load(open(shard))
        p = data["metadata"]["perf"]
        assert p["per_signature"]["prog:f"]["steps"] == 4

    def test_manifest_block_absent_without_join(self):
        goodput.open_run(run_id="nojoin")
        m = goodput.close_run()
        assert "perf" not in m

    def test_bench_manifest_carries_perf_block(self):
        _model("prog:bm", flops=2e9, peak=98.5)
        _steps("prog:bm", [1e-3] * 4)
        path = goodput.write_bench_manifest(
            "train_step", {"metric": "train_step_steps_per_sec",
                           "value": 100.0, "gate": {"ok": True}})
        m = goodput.load_manifest(path)
        assert m["perf"]["schema"] == "mxtpu.perf/1"
        assert "prog:bm" in m["perf"]["signatures"]
        assert m["perf"]["assumptions"]["hbm_bw_GBps"] == 819.0


# -- the compare CLI ---------------------------------------------------------

def _manifest(tmp, name, median_s=0.01, mfu=0.4, bound="compute",
              perf=True):
    m = {"schema": "mxtpu.goodput.run/1", "run_id": name,
         "outcome": "completed"}
    if perf:
        m["perf"] = {"schema": "mxtpu.perf/1", "signatures": {
            "fused_step:cafef00d": {
                "steps": 100, "median_s": median_s, "mfu": mfu,
                "bound": bound}}}
    p = os.path.join(str(tmp), name + ".json")
    with open(p, "w") as f:
        json.dump(m, f)
    return p


class TestCompareCLI:
    def test_identical_pair_passes(self, tmp_path):
        a = _manifest(tmp_path, "a")
        b = _manifest(tmp_path, "b")
        assert perf_report.main(["--compare", a, b]) == 0

    def test_2x_slowdown_flagged(self, tmp_path, capsys):
        a = _manifest(tmp_path, "a")
        b = _manifest(tmp_path, "b", median_s=0.02, mfu=0.2)
        assert perf_report.main(["--compare", a, b]) == 1
        out = capsys.readouterr().out
        assert "verdict: REGRESSION" in out

    def test_mfu_drop_needs_relative_and_absolute(self, tmp_path):
        """A 33% wobble on a 0.003 MFU microbench is under the 0.02
        absolute floor — never a page."""
        a = _manifest(tmp_path, "a", mfu=0.003)
        b = _manifest(tmp_path, "b", mfu=0.002)
        assert perf_report.main(["--compare", a, b]) == 0

    def test_bound_move_noted_not_gated(self, tmp_path, capsys):
        a = _manifest(tmp_path, "a", bound="compute")
        b = _manifest(tmp_path, "b", bound="overhead")
        assert perf_report.main(["--compare", a, b]) == 0
        assert "bound moved" in capsys.readouterr().out

    def test_render_single_run(self, tmp_path, capsys):
        a = _manifest(tmp_path, "a")
        assert perf_report.main([a]) == 0
        out = capsys.readouterr().out
        assert "fused_step:cafef00d" in out and "compute" in out

    def test_unreadable_and_schema_exit_2(self, tmp_path):
        assert perf_report.main([str(tmp_path / "nope.json")]) == 2
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        assert perf_report.main([str(bad)]) == 2
        a = _manifest(tmp_path, "a")
        assert perf_report.main(["--compare", a]) == 2

    def test_no_perf_blocks_exit_2(self, tmp_path):
        a = _manifest(tmp_path, "a", perf=False)
        b = _manifest(tmp_path, "b", perf=False)
        assert perf_report.main(["--compare", a, b]) == 2

    def test_single_sig_joins_across_retrace(self, tmp_path, capsys):
        """One signature on each side joins regardless of tag — a code
        change retraces under a new tag but is the same campaign."""
        a = _manifest(tmp_path, "a")
        b = os.path.join(str(tmp_path), "b.json")
        with open(b, "w") as f:
            json.dump({"schema": "mxtpu.goodput.run/1", "run_id": "b",
                       "outcome": "completed",
                       "perf": {"schema": "mxtpu.perf/1",
                                "signatures": {"fused_step:deadbeef": {
                                    "steps": 100, "median_s": 0.03,
                                    "mfu": 0.1,
                                    "bound": "overhead"}}}}, f)
        assert perf_report.main(["--compare", a, b]) == 1
        assert "->" in capsys.readouterr().out

    def test_cli_subprocess_entry(self, tmp_path):
        a = _manifest(tmp_path, "a")
        b = _manifest(tmp_path, "b", median_s=0.02, mfu=0.2)
        script = os.path.join(REPO, "tools", "perf_report.py")
        r = subprocess.run([sys.executable, script, "--compare", a, b],
                           capture_output=True, text=True)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "verdict: REGRESSION" in r.stdout
