"""Parallelism tests on the 8-device virtual CPU mesh.

Blueprint per SURVEY.md §4 "distributed tests without a real cluster": the
reference runs dist kvstore tests as local processes
(ci/docker/runtime_functions.sh:1281); here the mesh itself is the cluster
and shardings are validated by exact-numerics comparison against the
unsharded computation — the same check_consistency idea
(python/mxnet/test_utils.py:1314) across parallelism modes instead of
devices.
"""
import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel import (create_mesh, data_parallel, fsdp,
                                tensor_parallel, ring_self_attention,
                                ulysses_attention, ShardedTrainStep,
                                functional_call, extract_params)
from mxnet_tpu.parallel.ring_attention import blockwise_attention
from mxnet_tpu.parallel import transformer as T


def _dense_attention(q, k, v, causal=False):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.fixture(scope="module")
def qkv():
    key = jr.PRNGKey(0)
    ks = jr.split(key, 3)
    shape = (2, 4, 32, 8)  # [B, H, S, D]
    return tuple(jr.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(qkv, causal):
    q, k, v = qkv
    mesh = create_mesh(dp=2, tp=2, sp=2)
    want = _dense_attention(q, k, v, causal)
    with mesh.mesh:
        got = ring_self_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(qkv, causal):
    q, k, v = qkv
    mesh = create_mesh(dp=2, tp=2, sp=2)
    want = _dense_attention(q, k, v, causal)
    with mesh.mesh:
        got = ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_dense(qkv, causal):
    q, k, v = qkv
    want = _dense_attention(q, k, v, causal)
    got = blockwise_attention(q, k, v, block_size=8, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def _tiny_cfg(**kw):
    base = dict(vocab_size=64, dim=16, n_layers=2, n_heads=4, ffn_hidden=32)
    base.update(kw)
    return T.TransformerConfig(**base)


def test_transformer_ring_matches_local():
    """Same params, sharded-ring vs single-device local attention."""
    key = jr.PRNGKey(3)
    toks = jr.randint(jr.PRNGKey(4), (4, 16), 0, 64)
    cfg_local = _tiny_cfg(attn_mode="local")
    params = T.init_params(key, cfg_local)
    want = T.apply(params, toks, cfg_local)

    mesh = create_mesh(dp=2, tp=2, sp=2)
    cfg_ring = _tiny_cfg(attn_mode="ring")
    with mesh.mesh:
        got = T.apply(params, toks, cfg_ring, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-4, atol=5e-5)


def test_pipeline_matches_gspmd():
    """Explicit pp=2 pipeline produces the same loss as the pp=1 path."""
    key = jr.PRNGKey(5)
    toks = jr.randint(jr.PRNGKey(6), (4, 16), 0, 64)
    tgts = jr.randint(jr.PRNGKey(7), (4, 16), 0, 64)

    cfg1 = _tiny_cfg(attn_mode="local")
    params1 = T.init_params(key, cfg1)
    want = T.loss_fn(params1, toks, tgts, cfg1)

    cfg2 = _tiny_cfg(pp=2, n_microbatch=2)
    mesh = create_mesh(pp=2, dp=2, sp=2)
    params2 = T.init_params(key, cfg2)  # same weights, stacked [pp, L/pp]
    init_fn, step_fn = T.make_train_step(cfg2, mesh)
    with mesh.mesh:
        from mxnet_tpu.parallel import shard_map
        from jax.sharding import PartitionSpec as P
        specs = T.param_specs(cfg2)
        loss = shard_map(
            lambda ps, tk, tg: T._pipeline_loss_local(cfg2, ps, tk, tg),
            mesh=mesh.mesh,
            in_specs=(specs, P("dp", "sp"), P("dp", "sp")),
            out_specs=P(), check_vma=False)(params2, toks, tgts)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-4)


def test_transformer_train_step_decreases_loss():
    mesh = create_mesh(dp=2, tp=2, sp=2)
    cfg = _tiny_cfg(attn_mode="ring")
    init_fn, step_fn = T.make_train_step(cfg, mesh, learning_rate=0.1)
    toks = jr.randint(jr.PRNGKey(8), (4, 16), 0, 64)
    tgts = jr.randint(jr.PRNGKey(9), (4, 16), 0, 64)
    with mesh.mesh:
        state = init_fn(jr.PRNGKey(0))
        state, loss0 = step_fn(state, toks, tgts)  # donates state buffers
        for _ in range(5):
            state, loss = step_fn(state, toks, tgts)
    assert float(loss) < float(loss0)


def test_moe_train_step_runs():
    mesh = create_mesh(dp=2, ep=2, tp=2)
    cfg = _tiny_cfg(num_experts=4, attn_mode="local")
    init_fn, step_fn = T.make_train_step(cfg, mesh)
    toks = jr.randint(jr.PRNGKey(8), (4, 16), 0, 64)
    with mesh.mesh:
        state = init_fn(jr.PRNGKey(0))
        state, loss = step_fn(state, toks, toks)
    assert np.isfinite(float(loss))


def test_sharded_train_step_gluon_dp():
    """Gluon net + mxnet optimizer through one pjit'd DP step."""
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    import mxnet_tpu.optimizer as opt

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=20))
    net.add(nn.Dense(10, in_units=32))
    net.initialize()

    mesh = create_mesh(dp=8)
    step = ShardedTrainStep(net, SoftmaxCrossEntropyLoss(),
                            opt.create("sgd", learning_rate=0.1,
                                       momentum=0.9),
                            strategy=data_parallel(mesh))
    x = np.random.rand(16, 20).astype("float32")
    y = np.random.randint(0, 10, (16,)).astype("float32")
    losses = [step(x, y) for _ in range(8)]
    assert losses[-1] < losses[0]
    step.sync_to_block()  # params flow back into the Block


def test_sharded_train_step_fsdp():
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.loss import L2Loss
    import mxnet_tpu.optimizer as opt

    net = nn.Dense(8, in_units=64)
    net.initialize()
    mesh = create_mesh(dp=2, fsdp=4)
    strat = fsdp(mesh, min_size=64)
    step = ShardedTrainStep(net, L2Loss(), opt.create("adam",
                                                      learning_rate=0.01),
                            strategy=strat)
    x = np.random.rand(8, 64).astype("float32")
    y = np.random.rand(8, 8).astype("float32")
    l0 = step(x, y)
    for _ in range(5):
        l1 = step(x, y)
    assert l1 < l0
    # weight (8, 64): fsdp axis must actually shard dim 1
    sh = step.params["weight"].sharding.spec
    assert "fsdp" in str(sh)


def test_functional_call_matches_eager():
    from mxnet_tpu.gluon import nn
    net = nn.Dense(4, in_units=3)
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 3).astype("float32"))
    want = net(x).asnumpy()
    params = extract_params(net)
    got = functional_call(net, params, [x])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_collectives_shard_map():
    from mxnet_tpu.parallel import shard_map
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import all_reduce, reduce_scatter, ring_exchange
    mesh = create_mesh(dp=8)
    x = jnp.arange(16.0).reshape(8, 2)

    def body(x):
        return all_reduce(x, "dp")

    with mesh.mesh:
        got = shard_map(body, mesh=mesh.mesh, in_specs=P("dp"),
                        out_specs=P("dp"), check_vma=False)(x)
    want = np.tile(x.sum(0, keepdims=True), (8, 1))
    np.testing.assert_allclose(np.asarray(got), want)


def test_pipeline_embed_grad_synced_across_stages():
    """Regression: replicated embed/w_out grads must psum over 'pp' — only
    one stage touches them, others contribute zero."""
    mesh = create_mesh(pp=2, dp=2, sp=2)
    cfg = _tiny_cfg(pp=2, n_microbatch=2)
    init_fn, step_fn = T.make_train_step(cfg, mesh, learning_rate=0.1)
    toks = jr.randint(jr.PRNGKey(0), (4, 16), 0, 64)
    with mesh.mesh:
        state = init_fn(jr.PRNGKey(1))
        state, _ = step_fn(state, toks, toks)
    embed = state[0]["embed"]
    shards = [np.asarray(s.data) for s in embed.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_allclose(s, shards[0], rtol=1e-6, atol=1e-7)


def test_moe_aux_loss_in_objective():
    """Regression: load-balance aux loss must reach the training loss."""
    cfg = _tiny_cfg(num_experts=4, attn_mode="local")
    params = T.init_params(jr.PRNGKey(0), cfg)
    toks = jr.randint(jr.PRNGKey(1), (2, 8), 0, 64)
    l_with = float(T.loss_fn(params, toks, toks, cfg, aux_weight=1.0))
    l_without = float(T.loss_fn(params, toks, toks, cfg, aux_weight=0.0))
    assert l_with != l_without


def test_fsdp_accepts_raw_mesh():
    from jax.sharding import PartitionSpec as P
    mesh = create_mesh(dp=2, fsdp=4)
    strat = fsdp(mesh.mesh, min_size=16)  # raw jax Mesh, not DeviceMesh
    spec = strat.param_rules.spec_for("weight", (8, 64))
    assert spec == P(None, "fsdp")


@pytest.mark.slow
def test_realistic_shapes_dp_tp_sp_train_step():
    """Non-trivial block sizes (dim 256, seq 512) on the 8-device CPU
    mesh — sharding arithmetic errors that only trigger past the tiny
    dryrun shapes (VERDICT r1 weak #8) surface here, before real
    hardware. One full train step; loss must be finite."""
    cfg = T.TransformerConfig(vocab_size=512, dim=256, n_layers=2,
                              n_heads=8, ffn_hidden=512, max_seq_len=512,
                              attn_mode="ring")
    mesh = create_mesh(dp=2, tp=2, sp=2)
    init_fn, step_fn = T.make_train_step(cfg, mesh)
    with mesh.mesh:
        state = init_fn(jr.PRNGKey(0))
        toks = jr.randint(jr.PRNGKey(1), (4, 512), 0, 512)
        state, loss = step_fn(state, toks, toks)
        assert np.isfinite(float(loss)), float(loss)


@pytest.mark.slow
def test_realistic_shapes_pipeline():
    """GPipe pp=2 at dim 256 / seq 512 on the CPU mesh."""
    cfg = T.TransformerConfig(vocab_size=512, dim=256, n_layers=4,
                              n_heads=8, ffn_hidden=512, max_seq_len=512,
                              pp=2, n_microbatch=2, attn_mode="local")
    mesh = create_mesh(pp=2, dp=2, sp=2)
    init_fn, step_fn = T.make_train_step(cfg, mesh)
    with mesh.mesh:
        state = init_fn(jr.PRNGKey(0))
        toks = jr.randint(jr.PRNGKey(1), (4, 512), 0, 512)
        state, loss = step_fn(state, toks, toks)
        assert np.isfinite(float(loss)), float(loss)


class TestRingFlash:
    """ring x flash composition (parallel/ring_flash.py): per-hop Pallas
    blocks (interpret mode on CPU) vs dense full attention, forward and
    gradients."""

    def _data(self, B=1, H=2, S=64, D=32, seed=0):
        import numpy as onp
        rs = onp.random.RandomState(seed)
        mk = lambda s: jnp.asarray(rs.randn(B, H, S, D).astype("float32"))  # noqa: E731
        return mk(0), mk(1), mk(2)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        from mxnet_tpu.parallel.ring_flash import ring_flash_self_attention
        from mxnet_tpu.pallas_kernels.flash_attention import \
            attention_reference
        q, k, v = self._data()
        mesh = create_mesh(sp=4)
        got = ring_flash_self_attention(q, k, v, mesh, causal=causal,
                                        batch_axis=None, head_axis=None,
                                        interpret=True)
        want = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)

    def test_bf16_matches_dense(self):
        """f32 hop accumulator: bf16 ring output stays at the dense
        reference's rounding level even with 8 hops."""
        from mxnet_tpu.parallel.ring_flash import ring_flash_self_attention
        from mxnet_tpu.pallas_kernels.flash_attention import \
            attention_reference
        q, k, v = self._data(S=64)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        mesh = create_mesh(sp=8)
        got = ring_flash_self_attention(qb, kb, vb, mesh, causal=True,
                                        batch_axis=None, head_axis=None,
                                        interpret=True)
        want = attention_reference(q, k, v, causal=True)
        err = np.abs(np.asarray(got, np.float32) - np.asarray(want)).max()
        assert err < 0.03, err

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_dense(self, causal):
        from mxnet_tpu.parallel.ring_flash import ring_flash_self_attention
        from mxnet_tpu.pallas_kernels.flash_attention import \
            attention_reference
        q, k, v = self._data()
        mesh = create_mesh(sp=4)

        def ring_loss(a, b, c):
            out = ring_flash_self_attention(a, b, c, mesh, causal=causal,
                                            batch_axis=None,
                                            head_axis=None,
                                            interpret=True)
            return (out.astype(jnp.float32) ** 2).sum()

        def dense_loss(a, b, c):
            return (attention_reference(
                a, b, c, causal=causal).astype(jnp.float32) ** 2).sum()

        g_ring = jax.grad(ring_loss, (0, 1, 2))(q, k, v)
        g_dense = jax.grad(dense_loss, (0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_ring, g_dense):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3,
                err_msg="d%s mismatch" % name)


def test_transformer_ring_flash_matches_local():
    """attn_mode='ring_flash' end-to-end in the transformer vs the
    unsharded local path."""
    key = jr.PRNGKey(3)
    toks = jr.randint(jr.PRNGKey(4), (4, 16), 0, 64)
    cfg_local = _tiny_cfg(attn_mode="local")
    params = T.init_params(key, cfg_local)
    want = T.apply(params, toks, cfg_local)
    mesh = create_mesh(dp=2, tp=2, sp=2)
    cfg_rf = _tiny_cfg(attn_mode="ring_flash")
    with mesh.mesh:
        got = T.apply(params, toks, cfg_rf, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-4, atol=5e-4)


def test_chunked_loss_matches_unchunked():
    """loss_chunks>1 must be numerically identical to the full-logits
    path (the [B,S,V] tensor never materializes; bench batch-8 enabler)."""
    cfg_a = _tiny_cfg()
    cfg_b = _tiny_cfg(loss_chunks=4)
    params = T.init_params(jr.PRNGKey(0), cfg_a)
    toks = jr.randint(jr.PRNGKey(1), (2, 16), 0, 64)
    tgts = jr.randint(jr.PRNGKey(2), (2, 16), 0, 64)
    la = T.loss_fn(params, toks, tgts, cfg_a)
    lb = T.loss_fn(params, toks, tgts, cfg_b)
    assert abs(float(la) - float(lb)) < 1e-5
    # gradients agree too
    ga = jax.grad(lambda p: T.loss_fn(p, toks, tgts, cfg_a))(params)
    gb = jax.grad(lambda p: T.loss_fn(p, toks, tgts, cfg_b))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                 np.asarray(b),
                                                 rtol=2e-4, atol=2e-5),
        ga, gb)


def test_selective_remat_matches_full():
    """remat_save=("ffn_prod",) changes memory planning, not numerics."""
    cfg_a = _tiny_cfg()
    cfg_b = _tiny_cfg(remat_save=("ffn_prod",))
    params = T.init_params(jr.PRNGKey(0), cfg_a)
    toks = jr.randint(jr.PRNGKey(3), (2, 16), 0, 64)
    tgts = jr.randint(jr.PRNGKey(4), (2, 16), 0, 64)
    ga = jax.grad(lambda p: T.loss_fn(p, toks, tgts, cfg_a))(params)
    gb = jax.grad(lambda p: T.loss_fn(p, toks, tgts, cfg_b))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                 np.asarray(b),
                                                 rtol=2e-4, atol=2e-5),
        ga, gb)


def test_flash_block_defaults_table():
    """Per-shape default blocks come from the measured table and clamp
    to the sequence length."""
    from mxnet_tpu.pallas_kernels.flash_attention import _default_blocks
    assert _default_blocks(2048) == (1024, 1024)
    assert _default_blocks(8192) == (1024, 1024)
    bq, bk = _default_blocks(64)
    assert bq <= 512 and bk <= 512
