"""Symbol-level control flow (sym.contrib.foreach/while_loop/cond).

Mirrors the reference's tests/python/unittest/test_contrib_control_flow.py
coverage for the symbolic API (ref: src/operator/control_flow.cc:1089
_foreach, :1150 _while_loop, :1211 _cond), lowered here to
lax.scan/while_loop/cond inside the bound XLA program.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym


def test_foreach_cumsum():
    data = sym.var('data')
    state = sym.var('state')
    outs, states = sym.contrib.foreach(
        lambda d, s: (d + s[0], [d + s[0]]), data, [state])
    exe = outs.bind(args={
        'data': mx.nd.array(np.arange(6, dtype='float32').reshape(3, 2)),
        'state': mx.nd.zeros((2,))})
    r = exe.forward()[0].asnumpy()
    exp = np.cumsum(np.arange(6).reshape(3, 2), axis=0)
    np.testing.assert_allclose(r, exp)
    # final state == last row of the cumsum
    exe2 = states[0].bind(args={
        'data': mx.nd.array(np.arange(6, dtype='float32').reshape(3, 2)),
        'state': mx.nd.zeros((2,))})
    np.testing.assert_allclose(exe2.forward()[0].asnumpy(), exp[-1])


def test_foreach_closure_and_multiseq():
    d1, d2, w = sym.var('d1'), sym.var('d2'), sym.var('w')
    outs, _ = sym.contrib.foreach(
        lambda d, s: (d[0] * w + d[1], []), [d1, d2], [])
    exe = outs.bind(args={'d1': mx.nd.ones((4, 3)),
                          'd2': mx.nd.full((4, 3), 2.0),
                          'w': mx.nd.full((3,), 10.0)})
    np.testing.assert_allclose(exe.forward()[0].asnumpy(), 12.0)


def test_foreach_mismatched_lengths_raises():
    d1, d2 = sym.var('d1'), sym.var('d2')
    outs, _ = sym.contrib.foreach(lambda d, s: (d[0] + d[1], []),
                                  [d1, d2], [])
    exe = outs.bind(args={'d1': mx.nd.ones((4, 2)),
                          'd2': mx.nd.ones((3, 2))})
    with pytest.raises(Exception):
        exe.forward()[0].asnumpy()


def test_foreach_grad():
    data = sym.var('data')
    state = sym.var('state')
    outs, _ = sym.contrib.foreach(
        lambda d, s: (d + s[0], [d + s[0]]), data, [state])
    exe = outs.bind(
        args={'data': mx.nd.array(
            np.arange(6, dtype='float32').reshape(3, 2)),
            'state': mx.nd.zeros((2,))},
        args_grad={'data': mx.nd.zeros((3, 2)),
                   'state': mx.nd.zeros((2,))})
    exe.forward(is_train=True)
    exe.backward()
    # d(sum over stacked cumsum)/d data[t] = T - t
    np.testing.assert_allclose(exe.grad_dict['data'].asnumpy()[:, 0],
                               [3., 2., 1.])
    np.testing.assert_allclose(exe.grad_dict['state'].asnumpy(), [3., 3.])


def test_while_loop_sum():
    i, s = sym.var('i'), sym.var('s')
    outs, final_vars = sym.contrib.while_loop(
        cond=lambda i, s: i <= 5.0,
        func=lambda i, s: ([i], [i + 1.0, s + i]),
        loop_vars=[i, s], max_iterations=10)
    args = {'i': mx.nd.array([1.0]), 's': mx.nd.array([0.0])}
    r = outs[0].bind(args=dict(args)).forward()[0].asnumpy()
    assert r.shape == (10, 1)  # padded to max_iterations
    np.testing.assert_allclose(r[:5, 0], [1, 2, 3, 4, 5])
    np.testing.assert_allclose(r[5:], 0.0)
    fs = final_vars[1].bind(args=dict(args)).forward()[0].asnumpy()
    np.testing.assert_allclose(fs, [15.0])


def test_while_loop_never_true():
    i = sym.var('i')
    outs, final_vars = sym.contrib.while_loop(
        cond=lambda i: i < 0.0,
        func=lambda i: ([i * 2.0], [i + 1.0]),
        loop_vars=[i], max_iterations=4)
    r = outs[0].bind(args={'i': mx.nd.array([3.0])}).forward()[0].asnumpy()
    np.testing.assert_allclose(r, 0.0)  # zero-filled, zero steps ran
    fv = final_vars[0].bind(
        args={'i': mx.nd.array([3.0])}).forward()[0].asnumpy()
    np.testing.assert_allclose(fv, [3.0])


def test_cond_branches():
    a, b = sym.var('a'), sym.var('b')
    pred = (a * b).sum() < 5.0
    out = sym.contrib.cond(pred,
                           lambda: (a + 5.0) * (b + 5.0),
                           lambda: (a - 5.0) * (b - 5.0))
    v = out.bind(args={'a': mx.nd.array([1.0]),
                       'b': mx.nd.array([2.0])}).forward()[0].asnumpy()
    np.testing.assert_allclose(v, [42.0])
    v2 = out.bind(args={'a': mx.nd.array([3.0]),
                        'b': mx.nd.array([4.0])}).forward()[0].asnumpy()
    np.testing.assert_allclose(v2, [(3.0 - 5.0) * (4.0 - 5.0)])


def test_control_flow_json_roundtrip():
    data = sym.var('data')
    state = sym.var('state')
    outs, _ = sym.contrib.foreach(
        lambda d, s: (d + s[0], [d + s[0]]), data, [state])
    back = sym.load_json(outs.tojson())
    assert back.list_arguments() == outs.list_arguments()
    x = np.arange(6, dtype='float32').reshape(3, 2)
    r = back.bind(args={'data': mx.nd.array(x),
                        'state': mx.nd.zeros((2,))}).forward()[0].asnumpy()
    np.testing.assert_allclose(r, np.cumsum(x, axis=0))


def test_control_flow_infer_shape():
    data = sym.var('data')
    state = sym.var('state')
    outs, states = sym.contrib.foreach(
        lambda d, s: (d + s[0], [d + s[0]]), data, [state])
    _, out_shapes, _ = outs.infer_shape(data=(3, 2), state=(2,))
    assert out_shapes == [(3, 2)]
    i = sym.var('i')
    w_outs, w_vars = sym.contrib.while_loop(
        cond=lambda i: i < 3.0, func=lambda i: ([i], [i + 1.0]),
        loop_vars=[i], max_iterations=7)
    _, osh, _ = w_outs[0].infer_shape(i=(1,))
    assert osh == [(7, 1)]


def test_foreach_in_module_fit():
    """An RNN-ish scan inside a Module-bound graph trains end to end."""
    from mxnet_tpu.module import Module
    import mxnet_tpu.io as mio
    T, B, H = 4, 8, 5
    data = sym.var('data')      # (T, B, H) after transpose below
    w = sym.var('scan_w')
    h0 = sym.zeros((B, H))
    outs, states = sym.contrib.foreach(
        lambda d, s: (d, [s[0] + mx.sym.FullyConnected(
            d, weight=w, num_hidden=H, no_bias=True, name='fc_scan')]),
        mx.sym.transpose(data, axes=(1, 0, 2)), [h0])
    head = mx.sym.FullyConnected(states[0], num_hidden=2, name='out_fc')
    loss = mx.sym.SoftmaxOutput(head, name='softmax')
    rs = np.random.RandomState(0)
    X = rs.rand(32, T, H).astype('float32')
    Y = (X.sum(axis=(1, 2)) > X.sum() / 32).astype('float32')
    it = mio.NDArrayIter(X, Y, batch_size=B, label_name='softmax_label')
    mod = Module(loss, data_names=['data'],
                 label_names=['softmax_label'])
    mod.bind(data_shapes=[('data', (B, T, H))],
             label_shapes=[('softmax_label', (B,))])
    mod.init_params(mx.initializer.Xavier())
    # scan_w is a free variable INSIDE the loop body: its shape is
    # hint-inferred through the subgraph and it binds like any argument
    assert 'scan_w' in loss.list_arguments()
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1})
    for _ in range(2):
        it.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
    out = mod.get_outputs()[0].asnumpy()
    assert out.shape == (B, 2) and np.isfinite(out).all()


def test_foreach_batchnorm_aux_updates():
    """BatchNorm moving stats INSIDE a foreach body must update during
    training forwards (aux threads through the scan carry)."""
    data = sym.var('data')                      # [T, B, C]
    outs, _ = sym.contrib.foreach(
        lambda d, s: (mx.sym.BatchNorm(d, name='bn_scan', momentum=0.5),
                      []),
        data, [])
    rs = np.random.RandomState(0)
    x = (rs.rand(3, 8, 4) * 10 + 5).astype('float32')
    args = {'data': mx.nd.array(x),
            'bn_scan_gamma': mx.nd.ones((4,)),
            'bn_scan_beta': mx.nd.zeros((4,))}
    aux = {'bn_scan_moving_mean': mx.nd.zeros((4,)),
           'bn_scan_moving_var': mx.nd.ones((4,))}
    exe = outs.bind(args=args, aux_states=aux)
    exe.forward(is_train=True)
    _ = exe.outputs[0].asnumpy()  # materialize
    mm = exe.aux_dict['bn_scan_moving_mean'].asnumpy()
    assert np.abs(mm).max() > 0.1, mm  # stats moved off init
    # inference uses the updated global stats without error
    out_inf = exe.forward(is_train=False)[0].asnumpy()
    assert np.isfinite(out_inf).all()
