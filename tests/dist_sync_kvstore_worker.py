"""Worker script for multi-process dist kvstore tests
(ref: tests/nightly/dist_sync_kvstore.py — the reference launches
scheduler+servers+workers as local processes via tools/launch.py and
asserts exact numeric equality of pulled values across ranks).

Run via:  python tools/launch.py -n 3 python tests/dist_sync_kvstore_worker.py
Each rank pushes rank-dependent values; everyone must pull identical
aggregates (check_diff_to_scalar analog, dist_sync_kvstore.py:31-45).
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# join the coordinator BEFORE anything touches the XLA backend —
# the same ordering ps-lite requires of its env handshake
import jax  # noqa: E402

jax.distributed.initialize(os.environ["MXTPU_COORDINATOR"],
                           int(os.environ["MXTPU_NUM_PROCS"]),
                           int(os.environ["MXTPU_PROC_ID"]))

import numpy as onp  # noqa: E402,F401

import mxnet_tpu as mx  # noqa: E402


def check_diff_to_scalar(arr, x, rank):
    """ref: dist_sync_kvstore.py:31 — exact equality, not allclose."""
    a = arr.asnumpy()
    assert (a == x).all(), "rank %d: expected %s, got %s" % (rank, x, a)


def main():
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    assert nworker > 1, "must run under tools/launch.py -n N (N>1)"
    shape = (4, 4)

    # 1. push/pull aggregation: sum over ranks of (rank+1) = N(N+1)/2
    kv.init(3, mx.nd.zeros(shape))
    kv.push(3, mx.nd.ones(shape) * (rank + 1))
    out = mx.nd.zeros(shape)
    kv.pull(3, out=out)
    expected = nworker * (nworker + 1) / 2
    check_diff_to_scalar(out, expected, rank)

    # 2. repeated rounds stay consistent (sync semantics: every round sees
    #    exactly nworker contributions, ref: kvstore_dist_server.h:349)
    for rnd in range(3):
        kv.push(3, mx.nd.ones(shape))
        kv.pull(3, out=out)
        check_diff_to_scalar(out, nworker, rank)

    # 3. str keys + pushpull fusion
    kv.init("w0", mx.nd.zeros(shape))
    kv.pushpull("w0", mx.nd.ones(shape) * rank, out=out)
    check_diff_to_scalar(out, sum(range(nworker)), rank)

    # 4. gradient compression path across ranks
    kvc = mx.kv.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 0.5,
                                  "size_lower_bound": 0})
    kvc.init(9, mx.nd.zeros(shape))
    kvc.push(9, mx.nd.ones(shape) * 0.6)   # quantizes to +0.5 per rank
    kvc.pull(9, out=out)
    check_diff_to_scalar(out, 0.5 * nworker, rank)

    # 5. gluon Trainer over dist kvstore: after steps on rank-dependent
    #    data, weights must be bit-identical across ranks
    #    (ref: tests/nightly/dist_device_sync_kvstore.py gluon trainer case)
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon import nn
    net = nn.Dense(2, in_units=4)
    net.initialize(init=mx.init.One())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv)
    rng = onp.random.RandomState(100 + rank)  # DIFFERENT data per rank
    for _ in range(3):
        x = mx.nd.array(rng.randn(8, 4).astype("float32"))
        with autograd.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer.step(8)
    w = net.weight.data().asnumpy()
    from jax.experimental import multihost_utils
    all_w = multihost_utils.process_allgather(w)
    for r in range(nworker):
        assert (all_w[r] == all_w[0]).all(), \
            "rank %d: weights diverged across ranks" % rank

    # 6. barrier then done
    mx.parallel.host_barrier("dist-test")
    print("rank %d/%d: all dist_sync kvstore checks passed" % (rank, nworker))


if __name__ == "__main__":
    main()
