"""Worker script for multi-process dist kvstore tests
(ref: tests/nightly/dist_sync_kvstore.py — the reference launches
scheduler+servers+workers as local processes via tools/launch.py and
asserts exact numeric equality of pulled values across ranks).

Run via:  python tools/launch.py -n 3 python tests/dist_sync_kvstore_worker.py
Each rank pushes rank-dependent values; everyone must pull identical
aggregates (check_diff_to_scalar analog, dist_sync_kvstore.py:31-45).
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# join the coordinator BEFORE anything touches the XLA backend —
# the same ordering ps-lite requires of its env handshake
import jax  # noqa: E402

jax.distributed.initialize(os.environ["MXTPU_COORDINATOR"],
                           int(os.environ["MXTPU_NUM_PROCS"]),
                           int(os.environ["MXTPU_PROC_ID"]))

import numpy as onp  # noqa: E402,F401

import mxnet_tpu as mx  # noqa: E402


def check_diff_to_scalar(arr, x, rank):
    """ref: dist_sync_kvstore.py:31 — exact equality, not allclose."""
    a = arr.asnumpy()
    assert (a == x).all(), "rank %d: expected %s, got %s" % (rank, x, a)


def main():
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    assert nworker > 1, "must run under tools/launch.py -n N (N>1)"
    shape = (4, 4)

    # 1. push/pull aggregation: sum over ranks of (rank+1) = N(N+1)/2
    kv.init(3, mx.nd.zeros(shape))
    kv.push(3, mx.nd.ones(shape) * (rank + 1))
    out = mx.nd.zeros(shape)
    kv.pull(3, out=out)
    expected = nworker * (nworker + 1) / 2
    check_diff_to_scalar(out, expected, rank)

    # 2. repeated rounds stay consistent (sync semantics: every round sees
    #    exactly nworker contributions, ref: kvstore_dist_server.h:349)
    for rnd in range(3):
        kv.push(3, mx.nd.ones(shape))
        kv.pull(3, out=out)
        check_diff_to_scalar(out, nworker, rank)

    # 3. str keys + pushpull fusion
    kv.init("w0", mx.nd.zeros(shape))
    kv.pushpull("w0", mx.nd.ones(shape) * rank, out=out)
    check_diff_to_scalar(out, sum(range(nworker)), rank)

    # 4. gradient compression path across ranks
    kvc = mx.kv.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 0.5,
                                  "size_lower_bound": 0})
    kvc.init(9, mx.nd.zeros(shape))
    kvc.push(9, mx.nd.ones(shape) * 0.6)   # quantizes to +0.5 per rank
    kvc.pull(9, out=out)
    check_diff_to_scalar(out, 0.5 * nworker, rank)

    # 5. gluon Trainer over dist kvstore: after steps on rank-dependent
    #    data, weights must be bit-identical across ranks
    #    (ref: tests/nightly/dist_device_sync_kvstore.py gluon trainer case)
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon import nn
    net = nn.Dense(2, in_units=4)
    net.initialize(init=mx.init.One())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv)
    rng = onp.random.RandomState(100 + rank)  # DIFFERENT data per rank
    for _ in range(3):
        x = mx.nd.array(rng.randn(8, 4).astype("float32"))
        with autograd.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer.step(8)
    w = net.weight.data().asnumpy()
    from jax.experimental import multihost_utils
    all_w = multihost_utils.process_allgather(w)
    for r in range(nworker):
        assert (all_w[r] == all_w[0]).all(), \
            "rank %d: weights diverged across ranks" % rank

    # 6. row_sparse push + row_sparse_pull: rank-dependent row sets must
    #    sum exactly and selective pulls ship only the asked rows
    #    (ref: dist_sync_kvstore.py test_sync_push_pull row_sparse cases,
    #    kvstore_dist.h:522 EncodeRowSparseKey)
    from mxnet_tpu.ndarray.sparse import row_sparse_array
    vocab, dim = 12, 3
    kv.init(11, mx.nd.zeros((vocab, dim)))
    my_rows = onp.array([rank % vocab, (rank + 2) % vocab], "int64")
    vals = onp.ones((2, dim), "float32") * (rank + 1)
    kv.push(11, row_sparse_array((mx.nd.array(vals),
                                  mx.nd.array(my_rows)),
                                 shape=(vocab, dim)))
    expected_dense = onp.zeros((vocab, dim), "float32")
    for r in range(nworker):
        for row in (r % vocab, (r + 2) % vocab):
            expected_dense[row] += r + 1
    dense_out = mx.nd.zeros((vocab, dim))
    kv.pull(11, out=dense_out)
    assert (dense_out.asnumpy() == expected_dense).all(), \
        "rank %d: row_sparse aggregation wrong" % rank
    want = mx.nd.array(onp.array([1, 5, 7], "int64"))
    sparse_out = row_sparse_array(
        (mx.nd.zeros((3, dim)), want), shape=(vocab, dim))
    kv.row_sparse_pull(11, out=sparse_out, row_ids=want)
    got = sparse_out.asnumpy()[[1, 5, 7]]
    assert (got == expected_dense[[1, 5, 7]]).all(), \
        "rank %d: row_sparse_pull rows wrong" % rank

    # 7. fp16 path: aggregation must be exact in half precision
    #    (ref: dist_sync_kvstore.py test_sync_init fp16 / 'init_test'
    #    dtype cases)
    kv.init(13, mx.nd.zeros(shape, dtype="float16"))
    kv.push(13, mx.nd.ones(shape, dtype="float16") * (rank + 1))
    out16 = mx.nd.zeros(shape, dtype="float16")
    kv.pull(13, out=out16)
    a16 = out16.asnumpy()
    assert a16.dtype == onp.float16, a16.dtype
    assert (a16 == expected).all(), \
        "rank %d: fp16 expected %s got %s" % (rank, expected, a16)

    # 8. server-side optimizer (update_on_kvstore): every rank must see
    #    the identical post-update weight w - lr*sum(grads)
    #    (ref: kvstore_dist_server.h:346 ApplyUpdates + set_optimizer)
    kvo = mx.kv.create("dist_sync")
    kvo.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
    kvo.init(17, mx.nd.ones(shape))
    kvo.push(17, mx.nd.ones(shape) * (rank + 1))
    kvo.pull(17, out=out)
    check_diff_to_scalar(out, 1.0 - 0.5 * expected, rank)

    # 9. large-tensor exactness (sync mode allreduces whole tensors —
    #    the BIGARRAY-bound *splitting* path is async-only and covered
    #    with a lowered bound in tests/test_async_sharded.py)
    big_shape = (70000,)
    kv.init(19, mx.nd.zeros(big_shape))
    kv.push(19, mx.nd.ones(big_shape) * (rank + 1))
    big_out = mx.nd.zeros(big_shape)
    kv.pull(19, out=big_out)
    check_diff_to_scalar(big_out, expected, rank)

    # 10. compression error-feedback across rounds: 0.3 quantizes to 0
    #     (residual 0.3), next 0.3 makes 0.6 -> +0.5 per rank
    #     (ref: gradient_compression.h error-feedback residual)
    kvc.init(23, mx.nd.zeros(shape))
    kvc.push(23, mx.nd.ones(shape) * 0.3)
    kvc.pull(23, out=out)
    check_diff_to_scalar(out, 0.0, rank)
    kvc.push(23, mx.nd.ones(shape) * 0.3)
    kvc.pull(23, out=out)
    check_diff_to_scalar(out, 0.5 * nworker, rank)

    # 11. list-form init/push/pull (the reference's multi-key calls)
    lkeys = [31, 32, 33]
    kv.init(lkeys, [mx.nd.zeros(shape)] * 3)
    kv.push(lkeys, [mx.nd.ones(shape) * (rank + 1 + i)
                    for i in range(3)])
    louts = [mx.nd.zeros(shape) for _ in range(3)]
    kv.pull(lkeys, out=louts)
    for i, o in enumerate(louts):
        check_diff_to_scalar(o, expected + i * nworker, rank)

    # 12. barrier then done
    mx.parallel.host_barrier("dist-test")
    print("rank %d/%d: all dist_sync kvstore checks passed" % (rank, nworker))


if __name__ == "__main__":
    main()
