"""contrib tests: AMP, quantization, estimator
(ref: tests/python/gpu/test_contrib_amp.py, tests/python/quantization/,
tests/python/unittest/test_gluon_estimator.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn
from mxnet_tpu.contrib import amp, quantization


@pytest.fixture
def amp_active():
    amp.init()
    yield
    amp._reset()


class TestAMP:
    def test_dtype_policy(self, amp_active):
        x = mx.nd.array(onp.random.randn(4, 8).astype("float32"))
        w = mx.nd.array(onp.random.randn(16, 8).astype("float32"))
        out = mx.nd.FullyConnected(x, w, None, num_hidden=16, no_bias=True)
        assert str(out.dtype) == "bfloat16"  # MXU op ran low precision
        assert str(mx.nd.softmax(out).dtype) == "float32"  # fp32 op

    def test_widest_cast(self, amp_active):
        a = mx.nd.array(onp.ones((2, 2), "float32")).astype("bfloat16")
        b = mx.nd.array(onp.ones((2, 2), "float32"))
        assert str((a + b).dtype) == "float32"

    def test_grad_flows_through_casts(self, amp_active):
        net = nn.Dense(4, in_units=8)
        net.initialize()
        x = mx.nd.array(onp.random.randn(4, 8).astype("float32"))
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        g = net.weight.grad().asnumpy()
        assert str(net.weight.grad().dtype) == "float32"
        assert onp.abs(g).sum() > 0

    def test_trainer_overflow_skip(self, amp_active):
        import jax.numpy as jnp
        net = nn.Dense(4, in_units=8)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        amp.init_trainer(tr)
        loss_fn = gluon.loss.L2Loss()
        x = mx.nd.array(onp.random.randn(4, 8).astype("float32"))
        y = mx.nd.array(onp.random.randn(4, 4).astype("float32"))

        def one_step():
            with autograd.record():
                with amp.scale_loss(loss_fn(net(x), y).mean(), tr) as sl:
                    pass
                sl.backward()

        one_step()
        w0 = net.weight.data().asnumpy().copy()
        tr.step(4)
        assert not onp.allclose(w0, net.weight.data().asnumpy())

        one_step()
        g = net.weight.grad()
        g._data = g._data.at[0, 0].set(jnp.inf)
        w0 = net.weight.data().asnumpy().copy()
        s0 = tr._amp_loss_scaler.loss_scale
        tr.step(4)
        assert onp.allclose(w0, net.weight.data().asnumpy())  # skipped
        assert tr._amp_loss_scaler.loss_scale == s0 / 2  # scale halved

    def test_convert_hybrid_block(self, amp_active):
        net = nn.HybridSequential()
        net.add(nn.Dense(8, in_units=4), nn.BatchNorm(in_channels=8))
        net.initialize()
        amp.convert_hybrid_block(net)
        dense, bn = net[0], net[1]
        assert str(dense.weight.data().dtype) == "bfloat16"
        assert str(bn.gamma.data().dtype) == "float32"  # norm stays fp32

    def test_op_lists(self):
        assert "FullyConnected" in amp.list_lp16_ops()
        assert "softmax" in amp.list_fp32_ops()
        assert "add" in amp.list_widest_type_cast()


class TestQuantization:
    def test_quantize_dequantize_roundtrip(self):
        x = mx.nd.array(onp.linspace(-3, 3, 64).astype("float32"))
        q, mn, mxr = quantization.quantize(x, -3.0, 3.0)
        assert str(q.dtype) == "int8"
        back = quantization.dequantize(q, mn, mxr)
        assert onp.abs(back.asnumpy() - x.asnumpy()).max() < 3.0 / 127 + 1e-6

    def test_entropy_threshold_gaussian(self):
        a = onp.random.RandomState(0).randn(100000)
        hist, edges = onp.histogram(a, bins=8001, range=(-5, 5))
        t = quantization._get_optimal_threshold(hist, edges)
        assert 2.0 < t < 5.0  # keeps most mass, clips far tail

    def test_quantize_net_dense(self):
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu", in_units=16),
                nn.Dense(10, in_units=32))
        net.initialize()
        x = mx.nd.array(onp.random.randn(32, 16).astype("float32"))
        ref = net(x).asnumpy()
        qnet = quantization.quantize_net(net, calib_data=[x],
                                         calib_mode="naive")
        out = qnet(x).asnumpy()
        rel = onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-8)
        assert rel < 0.05, rel

    def test_quantize_net_conv(self):
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1, in_channels=3))
        net.initialize()
        x = mx.nd.array(onp.random.randn(4, 3, 8, 8).astype("float32"))
        ref = net(x).asnumpy()
        qnet = quantization.quantize_net(net, calib_data=[x],
                                         calib_mode="naive")
        out = qnet(x).asnumpy()
        rel = onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-8)
        assert rel < 0.05, rel

    def test_exclude_layers(self):
        net = nn.HybridSequential()
        net.add(nn.Dense(8, in_units=4))
        net.initialize()
        x = mx.nd.array(onp.random.randn(2, 4).astype("float32"))
        qnet = quantization.quantize_net(net, calib_data=[x],
                                         exclude_layers=["0"])
        assert isinstance(qnet[0], nn.Dense)  # untouched


class TestEstimator:
    def _data(self):
        rng = onp.random.RandomState(0)
        X = rng.randn(64, 10).astype("float32")
        y = (X.sum(axis=1) > 0).astype("int64")
        return [(mx.nd.array(X[i:i + 16]), mx.nd.array(y[i:i + 16]))
                for i in range(0, 64, 16)]

    def _net(self):
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
        net.initialize()
        return net

    def test_fit_improves_accuracy(self):
        from mxnet_tpu.gluon.contrib.estimator import Estimator
        from mxnet_tpu.metric import Accuracy
        net = self._net()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        metrics=Accuracy(), trainer=tr)
        est.fit(train_data=self._data(), epochs=10)
        acc = [m for m in est.train_metrics if m.name == "accuracy"][0]
        assert acc.get()[1] > 0.85

    def test_validation_and_early_stopping(self):
        from mxnet_tpu.gluon.contrib.estimator import (
            Estimator, EarlyStoppingHandler)
        from mxnet_tpu.metric import Accuracy
        net = self._net()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        metrics=Accuracy(), trainer=tr)
        val_acc = [m for m in est.val_metrics
                   if "accuracy" in m.name][0]
        stop = EarlyStoppingHandler(monitor=val_acc, patience=2, mode="max")
        est.fit(train_data=self._data(), val_data=self._data(), epochs=50,
                event_handlers=[stop])
        # early stopping must have ended it well before 50 epochs
        assert stop.current_epoch < 50

    def test_checkpoint_handler(self, tmp_path):
        from mxnet_tpu.gluon.contrib.estimator import (
            Estimator, CheckpointHandler)
        from mxnet_tpu.metric import Accuracy
        import os
        net = self._net()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        metrics=Accuracy(), trainer=tr)
        ckpt = CheckpointHandler(str(tmp_path), model_prefix="m")
        est.fit(train_data=self._data(), epochs=2, event_handlers=[ckpt])
        assert os.path.exists(str(tmp_path / "m-epoch1.params"))
        assert os.path.exists(str(tmp_path / "m-epoch2.states"))

    def test_max_batches(self):
        from mxnet_tpu.gluon.contrib.estimator import Estimator
        from mxnet_tpu.metric import Accuracy
        net = self._net()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        metrics=Accuracy(), trainer=tr)
        est.fit(train_data=self._data(), batches=3)
        # stopped by batch count, not epochs
        assert est.stop_training


class TestReviewRegressions:
    def test_unscale_no_double_divide(self, amp_active):
        net = nn.Dense(2, in_units=2)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 1.0})
        amp.init_trainer(tr)
        x = mx.nd.array(onp.ones((1, 2), "float32"))
        with autograd.record():
            with amp.scale_loss(net(x).sum(), tr) as sl:
                pass
            sl.backward()
        amp.unscale(tr)
        g = net.weight.grad().asnumpy().copy()
        w0 = net.weight.data().asnumpy().copy()
        tr.step(1)
        delta = onp.abs(w0 - net.weight.data().asnumpy()).max()
        # lr=1, batch=1: delta must equal the unscaled grad, not grad/scale
        assert abs(delta - onp.abs(g).max()) < 1e-5

    def test_amp_applies_to_warm_hybridized_net(self, amp_active):
        amp._reset()  # start without amp, warm the cache
        net = nn.Dense(4, in_units=8)
        net.initialize()
        net.hybridize()
        x = mx.nd.array(onp.random.randn(2, 8).astype("float32"))
        assert str(net(x).dtype) == "float32"
        amp.init()
        assert str(net(x).dtype) == "bfloat16"  # cache not silently reused

    def test_quantize_hybridized_net(self):
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=8), nn.Dense(4))
        net.initialize()
        net.hybridize()
        x = mx.nd.array(onp.random.randn(16, 8).astype("float32"))
        ref = net(x).asnumpy()  # warm the cached graph
        qnet = quantization.quantize_net(net, calib_data=[x],
                                         calib_mode="naive")
        out = qnet(x).asnumpy()
        rel = onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-8)
        assert rel < 0.05, rel  # calibration saw real activations

    def test_entropy_hist_accumulates_across_batches(self):
        col = quantization.CalibrationCollector(mode="entropy")
        rng = onp.random.RandomState(0)
        col.collect("l", rng.randn(1000).astype("float32"))
        col.collect("l", (rng.randn(1000) * 3).astype("float32"))
        hist, _ = col.hists["l"]
        assert hist.sum() == 2000  # both batches retained after range grew

    def test_custom_op_lists_do_not_leak(self, amp_active):
        amp._reset()
        amp.init(target_precision_ops=["my_custom_op"])
        assert "my_custom_op" in amp.list_lp16_ops()
        amp._reset()
        amp.init()
        assert "my_custom_op" not in amp.list_lp16_ops()
        from mxnet_tpu.contrib.amp.lists import symbol as L
        assert "my_custom_op" not in L.TARGET_DTYPE_OPS

    def test_stopping_handler_user_supplied_max_batch(self):
        from mxnet_tpu.gluon.contrib.estimator import (Estimator,
                                                       StoppingHandler)
        from mxnet_tpu.metric import Accuracy
        rng = onp.random.RandomState(0)
        X = rng.randn(64, 10).astype("float32")
        y = (X.sum(axis=1) > 0).astype("int64")
        data = [(mx.nd.array(X[i:i + 16]), mx.nd.array(y[i:i + 16]))
                for i in range(0, 64, 16)]
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        metrics=Accuracy(), trainer=tr)
        handler = StoppingHandler()  # user-supplied, unparameterized
        est.fit(train_data=data, batches=2, event_handlers=[handler])
        assert handler.current_batch == 2  # synced max_batch, stopped


class TestText:
    def test_vocab(self):
        from mxnet_tpu.contrib import text
        counter = text.utils.count_tokens_from_str("a b b c c c")
        v = text.Vocabulary(counter, min_freq=2)
        assert v.idx_to_token[0] == "<unk>"
        assert v.to_indices("c") == 1
        assert v.to_indices("nope") == 0
        assert v.to_tokens(1) == "c"
        assert len(v) == 3

    def test_embedding_file_and_composite(self, tmp_path):
        from mxnet_tpu.contrib import text
        p = tmp_path / "emb.txt"
        p.write_text("hello 1.0 2.0\nworld 3.0 4.0\n")
        emb = text.embedding.CustomEmbedding(str(p))
        assert emb.vec_len == 2
        onp.testing.assert_allclose(
            emb.get_vecs_by_tokens("world").asnumpy(), [3.0, 4.0])
        assert (emb.get_vecs_by_tokens("zz").asnumpy() == 0).all()
        v = text.Vocabulary({"hello": 2, "zz": 1})
        comp = text.embedding.CompositeEmbedding(v, [emb])
        assert comp.idx_to_vec.shape == (3, 2)

    def test_registry(self, tmp_path):
        from mxnet_tpu.contrib import text
        p = tmp_path / "emb.txt"
        p.write_text("a 1.0\n")
        e = text.embedding.create("glove", pretrained_file_path=str(p))
        assert e.vec_len == 1


class TestNumpyDispatch:
    def test_array_function_protocol(self):
        from mxnet_tpu import np as mnp
        a = mnp.array([[1.0, 2.0], [3.0, 4.0]])
        m = onp.mean(a, axis=0)
        assert isinstance(m, mnp.ndarray)
        assert m.asnumpy().tolist() == [2.0, 3.0]
        c = onp.concatenate([a, a])
        assert isinstance(c, mnp.ndarray) and c.shape == (4, 2)

    def test_array_ufunc_protocol(self):
        from mxnet_tpu import np as mnp
        a = mnp.array([0.0, 1.0])
        s = onp.sin(a)
        assert isinstance(s, mnp.ndarray)
        onp.testing.assert_allclose(s.asnumpy(), onp.sin([0.0, 1.0]),
                                    atol=1e-6)

    def test_fasttext_header_skipped(self, tmp_path):
        from mxnet_tpu.contrib import text
        p = tmp_path / "ft.vec"
        p.write_text("2 3\nhello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
        emb = text.embedding.FastText(pretrained_file_path=str(p))
        assert emb.vec_len == 3
        assert len(emb) == 3  # <unk> + 2 tokens, header not a token
        onp.testing.assert_allclose(
            emb.get_vecs_by_tokens("hello").asnumpy(), [1.0, 2.0, 3.0])

    def test_vocab_most_freq_count_zero(self):
        from mxnet_tpu.contrib import text
        v = text.Vocabulary({"a": 5, "b": 3}, most_freq_count=0)
        assert len(v) == 1  # only <unk>


class TestSVRGCallbacks:
    def test_standard_callbacks_work(self):
        from mxnet_tpu import symbol as sym, io as mio, callback
        from mxnet_tpu.contrib.svrg_optimization import SVRGModule
        import mxnet_tpu as mx
        rng = onp.random.RandomState(0)
        X = rng.randn(32, 4).astype("float32")
        yv = (X.sum(1) > 0).astype("float32")
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        out = sym.SoftmaxOutput(sym.FullyConnected(data, num_hidden=2,
                                                   name="fc"), label,
                                name="softmax")
        it = mio.NDArrayIter(X, yv, batch_size=16)
        mod = SVRGModule(out, context=mx.cpu(), update_freq=1)
        seen = {"epoch_end": 0}

        def epoch_cb(epoch, symbol, arg_p, aux_p):
            assert "fc_weight" in arg_p
            seen["epoch_end"] += 1
        mod.fit(it, eval_data=it, num_epoch=2,
                batch_end_callback=callback.Speedometer(16, 1),
                epoch_end_callback=epoch_cb,
                optimizer_params={"learning_rate": 0.1})
        assert seen["epoch_end"] == 2


class TestContribNN:
    def test_concurrent_and_identity(self):
        from mxnet_tpu.gluon.contrib.nn import (HybridConcurrent, Identity,
                                                Concurrent)
        net = HybridConcurrent(axis=-1)
        net.add(nn.Dense(3, in_units=4), Identity(),
                nn.Dense(2, in_units=4))
        net.initialize()
        x = mx.nd.array(onp.ones((2, 4), "float32"))
        out = net(x)
        assert out.shape == (2, 9)
        net.hybridize()
        onp.testing.assert_allclose(out.asnumpy(), net(x).asnumpy(),
                                    rtol=1e-6)
        c = Concurrent(axis=-1)
        c.add(nn.Dense(3, in_units=4), Identity())
        c.initialize()
        assert c(x).shape == (2, 7)


def test_pixelshuffle_layers():
    """ref: gluon/contrib/nn/basic_layers.py PixelShuffle1D/2D/3D — the
    channel-major split (checked against the reference reshape chain)."""
    from mxnet_tpu.gluon.contrib import nn as cnn
    x1 = mx.nd.array(onp.arange(12, dtype="float32").reshape(1, 6, 2))
    y1 = cnn.PixelShuffle1D(2)(x1)
    assert y1.shape == (1, 3, 4)
    # C-major: out channel c comes from input channels [c*f, c*f+f)
    onp.testing.assert_allclose(
        y1.asnumpy()[0, 0], [0.0, 2.0, 1.0, 3.0])
    x2 = mx.nd.array(onp.arange(16, dtype="float32").reshape(1, 4, 2, 2))
    y2 = cnn.PixelShuffle2D((2, 2))(x2)
    assert y2.shape == (1, 1, 4, 4)
    x3 = mx.nd.array(onp.arange(2 * 8, dtype="float32")
                     .reshape(1, 8, 2, 1, 1))
    y3 = cnn.PixelShuffle3D(2)(x3)
    assert y3.shape == (1, 1, 4, 2, 2)


def test_sync_batchnorm_and_sparse_embedding():
    from mxnet_tpu.gluon.contrib import nn as cnn
    sbn = cnn.SyncBatchNorm(num_devices=4)
    sbn.initialize()
    x = mx.nd.array(onp.random.RandomState(0).rand(4, 3, 2, 2)
                    .astype("float32"))
    with mx.autograd.record():
        y = sbn(x)
    assert y.shape == x.shape
    emb = cnn.SparseEmbedding(10, 4)
    emb.initialize()
    out = emb(mx.nd.array(onp.array([1, 3], "float32")))
    assert out.shape == (2, 4)
    assert "SparseEmbedding" in repr(emb)


def test_variational_dropout_cell():
    from mxnet_tpu.gluon import rnn
    from mxnet_tpu.gluon.contrib.rnn import VariationalDropoutCell
    cell = VariationalDropoutCell(rnn.LSTMCell(8), drop_inputs=0.3,
                                  drop_outputs=0.3)
    cell.initialize()
    x = mx.nd.array(onp.random.RandomState(0).rand(2, 5, 4)
                    .astype("float32"))
    with mx.autograd.record():  # dropout active in train mode
        outputs, states = cell.unroll(5, x, merge_outputs=True)
    assert outputs.shape == (2, 5, 8)
    assert len(states) == 2


def test_lstmp_cell():
    from mxnet_tpu.gluon.contrib.rnn import LSTMPCell
    cell = LSTMPCell(hidden_size=8, projection_size=3)
    cell.initialize()
    x = mx.nd.array(onp.random.RandomState(0).rand(2, 4, 5)
                    .astype("float32"))
    outputs, states = cell.unroll(4, x, merge_outputs=True)
    assert outputs.shape == (2, 4, 3)          # projected size
    assert states[0].shape == (2, 3)           # h is projected
    assert states[1].shape == (2, 8)           # c keeps hidden size


def test_conv_rnn_cells():
    from mxnet_tpu.gluon.contrib.rnn import (Conv2DRNNCell, Conv2DLSTMCell,
                                             Conv2DGRUCell, Conv1DLSTMCell)
    rs = onp.random.RandomState(0)
    for cls, n_states in ((Conv2DRNNCell, 1), (Conv2DLSTMCell, 2),
                          (Conv2DGRUCell, 1)):
        cell = cls(input_shape=(3, 8, 8), hidden_channels=4,
                   i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
        cell.initialize()
        x = mx.nd.array(rs.rand(2, 5, 3, 8, 8).astype("float32"))
        outputs, states = cell.unroll(5, x, merge_outputs=True)
        assert outputs.shape == (2, 5, 4, 8, 8), cls.__name__
        assert len(states) == n_states
    cell1d = Conv1DLSTMCell(input_shape=(2, 10), hidden_channels=3,
                            i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell1d.initialize()
    x = mx.nd.array(rs.rand(2, 4, 2, 10).astype("float32"))
    outputs, _ = cell1d.unroll(4, x, merge_outputs=True)
    assert outputs.shape == (2, 4, 3, 10)
