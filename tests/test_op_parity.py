"""Operator-surface parity audit against the reference registry.

Scans every public `NNVM_REGISTER_OP` / `MXNET_REGISTER_OP_PROPERTY`
name in the reference's src/operator/ and asserts each resolves in this
framework — directly, via an alias, or via a documented semantic
equivalent. The exemption list below is the complete set of reference
names that intentionally have no direct counterpart, each with the
reason (VERDICT r1 "Missing #1" closure criterion).

Skips when /root/reference is not present (e.g. standalone checkouts).
"""
import glob
import os
import re

import pytest

REFERENCE = "/root/reference/src/operator"

# Names that map to a *different* surface by design. Key -> where/why.
SEMANTIC_EQUIVALENTS = {
    # numpy scalar/int-axes variants: the reference splits these because
    # its C++ dispatch cannot overload on python scalars; the jax-backed
    # np namespace handles scalars in the same function
    "_npi_true_divide_scalar": "np.true_divide(arr, scalar)",
    "_npi_rtrue_divide_scalar": "np.true_divide(scalar, arr)",
    "_npi_lcm_scalar": "np.lcm(arr, scalar)",
    "_npi_tensordot_int_axes": "np.tensordot(a, b, int_axes)",
    "_npi_boolean_mask_assign_scalar": "arr[mask] = scalar (setitem)",
    "_npi_boolean_mask_assign_tensor": "arr[mask] = tensor (setitem)",
    "_np__linalg_svd": "np.linalg.svd",
}

# Names that are not operators a user can reach, or that target other
# hardware. Each entry documents why no counterpart exists.
EXEMPT = {
    # C++ macro-expansion artifacts the .cc regex scan picks up: the
    # ##distr token-paste stamps the real per-distribution ops, which
    # ARE registered (sample_normal, random_pdf_gamma, ...)
    "_sample_##distr", "_random_pdf_##distr", "__name$", "name",
    # backward halves: gradients come from jax autodiff, not separate
    # registrations (SURVEY §2.1: FGradient -> jax.vjp by design)
    "_broadcast_backward", "_split_v2_backward",
    "_contrib_backward_hawkesll", "_contrib_backward_index_copy",
    "_contrib_backward_quadratic",
    # internal executor plumbing: cross-device copies are XLA
    # device_put/sharding transfers, not graph ops
    "_CrossDeviceCopy",
    # plugin/vendor stubs: reference placeholders for external libs
    # that do not exist on TPU (PARITY.md "known gaps")
    "_Native", "_NDArray",     # plugin/torch bridge (reference plugin/)
    "_TensorRT",               # TensorRT subgraph op (GPU inference)
    "_sg_mkldnn_conv",         # MKLDNN fused subgraph (x86)
    "_sg_mkldnn_fully_connected",
    "_contrib_tvm_vadd",       # TVM codegen demo op
}


def _reference_names():
    names = set()
    for f in glob.glob(os.path.join(REFERENCE, "**/*.cc"), recursive=True):
        txt = open(f, errors="ignore").read()
        for m in re.finditer(r"NNVM_REGISTER_OP\(([^)]+)\)", txt):
            names.add(m.group(1).strip())
        for m in re.finditer(r"MXNET_REGISTER_OP_PROPERTY\(([^,]+),", txt):
            names.add(m.group(1).strip())
    return names


@pytest.mark.skipif(not os.path.isdir(REFERENCE),
                    reason="reference checkout not available")
def test_every_reference_op_resolves():
    import mxnet_tpu as mx
    import mxnet_tpu.numpy as mnp
    import mxnet_tpu.numpy_extension as npx

    modules = [mx.nd, mnp, npx, mx.sym,
               getattr(mx.nd, "contrib", None),
               getattr(mx.nd, "image", None),
               getattr(mx.nd, "linalg", None),
               getattr(mx.nd, "sparse", None),
               getattr(mnp, "linalg", None),
               getattr(mnp, "random", None)]

    def resolves(n):
        cands = {n, n.lstrip("_")}
        base = n.lstrip("_")
        for pre in ("npi_", "np_", "np__", "npx_", "contrib_", "image_",
                    "sparse_", "linalg_", "random_", "sample_"):
            if base.startswith(pre):
                cands.add(base[len(pre):])
                cands.add("_" + base[len(pre):])
        return any(m is not None and hasattr(m, c)
                   for c in cands for m in modules)

    unresolved = []
    for n in sorted(_reference_names()):
        if n.startswith("_backward_"):
            continue  # autodiff by design (SURVEY §2.1)
        if n in EXEMPT or n in SEMANTIC_EQUIVALENTS:
            continue
        if not resolves(n):
            unresolved.append(n)
    assert not unresolved, (
        "reference ops with no counterpart and no documented exemption: "
        f"{unresolved}")


@pytest.mark.skipif(not os.path.isdir(REFERENCE),
                    reason="reference checkout not available")
def test_semantic_equivalents_actually_work():
    """The claimed equivalents must really exist and run."""
    import numpy as onp
    import mxnet_tpu.numpy as np

    a = np.array([4.0, 6.0])
    onp.testing.assert_allclose(np.true_divide(a, 2).asnumpy(), [2, 3])
    onp.testing.assert_allclose(np.true_divide(2, a).asnumpy(),
                                [0.5, 1 / 3], rtol=1e-6)
    onp.testing.assert_allclose(
        np.lcm(np.array([4, 6], dtype="int32"), 3).asnumpy(), [12, 6])
    assert np.tensordot(np.ones((2, 3)), np.ones((3, 4)), 1).shape == (2, 4)
    m = np.array([[1.0, 2.0], [3.0, 4.0]])
    mask = m > 2
    m[mask] = 0.0
    onp.testing.assert_allclose(m.asnumpy(), [[1, 2], [0, 0]])
    u, s, vt = np.linalg.svd(np.array([[2.0, 0.0], [0.0, 1.0]]))
    onp.testing.assert_allclose(sorted(s.asnumpy().tolist()), [1.0, 2.0],
                                atol=1e-5)


# nd-only names that are imperative by nature — no symbolic counterpart
# (VERDICT r3 item 7: documented imperative-only list).
ND_ONLY_IMPERATIVE = {
    # module plumbing / host-side helpers, not ops
    "Context", "NDArray", "annotations", "canonical_dtype",
    "current_context", "graph", "imperative_invoke", "jax", "jnp",
    "ndarray", "optimizer_ops", "pickle", "struct",
    # constructors / host IO: need concrete values, not graph nodes
    "array", "empty", "save", "waitall",
    # dynamic output shapes — XLA needs static shapes; imperative only
    "unique", "boolean_mask",
}

# sym-only names that have no nd meaning (graph construction)
SYM_ONLY_GRAPH = {"Variable", "var", "Group", "load_json", "Custom",
                  "contrib", "Symbol", "control_flow", "symbol",
                  # graph-infrastructure SUBMODULES: importing
                  # mxnet_tpu.symbol.infer / .subgraph anywhere (other
                  # tests do) binds them as package attributes, so they
                  # show up in dir(mx.sym) order-dependently
                  "infer", "subgraph"}


def test_nd_sym_namespace_parity():
    """Every nd name resolves in sym and vice versa, modulo the
    documented imperative-only / graph-only lists (ref: both namespaces
    generate from one registry, python/mxnet/symbol/register.py)."""
    import mxnet_tpu as mx

    nd_names = {n for n in dir(mx.nd) if not n.startswith("_")}
    sym_names = {n for n in dir(mx.sym) if not n.startswith("_")}
    missing_in_sym = nd_names - sym_names - ND_ONLY_IMPERATIVE
    missing_in_nd = sym_names - nd_names - SYM_ONLY_GRAPH
    assert not missing_in_sym, ("nd ops absent from sym and not in the "
                                "documented imperative-only list: %s"
                                % sorted(missing_in_sym))
    assert not missing_in_nd, ("sym names absent from nd and not in the "
                               "documented graph-only list: %s"
                               % sorted(missing_in_nd))


def test_nd_sym_subnamespace_parity():
    """sym.random/linalg/image/sparse expose nd's public names (modulo
    imperative-only constructors)."""
    import mxnet_tpu as mx

    pairs = {
        "random": set(),
        "linalg": set(),
        "image": {"make_op_func"},
        # sparse constructors/classes are storage-level, imperative only
        "sparse": {"CSRNDArray", "NDArray", "RowSparseNDArray", "array",
                   "csr_matrix", "row_sparse_array", "jnp",
                   "dot_csr_dense"},
    }
    for ns, exempt in pairs.items():
        nd_ns = {n for n in dir(getattr(mx.nd, ns))
                 if not n.startswith("_") and n != "annotations"}
        sym_ns = {n for n in dir(getattr(mx.sym, ns))
                  if not n.startswith("_") and n != "annotations"}
        missing = nd_ns - sym_ns - exempt
        assert not missing, "sym.%s missing %s" % (ns, sorted(missing))


def test_symbolic_optimizer_updates_match_nd():
    """The pure symbolic update ops and the imperative nd wrappers share
    one math layer — spot-check adam numerically through the executor."""
    import numpy as np
    import mxnet_tpu as mx

    rs = np.random.RandomState(0)
    w0 = rs.rand(6).astype("f")
    g0 = rs.rand(6).astype("f")
    m0 = rs.rand(6).astype("f")
    v0 = rs.rand(6).astype("f") + 0.1

    s = mx.sym.adam_update(mx.sym.Variable("w"), mx.sym.Variable("g"),
                           mx.sym.Variable("m"), mx.sym.Variable("v"),
                           lr=0.1, beta1=0.9, beta2=0.99, epsilon=1e-8)
    exe = s.simple_bind(w=(6,), g=(6,), m=(6,), v=(6,))
    exe.arg_dict["w"][:] = w0
    exe.arg_dict["g"][:] = g0
    exe.arg_dict["m"][:] = m0
    exe.arg_dict["v"][:] = v0
    new_w, new_m, new_v = [o.asnumpy() for o in exe.forward()]

    w = mx.nd.array(w0)
    m = mx.nd.array(m0)
    v = mx.nd.array(v0)
    out = mx.nd.adam_update(w, mx.nd.array(g0), m, v, lr=0.1, beta1=0.9,
                            beta2=0.99, epsilon=1e-8)
    np.testing.assert_allclose(new_w, out.asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(new_m, m.asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(new_v, v.asnumpy(), rtol=1e-6)
