"""Worker script for multi-process dist_async tests
(ref: tests/nightly/dist_async_kvstore.py). Each rank pushes its own
updates with NO synchronization barrier; the rank-0 server thread
applies each push immediately. Checks: every rank's pushes land
(total update count), and the final pulled weights reflect the summed
contributions — async eventually sees everything, just not atomically.

Run via: python tools/launch.py -n 3 python tests/dist_async_kvstore_worker.py
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as onp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def main():
    rank = int(os.environ["MXTPU_PROC_ID"])
    nproc = int(os.environ["MXTPU_NUM_PROCS"])
    kv = mx.kv.create("dist_async")
    assert kv.type == "dist_async"
    shape = (4,)
    import mxnet_tpu.optimizer as opt
    if rank == 0:
        # server-side optimizer: w -= lr * grad, applied per push
        kv.set_optimizer(opt.create("sgd", learning_rate=1.0, wd=0.0,
                                    rescale_grad=1.0))
    kv.init("w", mx.nd.zeros(shape))

    rounds = 5
    for _ in range(rounds):
        kv.push("w", mx.nd.ones(shape) * -(rank + 1))  # w += rank+1

    # wait until the server has applied everyone's pushes (async has no
    # barrier; poll like the reference's nightly test waits on values)
    import time
    want = nproc * rounds
    for _ in range(400):
        if kv.updates_applied() >= want:
            break
        time.sleep(0.05)
    assert kv.updates_applied() == want, kv.updates_applied()

    out = mx.nd.zeros(shape)
    kv.pull("w", out=out)
    total = sum(r + 1 for r in range(nproc)) * rounds
    got = out.asnumpy()
    assert (got == total).all(), (got, total)
    print("rank %d/%d: dist_async checks passed" % (rank, nproc))
    if rank == 0:
        kv.close()  # waits for the other ranks' done() signals
    else:
        kv.done()


if __name__ == "__main__":
    main()
