"""Device-feed double buffering (VERDICT r3 item 4)."""
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.io import DevicePrefetcher, DevicePrefetchIter


class _SlowIter:
    """Restartable iterator with a per-batch production delay."""

    def __init__(self, n, delay):
        self.n = n
        self.delay = delay

    def __iter__(self):
        for i in range(self.n):
            time.sleep(self.delay)
            yield np.full((4,), i, dtype=np.float32)

    def reset(self):
        pass


def test_order_and_values():
    pf = DevicePrefetchIter(_SlowIter(6, 0.0))
    got = [int(np.asarray(b)[0]) for b in pf]
    assert got == list(range(6))


def test_reset_restarts():
    pf = DevicePrefetchIter(_SlowIter(4, 0.0))
    assert len(list(pf)) == 4
    pf.reset()
    assert len(list(pf)) == 4


def test_overlap_hides_producer_latency():
    """Consumer work overlaps producer delay: wall ~ max, not sum."""
    n, delay = 8, 0.03
    pf = DevicePrefetchIter(_SlowIter(n, delay))
    next(pf)  # thread warm, first batch out
    t0 = time.perf_counter()
    for _ in pf:
        time.sleep(delay)  # consumer busy exactly as long as producer
    wall = time.perf_counter() - t0
    serial = 2 * delay * (n - 1)
    # perfectly overlapped would be ~delay*(n-1); allow generous slack
    # for the 1-core CI host, but require clearly better than serial
    assert wall < serial * 0.8, (wall, serial)


def test_exception_propagates():
    def boom():
        yield np.zeros(2)
        raise RuntimeError("producer failed")

    pf = DevicePrefetchIter(boom())
    next(pf)
    with pytest.raises(RuntimeError, match="producer failed"):
        next(pf)


def test_worker_death_raises_once_then_exhausts():
    """Restart-or-die contract: the worker exception surfaces exactly
    once; afterwards the iterator reads exhausted (StopIteration) so a
    `for` loop over a died prefetcher terminates instead of hanging on
    an empty queue or replaying the same exception forever."""
    def boom():
        raise RuntimeError("producer failed")
        yield  # pragma: no cover — makes it a generator

    pf = DevicePrefetchIter(boom())
    with pytest.raises(RuntimeError, match="producer failed"):
        next(pf)
    for _ in range(3):
        with pytest.raises(StopIteration):
            next(pf)
    assert list(pf) == []  # for-loop form terminates too


def test_reset_recovers_after_worker_death():
    """reset() after a death starts a FRESH worker over the restarted
    source — full recovery, not permanent poisoning."""
    class FlakyOnce:
        def __init__(self):
            self.runs = 0

        def __iter__(self):
            self.runs += 1
            if self.runs == 1:
                raise OSError("transient source failure")
            for i in range(3):
                yield np.full((2,), i, dtype=np.float32)

        def reset(self):
            pass

    pf = DevicePrefetchIter(FlakyOnce())
    with pytest.raises(OSError):
        next(pf)
    with pytest.raises(StopIteration):
        next(pf)
    pf.reset()
    assert [int(np.asarray(b)[0]) for b in pf] == [0, 1, 2]


def test_gluon_dataloader_prefetcher():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    n = 12
    ds = ArrayDataset(np.arange(n * 3, dtype="f").reshape(n, 3),
                      np.arange(n, dtype="f"))
    loader = DataLoader(ds, batch_size=4)
    pf = DevicePrefetcher(loader)
    assert len(pf) == 3
    seen = 0
    for x, y in pf:
        assert isinstance(x, mx.nd.NDArray) and x.shape == (4, 3)
        seen += 1
    assert seen == 3
    # second epoch works (reset-on-iter)
    assert sum(1 for _ in pf) == 3


def test_ndarray_batches_stay_ndarray():
    batches = [(mx.nd.array(np.ones((2, 2), "f")),
                mx.nd.array(np.zeros((2,), "f")))]
    pf = DevicePrefetchIter(iter(batches))
    x, y = next(pf)
    assert isinstance(x, mx.nd.NDArray)
    np.testing.assert_allclose(x.asnumpy(), 1.0)


# -- ISSUE 11 satellites: gauge accounting + drain-and-join resets ------------

def _poll(cond, timeout=5.0):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.005)
    return False


def test_queue_depth_gauge_reseeds_from_live_queue_on_restart():
    """Regression (ISSUE 11): a worker restart while items sit in the
    queue must re-seed the ``io.prefetch_queue_depth`` gauge from the
    LIVE queue — never leave the pre-restart depth published (stale),
    and never go negative the way delta bookkeeping over discarded
    items would."""
    from mxnet_tpu.io import _stats as io_stats

    class FirstRunFull:
        """First run streams plenty (queue fills); after reset the
        source is empty — so any nonzero post-reset gauge value can
        only be staleness."""

        def __init__(self):
            self.runs = 0

        def __iter__(self):
            self.runs += 1
            if self.runs == 1:
                for i in range(100):
                    yield np.full((2,), i, dtype=np.float32)

        def reset(self):
            pass

    io_stats.reset()
    pf = DevicePrefetchIter(FirstRunFull(), depth=4)
    next(pf)
    # let the producer run ahead: the gauge reflects a filling queue
    assert _poll(lambda: io_stats.get("prefetch_queue_depth", 0) >= 1)
    stale = io_stats.get("prefetch_queue_depth")
    assert stale >= 1
    pf.reset()  # discards the queued items, restarts onto an empty src
    g = io_stats.get("prefetch_queue_depth", None)
    assert g == 0, "gauge must be re-seeded from the live queue, " \
        "got %r (pre-reset %r)" % (g, stale)
    assert list(pf) == []  # second run really is empty
    assert io_stats.get("prefetch_queue_depth") >= 0


def test_gauge_never_negative_across_death_and_reset():
    from mxnet_tpu.io import _stats as io_stats

    class DieMidStream:
        def __init__(self):
            self.runs = 0

        def __iter__(self):
            self.runs += 1
            for i in range(3):
                yield np.full((1,), i, dtype=np.float32)
            if self.runs == 1:
                raise RuntimeError("source died")

        def reset(self):
            pass

    io_stats.reset()
    pf = DevicePrefetchIter(DieMidStream(), depth=4)
    seen = []
    with pytest.raises(RuntimeError):
        for b in pf:
            seen.append(b)
            assert io_stats.get("prefetch_queue_depth", 0) >= 0
    assert len(seen) == 3
    pf.reset()
    assert io_stats.get("prefetch_queue_depth", 0) >= 0
    assert len(list(pf)) == 3  # recovered run delivers everything
    assert io_stats.get("prefetch_queue_depth", 0) >= 0


def test_device_prefetch_reset_joins_old_worker():
    """reset() must drain AND JOIN: after it returns, the previous
    worker thread is provably finished — it cannot place into the
    replaced (dead) queue or race the restarted source."""
    pf = DevicePrefetchIter(_SlowIter(50, 0.001), depth=2)
    next(pf)
    old_threads = []
    for _ in range(4):
        old_threads.append(pf._thread)
        pf.reset()
        assert not old_threads[-1].is_alive()
    for t in old_threads:
        assert not t.is_alive()
    assert len(list(pf)) == 50


def test_prefetching_iter_reset_joins_old_worker_lock_clean():
    """PrefetchingIter.reset() under the runtime lock detector:
    repeated mid-production resets leave no orphan producer (the old
    thread is joined before a new one starts) and no lock-order
    inversions."""
    from mxnet_tpu._debug import locktrace
    from mxnet_tpu.io import NDArrayIter, PrefetchingIter

    prev = locktrace.enable()
    locktrace.reset()
    try:
        data = np.arange(64, dtype="f").reshape(16, 4)
        it = PrefetchingIter(NDArrayIter(data, batch_size=4))
        for _ in range(5):
            it.next()  # mid-epoch: the producer is live
            old = it._thread
            it.reset()
            # the join happened INSIDE reset — the old producer is done
            assert not old.is_alive()
            assert it._thread is not old
        # post-reset epochs deliver the full pass
        n = 0
        try:
            while True:
                it.next()
                n += 1
        except StopIteration:
            pass
        assert n == 4
        r = locktrace.report()
        assert r["inversions"] == [], r["inversions"]
    finally:
        locktrace.reset()
        if not prev:
            locktrace.disable()


def test_reset_cancels_infinite_producer():
    """reset() must not require the producer to finish (review r4)."""
    def forever():
        i = 0
        while True:
            yield np.full((2,), i, dtype=np.float32)
            i += 1

    pf = DevicePrefetchIter(forever())
    next(pf)
    t0 = time.perf_counter()
    pf.reset()  # would hang without cancellation
    assert time.perf_counter() - t0 < 5.0
    # the replacement worker is live (generator resumes, not rewound)
    assert np.asarray(next(pf)).shape == (2,)
