"""Fused RNN layer tests (ref: tests/python/unittest/test_gluon_rnn.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon import rnn


def _x(t, n, c, seed=0):
    rng = np.random.RandomState(seed)
    return mx.nd.array(rng.randn(t, n, c).astype("float32"))


@pytest.mark.parametrize("layer_cls,nstates", [(rnn.LSTM, 2), (rnn.GRU, 1),
                                               (rnn.RNN, 1)])
def test_layer_shapes(layer_cls, nstates):
    net = layer_cls(16, num_layers=2)
    net.initialize()
    x = _x(5, 3, 8)
    out = net(x)
    assert out.shape == (5, 3, 16)
    states = net.begin_state(batch_size=3)
    assert len(states) == nstates
    out, st = net(x, states)
    assert out.shape == (5, 3, 16)
    assert all(s.shape == (2, 3, 16) for s in st)


def test_bidirectional():
    net = rnn.LSTM(16, num_layers=2, bidirectional=True)
    net.initialize()
    out, st = net(_x(5, 3, 8), net.begin_state(batch_size=3))
    assert out.shape == (5, 3, 32)
    assert st[0].shape == (4, 3, 16)


def test_ntc_layout():
    net = rnn.GRU(10, layout="NTC")
    net.initialize()
    assert net(_x(3, 5, 4)).shape == (3, 5, 10)


def test_fused_matches_cell():
    """Fused LSTM layer == unfolded LSTMCell with shared weights."""
    fused = rnn.LSTM(6, input_size=4)
    fused.initialize()
    cell = rnn.LSTMCell(6, input_size=4)
    cell.initialize()
    cell.i2h_weight.set_data(fused.l0_i2h_weight.data())
    cell.h2h_weight.set_data(fused.l0_h2h_weight.data())
    cell.i2h_bias.set_data(fused.l0_i2h_bias.data())
    cell.h2h_bias.set_data(fused.l0_h2h_bias.data())
    xs = _x(7, 2, 4, seed=3)
    of = fused(xs)
    oc, _ = cell.unroll(7, xs, layout="TNC", merge_outputs=True)
    np.testing.assert_allclose(of.asnumpy(), oc.asnumpy(), atol=1e-5)


def test_gradients_flow():
    net = rnn.LSTM(8, num_layers=2)
    net.initialize()
    x = _x(5, 3, 4)
    with mx.autograd.record():
        loss = net(x).sum()
    loss.backward()
    for name in ("l0_i2h_weight", "l1_h2h_weight", "l0_i2h_bias"):
        g = getattr(net, name).grad().asnumpy()
        assert np.abs(g).sum() > 0, name


def test_deferred_init_and_repr():
    net = rnn.LSTM(8)
    net.initialize()
    net(_x(2, 2, 5))
    assert net.l0_i2h_weight.shape == (32, 5)
    assert "LSTM" in repr(net)


def test_state_shape_validation():
    net = rnn.GRU(8, input_size=4)
    net.initialize()
    bad = [mx.nd.zeros((1, 9, 8))]
    with pytest.raises(ValueError):
        net(_x(3, 2, 4), bad)


def test_unfuse():
    net = rnn.LSTM(6, num_layers=2, input_size=4)
    net.initialize()
    stack = net.unfuse()
    stack.initialize()
    out, _ = stack.unroll(5, _x(5, 2, 4), layout="TNC", merge_outputs=True)
    assert out.shape == (5, 2, 6)


def test_use_sequence_length():
    """Variable-length fused RNN: padding must not affect states/outputs."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.nn import rnn_fused

    rng = np.random.RandomState(7)
    T, N, I, H = 6, 3, 4, 5
    x = rng.randn(T, N, I).astype("float32")
    lens = np.array([6, 3, 1], dtype="int32")
    nparams = 4 * H * I + 4 * H * H + 2 * 4 * H
    params = rng.randn(nparams).astype("float32") * 0.1
    h0 = np.zeros((1, N, H), "float32")
    c0 = np.zeros((1, N, H), "float32")

    out, hT, cT = rnn_fused(jnp.array(x), jnp.array(params), jnp.array(h0),
                            jnp.array(c0), jnp.array(lens), mode="lstm",
                            state_size=H, state_outputs=True,
                            use_sequence_length=True)
    # sample 1 (len 3): same as running only its first 3 steps unpadded
    out_ref, hT_ref, cT_ref = rnn_fused(
        jnp.array(x[:3, 1:2]), jnp.array(params), jnp.array(h0[:, 1:2]),
        jnp.array(c0[:, 1:2]), mode="lstm", state_size=H, state_outputs=True)
    np.testing.assert_allclose(np.asarray(out)[:3, 1], np.asarray(out_ref)[:, 0],
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(hT)[0, 1], np.asarray(hT_ref)[0, 0],
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(cT)[0, 1], np.asarray(cT_ref)[0, 0],
                               atol=1e-6)
    # outputs past valid length are zero
    assert np.abs(np.asarray(out)[3:, 1]).max() == 0
    assert np.abs(np.asarray(out)[1:, 2]).max() == 0


def test_bidirectional_sequence_length():
    """Reverse direction must see real tokens first (SequenceReverse)."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.nn import rnn_fused

    rng = np.random.RandomState(11)
    T, N, I, H = 5, 2, 3, 4
    x = rng.randn(T, N, I).astype("float32")
    lens = np.array([5, 2], dtype="int32")
    isz = 4 * H * I + 4 * H * H
    rsz = 4 * H * I + 4 * H * H
    nparams = isz + rsz + 4 * 4 * H
    params = rng.randn(nparams).astype("float32") * 0.1
    h0 = np.zeros((2, N, H), "float32")
    c0 = np.zeros((2, N, H), "float32")
    out, hT, _ = rnn_fused(jnp.array(x), jnp.array(params), jnp.array(h0),
                           jnp.array(c0), jnp.array(lens), mode="lstm",
                           state_size=H, state_outputs=True,
                           bidirectional=True, use_sequence_length=True)
    # sample 1 (len 2): equivalent to unpadded bidirectional run of length 2
    out_ref, hT_ref, _ = rnn_fused(
        jnp.array(x[:2, 1:2]), jnp.array(params), jnp.array(h0[:, 1:2]),
        jnp.array(c0[:, 1:2]), mode="lstm", state_size=H, state_outputs=True,
        bidirectional=True)
    np.testing.assert_allclose(np.asarray(out)[:2, 1], np.asarray(out_ref)[:, 0],
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(hT)[:, 1], np.asarray(hT_ref)[:, 0],
                               atol=1e-6)


def test_lstm_state_clip_per_step():
    """Clipping applies to the cell state at every step, not just the end."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.nn import rnn_fused

    rng = np.random.RandomState(3)
    T, N, I, H = 8, 1, 2, 3
    x = (rng.randn(T, N, I) * 10).astype("float32")
    nparams = 4 * H * I + 4 * H * H + 2 * 4 * H
    params = (rng.randn(nparams) * 2).astype("float32")
    h0 = np.zeros((1, N, H), "float32")
    c0 = np.zeros((1, N, H), "float32")
    out_c, _, _ = rnn_fused(jnp.array(x), jnp.array(params), jnp.array(h0),
                            jnp.array(c0), mode="lstm", state_size=H,
                            state_outputs=True, lstm_state_clip_min=-0.1,
                            lstm_state_clip_max=0.1)
    out_u, _, _ = rnn_fused(jnp.array(x), jnp.array(params), jnp.array(h0),
                            jnp.array(c0), mode="lstm", state_size=H,
                            state_outputs=True)
    # per-step clip bounds every hidden output by tanh(0.1)
    assert np.abs(np.asarray(out_c)).max() <= np.tanh(0.1) + 1e-6
    assert not np.allclose(np.asarray(out_c), np.asarray(out_u))
