"""Imperative fast path: jitted op-dispatch cache + engine bulking.

Covers the MXNET_IMPERATIVE_JIT dispatch cache (numerics parity fast vs
untraced, retrace behavior on shape/dtype/attr change, AMP-version cache
invalidation, gradients through jitted forwards, NaiveEngine error
surfacing) and the engine.bulk() lazy segment (accumulate/flush semantics,
sync points, parity). The repeated-op cache-hit test is the tier-1 smoke
guard: it fails if the fast path silently rots into always-falling-back.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, profiler
from mxnet_tpu import c_runtime
from mxnet_tpu.ndarray import register as R
from mxnet_tpu.ops import registry as _registry


@pytest.fixture(autouse=True)
def _fast_path_on():
    prev = R.set_imperative_jit(True)
    R.reset_dispatch_stats()
    yield
    R.set_imperative_jit(prev)


def _warm(f, n=3):
    """Call f enough times that the dispatch cache compiles (the cache
    only jits a key once it repeats)."""
    out = None
    for _ in range(n):
        out = f()
    return out


# ---------------------------------------------------------------------------
# dispatch cache
# ---------------------------------------------------------------------------

def test_cache_registers_hits_on_repeated_op():
    # tier-1 smoke guard (CI): a repeated op MUST produce cache hits
    x = mx.nd.ones((4, 4))
    y = mx.nd.ones((4, 4))
    R.reset_dispatch_stats()
    for _ in range(5):
        (x * y).wait_to_read()
    st = R.dispatch_stats()
    assert st["hits"] > 0, st
    assert st["misses"] >= 1, st
    # the profiler exposes the same counters and includes them in dumps()
    assert profiler.imperative_stats()["hits"] == st["hits"]
    assert "imperative dispatch:" in profiler.dumps()


def test_numerics_parity_fast_vs_slow_bitwise():
    rs = np.random.RandomState(0)
    x = mx.nd.array((rs.rand(8, 8) + 0.5).astype("float32"))
    y = mx.nd.array((rs.rand(8, 8) + 0.5).astype("float32"))
    cases = {
        "add": lambda: x + y,
        "subtract": lambda: x - y,
        "multiply": lambda: x * y,
        "divide": lambda: x / y,
        "mul_scalar": lambda: x * 2.5,
        "add_scalar": lambda: x + 1.25,
        "relu": lambda: mx.nd.relu(x - 0.7),
        "sigmoid": lambda: mx.nd.sigmoid(x),
        "exp": lambda: mx.nd.exp(x),
        "softmax": lambda: mx.nd.softmax(x),
        "dot": lambda: mx.nd.dot(x, y),
        "sum_axis": lambda: mx.nd.sum(x, axis=1),
        "reshape": lambda: x.reshape((4, 16)),
    }
    for name, f in cases.items():
        R.set_imperative_jit(False)
        slow = f().asnumpy()
        R.set_imperative_jit(True)
        fast = _warm(f).asnumpy()
        assert np.array_equal(slow, fast), \
            "bitwise mismatch for %s" % name


def test_retrace_on_shape_change_new_key_on_attr_change():
    def run(arr, **kw):
        out = None
        for _ in range(3):
            out = mx.nd.sum(arr, **kw)
        return out

    R._clear_dispatch_cache()  # key-space isolation from other tests
    R.reset_dispatch_stats()
    run(mx.nd.ones((4, 5)))
    assert R.dispatch_stats()["retraces"] == 0
    # same op+attrs, new shape -> retrace
    run(mx.nd.ones((6, 7)))
    assert R.dispatch_stats()["retraces"] == 1
    # same op+attrs, new dtype -> retrace
    run(mx.nd.ones((4, 5), dtype="int32"))
    assert R.dispatch_stats()["retraces"] == 2
    # attr change -> different signature entirely (miss, not a retrace)
    before = R.dispatch_stats()
    run(mx.nd.ones((4, 5)), axis=1)
    after = R.dispatch_stats()
    assert after["retraces"] == before["retraces"]
    assert after["misses"] > before["misses"]


def test_amp_version_bump_invalidates_cache():
    x = mx.nd.ones((3, 3))
    _warm(lambda: x + x)
    R.reset_dispatch_stats()
    (x + x).wait_to_read()
    assert R.dispatch_stats()["hits"] == 1
    # any hook change bumps _amp_version: previously cached entries must
    # not be reused (the hook may rewrite inputs)
    R.set_amp_cast_hook(None)
    R.reset_dispatch_stats()
    (x + x).wait_to_read()
    st = R.dispatch_stats()
    assert st["hits"] == 0 and st["misses"] == 1, st


def test_gradients_through_jitted_ops():
    rs = np.random.RandomState(0)
    av = rs.rand(5, 4).astype("float32")
    bv = (rs.rand(5, 4) + 0.5).astype("float32")

    def grads():
        a = mx.nd.array(av)
        b = mx.nd.array(bv)
        a.attach_grad()
        b.attach_grad()
        with autograd.record():
            out = mx.nd.sum(mx.nd.sigmoid(a * b + 1.0) * a)
        out.backward()
        return a.grad.asnumpy(), b.grad.asnumpy()

    R.set_imperative_jit(False)
    ga_slow, gb_slow = grads()
    R.set_imperative_jit(True)
    ga_fast, gb_fast = _warm(grads)
    np.testing.assert_allclose(ga_fast, ga_slow, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(gb_fast, gb_slow, rtol=1e-6, atol=1e-6)
    # second-order entry points still work through the jitted forwards
    a = mx.nd.array(av)
    a.attach_grad()
    with autograd.record():
        out = (a * a).sum()
    out.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), 2 * av, rtol=1e-6)


def test_nojit_op_falls_back_and_matches_eager():
    if "_test_nojit_double" not in _registry._OPS:
        @_registry.register("_test_nojit_double", no_grad=True, nojit=True)
        def _test_nojit_double(x):
            # genuine host callback: concretizes the input
            return jnp.asarray(np.asarray(x) * 2.0)
    R.reset_dispatch_stats()
    out = R.invoke_by_name("_test_nojit_double", mx.nd.ones((2, 2)))
    np.testing.assert_array_equal(out.asnumpy(), np.full((2, 2), 2.0))
    assert R.dispatch_stats()["fallbacks"] == 1


def test_trace_incompatible_op_auto_falls_back():
    if "_test_datadep" not in _registry._OPS:
        @_registry.register("_test_datadep", no_grad=True)
        def _test_datadep(x):
            # data-dependent host branch: fails under trace, fine eagerly
            return x + float(np.asarray(x).sum())
    R.reset_dispatch_stats()
    xs = mx.nd.ones((3,))
    expect = np.ones(3) + 3.0
    for _ in range(4):
        out = R.invoke_by_name("_test_datadep", xs)
    np.testing.assert_allclose(out.asnumpy(), expect)
    st = R.dispatch_stats()
    assert st["fallbacks"] >= 1, st
    assert st["hits"] == 0, st


def test_naive_engine_errors_at_faulting_op(monkeypatch):
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    assert engine.is_naive()
    x = mx.nd.ones((2, 3))
    bad = mx.nd.ones((4, 5))
    # the error must surface at the faulting call, not a later sync point
    with pytest.raises(Exception):
        mx.nd.dot(x, bad)
    # and a valid op still runs (forced-sync path)
    out = _warm(lambda: x * 2.0)
    np.testing.assert_array_equal(out.asnumpy(), np.full((2, 3), 2.0))


# ---------------------------------------------------------------------------
# bulking
# ---------------------------------------------------------------------------

def test_bulk_accumulates_and_flushes_at_read():
    x = mx.nd.array(np.arange(6, dtype="float32").reshape(2, 3))
    with engine.bulk(8):
        c = x
        for _ in range(5):
            c = c + 1.0
        assert R.bulk_segment_depth() == 5
        got = c.asnumpy()  # read = sync point
        assert R.bulk_segment_depth() == 0
    np.testing.assert_array_equal(got, x.asnumpy() + 5)
    assert R.dispatch_stats()["bulk_ops"] == 5
    assert R.dispatch_stats()["bulk_flushes"] >= 1


def test_bulk_flushes_when_segment_full():
    x = mx.nd.ones((2, 2))
    with engine.bulk(3):
        c = x + 1.0
        c = c + 1.0
        assert R.bulk_segment_depth() == 2
        c = c + 1.0  # hits bulk_size() -> auto flush
        assert R.bulk_segment_depth() == 0
        np.testing.assert_array_equal(c.asnumpy(), np.full((2, 2), 4.0))


def test_bulk_parity_bitwise_with_eager():
    rs = np.random.RandomState(1)
    x = mx.nd.array(rs.rand(6, 6).astype("float32"))
    y = mx.nd.array((rs.rand(6, 6) + 0.5).astype("float32"))

    def chain():
        c = x
        for _ in range(3):
            c = c * 0.5
            c = mx.nd.softmax(c)
            c = c + y
        return c

    R.set_imperative_jit(False)
    eager = chain().asnumpy()
    R.set_imperative_jit(True)
    for _ in range(2):
        with engine.bulk(16):
            bulked = chain().asnumpy()
    assert np.array_equal(eager, bulked)


def test_wait_for_all_drains_bulk_segment():
    x = mx.nd.ones((3,))
    with engine.bulk(16):
        c = x + 41.0
        assert R.bulk_segment_depth() == 1
        engine.wait_for_all()
        assert R.bulk_segment_depth() == 0
    np.testing.assert_array_equal(c.asnumpy(), np.full((3,), 42.0))


def test_waitall_drains_bulk_segment():
    x = mx.nd.ones((3,))
    with engine.bulk(16):
        c = x * 3.0
        assert R.bulk_segment_depth() == 1
        mx.nd.waitall()
        assert R.bulk_segment_depth() == 0
    np.testing.assert_array_equal(c.asnumpy(), np.full((3,), 3.0))


def test_autograd_is_a_bulk_sync_point():
    x = mx.nd.ones((3,))
    x.attach_grad()
    with engine.bulk(16):
        base = mx.nd.ones((3,)) * 2.0  # queued (not recording)
        with autograd.record():
            out = x * base  # consumes the pending array -> flush
        out.backward()
    np.testing.assert_array_equal(x.grad.asnumpy(), np.full((3,), 2.0))


def test_bulk_scope_exit_flushes():
    x = mx.nd.ones((2,))
    with engine.bulk(16):
        c = x + 1.0
        assert R.bulk_segment_depth() == 1
    # scope exit flushed; the array must be concrete without further sync
    assert R.bulk_segment_depth() == 0
    np.testing.assert_array_equal(c.asnumpy(), np.full((2,), 2.0))


def test_bulk_with_fast_path_disabled_is_knob_only():
    R.set_imperative_jit(False)
    x = mx.nd.ones((2,))
    with engine.bulk(8):
        c = x + 1.0
        assert R.bulk_segment_depth() == 0  # executed eagerly
    np.testing.assert_array_equal(c.asnumpy(), np.full((2,), 2.0))


def test_nested_bulk_scopes_compose():
    x = mx.nd.ones((2,))
    with engine.bulk(8):
        a = x + 1.0
        with engine.bulk(4):
            b = a + 1.0
            assert engine.bulk_size() == 4
        # inner exit restored the outer segment; ops still bulk
        c = b + 1.0
        assert R.bulk_segment_depth() >= 1
        assert engine.bulk_size() == 8
    np.testing.assert_array_equal(c.asnumpy(), np.full((2,), 4.0))


def test_scalar_attr_type_is_part_of_cache_key():
    # 2 == 2.0 == True hash-collide; replaying an int-2 closure for a
    # float-2.0 call would change dtype promotion vs the untraced path
    x = mx.nd.array(np.ones((3,), "int32"))
    _warm(lambda: x * 2)          # caches the int-attr closure
    d_int = (x * 2).dtype
    d_float = _warm(lambda: x * 2.0).dtype
    R.set_imperative_jit(False)
    assert (x * 2).dtype == d_int
    assert (x * 2.0).dtype == d_float
    assert d_int != d_float  # int stays int32; float promotes


def test_out_delivery_does_not_flush_bulk_segment():
    x = mx.nd.ones((2, 2))
    y = mx.nd.ones((2, 2))
    o = mx.nd.zeros((2, 2))
    R.reset_dispatch_stats()
    with engine.bulk(16):
        for _ in range(4):
            mx.nd.broadcast_add(x, y, out=o)
        assert R.dispatch_stats()["bulk_flushes"] == 0
        np.testing.assert_array_equal(o.asnumpy(), np.full((2, 2), 2.0))
    assert R.dispatch_stats()["bulk_flushes"] == 1


def test_bulk_attr_mutation_between_queue_and_flush():
    x = mx.nd.array(np.arange(6, dtype="float32").reshape(2, 3))
    axes = [1, 0]
    with engine.bulk(8):
        y = mx.nd.transpose(x, axes=axes)
        axes[0], axes[1] = 0, 1  # caller mutates the attr before flush
        got = y.asnumpy()
    np.testing.assert_array_equal(got, x.asnumpy().T)


def test_optimizer_updates_fuse_inside_bulk():
    w = mx.nd.ones((8,))
    g = mx.nd.ones((8,)) * 0.1
    m = mx.nd.zeros((8,))
    R.reset_dispatch_stats()
    with engine.bulk(16):
        mx.nd.sgd_mom_update(w, g, m, lr=0.1, momentum=0.9, out=w)
        mx.nd.sgd_mom_update(w, g, m, lr=0.1, momentum=0.9, out=w)
        assert R.dispatch_stats()["bulk_flushes"] == 0  # still queued
    assert R.dispatch_stats()["bulk_flushes"] == 1
    assert R.dispatch_stats()["bulk_ops"] == 2
    # parity with the untraced path
    we, ge, me = mx.nd.ones((8,)), mx.nd.ones((8,)) * 0.1, mx.nd.zeros((8,))
    R.set_imperative_jit(False)
    mx.nd.sgd_mom_update(we, ge, me, lr=0.1, momentum=0.9, out=we)
    mx.nd.sgd_mom_update(we, ge, me, lr=0.1, momentum=0.9, out=we)
    assert np.array_equal(w.asnumpy(), we.asnumpy())
    assert np.array_equal(m.asnumpy(), me.asnumpy())


def test_one_shot_segment_signature_replays_eagerly():
    # a per-step attr change (lr schedule) makes every segment signature
    # unique; those must NOT pay a whole-segment trace+compile per flush
    w = mx.nd.ones((8,))
    g = mx.nd.ones((8,)) * 0.1
    m = mx.nd.zeros((8,))
    n0 = len(R._SEGMENT_CACHE)
    for i in range(5):
        with engine.bulk(8):
            mx.nd.sgd_mom_update(w, g, m, lr=0.1 / (113.7 + i),
                                 momentum=0.9, out=w)
    assert len(R._SEGMENT_CACHE) == n0  # replayed eagerly, not compiled
    # and a REPEATED signature still compiles (second sight)
    for _ in range(3):
        with engine.bulk(8):
            mx.nd.sgd_mom_update(w, g, m, lr=0.0625, momentum=0.9, out=w)
    assert len(R._SEGMENT_CACHE) == n0 + 1


def test_failed_flush_does_not_leave_zombie_segment():
    if "_test_exit_boom" not in _registry._OPS:
        import jax

        @_registry.register("_test_exit_boom", no_grad=True)
        def _test_exit_boom(q):
            def cb(v):
                raise ValueError("exit boom")
            return jax.pure_callback(
                cb, jax.ShapeDtypeStruct(q.shape, q.dtype), q)
    x = mx.nd.ones((2,))
    with pytest.raises(Exception):
        with engine.bulk(16):
            R.invoke_by_name("_test_exit_boom", x)
            # no sync point before scope exit: the flush at exit raises
    # the segment must be gone and the bulk size restored
    assert R.bulk_segment_depth() == 0
    y = x + 1.0  # must execute eagerly, not queue into a zombie segment
    np.testing.assert_array_equal(y.asnumpy(), np.full((2,), 2.0))


def test_engine_set_bulk_size_returns_prev_int():
    prev = c_runtime.engine_set_bulk_size(7)
    assert isinstance(prev, int)
    assert c_runtime.engine_set_bulk_size(prev) == 7
    assert engine.bulk_size() == prev


def test_set_bulk_size_is_a_segment_boundary():
    x = mx.nd.ones((2,))
    with engine.bulk(16):
        c = x + 1.0
        assert R.bulk_segment_depth() == 1
        engine.set_bulk_size(engine.bulk_size())  # resize -> flush
        assert R.bulk_segment_depth() == 0
    np.testing.assert_array_equal(c.asnumpy(), np.full((2,), 2.0))
