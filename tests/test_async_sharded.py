"""Sharded async PS server group (VERDICT r3 item 6): key placement
across servers (EncodeDefaultKey semantics), big-array splitting, and
clean multi-server shutdown with zero done() warnings.

ref: src/kvstore/kvstore_dist.h:58 MXNET_KVSTORE_BIGARRAY_BOUND,
:263 EncodeDefaultKey (small keys -> key %% num_servers; big arrays
sliced across the whole group).
"""
import multiprocessing as mp
import os
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx

# minutes-scale on the 1-core CI host (subprocess clusters / full
# registry sweep / JPEG decode) — deselect with -m 'not slow' for
# the quick lane; the full lane always runs them
pytestmark = pytest.mark.slow


@pytest.fixture()
def sharded_env(monkeypatch):
    monkeypatch.delenv("MXTPU_COORDINATOR", raising=False)
    monkeypatch.setenv("MXTPU_PROC_ID", "0")
    monkeypatch.setenv("MXTPU_NUM_PROCS", "1")
    monkeypatch.setenv("MXTPU_NUM_SERVERS", "2")
    monkeypatch.setenv("MXTPU_ASYNC_PS_PORT", "0")
    # serve_group publishes bound ports into these; ensure they are
    # both absent at entry and restored at teardown
    monkeypatch.delenv("MXTPU_ASYNC_PS_PORT_1", raising=False)
    monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "1000")
    yield


def test_key_placement_and_split(sharded_env):
    kv = mx.kv.create("dist_async")
    try:
        assert len(kv._servers) == 2 and len(kv._clients) == 2
        # small int keys place at key % num_servers (EncodeDefaultKey)
        kv.init(0, mx.nd.array(np.ones((4,), np.float32)))
        kv.init(1, mx.nd.array(np.full((4,), 2.0, np.float32)))
        assert tuple(kv._clients[0].shape_of(0)) == (4,)
        assert tuple(kv._clients[1].shape_of(1)) == (4,)
        with pytest.raises(Exception):
            kv._clients[1].shape_of(0)  # not on the other server
        # big array splits into contiguous flat shards, one per server
        big = np.arange(2500, dtype=np.float32).reshape(50, 50)
        kv.init("w_big", mx.nd.array(big))
        assert "w_big" in kv._split
        lens = kv._split["w_big"][2]
        assert sum(lens) == 2500 and len(lens) == 2
        s0 = kv._clients[0].pull("w_big#s0")
        s1 = kv._clients[1].pull("w_big#s1")
        np.testing.assert_allclose(
            np.concatenate([s0.ravel(), s1.ravel()]), big.ravel())
        # pull reassembles
        out = mx.nd.array(np.zeros_like(big))
        kv.pull("w_big", out=out)
        np.testing.assert_allclose(out.asnumpy(), big)
    finally:
        kv.close()


def test_split_push_through_optimizer(sharded_env):
    import mxnet_tpu.optimizer as opt
    kv = mx.kv.create("dist_async")
    try:
        w0 = np.ones((60, 30), np.float32)  # 1800 > bound -> split
        kv.init("w", mx.nd.array(w0))
        kv.set_optimizer(opt.create("sgd", learning_rate=0.5, wd=0.0))
        kv.push("w", mx.nd.array(np.full_like(w0, 2.0)))
        out = mx.nd.array(np.zeros_like(w0))
        kv.pull("w", out=out)
        # w - lr * g = 1 - 0.5*2 = 0, uniformly across BOTH shards
        np.testing.assert_allclose(out.asnumpy(), 0.0, atol=1e-6)
        assert kv.updates_applied() == 2  # one per server shard
    finally:
        kv.close()


def test_split_push_compressed(sharded_env):
    import mxnet_tpu.optimizer as opt
    kv = mx.kv.create("dist_async")
    try:
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5,
                                     "size_lower_bound": 128})
        w0 = np.ones((2048,), np.float32)
        kv.init("wc", mx.nd.array(w0))
        kv.set_optimizer(opt.create("sgd", learning_rate=1.0, wd=0.0))
        kv.push("wc", mx.nd.array(np.full_like(w0, 0.9)))
        out = mx.nd.array(np.zeros_like(w0))
        kv.pull("wc", out=out)
        # 2-bit quantizes grad 0.9 -> threshold 0.5; w = 1 - 0.5
        np.testing.assert_allclose(out.asnumpy(), 0.5, atol=1e-6)
    finally:
        kv.close()


def test_clean_shutdown_no_warnings(sharded_env):
    """Done-criterion: shutdown with ZERO stall warnings."""
    kv = mx.kv.create("dist_async")
    kv.init(7, mx.nd.array(np.zeros((4,), np.float32)))
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        kv.close()  # would raise if the done() stall warning fired


def _sharded_worker(rank, nproc, port0, port1):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["MXTPU_PROC_ID"] = str(rank)
    os.environ["MXTPU_NUM_PROCS"] = str(nproc)
    os.environ["MXTPU_NUM_SERVERS"] = "2"
    os.environ["MXTPU_ASYNC_PS_PORT"] = port0
    os.environ["MXTPU_ASYNC_PS_PORT_1"] = port1
    os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"] = "1000"
    import warnings as _w
    import mxnet_tpu as mx2
    with _w.catch_warnings():
        _w.simplefilter("error", RuntimeWarning)
        kv = mx2.kv.create("dist_async")
        kv.init(0, mx2.nd.array(np.zeros((4,), np.float32)))
        kv.init(1, mx2.nd.array(np.zeros((4,), np.float32)))
        kv.init("big", mx2.nd.array(np.zeros((1600,), np.float32)))
        kv.push(0, mx2.nd.array(np.ones((4,), np.float32)))
        kv.push(1, mx2.nd.array(np.ones((4,), np.float32)))
        kv.push("big", mx2.nd.array(np.ones((1600,), np.float32)))
        kv._barrier()
        out = mx2.nd.array(np.zeros((1600,), np.float32))
        kv.pull("big", out=out)
        # sum semantics without optimizer: every worker's push landed
        assert out.asnumpy().sum() >= 1600, out.asnumpy().sum()
        kv.close()  # clean: zero RuntimeWarnings or we exit nonzero


def test_multiprocess_two_servers():
    """3 workers, 2 servers hosted by ranks 0 and 1; keys split across
    both; every worker shuts down with zero stall warnings."""
    os.environ.pop("MXTPU_COORDINATOR", None)
    os.environ["MXTPU_PROC_ID"] = "0"
    os.environ["MXTPU_NUM_PROCS"] = "3"
    os.environ["MXTPU_NUM_SERVERS"] = "2"
    os.environ["MXTPU_ASYNC_PS_PORT"] = "0"
    os.environ.pop("MXTPU_ASYNC_PS_PORT_1", None)
    os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"] = "1000"
    os.environ["MXTPU_PS_DONE_TIMEOUT"] = "60"
    try:
        # pre-agree server 1's port BEFORE rank 0 builds its client set
        # (rank 1 will host it; rank 0 needs the address at construction)
        import socket
        with socket.socket() as s:
            s.bind(("", 0))
            port1 = str(s.getsockname()[1])
        os.environ["MXTPU_ASYNC_PS_PORT_1"] = port1
        # rank 0 (this process) hosts server 0; rank 1 hosts server 1
        kv = mx.kv.create("dist_async")
        try:
            assert len(kv._servers) == 1  # rank 0 hosts exactly server 0
            port0 = os.environ["MXTPU_ASYNC_PS_PORT"]
            ctx = mp.get_context("spawn")
            procs = [ctx.Process(target=_sharded_worker,
                                 args=(r, 3, port0, port1))
                     for r in (1, 2)]
            for p in procs:
                p.start()
            # this process is ALSO worker rank 0; signal done BEFORE
            # joining (rank 1's close waits for our done on server 1)
            _rank0_worker_body(kv)
            kv.done()
            for p in procs:
                p.join(120)
            assert all(p.exitcode == 0 for p in procs), \
                [p.exitcode for p in procs]
        finally:
            with warnings.catch_warnings():
                warnings.simplefilter("error", RuntimeWarning)
                kv.close()
    finally:
        for k in ("MXTPU_NUM_SERVERS", "MXTPU_ASYNC_PS_PORT_1",
                  "MXNET_KVSTORE_BIGARRAY_BOUND"):
            os.environ.pop(k, None)


def _rank0_worker_body(kv):
    kv.init(0, mx.nd.array(np.zeros((4,), np.float32)))
    kv.init(1, mx.nd.array(np.zeros((4,), np.float32)))
    kv.init("big", mx.nd.array(np.zeros((1600,), np.float32)))
    kv.push("big", mx.nd.array(np.ones((1600,), np.float32)))
    kv._barrier()


def test_row_sparse_init_routes_whole_key(sharded_env):
    """A big row-sparse param must NOT be flat-split (its RSP pushes
    are whole-key routed) — review r4 finding."""
    from mxnet_tpu.ndarray.sparse import row_sparse_array
    kv = mx.kv.create("dist_async")
    try:
        dense = np.ones((64, 32), np.float32)  # 2048 > bound
        rsp = row_sparse_array((dense, np.arange(64)), shape=(64, 32))
        kv.init("emb", rsp)
        assert "emb" not in kv._split
        owner = kv._owner("emb")
        assert tuple(kv._clients[owner].shape_of("emb")) == (64, 32)
        # RSP push lands on the same server
        kv.push("emb", row_sparse_array(
            (np.full((2, 32), 3.0, np.float32), np.array([1, 5])),
            shape=(64, 32)))
        out = kv._clients[owner].pull("emb")
        # no optimizer installed: pushed rows are assigned (async apply)
        np.testing.assert_allclose(out[1], 3.0)
        np.testing.assert_allclose(out[0], 1.0)  # untouched row intact
    finally:
        kv.close()
