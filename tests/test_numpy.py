"""mx.np / mx.npx frontend tests (ref: tests/python/unittest/test_numpy_op.py,
test_numpy_ndarray.py, numpy_dispatch_protocol tests)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np, npx, autograd


class TestNdarray:
    def test_zero_dim(self):
        a = np.array(3.5)
        assert a.shape == ()
        assert float(a) == 3.5
        assert a.ndim == 0

    def test_creation(self):
        assert np.zeros((2, 3)).dtype == onp.float32
        assert np.ones((2,), dtype=np.int32).dtype == onp.int32
        assert np.full((2,), 7.0).asnumpy().tolist() == [7.0, 7.0]
        assert np.arange(5).shape == (5,)
        assert np.eye(3).asnumpy()[1, 1] == 1
        a, step = np.linspace(0, 1, 5, retstep=True)
        assert a.shape == (5,) and abs(step - 0.25) < 1e-6

    def test_float64_input_downcast(self):
        # reference np default dtype is float32
        assert np.array([1.0, 2.0]).dtype == onp.float32

    def test_operators_promotion(self):
        a = np.array([1.0, 2.0])
        b = np.arange(2)  # float32 by reference convention
        assert (a + b).dtype == onp.float32
        assert (a / 2).asnumpy().tolist() == [0.5, 1.0]
        assert (a // 2).asnumpy().tolist() == [0.0, 1.0]
        assert (a ** 2).asnumpy().tolist() == [1.0, 4.0]
        assert (a @ a).shape == ()

    def test_boolean_indexing(self):
        a = np.array([[1.0, 2.0], [3.0, 4.0]])
        assert a[a > 2].asnumpy().tolist() == [3.0, 4.0]

    def test_methods(self):
        a = np.array([[1.0, 2.0], [3.0, 4.0]])
        assert a.sum(axis=0).asnumpy().tolist() == [4.0, 6.0]
        assert a.mean() .item() == 2.5
        assert a.reshape(4).shape == (4,)
        assert a.reshape(-1, 2).shape == (2, 2)
        assert a.T.shape == (2, 2)
        assert a.astype(np.int32).dtype == onp.int32
        assert a.flatten().shape == (4,)
        assert int(a.argmax()) == 3
        assert a.clip(2.0, 3.0).asnumpy().max() == 3.0
        assert a.tolist() == [[1.0, 2.0], [3.0, 4.0]]

    def test_bool_ambiguity(self):
        with pytest.raises(ValueError):
            bool(np.array([1.0, 2.0]))
        assert bool(np.array(1.0))

    def test_conversions(self):
        a = np.array([1.0])
        nd = a.as_nd_ndarray()
        assert type(nd) is mx.nd.NDArray
        assert type(nd.as_np_ndarray()) is np.ndarray


class TestFunctions:
    def test_delegated_surface(self):
        # a broad sample of the reference's mx.np function inventory
        for name in ("sin", "cos", "exp", "log", "sqrt", "tanh", "where",
                     "concatenate", "stack", "split", "tile", "repeat",
                     "einsum", "tensordot", "matmul", "dot", "unique",
                     "sort", "argsort", "maximum", "minimum", "isnan",
                     "isinf", "broadcast_to", "expand_dims", "squeeze",
                     "swapaxes", "moveaxis", "flip", "roll", "pad", "trace",
                     "tril", "triu", "cumsum", "median", "percentile",
                     "logical_and", "bincount", "meshgrid", "diff",
                     "nan_to_num", "take_along_axis", "searchsorted"):
            assert hasattr(np, name), name

    def test_where_and_unique(self):
        a = np.array([1.0, 2.0, 1.0])
        u = np.unique(a)
        assert u.asnumpy().tolist() == [1.0, 2.0]
        w = np.where(a > 1.5, a, np.zeros_like(a))
        assert w.asnumpy().tolist() == [0.0, 2.0, 0.0]

    def test_concat_stack(self):
        a, b = np.ones((2, 2)), np.zeros((2, 2))
        assert np.concatenate([a, b], axis=0).shape == (4, 2)
        assert np.stack([a, b]).shape == (2, 2, 2)
        parts = np.split(np.ones((4, 6)), 3, axis=1)
        assert len(parts) == 3 and parts[0].shape == (4, 2)

    def test_out_kwarg(self):
        a = np.array([1.0, 2.0])
        out = np.zeros((2,))
        r = np.add(a, a, out=out)
        assert r is out
        assert out.asnumpy().tolist() == [2.0, 4.0]

    def test_linalg(self):
        a = np.array([[2.0, 0.0], [0.0, 3.0]])
        assert abs(float(np.linalg.det(a)) - 6.0) < 1e-5
        u, s, vt = np.linalg.svd(a)
        assert sorted(s.asnumpy().tolist()) == [2.0, 3.0]
        x = np.linalg.solve(a, np.array([2.0, 3.0]))
        onp.testing.assert_allclose(x.asnumpy(), [1.0, 1.0], atol=1e-5)
        assert abs(float(np.linalg.norm(a)) - onp.sqrt(13)) < 1e-5


class TestAutograd:
    def test_grad_through_np(self):
        x = np.array([1.0, 2.0, 3.0])
        x.attach_grad()
        with autograd.record():
            y = (np.sin(x) ** 2).sum()
        y.backward()
        expect = 2 * onp.sin([1, 2, 3.0]) * onp.cos([1, 2, 3.0])
        onp.testing.assert_allclose(x.grad.asnumpy(), expect, atol=1e-6)
        assert isinstance(x.grad, np.ndarray)

    def test_grad_through_linalg(self):
        x = np.array([[3.0]])
        x.attach_grad()
        with autograd.record():
            y = np.linalg.norm(x)
        y.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), [[1.0]], atol=1e-6)

    def test_mixed_np_nd_graph(self):
        """np ops and registry ops share one tape."""
        x = np.array([[1.0, -2.0]])
        x.attach_grad()
        with autograd.record():
            h = npx.activation(x, act_type="relu")
            y = (h * 3.0).sum()
        y.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), [[3.0, 0.0]], atol=1e-6)


class TestRandom:
    def test_shapes_and_ranges(self):
        npx.seed(42)
        u = np.random.uniform(-2.0, 2.0, size=(100,))
        assert u.shape == (100,)
        assert float(u.min()) >= -2.0 and float(u.max()) <= 2.0
        n = np.random.normal(0.0, 1.0, size=(50,))
        assert n.shape == (50,)
        r = np.random.randint(0, 10, size=(20,))
        assert int(r.min()) >= 0 and int(r.max()) < 10
        assert np.random.rand(2, 3).shape == (2, 3)
        assert np.random.randn(2, 3).shape == (2, 3)
        assert np.random.choice(5, size=(7,)).shape == (7,)
        assert np.random.gamma(2.0, size=(4,)).shape == (4,)
        assert np.random.exponential(size=(4,)).shape == (4,)

    def test_seed_reproducible(self):
        npx.seed(7)
        a = np.random.uniform(size=(5,)).asnumpy()
        npx.seed(7)
        b = np.random.uniform(size=(5,)).asnumpy()
        onp.testing.assert_array_equal(a, b)

    def test_multinomial(self):
        counts = np.random.multinomial(20, [0.5, 0.5], size=(3,))
        assert counts.shape == (3, 2)
        assert (counts.asnumpy().sum(axis=-1) == 20).all()

    def test_shuffle_permutation(self):
        x = np.arange(10)
        np.random.shuffle(x)
        assert sorted(x.asnumpy().tolist()) == list(range(10))
        p = np.random.permutation(10)
        assert sorted(p.asnumpy().tolist()) == list(range(10))


class TestNpx:
    def test_nn_ops_return_np(self):
        x = np.array([[-1.0, 2.0]])
        h = npx.activation(x, act_type="relu")
        assert isinstance(h, np.ndarray)
        assert h.asnumpy().tolist() == [[0.0, 2.0]]
        s = npx.softmax(np.array([[1.0, 1.0]]))
        onp.testing.assert_allclose(s.asnumpy(), [[0.5, 0.5]], atol=1e-6)

    def test_fully_connected(self):
        x = np.ones((2, 3))
        w = np.ones((4, 3))
        b = np.zeros((4,))
        out = npx.fully_connected(x, w, b, num_hidden=4)
        assert out.shape == (2, 4)
        assert out.asnumpy()[0, 0] == 3.0

    def test_reshape_arange_like(self):
        assert npx.reshape_like(np.ones((6,)), np.ones((2, 3))).shape == (2, 3)
        al = npx.arange_like(np.ones((2, 3)), axis=1)
        assert al.asnumpy().tolist() == [0.0, 1.0, 2.0]
        al2 = npx.arange_like(np.ones((2, 2)))
        assert al2.shape == (2, 2)

    def test_set_np_flags(self):
        from mxnet_tpu import util
        npx.set_np()
        assert npx.is_np_array() and npx.is_np_shape()
        npx.reset_np()
        assert not npx.is_np_array()

    def test_save_load_roundtrip(self, tmp_path):
        f = str(tmp_path / "arrs")
        npx.save(f, {"w": np.ones((2, 2))})
        out = npx.load(f)
        assert isinstance(out["w"], np.ndarray)
        assert out["w"].asnumpy().tolist() == [[1.0, 1.0], [1.0, 1.0]]


class TestReviewRegressions:
    def test_sampler_kwargs_honored(self):
        e = np.random.exponential(scale=100.0, size=(20000,))
        assert abs(float(e.mean()) / 100.0 - 1.0) < 0.1
        g = np.random.gamma(shape=9.0, size=(20000,))
        assert abs(float(g.mean()) / 9.0 - 1.0) < 0.1
        # NumPy positional form: exponential(scale, size)
        assert np.random.exponential(2.0, 100).shape == (100,)

    def test_where_kwarg_rejected(self):
        with pytest.raises(TypeError):
            np.add(np.array([1.0]), np.array([2.0]),
                   where=np.array([True]))

    def test_take_list_and_modes(self):
        a = np.array([10.0, 20.0])
        assert a.take([1, 0]).asnumpy().tolist() == [20.0, 10.0]
        with pytest.raises(IndexError):
            a.take(np.array([10], dtype="int32"))
        assert a.take([5], mode="clip").asnumpy().tolist() == [20.0]
        assert a.take([3], mode="wrap").asnumpy().tolist() == [20.0]

    def test_leaky_relu_alias(self):
        out = npx.leaky_relu(np.array([[-1.0, 1.0]]), slope=0.1)
        onp.testing.assert_allclose(out.asnumpy(), [[-0.1, 1.0]], atol=1e-6)


def test_npx_gamma():
    import numpy as onp
    x = mx.np.array([0.5, 1.0, 3.5, -0.5])
    out = onp.asarray(mx.npx.gamma(x))
    # Gamma(0.5)=sqrt(pi), Gamma(3.5)=15/8*sqrt(pi), Gamma(-0.5)=-2*sqrt(pi)
    sp = onp.sqrt(onp.pi)
    onp.testing.assert_allclose(out, [sp, 1.0, 15.0 / 8.0 * sp, -2 * sp],
                                rtol=1e-5)
