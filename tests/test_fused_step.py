"""Fused train step (gluon/fused_step.py): one donated jitted program
for forward + backward + optimizer update.

Covers the ISSUE 4 acceptance surface: bitwise parity eager-vs-fused
for SGD / SGD(momentum) / Adam over >=3 steps including an lr-schedule
change and a batch_size (rescale divisor) change mid-run with ZERO
retraces, a save_states/load_states round-trip that resumes identically
on both paths, multi-precision masters, every eager-fallback reason
(counted, never a crash), and the fused_step.* counters / train_step
spans in the profiler.

Parity contract: the eager reference is the HYBRIDIZED eager path
(backward = vjp of the same jitted forward). The non-hybridized per-op
tape can differ by ~1 ULP because XLA fuses tiny dots differently per
compilation context.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, profiler
from mxnet_tpu.gluon import fused_step as FS


def _make_net(seed_from=None, hybridize=True, in_units=8):
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, in_units=in_units, activation="relu"))
        net.add(gluon.nn.Dense(1, in_units=16))
    net.initialize(mx.init.Uniform(0.1))
    if hybridize:
        net.hybridize()
    if seed_from is not None:
        for (_, p1), (_, p2) in zip(
                sorted(seed_from.collect_params().items()),
                sorted(net.collect_params().items())):
            p2.set_data(p1.data().astype("float32"))
    return net


def _batch(n=4, in_units=8, seed=0):
    rs = np.random.RandomState(seed)
    x = mx.nd.array(rs.rand(n, in_units).astype("float32"))
    y = mx.nd.array(rs.rand(n, 1).astype("float32"))
    return x, y


def _eager_step(net, loss_fn, trainer, x, y, batch_size):
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(batch_size)
    return loss


def _params_bitwise(net_a, net_b):
    return all(
        np.array_equal(pa.data().asnumpy(), pb.data().asnumpy())
        for (_, pa), (_, pb) in zip(
            sorted(net_a.collect_params().items()),
            sorted(net_b.collect_params().items())))


@pytest.mark.parametrize("algo,kwargs", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
    ("adagrad", {"learning_rate": 0.05}),
    ("rmsprop", {"learning_rate": 0.01, "centered": True}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01,
             "clip_gradient": 0.5}),
], ids=["sgd", "sgd-momentum", "adam", "adagrad", "rmsprop-centered",
        "sgd-wd-clip"])
def test_fused_bitwise_parity_with_replay(algo, kwargs):
    """>=3 parity steps, then an lr change and a batch_size (divisor)
    change mid-run — both must REPLAY the compiled program (operands,
    not constants): fused_step.retraces == 0 and parity stays bitwise."""
    x, y = _batch()
    loss_fn = gluon.loss.L2Loss()
    net_a = _make_net()
    net_b = _make_net(net_a)
    tr_a = gluon.Trainer(net_a.collect_params(), algo, dict(kwargs))
    tr_b = gluon.Trainer(net_b.collect_params(), algo, dict(kwargs))
    step = gluon.train_step(net_b, loss_fn, tr_b)
    FS.reset_stats()

    modes = []
    for _ in range(3):
        la = _eager_step(net_a, loss_fn, tr_a, x, y, 4)
        lb = step(x, y, batch_size=4)
        modes.append(step.last_mode)
        assert np.array_equal(la.asnumpy(), lb.asnumpy())
    assert modes == ["eager-warming", "compile", "fused"]

    # lr-schedule tick: a runtime operand, not a baked constant
    tr_a.set_learning_rate(kwargs["learning_rate"] / 3)
    tr_b.set_learning_rate(kwargs["learning_rate"] / 3)
    _eager_step(net_a, loss_fn, tr_a, x, y, 4)
    step(x, y, batch_size=4)
    assert step.last_mode == "fused"

    # batch_size divisor change (same tensors): rescale is an operand too
    _eager_step(net_a, loss_fn, tr_a, x, y, 8)
    step(x, y, batch_size=8)
    assert step.last_mode == "fused"

    st = FS.stats()
    assert st["retraces"] == 0, st
    assert st["fallbacks"] == 0, st
    assert st["hits"] >= 3, st
    assert _params_bitwise(net_a, net_b)
    # raw grads are adopted back into Parameter.grad() identically
    for (_, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                sorted(net_b.collect_params().items())):
        assert np.array_equal(pa.grad().asnumpy(), pb.grad().asnumpy())


def test_fuse_step_closure_form_matches_block_form():
    x, y = _batch()
    loss_fn = gluon.loss.L2Loss()
    net_a = _make_net()
    net_b = _make_net(net_a)
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.1})
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1})
    step_a = gluon.train_step(net_a, loss_fn, tr_a)
    step_b = tr_b.fuse_step(lambda xx, yy: loss_fn(net_b(xx), yy))
    for _ in range(3):
        la = step_a(x, y, batch_size=4)
        lb = step_b(x, y, batch_size=4)
        assert np.array_equal(la.asnumpy(), lb.asnumpy())
    assert step_b.last_mode == "fused"
    assert _params_bitwise(net_a, net_b)


def test_save_load_states_roundtrip_resumes_identically(tmp_path):
    """Mid-training checkpoint: both resume paths (eager and fused) must
    continue bitwise-identically — the fused step shares the updater's
    state store and the optimizer's update counts."""
    x, y = _batch()
    loss_fn = gluon.loss.L2Loss()
    pfile = str(tmp_path / "net.params")
    sfile = str(tmp_path / "trainer.states")

    net = _make_net()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    step = gluon.train_step(net, loss_fn, tr)
    for _ in range(3):
        step(x, y, batch_size=4)
    assert tr._optimizer.num_update == 3
    net.save_parameters(pfile)
    tr.save_states(sfile)

    def resume(fused):
        net2 = _make_net()
        net2.load_parameters(pfile)
        tr2 = gluon.Trainer(net2.collect_params(), "adam",
                            {"learning_rate": 0.01})
        tr2.load_states(sfile)
        assert tr2._optimizer.num_update == 3
        if fused:
            s2 = gluon.train_step(net2, loss_fn, tr2)
            for _ in range(3):
                s2(x, y, batch_size=4)
        else:
            for _ in range(3):
                _eager_step(net2, loss_fn, tr2, x, y, 4)
        return [p.data().asnumpy()
                for _, p in sorted(net2.collect_params().items())]

    fused_ws = resume(True)
    eager_ws = resume(False)
    for a, b in zip(fused_ws, eager_ws):
        assert np.array_equal(a, b)


def test_multi_precision_parity_fp16_master():
    x, y = _batch()
    x, y = x.astype("float16"), y.astype("float16")
    loss_fn = gluon.loss.L2Loss()
    net_a = _make_net()
    net_b = _make_net(net_a)
    net_a.cast("float16")
    net_b.cast("float16")
    kw = {"learning_rate": 0.1, "momentum": 0.9, "multi_precision": True}
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd", dict(kw))
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd", dict(kw))
    step = gluon.train_step(net_b, loss_fn, tr_b)
    for _ in range(4):
        _eager_step(net_a, loss_fn, tr_a, x, y, 4)
        step(x, y, batch_size=4)
    assert step.last_mode == "fused"
    assert _params_bitwise(net_a, net_b)
    # the fp32 masters (state[0] of each entry) stay bitwise too
    ua, ub = tr_a._updater, tr_b._updater
    for k in ua.states:
        assert np.array_equal(ua.states[k][0].asnumpy(),
                              ub.states[k][0].asnumpy())


def test_deferred_init_first_step_falls_back_then_fuses():
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu"))  # no in_units
        net.add(gluon.nn.Dense(1))
    net.initialize(mx.init.Uniform(0.1))
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
    x, y = _batch()
    step(x, y, batch_size=4)
    assert step.last_mode == "fallback:deferred-init"
    for _ in range(2):
        step(x, y, batch_size=4)
    assert step.last_mode == "compile"
    step(x, y, batch_size=4)
    assert step.last_mode == "fused"


# -- fallback reasons: counted, never a crash --------------------------------

def test_deferred_frozen_param_outside_trainer_falls_back():
    """A deferred-init parameter the TRAINER does not own (frozen layer
    in a fine-tune subset) must fall back, not crash with
    DeferredInitializationError at signature time."""
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu"))  # frozen, deferred
        net.add(gluon.nn.Dense(1, in_units=16))
    net.initialize(mx.init.Uniform(0.1))
    net.hybridize()
    # trainer owns only the second layer's params
    tr = gluon.Trainer(net[1].collect_params(), "sgd",
                       {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
    x, y = _batch()
    step(x, y, batch_size=4)
    assert step.last_mode == "fallback:deferred-init"
    for _ in range(2):
        step(x, y, batch_size=4)
    assert step.last_mode == "compile"


def test_ignore_stale_grad_skips_stale_params():
    """Reference semantics: ignore_stale_grad=True SKIPS params whose
    grad was not refreshed by backward instead of re-applying the old
    gradient (momentum would keep charging on unused weights)."""
    net = _make_net()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    x, y = _batch()
    loss_fn = gluon.loss.L2Loss()
    _eager_step(net, loss_fn, tr, x, y, 4)
    before = {n: p.data().asnumpy().copy()
              for n, p in net.collect_params().items()}
    # no new backward: every grad is stale — the step must be a no-op
    tr.step(4, ignore_stale_grad=True)
    for n, p in net.collect_params().items():
        assert np.array_equal(before[n], p.data().asnumpy()), n


def test_fallback_non_hybridized_block_still_trains():
    x, y = _batch()
    loss_fn = gluon.loss.L2Loss()
    net = _make_net(hybridize=False)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    step = gluon.train_step(net, loss_fn, tr)
    FS.reset_stats()
    before = [p.data().asnumpy().copy()
              for _, p in sorted(net.collect_params().items())]
    step(x, y, batch_size=4)
    assert step.last_mode == "fallback:non-hybridized"
    assert FS.stats()["fallbacks"] == 1
    after = [p.data().asnumpy()
             for _, p in sorted(net.collect_params().items())]
    assert any(not np.array_equal(a, b) for a, b in zip(before, after))


def test_fallback_kvstore_attached():
    x, y = _batch()
    net = _make_net()
    kv = mx.kv.create("local")
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                       kvstore=kv)
    step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
    step(x, y, batch_size=4)
    assert step.last_mode == "fallback:kvstore"


def test_fallback_unsupported_optimizer():
    x, y = _batch()
    net = _make_net()
    tr = gluon.Trainer(net.collect_params(), "ftml", {})
    step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
    step(x, y, batch_size=4)
    assert step.last_mode == "fallback:optimizer:FTML"


def test_fallback_disabled_via_toggle():
    x, y = _batch()
    net = _make_net()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
    prev = FS.set_fused_step(False)
    try:
        step(x, y, batch_size=4)
        assert step.last_mode == "fallback:disabled"
    finally:
        FS.set_fused_step(prev)


def test_fallback_inside_record_scope():
    x, y = _batch()
    net = _make_net()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
    with autograd.record():
        pass
    # a LIVE record scope must not let the fused program swallow the tape
    with autograd.record():
        inner = _batch()[0] * 1.0  # the scope is genuinely recording
        assert autograd.is_recording()
        step(x, y, batch_size=4)
    assert step.last_mode == "fallback:recording-scope"
    del inner


def test_fallback_amp_loss_scaler():
    """amp.init_trainer wraps Trainer._update with overflow-skip logic
    the fused program can't honor — such trainers run eagerly."""
    x, y = _batch()
    net = _make_net()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    tr._amp_loss_scaler = object()  # stand-in for amp.init_trainer
    step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
    step(x, y, batch_size=4)
    assert step.last_mode == "fallback:amp-loss-scaler"


def test_fallback_grad_req_add():
    x, y = _batch()
    net = _make_net()
    for p in net.collect_params().values():
        p.grad_req = "add"
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
    step(x, y, batch_size=4)
    assert step.last_mode == "fallback:grad-req-add"


def test_shape_change_is_a_retrace_not_a_failure():
    """A genuinely new input SHAPE compiles a second program and counts
    one retrace (the shape-churn indicator) — operand changes never do."""
    loss_fn = gluon.loss.L2Loss()
    net = _make_net()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    step = gluon.train_step(net, loss_fn, tr)
    FS.reset_stats()
    x4, y4 = _batch(4)
    x8, y8 = _batch(8)
    for _ in range(2):
        step(x4, y4, batch_size=4)
    for _ in range(2):
        step(x8, y8, batch_size=8)
    assert step.last_mode == "compile"
    assert FS.stats()["retraces"] == 1


# -- observability -----------------------------------------------------------

def test_counters_surface_in_profiler_metrics():
    x, y = _batch()
    net = _make_net()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
    FS.reset_stats()
    for _ in range(3):
        step(x, y, batch_size=4)
    m = profiler.metrics()
    assert m["fused_step"] == FS.stats()
    assert m["fused_step"]["misses"] == 2 and m["fused_step"]["hits"] == 1
    assert "fused_step" in profiler.dumps()


def test_train_step_span_in_gluon_lane(tmp_path):
    import json
    x, y = _batch()
    net = _make_net()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
    step(x, y, batch_size=4)  # warm outside the profile
    fn = str(tmp_path / "trace.json")
    profiler.set_config(filename=fn, xprof=False)
    profiler.set_state("run")
    try:
        step(x, y, batch_size=4)
        step(x, y, batch_size=4)
    finally:
        profiler.set_state("stop")
    profiler.dump()
    events = json.load(open(fn))["traceEvents"]
    spans = [e for e in events if e.get("name") == "gluon.train_step"]
    profiler._reset()
    assert spans, "no gluon.train_step span recorded"
    assert all(e["tid"] == profiler.LANES["gluon"] for e in spans)
    assert any(e.get("args", {}).get("mode") == "fused" for e in spans)
    assert all(e.get("args", {}).get("batch_size") == 4 for e in spans)


def test_fused_step_clean_under_lock_detector():
    """Acceptance: fused-step runs under the runtime lock-order detector
    (MXNET_DEBUG_LOCKS) report zero inversions and zero boundary
    violations — the compile happens without any framework lock held."""
    from mxnet_tpu._debug import locktrace
    x, y = _batch()
    net = _make_net()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
    prev = locktrace.enable()
    locktrace.reset()
    try:
        for _ in range(3):
            step(x, y, batch_size=4)
        assert step.last_mode == "fused"
        r = locktrace.report()
        assert r["inversion_total"] == 0, r
        assert r["boundary_violation_total"] == 0, r
    finally:
        locktrace.reset()
        if not prev:
            locktrace.disable()


def test_env_gate_defaults_on():
    assert os.environ.get("MXNET_GLUON_FUSED_STEP") is None \
        or FS.fused_step_enabled() in (True, False)  # smoke: import-time read
    assert isinstance(FS.fused_step_enabled(), bool)
