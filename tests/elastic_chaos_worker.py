"""Worker for the elastic rank-kill chaos test (ISSUE 7 acceptance).

Each rank runs a deterministic synchronous-DP training loop:

- local gradients on a 2-device in-process mesh with BUCKETED,
  BACKWARD-OVERLAPPED reduction (``parallel.overlap`` inside
  ``shard_map`` — tentpole b),
- cross-process reduction through ``parallel.elastic.HostGradReducer``
  over the async-PS kvstore, summed in sorted-rank order so every rank
  applies bitwise-identical updates,
- ``elastic_train_loop`` + ``ElasticController`` + ``CheckpointManager``
  wrapping the whole thing (tentpole a).

Chaos: the rank named by ``MXTPU_CHAOS_DIE_RANK`` SIGKILLs itself at
step ``MXTPU_CHAOS_DIE_AT`` (mid-epoch, no cleanup, no done()). The
survivors' barriers abort naming the dead rank, the controller confirms
via the heartbeat staleness table, reshards the world onto the
survivors, rewinds to the newest crash-consistent checkpoint, and the
job converges. Rank 0 prints the restore/metrics breadcrumbs the test
asserts on and saves the final params for the bitwise comparison
against a clean run resumed from the same checkpoint.

Run via: python tools/launch.py --elastic -n 2 python
         tests/elastic_chaos_worker.py
(single-process clean-reference mode: MXTPU_NUM_PROCS=1, no kvstore
barriers — the reducer short-circuits at world size 1.)
"""
import json
import os
import signal
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import jax.random as jr  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import profiler  # noqa: E402
from mxnet_tpu.parallel import (  # noqa: E402
    CheckpointManager, ElasticController, HostGradReducer, create_mesh,
    elastic_train_loop, shard_for_rank, shard_map, tag_gradient_buckets)

DIM = 16
GLOBAL_BATCH = 8
W_TRUE = np.linspace(-1.0, 1.0, DIM).astype(np.float32)


def gen_batch(step):
    """Global batch for one step — a pure function of the step index, so
    any world split of it is epoch-reproducible."""
    rs = np.random.RandomState(1234 + int(step))
    X = rs.randn(GLOBAL_BATCH, DIM).astype(np.float32)
    Y = (X @ W_TRUE).astype(np.float32)
    return X, Y


def make_grad_fn(mesh):
    """Local-shard loss+grad with the gradient psum bucketed and placed
    mid-backward (overlap markers) over the in-process 'dp' axis."""

    def body(w, Xl, Yl):
        def loss_of(wv):
            (wv_t,) = tag_gradient_buckets([wv], "dp", op="sum")
            r = Xl @ wv_t - Yl
            return 0.5 * jnp.sum(r * r)

        loss, g = jax.value_and_grad(loss_of)(w)
        return jax.lax.psum(loss, "dp"), g

    smapped = shard_map(body, mesh, in_specs=(P(), P("dp"), P("dp")),
                        out_specs=(P(), P()), check_vma=False)
    return jax.jit(smapped)


def main():
    rank = int(os.environ.get("MXTPU_PROC_ID", "0"))
    nproc = int(os.environ.get("MXTPU_NUM_PROCS", "1"))
    steps = int(os.environ.get("MXTPU_CHAOS_STEPS", "30"))
    save_every = int(os.environ.get("MXTPU_CHAOS_SAVE_EVERY", "5"))
    die_rank = int(os.environ.get("MXTPU_CHAOS_DIE_RANK", "-1"))
    die_at = int(os.environ.get("MXTPU_CHAOS_DIE_AT", "-1"))
    ckpt_dir = os.environ["MXTPU_CHAOS_CKPT_DIR"]
    out_dir = os.environ["MXTPU_CHAOS_OUT_DIR"]

    mesh = create_mesh(devices=jax.devices()[:2])  # local dp=2
    grad_fn = make_grad_fn(mesh)

    kv = mx.kv.create("dist_async") if nproc > 1 else None
    reducer = HostGradReducer(kv) if kv is not None else None
    controller = ElasticController(kvstore=kv, world=range(nproc),
                                   rank=rank) if kv is not None else None

    restores = []

    def on_restore(state, step):
        restores.append(int(step))
        print("ELASTIC_RESTORED rank=%d step=%d world=%s"
              % (rank, step, controller.survivors if controller
                 else [0]), flush=True)

    def step_fn(state, idx):
        idx = int(idx)
        if rank == die_rank and idx == die_at:
            # mid-epoch SIGKILL: no cleanup, no done(), heartbeats stop
            os.kill(os.getpid(), signal.SIGKILL)
        world = controller.survivors if controller else [0]
        X, Y = gen_batch(idx)
        rows = shard_for_rank(GLOBAL_BATCH, world, rank)
        Xl = jnp.asarray(X[rows.start:rows.stop])
        Yl = jnp.asarray(Y[rows.start:rows.stop])
        _, g_local = grad_fn(state["w"], Xl, Yl)
        g_local = np.asarray(g_local, np.float32)
        g_total = reducer.allreduce(g_local, world, rank) \
            if reducer is not None else g_local
        key, sub = jr.split(state["rng"])
        noise = 0.001 * jr.normal(sub, (DIM,), jnp.float32)
        grad = jnp.asarray(g_total) / GLOBAL_BATCH + noise
        m = 0.9 * state["m"] + grad
        w = state["w"] - 0.02 * m
        return {"w": w, "m": m, "rng": key}, None

    ckpt = CheckpointManager(ckpt_dir, keep=50, use_orbax=False)
    state0 = {"w": jnp.zeros((DIM,), jnp.float32),
              "m": jnp.zeros((DIM,), jnp.float32),
              "rng": jr.PRNGKey(7)}
    state, last, done = elastic_train_loop(
        step_fn, state0, list(range(steps)), ckpt,
        save_every=(save_every if rank == 0 else 0),
        max_failures=3, on_restore=on_restore, controller=controller)

    w = np.asarray(state["w"], np.float32)
    err = float(np.max(np.abs(w - W_TRUE)))
    np.save(os.path.join(out_dir, "params_rank%d.npy" % rank), w)
    print("ELASTIC_METRICS rank=%d %s"
          % (rank, json.dumps(profiler.metrics().get("elastic", {}))),
          flush=True)
    print("ELASTIC_OK rank=%d done=%s last=%d err=%.5f restores=%s"
          % (rank, done, last, err, restores), flush=True)

    if kv is not None:
        kv.close()


if __name__ == "__main__":
    main()
