"""Control-plane survivability (ISSUE 20): kvstore server failover with
journaled state + fencing epochs, coordinated SIGTERM preemption, and
on-the-wire network chaos.

The chaos acceptance trio from the issue:

- server death mid-train -> journal-replay rejoin, zero lost updates
- partitioned stale rank fenced, survivors bitwise-identical to an
  unfaulted twin
- SIGTERM'd run closes ``outcome=preempted`` and its resume books
  ``replay_span == 0``

plus the satellites: bounded recv (``MXTPU_PS_RECV_TIMEOUT`` surfacing
``net.half_open`` as a counted retry), SnapshotTable's deterministic
lowest-rank tie-break, and the seeded `_retry` jitter stream.
"""
import os
import signal
import socket
import struct
import tempfile
import time

import numpy as np
import pytest

import mxnet_tpu._retry as _retry
from mxnet_tpu import kvstore_async as KA
from mxnet_tpu import profiler
from mxnet_tpu._debug import faultpoint, goodput
from mxnet_tpu.kvstore_server import SnapshotTable


def _counter(name):
    return profiler.metrics()["counters"].get(name, 0)


def _abrupt_kill(srv, *clients):
    """Die without stop(): no journal close, no compaction flush — the
    standby's state must come from journal replay alone. The established
    client sockets are reset too (their server threads are orphaned)."""
    srv._stop.set()
    srv._srv.close()
    for c in clients:
        if c._sock is not None:
            c._sock.close()


def _reserve_port():
    """Pick a port the standby can bind later (closed before use)."""
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(autouse=True)
def _no_chaos():
    faultpoint.reset()
    yield
    faultpoint.reset()


class TestJournal:
    def test_replay_restores_store_epoch_and_snapshots(self, tmp_path):
        jdir = str(tmp_path / "journal")
        srv1 = KA.AsyncPSServer(journal_dir=jdir)
        cli = KA.AsyncPSClient("127.0.0.1", srv1.port)
        cli.init("w", np.arange(6, dtype=np.float32))
        cli.push("w", np.full(6, 3.0, np.float32))
        cli.push("w", np.full(6, 7.0, np.float32))
        cli.put_snapshot(0, 11, b"peer-state-blob")
        assert cli.bump_epoch(5) == 5
        _abrupt_kill(srv1, cli)

        srv2 = KA.AsyncPSServer(journal_dir=jdir)
        try:
            assert srv2.journal_replayed > 0
            # store replayed to the dead primary's exact state
            np.testing.assert_array_equal(
                srv2._store["w"], np.full(6, 7.0, np.float32))
            # fencing epoch survives the restart — a stale pre-reshard
            # writer stays fenced even across a server death
            assert srv2._epoch == 5
            # published peer snapshots replay too (restore-from-peer
            # survives control-plane failover)
            assert srv2._snapshots.items() == [(0, 11, b"peer-state-blob")]
        finally:
            srv2.stop()

    def test_compaction_then_tail_replay(self, tmp_path):
        jdir = str(tmp_path / "journal")
        srv1 = KA.AsyncPSServer(journal_dir=jdir)
        # instance override shadows the 4MiB class default: every
        # store-mutating append crosses the threshold and compacts
        srv1._JOURNAL_SEG_BYTES = 1
        before = _counter("kvstore.journal_compactions")
        cli = KA.AsyncPSClient("127.0.0.1", srv1.port)
        cli.init("w", np.zeros(4, np.float32))
        for v in (1.0, 2.0, 3.0):
            cli.push("w", np.full(4, v, np.float32))
        assert _counter("kvstore.journal_compactions") > before
        assert os.path.exists(os.path.join(jdir, "table.snap"))
        _abrupt_kill(srv1, cli)

        srv2 = KA.AsyncPSServer(journal_dir=jdir)
        try:
            np.testing.assert_array_equal(
                srv2._store["w"], np.full(4, 3.0, np.float32))
        finally:
            srv2.stop()

    def test_torn_tail_ends_replay_cleanly(self, tmp_path):
        jdir = str(tmp_path / "journal")
        srv1 = KA.AsyncPSServer(journal_dir=jdir)
        cli = KA.AsyncPSClient("127.0.0.1", srv1.port)
        cli.init("w", np.zeros(4, np.float32))
        cli.push("w", np.full(4, 9.0, np.float32))
        _abrupt_kill(srv1, cli)
        # the mutation in flight when the server died: a length header
        # promising 100 bytes with only 2 behind it
        segs = sorted(n for n in os.listdir(jdir) if n.endswith(".jnl"))
        with open(os.path.join(jdir, segs[-1]), "ab") as f:
            f.write(struct.pack(">I", 100) + b"xy")

        srv2 = KA.AsyncPSServer(journal_dir=jdir)
        try:
            np.testing.assert_array_equal(
                srv2._store["w"], np.full(4, 9.0, np.float32))
        finally:
            srv2.stop()


class TestFailover:
    def test_client_fails_over_to_journal_replayed_standby(self, tmp_path):
        jdir = str(tmp_path / "journal")
        srv1 = KA.AsyncPSServer(journal_dir=jdir)
        standby_port = _reserve_port()
        cli = KA.AsyncPSClient(
            "127.0.0.1", srv1.port,
            endpoints=[("127.0.0.1", srv1.port),
                       ("127.0.0.1", standby_port)])
        cli.init("w", np.arange(8, dtype=np.float32))
        cli.push("w", np.arange(8, dtype=np.float32) * 2)
        before = np.asarray(cli.pull("w"))
        fo0 = sum(v for k, v in profiler.metrics()["counters"].items()
                  if k.startswith("kvstore.failovers."))
        _abrupt_kill(srv1, cli)

        srv2 = KA.AsyncPSServer(port=standby_port, journal_dir=jdir)
        try:
            # same client object: the pull walks the endpoint list
            # inside its ordinary retry budget — zero lost updates
            after = np.asarray(cli.pull("w"))
            np.testing.assert_array_equal(before, after)
            fo1 = sum(v for k, v in profiler.metrics()["counters"].items()
                      if k.startswith("kvstore.failovers."))
            assert fo1 - fo0 >= 1
            # and the failed-over wire is fully live, not read-only
            cli.push("w", np.full(8, 5.0, np.float32))
            np.testing.assert_array_equal(
                cli.pull("w"), np.full(8, 5.0, np.float32))
        finally:
            srv2.stop()

    def test_env_endpoints_require_matching_first_entry(self, monkeypatch):
        srv = KA.AsyncPSServer()
        try:
            spec = "127.0.0.1:%d,127.0.0.1:19999" % srv.port
            monkeypatch.setenv("MXTPU_PS_ENDPOINTS", spec)
            cli = KA.AsyncPSClient("127.0.0.1", srv.port)
            assert cli._endpoints == [("127.0.0.1", srv.port),
                                      ("127.0.0.1", 19999)]
            # a sharded-group client built against a DIFFERENT server
            # keeps its single address: the env names the failover
            # chain for the primary endpoint only
            monkeypatch.setenv("MXTPU_PS_ENDPOINTS",
                               "127.0.0.1:19998,127.0.0.1:19999")
            other = KA.AsyncPSClient("127.0.0.1", srv.port)
            assert other._endpoints == [("127.0.0.1", srv.port)]
            cli.stop_server()
        finally:
            srv.stop()


class TestFencing:
    def test_stale_epoch_push_rejected_survivor_state_intact(
            self, monkeypatch):
        monkeypatch.setenv("MXTPU_PS_FENCING", "1")
        srv = KA.AsyncPSServer()
        try:
            survivor = KA.AsyncPSClient("127.0.0.1", srv.port)
            stale = KA.AsyncPSClient("127.0.0.1", srv.port)
            survivor.init("w", np.zeros(4, np.float32))
            # both sides at epoch 0: accepted
            stale.push("w", np.full(4, 1.0, np.float32))
            # reshard commits epoch 1 on the server and the survivor
            assert survivor.bump_epoch(1) == 1
            survivor.set_fence_epoch(1)
            survivor.push("w", np.full(4, 2.0, np.float32))
            fenced0 = _counter("kvstore.fenced_writes")
            with pytest.raises(RuntimeError, match="fenced epoch"):
                stale.push("w", np.full(4, 99.0, np.float32))
            assert _counter("kvstore.fenced_writes") - fenced0 >= 1
            # rejected BEFORE apply: state is bitwise the survivor-only
            # history, as if the partitioned rank never wrote
            np.testing.assert_array_equal(
                survivor.pull("w"), np.full(4, 2.0, np.float32))
        finally:
            srv.stop()

    def test_epoch_is_monotonic_and_queryable(self, srv=None):
        srv = KA.AsyncPSServer()
        try:
            cli = KA.AsyncPSClient("127.0.0.1", srv.port)
            assert cli.bump_epoch() == 0        # -1 merely queries
            assert cli.bump_epoch(4) == 4
            assert cli.bump_epoch(2) == 4       # lower proposal: no-op
            assert cli.bump_epoch() == 4
        finally:
            srv.stop()

    def test_v0_unstamped_push_accepted_by_fencing_server(
            self, monkeypatch):
        monkeypatch.setenv("MXTPU_PS_FENCING", "1")
        srv = KA.AsyncPSServer()
        try:
            fenced = KA.AsyncPSClient("127.0.0.1", srv.port)
            fenced.init("w", np.zeros(4, np.float32))
            assert fenced.bump_epoch(3) == 3
            # a v0 peer's push carries no epoch tail; the length-gated
            # check must wave it through (mixed-version interop), never
            # misread adjacent bytes as a stale epoch
            monkeypatch.setenv("MXTPU_PS_FENCING", "0")
            v0 = KA.AsyncPSClient("127.0.0.1", srv.port)
            v0.push("w", np.full(4, 6.0, np.float32))
            np.testing.assert_array_equal(
                v0.pull("w"), np.full(4, 6.0, np.float32))
        finally:
            srv.stop()


class TestPreemption:
    def test_preempt_notice_visible_then_withdrawn(self):
        srv = KA.AsyncPSServer()
        try:
            cli = KA.AsyncPSClient("127.0.0.1", srv.port)
            cli.preempt_notice(3, 41)
            # visible immediately — peers reshard proactively instead
            # of waiting out the heartbeat dead-timeout
            assert 3 in cli.dead_nodes(timeout=60.0)
            cli.done(3)  # drain finished: withdraw the notice
            assert 3 not in cli.dead_nodes(timeout=60.0)
        finally:
            srv.stop()

    def test_sigterm_closes_preempted_and_resume_replays_zero(
            self, tmp_path, monkeypatch):
        import jax.numpy as jnp
        from mxnet_tpu.parallel.elastic import (
            CheckpointManager, ElasticController, elastic_train_loop)

        monkeypatch.setenv("MXTPU_PREEMPT_GRACE_S", "30")
        batches = [jnp.asarray(float(i)) for i in range(8)]
        ck_dir = str(tmp_path / "ck")

        class _KV:
            def __init__(self):
                self.announced = []
                self.num_workers = 2

            def dead_nodes(self, timeout=3.0):
                return []

            def resize(self, n):
                self.num_workers = int(n)

            def announce_preemption(self, step):
                self.announced.append(int(step))
                return 1

        def step(state, b):
            if int(b) == 3:
                signal.raise_signal(signal.SIGTERM)
            time.sleep(0.01)
            return {"acc": state["acc"] + b}, None

        kv = _KV()
        ctl = ElasticController(kvstore=kv, world=range(2), rank=0,
                                poll_interval=0.0)
        ck = CheckpointManager(ck_dir, use_orbax=False,
                               async_persist=True, delta=False)
        _, last, done = elastic_train_loop(
            step, {"acc": jnp.asarray(0.0)}, batches, ck,
            save_every=100, max_failures=0, controller=ctl)
        assert not done and last == 3
        assert kv.announced == [3]  # notice broadcast before draining
        m = goodput.last_manifest()
        assert m["outcome"] == "preempted"

        monkeypatch.delenv("MXTPU_PREEMPT_GRACE_S")

        def plain(state, b):
            time.sleep(0.01)
            return {"acc": state["acc"] + b}, None

        ck = CheckpointManager(ck_dir, use_orbax=False,
                               async_persist=True, delta=False)
        res_state, _, done = elastic_train_loop(
            plain, {"acc": jnp.asarray(0.0)}, batches, ck,
            save_every=100, max_failures=0)
        assert done
        m = goodput.last_manifest()
        rec = [e for e in m["events"] if e["kind"] == "recovery"][-1]
        # the grace-window save IS the newest step: nothing to replay
        assert rec["recovery_kind"] == "resume"
        assert rec["restored_step"] == 3
        assert rec["replay_span"] == 0
        # bitwise vs an uninterrupted twin
        ck = CheckpointManager(str(tmp_path / "ck_twin"),
                               use_orbax=False, async_persist=True,
                               delta=False)
        twin_state, _, done = elastic_train_loop(
            plain, {"acc": jnp.asarray(0.0)}, batches, ck,
            save_every=100, max_failures=0)
        assert done
        assert float(res_state["acc"]) == float(twin_state["acc"])


class TestRecvTimeout:
    def test_half_open_surfaces_as_counted_retry(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PS_RECV_TIMEOUT", "0.1")
        srv = KA.AsyncPSServer()
        try:
            cli = KA.AsyncPSClient("127.0.0.1", srv.port)
            cli.init("w", np.full(4, 8.0, np.float32))
            r0 = _counter("kvstore.transport_retries")
            # the server's conn thread has at most ONE pending chaos
            # check (its recv-entry check for the iteration parked
            # since the init reply), and a server-side trigger cannot
            # raise (no recv timeout on the conn socket) — so with two
            # triggers armed the client's own recv seam fires at least
            # once whatever the interleaving: the silent peer surfaces
            # as socket.timeout instead of an indefinite block, and
            # the retry loop resends
            faultpoint.configure("net.half_open=delay:0ms@n=2")
            np.testing.assert_array_equal(
                cli.pull("w"), np.full(4, 8.0, np.float32))
            assert _counter("kvstore.transport_retries") - r0 >= 1
            assert faultpoint.triggers("net.half_open") >= 1
        finally:
            faultpoint.reset()
            srv.stop()

    def test_without_timeout_half_open_does_not_raise(self):
        # off by default: barrier/wait_done park legitimately for
        # seconds, so the unbounded recv is the v0 contract
        srv = KA.AsyncPSServer()
        try:
            cli = KA.AsyncPSClient("127.0.0.1", srv.port)
            cli.init("w", np.zeros(2, np.float32))
            assert cli._sock.gettimeout() is None
            faultpoint.configure("net.half_open=delay:0ms@n=1")
            r0 = _counter("kvstore.transport_retries")
            cli.pull("w")  # trigger fires but cannot raise: no timeout
            assert _counter("kvstore.transport_retries") == r0
        finally:
            faultpoint.reset()
            srv.stop()


class TestNetChaosPoints:
    def test_partition_retried_to_success(self):
        srv = KA.AsyncPSServer()
        try:
            cli = KA.AsyncPSClient("127.0.0.1", srv.port)
            cli.init("w", np.full(4, 2.0, np.float32))
            r0 = _counter("kvstore.transport_retries")
            faultpoint.configure(
                "net.partition=raise:ConnectionError@n=1")
            np.testing.assert_array_equal(
                cli.pull("w"), np.full(4, 2.0, np.float32))
            assert _counter("kvstore.transport_retries") - r0 >= 1
            assert faultpoint.triggers("net.partition") == 1
        finally:
            faultpoint.reset()
            srv.stop()

    def test_drop_swallows_frame_recv_timeout_recovers(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PS_RECV_TIMEOUT", "0.1")
        srv = KA.AsyncPSServer()
        try:
            cli = KA.AsyncPSClient("127.0.0.1", srv.port)
            cli.init("w", np.full(4, 4.0, np.float32))
            r0 = _counter("kvstore.transport_retries")
            faultpoint.configure("net.drop=delay:0ms@n=1")
            # request frame sent locally, never arrives; the bounded
            # recv surfaces the silence and the retry resends
            np.testing.assert_array_equal(
                cli.pull("w"), np.full(4, 4.0, np.float32))
            assert _counter("kvstore.transport_retries") - r0 >= 1
        finally:
            faultpoint.reset()
            srv.stop()

    def test_delay_stretches_round_trip(self):
        srv = KA.AsyncPSServer()
        try:
            cli = KA.AsyncPSClient("127.0.0.1", srv.port)
            cli.init("w", np.zeros(2, np.float32))
            faultpoint.configure("net.delay=delay:30ms")
            t0 = time.perf_counter()
            cli.pull("w")
            # at minimum the client-side send seam slept once
            assert time.perf_counter() - t0 >= 0.03
            assert faultpoint.triggers("net.delay") >= 1
        finally:
            faultpoint.reset()
            srv.stop()


class TestSnapshotTieBreak:
    def test_equal_step_lowest_rank_wins_both_orders(self):
        for order in ((0, 1), (1, 0)):
            t = SnapshotTable()
            for rank in order:
                t.put(rank, 5, b"blob%d" % rank)
            got = t.get_newest(exclude_rank=9, heartbeats={},
                               stale_timeout=0)
            assert got[0] == 0 and got[2] == b"blob0"

    def test_higher_step_still_beats_lower_rank(self):
        t = SnapshotTable()
        t.put(0, 5, b"old")
        t.put(1, 6, b"new")
        got = t.get_newest(exclude_rank=9, heartbeats={},
                           stale_timeout=0)
        assert got[:2] == (1, 6)


class TestRetrySeeded:
    def test_same_seed_replays_identical_backoff(self, monkeypatch):
        monkeypatch.setenv("MXNET_FAULTPOINTS_SEED", "1234")
        a = _retry.RetryPolicy(base=0.01, cap=0.08)
        b = _retry.RetryPolicy(base=0.01, cap=0.08)
        seq_a = [a.backoff(i) for i in range(1, 7)]
        seq_b = [b.backoff(i) for i in range(1, 7)]
        assert seq_a == seq_b
        monkeypatch.setenv("MXNET_FAULTPOINTS_SEED", "5678")
        c = _retry.RetryPolicy(base=0.01, cap=0.08)
        assert [c.backoff(i) for i in range(1, 7)] != seq_a

    def test_unseeded_policies_share_production_rng(self, monkeypatch):
        monkeypatch.delenv("MXNET_FAULTPOINTS_SEED", raising=False)
        assert _retry.RetryPolicy()._rng is None

    def test_deadline_honored_within_one_max_delay(self):
        policy = _retry.RetryPolicy(max_retries=100, base=0.05,
                                    cap=0.05, deadline=0.3)
        calls = []

        def always_fails():
            calls.append(1)
            raise ConnectionError("down")

        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            _retry.call(always_fails, policy=policy)
        elapsed = time.monotonic() - t0
        # the loop stops BEFORE a sleep that would cross the deadline,
        # so worst case is deadline + one jittered cap (1.5x) + slack
        assert elapsed <= 0.3 + 0.05 * 1.5 + 0.2
        assert len(calls) > 2  # it did retry, not fail fast
