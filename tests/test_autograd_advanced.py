"""Higher-order gradients, custom Functions, dlpack interop, rtc
(ref: tests/python/unittest/test_higher_order_grad.py, test_autograd.py
Function cases, test_dlpack.py, tests/python/gpu/test_rtc.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


class TestHigherOrderGrad:
    def test_sin_second_order(self):
        """d2/dx2 sin(x) = -sin(x) (ref: test_higher_order_grad.py:sin)."""
        x = mx.nd.array(onp.array([0.5, 1.0, 2.0], "float32"))
        x.attach_grad()
        with autograd.record():
            y = mx.nd.sin(x).sum()
            dx = autograd.grad(y, [x], create_graph=True)[0]
            z = dx.sum()
        z.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(),
                                    -onp.sin([0.5, 1.0, 2.0]), atol=1e-6)

    def test_power_chain(self):
        """d/dx (d/dx x^3)^2 = d/dx 9x^4 = 36x^3."""
        x = mx.nd.array(onp.array([1.0, 2.0], "float32"))
        x.attach_grad()
        with autograd.record():
            y = (x ** 3).sum()
            dx = autograd.grad(y, [x], create_graph=True)[0]
            z = (dx ** 2).sum()
        z.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), [36.0, 288.0],
                                    atol=1e-4)

    def test_log_second_order(self):
        """d2/dx2 log(x) = -1/x^2 (ref: test_higher_order_grad.py:log)."""
        x = mx.nd.array(onp.array([1.0, 2.0, 4.0], "float32"))
        x.attach_grad()
        with autograd.record():
            y = mx.nd.log(x).sum()
            dx = autograd.grad(y, [x], create_graph=True)[0]
            z = dx.sum()
        z.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(),
                                    [-1.0, -0.25, -0.0625], atol=1e-6)


class TestFunction:
    def test_custom_function(self):
        """ref: python/mxnet/autograd.py:368 Function; tests/python/
        unittest/test_autograd.py test_function."""

        class Sigmoid(autograd.Function):
            def forward(self, x):
                y = 1.0 / (1.0 + mx.nd.exp(-x))
                self.save_for_backward(y)
                return y

            def backward(self, dy):
                y, = self.saved_tensors
                return dy * y * (1.0 - y)

        x = mx.nd.array(onp.array([0.0, 1.0, -1.0], "float32"))
        x.attach_grad()
        fn = Sigmoid()
        with autograd.record():
            out = fn(x)
            loss = out.sum()
        loss.backward()
        s = 1.0 / (1.0 + onp.exp(-onp.array([0.0, 1.0, -1.0])))
        onp.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s),
                                    atol=1e-6)


class TestDLPack:
    def test_roundtrip_jax(self):
        import jax.dlpack
        import jax.numpy as jnp
        a = mx.nd.array(onp.arange(6, dtype="float32").reshape(2, 3))
        cap = a.dlpack
        back = jnp.from_dlpack(cap) if hasattr(jnp, "from_dlpack") else \
            jax.dlpack.from_dlpack(cap)
        onp.testing.assert_array_equal(onp.asarray(back), a.asnumpy())

    def test_array_protocol(self):
        a = mx.nd.array(onp.ones((2, 2), "float32"))
        assert onp.asarray(a).shape == (2, 2)


class TestRTC:
    def test_cuda_module_guided_error(self):
        from mxnet_tpu import rtc
        with pytest.raises(NotImplementedError, match="[Pp]allas"):
            rtc.CudaModule("__global__ void k() {}")

    def test_pallas_module_launch(self):
        import jax.numpy as jnp
        from mxnet_tpu import rtc

        mod = rtc.PallasModule({"axpy": lambda a, x, y: a * x + y})
        kern = mod.get_kernel("axpy")
        out = kern.launch([mx.nd.array(onp.float32(2.0)),
                           mx.nd.ones((4,)), mx.nd.ones((4,))])
        onp.testing.assert_allclose(out.asnumpy(), [3.0] * 4)
        assert mod.names() == ["axpy"]


class TestOpperf:
    def test_harness_runs(self):
        import sys
        sys.path.insert(0, "benchmark/opperf")
        from opperf import run_performance_test
        res = run_performance_test(ops=["add", "dot"], warmup=1, runs=2)
        assert len(res) == 2
        for r in res:
            assert "error" not in r, r
            assert r["fwd_ms"] > 0
            assert r["fwd_bwd_ms"] is not None


class TestSVRG:
    def test_svrg_converges(self):
        from mxnet_tpu import symbol as sym
        from mxnet_tpu import io as mio
        from mxnet_tpu.contrib.svrg_optimization import SVRGModule

        rng = onp.random.RandomState(0)
        X = rng.randn(96, 8).astype("float32")
        y = (X.sum(1) > 0).astype("float32")
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        fc = sym.FullyConnected(data, num_hidden=2, name="fc")
        out = sym.SoftmaxOutput(fc, label, name="softmax")
        it = mio.NDArrayIter(X, y, batch_size=16)
        mod = SVRGModule(out, context=mx.cpu(), update_freq=2)
        mod.fit(it, num_epoch=6, optimizer="sgd",
                optimizer_params={"learning_rate": 0.2})
        it.reset()
        acc = dict(mod.score(it, "acc"))["accuracy"]
        assert acc > 0.9, acc

    def test_svrg_optimizer_registered(self):
        import mxnet_tpu.optimizer as opt
        from mxnet_tpu.contrib.svrg_optimization import _SVRGOptimizer
        o = opt.create("_svrgoptimizer", default_optimizer="sgd",
                       learning_rate=0.1)
        assert isinstance(o, _SVRGOptimizer)


def test_onnx_available():
    """The ONNX bridge is self-contained (contrib/onnx/proto.py) — no
    onnx package gate anymore; a missing file is a plain file error."""
    from mxnet_tpu.contrib import onnx as mxonnx
    with pytest.raises(FileNotFoundError):
        mxonnx.import_model("/nonexistent/m.onnx")
