"""Test harness config: force an 8-device virtual CPU mesh so multi-chip
sharding paths (DP/TP/PP/CP) are exercised without TPU hardware. Mirrors the
reference's local-process cluster simulation for dist tests
(ref: ci/docker/runtime_functions.sh:1281 launching tools/launch.py -n 7
--launcher local).

The environment may preload an accelerator plugin (sitecustomize on
PYTHONPATH) and pin JAX_PLATFORMS to it before conftest runs. In that case we
re-exec pytest once with a clean environment: PYTHONPATH stripped,
JAX_PLATFORMS=cpu, and the 8-device host-platform flag set before any jax
import in the child.
"""
import os
import sys

_WANT_FLAG = "--xla_force_host_platform_device_count"


def _needs_reexec():
    if os.environ.get("MXTPU_TEST_CHILD") == "1":
        return False
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return True
    if _WANT_FLAG not in os.environ.get("XLA_FLAGS", ""):
        return True
    return False


if _needs_reexec():
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # drop preloaded accelerator sitecustomize
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " %s=8" % _WANT_FLAG).strip()
    env["MXTPU_TEST_CHILD"] = "1"
    os.execve(sys.executable,
              [sys.executable, "-m", "pytest"] + sys.argv[1:], env)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if _WANT_FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " %s=8" % _WANT_FLAG).strip()

import numpy as _np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_rngs():
    _np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
    yield
