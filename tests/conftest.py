"""Test harness config: force an 8-device virtual CPU mesh so multi-chip
sharding paths (DP/TP/PP/CP) are exercised without TPU hardware. Mirrors the
reference's local-process cluster simulation for dist tests
(ref: ci/docker/runtime_functions.sh:1281 launching tools/launch.py -n 7
--launcher local).

The environment may preload an accelerator plugin (sitecustomize on
PYTHONPATH) that registers a TPU PJRT backend and pins JAX_PLATFORMS before
conftest runs. JAX resolves backends lazily, so as long as no computation has
executed yet we can redirect to an 8-device virtual CPU platform in-process:
set XLA_FLAGS before the CPU client is created and override the platform via
jax.config (the env var alone is too late once jax is imported).

NOTE: do NOT os.exec-re-exec pytest from here. pytest's fd-level capture is
already active while conftest imports, so an exec'd child inherits fds
pointing at the dead parent's capture tempfiles and every byte of test output
is silently lost (exit code still propagates, which makes it look like an
empty-but-green run).
"""
import os

_WANT_FLAG = "--xla_force_host_platform_device_count"

if _WANT_FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " %s=8" % _WANT_FLAG).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Flight-recorder shards written by suites that exercise crash/OOM/leak
# paths land in a session tmpdir, never the working tree (tests that
# assert on shard paths override per-test with monkeypatch).
if "MXTPU_FLIGHTREC_DIR" not in os.environ:
    import tempfile
    os.environ["MXTPU_FLIGHTREC_DIR"] = tempfile.mkdtemp(
        prefix="mxtpu_flightrec_")

# Goodput run manifests (elastic_train_loop opens a run per call) land
# in a session tmpdir, never the working tree (tests that assert on
# manifest paths override per-test with monkeypatch).
if "MXTPU_RUNS_DIR" not in os.environ:
    import tempfile
    os.environ["MXTPU_RUNS_DIR"] = tempfile.mkdtemp(
        prefix="mxtpu_runs_")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as _np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_rngs():
    import random as _pyrandom
    _pyrandom.seed(0)  # image augmenters draw skip/shuffle/crop from it
    _np.random.seed(0)
    import mxnet_tpu as mx
    mx.random.seed(0)
    yield


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: minutes-scale tests (realistic-shape mesh "
        "steps, subprocess clusters, full registry sweeps, JPEG "
        "pipelines); always run by default — `-m 'not slow'` is the "
        "quick lane")
