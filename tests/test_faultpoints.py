"""Faultpoint chaos suite (ISSUE 5): the framework's "never a crash"
degradation paths exercised under real, injected failure.

Three invariants, asserted throughout:

* **no hang** — every faulted operation either succeeds (retry/fallback)
  or raises within its bounded retry budget; nothing blocks forever,
* **no silent corruption** — wherever a retry or fallback succeeds, the
  results are BITWISE equal to the fault-free reference that runs the
  same code path (eager vs eager, transport-retried vs clean wire),
* **full accounting** — every injected fault is visible in
  ``profiler.metrics()['faults']`` and the matching retry/fallback
  counter ticks (``kvstore.transport_retries``, ``kvstore.connect_retries``,
  ``io.prefetch_worker_deaths``, imperative ``fallbacks``/``bulk_fallbacks``,
  ``fused_step.fallbacks``).

Schedules are seeded (``MXNET_FAULTPOINTS_SEED``): every chaos run here
is deterministic and replayable.
"""
import multiprocessing as mp
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon, profiler
from mxnet_tpu._debug import faultpoint as fp
from mxnet_tpu.io import DevicePrefetchIter
from mxnet_tpu.kvstore_async import AsyncPSClient, AsyncPSServer
from mxnet_tpu.ndarray import register as R


@pytest.fixture(autouse=True)
def _clean_faultpoints(monkeypatch):
    # fast retries for every test: chaos must not make the suite slow
    monkeypatch.setenv("MXTPU_PS_RETRY_BASE", "0.01")
    monkeypatch.setenv("MXTPU_PS_RETRY_CAP", "0.05")
    fp.reset()
    yield
    fp.reset()
    profiler._reset()


@pytest.fixture()
def server():
    srv = AsyncPSServer()
    yield srv
    srv.stop()


# -- spec grammar / determinism ----------------------------------------------

class TestSpec:
    def test_env_grammar_roundtrip(self):
        pts = fp.configure(
            "kvstore.send=raise:ConnectionError@p=0.3;"
            "io.prefetch.place=delay:50ms@n=3", seed=1)
        assert pts == ["io.prefetch.place", "kvstore.send"]
        rep = fp.report()
        assert rep["active"]
        assert rep["points"]["kvstore.send"] == "raise:ConnectionError@p=0.3"

    def test_dict_form_and_reset(self):
        fp.configure({"fused_step.trace": "raise:RuntimeError@n=1"})
        assert fp.is_active()
        fp.reset()
        assert not fp.is_active()
        assert fp.metrics() == {}

    @pytest.mark.parametrize("bad", [
        "nosuchpoint=raise:ValueError",       # unknown point
        "kvstore.send=explode",               # unknown action
        "kvstore.send=raise:open",            # not an Exception subclass
        "kvstore.send=raise:ValueError@p=7",  # p out of range
        "kvstore.send=raise:ValueError@z=1",  # unknown modifier
        "kvstore.send",                       # missing '='
    ])
    def test_bad_specs_fail_loudly(self, bad):
        with pytest.raises(ValueError):
            fp.configure(bad)

    def test_delay_units(self):
        fp.configure({"io.prefetch.place": "delay:1ms"})
        t0 = time.perf_counter()
        fp.check("io.prefetch.place")  # sleeps, does not raise
        assert time.perf_counter() - t0 < 1.0
        assert fp.triggers("io.prefetch.place") == 1

    def _pattern(self, seed, hits=40):
        fp.configure({"kvstore.send": "raise:ConnectionError@p=0.5"},
                     seed=seed)
        out = []
        for _ in range(hits):
            try:
                fp.check("kvstore.send")
                out.append(0)
            except ConnectionError:
                out.append(1)
        fp.reset()
        return out

    def test_seeded_schedule_is_replayable(self):
        a = self._pattern(seed=42)
        b = self._pattern(seed=42)
        c = self._pattern(seed=43)
        assert a == b                 # same seed -> identical schedule
        assert 0 < sum(a) < len(a)    # p=0.5 actually mixes
        assert a != c                 # and the seed actually matters

    def test_health_corrupt_seeded_schedule_replays(self):
        """The ISSUE 15 chaos seam draws from the same per-point seeded
        stream: a probabilistic health.grad.corrupt schedule replays
        identically run-to-run (through the healthmon probe that maps
        the raise into a corruption operand), so a detected-SDC chaos
        run is reproducible."""
        from mxnet_tpu._debug import healthmon

        def pattern(seed):
            fp.configure(
                {"health.grad.corrupt": "raise:ArithmeticError@p=0.5"},
                seed=seed)
            out = [healthmon.corruption_operand() for _ in range(32)]
            fp.reset()
            return [0 if v == 0.0 else 1 for v in out]

        a, b, c = pattern(7), pattern(7), pattern(8)
        assert a == b
        assert 0 < sum(a) < len(a)
        assert a != c

    def test_skip_and_n_modifiers(self):
        fp.configure({"kvstore.send": "raise:OSError@skip=2@n=1"})
        fp.check("kvstore.send")      # skipped
        fp.check("kvstore.send")      # skipped
        with pytest.raises(OSError):
            fp.check("kvstore.send")  # armed, fires
        fp.check("kvstore.send")      # n exhausted: quiet again
        assert fp.triggers("kvstore.send") == 1

    def test_faults_surface_in_profiler_metrics(self):
        fp.configure({"checkpoint.save": "raise:RuntimeError@n=1"})
        with pytest.raises(RuntimeError):
            fp.check("checkpoint.save")
        # counted with NO active profile run: accounting must not
        # depend on tracing being on
        assert profiler.metrics()["faults"] == {"checkpoint.save": 1}


# -- kvstore transport chaos --------------------------------------------------

class TestKVStoreChaos:
    def test_push_pull_survive_send_faults_bitwise(self, server):
        """Flaky transport, hardened client: every push/pull lands, the
        final value is bitwise what a clean wire produces, and both the
        faults and the retries are accounted."""
        profiler.set_config(filename="/tmp/fp_kv_profile.json",
                            xprof=False)
        profiler.set_state("run")
        try:
            fp.configure({"kvstore.send": "raise:ConnectionError@p=0.4",
                          "kvstore.pull": "raise:ConnectionError@p=0.4"},
                         seed=3)
            c = AsyncPSClient("127.0.0.1", server.port)
            c.init(1, np.zeros((8,), np.float32))
            for i in range(12):
                c.push(1, np.full((8,), float(i), np.float32))
            out = c.pull(1)
            # store-replace semantics: last push wins, bit-for-bit
            np.testing.assert_array_equal(
                out, np.full((8,), 11.0, np.float32))
            m = profiler.metrics()
            assert m["faults"].get("kvstore.send", 0) > 0
            total_faults = (m["faults"].get("kvstore.send", 0)
                            + m["faults"].get("kvstore.pull", 0))
            # full accounting: one transport retry per injected fault
            assert m["counters"]["kvstore.transport_retries"] \
                == total_faults
        finally:
            profiler.set_state("stop")

    def test_connect_faults_retry_then_succeed(self, server):
        profiler.set_config(filename="/tmp/fp_kv_profile.json",
                            xprof=False)
        profiler.set_state("run")
        try:
            fp.configure({"kvstore.connect": "raise:ConnectionError@n=2"})
            c = AsyncPSClient("127.0.0.1", server.port)
            c.init(2, np.ones((4,), np.float32))  # first use connects
            np.testing.assert_array_equal(
                c.pull(2), np.ones((4,), np.float32))
            m = profiler.metrics()
            assert fp.triggers("kvstore.connect") == 2
            assert m["counters"]["kvstore.connect_retries"] == 2
        finally:
            profiler.set_state("stop")

    def test_retry_budget_bounds_wall_time(self, server, monkeypatch):
        """A permanently broken transport raises within the bounded
        retry budget instead of hanging (the no-hang invariant)."""
        monkeypatch.setenv("MXTPU_PS_RETRY_MAX", "3")
        fp.configure({"kvstore.send": "raise:ConnectionError"})  # p=1
        c = AsyncPSClient("127.0.0.1", server.port)
        t0 = time.perf_counter()
        with pytest.raises(ConnectionError):
            c.push(3, np.zeros((2,), np.float32))
        assert time.perf_counter() - t0 < 5.0
        assert fp.triggers("kvstore.send") == 4  # 1 try + 3 retries

    def test_non_idempotent_ops_do_not_resend(self, server):
        """done() mutates server state (the shutdown count): a transport
        fault there must fail fast, never auto-resend."""
        fp.configure({"kvstore.send": "raise:ConnectionError"})
        c = AsyncPSClient("127.0.0.1", server.port)
        with pytest.raises(ConnectionError):
            c.done(0)
        assert fp.triggers("kvstore.send") == 1  # exactly one attempt

    def test_barrier_timeout_names_dead_ranks(self, server, monkeypatch):
        monkeypatch.setenv("MXTPU_PS_BARRIER_TIMEOUT", "1")
        monkeypatch.setenv("MXTPU_PS_DEAD_TIMEOUT", "0.3")
        beater = AsyncPSClient("127.0.0.1", server.port)
        beater.start_heartbeat(7, interval=0.1)
        time.sleep(0.3)
        beater.stop_heartbeat()       # rank 7 "dies"
        time.sleep(0.6)               # let the beat go stale
        a = AsyncPSClient("127.0.0.1", server.port)
        with pytest.raises(RuntimeError) as ei:
            a.barrier(2)              # partner never arrives
        msg = str(ei.value)
        assert "barrier aborted" in msg
        assert "dead ranks" in msg and "7" in msg, msg


# -- prefetch chaos -----------------------------------------------------------

class _Range:
    def __init__(self, n):
        self.n = n

    def __iter__(self):
        for i in range(self.n):
            yield np.full((2,), i, dtype=np.float32)

    def reset(self):
        pass


class TestPrefetchChaos:
    def test_worker_death_raises_once_then_stops_then_resets(self):
        profiler.set_config(filename="/tmp/fp_io_profile.json",
                            xprof=False)
        profiler.set_state("run")
        try:
            fp.configure({"io.prefetch.place": "raise:OSError@n=1"})
            pf = DevicePrefetchIter(_Range(4))
            with pytest.raises(OSError):      # surfaced exactly once...
                next(pf)
            with pytest.raises(StopIteration):  # ...then exhausted, not
                next(pf)                        # replayed forever
            with pytest.raises(StopIteration):
                next(pf)
            pf.reset()                        # restart-or-die: restart
            got = [int(np.asarray(b)[0]) for b in pf]
            assert got == [0, 1, 2, 3]        # fault-free parity
            m = profiler.metrics()
            assert m["faults"] == {"io.prefetch.place": 1}
            assert m["counters"]["io.prefetch_worker_deaths"] == 1
        finally:
            profiler.set_state("stop")

    def test_delay_faults_do_not_corrupt_order(self):
        fp.configure({"io.prefetch.place": "delay:5ms@p=0.5"}, seed=11)
        pf = DevicePrefetchIter(_Range(8))
        got = [int(np.asarray(b)[0]) for b in pf]
        assert got == list(range(8))          # slowdown is not reorder
        assert fp.triggers("io.prefetch.place") > 0


# -- compile/trace fallback chaos ---------------------------------------------

class TestCompileFallbackChaos:
    def _chain(self, x):
        y = x * 2.0
        z = y + 1.0
        return (z * z).asnumpy()

    def test_jit_compile_faults_fall_back_bitwise(self):
        """Every dispatch-cache compile fails (p=1): ops run untraced,
        results bitwise-match the jit-disabled eager truth, fallbacks
        tick, never a crash."""
        x = mx.nd.array(np.arange(6, dtype=np.float32))
        prev = R.set_imperative_jit(False)
        try:
            want = self._chain(x)             # the untraced truth
        finally:
            R.set_imperative_jit(prev)
        fp.configure({"imperative.jit.compile": "raise:RuntimeError"})
        R.reset_dispatch_stats()
        for _ in range(4):                    # past the compile threshold
            got = self._chain(x)
        np.testing.assert_array_equal(got, want)
        st = R.dispatch_stats()
        assert st["fallbacks"] > 0
        assert fp.triggers("imperative.jit.compile") > 0
        assert profiler.metrics()["faults"]["imperative.jit.compile"] \
            == fp.triggers("imperative.jit.compile")

    def test_bulk_compile_faults_replay_eagerly_bitwise(self):
        x = mx.nd.array(np.arange(5, dtype=np.float32))
        prev = R.set_imperative_jit(False)
        try:
            with engine.bulk(8):
                want = ((x + 3.0) * (x - 1.0)).asnumpy()
        finally:
            R.set_imperative_jit(prev)
        fp.configure({"engine.bulk.compile": "raise:RuntimeError"})
        R.reset_dispatch_stats()
        for _ in range(3):
            with engine.bulk(8):
                got = ((x + 3.0) * (x - 1.0)).asnumpy()
        np.testing.assert_array_equal(got, want)
        st = R.dispatch_stats()
        assert st["bulk_fallbacks"] >= 1
        assert fp.triggers("engine.bulk.compile") >= 1

    def test_fused_step_trace_faults_fall_back_bitwise(self):
        """fused_step.trace faults: every step takes the eager fallback
        and the whole run is bitwise identical to a pure-eager run of
        the same net (the fallback IS the eager path)."""
        def make(seed_from=None):
            net = gluon.nn.HybridSequential()
            with net.name_scope():
                net.add(gluon.nn.Dense(8, in_units=4, activation="relu"))
                net.add(gluon.nn.Dense(1, in_units=8))
            net.initialize(mx.init.Uniform(0.1))
            net.hybridize()
            if seed_from is not None:
                for (_, p1), (_, p2) in zip(
                        sorted(seed_from.collect_params().items()),
                        sorted(net.collect_params().items())):
                    p2.set_data(p1.data())
            return net

        rs = np.random.RandomState(0)
        x = mx.nd.array(rs.rand(4, 4).astype("float32"))
        y = mx.nd.array(rs.rand(4, 1).astype("float32"))
        loss_fn = gluon.loss.L2Loss()

        net_a = make()
        net_b = make(seed_from=net_a)

        # reference: the plain eager record/backward/step loop
        tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                             {"learning_rate": 0.1})
        for _ in range(4):
            with autograd.record():
                loss_b = loss_fn(net_b(x), y)
            loss_b.backward()
            tr_b.step(4)

        # faulted: every trace attempt raises -> per-step eager fallback
        from mxnet_tpu.gluon import fused_step as FS
        fp.configure({"fused_step.trace": "raise:RuntimeError"})
        FS.reset_stats()
        tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                             {"learning_rate": 0.1})
        step = tr_a.fuse_step(lambda xx, yy: loss_fn(net_a(xx), yy))
        for _ in range(4):
            loss_a = step(x, y, batch_size=4)
        assert step.last_mode == "fallback:trace-failed"
        assert FS.stats()["fallbacks"] > 0
        assert fp.triggers("fused_step.trace") > 0
        np.testing.assert_array_equal(loss_a.asnumpy(), loss_b.asnumpy())
        for (_, pa), (_, pb) in zip(
                sorted(net_a.collect_params().items()),
                sorted(net_b.collect_params().items())):
            np.testing.assert_array_equal(pa.data().asnumpy(),
                                          pb.data().asnumpy())

    def test_storage_alloc_faults_degrade_to_host(self):
        fp.configure({"storage.alloc": "raise:MemoryError@n=3"})
        a = mx.nd.zeros((4,))
        b = mx.nd.ones((4,))
        np.testing.assert_array_equal(a.asnumpy(), np.zeros((4,), "f"))
        np.testing.assert_array_equal(b.asnumpy(), np.ones((4,), "f"))
        assert fp.triggers("storage.alloc") >= 2


# -- crash-consistent checkpoints ---------------------------------------------

class TestCheckpointChaos:
    def test_nd_save_crash_never_corrupts_latest(self, tmp_path):
        fname = str(tmp_path / "weights.params")
        good = {"w": mx.nd.array(np.arange(4, dtype=np.float32))}
        mx.nd.save(fname, good)
        fp.configure({"checkpoint.save": "raise:RuntimeError@n=1"})
        with pytest.raises(RuntimeError):
            mx.nd.save(fname, {"w": mx.nd.zeros((4,))})  # crash mid-save
        # the published file is the intact PREVIOUS checkpoint...
        loaded = mx.nd.load(fname)
        np.testing.assert_array_equal(loaded["w"].asnumpy(),
                                      np.arange(4, dtype=np.float32))
        # ...and the aborted temp never leaks
        assert os.listdir(str(tmp_path)) == ["weights.params"]
        # a post-crash save works again
        mx.nd.save(fname, {"w": mx.nd.zeros((4,))})
        np.testing.assert_array_equal(mx.nd.load(fname)["w"].asnumpy(),
                                      np.zeros((4,), "f"))

    def test_trainer_save_states_crash_consistent(self, tmp_path):
        net = gluon.nn.Dense(2, in_units=3)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
        x = mx.nd.array(np.ones((2, 3), np.float32))
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        tr.step(2)
        fname = str(tmp_path / "trainer.states")
        tr.save_states(fname)
        before = open(fname, "rb").read()
        fp.configure({"checkpoint.save": "raise:OSError@n=1"})
        with pytest.raises(OSError):
            tr.save_states(fname)
        assert open(fname, "rb").read() == before  # bitwise intact
        tr.load_states(fname)                      # and loadable

    def test_checkpoint_manager_crash_keeps_previous_step(self, tmp_path):
        from mxnet_tpu.parallel import CheckpointManager
        ckpt = CheckpointManager(str(tmp_path / "ckpt"), use_orbax=False)
        state0 = {"w": np.arange(3, dtype=np.float32)}
        ckpt.save(0, state0)
        fp.configure({"checkpoint.save": "raise:RuntimeError@n=1"})
        with pytest.raises(RuntimeError):
            ckpt.save(1, {"w": np.zeros(3, np.float32)})
        # step 1 never published; step 0 restores bitwise
        assert ckpt.latest_step() == 0
        restored, step = ckpt.restore()
        assert step == 0
        np.testing.assert_array_equal(restored["w"], state0["w"])
        # recovery: the next save publishes normally
        ckpt.save(1, {"w": np.zeros(3, np.float32)})
        assert ckpt.latest_step() == 1


# -- the chaos training loop (tier-1 acceptance) ------------------------------

class TestChaosTrainingLoop:
    def _run_loop(self, faulted):
        """Small training loop: prefetched batches + fused step. Returns
        (losses, final params). Faulted runs add seeded raises/delays on
        the compile/trace/io seams — all of which must degrade, never
        crash, and must not change the math."""
        # fresh dispatch cache: the compile seams must actually be
        # crossed inside the measured loop (and identically on every
        # run, so faulted/clean and run/re-run comparisons line up)
        R._clear_dispatch_cache()
        R.reset_dispatch_stats()
        if faulted:
            fp.configure({
                "imperative.jit.compile": "raise:RuntimeError@p=0.5",
                "fused_step.trace": "raise:RuntimeError",
                "io.prefetch.place": "delay:1ms@p=0.3",
                "storage.alloc": "raise:MemoryError@p=0.2",
            }, seed=5)
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(8, in_units=4, activation="relu"))
            net.add(gluon.nn.Dense(1, in_units=8))
        net.initialize(mx.init.Xavier(rnd_type="uniform"))
        net.hybridize()
        loss_fn = gluon.loss.L2Loss()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05})
        step = tr.fuse_step(lambda xx, yy: loss_fn(net(xx), yy))
        rs = np.random.RandomState(0)
        batches = [(rs.rand(4, 4).astype("float32"),
                    rs.rand(4, 1).astype("float32")) for _ in range(6)]

        def to_nd(b):
            return mx.nd.array(b[0]), mx.nd.array(b[1])

        losses = []
        pf = DevicePrefetchIter(iter(batches), place_fn=to_nd)
        for x, y in pf:
            losses.append(float(step(x, y, batch_size=4)
                                .asnumpy().mean()))
        # name-independent: block naming counters advance per instance,
        # so compare params positionally in sorted-name order
        params = [p.data().asnumpy()
                  for _, p in sorted(net.collect_params().items())]
        triggered = dict(fp.metrics())
        fp.reset()
        return losses, params, triggered

    def test_faulted_loop_matches_fault_free_bitwise(self):
        t0 = time.perf_counter()
        clean_losses, clean_params, _ = self._run_loop(faulted=False)
        mx.random.seed(0)
        faulted_losses, faulted_params, triggered = \
            self._run_loop(faulted=True)
        # no hang: the whole faulted loop finishes promptly
        assert time.perf_counter() - t0 < 120.0
        # faults actually fired on the seams this loop crosses
        assert triggered.get("fused_step.trace", 0) > 0
        assert triggered.get("imperative.jit.compile", 0) > 0
        # no silent corruption: losses and final params are bitwise
        # equal — raises hit fallback paths that compute the same math,
        # delays only reorder time (fallbacks are eager; the clean run's
        # warming steps are eager too, and both paths' updates agree
        # bitwise on this net — the fused-step parity contract)
        assert faulted_losses == clean_losses
        assert len(faulted_params) == len(clean_params)
        for fa, cl in zip(faulted_params, clean_params):
            np.testing.assert_array_equal(fa, cl)

    def test_chaos_run_is_deterministic(self):
        """Same seed, same schedule: two faulted runs trigger the same
        fault counts and produce identical losses (replayability)."""
        mx.random.seed(0)
        l1, p1, t1 = self._run_loop(faulted=True)
        mx.random.seed(0)
        l2, p2, t2 = self._run_loop(faulted=True)
        assert t1 == t2
        assert l1 == l2


class TestServeGroupPortCeiling:
    def test_coordinator_near_port_ceiling_wraps_deterministically(
            self, monkeypatch):
        """A launcher coordinator port near 65535 must not overflow the
        derived server ports (cport + 1001 + s): the window wraps back
        into valid space, every rank computing the same base."""
        from mxnet_tpu.kvstore_async import serve_group
        monkeypatch.setenv("MXTPU_COORDINATOR", "127.0.0.1:65300")
        monkeypatch.setenv("MXTPU_NUM_PROCS", "1")
        monkeypatch.setenv("MXTPU_ASYNC_PS_PORT", "0")
        monkeypatch.delenv("MXTPU_NUM_SERVERS", raising=False)
        servers, clients = serve_group(0)
        try:
            assert servers and 0 < servers[0].port <= 65535
            clients[0].init(1, np.ones((2,), np.float32))
            np.testing.assert_array_equal(
                clients[0].pull(1), np.ones((2,), np.float32))
        finally:
            for s in servers:
                s.stop()


# -- slow: multiprocess PS chaos with a killed+restarted worker ---------------

def _ps_chaos_worker(rank, nproc, port_env_val, steps, die_at):
    os.environ["MXTPU_PROC_ID"] = str(rank)
    os.environ["MXTPU_NUM_PROCS"] = str(nproc)
    os.environ["MXTPU_ASYNC_PS_PORT"] = port_env_val
    os.environ["MXTPU_PS_HEARTBEAT_INTERVAL"] = "0.1"
    os.environ["MXTPU_PS_RETRY_BASE"] = "0.01"
    os.environ["MXTPU_PS_RETRY_CAP"] = "0.1"
    # flaky wire for every push/pull this worker makes — seeded per rank
    os.environ["MXNET_FAULTPOINTS"] = \
        "kvstore.send=raise:ConnectionError@p=0.15;" \
        "kvstore.pull=raise:ConnectionError@p=0.15"
    os.environ["MXNET_FAULTPOINTS_SEED"] = str(100 + rank)
    import numpy as np2
    import mxnet_tpu as mx2
    kv = mx2.kv.create("dist_async")
    target = np2.full((8,), 3.0, np2.float32)
    out = mx2.nd.zeros((8,))
    for step in range(steps):
        if step == die_at:
            kv._client.stop_heartbeat()
            os._exit(0)  # crash mid-training, no done()
        kv.pull(1, out=out)
        w = out.asnumpy()
        grad = w - target  # d/dw 0.5*(w-target)^2 — sgd pulls w to 3.0
        kv.push(1, mx2.nd.array(grad))
    kv.close()


class TestMultiprocessChaos:
    @pytest.mark.slow
    def test_worker_killed_and_restarted_under_send_faults(self):
        """Async PS under chaos: both workers train on a flaky wire
        (15% injected send/pull failure), one worker is killed
        mid-training and restarted. The run must neither deadlock nor
        diverge: the server survives, the dead rank is detected, and the
        weights converge to the optimum's ballpark."""
        os.environ.pop("MXTPU_COORDINATOR", None)
        os.environ["MXTPU_PROC_ID"] = "0"
        os.environ["MXTPU_NUM_PROCS"] = "3"
        os.environ["MXTPU_ASYNC_PS_PORT"] = "0"
        os.environ["MXTPU_PS_HEARTBEAT_INTERVAL"] = "0.1"
        os.environ["MXTPU_PS_DONE_TIMEOUT"] = "30"
        import mxnet_tpu.optimizer as opt
        kv = mx.kv.create("dist_async")
        try:
            kv.init(1, mx.nd.zeros((8,)))
            kv.set_optimizer(opt.create("sgd", learning_rate=0.2,
                                        wd=0.0))
            port = os.environ["MXTPU_ASYNC_PS_PORT"]
            ctx = mp.get_context("spawn")
            w1 = ctx.Process(target=_ps_chaos_worker,
                             args=(1, 3, port, 30, -1))
            w2 = ctx.Process(target=_ps_chaos_worker,
                             args=(2, 3, port, 30, 8))
            w1.start()
            w2.start()
            w2.join(120)
            assert w2.exitcode == 0      # died on schedule, no deadlock
            time.sleep(1.0)
            assert 2 in kv.get_dead_nodes(timeout=0.8)
            # restart the dead rank; it finishes its training share
            w2b = ctx.Process(target=_ps_chaos_worker,
                              args=(2, 3, port, 30, -1))
            w2b.start()
            w1.join(120)
            w2b.join(120)
            assert w1.exitcode == 0 and w2b.exitcode == 0
            out = mx.nd.zeros((8,))
            kv.pull(1, out=out)
            w = out.asnumpy()
            assert np.all(np.isfinite(w))
            # same final-loss ballpark as a fault-free run: sgd on this
            # quadratic converges to the target; chaos (duplicated or
            # dropped-then-retried pushes, a mid-flight restart) may
            # wiggle the tail but not the destination
            np.testing.assert_allclose(w, 3.0, atol=0.5)
        finally:
            kv.close()


# -- elastic-recovery fault points (ISSUE 7) ----------------------------------

class TestElasticFaultpoints:
    """The three seams welded into the elastic recovery loop:
    ``collective.allreduce`` (a failed cross-host reduction),
    ``elastic.restore`` (checkpoint bytes unreadable at restore time),
    ``elastic.reshard`` (the world-shrink commit itself interrupted)."""

    def test_catalog_documents_every_point(self):
        """Catalog check: every woven point is documented in the module
        docstring's table and in docs/RESILIENCE.md, and the docstring
        names no point that does not exist — a new faultpoint cannot
        land without its docs (and this test) noticing."""
        import re
        doc = fp.__doc__
        table = doc[doc.index("Fault-point catalog"):
                    doc.index("Configuration")]
        # first-column entries only (the point names); the prose in the
        # second column also backticks code references
        documented = set(re.findall(r"^``([a-z_]+(?:\.[a-z_]+)+)``",
                                    table, re.M))
        assert documented == set(fp.POINTS), (
            "faultpoint docstring catalog out of sync with POINTS: "
            "missing %s, stale %s" % (sorted(set(fp.POINTS) - documented),
                                      sorted(documented - set(fp.POINTS))))
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(repo, "docs", "RESILIENCE.md")) as f:
            resilience = f.read()
        undocd = [p for p in fp.POINTS if p not in resilience]
        assert not undocd, "points missing from docs/RESILIENCE.md: %s" \
            % sorted(undocd)

    def test_elastic_restore_fault_counts_and_recovers(self, tmp_path):
        from mxnet_tpu.parallel import CheckpointManager
        ckpt = CheckpointManager(str(tmp_path / "c"), use_orbax=False)
        state = {"w": np.arange(4, dtype=np.float32)}
        ckpt.save(3, state)
        fp.configure({"elastic.restore": "raise:OSError@n=1"})
        with pytest.raises(OSError):
            ckpt.restore()
        assert fp.metrics().get("elastic.restore") == 1
        # the schedule is exhausted (n=1): the retry restores bitwise
        restored, step = ckpt.restore()
        assert step == 3
        np.testing.assert_array_equal(restored["w"], state["w"])

    def test_elastic_reshard_fault_leaves_world_uncommitted(self):
        from mxnet_tpu.parallel import ElasticController

        class _KV:
            dead = [1]
            num_workers = 2
            resized = []

            def dead_nodes(self, timeout=3.0):
                return list(self.dead)

            def resize(self, n):
                self.resized.append(int(n))

        kv = _KV()
        ctl = ElasticController(kvstore=kv, world=range(2), rank=0,
                                poll_interval=0.0)
        ctl.poll(force=True)
        fp.configure({"elastic.reshard": "raise:RuntimeError@n=1"})
        with pytest.raises(RuntimeError):
            ctl.reshard()
        # the fault fired BEFORE the commit: world and kvstore untouched
        assert ctl.world == [0, 1] and kv.resized == []
        assert fp.metrics().get("elastic.reshard") == 1
        survivors, _ = ctl.reshard()       # retry commits
        assert survivors == [0] and kv.resized == [1]

    def test_collective_fault_drives_loop_recovery_bitwise(self, tmp_path):
        """An injected collective failure inside the step is classified,
        recovered from the newest checkpoint, and the finished run is
        BITWISE equal to a fault-free one (restore rewinds to saved
        state, steps are pure functions of (state, batch))."""
        import jax.numpy as jnp
        from mxnet_tpu.parallel import (CheckpointManager,
                                        HostGradReducer,
                                        elastic_train_loop)
        reducer = HostGradReducer(None)    # world of 1: no wire, but
                                           # the fault seam still fires

        def step(state, b):
            g = reducer.allreduce(
                np.full(4, float(b), np.float32), [0], 0)
            return {"w": state["w"] + jnp.asarray(g)}, None

        def run(faulted, sub):
            fp.reset()
            if faulted:
                # skip=1: step 0 completes and publishes the first
                # checkpoint (a failure with nothing saved re-raises by
                # design); later hits draw p=0.4
                fp.configure(
                    {"collective.allreduce":
                     "raise:ConnectionError@p=0.4@n=4@skip=1"}, seed=11)
            ckpt = CheckpointManager(str(tmp_path / sub),
                                     use_orbax=False)
            state, last, done = elastic_train_loop(
                step, {"w": jnp.zeros(4, jnp.float32)},
                list(range(8)), ckpt, save_every=2, max_failures=6)
            assert done and last == 7
            triggered = fp.metrics().get("collective.allreduce", 0)
            fp.reset()
            return np.asarray(state["w"]), triggered

        # seeded schedule: p=0.4 over >=8 hits fires at least once
        w_clean, _ = run(False, "clean")
        w_chaos, hits = run(True, "chaos")
        assert hits >= 1
        assert np.array_equal(w_clean, w_chaos)
        el = profiler.metrics()["elastic"]
        assert el.get("failures", 0) >= 1 and el.get("restores", 0) >= 1


# -- io data-plane fault points (ISSUE 11) ------------------------------------

class TestIOPlaneFaultpoints:
    """The four seams woven into the sharded data plane
    (``io.shard.read`` / ``io.record.corrupt`` / ``io.worker.decode`` /
    ``io.service.fetch``) obey the same chaos contract as every other
    point: deterministic seeded replay, full accounting, and recovery
    paths that end in bitwise-identical output. The deep end-to-end
    coverage lives in tests/test_shard_service.py; here we pin the
    replay property for the pool seam specifically."""

    def test_decode_chaos_replays_deterministically(self):
        from mxnet_tpu.io import DecodePool

        def run():
            fp.configure(
                {"io.worker.decode": "raise:ValueError@p=0.3"},
                seed=21)
            # one worker => a strictly sequential hit series, so the
            # per-point RNG makes the trigger pattern a pure function
            # of (seed, hit index)
            pool = DecodePool(list(range(30)), lambda x: x, workers=1)
            out = list(pool)
            n = fp.triggers("io.worker.decode")
            fp.reset()
            return out, n

        (o1, n1), (o2, n2) = run(), run()
        assert o1 == o2 == list(range(30))  # nothing lost, order kept
        assert n1 == n2 and n1 > 0          # identical trigger pattern
