"""Training C ABI test: compile example/capi/train_mnist.c with gcc and
run it against libmxnet_tpu.so — the VERDICT r1 'done' criterion for the
widened C surface (a cpp-package-style demo training MNIST through the
ABI in CI). Also unit-drives the MXT* entry points through ctypes.

Ref slot: the reference validates its C surface via cpp-package tests +
tests/cpp/; six language frontends attach at this seam
(include/mxnet/c_api.h).
"""
import ctypes
import os
import shutil
import subprocess
import sys

import numpy as onp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "mxnet_tpu", "libmxnet_tpu.so")
DEMO = os.path.join(REPO, "example", "capi", "train_mnist.c")


def _build_lib():
    if not os.path.exists(LIB):
        subprocess.run(["make", "-C", os.path.join(REPO, "src")],
                       check=True, capture_output=True)
    return os.path.exists(LIB)


def _has_training_abi():
    if not _build_lib():
        return False
    lib = ctypes.CDLL(LIB)
    return hasattr(lib, "MXTImperativeInvoke")


pytestmark = pytest.mark.skipif(
    not _has_training_abi(), reason="native training ABI not built")


class TestCtypesSurface:
    """Drive the MXT* training surface from Python ctypes, in-process."""

    @classmethod
    def setup_class(cls):
        import mxnet_tpu  # noqa: F401 — interpreter already initialized
        lib = ctypes.CDLL(LIB)
        lib.MXTGetLastError.restype = ctypes.c_char_p
        # argtypes matter: a bare python int from an array index would be
        # truncated to 32 bits without them
        vp, u32, i64p = ctypes.c_void_p, ctypes.c_uint32, \
            ctypes.POINTER(ctypes.c_int64)
        vpp = ctypes.POINTER(vp)
        lib.MXTNDArrayCreate.argtypes = [i64p, u32, ctypes.c_int, vpp]
        lib.MXTNDArrayFromData.argtypes = [i64p, u32, ctypes.c_int, vp,
                                           ctypes.c_size_t, vpp]
        lib.MXTNDArrayFree.argtypes = [vp]
        lib.MXTNDArrayGetShape.argtypes = [vp, ctypes.POINTER(u32), i64p]
        lib.MXTNDArraySyncCopyToCPU.argtypes = [vp, vp, ctypes.c_size_t]
        lib.MXTImperativeInvoke.argtypes = [
            ctypes.c_char_p, u32, vpp, u32,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(u32), vpp,
            u32]
        lib.MXTAutogradMarkVariables.argtypes = [u32, vpp]
        lib.MXTAutogradSetIsRecording.argtypes = [ctypes.c_int]
        lib.MXTAutogradBackward.argtypes = [u32, vpp]
        lib.MXTNDArrayGetGrad.argtypes = [vp, vpp]
        cls.lib = lib

    def _check(self, rc):
        assert rc == 0, self.lib.MXTGetLastError().decode()

    def test_ndarray_create_shape_copy(self):
        h = ctypes.c_void_p()
        shape = (ctypes.c_int64 * 2)(3, 4)
        self._check(self.lib.MXTNDArrayCreate(shape, 2, 0,
                                              ctypes.byref(h)))
        ndim = ctypes.c_uint32()
        out_shape = (ctypes.c_int64 * 8)()
        self._check(self.lib.MXTNDArrayGetShape(h, ctypes.byref(ndim),
                                                out_shape))
        assert ndim.value == 2
        assert list(out_shape[:2]) == [3, 4]
        buf = (ctypes.c_float * 12)()
        self._check(self.lib.MXTNDArraySyncCopyToCPU(h, buf, 48))
        assert list(buf) == [0.0] * 12
        self._check(self.lib.MXTNDArrayFree(h))

    def test_from_data_and_invoke(self):
        data = onp.arange(6, dtype="float32").reshape(2, 3)
        h = ctypes.c_void_p()
        shape = (ctypes.c_int64 * 2)(2, 3)
        self._check(self.lib.MXTNDArrayFromData(
            shape, 2, 0, data.ctypes.data_as(ctypes.c_void_p),
            data.nbytes, ctypes.byref(h)))
        outs = (ctypes.c_void_p * 4)()
        nout = ctypes.c_uint32()
        ins = (ctypes.c_void_p * 1)(h)
        self._check(self.lib.MXTImperativeInvoke(
            b"relu", 1, ins, 0, None, None, ctypes.byref(nout), outs, 4))
        assert nout.value == 1
        buf = (ctypes.c_float * 6)()
        self._check(self.lib.MXTNDArraySyncCopyToCPU(outs[0], buf, 24))
        onp.testing.assert_allclose(list(buf), data.ravel())
        self.lib.MXTNDArrayFree(h)
        self.lib.MXTNDArrayFree(outs[0])

    def test_invoke_with_params(self):
        data = onp.ones((2, 2), "float32")
        h = ctypes.c_void_p()
        shape = (ctypes.c_int64 * 2)(2, 2)
        self._check(self.lib.MXTNDArrayFromData(
            shape, 2, 0, data.ctypes.data_as(ctypes.c_void_p),
            data.nbytes, ctypes.byref(h)))
        keys = (ctypes.c_char_p * 1)(b"scalar")
        vals = (ctypes.c_char_p * 1)(b"2.5")
        outs = (ctypes.c_void_p * 1)()
        nout = ctypes.c_uint32()
        ins = (ctypes.c_void_p * 1)(h)
        self._check(self.lib.MXTImperativeInvoke(
            b"_mul_scalar", 1, ins, 1, keys, vals, ctypes.byref(nout),
            outs, 1))
        buf = (ctypes.c_float * 4)()
        self._check(self.lib.MXTNDArraySyncCopyToCPU(outs[0], buf, 16))
        assert list(buf) == [2.5] * 4
        self.lib.MXTNDArrayFree(h)
        self.lib.MXTNDArrayFree(outs[0])

    def test_autograd_round_trip(self):
        data = onp.asarray([[3.0]], "float32")
        h = ctypes.c_void_p()
        shape = (ctypes.c_int64 * 2)(1, 1)
        self._check(self.lib.MXTNDArrayFromData(
            shape, 2, 0, data.ctypes.data_as(ctypes.c_void_p),
            data.nbytes, ctypes.byref(h)))
        arr = (ctypes.c_void_p * 1)(h)
        self._check(self.lib.MXTAutogradMarkVariables(1, arr))
        self._check(self.lib.MXTAutogradSetIsRecording(1))
        outs = (ctypes.c_void_p * 1)()
        nout = ctypes.c_uint32()
        ins = (ctypes.c_void_p * 2)(h, h)
        self._check(self.lib.MXTImperativeInvoke(
            b"elemwise_mul", 2, ins, 0, None, None, ctypes.byref(nout),
            outs, 1))
        self._check(self.lib.MXTAutogradSetIsRecording(0))
        loss = (ctypes.c_void_p * 1)(outs[0])
        self._check(self.lib.MXTAutogradBackward(1, loss))
        g = ctypes.c_void_p()
        self._check(self.lib.MXTNDArrayGetGrad(h, ctypes.byref(g)))
        buf = (ctypes.c_float * 1)()
        self._check(self.lib.MXTNDArraySyncCopyToCPU(g, buf, 4))
        assert abs(buf[0] - 6.0) < 1e-5  # d(x^2)/dx = 2x = 6
        for p in (h, outs[0], g):
            self.lib.MXTNDArrayFree(p)

    def test_error_reporting(self):
        outs = (ctypes.c_void_p * 1)()
        nout = ctypes.c_uint32()
        rc = self.lib.MXTImperativeInvoke(
            b"not_a_real_op", 0, None, 0, None, None, ctypes.byref(nout),
            outs, 1)
        assert rc == -1
        assert b"not_a_real_op" in self.lib.MXTGetLastError()


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_cpp_package_demo_trains(tmp_path):
    """Compile the header-only C++ frontend demo (cpp-package analog)
    and run it standalone — the reference's cpp-package/example/mlp.cpp
    slot over our C ABI."""
    exe = str(tmp_path / "train_mlp")
    subprocess.run(
        ["g++", "-O2", "-std=c++17",
         "-I", os.path.join(REPO, "cpp-package", "include"),
         os.path.join(REPO, "cpp-package", "example", "train_mlp.cpp"),
         "-o", exe,
         "-L" + os.path.join(REPO, "mxnet_tpu"), "-lmxnet_tpu",
         "-Wl,-rpath," + os.path.join(REPO, "mxnet_tpu")],
        check=True, capture_output=True)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    res = subprocess.run([exe], env=env, capture_output=True, text=True,
                         timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "cpp-package MLP training OK" in res.stdout


@pytest.mark.skipif(shutil.which("gcc") is None, reason="no gcc")
def test_c_demo_trains_mnist(tmp_path):
    """Compile the pure-C demo and run it as a standalone process
    (embedded CPython): loss must drop 5x."""
    exe = str(tmp_path / "train_mnist")
    subprocess.run(
        ["gcc", "-O2", DEMO, "-o", exe,
         "-L" + os.path.join(REPO, "mxnet_tpu"), "-lmxnet_tpu",
         "-Wl,-rpath," + os.path.join(REPO, "mxnet_tpu")],
        check=True, capture_output=True)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    res = subprocess.run([exe], env=env, capture_output=True, text=True,
                         timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "C-ABI MNIST training OK" in res.stdout
