"""Operator tests (model: tests/python/unittest/test_operator.py).
Forward values vs numpy; gradients vs finite differences for a core subset."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_unary_math():
    x = np.random.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
    a = nd.array(x)
    for name, ref in [("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt),
                      ("square", np.square), ("abs", np.abs),
                      ("sin", np.sin), ("cos", np.cos), ("tanh", np.tanh),
                      ("floor", np.floor), ("ceil", np.ceil),
                      ("log1p", np.log1p), ("expm1", np.expm1)]:
        assert_almost_equal(getattr(nd, name)(a), ref(x), rtol=1e-5, atol=1e-6)
    assert_almost_equal(nd.sigmoid(a), 1 / (1 + np.exp(-x)), rtol=1e-5)
    assert_almost_equal(nd.relu(nd.array(x - 1)), np.maximum(x - 1, 0))
    assert_almost_equal(nd.rsqrt(a), 1 / np.sqrt(x), rtol=1e-5)
    assert_almost_equal(nd.reciprocal(a), 1 / x, rtol=1e-5)
    assert_almost_equal(nd.clip(a, a_min=0.8, a_max=1.5), np.clip(x, 0.8, 1.5))


def test_broadcast_binary():
    a = np.random.rand(2, 1, 4).astype(np.float32)
    b = np.random.rand(1, 3, 4).astype(np.float32)
    na, nb = nd.array(a), nd.array(b)
    assert_almost_equal(nd.broadcast_add(na, nb), a + b)
    assert_almost_equal(nd.broadcast_mul(na, nb), a * b)
    assert_almost_equal(nd.broadcast_maximum(na, nb), np.maximum(a, b))
    assert_almost_equal(nd.broadcast_power(na, nb), a ** b, rtol=1e-4)


def test_gradients_numeric():
    check_numeric_gradient(lambda x: (nd.tanh(x)).sum(), [np.random.rand(3, 2)])
    check_numeric_gradient(lambda x: (nd.sigmoid(x) ** 2).sum(),
                           [np.random.rand(4)])
    check_numeric_gradient(lambda a, b: nd.dot(a, b).sum(),
                           [np.random.rand(2, 3), np.random.rand(3, 2)])
    check_numeric_gradient(lambda x: nd.softmax(x, axis=-1).sum(axis=0)[0],
                           [np.random.rand(3, 4)])


def test_fully_connected():
    x = nd.array(np.random.rand(5, 8).astype(np.float32))
    w = nd.array(np.random.rand(3, 8).astype(np.float32))
    b = nd.array(np.random.rand(3).astype(np.float32))
    out = nd.FullyConnected(x, w, b, num_hidden=3)
    assert_almost_equal(out, x.asnumpy() @ w.asnumpy().T + b.asnumpy(),
                        rtol=1e-4)
    out2 = nd.FullyConnected(x, w, None, num_hidden=3, no_bias=True)
    assert_almost_equal(out2, x.asnumpy() @ w.asnumpy().T, rtol=1e-4)


def test_convolution_shapes_and_values():
    # identity kernel check
    x = nd.array(np.random.rand(1, 1, 5, 5).astype(np.float32))
    w = nd.zeros((1, 1, 3, 3))
    w[0, 0, 1, 1] = 1.0
    out = nd.Convolution(x, w, None, kernel=(3, 3), pad=(1, 1), num_filter=1,
                         no_bias=True)
    assert_almost_equal(out, x.asnumpy(), rtol=1e-5)
    # shape math: stride + dilate
    x2 = nd.zeros((2, 3, 16, 16))
    w2 = nd.zeros((8, 3, 3, 3))
    out2 = nd.Convolution(x2, w2, None, kernel=(3, 3), stride=(2, 2),
                          pad=(1, 1), num_filter=8, no_bias=True)
    assert out2.shape == (2, 8, 8, 8)
    # grouped conv
    w3 = nd.zeros((8, 1, 3, 3))
    xg = nd.zeros((2, 8, 8, 8))
    out3 = nd.Convolution(xg, w3, None, kernel=(3, 3), pad=(1, 1),
                          num_filter=8, num_group=8, no_bias=True)
    assert out3.shape == (2, 8, 8, 8)
    # 1D conv
    x1 = nd.zeros((2, 4, 10))
    w1 = nd.zeros((6, 4, 3))
    assert nd.Convolution(x1, w1, None, kernel=(3,), num_filter=6,
                          no_bias=True).shape == (2, 6, 8)


def test_conv_gradient():
    np.random.seed(3)
    x = np.random.rand(1, 2, 4, 4)
    w = np.random.rand(2, 2, 3, 3)

    def f(xx, ww):
        return nd.Convolution(xx, ww, None, kernel=(3, 3), pad=(1, 1),
                              num_filter=2, no_bias=True).sum()
    check_numeric_gradient(f, [x, w], rtol=2e-2, atol=1e-3)


def test_deconvolution():
    x = nd.array(np.random.rand(1, 3, 4, 4).astype(np.float32))
    w = nd.array(np.random.rand(3, 5, 3, 3).astype(np.float32))
    out = nd.Deconvolution(x, w, None, kernel=(3, 3), stride=(2, 2),
                           num_filter=5, no_bias=True)
    assert out.shape == (1, 5, 9, 9)
    # parity with torch-style formula: (in-1)*stride - 2*pad + kernel
    out2 = nd.Deconvolution(x, w, None, kernel=(3, 3), stride=(1, 1),
                            pad=(1, 1), num_filter=5, no_bias=True)
    assert out2.shape == (1, 5, 4, 4)


def test_pooling():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    a = nd.array(x)
    mp = nd.Pooling(a, kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert_almost_equal(mp, np.array([[[[5, 7], [13, 15]]]], np.float32))
    ap = nd.Pooling(a, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    assert_almost_equal(ap, np.array([[[[2.5, 4.5], [10.5, 12.5]]]], np.float32))
    gp = nd.Pooling(a, pool_type="max", global_pool=True)
    assert gp.shape == (1, 1, 1, 1) and float(gp.asnumpy().ravel()[0]) == 15
    fp = nd.Pooling(a, kernel=(3, 3), stride=(2, 2), pool_type="max",
                    pooling_convention="full")
    assert fp.shape == (1, 1, 2, 2)


def test_batchnorm_train_and_inference():
    x = nd.array(np.random.rand(4, 3, 5, 5).astype(np.float32))
    gamma, beta = nd.ones((3,)), nd.zeros((3,))
    mean, var = nd.zeros((3,)), nd.ones((3,))
    with autograd.record():
        out, bm, bv = nd.BatchNorm(x, gamma, beta, mean, var, fix_gamma=False)
    o = out.asnumpy()
    assert abs(o.mean()) < 1e-4 and abs(o.std() - 1) < 1e-2
    # inference mode uses moving stats
    out2, _, _ = nd.BatchNorm(x, gamma, beta, mean, var, fix_gamma=False)
    assert_almost_equal(out2, x.asnumpy() / np.sqrt(1 + 1e-3), rtol=1e-3)


def test_layernorm():
    x = nd.array(np.random.rand(2, 6).astype(np.float32))
    out = nd.LayerNorm(x, nd.ones((6,)), nd.zeros((6,)))
    o = out.asnumpy()
    assert np.allclose(o.mean(axis=-1), 0, atol=1e-5)
    assert np.allclose(o.std(axis=-1), 1, atol=1e-2)


def test_activation_and_leaky():
    x = nd.array(np.array([-2.0, -0.5, 0.5, 2.0], np.float32))
    assert_almost_equal(nd.Activation(x, act_type="relu"),
                        np.maximum(x.asnumpy(), 0))
    lr = nd.LeakyReLU(x, act_type="leaky", slope=0.1)
    assert_almost_equal(lr, np.where(x.asnumpy() > 0, x.asnumpy(),
                                     0.1 * x.asnumpy()))
    el = nd.LeakyReLU(x, act_type="elu", slope=1.0)
    assert_almost_equal(el, np.where(x.asnumpy() > 0, x.asnumpy(),
                                     np.expm1(x.asnumpy())), rtol=1e-5)
    g = nd.LeakyReLU(x, act_type="gelu")
    assert g.shape == x.shape


def test_softmax_family():
    x = np.random.rand(3, 5).astype(np.float32)
    a = nd.array(x)
    sm = nd.softmax(a, axis=-1).asnumpy()
    assert np.allclose(sm.sum(-1), 1, atol=1e-5)
    lsm = nd.log_softmax(a, axis=-1).asnumpy()
    assert_almost_equal(np.exp(lsm), sm, rtol=1e-5)
    ce = nd.softmax_cross_entropy(a, nd.array([1, 2, 3], dtype="int32"))
    expect = -np.log(sm[np.arange(3), [1, 2, 3]]).sum()
    assert_almost_equal(ce, expect, rtol=1e-4)


def test_embedding():
    w = nd.array(np.random.rand(10, 4).astype(np.float32))
    idx = nd.array([1, 3, 5], dtype="int32")
    out = nd.Embedding(idx, w, input_dim=10, output_dim=4)
    assert_almost_equal(out, w.asnumpy()[[1, 3, 5]])
    # gradient flows into weight rows
    w.attach_grad()
    with autograd.record():
        y = nd.Embedding(idx, w, input_dim=10, output_dim=4).sum()
    y.backward()
    g = w.grad.asnumpy()
    assert g[1].sum() == 4 and g[0].sum() == 0


def test_sequence_ops():
    data = nd.array(np.arange(24, dtype=np.float32).reshape(4, 3, 2))  # (T,B,E)
    length = nd.array([2, 4, 1], dtype="int32")
    masked = nd.SequenceMask(data, length, use_sequence_length=True, value=-1)
    m = masked.asnumpy()
    assert m[3, 0, 0] == -1 and m[1, 0, 0] == data.asnumpy()[1, 0, 0]
    last = nd.SequenceLast(data, length, use_sequence_length=True)
    assert_almost_equal(last, data.asnumpy()[[1, 3, 0], [0, 1, 2]])
    rev = nd.SequenceReverse(data, length, use_sequence_length=True)
    assert_almost_equal(rev.asnumpy()[0, 0], data.asnumpy()[1, 0])


def test_lrn_l2norm():
    x = nd.array(np.random.rand(2, 8, 4, 4).astype(np.float32))
    out = nd.LRN(x, nsize=5)
    assert out.shape == x.shape
    l2 = nd.L2Normalization(x, mode="instance")
    n = np.sqrt((x.asnumpy().reshape(2, -1) ** 2).sum(1) + 1e-10)
    assert_almost_equal(l2.asnumpy()[0], x.asnumpy()[0] / n[0], rtol=1e-4)


def test_where_gather_scatter():
    cond = nd.array([1.0, 0.0, 1.0])
    x, y = nd.array([1.0, 2.0, 3.0]), nd.array([10.0, 20.0, 30.0])
    assert_almost_equal(nd.where(cond, x, y), np.array([1, 20, 3], np.float32))
    data = nd.array(np.arange(9, dtype=np.float32).reshape(3, 3))
    idx = nd.array([[0, 2], [1, 0]], dtype="int32")  # 2 points (0,1),(2,0)
    out = nd.gather_nd(data, idx)
    assert_almost_equal(out, np.array([1.0, 6.0], np.float32))
    sc = nd.scatter_nd(nd.array([5.0, 7.0]), idx, shape=(3, 3))
    assert float(sc.asnumpy()[0, 1]) == 5.0 and float(sc.asnumpy()[2, 0]) == 7.0


def test_linalg():
    a = np.random.rand(3, 3).astype(np.float32)
    spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
    L = nd.linalg.potrf(nd.array(spd))
    assert_almost_equal(L.asnumpy() @ L.asnumpy().T, spd, rtol=1e-3)
    A = nd.array(np.random.rand(2, 3, 4).astype(np.float32))
    B = nd.array(np.random.rand(2, 4, 5).astype(np.float32))
    out = nd.linalg.gemm2(A, B)
    assert_almost_equal(out, np.matmul(A.asnumpy(), B.asnumpy()), rtol=1e-4)
    C = nd.array(np.random.rand(3, 3).astype(np.float32))
    inv = nd.linalg.inverse(C)
    assert_almost_equal(inv.asnumpy() @ C.asnumpy(), np.eye(3), atol=1e-3)


def test_cast_and_dtype_ops():
    x = nd.array([1.5, 2.5])
    assert nd.cast(x, dtype="int32").dtype == np.int32
    assert nd.cast(x, dtype="bfloat16").asnumpy().dtype.name in ("bfloat16",
                                                                 "float32")
    assert nd.zeros_like(x).shape == x.shape
    assert float(nd.ones_like(x).sum().asscalar()) == 2.0


def test_smooth_l1():
    x = nd.array([-2.0, -0.5, 0.5, 2.0])
    out = nd.smooth_l1(x, scalar=1.0)
    expect = np.where(np.abs(x.asnumpy()) < 1, 0.5 * x.asnumpy() ** 2,
                      np.abs(x.asnumpy()) - 0.5)
    assert_almost_equal(out, expect)


def test_upsampling_depthspace():
    x = nd.array(np.random.rand(1, 4, 2, 2).astype(np.float32))
    up = nd.UpSampling(x, scale=2, sample_type="nearest")
    assert up.shape == (1, 4, 4, 4)
    d2s = nd.depth_to_space(x, block_size=2)
    assert d2s.shape == (1, 1, 4, 4)
    assert_almost_equal(nd.space_to_depth(d2s, block_size=2), x.asnumpy())


def test_batchnorm_large_mean_f32_no_cancellation():
    """f32 inputs with |mean| >> std must not lose the variance to
    catastrophic cancellation (the two-pass f32 branch in
    ops/nn.py batch_norm; half-precision inputs take the fused
    single-pass branch whose cancellation error sits far below the
    input quantization noise)."""
    import numpy as onp
    from mxnet_tpu import autograd
    rs = onp.random.RandomState(0)
    x = (1000.0 + 0.1 * rs.randn(64, 8, 4, 4)).astype("float32")
    ones = mx.nd.array(onp.ones(8, "float32"))
    zeros = mx.nd.array(onp.zeros(8, "float32"))
    with autograd.record():
        out, mean, var = nd.BatchNorm(mx.nd.array(x), ones, zeros,
                                      zeros, ones, fix_gamma=False,
                                      eps=1e-5)
    v = var.asnumpy()
    onp.testing.assert_allclose(v, 0.01, rtol=0.15)
    assert 0.85 < out.asnumpy().std() < 1.15
