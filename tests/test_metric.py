"""Metric tests (ref: tests/python/unittest/test_metric.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import metric


def test_accuracy():
    m = metric.Accuracy()
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = mx.nd.array([1, 0, 0])
    m.update([label], [pred])
    name, acc = m.get()
    assert name == "accuracy"
    assert acc == pytest.approx(2.0 / 3)


def test_accuracy_same_shape_pred():
    m = metric.Accuracy()
    m.update([mx.nd.array([1, 1, 0])], [mx.nd.array([1, 0, 0])])
    assert m.get()[1] == pytest.approx(2.0 / 3)


def test_top_k_accuracy():
    m = metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array([[0.1, 0.5, 0.4], [0.6, 0.3, 0.1]])
    label = mx.nd.array([2, 2])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(0.5)


def test_top_k_accuracy_column_labels():
    """Regression: (N, 1) labels must not broadcast to (N, N, k) —
    the mis-broadcast counted cross-row matches and pushed the metric
    past 1.0."""
    m = metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array([[0.1, 0.5, 0.4], [0.6, 0.3, 0.1], [0.2, 0.2, 0.6]])
    label = mx.nd.array([[2], [2], [2]])  # column vector, not flat
    m.update([label], [pred])
    acc = m.get()[1]
    assert acc <= 1.0
    # same data flat: identical answer
    m2 = metric.TopKAccuracy(top_k=2)
    m2.update([mx.nd.array([2, 2, 2])], [pred])
    assert acc == pytest.approx(m2.get()[1])


def test_f1():
    m = metric.F1()
    pred = mx.nd.array([[0.2, 0.8], [0.9, 0.1], [0.3, 0.7], [0.6, 0.4]])
    label = mx.nd.array([1, 0, 0, 1])
    m.update([label], [pred])
    # tp=1 fp=1 fn=1 → precision=recall=0.5 → f1=0.5
    assert m.get()[1] == pytest.approx(0.5)


def test_mcc_perfect():
    m = metric.MCC()
    pred = mx.nd.array([[0.1, 0.9], [0.8, 0.2]])
    label = mx.nd.array([1, 0])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(1.0)


def test_regression_metrics():
    pred = mx.nd.array([1.0, 2.0, 3.0])
    label = mx.nd.array([1.5, 2.0, 2.5])
    mae = metric.MAE(); mae.update([label], [pred])
    mse = metric.MSE(); mse.update([label], [pred])
    rmse = metric.RMSE(); rmse.update([label], [pred])
    assert mae.get()[1] == pytest.approx(1.0 / 3)
    assert mse.get()[1] == pytest.approx((0.25 + 0 + 0.25) / 3)
    assert rmse.get()[1] == pytest.approx(np.sqrt((0.25 + 0 + 0.25) / 3))


def test_perplexity():
    m = metric.Perplexity(ignore_label=None)
    pred = mx.nd.array([[0.25, 0.75], [0.5, 0.5]])
    label = mx.nd.array([1, 0])
    m.update([label], [pred])
    expect = np.exp(-(np.log(0.75) + np.log(0.5)) / 2)
    assert m.get()[1] == pytest.approx(expect, rel=1e-5)


def test_cross_entropy_nll():
    pred = mx.nd.array([[0.25, 0.75], [0.5, 0.5]])
    label = mx.nd.array([1, 0])
    ce = metric.CrossEntropy(); ce.update([label], [pred])
    expect = -(np.log(0.75) + np.log(0.5)) / 2
    assert ce.get()[1] == pytest.approx(expect, rel=1e-5)
    nll = metric.NegativeLogLikelihood(); nll.update([label], [pred])
    assert nll.get()[1] == pytest.approx(expect, rel=1e-5)


def test_pearson():
    m = metric.PearsonCorrelation()
    pred = mx.nd.array([1.0, 2.0, 3.0, 4.0])
    label = mx.nd.array([2.0, 4.0, 6.0, 8.0])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(1.0, rel=1e-6)


def test_composite_and_create():
    m = metric.create(["acc", "mse"])
    assert isinstance(m, metric.CompositeEvalMetric)
    pred = mx.nd.array([[0.3, 0.7]])
    label = mx.nd.array([1])
    m.update([label], [pred])
    names, values = m.get()
    assert "accuracy" in names and "mse" in names


def test_custom_metric():
    def feval(label, pred):
        return float(np.abs(label - pred).sum())
    m = metric.np(feval)
    m.update([mx.nd.array([1.0])], [mx.nd.array([0.5])])
    assert m.get()[1] == pytest.approx(0.5)


def test_loss_metric_and_reset():
    m = metric.Loss()
    m.update(None, [mx.nd.array([1.0, 2.0])])
    assert m.get()[1] == pytest.approx(1.5)
    m.reset()
    assert np.isnan(m.get()[1])


def test_device_metrics_never_pull_batches_to_host(monkeypatch):
    """The device-accumulating metrics must not materialize per batch:
    update() may not call asnumpy(), and only get() syncs (measured
    3.3x eval-loop speedup on the real chip, benchmark/metric_sync.py)."""
    def _boom(self):
        raise AssertionError("metric update() pulled a batch to host")
    monkeypatch.setattr(mx.nd.NDArray, "asnumpy", _boom)
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = mx.nd.array([1, 0, 0])
    for m in (metric.Accuracy(), metric.F1(), metric.CrossEntropy()):
        m.update([label], [pred])
    metric.MSE().update([mx.nd.array([1.0, 2.0])],
                        [mx.nd.array([1.5, 2.5])])
    metric.Loss().update(None, [mx.nd.array([1.0, 2.0])])
    monkeypatch.undo()
    m = metric.Accuracy()
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(2.0 / 3)
