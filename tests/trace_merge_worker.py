"""Worker script for the 2-process distributed-observability test
(ISSUE 6 acceptance): each rank runs a short dist_async kvstore training
loop plus a fused gluon step with profiling on, scrapes its own
``/metrics`` endpoint mid-run, and dumps a per-rank trace shard
(``pid=rank``) into ``MXTPU_TRACE_DIR`` for the launcher-side merge.

Run via: python tools/launch.py -n 2 python tests/trace_merge_worker.py
"""
import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as onp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, profiler  # noqa: E402,F401


def main():
    rank = int(os.environ["MXTPU_PROC_ID"])
    nproc = int(os.environ["MXTPU_NUM_PROCS"])
    outdir = os.environ["MXTPU_TRACE_DIR"]
    shard = os.path.join(outdir, "trace_rank%d.json" % rank)
    assert profiler.PID == rank, (profiler.PID, rank)

    profiler.set_config(filename=shard, xprof=False)
    profiler.set_state("run")
    port = profiler.serve_metrics(port=0)

    kv = mx.kv.create("dist_async")
    kv.init("w", mx.nd.zeros((8,)))
    for _ in range(6):
        kv.push("w", mx.nd.ones((8,)) * (rank + 1))
        out = mx.nd.zeros((8,))
        kv.pull("w", out=out)

    # a few fused train steps so fused_step.step has a histogram
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize(mx.init.Uniform(0.1))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    step = gluon.train_step(net, gluon.loss.L2Loss(), trainer)
    x = mx.nd.array(onp.ones((4, 4), onp.float32))
    y = mx.nd.array(onp.zeros((4, 1), onp.float32))
    for _ in range(4):
        step(x, y, batch_size=4)

    # let at least one timestamped heartbeat per server land so the
    # shard carries a primary clock-sync sample
    deadline = time.time() + 10
    while time.time() < deadline:
        if any(v.get("primary") for v in profiler.clock_sync().values()):
            break
        time.sleep(0.05)
    assert any(v.get("primary") for v in profiler.clock_sync().values()), \
        "no primary clock-sync sample arrived"

    # acceptance: p50/p95/p99 for the wired-in histograms
    lat = profiler.metrics()["latency"]
    for name in ("kvstore.pull_rtt", "kvstore.push_rtt",
                 "fused_step.step"):
        h = lat[name]
        assert h["count"] > 0 and h["p50_us"] <= h["p95_us"] \
            <= h["p99_us"] <= h["max_us"], (name, h)
    print("rank %d: LATENCY_OK" % rank)

    # acceptance: live scrape of our own /metrics mid-run is valid
    # Prometheus text exposition including those histograms
    from urllib.request import urlopen
    body = urlopen("http://127.0.0.1:%d/metrics" % port,
                   timeout=5).read().decode()
    assert "# TYPE mxtpu_latency_seconds histogram" in body
    assert 'name="kvstore.pull_rtt"' in body
    assert "mxtpu_counter_total" in body
    for line in body.splitlines():
        assert line.startswith("#") or " " in line, line
    print("rank %d: SCRAPE_OK" % rank)

    # the worker can also pull the PS server's own metrics
    srv_metrics = kv.server_metrics()
    assert srv_metrics and "latency" in srv_metrics[0]
    assert any(k.startswith("rank_heartbeat_age.")
               for k in srv_metrics[0]["kvstore_server"]), \
        srv_metrics[0].get("kvstore_server")
    print("rank %d: SERVER_METRICS_OK" % rank)

    kv._barrier()
    profiler.set_state("stop")
    profiler.dump()
    data = json.load(open(shard))
    assert data["metadata"]["rank"] == rank
    assert all(e.get("pid") == rank for e in data["traceEvents"])
    print("rank %d/%d: OBS_WORKER_OK" % (rank, nproc))
    if rank == 0:
        kv.close()
    else:
        kv.done()


if __name__ == "__main__":
    main()
