"""Reference-artifact compatibility (VERDICT r2 weak items 5+6).

- Symbol JSON: fixtures emitted by REAL Apache MXNet, checked in from
  the reference's own test data (tests/fixtures/ref_mxnet_1x_symbol.json
  = tests/python/mkl/data/test_mkldnn_test_mkldnn_model_model1.json,
  1.x format mxnet_version 10200; ref_mxnet_legacy_symbol.json =
  tests/python/unittest/save_000800.json, pre-1.0 param/attr format) —
  not self-referential round trips.
- CSR: dot(csr, dense) runs a device-native kernel on the CSR
  components (ref: src/operator/tensor/dot-inl.h DotCsrDnsDns), no
  densification.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


class TestReferenceSymbolJSON:
    def test_1x_format_loads_binds_forwards(self):
        """The 1.x-format VGG-style net from the reference's own test
        data: 34 arguments, conv/pool/fc stack, SoftmaxOutput head."""
        sym = mx.sym.load_json(
            open(os.path.join(FIX, "ref_mxnet_1x_symbol.json")).read())
        args = sym.list_arguments()
        assert len(args) == 34
        assert sym.list_outputs() == ["softmax_output"]
        ops = {n.op for n in sym._topo() if not n.is_variable()}
        assert {"Convolution", "Pooling", "Activation",
                "SoftmaxOutput"} <= ops
        ex = sym.simple_bind(grad_req="null", data=(1, 3, 32, 32),
                             softmax_label=(1,))
        (out,) = ex.forward()
        p = out.asnumpy()
        assert p.shape[0] == 1 and np.allclose(p.sum(), 1.0, atol=1e-5)

    def test_legacy_format_loads_binds_forwards(self):
        """The pre-1.0 format (per-node param/attr dicts, 2-tuple
        inputs) that the reference upgrades via legacy_json_util.cc."""
        sym = mx.sym.load_json(
            open(os.path.join(FIX, "ref_mxnet_legacy_symbol.json")).read())
        args = sym.list_arguments()
        assert "fc1_weight" in args and "data" in args
        ex = sym.simple_bind(grad_req="null", data=(2, 100),
                             softmax_label=(2,))
        (out,) = ex.forward()
        assert np.allclose(out.asnumpy().sum(axis=-1), 1.0, atol=1e-5)

    def test_legacy_metadata_preserved(self):
        """ctx_group/lr_mult metadata from the legacy 'attr' dicts is
        kept (underscore-prefixed) instead of leaking into kernels."""
        sym = mx.sym.load_json(
            open(os.path.join(FIX, "ref_mxnet_legacy_symbol.json")).read())
        data = next(n for n in sym._topo() if n.name == "data")
        assert data.attrs.get("__ctx_group__") == "stage1"


class TestCSRDeviceNativeDot:
    def _csr(self):
        dense = np.array([[0, 2, 0, 1],
                          [0, 0, 0, 0],
                          [3, 0, 0, 4]], np.float32)
        return mx.nd.sparse.csr_matrix(dense) \
            if hasattr(mx.nd.sparse, "csr_matrix") \
            else mx.nd.array(dense).tostype("csr"), dense

    def test_dot_csr_dense_matches_dense(self):
        csr, dense = self._csr()
        rhs = mx.nd.array(np.arange(8, dtype=np.float32).reshape(4, 2))
        out = mx.nd.dot(csr, rhs)
        np.testing.assert_allclose(out.asnumpy(), dense @ rhs.asnumpy())

    def test_dot_csr_transpose(self):
        csr, dense = self._csr()
        rhs = mx.nd.array(np.arange(6, dtype=np.float32).reshape(3, 2))
        out = mx.nd.dot(csr, rhs, transpose_a=True)
        np.testing.assert_allclose(out.asnumpy(), dense.T @ rhs.asnumpy())

    def test_dot_dense_csr(self):
        csr, dense = self._csr()
        lhs = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
        out = mx.nd.dot(lhs, csr)
        np.testing.assert_allclose(out.asnumpy(), lhs.asnumpy() @ dense)

    def test_kernel_never_touches_dense_buffer(self):
        """The kernel consumes ONLY the CSR components — proof it does
        not densify on contact."""
        from mxnet_tpu.ndarray.sparse import dot_csr_dense
        import jax.numpy as jnp
        _, dense = self._csr()
        vals = jnp.asarray([2.0, 1.0, 3.0, 4.0])
        cols = jnp.asarray([1, 3, 0, 3])
        indptr = jnp.asarray([0, 2, 2, 4])
        rhs = jnp.arange(8, dtype=jnp.float32).reshape(4, 2)
        out = dot_csr_dense(vals, cols, indptr, rhs, 3)
        np.testing.assert_allclose(np.asarray(out), dense @ np.asarray(rhs))

    def test_kernel_differentiable_and_jittable(self):
        from mxnet_tpu.ndarray.sparse import dot_csr_dense
        import jax
        import jax.numpy as jnp
        vals = jnp.asarray([2.0, 1.0, 3.0, 4.0])
        cols = jnp.asarray([1, 3, 0, 3])
        indptr = jnp.asarray([0, 2, 2, 4])
        rhs = jnp.ones((4, 2), jnp.float32)

        @jax.jit
        def loss(v, d):
            return jnp.sum(dot_csr_dense(v, cols, indptr, d, 3))

        gv, gd = jax.grad(loss, argnums=(0, 1))(vals, rhs)
        # d/dv_j = sum over out columns of dense[col_j] = 2.0 each
        np.testing.assert_allclose(np.asarray(gv), 2.0)
        assert np.isfinite(np.asarray(gd)).all()


class TestCSRDotIntegration:
    def test_autograd_records_sparse_dot(self):
        """Gradients must flow through mx.nd.dot(csr, w) — a silent
        zero grad would make sparse-feature training learn nothing."""
        dense = np.array([[0, 2, 0], [1, 0, 3]], np.float32)
        csr = mx.nd.array(dense).tostype("csr")
        w = mx.nd.array(np.ones((3, 2), np.float32))
        w.attach_grad()
        with mx.autograd.record():
            loss = mx.nd.dot(csr, w).sum()
        loss.backward()
        np.testing.assert_allclose(w.grad.asnumpy(),
                                   dense.T @ np.ones((2, 2), np.float32))

    def test_csr_csr_densify_fallback(self):
        a = mx.nd.array(np.eye(3, dtype=np.float32)).tostype("csr")
        b = mx.nd.array(np.arange(9, dtype=np.float32)
                        .reshape(3, 3)).tostype("csr")
        out = mx.nd.dot(a, b)  # falls back to the dense path, no recursion
        np.testing.assert_allclose(out.asnumpy(),
                                   np.arange(9).reshape(3, 3))

    def test_out_kwarg_honored(self):
        csr = mx.nd.array(np.eye(2, dtype=np.float32)).tostype("csr")
        rhs = mx.nd.array(np.ones((2, 2), np.float32))
        buf = mx.nd.zeros((2, 2))
        res = mx.nd.dot(csr, rhs, out=buf)
        assert res is buf
        np.testing.assert_allclose(buf.asnumpy(), np.ones((2, 2)))

    def test_unsupported_transpose_raises(self):
        csr = mx.nd.array(np.eye(2, dtype=np.float32)).tostype("csr")
        rhs = mx.nd.array(np.ones((2, 2), np.float32))
        with pytest.raises(NotImplementedError):
            mx.nd.dot(csr, rhs, transpose_b=True)
