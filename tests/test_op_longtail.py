"""Long-tail op batch tests: hawkesll, count_sketch, index_array,
KL sparse reg, window fns, image ops, quantized family, DGL graph ops.

Ref slots: tests/python/unittest/test_contrib_hawkesll.py,
test_contrib_stes_op.py, test_numpy_op.py window cases,
tests/python/unittest/test_image.py, test_contrib_quantization.py (in
tests/python/quantization/), test_dgl_graph.py.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _nd(a, dtype="float32"):
    return mx.nd.array(onp.asarray(a, dtype=dtype))


class TestHawkesLL:
    def test_single_event_closed_form(self):
        """One event at t=tau, one mark: ll = log(mu) - mu*tau - remaining
        compensator to max_time."""
        mu_v, tau, T_max, alpha_v, beta_v = 0.4, 0.7, 2.0, 0.3, 1.5
        ll, st = nd.contrib.hawkesll(
            _nd([[mu_v]]), _nd([alpha_v]), _nd([beta_v]), _nd([[0.0]]),
            _nd([[tau]]), mx.nd.array(onp.array([[0]], "int32")),
            _nd([1.0]), _nd([T_max]))
        # event term: log(mu) - mu*tau (state was 0 before the event)
        # remaining: mu*(T-tau) + alpha*1*(1-exp(-beta*(T-tau)))
        d = T_max - tau
        want = (onp.log(mu_v) - mu_v * tau
                - (mu_v * d + alpha_v * (1 - onp.exp(-beta_v * d))))
        onp.testing.assert_allclose(ll.asnumpy()[0], want, rtol=1e-5)
        # final state: exp(-beta d) * (1 + 0)
        onp.testing.assert_allclose(st.asnumpy()[0, 0],
                                    onp.exp(-beta_v * d), rtol=1e-5)

    def test_valid_length_masks_tail(self):
        args = lambda T: (  # noqa: E731
            _nd(onp.full((1, 2), 0.5)), _nd([0.2, 0.2]), _nd([1.0, 1.0]),
            _nd(onp.zeros((1, 2))),
            _nd(onp.full((1, T), 0.3)),
            mx.nd.array(onp.zeros((1, T), "int32")),
            _nd([3.0]), _nd([5.0]))
        ll_5, _ = nd.contrib.hawkesll(*args(5))
        ll_3pad, _ = nd.contrib.hawkesll(*args(8))  # 8 slots, 3 valid
        onp.testing.assert_allclose(ll_5.asnumpy(), ll_3pad.asnumpy(),
                                    rtol=1e-5)

    def test_differentiable(self):
        mu = _nd(onp.full((1, 2), 0.5))
        mu.attach_grad()
        with mx.autograd.record():
            ll, st = nd.contrib.hawkesll(
                mu, _nd([0.2, 0.2]), _nd([1.0, 1.0]),
                _nd(onp.zeros((1, 2))), _nd(onp.full((1, 4), 0.3)),
                mx.nd.array(onp.array([[0, 1, 0, 1]], "int32")),
                _nd([4.0]), _nd([2.0]))
            loss = ll.sum()
        loss.backward()
        assert onp.abs(mu.grad.asnumpy()).min() > 0


class TestCountSketch:
    def test_projection(self):
        d = _nd([[1.0, 2.0, 3.0, 4.0]])
        h = _nd([0, 2, 2, 1])
        s = _nd([1, -1, 1, -1])
        out = nd.contrib.count_sketch(d, h, s, out_dim=3).asnumpy()
        onp.testing.assert_allclose(out, [[1.0, -4.0, 1.0]])

    def test_gradient_is_transpose(self):
        d = _nd(onp.random.RandomState(0).randn(2, 4))
        h = _nd([0, 1, 1, 2])
        s = _nd([1, -1, 1, 1])
        d.attach_grad()
        with mx.autograd.record():
            out = nd.contrib.count_sketch(d, h, s, out_dim=3)
        out.backward()
        # d(sum out)/d(data[i]) = s[i]
        onp.testing.assert_allclose(d.grad.asnumpy(),
                                    onp.tile([1, -1, 1, 1], (2, 1)))


class TestIndexArray:
    def test_full(self):
        out = nd.contrib.index_array(mx.nd.zeros((2, 3))).asnumpy()
        for i in range(2):
            for j in range(3):
                assert out[i, j].tolist() == [i, j]

    def test_axes_subset(self):
        out = nd.contrib.index_array(mx.nd.zeros((2, 3, 4)),
                                     axes=(2, 0)).asnumpy()
        assert out.shape == (2, 3, 4, 2)
        assert out[1, 0, 3].tolist() == [3, 1]


class TestKLSparseReg:
    def test_identity_forward_penalized_backward(self):
        rs = onp.random.RandomState(0)
        x = _nd(rs.rand(4, 3) * 0.5 + 0.25)
        x.attach_grad()
        with mx.autograd.record():
            y = nd.IdentityAttachKLSparseReg(x, sparseness_target=0.1,
                                             penalty=0.01)
        onp.testing.assert_allclose(y.asnumpy(), x.asnumpy())
        y.backward()
        rho_hat = x.asnumpy().mean(axis=0)
        want = 1.0 + 0.01 * (-0.1 / rho_hat + 0.9 / (1 - rho_hat))
        onp.testing.assert_allclose(x.grad.asnumpy(),
                                    onp.tile(want, (4, 1)), rtol=1e-5)


class TestImageOps:
    def test_to_tensor_normalize(self):
        rs = onp.random.RandomState(1)
        img = rs.randint(0, 255, (5, 7, 3)).astype("uint8")
        t = nd.image.to_tensor(_nd(img, "uint8")).asnumpy()
        onp.testing.assert_allclose(
            t, img.transpose(2, 0, 1).astype("float32") / 255, atol=1e-6)
        n = nd.image.normalize(mx.nd.array(t), mean=(0.4, 0.5, 0.6),
                               std=(0.2, 0.2, 0.2)).asnumpy()
        onp.testing.assert_allclose(
            n[1], (t[1] - 0.5) / 0.2, atol=1e-5)

    def test_flips(self):
        img = _nd(onp.arange(12).reshape(2, 2, 3))
        lr = nd.image.flip_left_right(img).asnumpy()
        onp.testing.assert_array_equal(lr, img.asnumpy()[:, ::-1])
        tb = nd.image.flip_top_bottom(img).asnumpy()
        onp.testing.assert_array_equal(tb, img.asnumpy()[::-1])

    def test_resize_and_crop(self):
        img = _nd(onp.arange(48).reshape(4, 4, 3))
        r = nd.image.resize(img, size=(2, 2))
        assert r.shape == (2, 2, 3)
        c = nd.image.crop(img, x=1, y=0, width=2, height=3)
        onp.testing.assert_array_equal(c.asnumpy(),
                                       img.asnumpy()[0:3, 1:3])

    def test_random_ops_shapes(self):
        img = _nd(onp.random.RandomState(2).rand(4, 4, 3))
        for fn, kw in [(nd.image.random_flip_left_right, {}),
                       (nd.image.random_brightness,
                        dict(min_factor=0.5, max_factor=1.5)),
                       (nd.image.random_contrast,
                        dict(min_factor=0.5, max_factor=1.5)),
                       (nd.image.random_saturation,
                        dict(min_factor=0.5, max_factor=1.5)),
                       (nd.image.random_hue,
                        dict(min_factor=-0.1, max_factor=0.1)),
                       (nd.image.random_lighting, {})]:
            out = fn(img, **kw)
            assert out.shape == img.shape, fn

    def test_hue_identity_at_zero(self):
        img = _nd(onp.random.RandomState(3).rand(4, 4, 3))
        out = nd.image.random_hue(img, min_factor=0.0,
                                  max_factor=0.0).asnumpy()
        # the NTSC YIQ matrices round-trip to ~1.4e-3 (same constants as
        # the reference's image_random-inl.h)
        onp.testing.assert_allclose(out, img.asnumpy(), atol=5e-3)


class TestQuantizedOps:
    def test_quantize_v2_requantize_roundtrip(self):
        x = _nd(onp.linspace(-2, 2, 64))
        q, mn, mx_ = nd.contrib.quantize_v2(x)
        s = max(abs(float(mn.asnumpy())), abs(float(mx_.asnumpy()))) / 127
        onp.testing.assert_allclose(q.asnumpy() * s, x.asnumpy(),
                                    atol=s)

    def test_quantized_fc_matches_float(self):
        rs = onp.random.RandomState(4)
        x = rs.randn(3, 8).astype("float32")
        w = rs.randn(5, 8).astype("float32")
        qx, mnx, mxx = nd.contrib.quantize_v2(_nd(x))
        qw, mnw, mxw = nd.contrib.quantize_v2(_nd(w))
        acc, mn, mx_ = nd.contrib.quantized_fully_connected(
            qx, qw, None, mnx, mxx, mnw, mxw, _nd(0), _nd(0),
            num_hidden=5, no_bias=True)
        sd = max(abs(float(mnx.asnumpy())), abs(float(mxx.asnumpy()))) / 127
        sw = max(abs(float(mnw.asnumpy())), abs(float(mxw.asnumpy()))) / 127
        got = acc.asnumpy().astype("float64") * sd * sw
        want = x @ w.T
        assert onp.abs(got - want).max() < 0.15

    def test_quantized_conv_matches_float(self):
        rs = onp.random.RandomState(5)
        x = rs.randn(1, 2, 6, 6).astype("float32")
        w = rs.randn(3, 2, 3, 3).astype("float32")
        qx, mnx, mxx = nd.contrib.quantize_v2(_nd(x))
        qw, mnw, mxw = nd.contrib.quantize_v2(_nd(w))
        acc, mn, mx_ = nd.contrib.quantized_conv(
            qx, qw, None, mnx, mxx, mnw, mxw, _nd(0), _nd(0),
            kernel=(3, 3), num_filter=3, no_bias=True)
        sd = max(abs(float(mnx.asnumpy())), abs(float(mxx.asnumpy()))) / 127
        sw = max(abs(float(mnw.asnumpy())), abs(float(mxw.asnumpy()))) / 127
        got = acc.asnumpy().astype("float64") * sd * sw
        want = nd.Convolution(_nd(x), _nd(w), kernel=(3, 3), num_filter=3,
                              no_bias=True).asnumpy()
        assert onp.abs(got - want).max() < 0.3

    def test_quantized_pooling(self):
        x = onp.arange(16, dtype="int8").reshape(1, 1, 4, 4)
        q, mn, mx_ = nd.contrib.quantized_pooling(
            mx.nd.array(x.astype("float32")).astype("int8"),
            _nd(-1), _nd(1), kernel=(2, 2), stride=(2, 2),
            pool_type="max")
        onp.testing.assert_array_equal(q.asnumpy(),
                                       [[[[5, 7], [13, 15]]]])

    def test_quantized_elemwise_add(self):
        a = _nd(onp.array([0.5, -0.25]))
        b = _nd(onp.array([0.25, 0.25]))
        qa, mna, mxa = nd.contrib.quantize_v2(a)
        qb, mnb, mxb = nd.contrib.quantize_v2(b)
        out, mn, mx_ = nd.contrib.quantized_elemwise_add(
            qa, qb, mna, mxa, mnb, mxb)
        s = float(mx_.asnumpy()) / (2.0 ** 31)
        got = out.asnumpy() * s
        onp.testing.assert_allclose(got, [0.75, 0.0], atol=0.01)

    def test_calibrate_entropy(self):
        rs = onp.random.RandomState(6)
        acts = rs.randn(10000).astype("float32")
        hist, edges = onp.histogram(acts, bins=1001)
        mn, mx_ = nd.contrib.calibrate_entropy(_nd(hist), _nd(edges))
        thr = float(mx_.asnumpy())
        assert 0.5 < thr < 4.5  # a sane KL threshold for N(0,1)
        assert float(mn.asnumpy()) == -thr


class TestDGLGraph:
    def _graph(self):
        data_np = onp.arange(1, 21)
        indices_np = onp.array([1, 2, 3, 4, 0, 2, 3, 4, 0, 1, 3, 4,
                                0, 1, 2, 4, 0, 1, 2, 3])
        indptr_np = onp.array([0, 4, 8, 12, 16, 20])
        return mx.nd.sparse.csr_matrix((data_np, indices_np, indptr_np),
                                       shape=(5, 5))

    def test_uniform_sample_reference_example(self):
        """ref: dgl_graph.cc:744 docstring example."""
        a = self._graph()
        seed = mx.nd.array(onp.arange(5, dtype="int64"))
        v, subg, layer = nd.contrib.dgl_csr_neighbor_uniform_sample(
            a, seed, num_args=2, num_hops=1, num_neighbor=2,
            max_num_vertices=5)
        assert v.asnumpy().tolist() == [0, 1, 2, 3, 4, 5]
        assert layer.asnumpy().tolist() == [0, 0, 0, 0, 0]
        dense = subg.asnumpy()
        # sampled edges carry the original edge values
        orig = a.asnumpy()
        nz = dense != 0
        onp.testing.assert_array_equal(dense[nz], orig[nz])
        # each row sampled at most num_neighbor edges
        assert (nz.sum(axis=1) <= 2).all()

    def test_non_uniform_sample_respects_zero_prob(self):
        a = self._graph()
        prob = mx.nd.array(onp.array([1, 0, 0, 0, 1], "float32"))
        seed = mx.nd.array(onp.array([1], "int64"))
        v, subg, layer = nd.contrib.dgl_csr_neighbor_non_uniform_sample(
            a, prob, seed, num_args=3, num_hops=1, num_neighbor=2,
            max_num_vertices=5)
        dense = subg.asnumpy()
        # only cols 0 and 4 can be sampled from row 1
        assert dense[1, 1] == 0 and dense[1, 2] == 0 and dense[1, 3] == 0

    def test_subgraph_reference_example(self):
        """ref: dgl_graph.cc:1115 docstring example."""
        x = onp.array([[1, 0, 0, 2], [3, 0, 4, 0],
                       [0, 5, 0, 0], [0, 6, 7, 0]], "int64")
        g = mx.nd.sparse.csr_matrix(x)
        new, orig = nd.contrib.dgl_subgraph(
            g, mx.nd.array([0, 1, 2]), num_args=2, return_mapping=True)
        onp.testing.assert_array_equal(
            new.asnumpy(), [[1, 0, 0], [2, 0, 3], [0, 4, 0]])
        onp.testing.assert_array_equal(
            orig.asnumpy(), [[1, 0, 0], [3, 0, 4], [0, 5, 0]])

    def test_edge_id_reference_example(self):
        x = onp.array([[1, 0, 0], [0, 2, 0], [0, 0, 3]], "int64")
        g = mx.nd.sparse.csr_matrix(x)
        out = nd.contrib.edge_id(g, mx.nd.array([0, 0, 1, 1, 2, 2]),
                                 mx.nd.array([0, 1, 1, 2, 0, 2]))
        assert out.asnumpy().tolist() == [1.0, -1.0, 2.0, -1.0, -1.0, 3.0]

    def test_adjacency_and_getnnz(self):
        a = self._graph()
        adj = nd.contrib.dgl_adjacency(a)
        assert adj.asnumpy().sum() == 20.0
        assert int(nd.contrib.getnnz(a).asnumpy()) == 20
        assert nd.contrib.getnnz(a, axis=1).asnumpy().tolist() == [4] * 5

    def test_graph_compact(self):
        a = self._graph()
        seed = mx.nd.array(onp.array([0, 1], "int64"))
        v, subg, layer = nd.contrib.dgl_csr_neighbor_uniform_sample(
            a, seed, num_args=2, num_hops=1, num_neighbor=1,
            max_num_vertices=8)
        n = int(v.asnumpy()[-1])
        comp = nd.contrib.dgl_graph_compact(
            subg, v, num_args=2, graph_sizes=(n,), return_mapping=False)
        assert comp.shape == (n, n)


class TestRNNParamConcat:
    def test_concat(self):
        a = _nd(onp.arange(4))
        b = _nd(onp.arange(6))
        out = nd._rnn_param_concat(a, b, dim=0)
        assert out.shape == (10,)
