"""ONNX bridge tests (ref slot: tests/python-pytest/onnx/ in the
reference). Covers the hand-rolled protobuf codec (against
hand-computed wire bytes), export/import round trips incl. model-zoo
resnet18, metadata, and import_to_gluon."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as onnx_mxnet
from mxnet_tpu.contrib.onnx import proto as P


class TestProtoCodec:
    def test_varint_wire_bytes(self):
        """Hand-computed bytes per the protobuf spec."""
        out = bytearray()
        P._w_varint(out, 1)
        assert bytes(out) == b"\x01"
        out = bytearray()
        P._w_varint(out, 300)   # 0xAC 0x02
        assert bytes(out) == b"\xac\x02"
        v, pos = P._r_varint(b"\xac\x02", 0)
        assert v == 300 and pos == 2

    def test_tensor_proto_roundtrip(self):
        arr = onp.arange(12, dtype="float32").reshape(3, 4)
        t = P.tensor_from_numpy("w", arr)
        t2 = P.TensorProto.decode(t.encode())
        assert t2.name == "w" and t2.dims == [3, 4]
        onp.testing.assert_array_equal(P.tensor_to_numpy(t2), arr)

    def test_tensor_int64(self):
        arr = onp.array([1, -2, 3], "int64")
        t2 = P.TensorProto.decode(P.tensor_from_numpy("i", arr).encode())
        onp.testing.assert_array_equal(P.tensor_to_numpy(t2), arr)

    def test_node_attrs_roundtrip(self):
        n = P.NodeProto("Conv", name="c", inputs=["x", "w"],
                        outputs=["y"],
                        attrs={"kernel_shape": [3, 3], "alpha": 0.5,
                               "mode": "same", "group": 1})
        n2 = P.NodeProto.decode(n.encode())
        assert n2.op_type == "Conv" and n2.inputs == ["x", "w"]
        assert n2.attrs["kernel_shape"] == [3, 3]
        assert abs(n2.attrs["alpha"] - 0.5) < 1e-7
        assert n2.attrs["mode"] == "same"
        assert n2.attrs["group"] == 1

    def test_known_model_header_bytes(self):
        """ModelProto{ir_version=7} must open with field1 varint 7 =
        tag 0x08, value 0x07 (spec-derived, not codec-derived)."""
        g = P.GraphProto()
        m = P.ModelProto(graph=g, ir_version=7)
        assert m.encode()[:2] == b"\x08\x07"

    def test_negative_int_attr(self):
        n = P.NodeProto("Softmax", outputs=["y"], attrs={"axis": -1})
        n2 = P.NodeProto.decode(n.encode())
        assert n2.attrs["axis"] == -1


def _small_net():
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Conv2D(8, 3, padding=1),
            mx.gluon.nn.BatchNorm(),
            mx.gluon.nn.Activation("relu"),
            mx.gluon.nn.MaxPool2D(2),
            mx.gluon.nn.Flatten(),
            mx.gluon.nn.Dense(10))
    net.initialize()
    return net


class TestRoundTrip:
    def _roundtrip(self, net, shape, tmp_path, atol=1e-5):
        x = mx.nd.array(
            onp.random.RandomState(0).rand(*shape).astype("float32"))
        ref = net(x).asnumpy()
        sym = net(mx.sym.var("data"))
        params = {p.name: p.data() for p in net.collect_params().values()}
        path = str(tmp_path / "m.onnx")
        onnx_mxnet.export_model(sym, params, [shape], onnx_file_path=path)
        sym2, arg_params, aux_params = onnx_mxnet.import_model(path)
        args = dict(arg_params)
        args["data"] = x
        out = sym2.bind(args=args, aux_states=aux_params) \
            .forward()[0].asnumpy()
        assert float(onp.abs(out - ref).max()) <= atol, \
            float(onp.abs(out - ref).max())

    def test_small_net(self, tmp_path):
        net = _small_net()
        net(mx.nd.zeros((1, 3, 16, 16)))
        self._roundtrip(net, (1, 3, 16, 16), tmp_path)

    def test_resnet18(self, tmp_path):
        """VERDICT r1 'done' criterion: model-zoo resnet18 export->import
        reproduces outputs."""
        from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
        net = resnet18_v1()
        net.initialize()
        net(mx.nd.zeros((1, 3, 224, 224)))
        self._roundtrip(net, (1, 3, 224, 224), tmp_path, atol=1e-4)

    def test_metadata(self, tmp_path):
        net = _small_net()
        net(mx.nd.zeros((1, 3, 16, 16)))
        sym = net(mx.sym.var("data"))
        params = {p.name: p.data() for p in net.collect_params().values()}
        path = str(tmp_path / "m.onnx")
        onnx_mxnet.export_model(sym, params, [(1, 3, 16, 16)],
                                onnx_file_path=path)
        meta = onnx_mxnet.get_model_metadata(path)
        assert meta["input_tensor_data"] == [("data", (1, 3, 16, 16))]
        assert len(meta["output_tensor_data"]) == 1

    def test_import_to_gluon(self, tmp_path):
        net = _small_net()
        x = mx.nd.array(
            onp.random.RandomState(1).rand(2, 3, 16, 16).astype("float32"))
        ref = net(x).asnumpy()
        sym = net(mx.sym.var("data"))
        params = {p.name: p.data() for p in net.collect_params().values()}
        path = str(tmp_path / "m.onnx")
        onnx_mxnet.export_model(sym, params, [(2, 3, 16, 16)],
                                onnx_file_path=path)
        gnet = onnx_mxnet.import_to_gluon(path)
        out = gnet(x).asnumpy()
        onp.testing.assert_allclose(out, ref, atol=1e-5)

    def test_unknown_op_raises(self, tmp_path):
        g = P.GraphProto()
        g.nodes.append(P.NodeProto("NotARealOp", inputs=["x"],
                                   outputs=["y"]))
        g.inputs.append(P.ValueInfo("x", P.DT_FLOAT, [1]))
        g.outputs.append(P.ValueInfo("y", P.DT_FLOAT, [1]))
        path = str(tmp_path / "bad.onnx")
        with open(path, "wb") as f:
            f.write(P.ModelProto(graph=g).encode())
        with pytest.raises(NotImplementedError):
            onnx_mxnet.import_model(path)
