"""Persistent AOT compile cache + restore-from-peer (ISSUE 19b/19c;
mxnet_tpu/gluon/compile_cache.py, kvstore snapshot plane,
parallel/elastic.py peer restore).

Three halves:

* compile cache units — store/load roundtrip of a real compiled
  executable, the never-fatal contract (miss / corrupt entry / disabled
  cache all degrade to ``None`` with the right counter, never an
  exception), and key sensitivity (different signature keys land on
  different entries); plus the in-process warm path: a second
  identically-seeded fused trainer replays the first one's executable
  off disk, bitwise;
* the snapshot plane — SnapshotTable semantics (newest-step wins,
  requester exclusion, heartbeat liveness filter, ``stale_timeout <= 0``
  escape hatch) and the real v1 wire (opcodes 18/19) end to end,
  including the no-snapshot ``None`` reply;
* restore_from_peer fallbacks — transport error (the shape a v0
  server's ``_RE_ERR`` reply surfaces as), no snapshot, HMAC mismatch
  (an unauthenticated blob must never reach ``pickle.loads``), torn
  decode, and the missing-secret off switch — every one counted and
  ``None``, never raised — plus the happy roundtrip and the
  elastic-loop e2e where a dead rank resumes from its live peer's
  in-memory state with zero rewind/replay, bitwise-identical to an
  unfaulted twin.
"""
import hmac as _hmac
import hashlib
import os
import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, profiler
from mxnet_tpu import kvstore_async as KA
from mxnet_tpu._debug import faultpoint, goodput, watchdog
from mxnet_tpu.gluon import compile_cache as CC
from mxnet_tpu.kvstore_server import SnapshotTable
from mxnet_tpu.parallel.elastic import CheckpointManager, \
    ElasticController, elastic_train_loop, publish_peer_snapshot, \
    restore_from_peer


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_RUNS_DIR", str(tmp_path / "runs"))
    for var in ("MXTPU_COMPILE_CACHE_DIR", "MXTPU_PEER_RESTORE",
                "MXTPU_PS_SECRET", "MXTPU_CKPT_ASYNC",
                "MXTPU_CKPT_DELTA"):
        monkeypatch.delenv(var, raising=False)
    CC.reset_stats()
    goodput.reset()
    watchdog.reset()
    faultpoint.reset()
    yield
    faultpoint.reset()
    goodput.reset()
    watchdog.reset()
    CC.reset_stats()


# -- compile cache units ------------------------------------------------------

def _compiled(mul=2.0):
    fn = jax.jit(lambda x: x * mul + 1.0)
    return fn.lower(jnp.arange(4.0)).compile()


class TestCompileCacheUnits:
    def test_disabled_without_env(self):
        """No MXTPU_COMPILE_CACHE_DIR: the cache is inert — no paths,
        no counters, store refuses."""
        assert not CC.enabled()
        assert CC.cache_path(("k",)) is None
        assert CC.load(("k",)) is None
        assert CC.store(("k",), _compiled()) is False
        assert CC.stats() == {"hits": 0, "misses": 0, "stores": 0,
                              "deserialize_errors": 0,
                              "store_errors": 0}

    def test_roundtrip_executable(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR",
                           str(tmp_path / "cc"))
        compiled = _compiled()
        key = ("sig", "avals", "tokens")
        assert CC.store(key, compiled) is True
        assert CC.stats()["stores"] == 1
        loaded = CC.load(key)
        assert loaded is not None
        assert CC.stats()["hits"] == 1
        x = jnp.arange(4.0)
        np.testing.assert_array_equal(np.asarray(loaded(x)),
                                      np.asarray(compiled(x)))

    def test_miss_counts(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR",
                           str(tmp_path / "cc"))
        assert CC.load(("nope",)) is None
        assert CC.stats()["misses"] == 1

    def test_corrupt_entry_never_fatal(self, tmp_path, monkeypatch):
        """A torn/garbage entry is a counted deserialize_error and a
        ``None`` (fresh compile follows) — never an exception."""
        monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR",
                           str(tmp_path / "cc"))
        key = ("sig",)
        assert CC.store(key, _compiled())
        path = CC.cache_path(key)
        with open(path, "wb") as f:
            f.write(b"not a pickled executable")
        assert CC.load(key) is None
        assert CC.stats()["deserialize_errors"] == 1
        assert CC.stats()["hits"] == 0

    def test_key_sensitivity(self, tmp_path, monkeypatch):
        """Different signature keys map to different entries; the same
        key is stable across calls (the on-disk contract the fused
        step's full compile signature relies on)."""
        monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR",
                           str(tmp_path / "cc"))
        a = CC.cache_path(("sig", "a"))
        b = CC.cache_path(("sig", "b"))
        assert a != b
        assert a == CC.cache_path(("sig", "a"))
        assert a.startswith(str(tmp_path / "cc"))
        assert a.endswith(".xc")


def _make_net(seed_from=None):
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, in_units=8, activation="relu"))
        net.add(gluon.nn.Dense(1, in_units=16))
    net.initialize(mx.init.Uniform(0.1))
    net.hybridize()
    if seed_from is not None:
        for (_, p1), (_, p2) in zip(
                sorted(seed_from.collect_params().items()),
                sorted(net.collect_params().items())):
            p2.set_data(p1.data().astype("float32"))
    return net


class TestWarmFusedStep:
    def test_second_trainer_replays_cached_executable_bitwise(
            self, tmp_path, monkeypatch):
        """Cold fused trainer stores its AOT executable; a second,
        identically-seeded trainer's compile step serves it from disk
        (hits counted, nothing re-stored) and trains bitwise-identical
        to the cold run."""
        monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR",
                           str(tmp_path / "cc"))
        rs = np.random.RandomState(0)
        x = mx.nd.array(rs.rand(4, 8).astype("float32"))
        y = mx.nd.array(rs.rand(4, 1).astype("float32"))
        loss_fn = gluon.loss.L2Loss()

        net_a = _make_net()
        net_b = _make_net(seed_from=net_a)   # same init, BEFORE stepping
        tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                             {"learning_rate": 0.1})
        step_a = gluon.train_step(net_a, loss_fn, tr_a)
        for _ in range(3):
            step_a(x, y, batch_size=4)
        assert step_a.last_mode == "fused"
        cold = CC.stats()
        assert cold["stores"] == 1 and cold["hits"] == 0, cold

        tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                             {"learning_rate": 0.1})
        step_b = gluon.train_step(net_b, loss_fn, tr_b)
        for _ in range(3):
            step_b(x, y, batch_size=4)
        assert step_b.last_mode == "fused"
        warm = CC.stats()
        assert warm["hits"] == 1, warm
        assert warm["stores"] == 1, warm   # cache hit is never re-stored
        for (_, pa), (_, pb) in zip(
                sorted(net_a.collect_params().items()),
                sorted(net_b.collect_params().items())):
            assert np.array_equal(pa.data().asnumpy(),
                                  pb.data().asnumpy())


# -- snapshot plane -----------------------------------------------------------

class TestSnapshotTable:
    def test_newest_step_wins_and_requester_excluded(self):
        t = SnapshotTable()
        t.put(0, 5, b"r0s5")
        t.put(1, 3, b"r1s3")
        assert t.get_newest(2, {}, 0) == (0, 5, b"r0s5")
        assert t.get_newest(0, {}, 0) == (1, 3, b"r1s3")
        t.put(1, 9, b"r1s9")             # replace: one slot per rank
        assert len(t) == 2
        assert t.get_newest(2, {}, 0) == (1, 9, b"r1s9")

    def test_heartbeat_liveness_filter(self):
        """A publisher with a stale (or absent) heartbeat is skipped —
        its snapshot may predate the failure being recovered from;
        stale_timeout <= 0 disables the filter."""
        t = SnapshotTable()
        t.put(1, 7, b"blob")
        now = time.monotonic()
        assert t.get_newest(0, {}, 3.0) is None           # no heartbeat
        assert t.get_newest(0, {1: now - 60.0}, 3.0) is None   # stale
        assert t.get_newest(0, {1: now}, 3.0) == (1, 7, b"blob")
        assert t.get_newest(0, {}, 0) == (1, 7, b"blob")  # filter off

    def test_drop(self):
        t = SnapshotTable()
        t.put(1, 7, b"blob")
        t.drop(1)
        assert len(t) == 0
        assert t.get_newest(0, {}, 0) is None


class TestSnapshotWire:
    def test_put_get_roundtrip_with_liveness(self):
        """Opcodes 18/19 end to end: a published snapshot is served to
        a different rank only while the publisher's heartbeat is fresh
        (the server-side filter over the real wire); the requester's
        own slot never comes back."""
        srv = KA.AsyncPSServer()
        try:
            cli0 = KA.AsyncPSClient("127.0.0.1", srv.port)
            cli1 = KA.AsyncPSClient("127.0.0.1", srv.port)
            assert cli0.get_snapshot(0, stale_timeout=0) is None
            cli1.put_snapshot(1, 7, b"\x00payload\xff")
            # no heartbeat from rank 1 yet: default liveness filter
            # (MXTPU_PS_DEAD_TIMEOUT) must hold the snapshot back
            assert cli0.get_snapshot(0) is None
            cli1.heartbeat(1)
            assert cli0.get_snapshot(0) == (1, 7, b"\x00payload\xff")
            assert cli0.get_snapshot(0, stale_timeout=0) == \
                (1, 7, b"\x00payload\xff")
            # requester exclusion: rank 1 asking only sees OTHER ranks
            assert cli1.get_snapshot(1, stale_timeout=0) is None
        finally:
            srv.stop()


# -- restore_from_peer fallbacks ---------------------------------------------

class _CaptureKV:
    """publish_snapshot/peer_snapshot facade over an in-memory slot —
    the client-side crypto path without a server."""

    def __init__(self):
        self.slot = None

    def publish_snapshot(self, step, blob):
        self.slot = (1, int(step), bytes(blob))

    def peer_snapshot(self, stale_timeout=None):
        return self.slot


def _fallbacks():
    return profiler.elastic_stats().get("peer_restore_fallbacks", 0)


class TestRestoreFromPeer:
    def test_roundtrip_and_counters(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PS_SECRET", "s3cret")
        kv = _CaptureKV()
        state = {"w": jnp.asarray([1.0, 2.0]), "n": jnp.asarray(3.0)}
        before = profiler.elastic_stats().get("peer_restores", 0)
        assert publish_peer_snapshot(kv, 5, state) is True
        got = restore_from_peer(kv)
        assert got is not None
        host, step = got
        assert step == 5
        np.testing.assert_array_equal(np.asarray(host["w"]),
                                      [1.0, 2.0])
        assert profiler.elastic_stats()["peer_restores"] == before + 1

    def test_no_secret_is_off(self):
        """Without MXTPU_PS_SECRET neither side participates: publish
        refuses (an unauthenticated blob must never go out) and restore
        skips straight to the filesystem."""
        kv = _CaptureKV()
        assert publish_peer_snapshot(kv, 1, {"w": jnp.asarray(1.0)}) \
            is False
        kv.slot = (1, 1, b"x" * 64)
        assert restore_from_peer(kv) is None

    def test_kv_without_snapshot_plane(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PS_SECRET", "s3cret")
        assert restore_from_peer(object()) is None

    def test_transport_error_falls_back(self, monkeypatch):
        """The v0-interop shape: an old server answers the unknown
        opcode with _RE_ERR, which the client surfaces as RuntimeError
        — counted as a 'transport' fallback, never raised."""
        monkeypatch.setenv("MXTPU_PS_SECRET", "s3cret")

        class _V0KV:
            def peer_snapshot(self, stale_timeout=None):
                raise RuntimeError("server error")

        before = _fallbacks()
        assert restore_from_peer(_V0KV()) is None
        assert _fallbacks() == before + 1

    def test_no_snapshot_falls_back(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PS_SECRET", "s3cret")
        before = _fallbacks()
        assert restore_from_peer(_CaptureKV()) is None
        assert _fallbacks() == before + 1

    def test_hmac_mismatch_never_unpickles(self, monkeypatch):
        """A tampered blob fails MAC verification BEFORE pickle.loads
        — the poisoned payload is never deserialized."""
        monkeypatch.setenv("MXTPU_PS_SECRET", "s3cret")
        kv = _CaptureKV()
        assert publish_peer_snapshot(kv, 2, {"w": jnp.asarray(1.0)})
        rank, step, blob = kv.slot
        kv.slot = (rank, step, blob[:32] + b"\x00" + blob[33:])

        def _boom(*a, **k):              # pragma: no cover
            raise AssertionError("pickle.loads reached on bad MAC")

        monkeypatch.setattr(pickle, "loads", _boom)
        before = _fallbacks()
        assert restore_from_peer(kv) is None
        assert _fallbacks() == before + 1

    def test_torn_body_counts_decode(self, monkeypatch):
        """A correctly-MACed but unpicklable body (torn writer) is a
        counted 'decode' fallback."""
        monkeypatch.setenv("MXTPU_PS_SECRET", "s3cret")
        body = b"this is not a pickle"
        mac = _hmac.new(b"s3cret", body, hashlib.sha256).digest()
        kv = _CaptureKV()
        kv.slot = (1, 4, mac + body)
        before = _fallbacks()
        assert restore_from_peer(kv) is None
        assert _fallbacks() == before + 1


# -- elastic-loop e2e: dead rank resumes from its live peer ------------------

class _FakeKV:
    def __init__(self, nworkers=2):
        self.dead = []
        self.num_workers = nworkers

    def dead_nodes(self, timeout=3.0):
        return list(self.dead)

    def resize(self, n):
        self.num_workers = int(n)


class _PeerKV(_FakeKV):
    """Dead-table fake whose snapshot plane is the REAL v1 wire."""

    def __init__(self, client, rank, nworkers=2):
        _FakeKV.__init__(self, nworkers)
        self._client = client
        self._rank = int(rank)

    def publish_snapshot(self, step, blob):
        self._client.put_snapshot(self._rank, step, blob)

    def peer_snapshot(self, stale_timeout=None):
        return self._client.get_snapshot(self._rank, stale_timeout)


def test_loop_restores_from_peer_with_zero_replay(tmp_path,
                                                  monkeypatch):
    """Rank 0 dies at batch 5 with checkpoints only at 0 and 3; its
    DP-identical peer published every step, so recovery restores step 4
    over the wire — recovery_kind 'peer', replay_span 0 — and the final
    state is bitwise-identical to an unfaulted twin."""
    monkeypatch.setenv("MXTPU_PS_SECRET", "zb-test-secret")
    monkeypatch.setenv("MXTPU_PEER_RESTORE", "1")
    batches = [jnp.asarray(float(i)) for i in range(8)]

    def base_step(state, b):
        return {"acc": state["acc"] + b}, None

    # unfaulted twin for the bitwise target
    twin_state, _, done = elastic_train_loop(
        base_step, {"acc": jnp.asarray(0.0)}, batches,
        CheckpointManager(str(tmp_path / "ck_twin"), use_orbax=False),
        save_every=3, max_failures=0,
        controller=ElasticController(kvstore=_FakeKV(),
                                     world=range(2), rank=0,
                                     poll_interval=0.0))
    assert done

    goodput.reset()
    watchdog.reset()
    srv = KA.AsyncPSServer()
    try:
        cli0 = KA.AsyncPSClient("127.0.0.1", srv.port)
        cli1 = KA.AsyncPSClient("127.0.0.1", srv.port)
        peer = _PeerKV(cli1, rank=1)
        kv = _PeerKV(cli0, rank=0)
        fired = []

        def step(state, b):
            i = int(b)
            if i == 5 and not fired:
                fired.append(1)
                kv.dead = [1]
                raise ConnectionError("collective failed: peer gone")
            ns, met = base_step(state, b)
            # the DP-identical peer: same post-step state in its own
            # slot, heartbeat fresh so the liveness filter serves it
            cli1.heartbeat(1)
            publish_peer_snapshot(peer, i, ns)
            return ns, met

        state, _, done = elastic_train_loop(
            step, {"acc": jnp.asarray(0.0)}, batches,
            CheckpointManager(str(tmp_path / "ck"), use_orbax=False),
            save_every=3, max_failures=0,
            controller=ElasticController(kvstore=kv, world=range(2),
                                         rank=0, poll_interval=0.0))
    finally:
        srv.stop()
    assert done
    m = goodput.last_manifest()
    rec = [e for e in m["events"] if e["kind"] == "recovery"][-1]
    assert rec["recovery_kind"] == "peer"
    assert rec["restored_step"] == 4
    assert rec["replay_span"] == 0
    assert m["counters"]["peer_restores"] == 1
    assert float(state["acc"]) == float(twin_state["acc"])
