"""The 3D-parallel GSPMD fused step (ISSUE 16 tentpole).

A mesh with model axes (tp/sp > 1), or explicit partition rules, turns
``FusedTrainStep`` into ONE GSPMD program: ``jax.jit`` with the params
placed by regex partition rules and the step's ``out_shardings`` pinned
to its ``in_shardings`` (SNIPPETS [1] matched-shardings contract — step
N's donated outputs feed step N+1 with zero resharding). The dp-only
``shard_map`` treatment is untouched.

Parity contract: the SAME mesh config replays bitwise (asserted); a
DIFFERENT topology splits contractions at different points, so cross-
topology agreement is reduction-order-limited (~1 ULP/step) and pinned
with a tight allclose, not equality.
"""
import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import fused_step as fs
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import create_mesh
from mxnet_tpu.parallel.compat import PartitionSpec as P

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")


def _net(seed=0):
    rs = np.random.RandomState(seed)
    w1 = rs.randn(16, 12).astype(np.float32) * 0.1
    b1 = np.zeros(16, np.float32)
    w2 = rs.randn(4, 16).astype(np.float32) * 0.1
    b2 = np.zeros(4, np.float32)
    net = nn.HybridSequential()
    # explicit prefixes: rule tests regex-match on the param names, and
    # the auto-generated denseN_ counter depends on how many Dense
    # layers earlier tests created in this process
    net.add(nn.Dense(16, activation="relu", in_units=12, prefix="d0_"))
    net.add(nn.Dense(4, in_units=16, prefix="d1_"))
    net.initialize()
    net.hybridize()
    params = [p for _, p in sorted(net.collect_params().items())]
    vals = [b1, w1, b2, w2] if params[0].shape == (16,) \
        else [w1, b1, w2, b2]
    for p, v in zip(params, vals):
        assert p.shape == v.shape
        p.set_data(mx.nd.array(v))
    return net


def _train(mesh, steps=5, rules=None, seed=0):
    net = _net(seed)
    loss = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    step = tr.fuse_step(lambda xx, yy: loss(net(xx), yy),
                        mesh=mesh, bucket_bytes=512, rules=rules)
    rs = np.random.RandomState(7)
    losses = []
    for _ in range(steps):
        x = mx.nd.array(rs.rand(8, 12).astype(np.float32))
        y = mx.nd.array(rs.rand(8, 4).astype(np.float32))
        losses.append(float(step(x, y, batch_size=8).asnumpy().mean()))
    params = [p.data().asnumpy()
              for _, p in sorted(net.collect_params().items())]
    return losses, params, step


class TestGspmdParity:
    def test_mode_selection(self):
        _, _, s_dp = _train(create_mesh(devices=jax.devices()[:4]),
                            steps=1)
        assert s_dp._gspmd_mode() is False       # dp-only: legacy path
        _, _, s_3d = _train(create_mesh(dp=2, tp=2, sp=2), steps=1)
        assert s_3d._gspmd_mode() is True        # model axes: GSPMD

    def test_five_step_parity_across_topologies(self):
        """Final params after 5 fused steps: single-device vs dp-only
        vs dp×tp×sp agree to reduction-order (~1 ULP/step); the SAME
        3D config replays BITWISE."""
        l0, p0, _ = _train(None)
        l1, p1, _ = _train(create_mesh(devices=jax.devices()[:4]))
        l2, p2, s2 = _train(create_mesh(dp=2, tp=2, sp=2))
        _, p2b, _ = _train(create_mesh(dp=2, tp=2, sp=2))
        assert s2.last_mode == "fused"
        for a, b in zip(p2, p2b):                # determinism: bitwise
            np.testing.assert_array_equal(a, b)
        np.testing.assert_allclose(l0, l1, rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(l0, l2, rtol=1e-6, atol=1e-8)
        for a, b in zip(p0, p1):
            np.testing.assert_allclose(a, b, rtol=5e-6, atol=5e-8)
        for a, b in zip(p0, p2):
            np.testing.assert_allclose(a, b, rtol=5e-6, atol=5e-8)

    def test_matched_step_shardings_zero_resharding(self):
        """The compiled program's weight/optimizer-state OUTPUT
        shardings equal its INPUT shardings — step N feeds step N+1
        without a resharding transfer."""
        _, _, step = _train(create_mesh(dp=2, tp=2, sp=2))
        compiled, hlo = step.last_program()
        assert compiled is not None and hlo is not None
        assert step.matched_step_shardings() is True

    def test_gspmd_wire_bytes_within_1pct_of_analytic(self):
        """HLO-measured all-reduce payload of the dp×tp×sp MLP step ==
        4 bytes * trainable params (replicated params, dp-sharded
        batch: ONE gradient reduction) within 1%."""
        from benchmark import comm_model as cm
        _, _, step = _train(create_mesh(dp=2, tp=2, sp=2))
        _, hlo = step.last_program()
        by, counts, unresolved = cm.hlo_collective_bytes(hlo)
        assert unresolved == 0
        n_params = 16 * 12 + 16 + 4 * 16 + 4
        analytic = 4 * n_params
        got = by["all-reduce"]
        assert abs(got - analytic) / analytic < 0.01, (got, analytic)
        assert by["collective-permute"] == 0
        assert by["all-to-all"] == 0


class TestExplicitRules:
    def test_tp_rules_shard_params_and_still_train(self):
        """Explicit regex rules actually shard the weights over 'tp'
        in the COMPILED program (not just in metadata), the matched-
        shardings contract holds for genuinely distributed state, and
        training matches the replicated run."""
        mesh = create_mesh(dp=2, tp=2, sp=2)
        rules = [
            (r"d0.*weight$", ("tp", None)),       # column-parallel
            (r"d1.*weight$", (None, "tp")),       # row-parallel
            (r"d0.*bias$", ("tp",)),
        ]
        l0, p0, _ = _train(None)
        l1, p1, step = _train(mesh, rules=rules)
        assert step.last_mode == "fused"
        assert step.matched_step_shardings() is True
        compiled, _ = step.last_program()
        in_specs = [getattr(s, "spec", None) for s in
                    jax.tree_util.tree_leaves(
                        compiled.input_shardings[0][0])]
        assert any(sp is not None and any(ax is not None for ax in sp)
                   for sp in in_specs), in_specs  # something IS sharded
        np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-7)
        for a, b in zip(p0, p1):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


class TestFallbacksAndKnobs:
    def test_mesh_fallback_counter_warn_and_marker(self):
        mesh = create_mesh(dp=2, tp=2, sp=2)
        net = _net(3)
        loss = gluon.loss.L2Loss()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05})
        step = tr.fuse_step(lambda xx, yy: loss(net(xx), yy), mesh=mesh)
        rs = np.random.RandomState(3)
        x = mx.nd.array(rs.rand(7, 12).astype(np.float32))  # 7 % 2 != 0
        y = mx.nd.array(rs.rand(7, 4).astype(np.float32))
        before = fs.stats()["mesh_fallbacks"]
        from mxnet_tpu._debug import flightrec
        ring_before = sum(1 for ev in flightrec.snapshot()
                          if ev[1] == "fused_step.mesh_fallback")
        with pytest.warns(UserWarning, match="does not divide mesh"):
            step(x, y, batch_size=7)
        step(x, y, batch_size=7)                 # second demotion
        assert fs.stats()["mesh_fallbacks"] == before + 2
        assert step.last_mode == "fallback:mesh-batch-indivisible"
        ring_after = sum(1 for ev in flightrec.snapshot()
                         if ev[1] == "fused_step.mesh_fallback")
        assert ring_after == ring_before + 2     # marker per occurrence
        # ... but the warning fired ONCE (checked implicitly: a second
        # pytest.warns here would hang on no-warning; assert the flag)
        assert step._warned_mesh_indivisible is True

    def test_gspmd_escape_hatch_env(self, monkeypatch):
        """MXTPU_GSPMD_STEP=0 (a compile-signature token) forces the
        legacy dp-only treatment on a 3D mesh."""
        monkeypatch.setenv("MXTPU_GSPMD_STEP", "0")
        l2, p2, step = _train(create_mesh(dp=2, tp=2, sp=2))
        assert step._gspmd_mode() is False
        assert step.last_mode == "fused"         # still fuses (manual dp)
        l0, p0, _ = _train(None)
        np.testing.assert_allclose(l0, l2, rtol=1e-6, atol=1e-8)

    def test_loss_fn_mesh_weld(self):
        """A loss callable declaring a ``mesh`` kwarg receives the
        step's mesh — the Trainer/loss weld that lets
        parallel.transformer.loss_fn auto-select the single-reduction
        chunked CE without a side channel."""
        mesh = create_mesh(dp=2, tp=2, sp=2)
        seen = []
        l2 = gluon.loss.L2Loss()

        def lf(xx, yy, mesh=None):
            seen.append(mesh)
            return l2(xx, yy)

        net = _net(1)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05})
        step = tr.fuse_step(lambda xx, yy: lf(net(xx), yy, mesh=None),
                            mesh=mesh)
        # the weld binds on the OUTER loss callable handed to fuse_step
        step2 = tr.fuse_step(lf, mesh=mesh)
        rs = np.random.RandomState(1)
        x = mx.nd.array(rs.rand(8, 4).astype(np.float32))
        y = mx.nd.array(rs.rand(8, 4).astype(np.float32))
        step2(x, y, batch_size=8)
        assert seen and all(m is mesh for m in seen)


class TestCeLocalAccumSelect:
    def _cfg(self, **kw):
        from mxnet_tpu.parallel import transformer as T
        base = dict(vocab_size=64, dim=16, n_layers=2, n_heads=4,
                    ffn_hidden=32, loss_chunks=4)
        base.update(kw)
        return T.TransformerConfig(**base)

    def test_auto_matrix(self):
        from mxnet_tpu.parallel import transformer as T
        mesh3d = create_mesh(dp=2, tp=2, sp=2)
        tp_only = create_mesh(tp=8)
        cfg = self._cfg()
        # dp*sp > 1, shapes divide -> auto ON
        assert T.ce_local_accum_active(cfg, mesh3d, 8, 64) is True
        # no mesh / no chunking -> OFF
        assert T.ce_local_accum_active(cfg, None, 8, 64) is False
        assert T.ce_local_accum_active(
            self._cfg(loss_chunks=1), mesh3d, 8, 64) is False
        # batch not sharded (dp*sp == 1) -> nothing to save
        assert T.ce_local_accum_active(cfg, tp_only, 8, 64) is False
        # explicit False pins the plain path
        assert T.ce_local_accum_active(
            self._cfg(ce_local_accum=False), mesh3d, 8, 64) is False

    def test_env_override_and_indivisible_warns_once(self, monkeypatch):
        from mxnet_tpu.parallel import transformer as T
        mesh3d = create_mesh(dp=2, tp=2, sp=2)
        cfg = self._cfg()
        monkeypatch.setenv("MXTPU_CE_LOCAL_ACCUM", "0")
        assert T.ce_local_accum_active(cfg, mesh3d, 8, 64) is False
        monkeypatch.setenv("MXTPU_CE_LOCAL_ACCUM", "auto")
        # indivisible shapes decline with a warn-once, never a crash
        T._WARNED.discard("ce-local-accum-indivisible")
        with pytest.warns(RuntimeWarning, match="auto-select declined"):
            assert T.ce_local_accum_active(cfg, mesh3d, 7, 64) is False
        assert T.ce_local_accum_active(cfg, mesh3d, 7, 64) is False

    def test_env_is_signature_token(self):
        from mxnet_tpu.ndarray import register as reg
        names = [n for n, _ in reg._SIG_TOKENS]
        assert "MXTPU_CE_LOCAL_ACCUM" in names
        assert "MXTPU_GSPMD_STEP" in names
        # ... and flipping one changes the token tuple (recompile key)
        before = reg.signature_tokens()
        import os
        os.environ["MXTPU_GSPMD_STEP"] = "0"
        try:
            assert reg.signature_tokens() != before
        finally:
            os.environ.pop("MXTPU_GSPMD_STEP", None)
