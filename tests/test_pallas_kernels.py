"""The PR 9 Pallas kernel campaign (pallas_kernels/batchnorm_fused.py,
optimizer_apply.py, quantized_matmul.py) in interpreter mode on CPU.

Parity contracts under test (same as BENCH_MODEL=fused_kernels):
- fused BatchNorm: bitwise-equal stats AND output vs its reference
  (the deterministic tree/exact-product design makes even the
  normalize chain reproducible across fusion contexts and tilings),
  custom_vjp grads vs reference autodiff, fits-guard fallback, and the
  gluon.nn.BatchNorm moving-stats round-trip through save/load.
- packed optimizer apply: BITWISE-equal to the per-parameter step_fn
  chain inside one jit for SGD/momentum/Adam, on both the flat jnp
  path and the interpret-mode kernel; the fused train step produces
  bit-identical parameters with MXTPU_FUSED_APPLY=0/1/interpret.
- quantized matmul: int32 accumulator exactly equal to the XLA dot
  (integer math is exact), f32 scaled epilogue within 1 ULP, and the
  ops/quantized.py wiring (FC + 1x1 conv) bitwise across paths.
The real-TPU speedup half of the contract lives in bench.py
(BENCH_MODEL=fused_kernels, >=1.5x where a real backend is present).
"""
import importlib

import numpy as np
import jax
import jax.numpy as jnp
import pytest

BN = importlib.import_module("mxnet_tpu.pallas_kernels.batchnorm_fused")
OA = importlib.import_module("mxnet_tpu.pallas_kernels.optimizer_apply")
QM = importlib.import_module("mxnet_tpu.pallas_kernels.quantized_matmul")


def _bn_mats(N, H, W, C, dtype="float32", seed=0):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(N, H, W, C).astype("float32") * 2 + 1) \
        .astype(dtype)
    g = jnp.asarray(rs.rand(C).astype("float32") + 0.5)
    b = jnp.asarray(rs.randn(C).astype("float32"))
    return x, g, b


def _eq(a, b):
    return bool(jnp.array_equal(jnp.asarray(a), jnp.asarray(b),
                                equal_nan=True))


# ---------------------------------------------------------------------------
# deterministic reduction primitives
# ---------------------------------------------------------------------------

class TestDeterministicReduction:
    def test_tree_fold_jit_eager_bitwise(self):
        """The whole point of the fold: the same bits from any
        compilation context."""
        rs = np.random.RandomState(1)
        v = jnp.asarray(rs.randn(333, 24).astype("float32"))
        assert _eq(BN.tree_fold_rows(v),
                   jax.jit(BN.tree_fold_rows)(v))

    def test_tree_fold_is_the_sum(self):
        rs = np.random.RandomState(2)
        v = jnp.asarray(rs.randn(100, 8).astype("float32"))
        np.testing.assert_allclose(
            np.asarray(BN.tree_fold_rows(v)[0]),
            np.asarray(v).sum(0), rtol=1e-6)

    def test_tile_decomposition_matches_full_tree(self):
        """fold_partials(concat(per-tile fold_blocks)) == full tree for
        any FOLD_BLOCK-aligned tiling — the property that makes the
        stats kernel's tiled partials bitwise-equal to the
        reference."""
        rs = np.random.RandomState(3)
        v = jnp.asarray(rs.randn(256, 16).astype("float32"))
        full = BN.tree_fold_rows(v)
        for tr in (64, 128):
            parts = jnp.concatenate(
                [BN.fold_blocks(v[i:i + tr])
                 for i in range(0, 256, tr)], axis=0)
            assert _eq(BN.fold_partials(parts), full), tr

    def test_exact_sq_and_mul(self):
        rs = np.random.RandomState(4)
        x = jnp.asarray(rs.randn(1000).astype("float32") * 100)
        y = jnp.asarray(rs.randn(1000).astype("float32"))
        np.testing.assert_allclose(np.asarray(BN.exact_sq(x)),
                                   np.asarray(x) ** 2, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(BN.exact_mul(x, y)),
                                   np.asarray(x) * np.asarray(y),
                                   rtol=1e-6)
        # context-independence: jit == eager bitwise
        assert _eq(BN.exact_sq(x), jax.jit(BN.exact_sq)(x))
        assert _eq(BN.exact_mul(x, y), jax.jit(BN.exact_mul)(x, y))
        # non-finite mirror plain multiply
        sp = jnp.asarray(np.array([np.inf, -np.inf, np.nan, 0.0],
                                  "float32"))
        assert _eq(BN.exact_sq(sp), sp * sp)


# ---------------------------------------------------------------------------
# fused BatchNorm
# ---------------------------------------------------------------------------

class TestBatchNormFused:
    @pytest.mark.parametrize("shape", [(4, 6, 6, 16), (2, 8, 8, 32)])
    @pytest.mark.parametrize("act", [None, "relu"])
    def test_forward_bitwise_vs_reference(self, shape, act):
        x, g, b = _bn_mats(*shape)
        k = jax.jit(lambda *a: BN.fused_batch_norm(
            *a, act=act, interpret=True))(x, g, b)
        r = jax.jit(lambda *a: BN.batchnorm_reference(*a, act=act))(
            x, g, b)
        for a, c in zip(k, r):
            assert _eq(a, c)

    def test_multi_tile_matches_reference(self, monkeypatch):
        """Force a 4-row-tile x 2-channel-tile grid: the per-tile
        partials must reassemble into the exact reference tree."""
        monkeypatch.setattr(BN, "_tiles",
                            lambda R, C, xb, nb: (64, 16, True))
        x, g, b = _bn_mats(4, 8, 8, 32)  # R=256 -> 4 row tiles
        k = BN.fused_batch_norm(x, g, b, interpret=True)
        r = BN.batchnorm_reference(x, g, b)
        for a, c in zip(k, r):
            assert _eq(a, c)

    def test_bf16_stats_in_f32(self):
        x, g, b = _bn_mats(2, 4, 4, 16, dtype="bfloat16")
        out, mean, var = BN.fused_batch_norm(x, g, b, interpret=True)
        assert out.dtype == jnp.bfloat16
        assert mean.dtype == jnp.float32 and var.dtype == jnp.float32
        _, rm, rv = BN.batchnorm_reference(x, g, b)
        assert _eq(mean, rm) and _eq(var, rv)

    def test_gradients_match_reference(self):
        # act="relu" covers the mask recomputation ON TOP of the base
        # backward; the shape matches test_forward so the interpret
        # kernels compile once per suite run
        act = "relu"
        x, g, b = _bn_mats(4, 6, 6, 16, seed=7)

        def lk(x, g, b):
            return jnp.sum(BN.fused_batch_norm(
                x, g, b, act=act, interpret=True)[0] ** 2)

        def lr(x, g, b):
            return jnp.sum(BN.batchnorm_reference(x, g, b, act=act)[0]
                           ** 2)

        gk = jax.grad(lk, argnums=(0, 1, 2))(x, g, b)
        gr = jax.grad(lr, argnums=(0, 1, 2))(x, g, b)
        for a, c in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       atol=2e-4, rtol=2e-4)

    def test_stat_output_cotangents(self):
        """Differentiating through the mean/var OUTPUTS must match the
        reference autodiff (the custom_vjp adds the d mean/dx and
        d var/dx terms explicitly)."""
        x, g, b = _bn_mats(4, 6, 6, 16, seed=9)

        def lk(x):
            o, m, v = BN.fused_batch_norm(x, g, b, interpret=True)
            return jnp.sum(m * 3.0) + jnp.sum(v * 0.5)

        def lr(x):
            o, m, v = BN.batchnorm_reference(x, g, b)
            return jnp.sum(m * 3.0) + jnp.sum(v * 0.5)

        np.testing.assert_allclose(np.asarray(jax.grad(lk)(x)),
                                   np.asarray(jax.grad(lr)(x)),
                                   atol=1e-5, rtol=1e-5)

    def test_fits_guard_falls_back_to_reference(self, monkeypatch):
        """An unfittable plan must take batchnorm_reference instead of
        dying at Mosaic compile time (conv_fused contract)."""
        called = []
        real = BN.batchnorm_reference
        monkeypatch.setattr(BN, "_use_pallas", lambda *a, **k: True)
        monkeypatch.setattr(BN, "_fwd_fits", lambda x2: False)
        monkeypatch.setattr(
            BN, "batchnorm_reference",
            lambda *a, **k: called.append(1) or real(*a, **k))
        x, g, b = _bn_mats(2, 4, 4, 8)
        out = BN.fused_batch_norm(x, g, b)
        assert called, "unfittable plan did not fall back"
        np.testing.assert_array_equal(np.asarray(out[0]),
                                      np.asarray(real(x, g, b)[0]))

    def test_engaged_gates(self, monkeypatch):
        x, g, b = _bn_mats(2, 4, 4, 8)
        monkeypatch.setenv("MXTPU_FUSED_BN", "0")
        assert not BN.engaged(x, 3)
        monkeypatch.setenv("MXTPU_FUSED_BN", "interpret")
        assert BN.engaged(x, 3)
        assert not BN.engaged(x, 1)  # channels not last

    def test_shape_validation(self):
        x, g, b = _bn_mats(2, 4, 4, 8)
        with pytest.raises(ValueError):
            BN.fused_batch_norm(x, g[:4], b, interpret=True)
        with pytest.raises(ValueError):
            BN.fused_batch_norm(x, g, b, act="gelu", interpret=True)


class TestBatchNormGluon:
    """ops/nn.py wiring + gluon.nn.BatchNorm semantics with the kernel
    engaged via the MXTPU_FUSED_BN=interpret CPU hook."""

    def _train(self, monkeypatch, tmp_path, mode, steps=2):
        import mxnet_tpu as mx
        from mxnet_tpu import autograd, gluon
        monkeypatch.setenv("MXTPU_FUSED_BN", mode)
        np.random.seed(0)
        mx.random.seed(0)
        net = gluon.nn.BatchNorm(axis=1, in_channels=16, momentum=0.8)
        net.initialize()
        rs = np.random.RandomState(1)
        for i in range(steps):
            x = mx.nd.array(rs.rand(32, 16).astype("float32") + i)
            with autograd.record():
                y = net(x)
            y.backward()
        return net, y

    def test_moving_stats_roundtrip_save_load(self, monkeypatch,
                                              tmp_path):
        import mxnet_tpu as mx
        from mxnet_tpu import autograd, gluon
        net, _ = self._train(monkeypatch, tmp_path, "interpret")
        rm = net.running_mean.data().asnumpy()
        rv = net.running_var.data().asnumpy()
        assert not np.allclose(rm, 0.0)  # stats actually moved
        path = str(tmp_path / "bn.params")
        net.save_parameters(path)
        net2 = gluon.nn.BatchNorm(axis=1, in_channels=16, momentum=0.8)
        net2.load_parameters(path)
        np.testing.assert_array_equal(
            rm, net2.running_mean.data().asnumpy())
        np.testing.assert_array_equal(
            rv, net2.running_var.data().asnumpy())
        # inference after reload uses the restored moving stats
        x = mx.nd.array(np.random.RandomState(5).rand(8, 16)
                        .astype("float32"))
        with autograd.pause():
            y1 = net(x).asnumpy()
            y2 = net2(x).asnumpy()
        np.testing.assert_array_equal(y1, y2)

    def test_kernel_vs_fallback_stats_agree(self, monkeypatch,
                                            tmp_path):
        """Running stats through the kernel path track the fallback's
        within f32 stat noise (different variance pass structure:
        single- vs two-pass)."""
        net_k, yk = self._train(monkeypatch, tmp_path, "interpret")
        net_f, yf = self._train(monkeypatch, tmp_path, "0")
        np.testing.assert_allclose(
            net_k.running_mean.data().asnumpy(),
            net_f.running_mean.data().asnumpy(), atol=1e-6)
        np.testing.assert_allclose(
            net_k.running_var.data().asnumpy(),
            net_f.running_var.data().asnumpy(), atol=1e-5)
        # outputs amplify the single- vs two-pass var gap through
        # 1/sqrt; f32-noise-level agreement, not bitwise
        np.testing.assert_allclose(yk.asnumpy(), yf.asnumpy(),
                                   atol=1e-4, rtol=1e-5)

    def test_env_flip_invalidates_dispatch_cache(self, monkeypatch):
        """MXTPU_FUSED_BN is part of the imperative dispatch-cache key
        (register._kernel_env_token): flipping it mid-process on an
        already-hot signature must retrace onto the other path, never
        silently replay the cached program."""
        import mxnet_tpu as mx
        from mxnet_tpu.ops import nn as opsnn
        monkeypatch.setenv("MXTPU_FUSED_BN", "interpret")
        rs = np.random.RandomState(0)
        args = [mx.nd.array(a) for a in (
            rs.rand(16, 24).astype("float32"), rs.rand(24),
            rs.rand(24), rs.rand(24), rs.rand(24) + 0.5)]
        # training-mode call: the path the env var actually routes
        kw = dict(eps=1e-3, fix_gamma=False, axis=1, _training=True)
        for _ in range(3):  # past the compile-on-repeat threshold
            out_k = mx.nd.BatchNorm(*args, **kw)[0].asnumpy()
        calls = []
        orig = opsnn.batch_moments
        monkeypatch.setattr(
            opsnn, "batch_moments",
            lambda *a, **k: calls.append(1) or orig(*a, **k))
        mx.nd.BatchNorm(*args, **kw)[0].asnumpy()
        assert not calls  # cache hit: no retrace on the hot signature
        monkeypatch.setenv("MXTPU_FUSED_BN", "0")
        out_f = mx.nd.BatchNorm(*args, **kw)[0].asnumpy()
        assert calls, "env flip did not retrace — cached kernel " \
            "program silently replayed"
        np.testing.assert_allclose(out_k, out_f, atol=1e-4, rtol=1e-5)

    def test_use_global_stats_keeps_fallback(self, monkeypatch):
        """Inference / use_global_stats never routes to the kernel
        (its contract is training-mode batch stats)."""
        from mxnet_tpu.ops import nn as opsnn
        monkeypatch.setenv("MXTPU_FUSED_BN", "interpret")
        called = []
        orig = BN.fused_batch_norm
        monkeypatch.setattr(BN, "fused_batch_norm",
                            lambda *a, **k: called.append(1) or
                            orig(*a, **k))
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.rand(8, 16).astype("float32"))
        g = jnp.asarray(rs.rand(16).astype("float32"))
        b = jnp.asarray(rs.rand(16).astype("float32"))
        mm = jnp.asarray(rs.rand(16).astype("float32"))
        mv = jnp.asarray(rs.rand(16).astype("float32") + 0.5)
        opsnn.batch_norm(x, g, b, mm, mv, axis=1, _training=False)
        opsnn.batch_norm(x, g, b, mm, mv, axis=1,
                         use_global_stats=True, _training=True)
        assert not called
        opsnn.batch_norm(x, g, b, mm, mv, axis=1, _training=True)
        assert called


class TestBatchNormFallbackNumerics:
    """The PR 9 satellite: the XLA-fallback batch_norm computes stats
    in f32 (never rounded to the input dtype before the inverse) and
    the whole op is bitwise-deterministic across compilation contexts
    — the properties behind dropping the per-op ULP budget from the
    11,482 BENCH_r05 measured to 64."""

    def test_output_bitwise_across_contexts(self):
        """jit vs eager == 0 ULP: reduction order is pinned by the
        tree and FMA contraction is neutralized by exact products, so
        no fusion context can move a single output bit — the
        regression guard for the 11,482-ULP class of drift."""
        from mxnet_tpu.ops.nn import batch_norm
        rs = np.random.RandomState(0)
        args = [jnp.asarray(a) for a in (
            rs.rand(8, 16, 8, 8).astype("float32"), rs.rand(16),
            rs.rand(16), rs.rand(16), rs.rand(16) + 0.5)]
        args = [a.astype(jnp.float32) for a in args]
        for kw in (dict(_training=True), dict(_training=False),
                   dict(_training=True, use_global_stats=True)):
            kw = dict(eps=1e-3, fix_gamma=False, axis=1, **kw)
            e = batch_norm(*args, **kw)
            j = jax.jit(lambda *a: batch_norm(*a, **kw))(*args)
            for a, c in zip(e, j):
                assert _eq(a, c), kw

    def test_half_precision_stats_accumulate_in_f32(self):
        """bf16 input: batch_moments' f32 stats land within f32 noise
        of the f64 truth — rounding them through bf16 (the old
        input-dtype accumulation bug) would be ~2^8 times coarser."""
        from mxnet_tpu.ops.nn import batch_moments
        rs = np.random.RandomState(3)
        x64 = rs.rand(64, 24).astype(np.float64) * 2 + 3
        x = jnp.asarray(x64.astype("float32")).astype(jnp.bfloat16)
        x64 = np.asarray(x, np.float64)  # the values the op really saw
        m32, v32 = batch_moments(x, (0,), axis=1, fp32_out=True)
        assert m32.dtype == jnp.float32 and v32.dtype == jnp.float32
        m_true = x64.mean(0)
        v_true = ((x64 - m_true) ** 2).mean(0)
        # f32-level agreement (~1e-7 rel); bf16-rounded stats would be
        # off by ~1e-2 rel on these magnitudes
        np.testing.assert_allclose(np.asarray(m32), m_true, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(v32), v_true, rtol=1e-3)
        bf16_err = np.abs(
            np.asarray(m32.astype(jnp.bfloat16), np.float64) - m_true)
        f32_err = np.abs(np.asarray(m32, np.float64) - m_true)
        assert f32_err.max() < bf16_err.max() / 16

    def test_half_precision_output_uses_f32_stats(self):
        """The normalize chain runs off the f32 stats: the bf16 output
        must match an all-f64 reference to within bf16 OUTPUT rounding
        (the old path added bf16 STAT rounding on top, visibly
        shifting outputs near the mean)."""
        from mxnet_tpu.ops.nn import batch_norm
        rs = np.random.RandomState(4)
        x = jnp.asarray(rs.rand(64, 24).astype("float32") * 2 + 3) \
            .astype(jnp.bfloat16)
        g = jnp.asarray(rs.rand(24).astype("float32") + 0.5)
        b = jnp.asarray(rs.rand(24).astype("float32"))
        out = batch_norm(x, g, b, jnp.zeros(24), jnp.ones(24),
                         eps=1e-5, fix_gamma=False, axis=1,
                         _training=True)[0]
        x64 = np.asarray(x, np.float64)
        m = x64.mean(0)
        v = ((x64 - m) ** 2).mean(0)
        ref = (x64 - m) / np.sqrt(v + 1e-5) * np.asarray(g, np.float64) \
            + np.asarray(b, np.float64)
        assert np.abs(np.asarray(out, np.float64) - ref).max() < 0.02


# ---------------------------------------------------------------------------
# packed optimizer apply
# ---------------------------------------------------------------------------

def _opt_cases():
    from mxnet_tpu.optimizer.optimizer import SGD, Adam
    shapes = [(64, 32), (32,), (32, 16), (16,), (7, 3)]
    rs = np.random.RandomState(0)
    ws = [jnp.asarray(rs.randn(*s).astype("float32")) for s in shapes]
    gs = [jnp.asarray(rs.randn(*s).astype("float32")) for s in shapes]
    return [
        ("sgd_momentum", SGD(momentum=0.9, learning_rate=0.05, wd=1e-4),
         ws, gs, [jnp.zeros_like(w) for w in ws]),
        ("sgd", SGD(momentum=0.0, learning_rate=0.05), ws, gs,
         [None] * len(ws)),
        ("adam", Adam(learning_rate=1e-3), ws, gs,
         [(jnp.asarray(rs.rand(*s).astype("float32") * 0.1),
           jnp.asarray(rs.rand(*s).astype("float32") * 0.01))
          for s in shapes]),
    ]


class TestOptimizerApply:
    @pytest.mark.parametrize("case", _opt_cases(),
                             ids=lambda c: c[0])
    @pytest.mark.parametrize("interp", [False, True],
                             ids=["flat", "interpret"])
    def test_bitwise_vs_per_param_in_jit(self, case, interp):
        _, opt, ws, gs, states = case
        lrs = [jnp.float32(0.05 + 0.01 * i) for i in range(len(ws))]
        wds = [jnp.float32(1e-4 * i) for i in range(len(ws))]
        rescale = jnp.float32(1.0 / 32)

        def perparam(ws, gs, states, lrs, wds, rescale):
            outs = [opt.step_fn(w, g, st, lr, wd, rescale)
                    for w, g, st, lr, wd in zip(ws, gs, states, lrs,
                                                wds)]
            return [o[0] for o in outs], [o[1] for o in outs]

        def packed(ws, gs, states, lrs, wds, rescale):
            return OA.packed_apply(opt, ws, gs, states, lrs, wds,
                                   rescale, interpret=interp)

        r_pp = jax.jit(perparam)(ws, gs, states, lrs, wds, rescale)
        r_pk = jax.jit(packed)(ws, gs, states, lrs, wds, rescale)
        for a, c in zip(jax.tree_util.tree_leaves(r_pp),
                        jax.tree_util.tree_leaves(r_pk)):
            assert _eq(a, c)

    def test_bucketize_is_bucket_plan(self):
        """ONE shared packing definition: the kernel segments are the
        wire-reduction buckets (parallel/overlap.bucket_plan)."""
        from mxnet_tpu.parallel.overlap import bucket_plan
        rs = np.random.RandomState(0)
        ws = [jnp.asarray(rs.randn(8, 8).astype(d))
              for d in ("float32", "float32", "bfloat16", "float32")]
        assert OA.bucketize(ws) == bucket_plan(ws)
        # dtype change splits the bucket
        assert len(OA.bucketize(ws)) >= 2

    def test_mixed_dtype_buckets(self):
        from mxnet_tpu.optimizer.optimizer import SGD
        opt = SGD(momentum=0.9, learning_rate=0.05)
        rs = np.random.RandomState(0)
        ws = [jnp.asarray(rs.randn(16, 8).astype("float32")),
              jnp.asarray(rs.randn(8,).astype("bfloat16")),
              jnp.asarray(rs.randn(4, 4).astype("float32"))]
        gs = [jnp.asarray(rs.randn(*w.shape).astype(str(w.dtype)))
              for w in ws]
        states = [jnp.zeros_like(w) for w in ws]
        lrs = [jnp.float32(0.05)] * 3
        wds = [jnp.float32(1e-4)] * 3
        rescale = jnp.float32(1.0)

        def perparam():
            outs = []
            for w, g, st, lr, wd in zip(ws, gs, states, lrs, wds):
                if w.dtype != jnp.float32:
                    lr = lr.astype(w.dtype)
                    wd = wd.astype(w.dtype)
                    rs_ = rescale.astype(w.dtype)
                else:
                    rs_ = rescale
                outs.append(opt.step_fn(w, g, st, lr, wd, rs_))
            return [o[0] for o in outs], [o[1] for o in outs]

        def packed():
            return OA.packed_apply(opt, ws, gs, states, lrs, wds,
                                   rescale, interpret=True)

        r_pp = jax.jit(perparam)()
        r_pk = jax.jit(packed)()
        for a, c in zip(jax.tree_util.tree_leaves(r_pp),
                        jax.tree_util.tree_leaves(r_pk)):
            assert _eq(a, c)

    def test_fused_apply_supported_flags(self):
        from mxnet_tpu.optimizer.optimizer import (SGD, Adam, RMSProp,
                                                   Optimizer)
        assert SGD().fused_apply_supported()
        assert Adam().fused_apply_supported()
        assert not RMSProp().fused_apply_supported()
        assert not Optimizer.fused_apply_supported(Optimizer())


class TestFusedStepApply:
    def _train(self, mode, monkeypatch, optimizer="sgd",
               opt_kwargs=None):
        import random

        import mxnet_tpu as mx
        from mxnet_tpu import gluon
        monkeypatch.setenv("MXTPU_FUSED_APPLY", mode)
        random.seed(0)
        np.random.seed(0)
        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(16, in_units=8, activation="relu"))
            net.add(gluon.nn.Dense(1, in_units=16))
        net.initialize(mx.init.Uniform(0.1))
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), optimizer,
                           opt_kwargs or {"learning_rate": 0.05,
                                          "momentum": 0.9})
        step = gluon.train_step(net, gluon.loss.L2Loss(), tr)
        rs = np.random.RandomState(0)
        x = mx.nd.array(rs.rand(8, 8).astype("float32"))
        y = mx.nd.array(rs.rand(8, 1).astype("float32"))
        for _ in range(3):  # warm, compile, one fused hit
            step(x, y, batch_size=8)
        assert step.last_mode == "fused", step.last_mode
        return [p.data().asnumpy()
                for _, p in sorted(net.collect_params().items())]

    @pytest.mark.parametrize("optimizer,kwargs", [
        ("sgd", {"learning_rate": 0.05, "momentum": 0.9}),
        ("adam", {"learning_rate": 0.001}),
    ])
    def test_train_step_bitwise_across_apply_modes(self, monkeypatch,
                                                   optimizer, kwargs):
        base = self._train("0", monkeypatch, optimizer, kwargs)
        for mode in ("1", "interpret"):
            got = self._train(mode, monkeypatch, optimizer, kwargs)
            for a, c in zip(base, got):
                np.testing.assert_array_equal(a, c)

    def test_unsupported_optimizer_stays_per_param(self, monkeypatch):
        """rmsprop has no packed form — MXTPU_FUSED_APPLY=1 must not
        change its fused-step results (selector returns None)."""
        base = self._train("0", monkeypatch, "rmsprop",
                           {"learning_rate": 0.01})
        got = self._train("1", monkeypatch, "rmsprop",
                          {"learning_rate": 0.01})
        for a, c in zip(base, got):
            np.testing.assert_array_equal(a, c)


# ---------------------------------------------------------------------------
# quantized matmul
# ---------------------------------------------------------------------------

class TestQuantizedMatmul:
    def _ints(self, m, k, n, seed=0):
        rs = np.random.RandomState(seed)
        x = jnp.asarray(rs.randint(-127, 128, (m, k)).astype("int8"))
        w = jnp.asarray(rs.randint(-127, 128, (k, n)).astype("int8"))
        return x, w

    @pytest.mark.parametrize("shape", [(32, 64, 48),    # single tile
                                       (256, 256, 256)])  # tiled grid
    def test_int32_accumulator_exact(self, shape):
        x, w = self._ints(*shape)
        acc = QM.quantized_matmul(x, w, interpret=True)
        assert acc.dtype == jnp.int32
        np.testing.assert_array_equal(
            np.asarray(acc),
            np.asarray(QM.quantized_matmul_reference(x, w)))

    def test_scaled_epilogue(self):
        x, w = self._ints(32, 64, 48)
        s = jnp.asarray(np.random.RandomState(1).rand(48)
                        .astype("float32") * 0.01)
        out = QM.quantized_matmul(x, w, scales=s, interpret=True)
        assert out.dtype == jnp.float32
        np.testing.assert_array_equal(
            np.asarray(out),
            np.asarray(QM.quantized_matmul_reference(x, w, scales=s)))

    def test_fits_guard_falls_back(self, monkeypatch):
        called = []
        real = QM.quantized_matmul_reference
        monkeypatch.setattr(QM, "_use_pallas", lambda *a, **k: True)
        monkeypatch.setattr(QM, "_fits", lambda m, k, n: False)
        monkeypatch.setattr(
            QM, "quantized_matmul_reference",
            lambda *a, **k: called.append(1) or real(*a, **k))
        x, w = self._ints(8, 32, 16)
        out = QM.quantized_matmul(x, w)
        assert called
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(real(x, w)))

    def test_engaged_requires_int8(self, monkeypatch):
        monkeypatch.setenv("MXTPU_QUANT_MATMUL", "interpret")
        x, w = self._ints(8, 32, 16)
        assert QM.engaged(x, w)
        assert not QM.engaged(x.astype(jnp.int32), w)
        monkeypatch.setenv("MXTPU_QUANT_MATMUL", "0")
        assert not QM.engaged(x, w)

    def test_fc_and_conv1x1_wiring(self, monkeypatch):
        """ops/quantized.py routes FC and 1x1 convs through the kernel
        bitwise-identically to the XLA int32 path."""
        from mxnet_tpu.ops.registry import get_op
        rs = np.random.RandomState(0)
        fc = get_op("quantized_fully_connected").fn
        conv = get_op("quantized_conv").fn
        x = jnp.asarray(rs.randint(-127, 128, (8, 64)).astype("int8"))
        w = jnp.asarray(rs.randint(-127, 128, (16, 64)).astype("int8"))
        xc = jnp.asarray(rs.randint(-127, 128, (2, 32, 5, 5))
                         .astype("int8"))
        wc = jnp.asarray(rs.randint(-127, 128, (16, 32, 1, 1))
                         .astype("int8"))
        outs = {}
        for mode in ("interpret", "0"):
            monkeypatch.setenv("MXTPU_QUANT_MATMUL", mode)
            outs[mode] = (
                fc(x, w, None, -1.0, 1.0, -0.5, 0.5, None, None,
                   num_hidden=16, no_bias=True)[0],
                conv(xc, wc, None, -1.0, 1.0, -0.5, 0.5, None, None,
                     kernel=(1, 1), num_filter=16, no_bias=True)[0])
        np.testing.assert_array_equal(np.asarray(outs["interpret"][0]),
                                      np.asarray(outs["0"][0]))
        np.testing.assert_array_equal(np.asarray(outs["interpret"][1]),
                                      np.asarray(outs["0"][1]))

    def test_shape_validation(self):
        x, w = self._ints(8, 32, 16)
        with pytest.raises(ValueError):
            QM.quantized_matmul(x, w.T)


# ---------------------------------------------------------------------------
# compile attribution (ISSUE 8c)
# ---------------------------------------------------------------------------

def test_kernel_compiles_are_attributed():
    """First build per kernel signature lands in
    profiler.compile_stats() under pallas:<kernel> — the Compile table
    entry OBSERVABILITY.md documents."""
    from mxnet_tpu import profiler
    x, g, b = _bn_mats(2, 4, 4, 128, seed=11)
    BN.fused_batch_norm(x, g, b, interpret=True)
    stats = profiler.compile_stats()
    assert any(k.startswith("pallas:batchnorm_fused") for k in stats), \
        sorted(stats)
    entry = stats["pallas:batchnorm_fused.stats"]
    assert entry["count"] >= 1 and entry["total_us"] > 0
