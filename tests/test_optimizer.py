"""Optimizer tests — mirror the reference's strategy of comparing fused
updates against straightforward numpy implementations
(ref: tests/python/unittest/test_optimizer.py compare_optimizer)."""
import numpy as np
import pytest

import mxnet_tpu as mx
import mxnet_tpu.optimizer as opt


def test_sgd_matches_numpy():
    rng = np.random.RandomState(0)
    w0 = rng.uniform(-1, 1, (5, 4)).astype("float32")
    g0 = rng.uniform(-1, 1, (5, 4)).astype("float32")
    lr, wd, mom = 0.1, 0.01, 0.9

    o = opt.SGD(learning_rate=lr, momentum=mom, wd=wd)
    u = opt.get_updater(o)
    w = mx.nd.array(w0)
    g = mx.nd.array(g0)

    w_np, m_np = w0.copy(), np.zeros_like(w0)
    for _ in range(3):
        u(0, g, w)
        m_np = mom * m_np - lr * (g0 + wd * w_np)
        w_np = w_np + m_np
    np.testing.assert_allclose(w.asnumpy(), w_np, rtol=1e-5, atol=1e-6)


def test_sgd_no_momentum_and_clip():
    w0 = np.ones((3,), "float32")
    g0 = np.array([10.0, -10.0, 0.1], "float32")
    o = opt.SGD(learning_rate=0.1, clip_gradient=1.0)
    u = opt.get_updater(o)
    w = mx.nd.array(w0)
    u(0, mx.nd.array(g0), w)
    expect = w0 - 0.1 * np.clip(g0, -1, 1)
    np.testing.assert_allclose(w.asnumpy(), expect, rtol=1e-6)


def test_adam_matches_numpy():
    rng = np.random.RandomState(1)
    w0 = rng.uniform(-1, 1, (6,)).astype("float32")
    g0 = rng.uniform(-1, 1, (6,)).astype("float32")
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8

    o = opt.Adam(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps)
    u = opt.get_updater(o)
    w = mx.nd.array(w0)
    g = mx.nd.array(g0)

    w_np = w0.copy()
    m_np, v_np = np.zeros_like(w0), np.zeros_like(w0)
    for t in range(1, 4):
        u(0, g, w)
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m_np = b1 * m_np + (1 - b1) * g0
        v_np = b2 * v_np + (1 - b2) * g0 * g0
        w_np = w_np - lr_t * m_np / (np.sqrt(v_np) + eps)
    np.testing.assert_allclose(w.asnumpy(), w_np, rtol=1e-5, atol=1e-6)


def test_multi_precision_bf16():
    w = mx.nd.array(np.ones((4,)), dtype="bfloat16")
    g = mx.nd.array(np.full((4,), 0.5), dtype="bfloat16")
    o = opt.SGD(learning_rate=0.1, momentum=0.9, multi_precision=True)
    u = opt.get_updater(o)
    for _ in range(5):
        u(0, g, w)
    assert w.dtype == np.dtype(mx.base.DTYPE_NAMES["bfloat16"])
    # master copy is fp32
    master, state = u.states[0]
    assert master.dtype == np.float32
    assert np.isfinite(w.asnumpy().astype("float32")).all()


def test_updater_state_roundtrip():
    o = opt.Adam(learning_rate=0.1)
    u = opt.get_updater(o)
    w = mx.nd.array(np.ones((3,)))
    g = mx.nd.array(np.full((3,), 0.2))
    u(0, g, w)
    blob = u.get_states(dump_optimizer=True)

    u2 = opt.get_updater(opt.Adam())
    u2.set_states(blob)
    w1 = mx.nd.array(w.asnumpy())
    w2 = mx.nd.array(w.asnumpy())
    u(0, g, w1)
    u2(0, g, w2)
    np.testing.assert_allclose(w1.asnumpy(), w2.asnumpy(), rtol=1e-6)


def test_lr_wd_mult():
    o = opt.SGD(learning_rate=1.0, param_idx2name={0: "a_weight",
                                                   1: "b_bias"})
    o.set_lr_mult({"a_weight": 0.5})
    o.set_wd_mult({})
    assert o._get_lr(0) == 0.5
    assert o._get_lr(1) == 1.0
    # bias gets wd_mult 0 automatically (non-_weight names)
    assert o._get_wd(1) == 0.0


def test_create_by_name_registry():
    for name in ("sgd", "adam", "rmsprop", "adagrad", "adadelta", "adamax",
                 "nadam", "ftrl", "signum", "nag", "ftml", "lamb", "lars",
                 "dcasgd", "sgld", "lbsgd", "adamw", "test"):
        o = opt.create(name)
        assert isinstance(o, opt.Optimizer), name


def test_lr_scheduler_factor():
    from mxnet_tpu.lr_scheduler import FactorScheduler
    s = FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25


def test_lr_scheduler_multifactor():
    from mxnet_tpu.lr_scheduler import MultiFactorScheduler
    s = MultiFactorScheduler(step=[5, 10], factor=0.1, base_lr=1.0)
    assert s(1) == 1.0
    assert abs(s(6) - 0.1) < 1e-12
    assert abs(s(11) - 0.01) < 1e-12


def test_lr_scheduler_multifactor_rejects_scalar_step():
    """Regression: a scalar step used to die with a TypeError deep in
    the milestone iteration; it must raise a clear ValueError at
    construction instead."""
    from mxnet_tpu.lr_scheduler import MultiFactorScheduler
    with pytest.raises(ValueError, match="list or tuple"):
        MultiFactorScheduler(step=5, factor=0.1)
    # tuples are as good as lists
    s = MultiFactorScheduler(step=(5, 10), factor=0.1, base_lr=1.0)
    assert s(1) == 1.0


def test_lr_scheduler_poly_cosine_warmup():
    from mxnet_tpu.lr_scheduler import PolyScheduler, CosineScheduler
    p = PolyScheduler(max_update=100, base_lr=1.0, pwr=2,
                      warmup_steps=10, warmup_begin_lr=0.0)
    assert p(5) == pytest.approx(0.5)       # linear warmup
    assert p(100) == pytest.approx(0.0)
    c = CosineScheduler(max_update=100, base_lr=1.0, final_lr=0.1)
    assert c(0) == pytest.approx(1.0)
    assert c(100) == pytest.approx(0.1)
    assert 0.1 < c(50) < 1.0


def test_optimizer_with_scheduler_steps_lr():
    from mxnet_tpu.lr_scheduler import FactorScheduler
    o = opt.SGD(learning_rate=1.0,
                lr_scheduler=FactorScheduler(step=2, factor=0.5))
    u = opt.get_updater(o)
    w = mx.nd.array(np.ones((2,)))
    g = mx.nd.array(np.zeros((2,)))
    for _ in range(6):
        u(0, g, w)
    assert o._get_lr(0) < 1.0
