"""Fused optimizer update ops (ndarray/optimizer_ops.py) vs the
reference's kernel formulas (ref: src/operator/optimizer_op-inl.h)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def _w(v):
    return nd.array(np.asarray(v, "float32"))


def test_sgd_update():
    w, g = _w([1.0, 2.0]), _w([0.5, -0.5])
    out = nd.sgd_update(w, g, lr=0.1, wd=0.01, rescale_grad=2.0, out=w)
    # (1 - lr*wd)*w - lr*rescale*g
    exp = (1 - 0.1 * 0.01) * np.array([1, 2.0]) - 0.1 * 2.0 * np.array(
        [0.5, -0.5])
    np.testing.assert_allclose(out.asnumpy(), exp, rtol=1e-6)
    assert out is w


def test_sgd_mom_update_state_mutation():
    w, g, m = _w([1.0]), _w([1.0]), _w([0.5])
    nd.sgd_mom_update(w, g, m, lr=0.1, momentum=0.9, out=w)
    # mom = 0.9*0.5 - 0.1*1 = 0.35 ; w = 1 + 0.35
    np.testing.assert_allclose(m.asnumpy(), [0.35], rtol=1e-6)
    np.testing.assert_allclose(w.asnumpy(), [1.35], rtol=1e-6)


def test_clip_gradient():
    w, g = _w([0.0]), _w([10.0])
    out = nd.sgd_update(w, g, lr=1.0, clip_gradient=1.0)
    np.testing.assert_allclose(out.asnumpy(), [-1.0])


def test_mp_sgd_update_master_weights():
    w = nd.array(np.array([1.0], "float16"))
    g = nd.array(np.array([1.0], "float16"))
    w32 = _w([1.0])
    out = nd.mp_sgd_update(w, g, w32, lr=0.25, out=w)
    np.testing.assert_allclose(w32.asnumpy(), [0.75])
    assert out.dtype == np.float16
    np.testing.assert_allclose(out.asnumpy(), [0.75])


def test_adam_update():
    w, g = _w([1.0]), _w([0.5])
    m, v = _w([0.0]), _w([0.0])
    nd.adam_update(w, g, m, v, lr=0.1, beta1=0.9, beta2=0.99,
                   epsilon=1e-8, out=w)
    np.testing.assert_allclose(m.asnumpy(), [0.05], rtol=1e-6)
    np.testing.assert_allclose(v.asnumpy(), [0.0025], rtol=1e-5)
    exp = 1.0 - 0.1 * 0.05 / (np.sqrt(0.0025) + 1e-8)
    np.testing.assert_allclose(w.asnumpy(), [exp], rtol=1e-5)


def test_rmsprop_update():
    w, g, n = _w([1.0]), _w([2.0]), _w([0.0])
    nd.rmsprop_update(w, g, n, lr=0.1, gamma1=0.5, epsilon=0.0, out=w)
    np.testing.assert_allclose(n.asnumpy(), [2.0], rtol=1e-6)
    np.testing.assert_allclose(w.asnumpy(),
                               [1.0 - 0.1 * 2.0 / np.sqrt(2.0)], rtol=1e-5)


def test_signsgd_and_signum():
    w, g = _w([1.0, -1.0]), _w([3.0, -0.2])
    out = nd.signsgd_update(w, g, lr=0.1)
    np.testing.assert_allclose(out.asnumpy(), [0.9, -0.9], rtol=1e-6)
    w2, m2 = _w([0.0]), _w([0.0])
    nd.signum_update(w2, _w([1.0]), m2, lr=0.1, momentum=0.9, out=w2)
    np.testing.assert_allclose(m2.asnumpy(), [-0.1], rtol=1e-5)
    np.testing.assert_allclose(w2.asnumpy(), [-0.1], rtol=1e-5)


def test_ftrl_update_zero_within_l1():
    w, g = _w([0.0]), _w([0.001])
    z, n = _w([0.0]), _w([0.0])
    nd.ftrl_update(w, g, z, n, lr=0.1, lamda1=1.0, out=w)
    np.testing.assert_allclose(w.asnumpy(), [0.0])  # |z| <= lamda1 -> 0


def test_nag_mom_update():
    w, g, m = _w([1.0]), _w([1.0]), _w([0.0])
    nd.nag_mom_update(w, g, m, lr=0.1, momentum=0.9, out=w)
    # mom = -lr*g = -0.1; w = 1 - 0 + 1.9*(0 - 0.1) = 0.81
    np.testing.assert_allclose(m.asnumpy(), [-0.1], rtol=1e-6)
    np.testing.assert_allclose(w.asnumpy(), [0.81], rtol=1e-6)


def test_adamw_update():
    w, g = _w([1.0]), _w([0.5])
    m, v = _w([0.0]), _w([0.0])
    nd.adamw_update(w, g, m, v, rescale_grad=1.0, lr=0.1, eta=1.0,
                    beta1=0.9, beta2=0.99, epsilon=1e-8, wd=0.1, out=w)
    exp = 1.0 - (0.1 * 0.05 / (np.sqrt(0.0025) + 1e-8) + 0.1 * 1.0)
    np.testing.assert_allclose(w.asnumpy(), [exp], rtol=1e-5)


def test_multi_sgd_and_preloaded():
    w1, g1 = _w([1.0]), _w([1.0])
    w2, g2 = _w([2.0]), _w([1.0])
    o1, o2 = nd.multi_sgd_update(w1, g1, w2, g2, lrs=(0.1, 0.2),
                                 wds=(0.0, 0.0), num_weights=2,
                                 out=(w1, w2))
    np.testing.assert_allclose(w1.asnumpy(), [0.9], rtol=1e-6)
    np.testing.assert_allclose(w2.asnumpy(), [1.8], rtol=1e-6)
    # preloaded: lrs/wds as tensors
    w3, g3 = _w([1.0]), _w([1.0])
    nd.preloaded_multi_sgd_update(w3, g3, _w([0.5]), _w([0.0]),
                                  num_weights=1, out=w3)
    np.testing.assert_allclose(w3.asnumpy(), [0.5], rtol=1e-6)


def test_multi_lars():
    lrs = _w([1.0, 1.0])
    w2 = _w([4.0, 0.0])   # |w| = 2, 0
    g2 = _w([1.0, 1.0])   # |g| = 1
    wds = _w([0.0, 0.0])
    out = nd.multi_lars(lrs, w2, g2, wds, eta=1.0, eps=0.0)
    np.testing.assert_allclose(out.asnumpy(), [2.0, 1.0], rtol=1e-5)


def test_sparse_adagrad_update():
    w, g, h = _w([1.0]), _w([2.0]), _w([0.0])
    nd.sparse_adagrad_update(w, g, h, lr=0.1, epsilon=0.0, out=w)
    np.testing.assert_allclose(h.asnumpy(), [4.0], rtol=1e-6)
    np.testing.assert_allclose(w.asnumpy(), [1.0 - 0.1 * 2.0 / 2.0],
                               rtol=1e-5)
