"""Legacy `mx.rnn` package (ref: python/mxnet/rnn/): cells, fused cell,
modifiers, BucketSentenceIter, checkpoint helpers, and an end-to-end
BucketingModule LM (the reference example/rnn workflow shape)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import rnn


def _bind_and_run(sym, data, seed=7, dtype="float32"):
    exe = sym.simple_bind(data=data.shape)
    rs = np.random.RandomState(seed)
    for name, arr in sorted(exe.arg_dict.items()):
        if name != "data":
            arr[:] = (rs.rand(*arr.shape) * 0.2 - 0.1).astype(dtype)
    exe.arg_dict["data"][:] = data
    return exe.forward()[0].asnumpy(), exe


def test_cell_unroll_shapes():
    for cell, h in ((rnn.RNNCell(10, prefix="r_"), 10),
                    (rnn.LSTMCell(12, prefix="l_"), 12),
                    (rnn.GRUCell(9, prefix="g_"), 9)):
        out, states = cell.unroll(4, inputs=mx.sym.Variable("data"),
                                  merge_outputs=True)
        y, _ = _bind_and_run(out, np.random.rand(3, 4, 6).astype("f"))
        assert y.shape == (3, 4, h)
        assert len(states) == len(cell.state_info)


def test_unroll_list_outputs():
    cell = rnn.LSTMCell(8, prefix="l_")
    outs, _ = cell.unroll(3, inputs=mx.sym.Variable("data"),
                          merge_outputs=False)
    assert isinstance(outs, list) and len(outs) == 3


def test_lstm_param_names_and_forget_bias():
    """i2h_bias carries LSTMBias init via the __init__ var attr."""
    cell = rnn.LSTMCell(5, prefix="lstm_")
    out, _ = cell.unroll(2, inputs=mx.sym.Variable("data"),
                         merge_outputs=True)
    args = set(out.list_arguments())
    assert {"lstm_i2h_weight", "lstm_i2h_bias", "lstm_h2h_weight",
            "lstm_h2h_bias", "data"} <= args
    attrs = out.attr_dict
    assert "lstmbias" in attrs["lstm_i2h_bias"]["__init__"]
    # Module init honors it: forget rows = 1, others 0
    mod = mx.mod.Module(out, data_names=("data",), label_names=None)
    mod.bind(data_shapes=[("data", (2, 2, 3))])
    mod.init_params(initializer=mx.init.Zero())
    bias = mod.get_params()[0]["lstm_i2h_bias"].asnumpy()
    assert np.allclose(bias[5:10], 1.0) and np.allclose(bias[:5], 0.0)


def test_unpack_pack_roundtrip():
    cell = rnn.LSTMCell(6, prefix="x_")
    cell.unroll(2, inputs=mx.sym.Variable("data"), merge_outputs=True)
    rs = np.random.RandomState(0)
    args = {"x_i2h_weight": mx.nd.array(rs.rand(24, 4)),
            "x_i2h_bias": mx.nd.array(rs.rand(24)),
            "x_h2h_weight": mx.nd.array(rs.rand(24, 6)),
            "x_h2h_bias": mx.nd.array(rs.rand(24))}
    unpacked = cell.unpack_weights({k: v.copy() for k, v in args.items()})
    assert "x_i2h_i_weight" in unpacked and "x_h2h_o_bias" in unpacked
    packed = cell.pack_weights(unpacked)
    for k in args:
        np.testing.assert_allclose(args[k].asnumpy(),
                                   packed[k].asnumpy(), rtol=1e-6)


@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn_tanh"])
def test_fused_matches_unfused(mode):
    """FusedRNNCell (lax.scan RNN op) == its unfuse() stack given the
    same weights routed through unpack_weights — validates the packed
    layout end to end."""
    T, N, I, H, L = 3, 2, 4, 5, 2
    fused = rnn.FusedRNNCell(H, num_layers=L, mode=mode, prefix="f_")
    fo, _ = fused.unroll(T, inputs=mx.sym.Variable("data"),
                         merge_outputs=True)
    rs = np.random.RandomState(3)
    nparam = fo.infer_shape(data=(N, T, I))[0]
    names = fo.list_arguments()
    pvec = None
    for nm, shp in zip(names, nparam):
        if nm == "f_parameters":
            pvec = mx.nd.array((rs.rand(*shp) * 0.4 - 0.2).astype("f"))
    assert pvec is not None
    exe = fo.bind(args={"data": mx.nd.zeros((N, T, I)),
                        "f_parameters": pvec})
    x = np.random.RandomState(5).rand(N, T, I).astype("f")
    exe.arg_dict["data"][:] = x
    y_fused = exe.forward()[0].asnumpy()

    stack = fused.unfuse()
    so, _ = stack.unroll(T, inputs=mx.sym.Variable("data"),
                         merge_outputs=True)
    per_gate = fused.unpack_weights({"f_parameters": pvec})
    per_layer = stack.pack_weights(per_gate)
    args = {"data": mx.nd.zeros((N, T, I))}
    args.update({k: v for k, v in per_layer.items()})
    sexe = so.bind(args=args)
    sexe.arg_dict["data"][:] = x
    y_stack = sexe.forward()[0].asnumpy()
    np.testing.assert_allclose(y_fused, y_stack, rtol=2e-3, atol=2e-3)


def test_fused_rnn_initializer():
    """init.FusedRNN fills the packed vector; lstm forget biases = 1."""
    fused = rnn.FusedRNNCell(4, num_layers=2, mode="lstm", prefix="f_")
    fo, _ = fused.unroll(2, inputs=mx.sym.Variable("data"),
                         merge_outputs=True)
    mod = mx.mod.Module(fo, data_names=("data",), label_names=None)
    mod.bind(data_shapes=[("data", (2, 2, 3))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    vec = mod.get_params()[0]["f_parameters"].asnumpy()
    unpacked = fused.unpack_weights(
        {"f_parameters": mx.nd.array(vec)})
    np.testing.assert_allclose(
        unpacked["f_l0_i2h_f_bias"].asnumpy(), 1.0)
    np.testing.assert_allclose(
        unpacked["f_l1_h2h_f_bias"].asnumpy(), 1.0)
    np.testing.assert_allclose(unpacked["f_l0_i2h_i_bias"].asnumpy(), 0.0)
    w = unpacked["f_l0_i2h_i_weight"].asnumpy()
    assert w.std() > 0  # inner init actually ran


def test_modifier_cells():
    base = rnn.LSTMCell(8, prefix="z_")
    zone = rnn.ZoneoutCell(base, zoneout_outputs=0.2, zoneout_states=0.1)
    out, _ = zone.unroll(3, inputs=mx.sym.Variable("data"),
                         merge_outputs=True)
    y, _ = _bind_and_run(out, np.random.rand(2, 3, 4).astype("f"))
    assert y.shape == (2, 3, 8)

    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(6, prefix="s0_"))
    stack.add(rnn.DropoutCell(0.3, prefix="d_"))
    stack.add(rnn.ResidualCell(rnn.LSTMCell(6, prefix="s1_")))
    out, states = stack.unroll(3, inputs=mx.sym.Variable("data"),
                               merge_outputs=True)
    y, _ = _bind_and_run(out, np.random.rand(2, 3, 6).astype("f"))
    assert y.shape == (2, 3, 6)
    assert len(states) == 4  # two LSTM cells x (h, c)


def test_bidirectional_cell():
    bi = rnn.BidirectionalCell(rnn.GRUCell(5, prefix="l_"),
                               rnn.GRUCell(5, prefix="r_"))
    out, states = bi.unroll(4, inputs=mx.sym.Variable("data"),
                            merge_outputs=True)
    y, _ = _bind_and_run(out, np.random.rand(2, 4, 3).astype("f"))
    assert y.shape == (2, 4, 10)
    assert len(states) == 2


def test_conv_cells():
    for klass in (rnn.ConvRNNCell, rnn.ConvLSTMCell, rnn.ConvGRUCell):
        cell = klass(input_shape=(1, 3, 8, 8), num_hidden=4)
        out, _ = cell.unroll(2, inputs=mx.sym.Variable("data"),
                             merge_outputs=False)
        y, _ = _bind_and_run(out[-1],
                             np.random.rand(2, 2, 3, 8, 8).astype("f"))
        assert y.shape == (2, 4, 8, 8)


def test_begin_state_variable():
    """func=Variable feeds states as graph inputs."""
    cell = rnn.LSTMCell(7, prefix="v_")
    states = cell.begin_state(func=mx.sym.Variable)
    out, _ = cell.unroll(2, inputs=mx.sym.Variable("data"),
                         begin_state=states, merge_outputs=True)
    args = out.list_arguments()
    assert "v_begin_state_0" in args and "v_begin_state_1" in args
    exe = out.simple_bind(data=(3, 2, 4), v_begin_state_0=(3, 7),
                          v_begin_state_1=(3, 7))
    assert exe.forward()[0].shape == (3, 2, 7)


def test_encode_sentences():
    sents = [["a", "b", "c"], ["b", "c"]]
    coded, vocab = rnn.encode_sentences(sents, start_label=1)
    assert coded[0] == [vocab["a"], vocab["b"], vocab["c"]]
    assert coded[1] == [vocab["b"], vocab["c"]]


def test_bucket_sentence_iter():
    rs = np.random.RandomState(0)
    sents = [list(rs.randint(1, 20, size=n))
             for n in rs.randint(3, 9, size=64)]
    it = rnn.BucketSentenceIter(sents, batch_size=4, buckets=[4, 8],
                                invalid_label=0)
    assert it.default_bucket_key == 8
    n = 0
    for batch in it:
        assert batch.bucket_key in (4, 8)
        data = batch.data[0].asnumpy()
        label = batch.label[0].asnumpy()
        assert data.shape == (4, batch.bucket_key)
        # label is data shifted left by one
        np.testing.assert_array_equal(label[:, :-1], data[:, 1:])
        n += 1
    assert n > 0
    it.reset()
    assert sum(1 for _ in it) == n


def test_rnn_checkpoint(tmp_path):
    prefix = str(tmp_path / "lm")
    fused = rnn.FusedRNNCell(4, num_layers=1, mode="lstm", prefix="c_")
    out, _ = fused.unroll(2, inputs=mx.sym.Variable("data"),
                          merge_outputs=True)
    rs = np.random.RandomState(1)
    shp = out.infer_shape(data=(2, 2, 3))[0]
    args = {n: mx.nd.array(rs.rand(*s).astype("f"))
            for n, s in zip(out.list_arguments(), shp) if n != "data"}
    rnn.save_rnn_checkpoint(fused, prefix, 3, out, args, {})
    # on disk the params are per-gate (readable / portable)
    import mxnet_tpu.model as model
    _, raw, _ = model.load_checkpoint(prefix, 3)
    assert "c_l0_i2h_i_weight" in raw
    sym2, arg2, _ = rnn.load_rnn_checkpoint(fused, prefix, 3)
    np.testing.assert_allclose(args["c_parameters"].asnumpy(),
                               arg2["c_parameters"].asnumpy(), rtol=1e-6)


def test_lm_bucketing_train():
    """The reference example/rnn workflow: BucketSentenceIter +
    sym_gen(seq_len) closing over shared cells -> BucketingModule.fit
    (ref: example/rnn/lstm_bucketing.py structure)."""
    vocab_size, emb, hid = 30, 8, 16
    rs = np.random.RandomState(0)
    sents = [list(rs.randint(2, vocab_size, size=n))
             for n in rs.randint(3, 9, size=96)]
    it = rnn.BucketSentenceIter(sents, batch_size=8, buckets=[4, 8],
                                invalid_label=0)

    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(hid, prefix="lstm_l0_"))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                                 output_dim=emb, name="embed")
        stack.reset()
        outputs, _ = stack.unroll(seq_len, inputs=embed,
                                  merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, hid))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab_size,
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label, name="softmax")
        return pred, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=it.default_bucket_key)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    metric = mx.metric.Perplexity(ignore_label=None)
    first = None
    for epoch in range(4):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        ppl = metric.get()[1]
        if first is None:
            first = ppl
    assert ppl < first, "perplexity did not improve: %s -> %s" % (first,
                                                                  ppl)


def test_symbol_sequence_length_input():
    """sequence_length binds as a real symbol input (review r4 finding)."""
    data = mx.sym.Variable("data")
    seqlen = mx.sym.Variable("len")
    s = mx.sym.SequenceMask(data=data, sequence_length=seqlen,
                            use_sequence_length=True, value=0.0)
    assert "len" in s.list_arguments()
    exe = s.simple_bind(data=(4, 2, 3), len=(2,))
    exe.arg_dict["data"][:] = np.ones((4, 2, 3), "f")
    exe.arg_dict["len"][:] = np.array([2, 4], "f")
    out = exe.forward()[0].asnumpy()
    assert out[2:, 0].sum() == 0 and out[:, 1].sum() > 0


def test_symbol_positional_overflow_raises():
    with pytest.raises(TypeError):
        mx.sym.relu(mx.sym.Variable("a"), mx.sym.Variable("b"))


def test_lr_mult_flows_to_optimizer():
    """sym.Variable(lr_mult=0) freezes a param through Module."""
    w = mx.sym.Variable("fcw", lr_mult=0.0)
    out = mx.sym.FullyConnected(data=mx.sym.Variable("data"), weight=w,
                                num_hidden=3, name="fc")
    out = mx.sym.LinearRegressionOutput(
        data=out, label=mx.sym.Variable("lab"))
    mod = mx.mod.Module(out, data_names=("data",), label_names=("lab",))
    from mxnet_tpu.io import DataBatch
    mod.bind(data_shapes=[("data", (4, 5))],
             label_shapes=[("lab", (4, 3))])
    mod.init_params(initializer=mx.init.Uniform(0.5))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    before = mod.get_params()[0]["fcw"].asnumpy().copy()
    batch = DataBatch([mx.nd.array(np.random.rand(4, 5))],
                      [mx.nd.array(np.random.rand(4, 3))])
    for _ in range(3):
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    after = mod.get_params()[0]["fcw"].asnumpy()
    np.testing.assert_allclose(before, after)
    # bias (no lr_mult) did move
    assert not np.allclose(
        mod.get_params()[0]["fc_bias"].asnumpy(), 0.0)
