"""Unified runtime telemetry tests (ref: tests/python/unittest/
test_profiler.py): set_config validation, record_op aggregation, trace
lanes, memory sampling, continuous dump, pause/resume markers, metrics()
round-trip, subsystem instrumentation, and storage.reset_peak."""
import json
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon, profiler, storage


@pytest.fixture(autouse=True)
def _clean_profiler(tmp_path):
    profiler._reset()
    profiler.set_config(filename=str(tmp_path / "profile.json"),
                        xprof=False, profile_memory=False,
                        continuous_dump=False, dump_period=1.0)
    yield
    profiler._reset()
    profiler.set_config(filename="profile.json", profile_memory=False,
                        continuous_dump=False, xprof=True)


def _trace(fn=None):
    fn = fn or profiler._state["filename"]
    with open(fn) as f:
        return json.load(f)


def _lane_events(data, lane):
    tid = profiler.LANES[lane]
    return [e for e in data["traceEvents"]
            if e.get("tid") == tid and e.get("ph") in ("X", "C", "i")]


# -- set_config (satellite: atomic validation) ------------------------------

def test_set_config_unknown_key_rejected_before_any_mutation(tmp_path):
    fn_before = profiler._state["filename"]
    with pytest.raises(ValueError, match="bogus"):
        profiler.set_config(filename=str(tmp_path / "other.json"),
                            aggregate_stats=True, bogus=1)
    # the KNOWN keys in the same call must not have been applied
    assert profiler._state["filename"] == fn_before
    assert profiler._state["aggregate_stats"] is False


def test_set_config_dump_period_validated_before_apply():
    with pytest.raises(ValueError, match="dump_period"):
        profiler.set_config(continuous_dump=True, dump_period=0)
    assert profiler._state["continuous_dump"] is False


def test_set_config_accepts_reference_parity_keys():
    profiler.set_config(profile_all=True, profile_symbolic=True,
                        profile_imperative=True, profile_api=True,
                        profile_process="worker")


# -- record_op aggregation ---------------------------------------------------

def test_record_op_aggregates_and_dumps_table():
    profiler.set_state("run")
    profiler.record_op("opA", 10.0)
    profiler.record_op("opA", 30.0)
    profiler.record_op("opB", 5.0)
    profiler.set_state("stop")
    table = profiler.dumps()
    m = profiler.metrics()
    assert m["aggregate"]["opA"]["count"] == 2
    assert m["aggregate"]["opA"]["total_us"] == pytest.approx(40.0)
    assert m["aggregate"]["opA"]["min_us"] == pytest.approx(10.0)
    assert m["aggregate"]["opA"]["max_us"] == pytest.approx(30.0)
    assert "opA" in table and "opB" in table
    assert "imperative dispatch:" in table


def test_record_op_is_noop_when_stopped_or_paused():
    profiler.record_op("ghost", 10.0)
    assert "ghost" not in profiler.metrics()["aggregate"]
    profiler.set_state("run")
    profiler.pause()
    profiler.record_op("ghost", 10.0)
    profiler.resume()
    profiler.set_state("stop")
    assert "ghost" not in profiler.metrics()["aggregate"]


# -- pause/resume markers (satellite) ---------------------------------------

def test_pause_resume_emit_instant_markers():
    profiler.set_state("run")
    profiler.pause()
    assert not profiler.is_running()
    profiler.resume()
    assert profiler.is_running()
    profiler.set_state("stop")
    profiler.dump()
    names = [e["name"] for e in _trace()["traceEvents"]
             if e.get("ph") == "i"]
    assert "profiler.pause" in names
    assert "profiler.resume" in names


# -- lane metadata -----------------------------------------------------------

def test_dump_contains_lane_metadata_events():
    profiler.set_state("run")
    profiler.record_op("x", 1.0)
    profiler.set_state("stop")
    profiler.dump()
    data = _trace()
    meta = [e for e in data["traceEvents"] if e.get("ph") == "M"]
    proc = [e for e in meta if e["name"] == "process_name"]
    assert proc and proc[0]["args"]["name"] == "mxnet_tpu"
    thread_names = {e["tid"]: e["args"]["name"] for e in meta
                    if e["name"] == "thread_name"}
    for lane, tid in profiler.LANES.items():
        assert thread_names[tid] == lane


# -- imperative + bulk lanes -------------------------------------------------

def test_imperative_ops_and_bulk_flush_land_in_their_lanes():
    a = mx.nd.array(np.ones((4, 4), np.float32))
    profiler.set_state("run")
    b = a * 2.0
    b = b + 1.0
    with engine.bulk(8):
        c = a + b
        c = c * 3.0
        c.asnumpy()
    profiler.set_state("stop")
    profiler.dump()
    data = _trace()
    imp = [e for e in _lane_events(data, "imperative")
           if e.get("ph") == "X"]
    assert len(imp) >= 2
    bulk = [e for e in _lane_events(data, "bulk")
            if e["name"] == "bulk_segment"]
    assert bulk, "bulk flush span missing"
    assert bulk[0]["args"]["ops"] >= 2
    assert bulk[0]["args"]["mode"] in (
        "cached", "compile", "eager-warming", "eager-fallback")


def test_profiling_off_records_nothing_from_subsystems():
    a = mx.nd.array(np.ones((4, 4), np.float32))
    _ = (a * 2.0 + 1.0).asnumpy()
    a.attach_grad()
    with autograd.record():
        y = (a * a).sum()
    y.backward()
    m = profiler.metrics()
    assert m["aggregate"] == {}
    assert m["counters"] == {}
    assert m["num_events"] == 0


# -- autograd lane -----------------------------------------------------------

def test_autograd_backward_span():
    x = mx.nd.array(np.arange(6, dtype=np.float32))
    x.attach_grad()
    profiler.set_state("run")
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    profiler.set_state("stop")
    m = profiler.metrics()
    assert m["aggregate"]["autograd.backward"]["count"] == 1
    profiler.dump()
    assert any(e["name"] == "autograd.backward"
               for e in _lane_events(_trace(), "autograd"))


# -- kvstore lane ------------------------------------------------------------

def test_kvstore_spans_and_byte_counters():
    kv = mx.kv.create("local")
    profiler.set_state("run")
    kv.init(7, mx.nd.ones((8, 8)))
    kv.push(7, mx.nd.ones((8, 8)))
    out = mx.nd.zeros((8, 8))
    kv.pull(7, out=out)
    profiler.set_state("stop")
    m = profiler.metrics()
    for name in ("kvstore.init", "kvstore.push", "kvstore.pull"):
        assert m["aggregate"][name]["count"] == 1, name
    assert m["counters"]["kvstore.bytes_pushed"] == 8 * 8 * 4
    assert m["counters"]["kvstore.bytes_pulled"] == 8 * 8 * 4
    profiler.dump()
    kv_events = _lane_events(_trace(), "kvstore")
    spans = [e for e in kv_events if e.get("ph") == "X"]
    assert any(e["args"]["bytes"] == 8 * 8 * 4 for e in spans)


# -- io lane -----------------------------------------------------------------

def test_io_prefetch_spans_and_queue_depth():
    from mxnet_tpu.io.prefetch import DevicePrefetchIter
    batches = [np.full((2, 2), i, np.float32) for i in range(4)]
    profiler.set_state("run")
    got = list(DevicePrefetchIter(iter(batches), depth=2))
    profiler.set_state("stop")
    assert len(got) == 4
    m = profiler.metrics()
    # one span per batch plus one for the end-of-stream sentinel read
    assert m["aggregate"]["io.batch_fetch"]["count"] >= 4
    assert m["aggregate"]["io.batch_place"]["count"] >= 1
    profiler.dump()
    io_events = _lane_events(_trace(), "io")
    assert any(e["name"] == "io.prefetch_queue_depth"
               and e.get("ph") == "C" for e in io_events)


# -- memory profiling (tentpole 1) ------------------------------------------

def test_memory_sampling_counters_and_table():
    profiler.set_config(profile_memory=True)
    profiler.set_state("run")
    _ = (mx.nd.ones((16, 16)) * 2.0).asnumpy()
    profiler.sample_memory("test")
    time.sleep(0.15)  # let the background sampler tick at least once
    profiler.set_state("stop")
    profiler.dump()
    mem = [e for e in _lane_events(_trace(), "memory")
           if e.get("ph") == "C"]
    assert mem, "no memory counter events"
    assert mem[0]["name"].startswith("memory:")
    assert set(mem[0]["args"]) == {"bytes_in_use", "peak_bytes_in_use"}
    assert "Device memory" in profiler.dumps()
    m = profiler.metrics()
    assert m["memory"]["devices"], "metrics() lost the memory snapshot"
    for vals in m["memory"]["devices"].values():
        assert {"bytes_in_use", "peak_bytes_in_use",
                "peak_since_reset"} <= set(vals)
    # the memory section is the single owner of allocation accounting
    # and the ledger (ISSUE 13)
    assert "ledger" in m["memory"]
    assert "alloc_fallbacks" in m["memory"]


def test_memory_sampling_off_by_default():
    profiler.set_state("run")
    profiler.sample_memory("test")
    profiler.set_state("stop")
    profiler.dump()
    assert not _lane_events(_trace(), "memory")


def test_bulk_flush_triggers_memory_sample():
    profiler.set_config(profile_memory=True)
    # sampler period pushed way out: only start + flush-boundary samples
    os.environ["MXNET_PROFILER_MEMORY_SAMPLE_PERIOD"] = "60"
    try:
        profiler.set_state("run")
        a = mx.nd.ones((4, 4))
        with engine.bulk(8):
            b = a + 1.0
            b = b * 2.0
            b.asnumpy()
        profiler.set_state("stop")
    finally:
        del os.environ["MXNET_PROFILER_MEMORY_SAMPLE_PERIOD"]
    profiler.dump()
    mem = [e for e in _lane_events(_trace(), "memory")
           if e.get("ph") == "C"]
    assert len(mem) >= 2  # the start sample + the bulk-flush sample


# -- continuous dump (tentpole 2) -------------------------------------------

def test_continuous_dump_writes_valid_json_mid_run(tmp_path):
    fn = str(tmp_path / "cont.json")
    profiler.set_config(filename=fn, continuous_dump=True,
                        dump_period=0.05)
    profiler.set_state("run")
    try:
        # the file exists (and parses) from the first moment of the run
        assert os.path.exists(fn)
        data0 = _trace(fn)
        assert "traceEvents" in data0
        profiler.record_op("mid_run_op", 12.0)
        deadline = time.time() + 5
        while time.time() < deadline:
            time.sleep(0.06)
            names = [e["name"] for e in _trace(fn)["traceEvents"]]
            if "mid_run_op" in names:
                break
        else:
            pytest.fail("periodic rewrite never picked up the event")
    finally:
        profiler.set_state("stop")
    # final rewrite on stop also contains everything
    assert any(e["name"] == "mid_run_op"
               for e in _trace(fn)["traceEvents"])


def test_dump_is_atomic_no_temp_left_behind(tmp_path):
    fn = str(tmp_path / "atomic.json")
    profiler.set_config(filename=fn)
    profiler.set_state("run")
    profiler.record_op("x", 1.0)
    profiler.set_state("stop")
    profiler.dump()
    assert os.path.exists(fn)
    leftovers = [p for p in os.listdir(str(tmp_path)) if ".tmp." in p]
    assert not leftovers


# -- metrics() (tentpole 4) --------------------------------------------------

def test_metrics_json_roundtrip_and_matches_dumps_totals():
    profiler.set_state("run")
    profiler.record_op("opX", 25.0)
    profiler.record_op("opX", 75.0)
    profiler.account("io.batches", 3)
    profiler.set_state("stop")
    m = profiler.metrics()
    # JSON-safe by construction
    m2 = json.loads(json.dumps(m))
    assert m2["aggregate"]["opX"]["count"] == 2
    assert m2["aggregate"]["opX"]["total_us"] == pytest.approx(100.0)
    assert m2["counters"]["io.batches"] == 3
    assert m2["imperative"] == profiler.imperative_stats()
    # same totals as the text table
    line = [ln for ln in profiler.dumps().splitlines()
            if ln.startswith("opX")][0]
    cols = line.split()
    assert int(cols[1]) == m["aggregate"]["opX"]["count"]
    assert float(cols[2]) == pytest.approx(
        m["aggregate"]["opX"]["total_us"], abs=0.1)


def test_dump_format_metrics_writes_snapshot(tmp_path):
    fn = str(tmp_path / "metrics.json")
    profiler.set_config(filename=fn)
    profiler.set_state("run")
    profiler.record_op("opY", 10.0)
    profiler.set_state("stop")
    profiler.dump(format="metrics")
    data = json.load(open(fn))
    assert data["aggregate"]["opY"]["count"] == 1
    assert set(data) >= {"aggregate", "imperative", "counters", "memory"}
    with pytest.raises(ValueError):
        profiler.dump(format="pdf")


def test_event_cap_drops_and_tallies(monkeypatch):
    monkeypatch.setattr(profiler, "_MAX_EVENTS", 3)
    profiler.set_state("run")
    for i in range(6):
        profiler.record_op("capped", 1.0)
    profiler.set_state("stop")
    m = profiler.metrics()
    assert m["num_events"] == 3
    assert m["counters"]["profiler.dropped_events"] == 3
    # aggregation keeps counting past the cap
    assert m["aggregate"]["capped"]["count"] == 6


def test_metrics_reset_clears_everything():
    profiler.set_state("run")
    profiler.record_op("opZ", 10.0)
    profiler.account("kvstore.bytes_pushed", 5)
    profiler.set_state("stop")
    profiler.metrics(reset=True)
    m = profiler.metrics()
    assert m["aggregate"] == {} and m["counters"] == {}
    assert m["num_events"] == 0


# -- account() accumulates with profiling off (ISSUE 6 satellite) -----------

def test_account_counts_with_profiler_stopped():
    """Regression: cumulative counters must not silently drop deltas
    while profiling is off — only the trace-event emission gates on
    _ACTIVE."""
    assert not profiler.is_running()
    profiler.account("kvstore.bytes_pushed", 100)
    profiler.account("transport_retries", 2, emit=False)
    m = profiler.metrics()
    assert m["counters"]["kvstore.bytes_pushed"] == 100
    assert m["counters"]["transport_retries"] == 2
    # but NO trace events were born from it
    assert m["num_events"] == 0
    # and the totals keep growing across an on/off boundary
    profiler.set_state("run")
    profiler.account("kvstore.bytes_pushed", 1)
    profiler.set_state("stop")
    profiler.account("kvstore.bytes_pushed", 1)
    assert profiler.metrics()["counters"]["kvstore.bytes_pushed"] == 102


def test_kvstore_byte_counters_accumulate_while_profiling_off():
    """The production wire-byte ledger survives profiling being off
    (the exact bug the ISSUE 6 satellite names)."""
    kv = mx.kv.create("local")
    kv.init(11, mx.nd.ones((4, 4)))
    kv.push(11, mx.nd.ones((4, 4)))
    out = mx.nd.zeros((4, 4))
    kv.pull(11, out=out)
    m = profiler.metrics()
    assert m["counters"]["kvstore.bytes_pushed"] == 4 * 4 * 4
    assert m["counters"]["kvstore.bytes_pulled"] == 4 * 4 * 4
    assert m["num_events"] == 0


# -- latency histograms (ISSUE 6 tentpole c) ---------------------------------

def _np_pct(data, q):
    # 'lower' = an actual sample value, the right reference for a
    # histogram quantile (default linear interpolation invents points
    # in the empty gap of a bimodal distribution)
    return float(np.percentile(data, q, method="lower"))


@pytest.mark.parametrize("dist", ["uniform", "bimodal", "heavy_tail"])
def test_latency_percentiles_match_numpy_reference(dist):
    rs = np.random.RandomState(42)
    if dist == "uniform":
        data = rs.uniform(10.0, 1000.0, 4000)
    elif dist == "bimodal":
        data = np.concatenate([rs.normal(100.0, 5.0, 2000),
                               rs.normal(50000.0, 1500.0, 2000)])
    else:  # heavy tail
        data = rs.lognormal(mean=5.0, sigma=2.0, size=4000)
    data = np.abs(data) + 1e-3
    profiler.set_state("run")
    for d in data:
        profiler.record_latency("t.%s" % dist, float(d))
    profiler.set_state("stop")
    h = profiler.metrics()["latency"]["t.%s" % dist]
    assert h["count"] == len(data)
    assert h["max_us"] == pytest.approx(float(data.max()))
    assert h["sum_us"] == pytest.approx(float(data.sum()), rel=1e-6)
    # log buckets are 12.5% wide: estimates must land within one bucket
    for q, key in ((50, "p50_us"), (95, "p95_us"), (99, "p99_us")):
        ref = _np_pct(data, q)
        assert h[key] == pytest.approx(ref, rel=0.13), (dist, q)
    assert h["p50_us"] <= h["p95_us"] <= h["p99_us"] <= h["max_us"]


def test_latency_single_sample_and_zero():
    profiler.set_state("run")
    profiler.record_latency("one", 123.4)
    profiler.record_latency("zeros", 0.0)
    profiler.set_state("stop")
    lat = profiler.metrics()["latency"]
    one = lat["one"]
    assert one["count"] == 1
    for key in ("p50_us", "p95_us", "p99_us"):
        # within the sample's own bucket, clamped to the true max
        assert 123.4 * (1 - 0.13) <= one[key] <= 123.4
    z = lat["zeros"]
    assert z["p50_us"] == 0.0 and z["max_us"] == 0.0


def test_latency_submicrosecond_samples_share_underflow_bucket():
    """All sub-0.5us samples land in the single [0, 0.5us) underflow
    bucket — frexp packing would otherwise hand each a distinct negative
    index aliasing (0, 0) bounds, zeroing the percentiles and emitting
    duplicate ``le`` series in one Prometheus exposition."""
    profiler.set_state("run")
    for v in (0.4, 0.3, 0.2, 0.05):
        profiler.record_latency("tiny", v)
    profiler.record_latency("tiny", 2.0)
    profiler.set_state("stop")
    h = profiler.metrics()["latency"]["tiny"]
    assert h["count"] == 5
    assert h["min_us"] == 0.05 and h["max_us"] == 2.0
    assert 0.0 < h["p50_us"] <= 0.5  # inside the underflow bucket
    body = profiler.prometheus_text()
    labels = [line.split(" ")[0] for line in body.splitlines()
              if 'name="tiny"' in line and "_bucket" in line]
    assert labels and len(labels) == len(set(labels)), labels


def test_latency_noop_when_stopped_and_reset_clears():
    profiler.record_latency("ghost", 10.0)
    assert "ghost" not in profiler.metrics()["latency"]
    profiler.set_state("run")
    profiler.record_latency("real", 10.0)
    profiler.set_state("stop")
    assert "real" in profiler.metrics()["latency"]
    profiler.metrics(reset=True)
    assert profiler.metrics()["latency"] == {}
    assert "Latency" not in profiler.dumps()


def test_latency_appears_in_dumps_table():
    profiler.set_state("run")
    for d in (10.0, 20.0, 30.0):
        profiler.record_latency("kvstore.pull_rtt", d)
    profiler.set_state("stop")
    table = profiler.dumps()
    assert "Latency" in table and "kvstore.pull_rtt" in table


# -- flow events + pid=rank (ISSUE 6 tentpole a/b) ---------------------------

def test_record_flow_emits_paired_s_f_events():
    profiler.set_state("run")
    profiler.record_op("client.req", 100.0, lane="kvstore")
    profiler.record_flow("req", 42, "s", lane="kvstore")
    profiler.record_flow("req", 42, "f", lane="kvstore")
    with pytest.raises(ValueError):
        profiler.record_flow("req", 42, "x")
    profiler.set_state("stop")
    profiler.dump()
    evs = _trace()["traceEvents"]
    s = [e for e in evs if e.get("ph") == "s"]
    f = [e for e in evs if e.get("ph") == "f"]
    assert s and f and s[0]["id"] == f[0]["id"] == 42
    assert f[0]["bp"] == "e"


def test_events_carry_rank_pid():
    profiler.set_state("run")
    profiler.record_op("op", 1.0)
    profiler.set_state("stop")
    profiler.dump()
    data = _trace()
    assert all(e.get("pid") == profiler.PID
               for e in data["traceEvents"])
    # the shard self-describes for trace_merge
    assert data["metadata"]["rank"] == profiler.PID


def test_record_clock_sync_keeps_min_rtt_sample():
    profiler.record_clock_sync("peer:1", 500.0, 80.0)
    profiler.record_clock_sync("peer:1", 900.0, 300.0)  # worse rtt
    profiler.record_clock_sync("peer:1", 510.0, 40.0, primary=True)
    cs = profiler.clock_sync()["peer:1"]
    assert cs["offset_us"] == 510.0 and cs["rtt_us"] == 40.0
    assert cs["samples"] == 3 and cs["primary"] is True


# -- /metrics endpoint (ISSUE 6 tentpole d) ----------------------------------

def _parse_prometheus(text):
    """Minimal exposition-format validator: returns {family: n_samples}
    and raises on malformed lines."""
    import re
    fams = {}
    typed = set()
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? "
        r"(-?[0-9.]+(?:[eE][-+]?[0-9]+)?|\+Inf|-Inf|NaN)$")
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            parts = line.split()
            assert parts[3] in ("counter", "gauge", "histogram",
                                "summary", "untyped"), line
            typed.add(parts[2])
            continue
        if line.startswith("#"):
            continue
        m = sample_re.match(line)
        assert m, "malformed sample line: %r" % line
        fams[m.group(1)] = fams.get(m.group(1), 0) + 1
    assert typed, "no TYPE lines"
    return fams


def test_serve_metrics_prometheus_scrape():
    from urllib.request import urlopen
    profiler.set_state("run")
    profiler.record_latency("kvstore.pull_rtt", 120.0)
    profiler.record_latency("fused_step.step", 800.0)
    profiler.account("kvstore.bytes_pushed", 64)
    port = profiler.serve_metrics(port=0)
    try:
        # idempotent: second call returns the same port
        assert profiler.serve_metrics(port=0) == port
        body = urlopen("http://127.0.0.1:%d/metrics" % port,
                       timeout=5).read().decode()
        fams = _parse_prometheus(body)
        assert fams.get("mxtpu_latency_seconds_bucket", 0) >= 2
        assert "mxtpu_latency_seconds_count" in fams
        assert 'name="kvstore.pull_rtt"' in body
        assert 'name="fused_step.step"' in body
        assert "mxtpu_counter_total" in fams
        # JSON twin of the same snapshot
        import json as _json
        raw = urlopen("http://127.0.0.1:%d/metrics.json" % port,
                      timeout=5).read()
        snap = _json.loads(raw)
        assert snap["counters"]["kvstore.bytes_pushed"] == 64
        # unknown path 404s without killing the server
        import urllib.error
        with pytest.raises(urllib.error.HTTPError):
            urlopen("http://127.0.0.1:%d/nope" % port, timeout=5)
        body2 = urlopen("http://127.0.0.1:%d/metrics" % port,
                        timeout=5).read()
        assert body2
    finally:
        profiler.set_state("stop")
        profiler.stop_metrics_server()
    # endpoint really is down now
    import urllib.error
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        urlopen("http://127.0.0.1:%d/metrics" % port, timeout=1)


def test_http_port_env_autostarts_endpoint(monkeypatch):
    from urllib.request import urlopen
    monkeypatch.setenv("MXNET_PROFILER_HTTP_PORT", "0")
    profiler.set_state("run")
    try:
        port = profiler.serve_metrics()  # idempotent: already started
        body = urlopen("http://127.0.0.1:%d/metrics" % port,
                       timeout=5).read().decode()
        assert "mxtpu_profiler_events" in body
    finally:
        profiler.set_state("stop")
        profiler.stop_metrics_server()


@pytest.mark.parametrize("bad", ["auto", "70000", "-1"])
def test_http_port_env_malformed_does_not_kill_profiling(monkeypatch, bad):
    """A telemetry config typo in MXNET_PROFILER_HTTP_PORT (non-numeric,
    or out of bind range — HTTPServer raises OverflowError past 65535)
    must not abort set_state('run') — host tracing survives it."""
    monkeypatch.setenv("MXNET_PROFILER_HTTP_PORT", bad)
    profiler.set_state("run")
    try:
        assert profiler.metrics() is not None
    finally:
        profiler.set_state("stop")
        profiler.stop_metrics_server()


# -- storage.reset_peak (satellite) -----------------------------------------

def test_storage_reset_peak_rebases_high_water_mark():
    marks = storage.reset_peak()
    assert marks  # one entry per device
    s0 = storage.stats()[0]
    dev = str(s0.device)
    assert s0.peak_since_reset == s0.bytes_in_use
    # simulate an allocation spike the framework observed
    with storage._hwm_lock:
        storage._hwm[dev] = s0.bytes_in_use + 12345
    s1 = [s for s in storage.stats() if str(s.device) == dev][0]
    assert s1.peak_since_reset >= s0.bytes_in_use + 12345
    storage.reset_peak()
    s2 = [s for s in storage.stats() if str(s.device) == dev][0]
    assert s2.peak_since_reset == s2.bytes_in_use


# -- acceptance: gluon loop with everything on ------------------------------

def test_end_to_end_gluon_loop_four_lanes(tmp_path):
    from mxnet_tpu.io.prefetch import DevicePrefetcher
    fn = str(tmp_path / "e2e.json")
    profiler.set_config(filename=fn, profile_all=True, profile_memory=True,
                        continuous_dump=True, dump_period=0.05,
                        xprof=False)

    rng = np.random.RandomState(0)
    xs = [mx.nd.array(rng.uniform(-1, 1, (8, 4)).astype("float32"))
          for _ in range(3)]
    ys = [mx.nd.array(rng.uniform(-1, 1, (8, 1)).astype("float32"))
          for _ in range(3)]
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize(mx.init.Uniform(0.1))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.L2Loss()

    profiler.set_state("run")
    try:
        for x, y in DevicePrefetcher(list(zip(xs, ys))):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(batch_size=8)
        with engine.bulk(8):
            pred = net(xs[0])
            pred = pred * 2.0
            pred.asnumpy()
        # continuous dump: trace exists and parses BEFORE stop
        assert os.path.exists(fn)
        mid = _trace(fn)
        assert isinstance(mid["traceEvents"], list)
        m_before = profiler.metrics()
    finally:
        profiler.set_state("stop")

    data = _trace(fn)
    inv = {tid: lane for lane, tid in profiler.LANES.items()}
    lanes_hit = {inv[e["tid"]] for e in data["traceEvents"]
                 if e.get("ph") in ("X", "C") and e.get("tid") in inv}
    assert {"imperative", "bulk", "autograd", "memory",
            "gluon"} <= lanes_hit, lanes_hit
    assert "io" in lanes_hit
    assert len(lanes_hit) >= 4
    # metrics totals agree with the dumps() aggregate for every span name
    m = profiler.metrics()
    assert set(m["aggregate"]) == set(m_before["aggregate"]) \
        or set(m_before["aggregate"]) <= set(m["aggregate"])
    table = profiler.dumps()
    for name, agg in m["aggregate"].items():
        assert name[:40] in table
    assert m["aggregate"]["gluon.Trainer.step"]["count"] == 3
    assert m["aggregate"]["autograd.backward"]["count"] == 3


# -- ISSUE 8 satellites: shutdown ordering + compile registry ---------------

def test_stop_shuts_metrics_server_before_final_trace_dump(tmp_path,
                                                           monkeypatch):
    """Regression (ISSUE 8 satellite): set_state('stop') must take the
    /metrics endpoint down BEFORE the final trace rewrite, so a scrape
    racing shutdown can never observe a partially-reset histogram
    snapshot — and after stop the endpoint is really gone."""
    from urllib.request import urlopen
    import urllib.error
    order = []
    real_stop = profiler.stop_metrics_server
    real_write = profiler._write_trace

    def spy_stop():
        order.append("stop_server")
        return real_stop()

    def spy_write():
        order.append("write_trace")
        return real_write()

    monkeypatch.setattr(profiler, "stop_metrics_server", spy_stop)
    monkeypatch.setattr(profiler, "_write_trace", spy_write)
    profiler.set_config(filename=str(tmp_path / "p.json"),
                        continuous_dump=True, dump_period=60.0)
    profiler.set_state("run")
    port = profiler.serve_metrics(port=0)
    profiler.record_latency("unit.lat", 100.0)
    body = urlopen("http://127.0.0.1:%d/metrics" % port,
                   timeout=5).read().decode()
    assert 'name="unit.lat"' in body
    order.clear()
    profiler.set_state("stop")
    assert "stop_server" in order and "write_trace" in order, order
    assert order.index("stop_server") < order.index("write_trace"), \
        "endpoint must go down before the final dump"
    with pytest.raises((urllib.error.URLError, ConnectionError,
                        OSError)):
        urlopen("http://127.0.0.1:%d/metrics" % port, timeout=1)
    # re-serving after a stop still works (operator recipe)
    port2 = profiler.serve_metrics(port=0)
    try:
        assert urlopen("http://127.0.0.1:%d/metrics" % port2,
                       timeout=5).read()
    finally:
        profiler.stop_metrics_server()


def test_record_compile_registry_accumulates_unconditionally():
    """Compiles are rare and expensive: the registry counts with
    profiling OFF (the `account` contract); only the trace span gates
    on an active run."""
    assert not profiler._ACTIVE
    profiler.record_compile("unit:prog", key="sig-a", dur_us=1000.0,
                            flops=2.0e9, bytes_accessed=5.0e5)
    profiler.record_compile("unit:prog", key="sig-b", dur_us=500.0)
    st = profiler.compile_stats()["unit:prog"]
    assert st["count"] == 2
    assert st["total_us"] == pytest.approx(1500.0)
    assert st["last_us"] == pytest.approx(500.0)
    assert st["key"] == "sig-b"          # newest signature wins
    assert st["flops"] == pytest.approx(2.0e9)  # sticky across records
    assert profiler.metrics()["num_events"] == 0  # no trace while off
    m = profiler.metrics(reset=True)
    assert m["compile"]["unit:prog"]["count"] == 2
    assert profiler.compile_stats() == {}  # reset clears the registry


def test_record_compile_emits_span_in_compile_lane():
    profiler.set_state("run")
    try:
        profiler.record_compile("unit:prog", key="sig", dur_us=250.0)
    finally:
        profiler.set_state("stop")
    profiler.dump()
    data = _trace()
    evs = [e for e in data["traceEvents"]
           if e.get("name") == "unit:prog" and e.get("ph") == "X"]
    assert len(evs) == 1
    assert evs[0]["tid"] == profiler.LANES["compile"]
    assert evs[0]["args"]["key"] == "sig"
