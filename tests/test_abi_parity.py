"""C ABI parity audit (VERDICT r3 item 3): every MXNET_DLL entry point
in the reference's include/mxnet/c_api.h must map to an MXT* analog or
carry a documented exemption.

Mapping rules:
- mechanical rename MXFoo -> MXTFoo;
- the Ex/EX/X/64/Ex64 suffix variants collapse onto the base MXT name
  (this ABI is 64-bit-native and single-variant by design — the
  reference grew the suffixes for ABI-stable migrations it no longer
  needs here);
- a small explicit table for non-mechanical renames.
"""
import ctypes
import glob
import os
import re
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = "/root/reference"
REF_HEADER = os.path.join(REFERENCE, "include", "mxnet", "c_api.h")
LIB = os.path.join(REPO, "mxnet_tpu", "libmxnet_tpu.so")

# MX name -> MXT name when not the mechanical MX->MXT rename.
RENAMES = {
    # CachedOp family uses noun-first naming like the rest of this ABI
    "MXCreateCachedOp": "MXTCachedOpCreate",
    "MXCreateCachedOpEx": "MXTCachedOpCreate",
    "MXInvokeCachedOp": "MXTCachedOpInvoke",
    "MXInvokeCachedOpEx": "MXTCachedOpInvoke",
    "MXFreeCachedOp": "MXTCachedOpFree",
    # RecordIO drops the "IO" infix
    "MXRecordIOWriterCreate": "MXTRecordWriterCreate",
    "MXRecordIOWriterFree": "MXTRecordWriterFree",
    "MXRecordIOWriterTell": "MXTRecordWriterTell",
    "MXRecordIOWriterWriteRecord": "MXTRecordWriterWrite",
    "MXRecordIOReaderCreate": "MXTRecordReaderCreate",
    "MXRecordIOReaderFree": "MXTRecordReaderFree",
    "MXRecordIOReaderSeek": "MXTRecordReaderSeek",
    "MXRecordIOReaderTell": "MXTRecordReaderTell",
    "MXRecordIOReaderReadRecord": "MXTRecordReaderNext",
    # same functionality, clearer name
    "MXNDArraySyncCopyFromNDArray": "MXTNDArrayCopyFrom",
    "MXDataIterCreateIter": "MXTDataIterCreate",
    "MXDataIterBeforeFirst": "MXTDataIterBeforeFirst",
    "MXAutogradBackward": "MXTAutogradBackward",
    "MXDumpProfile": "MXTProfileDump",
    "MXDumpProcessProfile": "MXTProfileDump",
    "MXSetProfilerConfig": "MXTProfileSetConfig",
    "MXSetProcessProfilerConfig": "MXTProfileSetConfig",
    "MXSetProfilerState": "MXTProfileSetState",
    "MXSetProcessProfilerState": "MXTProfileSetState",
    "MXProcessProfilePause": "MXTProfilePause",
    "MXAggregateProfileStatsPrintEx": "MXTAggregateProfileStatsPrint",
    "MXGetGPUMemoryInformation64": "MXTGetGPUMemoryInformation",
}

# MX name -> why there is deliberately no MXT analog.
EXEMPT = {
    # --- CUDA-only surfaces: the accelerator here is TPU/XLA ---
    "MXRtcCreate": "CUDA RTC; runtime kernels are Pallas via rtc.py",
    "MXRtcPush": "CUDA RTC",
    "MXRtcFree": "CUDA RTC",
    "MXRtcCudaModuleCreate": "CUDA RTC",
    "MXRtcCudaModuleFree": "CUDA RTC",
    "MXRtcCudaKernelCreate": "CUDA RTC",
    "MXRtcCudaKernelFree": "CUDA RTC",
    "MXRtcCudaKernelCall": "CUDA RTC",
    "MXLoadTVMOp": "TVM op library is CUDA/LLVM-specific",
    "MXSetNumOMPThreads": "no OpenMP pool; XLA owns host threading",
    # --- engine push: XLA async dispatch IS the engine (engine.py) ---
    "MXEnginePushAsync": "no user-schedulable engine ops under XLA "
                         "dispatch; engine.py documents the mapping",
    "MXEnginePushAsyncND": "see MXEnginePushAsync",
    "MXEnginePushSync": "see MXEnginePushAsync",
    "MXEnginePushSyncND": "see MXEnginePushAsync",
    # --- C function-pointer callbacks: the embedded-CPython seam makes
    #     Python-side hooks first-class instead ---
    "MXKVStoreSetUpdater": "C-callback updater; server-side optimizer is "
                           "MXTKVStoreSetOptimizer (pickled, HMAC'd)",
    "MXKVStoreSetUpdaterEx": "see MXKVStoreSetUpdater",
    "MXExecutorSetMonitorCallback": "C-callback monitor; use Python "
                                    "Monitor over MXTExecutor outputs",
    "MXExecutorSetMonitorCallbackEX": "see MXExecutorSetMonitorCallback",
    "MXCachedOpRegisterOpHook": "C-callback hook; Python-side "
                                "monitoring instead",
    "MXCustomOpRegister": "C-callback custom op; operator.py (Python) "
                          "and lib_api.h (.so plugins) are the custom-op "
                          "surfaces",
    "MXCustomFunctionRecord": "see MXCustomOpRegister",
    "MXKVStoreRunServer": "no dedicated server binary: sync kvstore is "
                          "collectives; async PS server is started by "
                          "kvstore_async (controller callback is the "
                          "Python seam)",
    "MXKVStoreSendCommmandToServers": "async PS exposes the profiler/ "
                                      "command channel Python-side "
                                      "(kvstore_async.py)",
    "MXKVStoreSetBarrierBeforeExit": "barrier-at-exit is automatic in "
                                     "the async PS clean-finalize path",
    # --- sparse STORAGE C accessors: XLA device tensors are dense;
    #     sparse formats are NDArray-API-level (ndarray/sparse.py) ---
    "MXNDArrayCreateSparseEx": "sparse storage is API-level over dense "
                               "device tensors",
    "MXNDArrayCreateSparseEx64": "see MXNDArrayCreateSparseEx",
    "MXNDArrayGetAuxNDArray": "see MXNDArrayCreateSparseEx",
    "MXNDArrayGetAuxNDArray64": "see MXNDArrayCreateSparseEx",
    "MXNDArrayGetAuxType": "see MXNDArrayCreateSparseEx",
    "MXNDArrayGetAuxType64": "see MXNDArrayCreateSparseEx",
    "MXNDArrayGetDataNDArray": "see MXNDArrayCreateSparseEx",
    "MXNDArraySyncCheckFormat": "see MXNDArrayCreateSparseEx",
    "MXKVStorePullWithSparse": "MXTKVStorePull + "
                               "MXTKVStorePullRowSparse cover both "
                               "paths",
    "MXKVStorePullWithSparseEx": "see MXKVStorePullWithSparse",
    # --- shared-memory IPC: PJRT owns device buffers; host shm IPC has
    #     no analog (process-parallel feeds use the launcher) ---
    "MXNDArrayCreateFromSharedMem": "PJRT owns buffers; no shm IPC",
    "MXNDArrayCreateFromSharedMemEx": "see MXNDArrayCreateFromSharedMem",
    "MXNDArrayGetSharedMemHandle": "see MXNDArrayCreateFromSharedMem",
    "MXNDArrayGetData": "raw device pointers are not exposed by PJRT; "
                        "use MXTNDArraySyncCopyToCPU / DLPack",
    "MXNDArrayGetGradState": "fresh-gradient bookkeeping is internal to "
                             "the tape; MXTNDArrayGetGrad is the surface",
    "MXNDArraySetGradState": "see MXNDArrayGetGradState",
    "MXNDArraySaveRawBytes": "legacy raw serialization; "
                             "MXTNDArraySave + SyncCopyToCPU cover it",
    "MXNDArrayLoadFromRawBytes": "see MXNDArraySaveRawBytes",
    "MXNDArrayToDLPack": "DLPack interop is Python-level "
                         "(NDArray.to_dlpack over jax dlpack); C-capsule "
                         "export of PJRT buffers is not stable",
    "MXNDArrayFromDLPack": "see MXNDArrayToDLPack",
    "MXNDArrayFromDLPackEx": "see MXNDArrayToDLPack",
    "MXNDArrayCallDLPackDeleter": "see MXNDArrayToDLPack",
    "MXDataIterGetIterInfo": "iterator registry metadata lives with "
                             "the Python classes; MXTListDataIters "
                             "exposes the names",
    "MXAutogradGetSymbol": "recorded-graph symbolization: the tape is "
                           "jax-native; export a graph by building it "
                           "symbolically (mx.sym) instead",
    # --- legacy pre-nnvm Function API ---
    "MXListFunctions": "legacy pre-nnvm Function API; "
                       "MXTListAllOpNames + MXTImperativeInvoke",
    "MXGetFunction": "see MXListFunctions",
    "MXFuncDescribe": "see MXListFunctions",
    "MXFuncGetInfo": "see MXListFunctions",
    "MXFuncInvoke": "see MXListFunctions",
    "MXFuncInvokeEx": "see MXListFunctions",
    "MXSymbolListAtomicSymbolCreators": "creator handles are name-keyed "
                                        "here: MXTListAllOpNames + "
                                        "MXTSymbolCreateAtomicSymbol",
    "MXSymbolGetAtomicSymbolInfo": "op metadata via Python registry "
                                   "docstrings; C surface exposes names",
    # --- graph passes owned by XLA / Python contrib here ---
    "MXQuantizeSymbol": "quantization passes live in contrib."
                        "quantization (Python) over the XLA graph",
    "MXReducePrecisionSymbol": "AMP pass is contrib.amp (Python)",
    "MXSetCalibTableToQuantizedSymbol": "see MXQuantizeSymbol",
    "MXGenBackendSubgraph": "subgraph partitioning is symbol/subgraph.py "
                            "(SubgraphProperty seam)",
    "MXOptimizeForBackend": "see MXGenBackendSubgraph",
    "MXGenAtomicSymbolFromSymbol": "fused-node symbolization is the "
                                   "subgraph seam (symbol/subgraph.py)",
    "MXSymbolCutSubgraph": "see MXGenBackendSubgraph",
    "MXSymbolRemoveAmpCast": "AMP cast nodes are a Python-pass concern "
                             "(contrib/amp)",
    "MXSymbolGrad": "symbol-level grad graphs come from jax.grad at "
                    "bind; the reference itself deprecated this entry",
    "MXExecutorGetOptimizedSymbol": "the optimized program is XLA HLO "
                                    "(ShardedTrainStep.lower exposes it "
                                    "Python-side), not a Symbol",
    "MXSymbolInferTypePartial": "MXTSymbolInferType is already partial-"
                                "tolerant (unknown inputs stay -1)",
}


def _ref_names():
    text = open(REF_HEADER).read()
    return sorted(set(re.findall(
        r"MXNET_DLL\s+[\w\s\*]+?\b(MX\w+)\s*\(", text)))


def _our_names():
    ours = set()
    for f in glob.glob(os.path.join(REPO, "src", "*.cc")):
        ours |= set(re.findall(r"\b(MXT\w+)\s*\(", open(f).read()))
    return ours


def _candidates(name):
    mapped = RENAMES.get(name)
    if mapped:
        return [mapped]
    base = "MXT" + name[2:]
    cands = [base]
    for suf in ("Ex64", "EX", "Ex", "X", "64"):
        if base.endswith(suf):
            cands.append(base[: -len(suf)])
    return cands


@pytest.mark.skipif(not os.path.exists(REF_HEADER),
                    reason="reference checkout not available")
def test_every_reference_abi_name_mapped_or_exempt():
    ours = _our_names()
    missing = []
    for name in _ref_names():
        if name in EXEMPT:
            continue
        if not any(c in ours for c in _candidates(name)):
            missing.append(name)
    assert not missing, (
        "reference MXNET_DLL names with neither an MXT analog nor a "
        "documented exemption: %s" % missing)


@pytest.mark.skipif(not os.path.exists(REF_HEADER),
                    reason="reference checkout not available")
def test_exemptions_are_not_stale():
    """An exemption for a name we actually implement is stale docs."""
    ours = _our_names()
    stale = [n for n in EXEMPT
             if any(c in ours for c in _candidates(n)) and n not in RENAMES]
    assert not stale, "exempt names that now have MXT analogs: %s" % stale


@pytest.mark.skipif(not os.path.exists(REF_HEADER),
                    reason="reference checkout not available")
def test_coverage_ratio():
    """Sanity floor: most of the surface is implemented, not exempted."""
    ref = _ref_names()
    ours = _our_names()
    implemented = [n for n in ref
                   if any(c in ours for c in _candidates(n))]
    ratio = len(implemented) / len(ref)
    assert ratio >= 0.60, "implemented %d/%d (%.0f%%)" % (
        len(implemented), len(ref), 100 * ratio)


def _lib():
    if not os.path.exists(LIB):
        subprocess.run(["make", "-C", os.path.join(REPO, "src")],
                       check=True, capture_output=True)
    return ctypes.CDLL(LIB)


def test_round4_entry_points_smoke():
    """The new long-tail functions execute, not just link."""
    lib = _lib()
    # libinfo features
    n = ctypes.c_uint32()
    arr = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTLibInfoFeatures(ctypes.byref(n), ctypes.byref(arr)) == 0
    assert n.value >= 2 and n.value % 2 == 0  # name/flag pairs
    # numpy-shape toggle round trip
    prev = ctypes.c_int()
    assert lib.MXTSetIsNumpyShape(1, ctypes.byref(prev)) == 0
    cur = ctypes.c_int()
    assert lib.MXTIsNumpyShape(ctypes.byref(cur)) == 0
    assert cur.value == 1
    assert lib.MXTSetIsNumpyShape(prev.value, ctypes.byref(cur)) == 0
    # device count
    cnt = ctypes.c_int()
    assert lib.MXTGetGPUCount(ctypes.byref(cnt)) == 0
    assert cnt.value >= 0  # 0 on a CPU-only host (accelerators only)
    # engine bulk size
    old = ctypes.c_int()
    assert lib.MXTEngineSetBulkSize(8, ctypes.byref(old)) == 0
    # roles
    w = ctypes.c_int()
    assert lib.MXTKVStoreIsWorkerNode(ctypes.byref(w)) == 0
    assert w.value == 1
    # profiler object family
    dom = ctypes.c_void_p()
    assert lib.MXTProfileCreateDomain(b"testdom", ctypes.byref(dom)) == 0
    task = ctypes.c_void_p()
    assert lib.MXTProfileCreateTask(dom, b"t0", ctypes.byref(task)) == 0
    assert lib.MXTProfileDurationStart(task) == 0
    assert lib.MXTProfileDurationStop(task) == 0
    ctr = ctypes.c_void_p()
    assert lib.MXTProfileCreateCounter(dom, b"c0", ctypes.byref(ctr)) == 0
    assert lib.MXTProfileSetCounter(ctr, ctypes.c_uint64(5)) == 0
    assert lib.MXTProfileAdjustCounter(ctr, ctypes.c_int64(-2)) == 0
    assert lib.MXTProfileDestroyHandle(task) == 0
    assert lib.MXTProfileDestroyHandle(ctr) == 0
    assert lib.MXTProfileDestroyHandle(dom) == 0
    # NDArray context/storage/detach/shallow-copy
    h = ctypes.c_void_p()
    shape = (ctypes.c_int64 * 2)(2, 3)
    assert lib.MXTNDArrayCreate(shape, 2, 0, ctypes.byref(h)) == 0
    dt = ctypes.c_int()
    di = ctypes.c_int()
    assert lib.MXTNDArrayGetContext(h, ctypes.byref(dt),
                                    ctypes.byref(di)) == 0
    st = ctypes.c_int()
    assert lib.MXTNDArrayGetStorageType(h, ctypes.byref(st)) == 0
    assert st.value == 0
    assert lib.MXTNDArrayWaitToRead(h) == 0
    d = ctypes.c_void_p()
    assert lib.MXTNDArrayDetach(h, ctypes.byref(d)) == 0
    sc = ctypes.c_void_p()
    assert lib.MXTShallowCopyNDArray(h, ctypes.byref(sc)) == 0
    for x in (d, sc, h):
        assert lib.MXTNDArrayFree(x) == 0
    assert lib.MXTNotifyShutdown() == 0
