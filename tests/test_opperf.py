"""Full-registry operator microbenchmark harness (VERDICT r2 item 8).

ref: benchmark/opperf/opperf.py in the reference runs EVERY registered
op with auto-generated inputs; this asserts our harness actually covers
the registry, not a curated subset.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmark", "opperf"))

from opperf import (auto_spec, bench_registry_op,  # noqa: E402
                    run_full_registry, _PROFILES)

# minutes-scale on the 1-core CI host (full registry sweep) — deselect
# with -m 'not slow' for the quick lane; the full lane always runs them
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def summary():
    return run_full_registry(runs=1, warmup=1)


def test_full_registry_coverage(summary):
    """Every registry name is swept; every unique op measures (errors
    would mean the auto-input synthesis regressed)."""
    from mxnet_tpu.ops import registry as r
    # other test modules may have registered graph-local pseudo-ops
    # (fused subgraph regions, plugin test ops) before this runs
    assert summary["registry_names"] \
        == len(r.list_ops()) - summary["skipped_pseudo_ops"]
    assert summary["registry_names"] >= 460
    assert summary["errors"] == 0, summary["error_detail"]
    assert summary["coverage_pct"] == 100.0
    assert summary["measured"] == summary["unique_ops"]


def test_results_structure(summary):
    assert len(summary["top10_slowest"]) == 10
    slowest = summary["top10_slowest"][0]
    assert {"op", "fwd_ms", "jnp_native_ms",
            "dispatch_overhead_ms"} <= set(slowest)
    # sorted descending by fwd time
    times = [r["fwd_ms"] for r in summary["top10_slowest"]]
    assert times == sorted(times, reverse=True)
    # baseline present and positive for every measured op
    for r_ in summary["results"].values():
        assert r_["jnp_native_ms"] > 0


def test_auto_spec_rules():
    """The synthesis rule: leading required non-static params become
    tensors; required statics get table values; optionals keep
    defaults."""
    from mxnet_tpu.ops import registry as r
    # x + weight are leading required params -> tensors; num_hidden/
    # no_bias/flatten have defaults -> left alone
    args, kwargs = auto_spec(r.get_op("FullyConnected"), _PROFILES[0])
    assert len(args) == 2 and not kwargs
    args, kwargs = auto_spec(r.get_op("relu"), _PROFILES[0])
    assert len(args) == 1 and not kwargs


def test_single_op_bench_runs():
    from mxnet_tpu.ops import registry as r
    res = bench_registry_op("add", r.get_op("add"), runs=2, warmup=1)
    assert res["fwd_ms"] > 0 and res["jnp_native_ms"] > 0
