"""Native C++ runtime tests: RecordIO codec + threaded reader
(ref: tests/cpp/ engine/storage unit tests; tests/python/unittest/
test_recordio.py)."""
import os
import struct

import pytest

from mxnet_tpu import _native
from mxnet_tpu.recordio import (MXRecordIO, MXIndexedRecordIO,
                                ThreadedRecordReader, _kMagic)

needs_native = pytest.mark.skipif(not _native.native_available(),
                                  reason="native library not built")

RECORDS = [b"hello", b"x" * 1000,
           struct.pack("<I", _kMagic) + b"tail",           # leading magic
           b"abc" + struct.pack("<I", _kMagic) * 2 + b"e",  # unaligned magic
           b"aaaa" + struct.pack("<I", _kMagic) + b"bbbb",  # aligned magic
           b""]


def _write_all(path, use_native):
    env = {} if use_native else {"MXNET_TPU_NO_NATIVE": "1"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    _native._LIB, _native._TRIED = None, False
    try:
        w = MXRecordIO(path, "w")
        for r in RECORDS:
            w.write(r)
        w.close()
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)
        _native._LIB, _native._TRIED = None, False


def _read_all(path, use_native):
    env = {} if use_native else {"MXNET_TPU_NO_NATIVE": "1"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    _native._LIB, _native._TRIED = None, False
    try:
        r = MXRecordIO(path, "r")
        out = []
        while True:
            rec = r.read()
            if rec is None:
                break
            out.append(rec)
        r.close()
        return out
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)
        _native._LIB, _native._TRIED = None, False


@needs_native
@pytest.mark.parametrize("writer_native,reader_native",
                         [(True, True), (True, False), (False, True)])
def test_roundtrip_cross_impl(tmp_path, writer_native, reader_native):
    """Native and Python implementations are byte-compatible, including
    dmlc split records (payloads containing the magic word)."""
    path = str(tmp_path / "t.rec")
    _write_all(path, writer_native)
    assert _read_all(path, reader_native) == RECORDS


@needs_native
def test_native_writer_splits_on_magic(tmp_path):
    """The native writer emits dmlc-style split records for aligned
    embedded magic words (cflag 1/3), unlike the Python fallback."""
    path = str(tmp_path / "t.rec")
    w = MXRecordIO(path, "w")
    assert w._backend is not None
    payload = b"aaaa" + struct.pack("<I", _kMagic) + b"bbbb"
    w.write(payload)
    w.close()
    with open(path, "rb") as f:
        raw = f.read()
    magic, lrec = struct.unpack_from("<II", raw, 0)
    assert magic == _kMagic
    assert lrec >> 29 == 1  # first chunk of a split record


@needs_native
def test_indexed_native(tmp_path):
    rec, idx = str(tmp_path / "a.rec"), str(tmp_path / "a.idx")
    w = MXIndexedRecordIO(idx, rec, "w")
    for i in range(20):
        w.write_idx(i, b"payload-%03d" % i)
    w.close()
    r = MXIndexedRecordIO(idx, rec, "r")
    assert r.read_idx(13) == b"payload-013"
    assert r.read_idx(0) == b"payload-000"
    assert r.keys == list(range(20))
    r.close()


@needs_native
def test_threaded_reader(tmp_path):
    path = str(tmp_path / "t.rec")
    _write_all(path, True)
    t = ThreadedRecordReader(path)
    assert list(t) == RECORDS
    t.reset()
    assert list(t) == RECORDS
    t.close()


@needs_native
def test_threaded_reader_shuffle_complete(tmp_path):
    path = str(tmp_path / "s.rec")
    w = MXRecordIO(path, "w")
    recs = [b"r%04d" % i for i in range(100)]
    for r in recs:
        w.write(r)
    w.close()
    t = ThreadedRecordReader(path, capacity=16, shuffle=True, seed=3)
    got = list(t)
    t.close()
    assert sorted(got) == sorted(recs)  # every record exactly once
    assert got != recs  # and actually shuffled


@needs_native
def test_error_reporting():
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError, match="cannot open"):
        MXRecordIO("/nonexistent/dir/x.rec", "r")


def test_runtime_feature_flag():
    import mxnet_tpu.runtime as rt
    feats = rt.feature_list()
    names = {f.name for f in feats}
    assert "NATIVE_ENGINE" in names


@needs_native
def test_corrupt_stream_raises(tmp_path):
    """Native reader must raise on corruption, not silently truncate
    (parity with the Python fallback's IOError)."""
    from mxnet_tpu.base import MXNetError
    path = str(tmp_path / "c.rec")
    w = MXRecordIO(path, "w")
    w.write(b"one")
    w.write(b"two")
    w.close()
    with open(path, "r+b") as f:
        f.seek(12)  # corrupt the second record's magic
        f.write(b"\xde\xad\xbe\xef")
    r = MXRecordIO(path, "r")
    assert r.read() == b"one"
    with pytest.raises(MXNetError, match="invalid RecordIO"):
        r.read()
    r.close()


def test_amp_widest_promotes_not_narrows():
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu.contrib import amp
    amp.init()
    try:
        a = mx.nd.array(onp.ones((2, 2), "float32")).astype("bfloat16")
        b = mx.nd.array(onp.ones((2, 2), "float16"))
        # bf16 + fp16 promote to fp32 under jnp rules, never narrow
        assert str((a + b).dtype) == "float32"
    finally:
        amp._reset()


def test_quantize_nested_blocks_distinct_thresholds():
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.contrib import quantization

    class TwoBranch(nn.HybridSequential):
        pass

    outer = nn.HybridSequential()
    b1, b2 = nn.HybridSequential(), nn.HybridSequential()
    b1.add(nn.Dense(4, in_units=4))
    b2.add(nn.Dense(4, in_units=4))
    outer.add(b1, b2)
    outer.initialize()
    x = mx.nd.array(onp.random.randn(8, 4).astype("float32"))
    outer(x)
    col = quantization.CalibrationCollector()
    # both branches' inner layers are locally named "0" but must calibrate
    # under distinct dotted paths
    paths = [path for _, _, path, child
             in quantization._walk_children(outer)
             if isinstance(child, nn.Dense)]
    assert len(set(paths)) == 2


@needs_native
def test_closed_handle_raises_not_crashes(tmp_path):
    path = str(tmp_path / "x.rec")
    w = MXRecordIO(path, "w")
    w.write(b"a")
    w.close()
    with pytest.raises(ValueError, match="closed"):
        w.write(b"b")
    t = ThreadedRecordReader(path)
    t.close()
    with pytest.raises(ValueError, match="closed"):
        t.read()
    with pytest.raises(ValueError, match="closed"):
        t.reset()


def test_python_writer_rejects_oversize(tmp_path):
    import os
    os.environ["MXNET_TPU_NO_NATIVE"] = "1"
    _native._LIB, _native._TRIED = None, False
    try:
        w = MXRecordIO(str(tmp_path / "o.rec"), "w")

        class FakeBuf:
            def __len__(self):
                return 1 << 29
        with pytest.raises(IOError, match="2\\^29"):
            w.write(FakeBuf())
        w.close()
    finally:
        del os.environ["MXNET_TPU_NO_NATIVE"]
        _native._LIB, _native._TRIED = None, False


def test_c_predict_abi(tmp_path):
    """Drive the native MXTPred* ABI end to end through ctypes, the way an
    embedding C application would (ref: include/mxnet/c_predict_api.h
    workflow: Create -> SetInput -> Forward -> GetOutputShape/GetOutput ->
    Reshape -> Free)."""
    import ctypes
    import numpy as np
    import mxnet_tpu as mx

    lib = _native.get_lib()
    if lib is None or not hasattr(lib, "MXTPredCreate"):
        pytest.skip("native predict ABI not built")

    # a small trained-ish graph: y = softmax(W2 relu(W1 x))
    x = mx.sym.var("data")
    h = mx.sym.FullyConnected(x, num_hidden=8, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    out = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(h, num_hidden=3,
                                                     name="fc2"),
                               name="softmax")
    rs = np.random.RandomState(0)
    args = {"fc1_weight": mx.nd.array(rs.rand(8, 4).astype("float32")),
            "fc1_bias": mx.nd.zeros((8,)),
            "fc2_weight": mx.nd.array(rs.rand(3, 8).astype("float32")),
            "fc2_bias": mx.nd.zeros((3,))}
    pfile = str(tmp_path / "net.params")
    mx.nd.save(pfile, {"arg:%s" % k: v for k, v in args.items()})
    with open(pfile, "rb") as f:
        param_blob = f.read()

    sym_json = out.tojson().encode()
    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint32 * 2)(0, 2)
    shape_data = (ctypes.c_uint32 * 2)(5, 4)
    handle = ctypes.c_void_p()
    rc = lib.MXTPredCreate(sym_json, param_blob, len(param_blob), 1, 0,
                           1, keys, indptr, shape_data,
                           ctypes.byref(handle))
    assert rc == 0, lib.MXTGetLastError().decode()

    xin = rs.rand(5, 4).astype("float32")
    rc = lib.MXTPredSetInput(handle, b"data",
                             xin.ctypes.data_as(
                                 ctypes.POINTER(ctypes.c_float)), xin.size)
    assert rc == 0, lib.MXTGetLastError().decode()
    assert lib.MXTPredForward(handle) == 0

    sdata = ctypes.POINTER(ctypes.c_uint32)()
    ndim = ctypes.c_uint32()
    rc = lib.MXTPredGetOutputShape(handle, 0, ctypes.byref(sdata),
                                   ctypes.byref(ndim))
    assert rc == 0, lib.MXTGetLastError().decode()
    shape = tuple(sdata[i] for i in range(ndim.value))
    assert shape == (5, 3)

    got = np.zeros(15, "float32")
    rc = lib.MXTPredGetOutput(handle, 0,
                              got.ctypes.data_as(
                                  ctypes.POINTER(ctypes.c_float)), got.size)
    assert rc == 0, lib.MXTGetLastError().decode()
    got = got.reshape(5, 3)
    np.testing.assert_allclose(got.sum(axis=1), 1.0, rtol=1e-4)

    # reference numerics via the Python predictor
    from mxnet_tpu.predictor import Predictor
    pref = Predictor(out.tojson(), input_shapes={"data": (5, 4)},
                     arg_params=args)
    pref.set_input("data", xin)
    pref.forward()
    np.testing.assert_allclose(got, pref.get_output(0), rtol=1e-4)

    # wrong size errors through the error ring, not a crash
    bad = np.zeros(7, "float32")
    rc = lib.MXTPredGetOutput(handle, 0,
                              bad.ctypes.data_as(
                                  ctypes.POINTER(ctypes.c_float)), bad.size)
    assert rc == -1
    assert b"size mismatch" in lib.MXTGetLastError()

    # reshape: new handle at batch 2, same params
    indptr2 = (ctypes.c_uint32 * 2)(0, 2)
    shape2 = (ctypes.c_uint32 * 2)(2, 4)
    h2 = ctypes.c_void_p()
    rc = lib.MXTPredReshape(1, keys, indptr2, shape2, handle,
                            ctypes.byref(h2))
    assert rc == 0, lib.MXTGetLastError().decode()
    x2 = xin[:2]
    assert lib.MXTPredSetInput(h2, b"data",
                               x2.ctypes.data_as(
                                   ctypes.POINTER(ctypes.c_float)),
                               x2.size) == 0
    assert lib.MXTPredForward(h2) == 0
    got2 = np.zeros(6, "float32")
    assert lib.MXTPredGetOutput(h2, 0,
                                got2.ctypes.data_as(
                                    ctypes.POINTER(ctypes.c_float)),
                                got2.size) == 0
    np.testing.assert_allclose(got2.reshape(2, 3), got[:2], rtol=1e-4)

    assert lib.MXTPredFree(h2) == 0
    assert lib.MXTPredFree(handle) == 0


def test_rec2idx_tool(tmp_path):
    """tools/rec2idx.py builds an .idx enabling random access
    (ref: /root/reference/tools/rec2idx.py IndexCreator)."""
    import subprocess
    import sys
    from mxnet_tpu.recordio import MXRecordIO, MXIndexedRecordIO
    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = MXRecordIO(rec, "w")
    for i in range(25):
        w.write(("record-%03d" % i).encode() * (i + 1))
    w.close()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "rec2idx.py"),
         rec, idx],
        capture_output=True, text=True,
        env=dict(os.environ, PYTHONPATH=repo), timeout=120)
    assert res.returncode == 0, res.stderr
    r = MXIndexedRecordIO(idx, rec, "r")
    assert r.read_idx(17) == b"record-017" * 18
    assert len(r.keys) == 25
