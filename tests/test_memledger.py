"""HBM memory observability (ISSUE 13): the tagged allocation ledger,
compiled-program peak attribution, the leak watchdog, and the OOM
post-mortem.

Pins the acceptance contract:

* ledger tag totals sum to within 5% of ``DeviceStats.bytes_in_use``
  deltas under ``JAX_PLATFORMS=cpu`` (the live_arrays stats fallback),
* a fused step with donated weights+state shows ZERO ledger growth
  across steps; a deliberately retained activation list shows exactly
  the retained bytes (bulked-eager and ``OpDef.inplace`` forms too),
* the synthetic-leak watchdog trips EXACTLY once per episode with a
  dump naming the leaking tag,
* an injected ``storage.alloc`` fault produces an OOM post-mortem shard
  carrying the ledger + modeled peaks + the failed request size,
* ``metrics()['memory']`` is the single owner of allocation accounting
  and counts with profiling off (the account contract).
"""
import gc
import glob
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, profiler, storage
from mxnet_tpu.gluon import nn
from mxnet_tpu._debug import faultpoint, flightrec, memwatch


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHTREC_DIR", str(tmp_path))
    memwatch.reset()
    storage.ledger_reset()
    flightrec.reset_ring()
    profiler._reset()
    yield
    faultpoint.reset()
    memwatch.reset()
    storage.ledger_reset()
    flightrec.reset_ring()
    profiler._reset()


def _settle():
    """Let transient buffers die and the ledger observe it."""
    gc.collect()
    return storage.ledger_metrics()


def _bytes_in_use():
    return sum(s.bytes_in_use for s in storage.stats())


# -- the ledger core ---------------------------------------------------------

def test_register_and_weakref_retire():
    led0 = _settle()
    a = mx.nd.ones((128, 1024))  # registered 'other' via _ctx_place
    led1 = _settle()
    grown = led1["by_tag"]["other"] - led0["by_tag"]["other"]
    assert grown == a.nbytes
    del a
    led2 = _settle()
    assert led2["by_tag"]["other"] == led0["by_tag"]["other"]


def test_pending_retire_marker_validated_and_pruned():
    """A retire that lands while the registration is still pending must
    not leave a stale id marker behind once the buffer dies — CPython
    reuses addresses, and a stale marker would silently swallow some
    future buffer's registration (review fix)."""
    a = mx.nd.ones((16, 16))
    storage.ledger_register(a._data, "workspace")  # pending, undrained
    storage.ledger_retire(a._data)                 # marker, not entry pop
    del a
    gc.collect()
    storage.ledger_metrics()  # drain: dead pending + marker both prune
    with storage._ledger_lock:
        assert storage._retired == {}


def test_non_oom_placement_failure_does_not_dump(tmp_path):
    """An unknown-ctx failure degrades (counted) but must NOT mislabel
    a post-mortem as OOM or burn the dump cap (review fix)."""
    class _BadCtx:
        def jax_device(self):
            raise TypeError("no such device")

    z = mx.nd.zeros((8, 8), ctx=_BadCtx())
    assert z.shape == (8, 8)  # degraded to host, never raised
    assert profiler.metrics()["memory"]["alloc_fallbacks"] == 1
    assert glob.glob(str(tmp_path / "flightrec_r*_oom_*.json")) == []


def test_explicit_retire_is_exactly_once():
    a = mx.nd.ones((64, 64))
    led = _settle()
    base = led["by_tag"]["other"]
    storage.ledger_retire(a._data)
    led = storage.ledger_metrics()
    assert led["by_tag"]["other"] == base - a.nbytes
    # the weakref death later must not double-retire
    del a
    led2 = _settle()
    assert led2["by_tag"]["other"] == base - 64 * 64 * 4


def test_specific_tag_wins_the_slot():
    """A buffer registered 'other' (creation) then re-registered
    'param' (adoption) counts once, under param."""
    a = mx.nd.ones((32, 32))
    storage.ledger_register(a, "param", site="test")
    led = _settle()
    assert led["counts"]["param"] >= 1
    # not double-counted: total growth is one buffer
    assert led["by_tag"]["param"] >= a.nbytes


def test_eager_activation_sites_carry_op_names():
    x = mx.nd.ones((64, 64))
    kept = mx.nd.softmax(x)  # retained activation
    led = _settle()
    assert led["by_tag"]["activation"] >= kept.nbytes
    sites = {s["site"] for s in led["top_sites"]}
    assert "softmax" in sites


def test_ledger_kill_switch():
    prev = storage.set_ledger_enabled(False)
    try:
        a = mx.nd.ones((128, 128))
        led = _settle()
        assert led["by_tag"]["other"] == 0
        assert led["enabled"] is False
        del a
    finally:
        storage.set_ledger_enabled(prev)


# -- retained activations: exact bytes (satellite) ---------------------------

def test_retained_activation_list_shows_exact_bytes():
    x = mx.nd.ones((128, 128))
    _settle()
    base = storage.ledger_metrics()["by_tag"]["activation"]
    retained = [x * (i + 1.0) for i in range(5)]
    led = _settle()
    expect = sum(r.nbytes for r in retained)
    assert led["by_tag"]["activation"] - base == expect
    # dropping the list retires exactly those bytes
    retained.clear()
    led2 = _settle()
    assert led2["by_tag"]["activation"] == base


def test_retained_bulk_activations_exact_bytes():
    from mxnet_tpu import engine
    x = mx.nd.ones((64, 64))
    _settle()
    base = storage.ledger_metrics()["by_tag"]["activation"]
    retained = []
    for _ in range(2):  # second pass replays the cached segment runner
        with engine.bulk(8):
            a = x + 1.0
            b = a * 2.0
        b.wait_to_read()
        retained.append(b)
        del a
    led = _settle()
    expect = sum(r.nbytes for r in retained)
    assert led["by_tag"]["activation"] - base == expect


def test_inplace_opdef_update_keeps_ledger_flat():
    """The OpDef.inplace form (mx.nd.sgd_update's state rebind): the new
    state buffer registers, the replaced one retires — no growth."""
    w = mx.nd.ones((64, 64))
    g = mx.nd.ones((64, 64))
    mom = mx.nd.zeros((64, 64))
    for _ in range(3):
        mx.nd.sgd_mom_update(w, g, mom, out=w, lr=0.1, momentum=0.9)
    w.wait_to_read()
    led0 = _settle()
    total0 = led0["total_bytes"]
    for _ in range(5):
        mx.nd.sgd_mom_update(w, g, mom, out=w, lr=0.1, momentum=0.9)
    w.wait_to_read()
    led1 = _settle()
    assert led1["total_bytes"] == total0


# -- fused step: donation shows zero growth (satellite) ----------------------

def _train_setup(opt="adam"):
    rs = np.random.RandomState(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(16))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), opt,
                            {"learning_rate": 0.01})
    l2 = gluon.loss.L2Loss()
    step = gluon.train_step(net, lambda o, t: l2(o, t), trainer)
    bx = mx.nd.array(rs.rand(32, 32).astype("float32"))
    by = mx.nd.array(rs.rand(32, 16).astype("float32"))
    return step, bx, by


def test_fused_step_zero_ledger_growth_across_steps():
    step, bx, by = _train_setup()
    for _ in range(6):  # warm + compile; params/grads/opt_state settle
        step(bx, by, batch_size=32)
    led0 = _settle()
    for _ in range(10):
        step(bx, by, batch_size=32)
    assert step.last_mode == "fused"
    led1 = _settle()
    assert led1["total_bytes"] == led0["total_bytes"], (led0, led1)
    # and the long-lived tags are populated (not trivially zero)
    assert led1["by_tag"]["param"] > 0
    assert led1["by_tag"]["grad"] > 0
    assert led1["by_tag"]["opt_state"] > 0


# -- acceptance: tag totals vs DeviceStats deltas (5%) -----------------------

def test_ledger_sums_within_5pct_of_device_bytes_delta():
    """Under JAX_PLATFORMS=cpu the live_arrays stats fallback makes
    DeviceStats.bytes_in_use real; a train_step run's ledger growth must
    explain the device-bytes growth to within 5%."""
    _settle()
    base_dev = _bytes_in_use()
    base_led = storage.ledger_metrics()["total_bytes"]
    step, bx, by = _train_setup()
    for _ in range(6):
        step(bx, by, batch_size=32)
    assert step.last_mode == "fused"
    keep = [mx.nd.softmax(bx) for _ in range(4)]  # retained activations
    gc.collect()
    dev_delta = _bytes_in_use() - base_dev
    led_delta = storage.ledger_metrics()["total_bytes"] - base_led
    assert dev_delta > 0
    assert abs(led_delta - dev_delta) <= 0.05 * dev_delta, \
        (led_delta, dev_delta)
    del keep


def test_cpu_device_stats_synthesized_from_live_arrays():
    before = _bytes_in_use()
    big = mx.nd.ones((512, 1024))
    after = _bytes_in_use()
    assert after - before >= big.nbytes
    del big


# -- compiled-program peak attribution + headroom ----------------------------

def test_fused_step_memory_analysis_in_compile_registry():
    step, bx, by = _train_setup()
    for _ in range(4):
        step(bx, by, batch_size=32)
    assert step.last_mode == "fused"
    m = profiler.metrics()
    mem = m["compile"]["fused_step"].get("memory")
    assert mem, "fused-step AOT compile did not record memory_analysis"
    # peak = args + out + temp - alias: under donation (off-CPU) the
    # weight/state outputs REUSE argument buffers and alias_bytes
    # records the overlap; on this CPU run alias is 0
    assert mem["peak_bytes"] == (mem["argument_bytes"]
                                 + mem["output_bytes"]
                                 + mem["temp_bytes"]
                                 - mem["alias_bytes"])
    assert mem["argument_bytes"] > 0
    hr = m["memory"]["headroom"]
    assert hr["modeled_peak_bytes"] == mem["peak_bytes"]
    # dumps() renders the Memory table
    text = profiler.dumps()
    assert "Memory (modeled)" in text
    assert "memory ledger" in text


def test_headroom_gauge_emitted_per_step_while_profiling(tmp_path):
    step, bx, by = _train_setup()
    for _ in range(4):
        step(bx, by, batch_size=32)
    assert step.last_mode == "fused"
    fn = str(tmp_path / "prof.json")
    profiler.set_config(filename=fn, xprof=False)
    profiler.set_state("run")
    try:
        for _ in range(3):
            step(bx, by, batch_size=32)
    finally:
        profiler.set_state("stop")
    profiler.dump()
    with open(fn) as f:
        events = json.load(f)["traceEvents"]
    gauges = [e for e in events if e.get("name") == "memory.headroom"]
    assert gauges, "no per-step memory.headroom gauge"
    assert gauges[0]["args"]["modeled_peak_bytes"] > 0


# -- leak watchdog -----------------------------------------------------------

def test_leak_watchdog_trips_once_and_names_tag(tmp_path):
    memwatch.configure(window=4, warmup_s=0.0, min_bytes=1 << 20,
                       poll_s=100)
    leak = []
    trips = []
    for _ in range(10):
        leak.append(mx.nd.ones((256, 1024)))  # 1 MiB each, retained
        trips.append(memwatch.check_now())
    assert sum(trips) == 1, trips  # exactly one dump per episode
    st = memwatch.stats()
    assert st["trips"] == 1 and st["dumps"] == 1
    dumps = glob.glob(str(tmp_path / "flightrec_r*_memleak_*.json"))
    assert len(dumps) == 1
    with open(dumps[0]) as f:
        d = json.load(f)
    info = d["metadata"]["trigger_info"]
    assert info["grown_bytes"] >= 3 * (1 << 20)
    assert info["top_tags"][0]["tag"] == "other"
    assert "slope_bytes_per_s" in info
    # the bundled metrics carry the full ledger too
    assert d["metadata"]["metrics"]["memory"]["ledger"]["total_bytes"] > 0


def test_leak_watchdog_rearms_after_recede(tmp_path):
    memwatch.configure(window=3, warmup_s=0.0, min_bytes=1 << 20,
                       poll_s=100)
    leak = []
    trips = 0
    for _ in range(6):
        leak.append(mx.nd.ones((256, 1024)))
        trips += int(memwatch.check_now())
    assert trips == 1
    # episode ends: usage recedes, window refills, second leak re-trips
    leak.clear()
    gc.collect()
    for _ in range(3):
        memwatch.check_now()
    leak2 = []
    for _ in range(6):
        leak2.append(mx.nd.ones((256, 1024)))
        trips += int(memwatch.check_now())
    assert trips == 2
    assert memwatch.stats()["trips"] == 2


def test_leak_watchdog_ignores_churn():
    """Non-monotone usage (alloc/free churn) never trips."""
    memwatch.configure(window=4, warmup_s=0.0, min_bytes=1 << 20,
                       poll_s=100)
    for i in range(12):
        a = mx.nd.ones((512, 1024))  # 2 MiB, dropped each iteration
        assert memwatch.check_now() is False
        del a
        gc.collect()
    assert memwatch.stats()["trips"] == 0


def test_memwatch_warmup_blocks_arming():
    memwatch.configure(window=2, warmup_s=3600.0, min_bytes=1,
                       poll_s=100)
    leak = [mx.nd.ones((256, 1024))]
    for _ in range(5):
        leak.append(mx.nd.ones((256, 1024)))
        assert memwatch.check_now() is False


# -- OOM post-mortem ---------------------------------------------------------

def test_injected_alloc_fault_writes_oom_shard(tmp_path):
    step, bx, by = _train_setup()
    for _ in range(4):
        step(bx, by, batch_size=32)  # modeled peaks exist
    faultpoint.configure("storage.alloc=raise:RuntimeError@n=1")
    z = mx.nd.zeros((128, 128))  # degrades to host, never raises
    assert z.shape == (128, 128)
    shards = glob.glob(str(tmp_path / "flightrec_r*_oom_*.json"))
    assert len(shards) == 1
    with open(shards[0]) as f:
        d = json.load(f)
    info = d["metadata"]["trigger_info"]
    assert info["where"] == "storage.alloc"
    assert info["requested_bytes"] == 128 * 128 * 4
    assert "ledger_by_tag" in info
    metrics = d["metadata"]["metrics"]
    assert "ledger" in metrics["memory"]         # the full ledger
    assert metrics["compile"]["fused_step"]["memory"]["peak_bytes"] > 0
    assert metrics["memory"]["alloc_fallbacks"] == 1


def test_is_oom_classifier():
    assert memwatch.is_oom(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                     "1073741824 bytes"))
    assert memwatch.is_oom(ValueError("Out of memory while trying"))
    assert not memwatch.is_oom(ValueError("shape mismatch"))
    assert not memwatch.is_oom(None)


def test_oom_excepthook_upgrade_and_no_double_dump(tmp_path):
    """An unhandled OOM-looking exception dumps with trigger 'oom'; one
    already reported via oom_report yields NO second shard."""
    exc = RuntimeError("RESOURCE_EXHAUSTED: out of memory")
    flightrec._sys_excepthook(RuntimeError, exc, None)
    shards = glob.glob(str(tmp_path / "flightrec_r*_oom_*.json"))
    assert len(shards) == 1
    exc2 = RuntimeError("RESOURCE_EXHAUSTED: out of memory again")
    memwatch.oom_report(exc2, requested_bytes=7, where="test")
    flightrec._sys_excepthook(RuntimeError, exc2, None)
    shards = sorted(glob.glob(str(tmp_path / "flightrec_r*_oom_*.json")))
    assert len(shards) == 2  # one per exception, never two for one
    # a NON-oom exception still dumps under the plain trigger
    flightrec._sys_excepthook(ValueError, ValueError("boom"), None)
    assert glob.glob(str(tmp_path / "flightrec_r*_exception_*.json"))


# -- metrics()['memory'] single ownership (satellite) ------------------------

def test_alloc_fallbacks_counted_with_profiling_off():
    assert not profiler.is_running()
    faultpoint.configure("storage.alloc=raise:RuntimeError@n=2")
    mx.nd.zeros((8, 8))
    mx.nd.zeros((8, 8))
    faultpoint.reset()
    m = profiler.metrics()
    assert m["memory"]["alloc_fallbacks"] == 2
    # single owner: the old generic counter namespace no longer has it
    assert "storage.alloc_fallbacks" not in m["counters"]


def test_empty_cache_counted_with_profiling_off():
    assert not profiler.is_running()
    before = profiler.metrics()["memory"]["empty_cache_calls"]
    storage.empty_cache()
    storage.release_all()
    m = profiler.metrics()
    assert m["memory"]["empty_cache_calls"] == before + 2


def test_memory_section_shape_and_prometheus():
    a = mx.nd.ones((64, 64))
    _settle()
    m = profiler.metrics()
    mem = m["memory"]
    assert set(storage.LEDGER_TAGS) == set(mem["ledger"]["by_tag"])
    assert {"alloc_fallbacks", "empty_cache_calls",
            "ledger"} <= set(mem)
    assert "memwatch" in mem
    text = profiler.prometheus_text()
    assert 'mxtpu_memory_ledger_bytes{rank="0",tag="other"}' in text
    assert "mxtpu_memory_alloc_events_total" in text
    del a


def test_ledger_series_in_memory_lane(tmp_path):
    """profile_memory runs emit the per-tag memory.ledger Counter
    series in the memory lane (sampler-daemon fed)."""
    import time
    fn = str(tmp_path / "prof.json")
    profiler.set_config(filename=fn, profile_memory=True, xprof=False)
    profiler.set_state("run")
    try:
        keep = mx.nd.ones((128, 128))
        time.sleep(0.4)  # let the sampler daemon tick
    finally:
        profiler.set_state("stop")
        profiler.set_config(profile_memory=False)
    profiler.dump()
    with open(fn) as f:
        events = json.load(f)["traceEvents"]
    series = [e for e in events if e.get("name") == "memory.ledger"]
    assert series, "no memory.ledger counter series"
    assert series[-1]["tid"] == profiler.LANES["memory"]
    assert any(v > 0 for v in series[-1]["args"].values())
    del keep
