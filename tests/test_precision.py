"""Matmul precision policy (mxnet_tpu/precision.py; VERDICT r4 item 3).

The reference's fp32 dot/conv is true fp32 via BLAS dispatch
(ref: 3rdparty/mshadow/mshadow/dot_engine-inl.h); on TPU the default MXU
path multiplies in bf16, so the policy surface here is what restores the
reference's accuracy contract. CPU CI can only prove the PLUMBING (env
knob, global setter, context scoping, per-call kwarg through nd/sym);
the numeric effect is measured on the real chip by the sweep's
dot_policy_float32 control (benchmark/tpu_numerics.py, gated in bench).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import precision

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _restore_policy():
    prev = mx.get_matmul_precision()
    yield
    mx.set_matmul_precision(prev)


def test_default_policy():
    assert mx.get_matmul_precision() == "default"


def test_set_returns_previous_and_roundtrips():
    prev = mx.set_matmul_precision("float32")
    assert prev == "default"
    assert mx.get_matmul_precision() == "float32"
    assert mx.set_matmul_precision("highest") == "float32"
    assert mx.set_matmul_precision(None) == "highest"
    assert mx.get_matmul_precision() == "default"


def test_context_manager_scopes_and_restores():
    with mx.matmul_precision("float32"):
        assert mx.get_matmul_precision() == "float32"
        with mx.matmul_precision("highest"):
            assert mx.get_matmul_precision() == "highest"
        assert mx.get_matmul_precision() == "float32"
    assert mx.get_matmul_precision() == "default"


def test_env_knob_applies_at_import():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    env[precision.ENV_VAR] = "highest"
    res = subprocess.run(
        [sys.executable, "-c",
         "import mxnet_tpu as mx; "
         "assert mx.get_matmul_precision() == 'highest', "
         "mx.get_matmul_precision()"],
        env=env, capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr


@pytest.mark.parametrize("op_call", [
    lambda a, b, p: mx.nd.dot(a, b, precision=p),
    lambda a, b, p: mx.nd.batch_dot(
        a.reshape(1, *a.shape), b.reshape(1, *b.shape), precision=p),
    lambda a, b, p: mx.nd.linalg_gemm2(a, b, precision=p),
    lambda a, b, p: mx.nd.FullyConnected(
        a, b, num_hidden=b.shape[0], no_bias=True, precision=p),
])
def test_per_call_precision_kwarg(op_call):
    """Every matmul-family op takes precision= and (on CPU, where every
    precision is true fp32) matches the default result exactly."""
    rs = np.random.RandomState(3)
    a = mx.nd.array(rs.rand(16, 16).astype("float32"))
    b = mx.nd.array(rs.rand(16, 16).astype("float32"))
    base = op_call(a, b, None).asnumpy()
    for p in ("float32", "highest"):
        np.testing.assert_array_equal(op_call(a, b, p).asnumpy(), base)


def test_conv_deconv_precision_kwarg():
    rs = np.random.RandomState(4)
    x = mx.nd.array(rs.rand(2, 3, 8, 8).astype("float32"))
    w = mx.nd.array(rs.rand(4, 3, 3, 3).astype("float32"))
    base = mx.nd.Convolution(x, w, kernel=(3, 3), num_filter=4,
                             no_bias=True).asnumpy()
    hi = mx.nd.Convolution(x, w, kernel=(3, 3), num_filter=4,
                           no_bias=True, precision="highest").asnumpy()
    np.testing.assert_array_equal(base, hi)
    wd = mx.nd.array(rs.rand(3, 4, 3, 3).astype("float32"))
    d = mx.nd.Deconvolution(x, wd, kernel=(3, 3), num_filter=4,
                            precision="float32")
    assert d.shape == (2, 4, 10, 10)


def test_symbol_path_accepts_precision():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    y = mx.sym.dot(a, b, precision="highest")
    rs = np.random.RandomState(5)
    av = mx.nd.array(rs.rand(8, 8).astype("float32"))
    bv = mx.nd.array(rs.rand(8, 8).astype("float32"))
    ex = y.bind(mx.cpu(), {"a": av, "b": bv})
    np.testing.assert_array_equal(ex.forward()[0].asnumpy(),
                                  mx.nd.dot(av, bv).asnumpy())


def test_policy_affects_jit_cache_key():
    """Entering the context must retrace: the policy is part of the
    lowered HLO, so a cached default-precision executable may not be
    reused for a float32-policy call."""
    import jax
    import jax.numpy as jnp

    traces = []

    @jax.jit
    def f(x):
        traces.append(1)
        return jnp.matmul(x, x)

    x = jnp.ones((8, 8), jnp.float32)
    f(x)
    with mx.matmul_precision("highest"):
        f(x)
    assert len(traces) == 2
