"""Sparse NDArray API, mx.image augmenters, and dlpack interchange.

Ports the strategies of tests/python/unittest/test_sparse_ndarray.py,
test_image.py and test_dlpack.py against the TPU-native implementations
(sparse is dense-backed with storage-format API parity — XLA has no
sparse tensors; docs/PARITY.md)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


# ---------------------------------------------------------------------------
# sparse
# ---------------------------------------------------------------------------

def test_row_sparse_roundtrip():
    dense = np.zeros((5, 3), "float32")
    dense[1] = 1.0
    dense[4] = 2.0
    rs = nd.sparse.row_sparse_array(dense)
    assert rs.stype == "row_sparse"
    np.testing.assert_allclose(rs.indices.asnumpy(), [1, 4])
    np.testing.assert_allclose(rs.data.asnumpy(),
                               [[1, 1, 1], [2, 2, 2]])
    back = rs.tostype("default")
    assert back.stype == "default"
    np.testing.assert_allclose(back.asnumpy(), dense)


def test_row_sparse_from_indices_values():
    vals = np.array([[1.0, 2.0]], "float32")
    rs = nd.sparse.row_sparse_array((vals, [2]), shape=(4, 2))
    d = rs.asnumpy()
    np.testing.assert_allclose(d[2], [1, 2])
    np.testing.assert_allclose(d[[0, 1, 3]], 0)


def test_csr_roundtrip():
    dense = np.array([[0, 1, 0], [2, 0, 3]], "float32")
    csr = nd.sparse.csr_matrix(dense)
    assert csr.stype == "csr"
    np.testing.assert_allclose(csr.asnumpy(), dense)
    # scipy-style components
    np.testing.assert_allclose(csr.indptr.asnumpy(), [0, 1, 3])
    np.testing.assert_allclose(csr.indices.asnumpy(), [1, 0, 2])
    np.testing.assert_allclose(csr.data.asnumpy(), [1, 2, 3])


def test_sparse_retain():
    dense = np.arange(12, dtype="float32").reshape(4, 3)
    rs = nd.sparse.row_sparse_array(dense)
    kept = rs.retain(nd.array(np.array([0, 2], "float32")))
    out = kept.asnumpy()
    np.testing.assert_allclose(out[[0, 2]], dense[[0, 2]])
    np.testing.assert_allclose(out[[1, 3]], 0)


def test_sparse_elemwise_and_dot():
    dense = np.random.RandomState(0).rand(4, 3).astype("float32")
    rs = nd.sparse.row_sparse_array(dense)
    # sparse participates in ordinary ops (dense compute under the hood)
    s = (rs * 2.0).asnumpy()
    np.testing.assert_allclose(s, dense * 2, rtol=1e-6)
    w = nd.array(np.ones((3, 2), "float32"))
    np.testing.assert_allclose(nd.dot(rs, w).asnumpy(), dense @ np.ones(
        (3, 2)), rtol=1e-5)


def test_sparse_zeros_and_cast_storage():
    z = nd.sparse.zeros("row_sparse", (3, 2))
    assert z.stype == "row_sparse" and float(z.asnumpy().sum()) == 0
    d = nd.array(np.eye(3, dtype="float32"))
    c = nd.sparse.cast_storage(d, "csr")
    assert c.stype == "csr"
    np.testing.assert_allclose(c.asnumpy(), np.eye(3))


# ---------------------------------------------------------------------------
# image
# ---------------------------------------------------------------------------

def _img(h=8, w=10, c=3):
    return nd.array(np.random.RandomState(0).randint(
        0, 255, (h, w, c)).astype("float32"))


def test_imresize_and_crops():
    img = _img()
    r = mx.image.imresize(img, 5, 4)
    assert r.shape == (4, 5, 3)
    fc = mx.image.fixed_crop(img, 2, 1, 4, 4)
    assert fc.shape == (4, 4, 3)
    np.testing.assert_allclose(fc.asnumpy(),
                               img.asnumpy()[1:5, 2:6], rtol=1e-5)
    cc, rect = mx.image.center_crop(img, (4, 4))
    assert cc.shape == (4, 4, 3) and len(rect) == 4
    rc, _ = mx.image.random_crop(img, (4, 4))
    assert rc.shape == (4, 4, 3)


def test_resize_short():
    img = _img(8, 10)
    out = mx.image.resize_short(img, 4)
    assert min(out.shape[:2]) == 4


def test_color_normalize():
    img = nd.array(np.full((2, 2, 3), 10.0, "float32"))
    out = mx.image.color_normalize(img, mx.nd.array([1.0, 1.0, 1.0]),
                                   mx.nd.array([3.0, 3.0, 3.0]))
    np.testing.assert_allclose(out.asnumpy(), 3.0, rtol=1e-5)


def test_augmenter_pipeline_and_dumps():
    aug = mx.image.CenterCropAug((4, 4))
    out = aug(_img())
    assert out.shape == (4, 4, 3)
    s = aug.dumps()
    assert "CenterCropAug".lower() in s.lower() or "4" in s


def test_create_augmenter_list():
    augs = mx.image.CreateAugmenter(data_shape=(3, 4, 4), resize=6,
                                    rand_crop=True, mean=True)
    img = _img()
    for a in augs:
        img = a(img)
    assert img.shape[2] == 3


# ---------------------------------------------------------------------------
# dlpack
# ---------------------------------------------------------------------------

def test_dlpack_roundtrip():
    x = nd.array(np.arange(6, dtype="float32").reshape(2, 3))
    back = np.from_dlpack(x)        # NDArray implements __dlpack__
    np.testing.assert_allclose(np.asarray(back), x.asnumpy())


def test_dlpack_to_jax_and_back():
    import jax.numpy as jnp
    x = nd.array(np.arange(4, dtype="float32"))
    j = jnp.from_dlpack(x.dlpack)   # .dlpack is the protocol carrier
    np.testing.assert_allclose(np.asarray(j), [0, 1, 2, 3])
