"""NDArray API tests (model: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3) and a.dtype == np.float32
    b = nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    c = nd.full((2, 2), 7.0)
    assert_almost_equal(c, np.full((2, 2), 7.0, np.float32))
    d = nd.array([[1, 2], [3, 4]], dtype="float32")
    assert d.shape == (2, 2)
    e = nd.arange(0, 10, 2)
    assert_almost_equal(e, np.arange(0, 10, 2, dtype=np.float32))
    assert nd.eye(3).asnumpy().trace() == 3.0


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[5.0, 6.0], [7.0, 8.0]])
    assert_almost_equal(a + b, np.array([[6, 8], [10, 12]], np.float32))
    assert_almost_equal(a - b, -np.array([[4, 4], [4, 4]], np.float32))
    assert_almost_equal(a * 2 + 1, a.asnumpy() * 2 + 1)
    assert_almost_equal(2 / a, 2 / a.asnumpy())
    assert_almost_equal(a ** 2, a.asnumpy() ** 2)
    assert_almost_equal(-a, -a.asnumpy())
    assert_almost_equal(abs(-a), a.asnumpy())
    assert_almost_equal(nd.maximum(a, 2.5), np.maximum(a.asnumpy(), 2.5))


def test_inplace():
    a = nd.ones((3,))
    a += 2
    assert_almost_equal(a, np.full((3,), 3.0, np.float32))
    a *= 2
    assert_almost_equal(a, np.full((3,), 6.0, np.float32))
    a /= 3
    assert_almost_equal(a, np.full((3,), 2.0, np.float32))


def test_comparison():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert_almost_equal(a > b, np.array([0, 0, 1], np.float32))
    assert_almost_equal(a == b, np.array([0, 1, 0], np.float32))
    assert_almost_equal(a <= b, np.array([1, 1, 0], np.float32))


def test_indexing():
    a = nd.array(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    assert_almost_equal(a[0], a.asnumpy()[0])
    assert_almost_equal(a[1, 2], a.asnumpy()[1, 2])
    assert_almost_equal(a[:, 1:3], a.asnumpy()[:, 1:3])
    assert float(a[1, 2, 3].asscalar()) == 23.0
    idx = nd.array([0, 1], dtype="int32")
    assert_almost_equal(a[idx], a.asnumpy()[[0, 1]])


def test_setitem():
    a = nd.zeros((3, 3))
    a[1] = 1.0
    a[2, 2] = 5.0
    expect = np.zeros((3, 3), np.float32)
    expect[1] = 1
    expect[2, 2] = 5
    assert_almost_equal(a, expect)
    a[0:2, 0] = nd.array([7.0, 8.0])
    expect[0:2, 0] = [7, 8]
    assert_almost_equal(a, expect)


def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((4, -1)).shape == (4, 6)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)
    assert a.reshape((2, 3, 2, 2)).shape == (2, 3, 2, 2)


def test_shape_ops():
    a = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert a.T.shape == (3, 2)
    assert a.expand_dims(0).shape == (1, 2, 3)
    assert nd.concat(a, a, dim=0).shape == (4, 3)
    assert nd.stack(a, a, axis=0).shape == (2, 2, 3)
    parts = nd.split(a, num_outputs=3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1)
    assert nd.tile(a, reps=(2, 2)).shape == (4, 6)
    assert a.flatten().shape == (2, 3)
    assert nd.flip(a, axis=1).asnumpy()[0, 0] == 2.0
    assert nd.pad(a.reshape(1, 1, 2, 3), mode="constant",
                  pad_width=(0, 0, 0, 0, 1, 1, 1, 1)).shape == (1, 1, 4, 5)


def test_reduce():
    a = nd.array(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    assert_almost_equal(a.sum(), a.asnumpy().sum())
    assert_almost_equal(a.sum(axis=1), a.asnumpy().sum(1))
    assert_almost_equal(a.mean(axis=(0, 2)), a.asnumpy().mean((0, 2)))
    assert_almost_equal(a.max(axis=2, keepdims=True), a.asnumpy().max(2, keepdims=True))
    assert_almost_equal(nd.sum(a, axis=1, exclude=True), a.asnumpy().sum((0, 2)))
    assert_almost_equal(a.norm(), np.linalg.norm(a.asnumpy().ravel()))
    assert float(a.argmax().asscalar()) == 23


def test_dot():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 5).astype(np.float32))
    assert_almost_equal(nd.dot(a, b), a.asnumpy() @ b.asnumpy(), rtol=1e-4)
    assert_almost_equal(nd.dot(a, b.T, transpose_b=True),
                        a.asnumpy() @ b.asnumpy(), rtol=1e-4)
    x = nd.array(np.random.rand(2, 3, 4).astype(np.float32))
    y = nd.array(np.random.rand(2, 4, 5).astype(np.float32))
    assert_almost_equal(nd.batch_dot(x, y),
                        np.matmul(x.asnumpy(), y.asnumpy()), rtol=1e-4)


def test_astype_copy_context():
    a = nd.ones((2, 2))
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.copy()
    c += 1
    assert_almost_equal(a, np.ones((2, 2), np.float32))
    d = a.as_in_context(mx.cpu(0))
    assert d.context.device_type == "cpu"


def test_save_load(tmp_path):
    f = str(tmp_path / "nd.bin")
    a = nd.array([[1.0, 2.0]])
    nd.save(f, {"w": a, "b": a * 2})
    loaded = nd.load(f)
    assert set(loaded) == {"w", "b"}
    assert_almost_equal(loaded["b"], a.asnumpy() * 2)
    nd.save(f, [a, a])
    assert len(nd.load(f)) == 2


def test_take_pick_onehot():
    a = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    idx = nd.array([2, 0], dtype="int32")
    assert_almost_equal(nd.take(a, idx, axis=0), a.asnumpy()[[2, 0]])
    p = nd.pick(a, nd.array([1, 2, 3], dtype="int32"), axis=1)
    assert_almost_equal(p, np.array([1, 6, 11], np.float32))
    oh = nd.one_hot(idx, depth=4)
    assert oh.shape == (2, 4) and float(oh.asnumpy()[0, 2]) == 1.0


def test_ordering():
    a = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    assert_almost_equal(nd.sort(a, axis=1), np.sort(a.asnumpy(), 1))
    assert_almost_equal(nd.argsort(a, axis=1), np.argsort(a.asnumpy(), 1).astype(np.float32))
    vals = nd.topk(a, k=2, axis=1, ret_typ="value")
    assert_almost_equal(vals, np.array([[3, 2], [5, 4]], np.float32))


def test_wait_and_scalar():
    a = nd.ones((2,))
    a.wait_to_read()
    assert float((a.sum()).asscalar()) == 2.0
    mx.waitall()


def test_bool_len_iter():
    a = nd.array([1.0])
    assert bool(a)
    b = nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert len(b) == 2
    rows = [r for r in b]
    assert rows[1].shape == (2,)
    with pytest.raises(ValueError):
        bool(b)


def test_save_load_reference_binary_format(tmp_path):
    """The .params container must be byte-compatible with the reference's
    MXNDArraySave (ref: src/ndarray/ndarray.cc:1829 list writer, :1603 V2
    record): uint64 0x112 header, V2 magic per record, int32 ndim +
    int64 dims, cpu context, mshadow type flag, raw bytes."""
    import struct
    f = str(tmp_path / "golden.params")
    # hand-build the file from the C++ spec, independent of the writer
    w = np.arange(6, dtype="float32").reshape(2, 3)
    b = np.array([1, 2], dtype="int64")
    with open(f, "wb") as fh:
        fh.write(struct.pack("<QQ", 0x112, 0))
        fh.write(struct.pack("<Q", 2))
        for a, flag in ((w, 0), (b, 6)):
            fh.write(struct.pack("<I", 0xF993fac9))
            fh.write(struct.pack("<i", 0))
            fh.write(struct.pack("<i", a.ndim))
            fh.write(struct.pack("<%dq" % a.ndim, *a.shape))
            fh.write(struct.pack("<ii", 1, 0))
            fh.write(struct.pack("<i", flag))
            fh.write(a.tobytes())
        fh.write(struct.pack("<Q", 2))
        for name in ("arg:weight", "arg:bias"):
            nb = name.encode()
            fh.write(struct.pack("<Q", len(nb)))
            fh.write(nb)
    loaded = nd.load(f)
    assert set(loaded) == {"arg:weight", "arg:bias"}
    np.testing.assert_array_equal(loaded["arg:weight"].asnumpy(), w)
    np.testing.assert_array_equal(loaded["arg:bias"].asnumpy(), b)
    assert str(loaded["arg:bias"].dtype) == "int64" or \
        str(loaded["arg:bias"].dtype) == "int32"  # canonical 32-bit jax

    # and the writer round-trips through the same byte layout
    f2 = str(tmp_path / "rt.params")
    nd.save(f2, {"arg:weight": loaded["arg:weight"]})
    with open(f2, "rb") as fh:
        header, _ = struct.unpack("<QQ", fh.read(16))
        count, = struct.unpack("<Q", fh.read(8))
        magic, = struct.unpack("<I", fh.read(4))
    assert header == 0x112 and count == 1 and magic == 0xF993fac9


def test_save_load_v3_npshape_record(tmp_path):
    """V3 (np-shape) records load identically (ref: ndarray.cc:1601)."""
    import struct
    f = str(tmp_path / "v3.params")
    a = np.float32(7.0).reshape(())  # zero-dim: the V3 case
    with open(f, "wb") as fh:
        fh.write(struct.pack("<QQ", 0x112, 0))
        fh.write(struct.pack("<Q", 1))
        fh.write(struct.pack("<I", 0xF993faca))
        fh.write(struct.pack("<i", 0))
        fh.write(struct.pack("<i", 0))
        fh.write(struct.pack("<ii", 1, 0))
        fh.write(struct.pack("<i", 0))
        fh.write(a.tobytes())
        fh.write(struct.pack("<Q", 0))
    out = nd.load(f)
    assert isinstance(out, list) and len(out) == 1  # reference semantics
    assert out[0].shape == ()
    assert float(out[0].asnumpy()) == 7.0


def test_save_bfloat16_stored_as_f32(tmp_path):
    f = str(tmp_path / "bf.params")
    a = nd.ones((2, 2)).astype("bfloat16")
    nd.save(f, {"w": a})
    out = nd.load(f)
    assert str(out["w"].dtype) == "float32"
    assert (out["w"].asnumpy() == 1.0).all()


def test_load_unnamed_always_list(tmp_path):
    """Reference mx.nd.load returns a LIST for unnamed records, even one."""
    f = str(tmp_path / "one.params")
    a = nd.array(np.ones((3, 2), "float32"))
    nd.save(f, [a])
    out = nd.load(f)
    assert isinstance(out, list) and len(out) == 1
    assert out[0].shape == (3, 2)


def test_save_bool_and_reject_unknown_dtype(tmp_path):
    f = str(tmp_path / "b.params")
    m = nd.array(np.array([True, False]))  # bool -> type flag 7
    nd.save(f, {"mask": m})
    out = nd.load(f)
    assert str(out["mask"].dtype) == "bool"
    assert out["mask"].asnumpy().tolist() == [True, False]


def test_load_truncated_raises_valueerror(tmp_path):
    f = tmp_path / "short.params"
    f.write_bytes(b"\x12\x01")
    import pytest
    with pytest.raises(ValueError, match="truncated|not an NDArray"):
        nd.load(str(f))


def test_boolean_mask_differentiable():
    """Regression: boolean_mask must record on the autograd tape."""
    from mxnet_tpu import autograd
    x = nd.array(np.arange(6, dtype="float32").reshape(3, 2))
    x.attach_grad()
    m = nd.array(np.array([1, 0, 1], "int32"))
    with autograd.record():
        y = nd.boolean_mask(x, m)
        loss = (y * 2).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               [[2, 2], [0, 0], [2, 2]])


class TestContribControlFlow:
    """ref: tests/python/unittest/test_contrib_control_flow.py."""

    def test_foreach_cumsum(self):
        data = nd.array(np.arange(12, dtype="float32").reshape(4, 3))
        out, final = nd.contrib.foreach(
            lambda x, s: (x + s, x + s), data, nd.zeros((3,)))
        expect = np.cumsum(np.arange(12).reshape(4, 3), axis=0)
        np.testing.assert_allclose(out.asnumpy(), expect)
        np.testing.assert_allclose(final.asnumpy(), expect[-1])

    def test_foreach_multi_state_and_grad(self):
        from mxnet_tpu import autograd
        x = nd.array(np.ones((3, 2), "float32"))
        x.attach_grad()
        with autograd.record():
            out, _ = nd.contrib.foreach(lambda t, s: (t * 2.0, s), x, [])
            out.sum().backward()
        np.testing.assert_allclose(x.grad.asnumpy(), np.full((3, 2), 2.0))

    def test_while_loop(self):
        outs, final_vars = nd.contrib.while_loop(
            cond=lambda i, s: i < 5,
            func=lambda i, s: ([i], [i + 1, s + i]),
            loop_vars=[nd.array([1.0]), nd.array([0.0])],
            max_iterations=10)
        assert outs[0].shape == (10, 1)  # padded to max_iterations
        assert float(final_vars[1].asnumpy()[0]) == 10.0  # 1+2+3+4
        np.testing.assert_allclose(outs[0].asnumpy()[:4, 0],
                                   [1, 2, 3, 4])

    def test_cond(self):
        t = nd.contrib.cond(nd.array([2.0]).sum() > 1,
                            lambda: nd.ones((2,)), lambda: nd.zeros((2,)))
        assert t.asnumpy().tolist() == [1.0, 1.0]
        f = nd.contrib.cond(nd.array([0.0]).sum() > 1,
                            lambda: nd.ones((2,)), lambda: nd.zeros((2,)))
        assert f.asnumpy().tolist() == [0.0, 0.0]
