"""Random op tests (model: tests/python/unittest/test_random.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_seed_reproducible():
    mx.random.seed(42)
    a = nd.random.uniform(shape=(100,)).asnumpy()
    mx.random.seed(42)
    b = nd.random.uniform(shape=(100,)).asnumpy()
    assert np.array_equal(a, b)
    c = nd.random.uniform(shape=(100,)).asnumpy()
    assert not np.array_equal(b, c)


def test_uniform_range():
    x = nd.random.uniform(low=2.0, high=5.0, shape=(1000,)).asnumpy()
    assert x.min() >= 2.0 and x.max() < 5.0
    assert abs(x.mean() - 3.5) < 0.2


def test_normal_moments():
    x = nd.random.normal(loc=1.0, scale=2.0, shape=(20000,)).asnumpy()
    assert abs(x.mean() - 1.0) < 0.1
    assert abs(x.std() - 2.0) < 0.1


def test_randint():
    x = nd.random.randint(0, 10, shape=(1000,)).asnumpy()
    assert x.min() >= 0 and x.max() <= 9
    assert x.dtype == np.int32


def test_sample_parameterized():
    mu = nd.array([0.0, 10.0])
    sigma = nd.array([1.0, 1.0])
    x = nd.random.normal(mu, sigma, shape=(500,)).asnumpy()
    assert x.shape == (2, 500)
    assert abs(x[0].mean()) < 0.3 and abs(x[1].mean() - 10) < 0.3


def test_multinomial():
    probs = nd.array([[0.0, 1.0, 0.0], [0.5, 0.0, 0.5]])
    s = nd.random.multinomial(probs, shape=(200,)).asnumpy()
    assert s.shape == (2, 200)
    assert (s[0] == 1).all()
    assert set(np.unique(s[1])).issubset({0, 2})


def test_shuffle():
    x = nd.array(np.arange(50, dtype=np.float32))
    y = nd.random.shuffle(x).asnumpy()
    assert sorted(y.tolist()) == list(range(50))


def test_poisson_exponential_gamma():
    p = nd.random.poisson(lam=4.0, shape=(5000,)).asnumpy()
    assert abs(p.mean() - 4.0) < 0.2
    e = nd.random.exponential(scale=2.0, shape=(5000,)).asnumpy()
    assert abs(e.mean() - 2.0) < 0.2
    g = nd.random.gamma(alpha=3.0, beta=2.0, shape=(5000,)).asnumpy()
    assert abs(g.mean() - 6.0) < 0.5


def test_next_key_inside_foreign_jit_no_tracer_leak():
    """Regression: an eager-style random op traced into someone else's jit
    must not store a tracer into the global RNG state — later eager calls
    would hit jax's UnexpectedTracerError."""
    import jax
    from mxnet_tpu import random as mxr

    @jax.jit
    def traced():
        return jax.random.uniform(mxr.next_key(), (2,))

    traced()
    # global state must still yield usable keys outside the trace
    k = mxr.next_key()
    val = jax.random.uniform(k, (2,))
    assert val.shape == (2,)


def test_seed_reproducible_counter_stream():
    import numpy as onp
    import jax
    from mxnet_tpu import random as mxr
    mxr.seed(11)
    a = [onp.asarray(jax.random.uniform(mxr.next_key(), (3,)))
         for _ in range(3)]
    mxr.seed(11)
    b = [onp.asarray(jax.random.uniform(mxr.next_key(), (3,)))
         for _ in range(3)]
    for x, y in zip(a, b):
        onp.testing.assert_array_equal(x, y)
    # distinct keys along the stream
    assert not onp.allclose(a[0], a[1])
