"""External op-library ABI (lib_api) tests.

ref: src/c_api/c_api.cc:96 MXLoadLib + include/mxnet/lib_api.h
initialize(version) contract + python/mxnet/library.py load().
"""
import os
import shutil
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def c_plugin(tmp_path_factory):
    if shutil.which("gcc") is None:
        pytest.skip("no gcc")
    d = tmp_path_factory.mktemp("libops")
    so = str(d / "librelu6.so")
    src = os.path.join(REPO, "example", "lib_ops", "relu6.c")
    subprocess.check_call(["gcc", "-shared", "-fPIC", "-O2",
                           "-I", os.path.join(REPO, "src"), src, "-o", so])
    mx.lib_api.load(so)
    return so


@pytest.fixture(scope="module")
def py_plugin():
    path = os.path.join(REPO, "example", "lib_ops", "gelu_plugin.py")
    mx.lib_api.load(path)
    return path


class TestCPlugin:
    def test_nd(self, c_plugin):
        x = mx.nd.array(np.array([-3.0, 2.0, 7.5], np.float32))
        y = mx.nd.relu6(x)
        np.testing.assert_allclose(y.asnumpy(), [0.0, 2.0, 6.0])
        z = mx.nd.scale2(x)
        np.testing.assert_allclose(z.asnumpy(), [-6.0, 4.0, 15.0])

    def test_inside_jit(self, c_plugin):
        # pure_callback islands must survive jit tracing
        import jax
        import jax.numpy as jnp
        fn = jax.jit(lambda a: mx.ops.registry.get_op("relu6").fn(a) + 1.0)
        out = fn(jnp.array([-1.0, 8.0]))
        np.testing.assert_allclose(np.asarray(out), [1.0, 7.0])

    def test_sym(self, c_plugin):
        data = mx.sym.var("data")
        net = mx.sym.relu6(data)
        ex = net.bind(mx.cpu(), {"data": mx.nd.array(
            np.array([[-1.0, 6.5]], np.float32))})
        (out,) = ex.forward()
        np.testing.assert_allclose(out.asnumpy(), [[0.0, 6.0]])

    def test_idempotent_load(self, c_plugin):
        h1 = mx.lib_api.load(c_plugin)
        h2 = mx.lib_api.load(c_plugin)
        assert h1 is h2
        assert c_plugin in mx.lib_api.loaded_libraries()


class TestPyPlugin:
    def test_nd_and_grad(self, py_plugin):
        x = mx.nd.array(np.linspace(-2, 2, 7).astype(np.float32))
        x.attach_grad()
        with mx.autograd.record():
            y = mx.nd.my_gelu(x)
        y.backward(mx.nd.ones_like(y))
        # custom VJP must match finite differences
        eps = 1e-3
        xn = x.asnumpy()
        import jax.numpy as jnp
        f = mx.ops.registry.get_op("my_gelu").fn
        num = (np.asarray(f(jnp.asarray(xn + eps)))
               - np.asarray(f(jnp.asarray(xn - eps)))) / (2 * eps)
        np.testing.assert_allclose(x.grad.asnumpy(), num, atol=1e-2)

    def test_autodiff_without_backward(self, py_plugin):
        x = mx.nd.array(np.array([0.5, -0.5], np.float32))
        x.attach_grad()
        with mx.autograd.record():
            y = mx.nd.my_softplus2(x)
        y.backward(mx.nd.ones_like(y))
        sig = 1 / (1 + np.exp(-x.asnumpy()))
        np.testing.assert_allclose(x.grad.asnumpy(), 2 * sig, rtol=1e-5)

    def test_gluon(self, py_plugin):
        class Net(mx.gluon.HybridBlock):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.dense = mx.gluon.nn.Dense(4)

            def hybrid_forward(self, F, x):
                return F.my_gelu(self.dense(x))

        net = Net()
        net.initialize()
        net.hybridize()
        out = net(mx.nd.array(np.ones((2, 3), np.float32)))
        assert out.shape == (2, 4)

    def test_library_alias(self, py_plugin):
        assert py_plugin in mx.library.loaded_libraries()


class TestContract:
    def test_missing_file(self):
        with pytest.raises(mx.base.MXNetError):
            mx.lib_api.load("/nonexistent/lib.so")

    def test_relative_path(self):
        with pytest.raises(mx.base.MXNetError):
            mx.lib_api.load("relative.so")

    def test_bad_extension(self, tmp_path):
        p = tmp_path / "notalib.txt"
        p.write_text("x")
        with pytest.raises(mx.base.MXNetError):
            mx.lib_api.load(str(p))

    def test_initialize_version_gate(self, tmp_path):
        # a plugin rejecting the framework version must fail the load
        p = tmp_path / "oldlib.py"
        p.write_text("def initialize(version):\n    return 0\n")
        with pytest.raises(RuntimeError, match="failed to initialize"):
            mx.lib_api.load(str(p))

    def test_missing_initialize(self, tmp_path):
        p = tmp_path / "noinit.py"
        p.write_text("x = 1\n")
        with pytest.raises(RuntimeError, match="initialize"):
            mx.lib_api.load(str(p))

    def test_failed_initialize_rolls_back_registrations(self, tmp_path):
        # a plugin that registers THEN fails the version gate must leave
        # nothing behind (MXLoadLib: zero return = nothing registered)
        p = tmp_path / "haflib.py"
        p.write_text(
            "import jax.numpy as jnp\n"
            "from mxnet_tpu import lib_api\n"
            "def initialize(version):\n"
            "    lib_api.register_op('halfbaked_op', lambda x: x + 1)\n"
            "    return 0\n")
        with pytest.raises(RuntimeError, match="failed to initialize"):
            mx.lib_api.load(str(p))
        assert not hasattr(mx.nd, "halfbaked_op")
        with pytest.raises(KeyError):
            mx.ops.registry.get_op("halfbaked_op")


class TestRegisterOp:
    def test_custom_vjp_with_static_kwargs(self):
        import jax.numpy as jnp

        def fwd(x, scale=2.0):
            return scale * x * x

        def bwd(residuals, g, scale=2.0):
            (x,) = residuals
            return (g * 2.0 * scale * x,)

        mx.lib_api.register_op("sqscale_t", fwd, backward=bwd)
        x = mx.nd.array(np.array([1.0, -2.0], np.float32))
        x.attach_grad()
        with mx.autograd.record():
            y = mx.nd.sqscale_t(x, scale=3.0)
        np.testing.assert_allclose(y.asnumpy(), [3.0, 12.0])
        y.backward(mx.nd.ones_like(y))
        np.testing.assert_allclose(x.grad.asnumpy(), [6.0, -12.0])

    def test_override_takes_effect_in_namespaces(self):
        import jax.numpy as jnp
        # register, then override: mx.nd must see the NEW semantics
        mx.lib_api.register_op("ovr_t", lambda x: x + 1.0)
        assert mx.nd.ovr_t(mx.nd.array([1.0])).asnumpy()[0] == 2.0
        with pytest.warns(RuntimeWarning, match="overrides operator"):
            mx.lib_api.register_op("ovr_t", lambda x: x + 10.0)
        assert mx.nd.ovr_t(mx.nd.array([1.0])).asnumpy()[0] == 11.0
        s = mx.sym.ovr_t(mx.sym.var("data"))
        ex = s.bind(mx.cpu(), {"data": mx.nd.array([1.0])})
        assert ex.forward()[0].asnumpy()[0] == 11.0
