"""Symbol graph API: composition, attributes, internals, inference.

Ports the strategies of tests/python/unittest/test_symbol.py,
test_attr.py and test_infer_shape.py."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def _mlp():
    data = sym.var("data")
    h = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    return mx.sym.FullyConnected(h, num_hidden=3, name="fc2")


def test_list_arguments_and_outputs():
    out = _mlp()
    args = out.list_arguments()
    assert args[0] == "data"
    assert set(args) == {"data", "fc1_weight", "fc1_bias", "fc2_weight",
                         "fc2_bias"}
    assert out.list_outputs() == ["fc2_output"]


def test_get_internals_and_select():
    out = _mlp()
    internals = out.get_internals()
    names = internals.list_outputs()
    assert any("relu1" in n for n in names)
    relu = internals["relu1"]
    assert relu.name == "relu1"
    # internal head is executable
    exe = relu.bind(args={
        "data": nd.ones((2, 4)),
        "fc1_weight": nd.ones((8, 4)),
        "fc1_bias": nd.zeros((8,))})
    assert exe.forward()[0].shape == (2, 8)


def test_infer_shape_forward_and_backward():
    out = _mlp()
    arg_shapes, out_shapes, _ = out.infer_shape(data=(5, 4))
    shapes = dict(zip(out.list_arguments(), arg_shapes))
    assert shapes["fc1_weight"] == (8, 4)
    assert shapes["fc2_weight"] == (3, 8)
    assert out_shapes == [(5, 3)]


def test_infer_shape_partial():
    out = _mlp()
    arg_shapes, out_shapes, _ = out.infer_shape_partial()
    # nothing known -> everything None but no exception
    assert out_shapes[0] is None


def test_attr_propagation_with_attrscope():
    from mxnet_tpu.attribute import AttrScope
    with AttrScope(ctx_group="stage1"):
        a = sym.var("a")
        b = a * 2.0
    assert b.attr("ctx_group") == "stage1"
    assert a.attr("ctx_group") == "stage1"
    c = sym.var("c")
    assert c.attr("ctx_group") is None


def test_explicit_attr_and_attr_dict():
    a = sym.var("a", attr={"mood": "angry"})
    d = a.attr_dict()[a.name] if callable(getattr(a, "attr_dict")) \
        else a.attr_dict[a.name]
    assert d["mood"] == "angry"


def test_symbol_group():
    a, b = sym.var("a"), sym.var("b")
    g = sym.Group([a + b, a * b])
    assert len(g.list_outputs()) == 2
    exe = g.bind(args={"a": nd.array([2.0]), "b": nd.array([3.0])})
    outs = exe.forward()
    np.testing.assert_allclose(outs[0].asnumpy(), [5.0])
    np.testing.assert_allclose(outs[1].asnumpy(), [6.0])


def test_symbol_copy_and_json():
    import copy
    out = _mlp()
    c = copy.deepcopy(out)
    assert c.list_arguments() == out.list_arguments()
    assert c.tojson() == out.tojson()


def test_symbol_save_load(tmp_path):
    out = _mlp()
    f = str(tmp_path / "net.json")
    out.save(f)
    back = sym.load(f)
    assert back.list_arguments() == out.list_arguments()


def test_name_uniqueness():
    syms = [mx.sym.FullyConnected(sym.var("x"), num_hidden=2)
            for _ in range(3)]
    names = [s.name for s in syms]
    assert len(set(names)) == 3


def test_symbol_arithmetic_scalars():
    a = sym.var("a")
    out = ((2.0 - a) / (a + 1.0)) ** 2.0
    exe = out.bind(args={"a": nd.array([1.0])})
    np.testing.assert_allclose(exe.forward()[0].asnumpy(), [0.25])


def test_eval_shortcut():
    a = sym.var("a")
    res = (a + 1.0).eval(a=nd.array([1.0, 2.0]))
    np.testing.assert_allclose(res[0].asnumpy(), [2.0, 3.0])


def test_grouped_executor_backward():
    a = sym.var("a")
    out = sym.Group([a * 2.0, a * 3.0])
    exe = out.bind(args={"a": nd.array([1.0])},
                   args_grad={"a": nd.zeros((1,))})
    exe.forward(is_train=True)
    exe.backward()
    # d(2a)/da + d(3a)/da with ones head grads
    np.testing.assert_allclose(exe.grad_dict["a"].asnumpy(), [5.0])
