"""Data IO tests (model: tests/python/unittest/test_io.py,
test_recordio.py, test_gluon_data.py in the reference)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.io import (NDArrayIter, CSVIter, PrefetchingIter, ResizeIter,
                          ImageRecordIter)


def test_ndarray_iter_basic():
    data = np.arange(40).reshape(10, 4).astype("float32")
    label = np.arange(10).astype("float32")
    it = NDArrayIter(data, label, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    # pad wraps around to the beginning
    np.testing.assert_allclose(batches[-1].data[0].asnumpy()[1:],
                               data[:2])


def test_ndarray_iter_discard_and_reset():
    data = np.arange(40).reshape(10, 4).astype("float32")
    it = NDArrayIter(data, None, batch_size=3, last_batch_handle="discard")
    assert len(list(it)) == 3
    it.reset()
    assert len(list(it)) == 3


def test_ndarray_iter_shuffle_covers_all():
    data = np.arange(8).astype("float32").reshape(8, 1)
    it = NDArrayIter(data, None, batch_size=4, shuffle=True)
    got = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    assert sorted(got.tolist()) == list(range(8))


def test_ndarray_iter_dict_input():
    it = NDArrayIter({"a": np.zeros((6, 2)), "b": np.ones((6, 3))},
                     batch_size=2)
    names = [d.name for d in it.provide_data]
    assert sorted(names) == ["a", "b"]


def test_csv_iter(tmp_path):
    data = np.random.rand(10, 3).astype("float32")
    f = str(tmp_path / "d.csv")
    np.savetxt(f, data, delimiter=",")
    it = CSVIter(data_csv=f, data_shape=(3,), batch_size=5)
    b = next(iter(it))
    np.testing.assert_allclose(b.data[0].asnumpy(), data[:5], rtol=1e-6)


def test_prefetching_iter():
    data = np.arange(24).reshape(12, 2).astype("float32")
    base = NDArrayIter(data, None, batch_size=4)
    it = PrefetchingIter(base)
    batches = [b.data[0].asnumpy() for b in it]
    assert len(batches) == 3
    it.reset()
    assert len([b for b in it]) == 3


def test_resize_iter():
    data = np.arange(24).reshape(12, 2).astype("float32")
    it = ResizeIter(NDArrayIter(data, None, batch_size=4), size=7)
    assert len(list(it)) == 7


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [b"hello", b"x" * 1000, b"", b"abc\x00def"]
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for p in payloads:
        assert r.read() == p
    assert r.read() is None


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(5):
        w.write_idx(i, b"rec%d" % i)
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    assert r.read_idx(3) == b"rec3"
    assert r.read_idx(0) == b"rec0"
    assert r.keys == list(range(5))


def test_pack_unpack_label_array():
    h = recordio.IRHeader(0, [1.0, 2.0, 3.0], 7, 0)
    s = recordio.pack(h, b"payload")
    h2, data = recordio.unpack(s)
    np.testing.assert_allclose(h2.label, [1, 2, 3])
    assert data == b"payload"
    assert h2.id == 7


def _write_image_rec(tmp_path, n=8, size=40):
    import cv2
    path = str(tmp_path / "img.rec")
    idx = str(tmp_path / "img.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        header = recordio.IRHeader(0, float(i % 3), i, 0)
        w.write_idx(i, recordio.pack_img(header, img, quality=90))
    w.close()
    return path, idx


def test_image_record_iter(tmp_path):
    path, idx = _write_image_rec(tmp_path)
    it = ImageRecordIter(path_imgrec=path, path_imgidx=idx,
                         data_shape=(3, 32, 32), batch_size=4,
                         shuffle=True, rand_crop=True, rand_mirror=True,
                         preprocess_threads=2)
    b = next(iter(it))
    assert b.data[0].shape == (4, 3, 32, 32)
    assert b.label[0].shape == (4,)
    labels = set()
    it.reset()
    for b in it:
        labels.update(b.label[0].asnumpy().tolist())
    assert labels <= {0.0, 1.0, 2.0}


def test_gluon_dataset_dataloader():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    X = np.random.rand(20, 3).astype("float32")
    Y = np.arange(20).astype("float32")
    ds = ArrayDataset(X, Y)
    assert len(ds) == 20
    x0, y0 = ds[0]
    loader = DataLoader(ds, batch_size=6, shuffle=True, last_batch="keep")
    bs = list(loader)
    assert len(bs) == 4
    assert bs[0][0].shape == (6, 3)


def test_gluon_dataloader_workers():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    X = np.arange(64).reshape(16, 4).astype("float32")
    ds = ArrayDataset(X)
    loader = DataLoader(ds, batch_size=4, num_workers=2)
    got = np.concatenate([b.asnumpy() for b in loader])
    np.testing.assert_allclose(got, X)


def test_gluon_dataset_transform():
    from mxnet_tpu.gluon.data import ArrayDataset
    X = np.ones((4, 2), "float32")
    Y = np.zeros(4, "float32")
    ds = ArrayDataset(X, Y).transform(lambda x, y: (x * 2, y + 1))
    x, y = ds[1]
    np.testing.assert_allclose(np.asarray(x), [2, 2])
    assert y == 1


def test_vision_synthetic_mnist(monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_SYNTHETIC_DATA", "1")
    from mxnet_tpu.gluon.data.vision import MNIST
    ds = MNIST(root=str(tmp_path), train=True)
    assert len(ds) == 1024
    x, y = ds[0]
    assert x.shape == (28, 28, 1)
    assert 0 <= int(y) < 10


def test_transforms_chain():
    from mxnet_tpu.gluon.data.vision import transforms as Tf
    img = mx.nd.array((np.random.rand(36, 36, 3) * 255).astype("uint8"))
    tf = Tf.Compose([Tf.Resize(32), Tf.CenterCrop(28), Tf.ToTensor(),
                     Tf.Normalize(mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2))])
    out = tf(img)
    assert out.shape == (3, 28, 28)


def test_image_imdecode_imresize():
    import cv2
    from mxnet_tpu import image as img_mod
    arr = (np.random.rand(20, 30, 3) * 255).astype(np.uint8)
    ok, buf = cv2.imencode(".png", arr)
    img = img_mod.imdecode(buf.tobytes())
    assert img.shape == (20, 30, 3)
    r = img_mod.imresize(img, 15, 10)
    assert r.shape == (10, 15, 3)
    s = img_mod.resize_short(img, 10)
    assert min(s.shape[:2]) == 10


def test_mnist_iter(tmp_path):
    # write tiny idx-ubyte files
    import struct
    n, h, w = 32, 8, 8
    imgs = (np.random.rand(n, h, w) * 255).astype(np.uint8)
    labs = np.random.randint(0, 10, n).astype(np.uint8)
    ip, lp = str(tmp_path / "im"), str(tmp_path / "lb")
    with open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, h, w))
        f.write(imgs.tobytes())
    with open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labs.tobytes())
    from mxnet_tpu.io import MNISTIter
    it = MNISTIter(image=ip, label=lp, batch_size=8, shuffle=False)
    b = next(iter(it))
    assert b.data[0].shape == (8, 1, 8, 8)
    np.testing.assert_allclose(b.label[0].asnumpy(), labs[:8])


def test_prefetching_iter_reset_mid_epoch():
    """Regression: reset mid-epoch must not leak pre-reset batches."""
    data = np.arange(10).reshape(10, 1).astype("float32")
    it = PrefetchingIter(NDArrayIter(data, None, batch_size=1))
    for _ in range(3):
        it.next()
    it.reset()
    b = it.next()
    assert float(b.data[0].asnumpy()[0, 0]) == 0.0


def test_create_mesh_unknown_axis_raises():
    from mxnet_tpu.parallel import create_mesh
    with pytest.raises(ValueError):
        create_mesh(tp_size=4)


def test_recordio_split_record_rejoin(tmp_path):
    """Records written split (dmlc-style, magic stripped) rejoin correctly."""
    import struct as _s
    path = str(tmp_path / "split.rec")
    magic = 0xced7230a
    magic_b = _s.pack("<I", magic)
    payload = b"AAAA" + magic_b + b"BBBB"   # contains the magic word
    p1, p2 = b"AAAA", b"BBBB"               # dmlc drops the magic at split
    with open(path, "wb") as f:
        for cflag, part in ((1, p1), (3, p2)):
            f.write(_s.pack("<II", magic, (cflag << 29) | len(part)))
            f.write(part)
            f.write(b"\x00" * ((4 - len(part) % 4) % 4))
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == payload


def test_gluon_dataloader_multiprocess_shm():
    """Fork-pool workers returning batches via POSIX shared memory
    (ref: gluon/data/dataloader.py worker pool + cpu_shared storage)."""
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    X = np.arange(96).reshape(24, 4).astype("float32")
    Y = (np.arange(24) % 3).astype("float32")
    ds = ArrayDataset(X, Y)
    loader = DataLoader(ds, batch_size=6, num_workers=2, thread_pool=False)
    for _ in range(2):  # two epochs: the worker pool is reused
        xs, ys = [], []
        for bx, by in loader:
            xs.append(bx.asnumpy())
            ys.append(by.asnumpy())
        np.testing.assert_allclose(np.concatenate(xs), X)
        np.testing.assert_allclose(np.concatenate(ys), Y)


def test_gluon_dataloader_shm_no_leak_on_abandon():
    """Abandoning iteration mid-epoch must not leak /dev/shm segments."""
    import glob
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    X = np.arange(400).reshape(100, 4).astype("float32")
    loader = DataLoader(ArrayDataset(X), batch_size=5, num_workers=2,
                        thread_pool=False, prefetch=8)
    before = set(glob.glob("/dev/shm/psm_*"))
    it = iter(loader)
    next(it)
    next(it)
    it.close()
    loader._shutdown_pool()
    import time
    time.sleep(0.5)
    leaked = set(glob.glob("/dev/shm/psm_*")) - before
    assert not leaked, leaked


def test_vision_transforms_crop_resize_and_hue():
    """ref: gluon/data/vision/transforms.py CropResize :238, RandomHue
    :502 (YIQ chroma rotation)."""
    from mxnet_tpu.gluon.data.vision import transforms
    from mxnet_tpu import nd
    rs = np.random.RandomState(0)
    img = nd.array(rs.randint(0, 255, (8, 10, 3)).astype("float32"))
    cr = transforms.CropResize(2, 1, 4, 5)
    out = cr(img)
    assert out.shape == (5, 4, 3)
    np.testing.assert_allclose(out.asnumpy(),
                               img.asnumpy()[1:6, 2:6], rtol=1e-5)
    crr = transforms.CropResize(2, 1, 4, 5, size=(8, 8))
    assert crr(img).shape == (8, 8, 3)
    hue = transforms.RandomHue(0.5)
    hout = hue(img)
    assert hout.shape == img.shape
    # luma (Y channel) is preserved by a pure chroma rotation
    coef = np.array([0.299, 0.587, 0.114], "float32")
    np.testing.assert_allclose((hout.asnumpy() * coef).sum(-1),
                               (img.asnumpy() * coef).sum(-1), rtol=1e-3)
    jit = transforms.RandomColorJitter(brightness=0.1, hue=0.1)
    assert jit(img).shape == img.shape


def test_image_record_iter_uint8_and_prefetch(tmp_path):
    """uint8 feed path (on-device normalize downstream) + prefetch
    thread + repeated reset (exercises the producer handoff race)."""
    path, idx = _write_image_rec(tmp_path, n=16)
    it = ImageRecordIter(path_imgrec=path, path_imgidx=idx,
                         data_shape=(3, 32, 32), batch_size=4,
                         shuffle=True, rand_crop=True, rand_mirror=True,
                         dtype="uint8", prefetch_buffer=2,
                         preprocess_threads=2)
    assert it.provide_data[0].dtype == np.uint8
    for _ in range(4):  # reset mid-epoch: old producer must be joined
        it.reset()
        b = next(iter(it))
        assert b.data[0].shape == (4, 3, 32, 32)
        arr = b.data[0].asnumpy()
        assert arr.dtype == np.uint8
        assert arr.max() > 0  # decoded real pixels, not garbage
    # full epochs still produce every record exactly once per epoch
    it.reset()
    n = sum(b.data[0].shape[0] for b in it)
    assert n == 16


def test_image_record_iter_batches_stay_on_host(tmp_path):
    """Iterator batches are host numpy-backed (reference iterators
    yield CPU NDArrays) — placement on the accelerator is the
    consumer's move, never the pipeline's."""
    path, idx = _write_image_rec(tmp_path)
    it = ImageRecordIter(path_imgrec=path, path_imgidx=idx,
                         data_shape=(3, 32, 32), batch_size=4)
    b = next(iter(it))
    assert isinstance(b.data[0]._data, np.ndarray)
    assert b.data[0].context.device_type.startswith("cpu")


def test_image_record_iter_normalize_matches_manual(tmp_path):
    """float32 path: batch-level vectorized mean/std equals the manual
    per-image computation."""
    path, idx = _write_image_rec(tmp_path)
    kw = dict(path_imgrec=path, path_imgidx=idx, data_shape=(3, 32, 32),
              batch_size=4, mean_r=100.0, mean_g=110.0, mean_b=120.0,
              std_r=50.0, std_g=51.0, std_b=52.0, prefetch_buffer=0)
    a = next(iter(ImageRecordIter(**kw)))
    raw = next(iter(ImageRecordIter(**{**kw, "mean_r": 0.0, "mean_g": 0.0,
                                       "mean_b": 0.0, "std_r": 1.0,
                                       "std_g": 1.0, "std_b": 1.0,
                                       "dtype": "uint8"})))
    manual = raw.data[0].asnumpy().astype(np.float32)
    mean = np.array([100.0, 110.0, 120.0], np.float32).reshape(1, 3, 1, 1)
    std = np.array([50.0, 51.0, 52.0], np.float32).reshape(1, 3, 1, 1)
    np.testing.assert_allclose(a.data[0].asnumpy(),
                               (manual - mean) / std, rtol=1e-5)


def test_raw_pixel_records_roundtrip_and_iterate(tmp_path):
    """Pre-decoded raw-pixel .rec fast path (recordio.pack_raw_img):
    byte-exact pixel round-trip through unpack_img with NO cv2 decode,
    and ImageRecordIter consumes raw and JPEG records identically."""
    from mxnet_tpu import recordio

    rng = np.random.RandomState(3)
    img = rng.randint(0, 255, (40, 48, 3), np.uint8)
    rec = recordio.pack_raw_img(recordio.IRHeader(0, 7.0, 0, 0), img)
    header, out = recordio.unpack_img(rec)
    assert header.label == 7.0
    np.testing.assert_array_equal(out, img)  # lossless, unlike JPEG
    # magic detection: JPEG payloads still take the cv2 path
    assert recordio.decode_raw_img(b"\xff\xd8\xff\xe0 not raw") is None

    # iterator fast path: raw .rec yields exact center-crop pixels
    recf = str(tmp_path / "raw.rec")
    idxf = str(tmp_path / "raw.idx")
    w = recordio.MXIndexedRecordIO(idxf, recf, "w")
    imgs = [rng.randint(0, 255, (36, 36, 3), np.uint8) for _ in range(8)]
    for i, im in enumerate(imgs):
        w.write_idx(i, recordio.pack_raw_img(
            recordio.IRHeader(0, float(i), i, 0), im))
    w.close()
    import mxnet_tpu as mx
    it = mx.io.ImageRecordIter(path_imgrec=recf, path_imgidx=idxf,
                               data_shape=(3, 32, 32), batch_size=4,
                               dtype="uint8", preprocess_threads=2)
    b = next(iter(it))
    got = b.data[0].asnumpy()  # NCHW uint8
    lbl = int(b.label[0].asnumpy()[0])
    src = imgs[lbl]
    want = src[2:34, 2:34, ::-1].transpose(2, 0, 1)  # center crop, BGR->RGB
    np.testing.assert_array_equal(got[0], want)
