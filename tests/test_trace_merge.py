"""Distributed observability plane tests (ISSUE 6): wire trace-context
round trips (client flow 's' paired with server flow 'f'), protocol
version negotiation against an old server, multi-rank trace merge with
heartbeat-based clock alignment, and the 2-process end-to-end run."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401 — conftest platform setup
from mxnet_tpu import kvstore_async as KA
from mxnet_tpu import profiler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_profiler(tmp_path):
    profiler._reset()
    profiler.set_config(filename=str(tmp_path / "shard.json"),
                        xprof=False)
    yield
    profiler._reset()
    profiler.set_config(filename="profile.json", xprof=True)


def _trace(fn=None):
    with open(fn or profiler._state["filename"]) as f:
        return json.load(f)


def _wait_flow_pairing(timeout=5.0):
    """Fence before set_state('stop'): the server records its
    ``ph:"f"`` half AFTER sending the response (the span must cover the
    handling), so the final request's client side can return before the
    server's bookkeeping lands — stopping the profiler inside that
    window drops the closing flow event and the s/f pairing asserts
    flake. Wait (bounded) until every opened flow has closed."""
    import time as _t
    deadline = _t.monotonic() + timeout
    while _t.monotonic() < deadline:
        with profiler._lock:
            n_s = sum(1 for e in profiler._events if e.get("ph") == "s")
            n_f = sum(1 for e in profiler._events if e.get("ph") == "f")
        if n_f >= n_s:
            return
        _t.sleep(0.01)


# -- wire trace-context: in-process client/server round trip ----------------

def test_wire_context_pairs_client_server_flows():
    srv = KA.AsyncPSServer()
    cli = KA.AsyncPSClient("127.0.0.1", srv.port)
    profiler.set_state("run")
    try:
        cli.init("w", np.zeros(4, np.float32))
        for _ in range(3):
            cli.push("w", np.ones(4, np.float32))
            cli.pull("w")
        _wait_flow_pairing()
    finally:
        profiler.set_state("stop")
        cli.stop_server()
        srv.stop()
    assert cli._peer_version == KA._PROTO_VERSION
    profiler.dump()
    evs = _trace()["traceEvents"]
    s_ids = {e["id"] for e in evs if e.get("ph") == "s"}
    f_ids = {e["id"] for e in evs if e.get("ph") == "f"}
    assert len(s_ids) >= 7  # init + 3 pushes + 3 pulls
    assert s_ids == f_ids, "every client flow must close server-side"
    m = profiler.metrics()
    # RTT histograms fed by the same round trips
    assert m["latency"]["kvstore.push_rtt"]["count"] == 3
    assert m["latency"]["kvstore.pull_rtt"]["count"] == 3
    assert m["aggregate"]["ps.server.push"]["count"] == 3
    assert m["aggregate"]["ps.client.pull"]["count"] == 3


def test_flow_ids_unique_across_clients_same_rank():
    """Two clients on one rank (per-server shard clients, the tmp client
    every barrier() creates) must never stamp the same flow id: req ids
    are drawn from one process-wide sequence, not per-client counters
    that would all start at 0 and cross-wire causality arrows."""
    srv = KA.AsyncPSServer()
    a = KA.AsyncPSClient("127.0.0.1", srv.port)
    b = KA.AsyncPSClient("127.0.0.1", srv.port)
    profiler.set_state("run")
    try:
        a.init("w", np.zeros(4, np.float32))
        for _ in range(3):
            a.push("w", np.ones(4, np.float32))
            b.pull("w")
        _wait_flow_pairing()
    finally:
        profiler.set_state("stop")
        a.stop_server()
        srv.stop()
    profiler.dump()
    evs = _trace()["traceEvents"]
    s_ids = [e["id"] for e in evs if e.get("ph") == "s"]
    assert len(s_ids) >= 7
    assert len(s_ids) == len(set(s_ids)), "duplicate client flow ids"
    assert set(s_ids) == {e["id"] for e in evs if e.get("ph") == "f"}


def test_profiling_off_wire_is_byte_identical_v0():
    """Off-path unchanged: with no profile run active a v1 client sends
    exactly the v0 frames (no flag bit, no context header)."""
    srv = KA.AsyncPSServer()
    cli = KA.AsyncPSClient("127.0.0.1", srv.port)
    sent = []
    real_send = KA._send_frame

    def spy(sock, payload):
        sent.append(bytes(payload[:1]))
        real_send(sock, payload)

    KA._send_frame = spy
    try:
        cli.init("w", np.zeros(4, np.float32))
        cli.push("w", np.ones(4, np.float32))
        cli.pull("w")
    finally:
        KA._send_frame = real_send
        cli.stop_server()
        srv.stop()
    assert sent and all(not (b[0] & KA._TRACE_FLAG) for b in sent)
    assert profiler.metrics()["num_events"] == 0


def test_old_server_negotiates_to_v0_and_still_works():
    """Interop: a server that predates _OP_HELLO answers unknown-opcode
    _RE_ERR; the client reads version 0 and never stamps trace-context,
    even while profiling is on."""

    class OldServer(KA.AsyncPSServer):
        def _handle(self, conn, buf):
            if buf[0] == KA._OP_HELLO:
                raise ValueError("unknown opcode %d" % buf[0])
            return super()._handle(conn, buf)

    srv = OldServer()
    cli = KA.AsyncPSClient("127.0.0.1", srv.port)
    sent = []
    real_send = KA._send_frame

    def spy(sock, payload):
        sent.append(bytes(payload[:1]))
        real_send(sock, payload)

    profiler.set_state("run")
    KA._send_frame = spy
    try:
        cli.init("w", np.zeros(4, np.float32))
        cli.push("w", np.ones(4, np.float32))
        out = cli.pull("w")
    finally:
        KA._send_frame = real_send
        profiler.set_state("stop")
        cli.stop_server()
        srv.stop()
    assert cli._peer_version == 0
    assert np.array_equal(out, np.ones(4, np.float32))
    assert all(not (b[0] & KA._TRACE_FLAG) for b in sent)


def test_heartbeat_clock_sync_and_age_gauge():
    srv = KA.AsyncPSServer()
    cli = KA.AsyncPSClient("127.0.0.1", srv.port)
    try:
        cli.heartbeat(5, sync_clock=True, clock_primary=True)  # negotiates
        cli.heartbeat(5, sync_clock=True, clock_primary=True)
        cs = profiler.clock_sync()
        peer = "127.0.0.1:%d" % srv.port
        assert peer in cs and cs[peer]["primary"]
        # same process, same perf_counter epoch offset differs only by
        # profiler import-time delta + rtt noise: bounded by ~1s here
        assert abs(cs[peer]["offset_us"]) < 1e6
        stats = profiler.metrics()["kvstore_server"]
        assert "rank_heartbeat_age.5" in stats
        assert 0.0 <= stats["rank_heartbeat_age.5"] < 10.0
    finally:
        cli.stop_server()
        srv.stop()


# -- merge_traces unit (synthetic shards) ------------------------------------

def _shard(rank, events, clock_sync=None):
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"rank": rank, "clock_sync": clock_sync or {}}}


def test_merge_aligns_clocks_and_remaps_pids(tmp_path):
    # rank 1's clock runs 10_000us behind server 0's: its shard carries
    # offset +10_000 and its raw timestamps sit BEFORE the causally
    # later server events until alignment shifts them
    fid = KA._flow_id(1, 7)
    shard0 = _shard(0, [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "mxnet_tpu"}},
        {"name": "ps.server.push", "ph": "X", "ts": 5200.0, "dur": 50.0,
         "pid": 0, "tid": 2},
        {"name": "ps.push", "ph": "f", "bp": "e", "id": fid,
         "ts": 5200.0, "pid": 0, "tid": 2},
    ])
    shard1 = _shard(1, [
        {"name": "ps.client.push", "ph": "X", "ts": -4900.0,
         "dur": 400.0, "pid": 1, "tid": 2},
        {"name": "ps.push", "ph": "s", "id": fid, "ts": -4900.0,
         "pid": 1, "tid": 2},
    ], clock_sync={"127.0.0.1:9999": {
        "offset_us": 10000.0, "rtt_us": 120.0, "samples": 3,
        "primary": True}})
    p0, p1 = tmp_path / "s0.json", tmp_path / "s1.json"
    p0.write_text(json.dumps(shard0))
    p1.write_text(json.dumps(shard1))
    out = tmp_path / "merged.json"
    merged, summary = profiler.merge_traces(
        [str(p0), str(p1)], output=str(out))
    assert summary["flows_paired"] == 1
    assert summary["offsets_us"] == {"0": 0.0, "1": 10000.0}
    evs = merged["traceEvents"]
    s = [e for e in evs if e.get("ph") == "s"][0]
    f = [e for e in evs if e.get("ph") == "f"][0]
    # monotone after alignment: flow start precedes its finish
    assert s["ts"] == pytest.approx(5100.0)
    assert s["ts"] <= f["ts"]
    assert s["pid"] == 1 and f["pid"] == 0
    # written file round-trips
    disk = json.loads(out.read_text())
    assert disk["metadata"]["offsets_us"]["1"] == 10000.0
    # --no-align path keeps raw timestamps
    raw, _ = profiler.merge_traces([str(p0), str(p1)], align=False)
    raw_s = [e for e in raw["traceEvents"] if e.get("ph") == "s"][0]
    assert raw_s["ts"] == pytest.approx(-4900.0)


def test_merge_cli_reports_pairs(tmp_path):
    fid = KA._flow_id(1, 9)
    p0 = tmp_path / "r0.json"
    p1 = tmp_path / "r1.json"
    p0.write_text(json.dumps(_shard(0, [
        {"name": "ps.pull", "ph": "f", "bp": "e", "id": fid,
         "ts": 10.0, "pid": 0, "tid": 2}])))
    p1.write_text(json.dumps(_shard(1, [
        {"name": "ps.pull", "ph": "s", "id": fid, "ts": 5.0,
         "pid": 1, "tid": 2}])))
    out = tmp_path / "m.json"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_merge.py"),
         str(p0), str(p1), "-o", str(out)],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "1 paired" in r.stdout
    assert json.loads(out.read_text())["traceEvents"]


# -- 2-process end-to-end (acceptance) ---------------------------------------

@pytest.mark.slow
def test_two_process_run_merges_into_one_trace(tmp_path):
    """A 2-process kvstore training run produces per-rank shards that
    merge into one chrome trace with paired client→server flows and
    monotone flow timestamps after clock alignment; each rank's
    /metrics scrape and latency percentiles are validated in-worker."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["MXTPU_TRACE_DIR"] = str(tmp_path)
    env["MXTPU_PS_HEARTBEAT_INTERVAL"] = "0.1"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable,
         os.path.join(REPO, "tests", "trace_merge_worker.py")],
        env=env, capture_output=True, text=True, timeout=480)
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout + r.stderr
    for rank in range(2):
        for marker in ("LATENCY_OK", "SCRAPE_OK", "SERVER_METRICS_OK"):
            assert "rank %d: %s" % (rank, marker) in out, out
        assert "rank %d/2: OBS_WORKER_OK" % rank in out, out

    shards = [str(tmp_path / ("trace_rank%d.json" % i)) for i in (0, 1)]
    merged, summary = profiler.merge_traces(
        shards, output=str(tmp_path / "merged.json"))
    assert sorted(summary["ranks"]) == [0, 1]
    assert summary["flows_started"] > 0
    assert summary["flows_paired"] > 0, summary
    # causality: every paired flow is monotone after alignment, within
    # the alignment error bound (half the sync RTT, generously padded)
    evs = merged["traceEvents"]
    starts = {e["id"]: e for e in evs if e.get("ph") == "s"}
    finishes = {e["id"]: e for e in evs if e.get("ph") == "f"}
    paired = set(starts) & set(finishes)
    rank1_sync = json.load(open(shards[1]))["metadata"]["clock_sync"]
    slack = max(v["rtt_us"] for v in rank1_sync.values()) / 2 + 100.0
    violations = [fid for fid in paired
                  if finishes[fid]["ts"] < starts[fid]["ts"] - slack]
    assert not violations, (len(violations), len(paired))
    # both ranks contribute events under their own pid
    pids = {e.get("pid") for e in evs}
    assert {0, 1} <= pids
