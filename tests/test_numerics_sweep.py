"""The on-device numerics harness itself (benchmark/tpu_numerics.py;
VERDICT r3 item 8). CI runs CPU-vs-CPU (same backend -> 0 ULP expected)
to prove the machinery: deterministic inputs across processes, ULP
accounting, flash cross-check. The real TPU-vs-CPU run happens in
bench.py under BENCH_NUMERICS=1 (recorded in BENCH_r*.json)."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HARNESS = os.path.join(REPO, "benchmark", "tpu_numerics.py")


def _clean_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    return env


def test_same_backend_sweep_is_exact(tmp_path):
    golden = str(tmp_path / "g.npz")
    r1 = subprocess.run([sys.executable, HARNESS, "--golden", golden],
                        env=_clean_env(), capture_output=True, text=True,
                        timeout=600)
    assert r1.returncode == 0, r1.stderr
    r2 = subprocess.run([sys.executable, HARNESS, "--check", golden],
                        env=_clean_env(), capture_output=True, text=True,
                        timeout=600)
    assert r2.returncode == 0, r2.stderr
    out = json.loads(r2.stdout[r2.stdout.index("{"):])
    # same backend, same deterministic inputs -> bit-exact
    assert out["worst_ulp"] == 0, out
    assert out["n_ops"] >= 20
    # flash check ran (reference path on CPU) and is numerically tight
    assert out["flash_fwd_rel_err"] < 1e-3
    assert out["flash_bwd_max_abs_err"] < 1e-2
    # the precision-policy controls are in the sweep and the ULP gate
    # passed (VERDICT r4 item 3: a sweep without a gate silently
    # absorbs regressions)
    assert "dot_policy_float32" in out["per_op"]
    assert "dot_precision_highest" in out["per_op"]
    assert out["gate"]["ok"], out["gate"]


def test_ulp_gate_fails_on_breach():
    """A budget breach must fail the sweep (and bench), not just be
    recorded."""
    sys.path.insert(0, os.path.join(REPO, "benchmark"))
    import tpu_numerics as tn

    out = {
        "per_op": {"dot": {"max_ulp": tn.ULP_BUDGETS["dot"] + 1,
                           "max_abs": 1.0},
                   "exp": {"max_ulp": 0, "max_abs": 0.0}},
        "flash_fwd_rel_err": 0.0,
        "flash_bwd_max_abs_err": 0.0,
        "model_resnet18_rel_err": 0.5,
    }
    breaches = tn.apply_gate(out)
    assert not out["gate"]["ok"]
    assert len(breaches) == 2  # dot ULP + model rel err
    assert any("dot" in b for b in breaches)
    assert any("model_resnet18_rel_err" in b for b in breaches)

    ok = {"per_op": {"dot": {"max_ulp": 3, "max_abs": 0.0}},
          "flash_fwd_rel_err": 0.0, "flash_bwd_max_abs_err": 0.0}
    assert tn.apply_gate(ok) == []
    assert ok["gate"]["ok"]

    # every sweep op has a budget — a new op without one would be
    # silently ungated
    for op in tn.OPS:
        assert op in tn.ULP_BUDGETS, op
