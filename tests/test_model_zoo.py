"""Model zoo architecture tests.

Mirrors the reference's tests/python/unittest/test_gluon_model_zoo.py:
every registered model builds, initializes, and produces (N, classes) logits.
Heavy ImageNet-sized forwards are limited to a representative subset to keep
CI time bounded; all names are at least constructed.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision, get_model

ALL_NAMES = [
    "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
    "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
    "resnet101_v2", "resnet152_v2",
    "vgg11", "vgg13", "vgg16", "vgg19",
    "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn",
    "alexnet",
    "densenet121", "densenet161", "densenet169", "densenet201",
    "squeezenet1.0", "squeezenet1.1",
    "inceptionv3",
    "mobilenet1.0", "mobilenet0.75", "mobilenet0.5", "mobilenet0.25",
    "mobilenetv2_1.0", "mobilenetv2_0.75", "mobilenetv2_0.5",
    "mobilenetv2_0.25",
]


def test_all_names_construct():
    for name in ALL_NAMES:
        net = get_model(name, classes=10)
        assert net is not None


def test_unknown_name_raises():
    with pytest.raises(ValueError):
        get_model("resnet1337_v9")


def test_pretrained_raises():
    with pytest.raises(RuntimeError):
        get_model("resnet18_v1", pretrained=True)


@pytest.mark.parametrize("name", ["resnet18_v1", "resnet18_v2",
                                  "mobilenet0.25", "squeezenet1.1"])
def test_small_model_forward(name):
    net = get_model(name, classes=7)
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 3, 224, 224).astype("float32"))
    y = net(x)
    assert y.shape == (2, 7)


def test_hybridized_forward_matches_eager():
    net = get_model("resnet18_v1", classes=5)
    net.initialize()
    x = mx.nd.array(np.random.rand(1, 3, 224, 224).astype("float32"))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hybrid = net(x).asnumpy()
    np.testing.assert_allclose(y_eager, y_hybrid, rtol=1e-4, atol=1e-4)


def test_thumbnail_resnet_cifar_shape():
    # thumbnail mode = 3x3 stem for 32x32 inputs (CIFAR), as in the reference
    net = vision.resnet18_v1(classes=10, thumbnail=True)
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 3, 32, 32).astype("float32"))
    assert net(x).shape == (2, 10)


def test_model_save_load_roundtrip(tmp_path):
    net = get_model("mobilenet0.25", classes=3)
    net.initialize()
    x = mx.nd.array(np.random.rand(1, 3, 224, 224).astype("float32"))
    y = net(x).asnumpy()
    f = str(tmp_path / "m.params")
    net.save_parameters(f)
    net2 = get_model("mobilenet0.25", classes=3)
    net2.load_parameters(f)
    np.testing.assert_allclose(y, net2(x).asnumpy(), rtol=1e-5, atol=1e-5)


def test_nhwc_layout_matches_nchw():
    """layout="NHWC" (TPU-native channels-last) must be numerically
    identical to NCHW given the same OIHW weights — the API contract
    that makes checkpoints layout-independent (docs/ROADMAP.md
    round-3 perf analysis)."""
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    a = resnet18_v1(classes=10)
    a.initialize()
    x = mx.nd.array(np.random.RandomState(3).rand(2, 3, 32, 32)
                    .astype("float32"))
    ya = a(x)
    b = resnet18_v1(classes=10, layout="NHWC")
    b.initialize()
    b(x)  # deferred init
    pa, pb = a.collect_params(), b.collect_params()
    for k1, k2 in zip(sorted(pa), sorted(pb)):
        assert pb[k2].shape == pa[k1].shape, (k1, k2)
        pb[k2].set_data(pa[k1].data())
    np.testing.assert_allclose(ya.asnumpy(), b(x).asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_nhwc_checkpoint_interchange(tmp_path):
    """An NCHW-trained checkpoint loads into an NHWC model unchanged."""
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    a = resnet18_v1(classes=5)
    a.initialize()
    x = mx.nd.array(np.random.RandomState(4).rand(1, 3, 32, 32)
                    .astype("float32"))
    y = a(x).asnumpy()
    f = str(tmp_path / "w.params")
    a.save_parameters(f)
    b = resnet18_v1(classes=5, layout="NHWC")
    b.load_parameters(f)
    np.testing.assert_allclose(y, b(x).asnumpy(), rtol=1e-4, atol=1e-4)


def test_nhwc_trains():
    """One SGD step on the NHWC variant produces finite decreasing loss."""
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    net = resnet18_v1(classes=4, layout="NHWC", thumbnail=True)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.05})
    loss_fn = SoftmaxCrossEntropyLoss()
    x = mx.nd.array(np.random.RandomState(5).rand(8, 3, 32, 32)
                    .astype("float32"))
    y = mx.nd.array(np.arange(8) % 4)
    losses = []
    for _ in range(5):
        with mx.autograd.record():
            l = loss_fn(net(x), y)
        l.backward()
        trainer.step(8)
        losses.append(float(l.mean().asnumpy()))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_conv_transpose_nhwc_matches_nchw():
    """Deconvolution honors channels-last too (same OIHW-style weights)."""
    from mxnet_tpu.gluon import nn as gnn
    a = gnn.Conv2DTranspose(6, kernel_size=3, strides=2, padding=1,
                            in_channels=4)
    a.initialize()
    x = mx.nd.array(np.random.RandomState(6).rand(2, 4, 8, 8)
                    .astype("float32"))
    ya = a(x).asnumpy()
    b = gnn.Conv2DTranspose(6, kernel_size=3, strides=2, padding=1,
                            in_channels=4, layout="NHWC")
    b.initialize()
    xn = mx.nd.array(np.transpose(x.asnumpy(), (0, 2, 3, 1)))
    b(xn)
    pa, pb = a.collect_params(), b.collect_params()
    for k1, k2 in zip(sorted(pa), sorted(pb)):
        pb[k2].set_data(pa[k1].data())
    yb = np.transpose(b(xn).asnumpy(), (0, 3, 1, 2))
    np.testing.assert_allclose(ya, yb, rtol=1e-4, atol=1e-5)
