"""Async PS hardening (VERDICT r2 item 9): row-sparse + 2-bit
compressed pushes on the async wire, heartbeats/dead-node query,
profiler command channel, and a multiprocess dead-worker restart.

ref: src/kvstore/kvstore_dist.h:522 (EncodeRowSparseKey), :121
(GetDeadNodes), gradient_compression.h:38,
include/mxnet/kvstore.h:49 (KVStoreServerProfilerCommand).
"""
import multiprocessing as mp
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.kvstore_async import AsyncPSServer, AsyncPSClient

# minutes-scale on the 1-core CI host (subprocess clusters / full
# registry sweep / JPEG decode) — deselect with -m 'not slow' for
# the quick lane; the full lane always runs them
pytestmark = pytest.mark.slow


@pytest.fixture()
def server():
    srv = AsyncPSServer()
    yield srv
    srv.stop()


class TestSparseWire:
    def test_row_sparse_push_touches_only_rows(self, server):
        c = AsyncPSClient("127.0.0.1", server.port)
        w = np.ones((16, 4), np.float32)
        c.init(1, w)
        before = c.bytes_pushed
        c.push_row_sparse(1, [2, 5], np.full((2, 4), 9.0, np.float32))
        sparse_bytes = c.bytes_pushed - before
        out = c.pull(1)
        np.testing.assert_allclose(out[2], 9.0)
        np.testing.assert_allclose(out[5], 9.0)
        np.testing.assert_allclose(out[0], 1.0)  # untouched rows intact
        # wire cost scales with touched rows, not the dense shape
        c.push(1, w)
        dense_bytes = c.bytes_pushed - before - sparse_bytes
        assert sparse_bytes < dense_bytes / 2

    def test_row_sparse_push_through_optimizer(self, server):
        import mxnet_tpu.optimizer as opt
        c = AsyncPSClient("127.0.0.1", server.port)
        c.init(3, np.ones((8, 2), np.float32))
        c.set_optimizer(opt.create("sgd", learning_rate=0.5, wd=0.0))
        c.push_row_sparse(3, [1], np.ones((1, 2), np.float32))
        out = c.pull(3)
        np.testing.assert_allclose(out[1], 0.5)   # 1 - 0.5*1
        np.testing.assert_allclose(out[0], 1.0)   # zero grad elsewhere

    def test_pull_row_sparse(self, server):
        c = AsyncPSClient("127.0.0.1", server.port)
        c.init(4, np.arange(12, dtype=np.float32).reshape(6, 2))
        rows = c.pull_row_sparse(4, [0, 5])
        np.testing.assert_allclose(rows, [[0, 1], [10, 11]])


class TestCompressedWire:
    def test_2bit_push_dequantizes_server_side(self, server):
        c = AsyncPSClient("127.0.0.1", server.port)
        from mxnet_tpu.pallas_kernels.compression import quantize_2bit_jnp
        import jax.numpy as jnp
        n = 64
        c.init(7, np.zeros((n,), np.float32))
        grad = np.full((n,), 1.0, np.float32)
        words, _res = quantize_2bit_jnp(jnp.asarray(grad),
                                        jnp.zeros(n), 0.5)
        before = c.bytes_pushed
        c.push_compressed(7, np.asarray(words), n, 0.5)
        wire = c.bytes_pushed - before
        assert wire < n * 4 / 2   # int32 words: 16x fewer than values
        out = c.pull(7)
        np.testing.assert_allclose(out, 0.5)  # store-replace semantics

    def test_kvstore_facade_compression_with_residual(self, tmp_path):
        os.environ["MXTPU_PROC_ID"] = "0"
        os.environ["MXTPU_NUM_PROCS"] = "1"
        os.environ["MXTPU_ASYNC_PS_PORT"] = "0"
        os.environ.pop("MXTPU_COORDINATOR", None)
        import mxnet_tpu.optimizer as opt
        kv = mx.kv.create("dist_async")
        try:
            kv.set_gradient_compression({"type": "2bit",
                                         "threshold": 0.5,
                                         "size_lower_bound": 1024})
            kv.set_optimizer(opt.create("sgd", learning_rate=1.0,
                                        wd=0.0))
            n = 2048  # >= size_lower_bound -> compressed path
            w = mx.nd.array(np.zeros((n,), np.float32))
            kv.init(9, w)
            g = mx.nd.array(np.full((n,), 0.3, np.float32))
            before = kv._client.bytes_pushed
            kv.push(9, g)      # 0.3 < thr: residual only, no step
            kv.push(9, g)      # residual 0.6 >= thr: quantized step
            wire = kv._client.bytes_pushed - before
            assert wire < 2 * n * 4 / 4   # both pushes compressed
            out = mx.nd.array(np.zeros((n,), np.float32))
            kv.pull(9, out=out)
            np.testing.assert_allclose(out.asnumpy(), -0.5, atol=1e-6)
        finally:
            kv.close()


class TestLiveness:
    def test_heartbeat_dead_node_and_recovery(self, server):
        a = AsyncPSClient("127.0.0.1", server.port)
        b = AsyncPSClient("127.0.0.1", server.port)
        a.start_heartbeat(0, interval=0.1)
        b.start_heartbeat(1, interval=0.1)
        time.sleep(0.4)
        assert a.dead_nodes(timeout=1.0) == []
        b.stop_heartbeat()           # rank 1 "dies"
        time.sleep(1.2)
        assert a.dead_nodes(timeout=1.0) == [1]
        # restarted worker resumes beating under the same rank
        b2 = AsyncPSClient("127.0.0.1", server.port)
        b2.start_heartbeat(1, interval=0.1)
        time.sleep(0.4)
        assert a.dead_nodes(timeout=1.0) == []
        a.stop_heartbeat()
        b2.stop_heartbeat()


class TestProfilerChannel:
    def test_server_profiler_command_dump(self, server, tmp_path):
        c = AsyncPSClient("127.0.0.1", server.port)
        out = str(tmp_path / "server_profile.json")
        c.profiler_command("set_config", "filename=%s" % out)
        c.profiler_command("state", "run")
        c.push(11, np.ones((4,), np.float32)) \
            if c.init(11, np.ones((4,), np.float32)) is None else None
        c.profiler_command("state", "stop")
        c.profiler_command("dump", "")
        assert os.path.exists(out)

    def test_unknown_command_errors(self, server):
        c = AsyncPSClient("127.0.0.1", server.port)
        with pytest.raises(RuntimeError, match="profiler command"):
            c.profiler_command("explode", "")


def _hardening_worker(rank, nproc, port_env_val, die_before_done):
    os.environ["MXTPU_PROC_ID"] = str(rank)
    os.environ["MXTPU_NUM_PROCS"] = str(nproc)
    os.environ["MXTPU_ASYNC_PS_PORT"] = port_env_val
    os.environ["MXTPU_PS_HEARTBEAT_INTERVAL"] = "0.1"
    import mxnet_tpu as mx2
    from mxnet_tpu.ndarray.sparse import row_sparse_array
    kv = mx2.kv.create("dist_async")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5,
                                 "size_lower_bound": 1024})
    kv.init(1, mx2.nd.array(np.zeros((16, 4), np.float32)))
    kv.init(2, mx2.nd.array(np.zeros((2048,), np.float32)))
    # sparse push
    rs = row_sparse_array((np.full((1, 4), 1.0, np.float32),
                           np.array([rank])), shape=(16, 4))
    kv.push(1, rs)
    # compressed push (over the bigarray bound)
    kv.push(2, mx2.nd.array(np.full((2048,), 0.6, np.float32)))
    if die_before_done:
        kv._client.stop_heartbeat()
        os._exit(0)  # crash without done() — the dead worker
    kv.close()


class TestMultiprocessRestart:
    def test_sparse_compressed_and_dead_worker_restart(self):
        os.environ.pop("MXTPU_COORDINATOR", None)
        os.environ["MXTPU_PROC_ID"] = "0"
        os.environ["MXTPU_NUM_PROCS"] = "3"
        os.environ["MXTPU_ASYNC_PS_PORT"] = "0"
        os.environ["MXTPU_PS_HEARTBEAT_INTERVAL"] = "0.1"
        os.environ["MXTPU_PS_DONE_TIMEOUT"] = "30"
        kv = mx.kv.create("dist_async")
        try:
            port = os.environ["MXTPU_ASYNC_PS_PORT"]
            # spawn (not fork): the parent already runs jax + server
            # threads, and forking that deadlocks in the child
            ctx = mp.get_context("spawn")
            # worker 1 completes; worker 2 dies before done()
            w1 = ctx.Process(target=_hardening_worker,
                             args=(1, 3, port, False))
            w2 = ctx.Process(target=_hardening_worker,
                             args=(2, 3, port, True))
            w1.start()
            w2.start()
            w1.join(90)
            w2.join(90)
            assert w1.exitcode == 0 and w2.exitcode == 0
            time.sleep(1.5)
            dead = kv.get_dead_nodes(timeout=1.0)
            assert 2 in dead and 1 not in dead, dead
            # restart the dead rank; it finishes the protocol
            w2b = ctx.Process(target=_hardening_worker,
                              args=(2, 3, port, False))
            w2b.start()
            w2b.join(90)
            assert w2b.exitcode == 0
            time.sleep(0.5)
            # both sparse rows landed (ranks 1 and 2 each touched row)
            out = mx.nd.array(np.zeros((16, 4), np.float32))
            kv.pull(1, out=out)
            v = out.asnumpy()
            assert v[1].sum() > 0 and v[2].sum() > 0
        finally:
            kv.close()


class TestBarrier:
    def test_rendezvous_releases_all(self, server):
        import threading
        clients = [AsyncPSClient("127.0.0.1", server.port)
                   for _ in range(3)]
        released = []

        def arrive(i):
            clients[i].barrier(3)
            released.append(i)

        t1 = threading.Thread(target=arrive, args=(0,))
        t2 = threading.Thread(target=arrive, args=(1,))
        t1.start()
        t2.start()
        time.sleep(0.5)
        assert released == []      # two of three: still blocked
        arrive(2)                  # third releases everyone
        t1.join(10)
        t2.join(10)
        assert sorted(released) == [0, 1, 2]

    def test_barrier_reusable_across_generations(self, server):
        c = AsyncPSClient("127.0.0.1", server.port)
        for _ in range(3):
            c.barrier(1)           # n=1 releases immediately, each time

    def test_barrier_size_mismatch_errors(self, server):
        import threading
        a = AsyncPSClient("127.0.0.1", server.port)
        b = AsyncPSClient("127.0.0.1", server.port)
        t = threading.Thread(target=lambda: a.barrier(2))
        t.start()
        time.sleep(0.3)
        with pytest.raises(RuntimeError, match="size mismatch"):
            b.barrier(5)
        b.barrier(2)  # correct size releases the pending rendezvous
        t.join(10)

    def test_barrier_timeout_aborts_and_withdraws(self, server,
                                                  monkeypatch):
        monkeypatch.setenv("MXTPU_PS_BARRIER_TIMEOUT", "1")
        a = AsyncPSClient("127.0.0.1", server.port)
        with pytest.raises(RuntimeError, match="barrier aborted"):
            a.barrier(2)           # partner never arrives
        # the withdrawn arrival must not poison the next generation
        monkeypatch.setenv("MXTPU_PS_BARRIER_TIMEOUT", "600")
        import threading
        released = []
        t = threading.Thread(
            target=lambda: (a.barrier(2), released.append(1)))
        t.start()
        time.sleep(0.5)
        assert released == []      # needs a REAL second arrival
        AsyncPSClient("127.0.0.1", server.port).barrier(2)
        t.join(10)
        assert released == [1]

    def test_heartbeat_flows_while_barrier_parked(self, server):
        import threading
        a = AsyncPSClient("127.0.0.1", server.port)
        a.start_heartbeat(0, interval=0.1)
        t = threading.Thread(target=lambda: a.barrier(2))
        t.start()
        time.sleep(1.2)            # parked well past the dead window
        watcher = AsyncPSClient("127.0.0.1", server.port)
        assert watcher.dead_nodes(timeout=1.0) == []  # NOT starved
        watcher.barrier(2)         # release
        t.join(10)
        a.stop_heartbeat()
