"""Pallas fused BN->ReLU->conv3x3 kernel (pallas_kernels/conv_fused.py)
and its model-zoo integration (resnet fuse=...).

Kernels run in interpreter mode on the CPU suite; the real-TPU path is
exercised by bench.py BENCH_FUSED=pallas (see docs/ROADMAP.md round-4
fused-conv study for the measured results).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mxnet_tpu.pallas_kernels import conv_fused as CF


def _mats(N, H, W, Ci, Co, seed=0):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(N, H, W, Ci).astype("float32"))
    s = jnp.asarray(rs.rand(Ci).astype("float32") + 0.5)
    b = jnp.asarray(rs.randn(Ci).astype("float32") * 0.1)
    w = jnp.asarray(rs.randn(3, 3, Ci, Co).astype("float32") * 0.1)
    return x, s, b, w


class TestKernels:
    @pytest.mark.parametrize("shape", [(3, 8, 8, 16, 24),   # NB=1
                                       (4, 4, 4, 8, 8)])    # NB>1 path
    def test_forward_matches_reference(self, shape):
        x, s, b, w = _mats(*shape)
        out = CF.fused_scale_relu_conv3x3(x, s, b, w, interpret=True)
        ref = CF.fused_conv_reference(x, s, b, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_no_relu(self):
        x, s, b, w = _mats(2, 6, 6, 8, 8)
        out = CF.fused_scale_relu_conv3x3(x, s, b, w, relu=False,
                                          interpret=True)
        ref = CF.fused_conv_reference(x, s, b, w, relu=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    @pytest.mark.parametrize("shape", [(3, 8, 8, 16, 24), (4, 4, 4, 8, 8)])
    def test_gradients_match_reference(self, shape):
        x, s, b, w = _mats(*shape)

        def lk(*a):
            return jnp.sum(
                CF.fused_scale_relu_conv3x3(*a, interpret=True) ** 2)

        def lr(*a):
            return jnp.sum(CF.fused_conv_reference(*a) ** 2)

        gk = jax.grad(lk, argnums=(0, 1, 2, 3))(x, s, b, w)
        gr = jax.grad(lr, argnums=(0, 1, 2, 3))(x, s, b, w)
        for a, c in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       atol=2e-3, rtol=1e-3)

    def test_tiled_backward_paths(self, monkeypatch):
        """Force the Ci-tiled dx grid AND a 2-Co-tile dW grid — the
        deep-stage VMEM configurations — and check grads still match."""
        monkeypatch.setattr(CF, "_bwd_dx_tiles",
                            lambda N, H, W, Ci, Co, cb: (1, Ci // 2, True))
        monkeypatch.setattr(CF, "_bwd_dw_tiles",
                            lambda N, H, W, Ci, Co, cb: (1, Co // 2, True))
        x, s, b, w = _mats(2, 6, 6, 16, 16)

        def lk(*a):
            return jnp.sum(
                CF.fused_scale_relu_conv3x3(*a, interpret=True) ** 2)

        def lr(*a):
            return jnp.sum(CF.fused_conv_reference(*a) ** 2)

        gk = jax.grad(lk, argnums=(0, 1, 2, 3))(x, s, b, w)
        gr = jax.grad(lr, argnums=(0, 1, 2, 3))(x, s, b, w)
        for a, c in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       atol=2e-3, rtol=1e-3)

    def test_shape_validation(self):
        x, s, b, w = _mats(2, 6, 6, 8, 8)
        with pytest.raises(ValueError):
            CF.fused_scale_relu_conv3x3(x, s, b, jnp.zeros((5, 5, 8, 8)))


class TestModelIntegration:
    def _run(self, fuse, seed=0):
        import random
        import mxnet_tpu as mx
        from mxnet_tpu import autograd
        from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

        random.seed(seed)
        np.random.seed(seed)
        mx.random.seed(seed)
        net = resnet50_v1(layout="NHWC", fuse=fuse)
        net.initialize()
        x = mx.nd.array(np.random.RandomState(1).randn(
            2, 3, 64, 64).astype("float32"))
        y = mx.nd.array(np.array([3.0, 7.0]))
        with autograd.record():
            loss = SoftmaxCrossEntropyLoss()(net(x), y).mean()
        loss.backward()
        params = net.collect_params()
        g3 = next(p.grad().asnumpy() for n, p in sorted(params.items())
                  if "stage2" in n and p.shape[-2:] == (3, 3))
        rm = next(p.data().asnumpy() for n, p in sorted(params.items())
                  if "running_mean" in n and "stage1" in n)
        return float(loss.asnumpy()), g3, rm

    def test_fused_resnet_matches_unfused(self):
        l0, g0, rm0 = self._run(False)
        l1, g1, rm1 = self._run(True)
        assert abs(l0 - l1) < 1e-3, (l0, l1)
        # running stats must be EXACT: same stat math, same aux updates
        np.testing.assert_array_equal(rm0, rm1)
        # grads agree within deep-net accumulation-order noise
        assert np.max(np.abs(g0 - g1)) / (np.max(np.abs(g0)) + 1e-9) < 0.05

    def test_fuse_requires_nhwc(self):
        from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
        with pytest.raises(ValueError):
            resnet50_v1(layout="NCHW", fuse=True)

    def test_fuse_auto_policy(self):
        """auto fuses only the >=512-wide 3x3 stages (where the kernel
        beats XLA's conv; see conv_fused.py docstring)."""
        from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
        net = resnet50_v1(layout="NHWC", fuse="auto")
        stages = net.features
        fused_flags = []
        for child in stages:
            name = getattr(child, "prefix", "") or ""
            if "stage" in name:
                fused_flags.append(child[0]._fuse)
        assert fused_flags == [False, False, False, True]

    def test_fused_hybridize_consistent(self):
        import random
        import mxnet_tpu as mx
        from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

        random.seed(0)
        np.random.seed(0)
        mx.random.seed(0)
        net = resnet50_v1(layout="NHWC", fuse=True)
        net.initialize()
        x = mx.nd.array(np.random.RandomState(2).randn(
            2, 3, 32, 32).astype("float32"))
        eager = net(x).asnumpy()
        net.hybridize()
        hybrid = net(x).asnumpy()
        np.testing.assert_allclose(eager, hybrid, atol=2e-3)


def test_over_budget_plan_falls_back_to_reference(monkeypatch):
    """When the shrunk (nb, tile) still exceeds the VMEM budget
    (ADVICE r4: reachable with fuse forced on large feature maps), the
    dispatcher must take fused_conv_reference instead of launching a
    pallas_call that dies at Mosaic compile time."""
    import numpy as np

    x, s, b, w = _mats(2, 6, 6, 16, 16)
    # simulate an unfittable plan
    monkeypatch.setattr(CF, "_fwd_tiles",
                        lambda *a: (1, 16, False))
    called = []
    real_ref = CF.fused_conv_reference
    monkeypatch.setattr(CF, "fused_conv_reference",
                        lambda *a, **k: called.append(1) or real_ref(*a, **k))
    monkeypatch.setattr(CF, "_use_pallas", lambda *a, **k: True)
    out = CF.fused_scale_relu_conv3x3(x, s, b, w)
    assert called, "over-budget plan did not fall back to the reference"
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(real_ref(x, s, b, w)),
                               rtol=1e-5, atol=1e-5)


def test_shrink_reports_fit():
    nb, tile, fits = CF._shrink(4, 512, lambda n, t: n * t, budget=256)
    assert fits and nb * tile <= 256
    # even the floor (nb=1, tile=128) exceeds this budget
    nb, tile, fits = CF._shrink(4, 512, lambda n, t: n * t, budget=16)
    assert (nb, tile) == (1, 128) and not fits
