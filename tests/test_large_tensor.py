"""Large-tensor audit (ref: tests/nightly/test_large_array.py,
test_large_vector.py — >2^31-element indexing).

The reference needs explicit int64 builds for large tensors; here XLA
uses 64-bit addressing internally, and the audit checks (a) indexing
arithmetic stays correct past the int32 element-count boundary, and
(b) the framework's index dtypes don't silently wrap. Full >2^31
float arrays need ~8 GB — beyond the CPU CI budget — so the boundary
cases run at >2^31 ELEMENTS with int8 (2.2 GB), gated behind
MXTPU_TEST_LARGE=1, while the always-on tests audit the indexing math
at the boundary with cheap shapes.

HBM-bound threshold note: one v5e chip (16 GB) holds a >2^31-element
int8/uint8 or bf16 array fine; float32 at 2^31 elements is 8.6 GB and
still fits, but the CPU CI host may not — hence the gate.
"""
import gc
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

LARGE = os.environ.get("MXTPU_TEST_LARGE", "0") == "1"
INT32_MAX = 2 ** 31 - 1


class TestIndexingBoundaries:
    """int64-safe index arithmetic without allocating huge arrays."""

    def test_flat_index_arithmetic_past_int32(self):
        # a (2^16, 2^16) array has 2^32 elements; ravel/unravel math on
        # its indices must not wrap. Use index computation only.
        shape = (2 ** 16, 2 ** 16)
        flat = onp.ravel_multi_index((2 ** 16 - 1, 2 ** 16 - 1), shape)
        assert flat == 2 ** 32 - 1  # numpy reference
        # framework size computation
        a = nd.zeros((4, 4))  # placeholder; check .size dtype handling
        assert isinstance(a.size, int)

    def test_size_and_nbytes_are_python_ints(self):
        """size/nbytes must be arbitrary-precision python ints, not
        int32-wrapping numpy scalars."""
        a = nd.zeros((1024, 1024))
        assert type(a.size) is int and type(a.nbytes) is int
        # simulated large shape arithmetic (no allocation)
        big_shape = (2 ** 20, 2 ** 13)  # 2^33 elements
        n = 1
        for s in big_shape:
            n *= s
        assert n == 2 ** 33  # would overflow int32 4x

    def test_take_with_large_index_values(self):
        """Index values near int32 max must not wrap when cast."""
        a = nd.array(onp.arange(10, dtype="float32"))
        idx = nd.array(onp.array([0, 9], dtype="int64"))
        out = a.take(idx)
        assert out.asnumpy().tolist() == [0.0, 9.0]

    def test_arange_large_stop_dtype(self):
        """Audit finding, documented: without JAX_ENABLE_X64, jax stores
        int64 as int32, so index VALUES beyond 2^31 need the x64 flag
        (element COUNTS beyond 2^31 are fine either way — XLA addresses
        buffers with 64-bit offsets; see TestOverInt32Elements). Verify
        both behaviors."""
        import subprocess
        import sys
        code = (
            "import mxnet_tpu as mx, numpy as onp\n"
            "a = mx.np.arange(%d, %d, dtype='int64')\n"
            "got = a.asnumpy()\n"
            "assert got[-1] == %d, got\n"
            "assert got.dtype == onp.int64, got.dtype\n"
            "print('x64 arange ok')\n"
            % (INT32_MAX - 2, INT32_MAX + 2, INT32_MAX + 1))
        env = dict(os.environ, JAX_ENABLE_X64="1", JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
        res = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=240)
        assert res.returncode == 0, res.stdout + res.stderr
        # and without the flag, values wrap to int32 — the documented
        # one-chip default
        a = mx.np.arange(0, 10, dtype="int64")
        assert a.asnumpy().dtype in (onp.int32, onp.int64)


@pytest.mark.skipif(not LARGE, reason="set MXTPU_TEST_LARGE=1 (needs "
                    ">2.2 GB of device/host memory)")
class TestOverInt32Elements:
    """Real >2^31-element arrays at int8 (ref: test_large_array.py
    MEDIUM_X/LARGE_X cases, scaled to one-chip memory)."""

    SHAPE = (2 ** 16 + 2, 2 ** 15)       # 2,147,549,184 > 2^31 elements

    def test_create_sum_index(self):
        a = nd.ones(self.SHAPE, dtype="int8")
        assert a.size > INT32_MAX
        # reduction over >2^31 elements (accumulate in int64 on host)
        s = int(a.sum(axis=1).asnumpy().astype(onp.int64).sum())
        assert s == a.size
        # corner element indexing
        last = a[self.SHAPE[0] - 1, self.SHAPE[1] - 1]
        assert int(last.asnumpy()) == 1
        del a
        gc.collect()

    def test_slice_beyond_int32_flat_offset(self):
        a = nd.zeros(self.SHAPE, dtype="int8")
        # row whose flat offset exceeds int32 range
        row = 2 ** 16 + 1
        b = a[row]
        assert b.shape == (self.SHAPE[1],)
        del a, b
        gc.collect()
