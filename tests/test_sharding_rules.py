"""Partition-rule matrix (ISSUE 16 satellite): the regex->PartitionSpec
machinery the GSPMD fused step shards its param tree by.

Covers the EasyLM-idiom ``match_partition_rules`` contract: first-match
precedence, scalar and non-divisible dims falling back to replicated
(``_fit_spec``), stacked ``[L, ...]`` layer trees, and the rule
round-trip through ``relayout_params`` on a live mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.parallel import create_mesh
from mxnet_tpu.parallel import sharding as sh
from mxnet_tpu.parallel.compat import PartitionSpec as P

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")


def mesh3d():
    return create_mesh(dp=2, tp=2, sp=2)


class TestRuleMatching:
    def test_first_match_wins(self):
        rules = sh.PartitionRules([
            (r"wq$", ("tp", None)),
            (r"w.*$", (None, "tp")),
        ])
        assert rules.spec_for("block/wq") == P("tp", None)
        assert rules.spec_for("block/wk") == P(None, "tp")
        # reversed order: the catch-all shadows the specific rule —
        # precedence is positional, never specificity-based
        rev = sh.PartitionRules([
            (r"w.*$", (None, "tp")),
            (r"wq$", ("tp", None)),
        ])
        assert rev.spec_for("block/wq") == P(None, "tp")

    def test_unmatched_replicates_strict_raises(self):
        rules = sh.PartitionRules([(r"weight$", ("tp", None))])
        assert rules.spec_for("bias") == P()
        tree = {"weight": jnp.zeros((4, 4)), "other": jnp.zeros((4,))}
        specs = sh.match_partition_rules(rules, tree)
        assert specs["other"] == P()
        with pytest.raises(ValueError, match="no partition rule"):
            sh.match_partition_rules(rules, tree, strict=True)

    def test_scalar_always_replicated(self):
        rules = sh.PartitionRules([(r".*", ("tp",))])
        tree = {"count": jnp.float32(3.0), "vec": jnp.zeros((8,))}
        specs = sh.match_partition_rules(rules, tree, mesh=mesh3d())
        assert specs["count"] == P()          # never consults the rules
        assert specs["vec"] == P("tp")

    def test_fit_spec_drops_non_divisible_dims(self):
        mesh = mesh3d()                       # tp=2
        rules = sh.PartitionRules([(r"w$", ("tp", "sp"))])
        # 7 % 2 != 0 on dim 0 -> that axis replicates; dim 1 divides
        assert rules.spec_for("w", (7, 4), mesh) == P(None, "sp")
        # both divide -> spec kept whole
        assert rules.spec_for("w", (8, 4), mesh) == P("tp", "sp")
        # rank shorter than the spec -> trimmed, not an error
        assert rules.spec_for("w", (8,), mesh) == P("tp")
        # size-1 mesh axis -> replicated (no sharding to express)
        dp_only = create_mesh(devices=jax.devices()[:4])
        assert rules.spec_for("w", (8, 4), dp_only) == P(None, None)

    def test_stacked_layer_tree_prepends_scan_axis(self):
        mesh = mesh3d()
        from mxnet_tpu.parallel import tensor_parallel
        strat = tensor_parallel(mesh)
        L, D, H, Dh, F, V = 2, 8, 4, 2, 16, 32
        tree = {
            "embed": jnp.zeros((V, D)),
            "layers": {
                "wq": jnp.zeros((L, D, H, Dh)),
                "wo": jnp.zeros((L, H, Dh, D)),
                "w_up": jnp.zeros((L, D, F)),
                "w_down": jnp.zeros((L, F, D)),
                "ln1": jnp.zeros((L, D)),
            },
            "w_out": jnp.zeros((D, V)),
        }
        specs = sh.match_partition_rules(strat, tree, mesh=mesh)
        # rule written for the PER-LAYER shape; the scanned [L, ...]
        # axis gets None prepended (transformer.param_specs layout)
        assert specs["layers"]["wq"] == P(None, None, "tp", None)
        assert specs["layers"]["wo"] == P(None, "tp", None, None)
        assert specs["layers"]["w_up"] == P(None, None, "tp")
        assert specs["layers"]["w_down"] == P(None, "tp", None)
        assert specs["layers"]["ln1"] == P()   # unmatched -> replicated
        assert specs["embed"] == P("tp", None)
        assert specs["w_out"] == P(None, "tp")

    def test_describe_fingerprint_is_stable_and_hashable(self):
        rules = sh.PartitionRules([(r"wq$", ("tp", None))])
        d = rules.describe()
        assert d == ((r"wq$", ("tp", None)),)
        hash(d)  # the fused step folds this into its cache signature


class TestRelayoutRoundTrip:
    def test_rules_round_trip_through_relayout_params(self):
        mesh = mesh3d()
        from mxnet_tpu.parallel import tensor_parallel
        strat = tensor_parallel(mesh)
        rs = np.random.RandomState(0)
        params = {
            "blk_wq_weight": jnp.asarray(
                rs.randn(8, 4).astype(np.float32)),
            "blk_out_proj_weight": jnp.asarray(
                rs.randn(4, 8).astype(np.float32)),
            "blk_bias": jnp.asarray(rs.randn(5).astype(np.float32)),
        }
        placed = sh.relayout_params(params, strat)
        raw = getattr(mesh, "mesh", mesh)
        assert placed["blk_wq_weight"].sharding.spec == P("tp", None)
        assert placed["blk_out_proj_weight"].sharding.spec \
            == P(None, "tp")
        # 5 % tp != 0 -> _fit_spec replicated it
        assert placed["blk_bias"].sharding.spec == P()
        for k in params:
            np.testing.assert_array_equal(np.asarray(placed[k]),
                                          np.asarray(params[k]))
            assert placed[k].sharding.mesh == raw
