"""Runtime lock-order / race detector (mxnet_tpu/_debug/locktrace.py,
``MXNET_DEBUG_LOCKS=1``).

Two halves:

* unit coverage of the detector itself (inversion detection, boundary
  violations, Condition support, disabled fast path), and
* the acceptance gate: the concurrency-heavy subsystems — profiler
  daemons (continuous dump + memory sampler), the imperative jit/bulk
  fast path from multiple threads, io prefetch, and the async
  parameter server — run UNDER the detector and must report zero
  lock-order inversions, with the findings surfaced in
  ``profiler.metrics()['locks']``.
"""
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, profiler
from mxnet_tpu._debug import locktrace


@pytest.fixture
def tracing():
    """Detector on + clean slate, restored afterwards."""
    prev = locktrace.enable()
    locktrace.reset()
    yield
    locktrace.reset()
    if not prev:
        locktrace.disable()


# -- detector unit behavior --------------------------------------------------

def test_inversion_detected(tracing):
    a = locktrace.named_lock("t.a")
    b = locktrace.named_lock("t.b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    r = locktrace.report()
    assert r["inversion_total"] == 1
    assert sorted(r["inversions"][0]["pair"]) == ["t.a", "t.b"]
    assert "t.a->t.b" in r["order_edges"]
    assert "t.b->t.a" in r["order_edges"]


def test_consistent_order_is_clean(tracing):
    a = locktrace.named_lock("t.first")
    b = locktrace.named_lock("t.second")
    for _ in range(5):
        with a:
            with b:
                pass
    r = locktrace.report()
    assert r["inversion_total"] == 0
    assert r["order_edges"] == ["t.first->t.second"]


def test_inversion_reported_once_not_per_repeat(tracing):
    a = locktrace.named_lock("t.x")
    b = locktrace.named_lock("t.y")
    for _ in range(4):
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    assert locktrace.report()["inversion_total"] == 1


def test_inversion_detected_through_outer_held_lock(tracing):
    """The edge must come from EVERY held lock: A held (with B taken in
    between) while acquiring C, vs C-then-A elsewhere, is a deadlock
    cycle even though A and C are never adjacent."""
    a = locktrace.named_lock("t.outer")
    b = locktrace.named_lock("t.middle")
    c = locktrace.named_lock("t.inner")
    with a:
        with b:
            with c:
                pass
    with c:
        with a:
            pass
    r = locktrace.report()
    assert r["inversion_total"] == 1, r
    assert sorted(r["inversions"][0]["pair"]) == ["t.inner", "t.outer"]


def test_reentrant_named_lock_nests_on_same_thread(tracing):
    """reentrant=True (lib_api.load's contract: a plugin loading a
    dependency plugin) must not self-deadlock and must keep balanced
    bookkeeping."""
    lk = locktrace.named_lock("t.re", reentrant=True)
    with lk:
        with lk:  # would deadlock on a plain Lock
            pass
    assert locktrace.report()["inversion_total"] == 0
    # held stack fully unwound: a later boundary sees nothing held
    engine.wait_for_all()
    assert locktrace.report()["boundary_violation_total"] == 0


def test_condition_wait_after_runtime_enable():
    """A lock acquired BEFORE enable() has no bookkeeping record;
    Condition.wait on it must still work (acquire-probe fallback), not
    raise 'cannot wait on un-acquired lock'."""
    locktrace.disable()
    locktrace.reset()
    cv = locktrace.named_condition("t.late")
    try:
        with cv:
            locktrace.enable()  # detector turned on mid-critical-section
            assert cv.wait(timeout=0.05) is False  # times out, no raise
    finally:
        locktrace.disable()
        locktrace.reset()


def test_boundary_violation_lock_held_across_sync(tracing):
    lk = locktrace.named_lock("t.held")
    with lk:
        engine.wait_for_all()
    r = locktrace.report()
    assert r["boundary_violation_total"] == 1
    v = r["boundary_violations"][0]
    assert v["boundary"] == "engine.wait_for_all"
    assert v["held"] == ["t.held"]


def test_boundary_clean_when_nothing_held(tracing):
    engine.wait_for_all()
    x = mx.nd.array([1.0])
    engine.wait_for_var(x)
    assert locktrace.report()["boundary_violation_total"] == 0


def test_named_condition_wait_notify(tracing):
    cv = locktrace.named_condition("t.cv")
    hits = []

    def waiter():
        with cv:
            cv.wait(timeout=5)
            hits.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    import time
    time.sleep(0.05)
    with cv:
        cv.notify_all()
    t.join(timeout=5)
    assert hits == [1]
    assert locktrace.report()["inversion_total"] == 0


def test_disabled_is_plain_lock():
    prev = locktrace.ENABLED
    locktrace.disable()
    try:
        locktrace.reset()
        lk = locktrace.named_lock("t.off")
        with lk:
            assert lk.locked()
        assert not lk.locked()
        assert locktrace.report()["acquisitions"] == 0
    finally:
        if prev:
            locktrace.enable()


def test_metrics_has_no_locks_section_when_disabled():
    prev = locktrace.ENABLED
    locktrace.disable()
    try:
        assert "locks" not in profiler.metrics()
    finally:
        if prev:
            locktrace.enable()


# -- acceptance: concurrency-heavy subsystems under the detector -------------

def _assert_clean(context):
    r = locktrace.report()
    assert r["inversions"] == [], (context, r["inversions"])
    assert r["boundary_violations"] == [], (context,
                                            r["boundary_violations"])


def test_profiler_daemons_under_detector(tracing, tmp_path):
    """Continuous-dump daemon + memory sampler + concurrent emitters +
    pause/resume + explicit dump: the profiler's two locks must keep a
    consistent order everywhere."""
    profiler._reset()
    profiler.set_config(filename=str(tmp_path / "t.json"),
                        aggregate_stats=True, profile_memory=True,
                        continuous_dump=True, dump_period=0.05,
                        xprof=False)
    try:
        _drive_profiler_daemons(tmp_path)
    finally:
        # set_config state is process-global: put the defaults back so
        # later suites see a pristine profiler
        profiler.set_config(filename="profile.json",
                            aggregate_stats=False, profile_memory=False,
                            continuous_dump=False, dump_period=1.0,
                            xprof=True)


def _drive_profiler_daemons(tmp_path):
    profiler.set_state("run")
    stop = threading.Event()

    def emitter(i):
        while not stop.is_set():
            profiler.record_op("op%d" % i, 1.0)
            profiler.account("c%d" % i, 1, emit=False)

    threads = [threading.Thread(target=emitter, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    import time
    time.sleep(0.3)
    profiler.pause()
    profiler.resume()
    profiler.dump()
    m = profiler.metrics()
    stop.set()
    for t in threads:
        t.join(timeout=10)
    profiler.set_state("stop")
    assert "locks" in m
    assert m["locks"]["enabled"]
    assert "profiler.events" in m["locks"]["locks"]
    _assert_clean("profiler daemons")
    profiler._reset()


def test_imperative_jit_and_bulk_under_detector(tracing):
    """Multi-threaded eager dispatch through the jit cache plus bulk
    segments: compile boundaries must never see a held framework
    lock."""
    def worker(seed):
        x = mx.nd.array(np.random.RandomState(seed).rand(4, 4)
                        .astype("float32"))
        for _ in range(6):
            y = mx.nd.relu(x + x) * 2
        with engine.bulk(8):
            z = x + x
            z = z * z
            z = mx.nd.relu(z)
        engine.wait_for_var(z)
        return y

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    engine.wait_for_all()
    _assert_clean("imperative jit/bulk")


def test_prefetch_under_detector(tracing):
    from mxnet_tpu.io.prefetch import DevicePrefetchIter

    class Source:
        def __init__(self):
            self.n = 0

        def reset(self):
            self.n = 0

        def __iter__(self):
            return self

        def __next__(self):
            if self.n >= 8:
                raise StopIteration
            self.n += 1
            return np.full((2, 2), self.n, "float32")

    it = DevicePrefetchIter(Source(), depth=2)
    got = [b for b in it]
    assert len(got) == 8
    it.reset()
    assert len(list(it)) == 8
    _assert_clean("device prefetch")


def test_kvstore_async_under_detector(tracing):
    """Server accept/serve threads + concurrent worker pushes + the
    barrier condition variable, all on traced locks."""
    from mxnet_tpu.kvstore_async import AsyncPSServer, AsyncPSClient

    srv = AsyncPSServer()
    try:
        c0 = AsyncPSClient("127.0.0.1", srv.port)
        c0.init("w", np.zeros((4,), np.float32))

        def worker(rank):
            c = AsyncPSClient("127.0.0.1", srv.port)
            for _ in range(5):
                c.push("w", np.ones((4,), np.float32))
                c.pull("w")
            c.barrier(3)

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads)
        out = c0.pull("w")
        # default apply (no optimizer) overwrites: last push wins
        np.testing.assert_allclose(np.asarray(out), np.ones((4,)))
    finally:
        srv.stop()
    _assert_clean("kvstore_async")
    r = locktrace.report()
    assert "kvstore_async.server" in r["locks"]
