"""Multi-process distributed tests, launched as local processes via the
cluster launcher — the reference's pattern for testing dist kvstore
without a real cluster (ref: ci/docker/runtime_functions.sh:1281
`tools/launch.py -n 7 --launcher local python dist_sync_kvstore.py`,
SURVEY.md §4 blueprint note)."""
import os
import subprocess
import sys

import pytest

# minutes-scale on the 1-core CI host (subprocess clusters / full
# registry sweep / JPEG decode) — deselect with -m 'not slow' for
# the quick lane; the full lane always runs them
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launcher(n, script, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # replace (not extend) PYTHONPATH: the axon sitecustomize on it would
    # grab the real TPU in every worker
    env["PYTHONPATH"] = REPO
    cmd = [sys.executable, os.path.join(REPO, "tools", "launch.py"),
           "-n", str(n), sys.executable, os.path.join(REPO, script)]
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)


@pytest.mark.parametrize("n", [2, 3, 8])
def test_dist_sync_kvstore(n):
    """n=8 is where rank-mapping bugs actually appear (VERDICT r1 weak
    #6); covers sync aggregation, compression, and the gluon Trainer
    weight-consistency check at that width."""
    res = _run_launcher(n, "tests/dist_sync_kvstore_worker.py",
                        timeout=480)
    assert res.returncode == 0, res.stdout + res.stderr
    for rank in range(n):
        assert ("rank %d/%d: all dist_sync kvstore checks passed"
                % (rank, n)) in res.stdout + res.stderr


def test_bandwidth_tool_emits_json():
    """tools/bandwidth/measure.py analog of the reference's
    tools/bandwidth/measure.py: must emit one JSON record per size with
    a bandwidth figure and verified aggregation numerics."""
    import json
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bandwidth",
                                      "measure.py"),
         "--sizes-mb", "1", "--num-batches", "3"],
        env=env, capture_output=True, text=True, timeout=240)
    assert res.returncode == 0, res.stdout + res.stderr
    recs = [json.loads(line) for line in res.stdout.splitlines()
            if line.startswith("{")]
    assert recs and recs[0]["metric"] == "kvstore_pushpull_bandwidth"
    assert recs[0]["gb_per_sec"] > 0


def test_bandwidth_tool_dist():
    import json
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable,
         os.path.join(REPO, "tools", "bandwidth", "measure.py"),
         "--kv-store", "dist_sync", "--sizes-mb", "1",
         "--num-batches", "3"],
        env=env, capture_output=True, text=True, timeout=240)
    assert res.returncode == 0, res.stdout + res.stderr
    recs = [json.loads(line) for line in res.stdout.splitlines()
            if line.startswith("{")]
    assert recs and recs[0]["num_workers"] == 2


def test_launcher_propagates_failure(tmp_path):
    bad = tmp_path / "bad_worker.py"
    bad.write_text("import sys; sys.exit(3)\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable, str(bad)],
        env=env, capture_output=True, text=True, timeout=60)
    assert res.returncode == 1
    assert "exit codes" in res.stderr


def test_launcher_sets_dmlc_env(tmp_path):
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import os\n"
        "print('R%s/%s' % (os.environ['MXTPU_PROC_ID'],"
        " os.environ['MXTPU_NUM_PROCS']))\n"
        "assert os.environ['DMLC_ROLE'] == 'worker'\n"
        "assert 'MXTPU_COORDINATOR' in os.environ\n")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable, str(probe)],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr
    assert "R0/2" in res.stdout and "R1/2" in res.stdout
