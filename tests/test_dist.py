"""Multi-process distributed tests, launched as local processes via the
cluster launcher — the reference's pattern for testing dist kvstore
without a real cluster (ref: ci/docker/runtime_functions.sh:1281
`tools/launch.py -n 7 --launcher local python dist_sync_kvstore.py`,
SURVEY.md §4 blueprint note)."""
import os
import subprocess
import sys

import pytest

# minutes-scale on the 1-core CI host (subprocess clusters / full
# registry sweep / JPEG decode) — deselect with -m 'not slow' for
# the quick lane; the full lane always runs them
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launcher(n, script, timeout=240, env_extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # replace (not extend) PYTHONPATH: the axon sitecustomize on it would
    # grab the real TPU in every worker
    env["PYTHONPATH"] = REPO
    env.update(env_extra or {})
    cmd = [sys.executable, os.path.join(REPO, "tools", "launch.py"),
           "-n", str(n), sys.executable, os.path.join(REPO, script)]
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)


@pytest.mark.parametrize("n", [2, 3, 8])
def test_dist_sync_kvstore(n):
    """n=8 is where rank-mapping bugs actually appear (VERDICT r1 weak
    #6); covers sync aggregation, compression, and the gluon Trainer
    weight-consistency check at that width."""
    res = _run_launcher(n, "tests/dist_sync_kvstore_worker.py",
                        timeout=480)
    assert res.returncode == 0, res.stdout + res.stderr
    for rank in range(n):
        assert ("rank %d/%d: all dist_sync kvstore checks passed"
                % (rank, n)) in res.stdout + res.stderr


def test_bandwidth_tool_emits_json():
    """tools/bandwidth/measure.py analog of the reference's
    tools/bandwidth/measure.py: must emit one JSON record per size with
    a bandwidth figure and verified aggregation numerics."""
    import json
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bandwidth",
                                      "measure.py"),
         "--sizes-mb", "1", "--num-batches", "3"],
        env=env, capture_output=True, text=True, timeout=240)
    assert res.returncode == 0, res.stdout + res.stderr
    recs = [json.loads(line) for line in res.stdout.splitlines()
            if line.startswith("{")]
    assert recs and recs[0]["metric"] == "kvstore_pushpull_bandwidth"
    assert recs[0]["gb_per_sec"] > 0


def test_bandwidth_tool_dist():
    import json
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable,
         os.path.join(REPO, "tools", "bandwidth", "measure.py"),
         "--kv-store", "dist_sync", "--sizes-mb", "1",
         "--num-batches", "3"],
        env=env, capture_output=True, text=True, timeout=240)
    assert res.returncode == 0, res.stdout + res.stderr
    recs = [json.loads(line) for line in res.stdout.splitlines()
            if line.startswith("{")]
    assert recs and recs[0]["num_workers"] == 2


_PHASE6_WORKER = "benchmark/multiproc_dryrun_worker.py"


def _assert_phase6_ok(res):
    assert res.returncode == 0, res.stdout + res.stderr
    out = res.stdout + res.stderr
    for rank in range(2):
        assert ("multiproc dryrun rank %d: dp=4 sp=2 over 2 procs ok"
                % rank) in out, out


def test_multiproc_dryrun_phase6():
    """Run the exact dryrun phase-6 command (2 procs x 4 virtual devices
    stitched by jax.distributed) so the driver's MULTICHIP check is
    exercised in CI — it regressed silently in r4 (VERDICT r4 item 1)."""
    res = _run_launcher(2, _PHASE6_WORKER, timeout=480, env_extra={
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4"})
    _assert_phase6_ok(res)


def test_multiproc_dryrun_phase6_hostile_preload(tmp_path):
    """Phase 6 with a simulated preloaded accelerator plugin: a
    sitecustomize that clobbers XLA_FLAGS and initializes the XLA backend
    at interpreter startup, before the worker's env mutations run — the
    exact r4 failure mode ("expected 8 global devices, got 1"). The
    worker's force_virtual_cpu_devices re-init must recover."""
    site = tmp_path / "sitecustomize.py"
    site.write_text(
        "import os\n"
        "os.environ.pop('XLA_FLAGS', None)\n"
        "import jax\n"
        "jax.devices()  # pins a 1-device backend before worker code runs\n")
    res = _run_launcher(2, _PHASE6_WORKER, timeout=480, env_extra={
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PYTHONPATH": str(tmp_path) + os.pathsep + REPO})
    _assert_phase6_ok(res)


def test_gspmd_fused_step_2proc():
    """MULTICHIP-style proof for the GSPMD fused step (ISSUE 16): the
    Trainer-path dp=2 x tp=2 x sp=2 program compiles and runs over a
    2-process mesh, holds the matched-shardings contract, and both
    ranks converge to the same loss. Shares phase6's backend
    requirement: a jaxlib with cross-process CPU collectives (the
    plain single-process form of the same step is covered by
    tests/test_gspmd_step.py on the 8-device virtual mesh)."""
    res = _run_launcher(2, "benchmark/gspmd_step_worker.py", timeout=480,
                        env_extra={
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4"})
    assert res.returncode == 0, res.stdout + res.stderr
    out = res.stdout + res.stderr
    losses = set()
    for rank in range(2):
        marker = ("gspmd fused step rank %d: dp=2 tp=2 sp=2 over 2 procs "
                  "ok, loss=" % rank)
        assert marker in out, out
        line = [ln for ln in out.splitlines() if marker in ln][0]
        losses.add(line.split("loss=")[1].strip())
    # the loss output is pinned replicated: both ranks print the exact
    # same digits or the sharding contract is broken
    assert len(losses) == 1, losses


def test_launcher_propagates_failure(tmp_path):
    bad = tmp_path / "bad_worker.py"
    bad.write_text("import sys; sys.exit(3)\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable, str(bad)],
        env=env, capture_output=True, text=True, timeout=60)
    assert res.returncode == 1
    assert "exit codes" in res.stderr


def test_launcher_sets_dmlc_env(tmp_path):
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import os\n"
        "print('R%s/%s' % (os.environ['MXTPU_PROC_ID'],"
        " os.environ['MXTPU_NUM_PROCS']))\n"
        "assert os.environ['DMLC_ROLE'] == 'worker'\n"
        "assert 'MXTPU_COORDINATOR' in os.environ\n")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable, str(probe)],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr
    assert "R0/2" in res.stdout and "R1/2" in res.stdout
