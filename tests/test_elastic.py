"""Elastic training: checkpoint/recovery (parallel/elastic.py).

The reference has only ps-lite heartbeat dead-node detection
(ref: src/kvstore/kvstore_dist.h:121 GetDeadNodes) and no checkpoint
recovery (SURVEY §5); these tests pin the TPU-native upgrade: resume
after simulated collective failures and preemption-save semantics."""
import os
import signal

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.parallel import CheckpointManager, elastic_train_loop


def _mgr(tmp_path, **kw):
    return CheckpointManager(str(tmp_path / "ckpt"), **kw)


@pytest.mark.parametrize("use_orbax", [False, True])
def test_checkpoint_roundtrip(tmp_path, use_orbax):
    if use_orbax:
        pytest.importorskip("orbax.checkpoint")
    m = CheckpointManager(str(tmp_path / ("o" if use_orbax else "p")),
                          use_orbax=use_orbax)
    state = {"w": jnp.arange(4.0), "step": jnp.asarray(7)}
    m.save(10, state)
    m.save(20, state)
    assert m.latest_step() == 20
    restored, step = m.restore()
    assert step == 20
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(restored)[0]).ravel()[:4]
        if not isinstance(restored, dict) else np.asarray(restored["w"]),
        np.arange(4.0))


def test_checkpoint_prune(tmp_path):
    m = _mgr(tmp_path, keep=2, use_orbax=False)
    for s in (1, 2, 3, 4):
        m.save(s, {"x": jnp.zeros(1)})
    assert m.all_steps() == [3, 4]


def test_elastic_loop_recovers_from_failures(tmp_path):
    """A step that fails twice mid-run: the loop must restore and finish
    with EXACTLY the same result as an uninterrupted run."""
    m = _mgr(tmp_path, use_orbax=False)
    batches = [jnp.asarray(float(i)) for i in range(10)]

    fail_at = {5: 2}  # step 5 fails twice

    def make_step(fail_budget):
        def step(state, b):
            if fail_budget.get(int(b), 0) > 0:
                fail_budget[int(b)] -= 1
                raise RuntimeError("simulated collective failure")
            return {"acc": state["acc"] + b}, None
        return step

    state0 = {"acc": jnp.asarray(0.0)}
    state, last, done = elastic_train_loop(
        make_step(dict(fail_at)), dict(state0), batches, m, save_every=2,
        max_failures=5)
    assert done and last == 9
    np.testing.assert_allclose(float(state["acc"]), sum(range(10)))


def test_elastic_loop_gives_up_after_max_failures(tmp_path):
    m = _mgr(tmp_path, use_orbax=False)

    def step(state, b):
        raise RuntimeError("permanently broken")

    with pytest.raises(RuntimeError, match="permanently broken"):
        elastic_train_loop(step, {"acc": jnp.asarray(0.0)},
                           [jnp.asarray(1.0)] * 3, m, save_every=1,
                           max_failures=2)


def test_elastic_resume_from_existing_checkpoint(tmp_path):
    """A fresh loop (new process after preemption) picks up from the
    newest checkpoint instead of step 0."""
    m = _mgr(tmp_path, use_orbax=False)
    seen = []

    def step(state, b):
        seen.append(float(b))
        return {"acc": state["acc"] + b}, None

    batches = [jnp.asarray(float(i)) for i in range(6)]
    # simulate an earlier incarnation that saved at step 3
    m.save(3, {"acc": jnp.asarray(float(0 + 1 + 2 + 3))})
    state, last, done = elastic_train_loop(
        step, {"acc": jnp.asarray(0.0)}, batches, m, save_every=100)
    assert done
    assert seen == [4.0, 5.0]          # steps 0..3 skipped
    np.testing.assert_allclose(float(state["acc"]), 15.0)


def test_preemption_guard_saves_and_exits(tmp_path):
    m = _mgr(tmp_path, use_orbax=False)

    def step(state, b):
        if float(b) == 2.0:
            # deliver the preemption signal mid-run
            os.kill(os.getpid(), signal.SIGTERM)
        return {"acc": state["acc"] + b}, None

    batches = [jnp.asarray(float(i)) for i in range(10)]
    state, last, done = elastic_train_loop(
        step, {"acc": jnp.asarray(0.0)}, batches, m, save_every=100)
    assert not done
    # checkpoint exists so the next incarnation resumes
    restored, step_no = m.restore()
    assert restored is not None and step_no == last
    state2, last2, done2 = elastic_train_loop(
        step, {"acc": jnp.asarray(0.0)}, batches, m, save_every=100)
    assert done2
    np.testing.assert_allclose(float(state2["acc"]), sum(range(10)))
